"""Worker script for the multi-process dist_sync proof.

Launched by ``tools/launch.py -n N --cpu python tests/dist_worker.py``
(model: ``/root/reference/tests/nightly/dist_sync_kvstore.py`` — numeric
check that N workers' pushes sum, incl. a big array and the
server-side-updater path)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx

SHAPE = (3, 4)
BIG_SHAPE = (120, 120)  # the reference uses a >BIGARRAY_BOUND tensor


def main():
    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    want = int(os.environ["MXNET_NUM_WORKERS"])
    assert nw == want, f"runtime has {nw} processes, launcher started {want}"

    # --- plain sum semantics (no updater) ----------------------------
    kv.init(3, mx.nd.zeros(SHAPE))
    kv.init(99, mx.nd.zeros(BIG_SHAPE))
    expected = sum(r + 1 for r in range(nw))
    for _ in range(3):
        kv.push(3, mx.nd.ones(SHAPE) * (rank + 1))
        out = mx.nd.zeros(SHAPE)
        kv.pull(3, out=out)
        np.testing.assert_allclose(out.asnumpy(),
                                   np.full(SHAPE, float(expected)))

    # multi-array push: local reduce then cross-worker sum
    kv.push(99, [mx.nd.ones(BIG_SHAPE), mx.nd.ones(BIG_SHAPE)])
    out = mx.nd.zeros(BIG_SHAPE)
    kv.pull(99, out=out)
    np.testing.assert_allclose(out.asnumpy(),
                               np.full(BIG_SHAPE, 2.0 * nw))

    kv.barrier()

    # --- updater path: identical replicated update everywhere --------
    kv.init("w", mx.nd.zeros(SHAPE))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5, rescale_grad=1.0,
                                      wd=0.0))
    kv.push("w", mx.nd.ones(SHAPE))
    out = mx.nd.zeros(SHAPE)
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(),
                               np.full(SHAPE, -0.5 * nw), rtol=1e-5)

    # --- liveness ----------------------------------------------------
    assert kv.get_num_dead_node(timeout=30) == 0
    kv.barrier()
    print(f"worker {rank}/{nw}: dist_sync kvstore OK", flush=True)


if __name__ == "__main__":
    main()
