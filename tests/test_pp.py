"""Pipeline parallelism (mxnet_tpu.pp) on the virtual 8-device CPU
mesh: the 1F1B/GPipe schedule tables, the symbol stage splitter's cut
contract, and the acceptance proof of full 3D parallelism — a
dp=2 × tp=2 × pp=2 run whose final weights equal a single-process run
on the same data (the PR-4/PR-8 ground-truth pattern).

Tolerances: pipelined gradients equal whole-graph vjp gradients up to
fp reassociation of the microbatch sum (measured ~1e-7 absolute on
these sizes), so multi-step SGD weight equivalence is asserted at
2e-5."""

import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import parallel, pp

RULES = (("hidden", "tp"), ("embed", None))


# ---------------------------------------------------------------------------
# schedule
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kind", ["1f1b", "gpipe"])
@pytest.mark.parametrize("M,S", [(1, 1), (4, 2), (8, 2), (8, 4), (3, 3)])
def test_schedule_complete_and_optimal(kind, M, S):
    sched = pp.build_schedule(M, S, kind)
    # every (stage, microbatch) forwarded and backwarded exactly once
    for s in range(S):
        f = [int(m) for m in sched.fwd[:, s] if m >= 0]
        b = [int(m) for m in sched.bwd[:, s] if m >= 0]
        assert sorted(f) == list(range(M))
        assert sorted(b) == list(range(M))
        assert f == sorted(f), "forwards must run in microbatch order"
    # dependency sanity: F(s,m) after F(s-1,m); B(s,m) after B(s+1,m)
    ft = {(s, int(m)): t for t in range(sched.num_ticks)
          for s in range(S) if (m := sched.fwd[t, s]) >= 0}
    bt = {(s, int(m)): t for t in range(sched.num_ticks)
          for s in range(S) if (m := sched.bwd[t, s]) >= 0}
    for (s, m), t in ft.items():
        if s > 0:
            assert ft[(s - 1, m)] < t
    for (s, m), t in bt.items():
        assert ft[(s, m)] < t
        if s < S - 1:
            assert bt[(s + 1, m)] < t
    # optimal flush length and the closed-form bubble
    assert sched.num_ticks == 2 * (M + S - 1)
    assert sched.bubble_fraction == pytest.approx(
        pp.bubble_fraction(M, S))


def test_schedule_bubble_meets_acceptance_bound():
    """At 8 microbatches the schedule bubble must sit under
    1/M × (pp−1) × 1.25 — the bench gate, provable from the table."""
    for S in (2, 4):
        sched = pp.build_schedule(8, S, "1f1b")
        assert sched.bubble_fraction < (1 / 8) * (S - 1) * 1.25


def test_schedule_validation():
    with pytest.raises(mx.base.MXNetError):
        pp.build_schedule(0, 2)
    with pytest.raises(mx.base.MXNetError):
        pp.build_schedule(4, 0)
    with pytest.raises(mx.base.MXNetError):
        pp.build_schedule(4, 2, "pipedream-2bw")


# ---------------------------------------------------------------------------
# model + trainer helpers
# ---------------------------------------------------------------------------

def _pp_sym(num_blocks=4, hidden=16):
    """Uniform residual-MLP trunk with annotated pipeline blocks."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(
        data, num_hidden=hidden, name="inproj",
        weight=mx.sym.Variable("inproj_weight",
                               attr=parallel.logical_axes("hidden",
                                                          "embed")))
    for i in range(num_blocks):
        with mx.AttrScope(__pp_block__=str(i)):
            h = mx.sym.FullyConnected(net, num_hidden=hidden,
                                      name=f"blk{i}_fc")
            net = net + mx.sym.Activation(h, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="head")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _data(steps=6, batch=16, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(batch * steps, 8).astype(np.float32)
    y = rng.randint(0, 4, size=batch * steps).astype(np.float32)
    return X, y


def _make_mod(plan=None, sym=None, arg_params=None, steps=6):
    mx.random.seed(7)
    X, y = _data(steps)
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(sym or _pp_sym(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params(mx.initializer.Uniform(0.1), arg_params=arg_params)
    if plan is not None:
        mod.set_mesh_plan(plan)
    mod.init_optimizer(kvstore="tpu" if plan else None, optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    return mod, it


def _run(mod, it, n_steps=None, skip=0):
    it.reset()
    done = 0
    for b in it:
        if n_steps is not None and done >= skip + n_steps:
            break
        if done >= skip:
            mod.forward_backward(b)
            mod.update()
        done += 1
    args, _ = mod.get_params()
    return {k: np.asarray(mx.nd.gather_global(v)) for k, v in args.items()}


def _plan_3d(microbatches=4, rules=RULES, **kw):
    import jax

    kw.setdefault("dp", 2)
    kw.setdefault("tp", 2)
    kw.setdefault("pp", 2)
    return parallel.MeshPlan(jax.devices(), microbatches=microbatches,
                             rules=rules, **kw)


# ---------------------------------------------------------------------------
# the 3D acceptance proof
# ---------------------------------------------------------------------------

def test_pp_trains_3d_matches_single_process():
    """dp=2 × tp=2 × pp=2 over the 8-device mesh, 4 microbatches,
    interleaved 1F1B: final weights equal the single-process run on the
    union data within 2e-5 (the PR-4/PR-8 ground-truth pattern)."""
    mod_ref, it_ref = _make_mod(None)
    ref = _run(mod_ref, it_ref)
    mod, it = _make_mod(_plan_3d())
    got = _run(mod, it)
    assert mod._mesh_plan.pp == 2 and mod._mesh_plan.microbatches == 4
    assert mod._pp_schedule.kind == "1f1b"
    assert mod._pp_schedule.num_ticks == 2 * (4 + 2 - 1)
    for k in ref:
        np.testing.assert_allclose(ref[k], got[k], rtol=2e-4, atol=2e-5,
                                   err_msg=k)


def test_pp_gpipe_schedule_matches_too(monkeypatch):
    monkeypatch.setenv("MXNET_PP_SCHEDULE", "gpipe")
    mod_ref, it_ref = _make_mod(None)
    ref = _run(mod_ref, it_ref, n_steps=3)
    mod, it = _make_mod(_plan_3d())
    got = _run(mod, it, n_steps=3)
    assert mod._pp_schedule.kind == "gpipe"
    for k in ref:
        np.testing.assert_allclose(ref[k], got[k], rtol=2e-4, atol=2e-5,
                                   err_msg=k)


def test_pp_zero_composes():
    """ZeRO-1 stays on under pp: per-name optimizer state flat
    'dp'-sharded, stage-resident slab state (S, flat) pp x dp-sharded
    — every device stores 1/(pp*dp) of the trunk's slots, all resolved
    through the same rules table ('zero' axis)."""
    from jax.sharding import PartitionSpec as P

    mod, it = _make_mod(_plan_3d())
    _run(mod, it, n_steps=2)
    assert mod._zero
    assert mod._pp_resident  # MXNET_PP_RESIDENT defaults on
    import jax

    slab_keys = set(mod._pp_slab_keys)
    assert slab_keys and slab_keys <= set(mod._fused_state)
    for key, tree in mod._fused_state.items():
        want = P("pp", "dp") if key in slab_keys else P("dp")
        for leaf in jax.tree_util.tree_leaves(tree):
            assert leaf.sharding.spec == want, (key, leaf.sharding)


def test_pp_resident_equals_replicated_and_drops_bytes(monkeypatch):
    """The stage-resident weight path (MXNET_PP_RESIDENT=1, default)
    trains identically to the replicated path AND to a single-process
    run, while the stacked block weights occupy ~1/pp the per-device
    bytes — the equivalence-gated workaround for the documented
    partitioner miscompile (the memory-pitfalls rule: never trust a
    new sharding constraint on this jaxlib without an equivalence
    test)."""
    mod_ref, it_ref = _make_mod(None)
    ref = _run(mod_ref, it_ref)
    monkeypatch.setenv("MXNET_PP_RESIDENT", "0")
    mod_rep, it_rep = _make_mod(_plan_3d())
    rep = _run(mod_rep, it_rep)
    assert not mod_rep._pp_resident
    rep_bytes = mod_rep.param_bytes_per_device()
    monkeypatch.setenv("MXNET_PP_RESIDENT", "1")
    mod_res, it_res = _make_mod(_plan_3d())
    # run all steps, snapshot bytes while the slabs are live
    it_res.reset()
    for b in it_res:
        mod_res.forward_backward(b)
        mod_res.update()
    assert mod_res._pp_resident
    res_bytes = mod_res.param_bytes_per_device()
    blk_bytes = sum(
        int(np.prod(mod_rep._exec.arg_dict[n].shape)) * 4
        for names in mod_res._pp_slot_names for n in names)
    res = {k: np.asarray(mx.nd.gather_global(v))
           for k, v in mod_res.get_params()[0].items()}
    for k in ref:
        np.testing.assert_allclose(ref[k], rep[k], rtol=2e-4,
                                   atol=2e-5, err_msg="rep:" + k)
        np.testing.assert_allclose(ref[k], res[k], rtol=2e-4,
                                   atol=2e-5, err_msg="res:" + k)
    # per-device drop equals the trunk's (1 - 1/pp) share exactly
    pp = mod_res._mesh_plan.pp
    assert rep_bytes - res_bytes == blk_bytes - blk_bytes // pp


def test_pp_resident_materialize_roundtrip(monkeypatch):
    """get_params hands authority back to the per-name arrays
    (materialize), the next step rebuilds the slabs, and values
    survive the round trip bit-exactly."""
    monkeypatch.setenv("MXNET_PP_RESIDENT", "1")
    mod, it = _make_mod(_plan_3d())
    _run(mod, it, n_steps=2)
    assert mod._pp_slabs is None  # _run's get_params materialized
    args1, _ = mod.get_params()
    host1 = {k: np.asarray(mx.nd.gather_global(v))
             for k, v in args1.items()}
    # step again (rebuild slabs), read again
    _run(mod, it, n_steps=1, skip=2)
    args2, _ = mod.get_params()
    # a freed per-name buffer would raise here; values must be sane
    for k, v in args2.items():
        assert np.isfinite(np.asarray(mx.nd.gather_global(v))).all(), k
    # and re-materializing right after a materialize is a no-op
    mod._materialize_pp_params()
    del host1


def test_pp_resident_optimizer_state_cross_layout(tmp_path,
                                                  monkeypatch):
    """Optimizer states written by a stage-resident run load into a
    replicated-weights run (and back): the slab-keyed (S, flat)
    pp x dp-sharded state checkpoints as per-name param-shaped values
    — the PR-4 layout-independence contract extended to slabs."""
    monkeypatch.setenv("MXNET_PP_RESIDENT", "1")
    mod_res, it = _make_mod(_plan_3d())
    _run(mod_res, it, n_steps=3)
    f = str(tmp_path / "res.states")
    mod_res.save_optimizer_states(f)
    args, auxs = mod_res.get_params()
    args_h = {k: np.asarray(mx.nd.gather_global(v))
              for k, v in args.items()}
    # finish the run on the resident module: the continuation target
    ref = _run(mod_res, it, n_steps=3, skip=3)

    monkeypatch.setenv("MXNET_PP_RESIDENT", "0")
    mod_rep, it2 = _make_mod(_plan_3d(), arg_params=args_h)
    mod_rep.load_optimizer_states(f)
    got = _run(mod_rep, it2, n_steps=3, skip=3)
    assert not mod_rep._pp_resident
    for k in ref:
        np.testing.assert_allclose(ref[k], got[k], rtol=2e-4,
                                   atol=2e-5, err_msg=k)

    # and the reverse direction: replicated-written states resume a
    # resident run
    f2 = str(tmp_path / "rep.states")
    mod_rep2, it3 = _make_mod(_plan_3d())
    _run(mod_rep2, it3, n_steps=3)
    mod_rep2.save_optimizer_states(f2)
    args2_h = {k: np.asarray(mx.nd.gather_global(v))
               for k, v in mod_rep2.get_params()[0].items()}
    ref2 = _run(mod_rep2, it3, n_steps=3, skip=3)
    monkeypatch.setenv("MXNET_PP_RESIDENT", "1")
    mod_res2, it4 = _make_mod(_plan_3d(), arg_params=args2_h)
    mod_res2.load_optimizer_states(f2)
    got2 = _run(mod_res2, it4, n_steps=3, skip=3)
    assert mod_res2._pp_resident
    for k in ref2:
        np.testing.assert_allclose(ref2[k], got2[k], rtol=2e-4,
                                   atol=2e-5, err_msg=k)


def test_pp_resident_plain_path_fallback(monkeypatch):
    """get_outputs() before update() flushes through the plain
    whole-graph executor: under residency the params materialize for
    the forward and the per-name grads re-stack into the slab-keyed
    optimizer state — training continues equivalent to the
    uninterrupted pipelined run within pipeline-reassociation
    tolerance."""
    monkeypatch.setenv("MXNET_PP_RESIDENT", "1")
    mod_ref, it_ref = _make_mod(None)
    ref = _run(mod_ref, it_ref, n_steps=3)
    mod, it = _make_mod(_plan_3d())
    it.reset()
    for i, b in enumerate(it):
        if i >= 3:
            break
        mod.forward(b)
        if i == 1:  # mid-run output query forces the plain path
            out = mod.get_outputs()[0]
            assert np.isfinite(np.asarray(out.asnumpy())).all()
        mod.backward()
        mod.update()
    got = {k: np.asarray(mx.nd.gather_global(v))
           for k, v in mod.get_params()[0].items()}
    for k in ref:
        np.testing.assert_allclose(ref[k], got[k], rtol=2e-4,
                                   atol=2e-5, err_msg=k)


def test_transformer_lm_rules_3d():
    """The transformer LM trains dp=2 × tp=2 × pp=2 purely from the
    logical-axis rules table — ZERO per-op __shard__ attrs anywhere —
    and matches the single-process run."""
    import jax
    from jax.sharding import PartitionSpec as P

    from mxnet_tpu.models import transformer

    V, T, BATCH = 32, 8, 16

    def train(plan):
        mx.random.seed(7)
        rng = np.random.RandomState(5)
        X = rng.randint(1, V, size=(BATCH * 4, T)).astype(np.float32)
        y = rng.randint(1, V, size=(BATCH * 4, T)).astype(np.float32)
        it = mx.io.NDArrayIter(X, y, batch_size=BATCH)
        sym = transformer.transformer_lm(V, T, num_layers=2, num_heads=2,
                                         d_model=16)
        for name, d in sym.attr_dict().items():
            assert "__shard__" not in d, f"per-op attr survives on {name}"
        mod = mx.mod.Module(sym, context=mx.cpu())
        mod.bind(data_shapes=it.provide_data,
                 label_shapes=it.provide_label, for_training=True)
        mod.init_params(mx.initializer.Uniform(0.05))
        if plan is not None:
            mod.set_mesh_plan(plan)
        mod.init_optimizer(kvstore="tpu" if plan else None,
                           optimizer="sgd",
                           optimizer_params={"learning_rate": 0.05,
                                             "momentum": 0.9})
        for b in it:
            mod.forward_backward(b)
            mod.update()
        args, _ = mod.get_params()
        return mod, {k: np.asarray(mx.nd.gather_global(v))
                     for k, v in args.items()}

    _, ref = train(None)
    plan = parallel.MeshPlan(jax.devices(), dp=2, tp=2, pp=2,
                             microbatches=4,
                             rules=transformer.lm_partition_rules())
    mod, got = train(plan)
    # the rules table really tensor-shards: qkv col-parallel, proj
    # row-parallel, embedding vocab-parallel
    ad = mod._exec.arg_dict
    assert tuple(ad["layer0_qkv_weight"]._data.sharding.spec) \
        == ("tp", None)
    assert tuple(ad["layer1_proj_weight"]._data.sharding.spec) \
        == (None, "tp")
    assert tuple(ad["tok_embed_weight"]._data.sharding.spec) \
        == ("tp", None)
    for k in ref:
        np.testing.assert_allclose(ref[k], got[k], rtol=2e-4, atol=2e-5,
                                   err_msg=k)


def test_pp_checkpoint_cross_layout():
    """dp×tp ↔ dp×tp×pp checkpoint round-trip through the PR-4
    layout-independent path: 3 steps under one layout + 3 under the
    other equals 6 uninterrupted single-process steps."""
    import jax

    mod_ref, it_ref = _make_mod(None)
    ref = _run(mod_ref, it_ref, n_steps=6)

    import tempfile

    for first, second in [
        (parallel.MeshPlan(jax.devices(), dp=4, tp=2, rules=RULES),
         _plan_3d()),
        (_plan_3d(),
         parallel.MeshPlan(jax.devices(), dp=4, tp=2, rules=RULES)),
    ]:
        mod1, it1 = _make_mod(first)
        _run(mod1, it1, n_steps=3)
        with tempfile.TemporaryDirectory() as d:
            fname = os.path.join(d, "opt.states")
            mod1.save_optimizer_states(fname)
            args, _ = mod1.get_params()
            args = {k: mx.nd.array(np.asarray(mx.nd.gather_global(v)))
                    for k, v in args.items()}
            mod2, it2 = _make_mod(second, arg_params=args)
            mod2.load_optimizer_states(fname)
            got = _run(mod2, it2, n_steps=3, skip=3)
        for k in ref:
            np.testing.assert_allclose(
                ref[k], got[k], rtol=2e-4, atol=2e-5,
                err_msg=f"{first.pp}->{second.pp} {k}")


# ---------------------------------------------------------------------------
# guards and validations
# ---------------------------------------------------------------------------

def test_pp_shared_pre_post_param():
    """A parameter read by BOTH the pre and post regions (the tied-
    embedding shape): each region's vjp contributes and the step sums
    them — weights still match the single-process run."""
    def tied_sym():
        shared = mx.sym.Variable("shared_bias", shape=(1, 16))
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, num_hidden=16, name="inproj")
        net = mx.sym.broadcast_add(net, shared, name="pre_add")
        for i in range(2):
            with mx.AttrScope(__pp_block__=str(i)):
                h = mx.sym.FullyConnected(net, num_hidden=16,
                                          name=f"tb{i}_fc")
                net = net + mx.sym.Activation(h, act_type="relu")
        net = mx.sym.FullyConnected(net, num_hidden=16, name="mid")
        net = mx.sym.broadcast_add(net, shared, name="post_add")
        net = mx.sym.FullyConnected(net, num_hidden=4, name="head")
        return mx.sym.SoftmaxOutput(net, name="softmax")

    mod_ref, it_ref = _make_mod(None, sym=tied_sym())
    ref = _run(mod_ref, it_ref, n_steps=4)
    mod, it = _make_mod(_plan_3d(rules=()), sym=tied_sym())
    got = _run(mod, it, n_steps=4)
    assert np.abs(ref["shared_bias"]).sum() > 0  # it actually trains
    for k in ref:
        np.testing.assert_allclose(ref[k], got[k], rtol=2e-4, atol=2e-5,
                                   err_msg=k)


def test_pp_remesh_raises_not_implemented():
    import jax

    mod, it = _make_mod(_plan_3d())
    _run(mod, it, n_steps=1)
    # the refusal is ACTIONABLE: names the dp-only elastic contract
    # AND points at the layout-independent checkpoint reshard path
    with pytest.raises(NotImplementedError,
                       match="(?s)dp-only.*checkpoint reshard"):
        mod.remesh(parallel.MeshPlan(jax.devices(), dp=4, tp=2,
                                     rules=RULES))
    # and re-meshing a dp plan ONTO a pp plan is equally refused
    mod2, it2 = _make_mod(parallel.MeshPlan(jax.devices(), dp=4, tp=2,
                                            rules=RULES))
    _run(mod2, it2, n_steps=1)
    with pytest.raises(NotImplementedError, match="dp-only"):
        mod2.remesh(_plan_3d())


def test_pp_requires_block_annotations():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod, it = _make_mod(_plan_3d(rules=()), sym=net)
    with pytest.raises(mx.base.MXNetError, match="__pp_block__"):
        b = next(iter(it))
        mod.forward_backward(b)
        mod.update()


def test_pp_aux_state_ops_raise():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="inproj")
    for i in range(2):
        with mx.AttrScope(__pp_block__=str(i)):
            h = mx.sym.FullyConnected(net, num_hidden=16, name=f"b{i}_fc")
            h = mx.sym.BatchNorm(h, name=f"b{i}_bn")
            net = net + mx.sym.Activation(h, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="head")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod, it = _make_mod(_plan_3d(pp=2, dp=2, tp=2), sym=net)
    with pytest.raises(mx.base.MXNetError, match="aux"):
        b = next(iter(it))
        mod.forward_backward(b)
        mod.update()


def test_split_blocks_validations():
    # non-contiguous block ids
    data = mx.sym.Variable("data")
    with mx.AttrScope(__pp_block__="0"):
        net = mx.sym.FullyConnected(data, num_hidden=8, name="a_fc")
    with mx.AttrScope(__pp_block__="2"):
        net = mx.sym.FullyConnected(net, num_hidden=8, name="b_fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    with pytest.raises(mx.base.MXNetError, match="contiguous"):
        pp.split_blocks(net)

    # a parameter shared across two blocks
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("shared_weight")
    with mx.AttrScope(__pp_block__="0"):
        net = mx.sym.FullyConnected(data, weight=w, num_hidden=8,
                                    name="c_fc")
    with mx.AttrScope(__pp_block__="1"):
        net = mx.sym.FullyConnected(net, weight=w, num_hidden=8,
                                    name="d_fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    with pytest.raises(mx.base.MXNetError, match="shared"):
        pp.split_blocks(net)

    # structurally different blocks
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="pre_fc")
    with mx.AttrScope(__pp_block__="0"):
        net = mx.sym.FullyConnected(net, num_hidden=8, name="e_fc")
    with mx.AttrScope(__pp_block__="1"):
        net = mx.sym.Activation(
            mx.sym.FullyConnected(net, num_hidden=8, name="f_fc"),
            act_type="relu")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    with pytest.raises(mx.base.MXNetError, match="identical"):
        pp.split_blocks(net)


def test_pp_layers_must_divide_stages():
    import jax

    plan = parallel.MeshPlan(jax.devices(), dp=2, tp=1, pp=4,
                             microbatches=4, rules=RULES)
    mod, it = _make_mod(plan, sym=_pp_sym(num_blocks=3))
    with pytest.raises(mx.base.MXNetError, match="divide"):
        b = next(iter(it))
        mod.forward_backward(b)
        mod.update()


def test_bench_pp_tool_runs():
    import json
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo, BENCH_PP_STEPS="2",
               BENCH_PP_WARMUP="1", BENCH_PP_MICRO="1,4",
               BENCH_PP_LAYERS="4", BENCH_PP_HIDDEN="32")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "bench_pp.py")],
        capture_output=True, text=True, timeout=600, cwd=repo, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "pp_train_throughput"
    assert rec["weights_match"] is True
    by_m = {row["microbatches"]: row for row in rec["sweep"]}
    assert by_m[4]["bubble_fraction"] == pytest.approx(
        pp.bubble_fraction(4, rec["pp"]))
