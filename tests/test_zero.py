"""ZeRO-1 sharded optimizer (MXNET_ZERO) on the virtual 8-device CPU
mesh: sharded-vs-replicated weight equivalence, state sharding and
per-device byte reduction, layout-independent checkpoints, bucketed
state migration, and the bench tool.

Tolerances: the sharded update computes each element's update on
exactly ONE device from the same psum'd gradient the replicated update
uses; the only permitted difference is fp reassociation of the
gradient reduction (reduce-scatter vs all-reduce schedules), so
equivalence is asserted at rtol=1e-6.
"""

import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx


@pytest.fixture(autouse=True)
def _clean_zero_env():
    old = os.environ.pop("MXNET_ZERO", None)
    yield
    if old is None:
        os.environ.pop("MXNET_ZERO", None)
    else:
        os.environ["MXNET_ZERO"] = old


def _sym(tp_shard=False):
    from mxnet_tpu import parallel

    data = mx.sym.Variable("data")
    kw = {"attr": parallel.shard_attr("tp", 0)} if tp_shard else {}
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1", **kw)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _data(steps=6, batch=16, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(batch * steps, 8).astype(np.float32)
    y = rng.randint(0, 4, size=batch * steps).astype(np.float32)
    return X, y


def _make_mod(zero, optimizer="adam", arg_params=None, tp=0, batch=16,
              opt_params=None):
    os.environ["MXNET_ZERO"] = "1" if zero else "0"
    mx.random.seed(7)
    X, y = _data(batch=batch)
    it = mx.io.NDArrayIter(X, y, batch_size=batch)
    mod = mx.mod.Module(_sym(tp_shard=bool(tp)), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params(mx.initializer.Uniform(0.1), arg_params=arg_params)
    if tp:
        from mxnet_tpu import parallel

        mod.set_mesh_plan(parallel.make_plan(tp=tp))
    mod.init_optimizer(kvstore="tpu", optimizer=optimizer,
                       optimizer_params=opt_params
                       or {"learning_rate": 0.05})
    return mod, it


def _run(mod, it, n_steps=None, skip=0):
    it.reset()
    done = 0
    for b in it:
        if n_steps is not None and done >= skip + n_steps:
            break
        if done >= skip:
            mod.forward_backward(b)
            mod.update()
        done += 1
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args.items()}


def _train(zero, optimizer="adam", **kw):
    mod, it = _make_mod(zero, optimizer, **kw)
    return mod, _run(mod, it)


@pytest.mark.parametrize("optimizer", ["adam", "sgd", "rmsprop"])
def test_zero_matches_replicated(optimizer):
    """Same model, same data: MXNET_ZERO=1 and =0 reach equal weights."""
    opt_params = {"learning_rate": 0.05}
    if optimizer == "sgd":
        opt_params["momentum"] = 0.9
    _, rep = _train(False, optimizer, opt_params=opt_params)
    mod, zer = _train(True, optimizer, opt_params=opt_params)
    assert mod._zero, "dp>1 mesh must default ZeRO on"
    for k in rep:
        np.testing.assert_allclose(rep[k], zer[k], rtol=1e-6, atol=1e-7,
                                   err_msg=k)


def test_zero_state_sharded_and_smaller():
    """Adam m/v live flat, 'dp'-sharded; per-device bytes drop ~dp×;
    the executor.opt_state_bytes gauge reports the sharded number."""
    import jax
    from jax.sharding import PartitionSpec as P

    from mxnet_tpu import profiler

    mod_rep, _ = _train(False)
    rep_bytes = mod_rep._opt_state_bytes_per_device()
    mod, _ = _train(True)
    zero_bytes = mod._opt_state_bytes_per_device()
    dp = mod._mesh_plan.dp
    assert dp == len(jax.devices())
    for n, tree in mod._fused_state.items():
        size, padded = mod._zero_meta[n]
        assert padded % dp == 0 and padded >= size
        for leaf in jax.tree_util.tree_leaves(tree):
            assert leaf.shape == (padded,)
            assert leaf.sharding.spec == P("dp")
    # equality would need pad-free divisibility; bias params pad up
    assert zero_bytes <= rep_bytes / dp * 1.5, (zero_bytes, rep_bytes)
    assert profiler.metrics_summary()["gauges"][
        "executor.opt_state_bytes"] == zero_bytes


def test_zero_off_without_mesh():
    """Single-device training never shards (dp=1 ⇒ replicated path)."""
    os.environ["MXNET_ZERO"] = "1"
    mx.random.seed(7)
    X, y = _data()
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_sym(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params(mx.initializer.Uniform(0.1))
    mod.init_optimizer(optimizer="adam")
    b = next(iter(it))
    mod.forward_backward(b)
    mod.update()
    assert not mod._zero


def test_zero_env_opt_out():
    """MXNET_ZERO=0 keeps the replicated update even on a dp>1 mesh
    (the mode is latched when the fused step is first built)."""
    mod, it = _make_mod(True)
    os.environ["MXNET_ZERO"] = "0"  # before the first update
    b = next(iter(it))
    mod.forward_backward(b)
    mod.update()
    assert not mod._zero


@pytest.mark.parametrize("save_zero,load_zero",
                         [(True, False), (False, True), (True, True)])
def test_zero_checkpoint_cross_layout(save_zero, load_zero):
    """Optimizer states saved under one layout load under the other:
    split training (3 steps, save, load elsewhere, 3 more) equals 6
    uninterrupted replicated steps."""
    mod_ref, it_ref = _make_mod(False)
    ref = _run(mod_ref, it_ref, n_steps=6)

    mod1, it1 = _make_mod(save_zero)
    _run(mod1, it1, n_steps=3)
    with tempfile.TemporaryDirectory() as d:
        fname = os.path.join(d, "opt.states")
        mod1.save_optimizer_states(fname)
        args, _ = mod1.get_params()
        mod2, it2 = _make_mod(load_zero, arg_params=args)
        mod2.load_optimizer_states(fname)
        got = _run(mod2, it2, n_steps=3, skip=3)
    for k in ref:
        np.testing.assert_allclose(ref[k], got[k], rtol=1e-6, atol=1e-7,
                                   err_msg=f"{save_zero}->{load_zero} {k}")


def test_zero_checkpoint_via_module_save(tmp_path):
    """Module.save_checkpoint/save_optimizer_states writes REAL fused
    state (not the empty eager Updater) and Module.load restores it."""
    mod, it = _make_mod(True, "adam")
    _run(mod, it, n_steps=4)
    prefix = str(tmp_path / "zckpt")
    mod.save_checkpoint(prefix, 1, save_optimizer_states=True)
    import pickle

    with open(prefix + "-0001.states", "rb") as f:
        data = pickle.loads(f.read())
    assert data["format"] == "mxnet_tpu-fused-states-v1"
    assert data["step"] == 4
    # Adam m/v are param-shaped (layout-independent), nonzero after 4
    # steps
    m, v = data["states"]["fc1_weight"]
    assert m.shape == (16, 8) and np.abs(m).sum() > 0


def test_zero_save_right_after_load_preserves_states():
    """load → save with NO step in between must round-trip the blob
    (regression: the pre-build save path wrote an empty Updater dict,
    silently dropping the checkpoint on e.g. rotation-at-resume)."""
    import pickle

    mod1, it1 = _make_mod(True)
    _run(mod1, it1, n_steps=3)
    with tempfile.TemporaryDirectory() as d:
        f1 = os.path.join(d, "a.states")
        f2 = os.path.join(d, "b.states")
        mod1.save_optimizer_states(f1)
        args, _ = mod1.get_params()
        mod2, _ = _make_mod(False, arg_params=args)
        mod2.load_optimizer_states(f1)
        mod2.save_optimizer_states(f2)  # fused programs not built yet
        with open(f2, "rb") as fh:
            data = pickle.loads(fh.read())
        assert data["format"] == "mxnet_tpu-fused-states-v1"
        assert data["step"] == 3
        m1, _ = data["states"]["fc1_weight"]
        with open(f1, "rb") as fh:
            orig = pickle.loads(fh.read())
        np.testing.assert_array_equal(m1, orig["states"]["fc1_weight"][0])


def test_zero_with_tensor_parallel():
    """ZeRO composes with a 'tp'-sharded param: the updated weight is
    gathered back to its tp layout and training matches ZeRO-off."""
    from jax.sharding import PartitionSpec as P

    _, rep = _train(False, tp=2)
    mod, zer = _train(True, tp=2)
    assert mod._zero and mod._mesh_plan.tp == 2
    assert mod._exec.arg_dict["fc1_weight"]._data.sharding.spec \
        == P("tp", None)
    for k in rep:
        np.testing.assert_allclose(rep[k], zer[k], rtol=1e-6, atol=1e-7,
                                   err_msg=k)


def test_zero_bucketing_state_migration():
    """_adopt_fused_state carries the sharded slots (and the ZeRO
    layout metadata) to the next bucket's module."""
    os.environ["MXNET_ZERO"] = "1"
    mx.random.seed(7)
    X, y = _data(batch=16)
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_sym(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params(mx.initializer.Uniform(0.1))
    mod.init_optimizer(kvstore="tpu", optimizer="adam")
    b = next(iter(it))
    mod.forward_backward(b)
    mod.update()
    assert mod._zero

    mod2 = mx.mod.Module(_sym(), context=mx.cpu())
    mod2.bind(data_shapes=[("data", (8, 8))],
              label_shapes=[("softmax_label", (8,))],
              for_training=True, shared_module=mod)
    mod2.set_mesh_plan(mod._mesh_plan)
    mod2.borrow_optimizer(mod)
    mod2._adopt_fused_state(mod)
    assert mod2._zero and mod2._zero_meta == mod._zero_meta
    assert mod2._fused_state is mod._fused_state
    b2 = mx.io.DataBatch(data=[mx.nd.array(X[:8])],
                         label=[mx.nd.array(y[:8])])
    mod2.forward(b2, is_train=True)
    mod2.backward()
    mod2.update()
    out = mod2.get_outputs()[0].asnumpy()
    assert np.isfinite(out).all()


def test_bench_zero_tool_runs():
    import json
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo,
               BENCH_ZERO_HIDDEN="64", BENCH_ZERO_ITERS="3",
               BENCH_ZERO_STEPS="2")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "bench_zero.py")],
        capture_output=True, text=True, timeout=600, cwd=repo, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "zero_opt_state_ratio"
    assert rec["weights_match"] is True
    # per-device state must shrink by ~dp (8 virtual devices; padding
    # slack on small biases keeps it below exactly 8)
    assert rec["value"] > 4.0, rec
