"""In-program comm/compute overlap (ISSUE 15 tentpole a) on the
virtual 8-device CPU mesh.

Three contracts:

1. **Structure** — the compiled (scheduled) HLO of the fused training
   step shows its per-bucket gradient collectives distributed through
   the backward/update compute, not clumped into one monolithic
   region: async ``*-start``/``*-done`` pairs with compute between
   them on toolchains that split collectives (TPU/GPU with
   MXNET_ASYNC_COLLECTIVES), or >= 2 collective groups separated by
   scheduled compute on sync-collective backends (this CPU build).
   ``mxnet_tpu.hlo.overlap_report`` is the single reader of both.

2. **Numerics** — the bucketed program (MXNET_ZERO_BUCKET_BYTES small
   => many buckets) matches the monolithic-collective program
   (``=0`` => one bucket) within 2e-5 on dp, dp x tp and
   dp x tp x pp meshes; on the dp-only mesh the match is BITWISE (the
   pack -> sum -> unpack layout is per-lane deterministic — the PR-3
   comm.py contract carried into the fused program).

3. **Attribution** — Module.account_program_comm feeds the goodput
   tracker a collective fraction from the compiled step's own cost
   surface, and the step-time decomposition keeps summing to 1.
"""

import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import hlo as mxhlo
from mxnet_tpu import parallel, profiler

RULES = (("hidden", "tp"), ("embed", None))


def _sym(blocks=4, hidden=32, pp_annot=False):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(
        data, num_hidden=hidden, name="inproj",
        weight=mx.sym.Variable("inproj_weight",
                               attr=parallel.logical_axes("hidden",
                                                          "embed")))
    for i in range(blocks):
        scope = mx.AttrScope(__pp_block__=str(i)) if pp_annot else None
        if scope is not None:
            with scope:
                h = mx.sym.FullyConnected(net, num_hidden=hidden,
                                          name=f"blk{i}_fc")
                net = net + mx.sym.Activation(h, act_type="relu")
        else:
            h = mx.sym.FullyConnected(net, num_hidden=hidden,
                                      name=f"blk{i}_fc")
            net = net + mx.sym.Activation(h, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="head")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _train(plan, steps=3, pp_annot=False, optimizer="adam", batch=32):
    mx.random.seed(5)
    rng = np.random.RandomState(0)
    X = rng.randn(batch * steps, 16).astype(np.float32)
    y = rng.randint(0, 8, size=batch * steps).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=batch)
    mod = mx.mod.Module(_sym(pp_annot=pp_annot), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params(mx.initializer.Uniform(0.07))
    mod.set_mesh_plan(plan)
    mod.init_optimizer(kvstore="tpu", optimizer=optimizer,
                       optimizer_params={"learning_rate": 0.05})
    for b in it:
        mod.forward_backward(b)
        mod.update()
    args, _ = mod.get_params()
    return mod, {k: np.asarray(mx.nd.gather_global(v))
                 for k, v in args.items()}


def _plans():
    import jax

    devs = jax.devices()
    return {
        "dp": lambda: parallel.MeshPlan(devs, dp=8, rules=RULES),
        "dp_tp": lambda: parallel.MeshPlan(devs, dp=4, tp=2,
                                           rules=RULES),
        "dp_tp_pp": lambda: parallel.MeshPlan(devs, dp=2, tp=2, pp=2,
                                              microbatches=2,
                                              rules=RULES),
    }


# ---------------------------------------------------------------------------
# 1. structural overlap in the compiled HLO
# ---------------------------------------------------------------------------

def test_fused_step_hlo_shows_overlap_structure(monkeypatch):
    """Per-bucket collectives interleave with scheduled compute in the
    fused step's compiled HLO; any async start/done pairs the backend
    creates must bracket real compute."""
    monkeypatch.setenv("MXNET_ZERO_BUCKET_BYTES", "4096")
    mod, _ = _train(_plans()["dp"]())
    assert len(mod._zero_buckets) >= 2  # the decomposition happened
    report = mxhlo.overlap_report(mod.fused_hlo_text())
    # collectives exist (ZeRO reduce + param all-gather)
    assert sum(report["collectives"].values()) >= len(mod._zero_buckets)
    assert report["overlapped"], report
    assert report["compute_between"] > 0, report
    # on an async backend every counted pair brackets compute by
    # definition; on this CPU build the sync schedule must interleave
    has_async = any(k.endswith("-start")
                    for k in report["collectives"])
    if has_async:
        assert report["async_pairs"] > 0, report
    else:
        assert report["interleaved_groups"] >= 2, report


def test_fused_step_hlo_pp_has_collective_permute(monkeypatch):
    """The stage-resident pipelined step moves activations between
    stages with collective-permute (the shard_map ppermute helpers) —
    visible in the compiled HLO."""
    monkeypatch.setenv("MXNET_PP_RESIDENT", "1")
    mod, _ = _train(_plans()["dp_tp_pp"](), pp_annot=True)
    assert mod._pp_resident
    report = mxhlo.overlap_report(mod.fused_hlo_text())
    names = set(report["collectives"])
    assert any("collective-permute" in n for n in names), report


def test_overlap_report_async_pairs_branch():
    """The inspector's TPU/GPU branch: ``*-start``/``*-done`` pairs
    count as overlapped ONLY when compute is scheduled between them."""
    overlapped = """HloModule m, is_scheduled=true
ENTRY %main {
  %p0 = f32[8,8]{1,0} parameter(0)
  %ags = (f32[8]{0}, f32[64]{0}) all-gather-start(f32[8]{0} %x)
  %f1 = f32[8,8]{1,0} fusion(f32[8,8]{1,0} %p0), kind=kLoop
  %d1 = f32[8,8]{1,0} dot(f32[8,8]{1,0} %f1, f32[8,8]{1,0} %p0)
  %agd = f32[64]{0} all-gather-done((f32[8]{0}, f32[64]{0}) %ags)
  ROOT %r = f32[8,8]{1,0} fusion(f32[8,8]{1,0} %d1), kind=kLoop
}
"""
    r = mxhlo.overlap_report(overlapped)
    assert r["async_pairs"] == 1 and r["overlapped"]
    serialized = overlapped.replace(
        "  %f1 = f32[8,8]{1,0} fusion(f32[8,8]{1,0} %p0), kind=kLoop\n"
        "  %d1 = f32[8,8]{1,0} dot(f32[8,8]{1,0} %f1, f32[8,8]{1,0} %p0)\n",
        "").replace(
        "ROOT %r = f32[8,8]{1,0} fusion(f32[8,8]{1,0} %d1), kind=kLoop",
        "ROOT %r = f32[8,8]{1,0} fusion(f32[8,8]{1,0} %p0), kind=kLoop")
    r2 = mxhlo.overlap_report(serialized)
    assert r2["async_pairs"] == 0  # back-to-back start/done = no overlap
    assert not r2["overlapped"]
    # byte accounting: the start's tuple counts only the RESULT
    # component (f32[64] = 256B), not the carried operand buffer
    assert mxhlo.collective_bytes(overlapped) == 256


# ---------------------------------------------------------------------------
# 2. bucketed == monolithic numerics
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mesh", ["dp", "dp_tp", "dp_tp_pp"])
def test_bucketed_matches_monolithic(mesh, monkeypatch):
    plans = _plans()
    pp_annot = mesh == "dp_tp_pp"
    monkeypatch.setenv("MXNET_ZERO_BUCKET_BYTES", "0")  # monolithic
    _, mono = _train(plans[mesh](), pp_annot=pp_annot)
    monkeypatch.setenv("MXNET_ZERO_BUCKET_BYTES", "2048")  # many buckets
    mod, bucketed = _train(plans[mesh](), pp_annot=pp_annot)
    if not pp_annot:  # resident pp routes the trunk via slabs instead
        assert len(mod._zero_buckets) >= 2
    for k in mono:
        np.testing.assert_allclose(mono[k], bucketed[k], rtol=2e-4,
                                   atol=2e-5, err_msg=f"{mesh}:{k}")


def test_bucketed_is_bitwise_on_dp(monkeypatch):
    """The per-lane pack -> sum -> unpack determinism contract: on the
    dp-only mesh the bucket width never changes a single bit."""
    monkeypatch.setenv("MXNET_ZERO_BUCKET_BYTES", "0")
    _, mono = _train(_plans()["dp"]())
    monkeypatch.setenv("MXNET_ZERO_BUCKET_BYTES", "2048")
    _, bucketed = _train(_plans()["dp"]())
    for k in mono:
        np.testing.assert_array_equal(mono[k], bucketed[k], err_msg=k)


def test_buckets_are_backward_ordered_and_capped(monkeypatch):
    monkeypatch.setenv("MXNET_ZERO_BUCKET_BYTES", "4096")
    mod, _ = _train(_plans()["dp"]())
    order = [n for b in mod._zero_buckets for n in b]
    assert order == list(reversed(mod._grad_param_names))
    dp = mod._mesh_plan.dp
    for bucket in mod._zero_buckets:
        nbytes = sum(mod._zero_meta[n][1] * 4 for n in bucket)
        assert len(bucket) == 1 or nbytes <= 4096


# ---------------------------------------------------------------------------
# 3. goodput attribution of in-program collectives
# ---------------------------------------------------------------------------

def test_account_program_comm_feeds_tracker():
    mod, _ = _train(_plans()["dp"]())
    frac = mod.account_program_comm()
    assert frac is not None and 0 < frac <= 0.9
    assert mod._program_comm_fraction == frac


def test_program_comm_fraction_decomposition_sums_to_one():
    g = profiler.GoodputTracker(registry=profiler.MetricsRegistry())
    g.set_program_comm_fraction(0.25)
    for _ in range(4):
        g.step(0.1, io_s=0.02)
    s = g.summary()
    d = s["decomposition"]
    assert sum(d.values()) == pytest.approx(1.0)
    # 25% of the in-step time books as comm WITHOUT any scheduler waits
    assert d["comm"] == pytest.approx(0.025 / 0.12, rel=1e-6)
    assert s["program_comm_fraction"] == 0.25
    # composes with host-side comm: scheduler waits come off the top
    g2 = profiler.GoodputTracker(registry=profiler.MetricsRegistry())
    g2.set_program_comm_fraction(0.5)
    g2.add_comm(0.04)
    g2.step(0.1)
    d2 = g2.summary()["decomposition"]
    assert sum(d2.values()) == pytest.approx(1.0)
    assert d2["comm"] == pytest.approx((0.04 + 0.5 * 0.06) / 0.1,
                                       rel=1e-6)


# ---------------------------------------------------------------------------
# env validation + flag wiring
# ---------------------------------------------------------------------------

def test_zero_bucket_bytes_validation(monkeypatch):
    for bad in ("banana", "-1"):
        monkeypatch.setenv("MXNET_ZERO_BUCKET_BYTES", bad)
        with pytest.raises(mx.MXNetError, match="MXNET_ZERO_BUCKET"):
            _train(_plans()["dp"](), steps=1)


def test_pp_resident_validation(monkeypatch):
    monkeypatch.setenv("MXNET_PP_RESIDENT", "banana")
    with pytest.raises(mx.MXNetError, match="MXNET_PP_RESIDENT"):
        _train(_plans()["dp_tp_pp"](), steps=1, pp_annot=True)


def test_async_collectives_validation(monkeypatch):
    from mxnet_tpu import config

    monkeypatch.setenv("MXNET_ASYNC_COLLECTIVES", "banana")
    with pytest.raises(mx.MXNetError, match="MXNET_ASYNC_COLLECTIVES"):
        config.ensure_overlap_flags()


def test_async_flags_appended_only_for_accelerators(monkeypatch):
    from mxnet_tpu import config

    # CPU: untouched (the TPU flag names are fatal-unknown there)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("XLA_FLAGS", "--xla_foo=1")
    assert config.ensure_overlap_flags() is False
    assert os.environ["XLA_FLAGS"] == "--xla_foo=1"
    # TPU: the async-collective set lands, user flags never overridden
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    monkeypatch.setenv(
        "XLA_FLAGS", "--xla_enable_async_all_gather=false")
    assert config.ensure_overlap_flags() is True
    flags = os.environ["XLA_FLAGS"].split()
    assert "--xla_enable_async_all_gather=false" in flags  # user wins
    assert flags.count("--xla_enable_async_all_gather=false") == 1
    assert not any(f == "--xla_enable_async_all_gather=true"
                   for f in flags)
    assert "--xla_tpu_enable_async_collective_fusion=true" in flags
    # off switch
    monkeypatch.setenv("MXNET_ASYNC_COLLECTIVES", "0")
    monkeypatch.setenv("XLA_FLAGS", "")
    assert config.ensure_overlap_flags() is False
    assert os.environ["XLA_FLAGS"] == ""
