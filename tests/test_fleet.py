"""Fleet-router semantics with in-process fake replicas (tier-1 fast):
retry-exactly-once on replica death, typed deadline shedding with
oldest-deadline-first ordering, zero-drop rolling weight swap, the
fleet wire (HMAC'd control frames), and the engine-side inflight/
drain/swap hooks.  The real multi-process kill -9 drill lives in
tools/bench_fleet.py and runs under the `slow` marker."""

import json
import os
import queue
import subprocess
import sys
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import checkpoint as ckpt_mod
from mxnet_tpu import fleet
from mxnet_tpu.base import MXNetError
from mxnet_tpu.elastic import HeartbeatWriter
from mxnet_tpu.fleet import (FleetClient, ReplicaClient, ReplicaServer,
                             Router, ShedError)
from mxnet_tpu.serving import ReplicaHarness


class FakeReplica:
    """In-process replica handle: one worker thread answering requests
    after ``service_ms``.  Implements the Router's handle duck type
    exactly (submit→Future-of-list, inflight, drain, resume, swap,
    stats, close) plus fault injection: ``freeze()`` stops answering
    (responses are HELD, like a replica that wedged), ``kill()``
    additionally stops the heartbeat, ``flush()`` releases held
    answers late (the zombie's last gasp)."""

    def __init__(self, rid, service_ms=2.0, hb_dir=None,
                 hb_interval=0.05, scale=1.0):
        self.rid = rid
        self.scale = scale
        self.service_s = service_ms / 1e3
        self.served = []          # specs answered (distribution asserts)
        self.swapped = []         # (step, inflight_at_swap)
        self.weights_step = -1
        self._q = queue.Queue()
        self._held = []
        self._frozen = threading.Event()
        self._lock = threading.Lock()
        self._inflight = set()
        self._accepting = True
        self._hb = HeartbeatWriter(hb_dir, rid, interval=hb_interval) \
            if hb_dir else None
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    # -- handle surface -------------------------------------------------
    def submit(self, spec):
        fut = Future()
        with self._lock:
            if not self._accepting:
                raise ConnectionError(f"replica {self.rid} is down")
            self._inflight.add(fut)
        self._q.put((spec, fut))
        return fut

    def inflight(self):
        with self._lock:
            return len(self._inflight)

    def drain(self, timeout=30.0):
        deadline = time.monotonic() + timeout
        while self.inflight() and time.monotonic() < deadline:
            time.sleep(0.002)
        return self.inflight()

    def resume(self):
        pass

    def swap(self, ckpt_dir, drain_timeout=60.0):
        _params, step, path = ckpt_mod.load_latest_params(ckpt_dir)
        self.swapped.append((step, self.inflight()))
        if self.inflight():
            raise MXNetError(
                f"swap with {self.inflight()} in flight — the router "
                "failed to drain this replica")
        self.weights_step = step
        return {"step": step, "path": path}

    def stats(self):
        return {"rid": self.rid, "served": len(self.served)}

    def close(self):
        pass

    # -- fault injection ------------------------------------------------
    def freeze(self):
        self._frozen.set()

    def kill(self):
        """kill -9 equivalent: stop answering AND stop heartbeating."""
        self.freeze()
        with self._lock:
            self._accepting = False
        if self._hb is not None:
            self._hb.stop(remove=True)

    def flush(self):
        """Release answers held while frozen — the zombie's late
        responses arriving after conviction."""
        held, self._held = self._held, []
        for spec, fut, result in held:
            self._finish(spec, fut, result)

    # -- worker ---------------------------------------------------------
    def _run(self):
        while True:
            spec, fut = self._q.get()
            if spec is None:
                return
            time.sleep(self.service_s)
            result = self._answer(spec)
            if self._frozen.is_set():
                self._held.append((spec, fut, result))
                continue
            self._finish(spec, fut, result)

    def _finish(self, spec, fut, result):
        with self._lock:
            self._inflight.discard(fut)
        self.served.append(spec)
        if fut.set_running_or_notify_cancel():
            fut.set_result(result)

    def _answer(self, spec):
        if spec["kind"] == "infer":
            x = next(iter(spec["inputs"].values()))
            return [np.asarray(x, np.float64) * self.scale]
        # decode: deterministic in (prompt, seed) — replica-independent,
        # like the real engines' seeded sampling
        p = np.asarray(spec["prompt"])
        seed = int(spec["seed"])
        return [np.asarray([(int(p.sum()) * 7 + seed * 31 + i) % 997
                            for i in range(int(spec["max_new"]))],
                           np.int32)]


def _router(replicas, **kw):
    kw.setdefault("retry_budget", 2)
    kw.setdefault("default_deadline_ms", 0)
    return Router(replicas, **kw)


def _results(futs, timeout=30.0):
    return [f.result(timeout) for f in futs]


# ---------------------------------------------------------------------------
# routing + spreading
# ---------------------------------------------------------------------------


def test_router_spreads_and_answers_correctly():
    reps = [FakeReplica(0), FakeReplica(1)]
    with _router(reps) as r:
        futs = [r.submit({"x": np.full((1, 3), i, np.float64)})
                for i in range(16)]
        outs = _results(futs)
        for i, out in enumerate(outs):
            assert np.array_equal(out[0], np.full((1, 3), i))
        assert len(reps[0].served) + len(reps[1].served) == 16
        # least-depth routing with 2 idle replicas must use both
        assert len(reps[0].served) > 0 and len(reps[1].served) > 0
        s = r.stats()
        assert s["responses"] == 16 and s["shed"] == 0
        assert s["retries"] == 0 and s["replica_deaths"] == 0


def test_decode_routes_and_unwraps_tokens():
    reps = [FakeReplica(0)]
    with _router(reps) as r:
        out = r.generate(np.asarray([3, 5], np.int32),
                         max_new_tokens=4).result(10)
        assert out.dtype == np.int32 and out.shape == (4,)


# ---------------------------------------------------------------------------
# replica death: transparent retry, exactly-once
# ---------------------------------------------------------------------------


def test_retry_exactly_once_on_replica_death(tmp_path):
    hb = str(tmp_path)
    reps = [FakeReplica(0, hb_dir=hb), FakeReplica(1, hb_dir=hb)]
    with _router(reps, fleet_dir=hb, dead_timeout=0.3,
                 replica_depth=4) as r:
        # kill replica 0 with work in flight: its requests must retry
        # on replica 1 and every client future must still resolve
        reps[0].service_s = 0.2
        futs = [r.submit({"x": np.full((1, 2), i, np.float64)})
                for i in range(8)]
        time.sleep(0.05)
        reps[0].kill()
        outs = _results(futs, timeout=30.0)
        for i, out in enumerate(outs):
            assert np.array_equal(out[0], np.full((1, 2), i))
        s = r.stats()
        assert s["replica_deaths"] == 1
        assert s["retries"] >= 1
        assert s["responses"] == 8 and s["failures"] == 0
        assert r.alive_replicas() == [1]
        # every request answered exactly once client-side
        assert all(f.done() for f in futs)


def test_zombie_late_answer_is_dropped_not_double_delivered(tmp_path):
    hb = str(tmp_path)
    reps = [FakeReplica(0, hb_dir=hb), FakeReplica(1, hb_dir=hb)]
    with _router(reps, fleet_dir=hb, dead_timeout=0.3,
                 replica_depth=8) as r:
        # slow enough that most of replica 0's share is still in
        # service when the freeze lands (held, not yet answered)
        reps[0].service_s = 0.04
        futs = [r.submit({"x": np.full((1, 2), i, np.float64)})
                for i in range(8)]
        time.sleep(0.06)
        reps[0].kill()
        outs = _results(futs, timeout=30.0)
        held = len(reps[0]._held)
        assert held > 0, "zombie held nothing — the fault never fired"
        # now the zombie's held answers arrive late
        reps[0].flush()
        time.sleep(0.3)
        s = r.stats()
        # exactly-once: every late answer was for an already-delivered
        # ticket — counted as a duplicate and DROPPED, responses stay 8
        assert s["responses"] == 8
        assert s["duplicates"] == held
        for i, out in enumerate(outs):
            assert np.array_equal(out[0], np.full((1, 2), i))


def test_retry_budget_exhaustion_fails_loudly(tmp_path):
    hb = str(tmp_path)
    reps = [FakeReplica(0, hb_dir=hb)]
    with _router(reps, fleet_dir=hb, dead_timeout=0.3,
                 retry_budget=0) as r:
        reps[0].service_s = 0.5
        fut = r.submit({"x": np.ones((1, 2))})
        time.sleep(0.05)
        reps[0].kill()
        with pytest.raises(MXNetError, match="retry budget"):
            fut.result(30.0)


def test_decode_retry_is_bit_identical(tmp_path):
    """The acceptance property: a retried decode yields the SAME
    tokens a single-replica run yields — the router's deterministic
    seed stamp + seed-keyed sampling."""
    hb = str(tmp_path)
    prompts = [np.asarray([2 + i, 9], np.int32) for i in range(6)]

    # single-replica reference run
    ref_rep = FakeReplica(0)
    with _router([ref_rep]) as r:
        ref = [r.generate(p, max_new_tokens=5).result(10) for p in prompts]

    reps = [FakeReplica(0, hb_dir=hb), FakeReplica(1, hb_dir=hb)]
    with _router(reps, fleet_dir=hb, dead_timeout=0.3) as r:
        reps[0].service_s = 0.15
        futs = [r.generate(p, max_new_tokens=5) for p in prompts]
        time.sleep(0.05)
        reps[0].kill()
        outs = _results(futs, timeout=30.0)
    for a, b in zip(ref, outs):
        assert np.array_equal(a, b), "retried decode re-sampled tokens"


# ---------------------------------------------------------------------------
# admission control + shedding
# ---------------------------------------------------------------------------


def _prime_cost(router, n=4, units=1):
    """Teach the cost model its first EMA samples."""
    futs = [router.submit({"x": np.zeros((units, 2))}) for _ in range(n)]
    _results(futs)


def test_deadline_provably_unmeetable_sheds_typed():
    rep = FakeReplica(0, service_ms=60.0)
    with _router([rep], replica_depth=2) as r:
        _prime_cost(r)
        # occupy the replica, then ask for the impossible
        bg = [r.submit({"x": np.zeros((1, 2))}) for _ in range(2)]
        fut = r.submit({"x": np.zeros((1, 2))}, deadline_ms=5.0)
        with pytest.raises(ShedError) as ei:
            fut.result(10)
        assert ei.value.reason in ("deadline", "expired")
        _results(bg)  # in-flight work unaffected by the shed
        assert r.stats()["shed"] == 1


def test_no_measurement_means_no_shed():
    """'Provably' requires measurements: an unmeasured bucket admits
    (measure instead of assume — the PR-1 exploration rule)."""
    rep = FakeReplica(0, service_ms=1.0)
    with _router([rep]) as r:
        out = r.submit({"x": np.zeros((1, 2))},
                       deadline_ms=10_000).result(10)
        assert out[0].shape == (1, 2)
        assert r.stats()["shed"] == 0


def test_overload_sheds_oldest_deadline_first():
    rep = FakeReplica(0, service_ms=80.0)
    with _router([rep], replica_depth=1, max_pending=2) as r:
        _prime_cost(r, n=2)
        # one in flight; then flood with staggered deadlines.  The
        # queue bound is 2, so the EARLIEST deadlines must shed first.
        deadlines = [5000.0, 500.0, 3000.0, 1000.0, 9000.0]
        futs = [r.submit({"x": np.full((1, 2), i)}, deadline_ms=d)
                for i, d in enumerate(deadlines)]
        shed, ok = [], []
        for d, f in zip(deadlines, futs):
            try:
                f.result(30)
                ok.append(d)
            except ShedError:
                shed.append(d)
        assert shed, "overload never shed"
        # ordering property: every shed deadline <= every survivor's
        assert max(shed) <= min(ok) + 1e-9
        s = r.stats()
        assert s["shed"] == len(shed) and s["shed"] >= 1


def test_fleet_env_validation_garbage_raises(monkeypatch):
    rep = FakeReplica(0)
    monkeypatch.setenv("MXNET_FLEET_RETRY_BUDGET", "banana")
    with pytest.raises(MXNetError, match="MXNET_FLEET_RETRY_BUDGET"):
        Router([rep])
    monkeypatch.setenv("MXNET_FLEET_RETRY_BUDGET", "-3")
    with pytest.raises(MXNetError, match="MXNET_FLEET_RETRY_BUDGET"):
        Router([rep])
    monkeypatch.delenv("MXNET_FLEET_RETRY_BUDGET")
    monkeypatch.setenv("MXNET_FLEET_SHED_DEADLINE_MS", "-1")
    with pytest.raises(MXNetError, match="MXNET_FLEET_SHED_DEADLINE_MS"):
        Router([rep])
    monkeypatch.delenv("MXNET_FLEET_SHED_DEADLINE_MS")
    monkeypatch.setenv("MXNET_FLEET_SWAP_DRAIN_TIMEOUT", "0")
    with pytest.raises(MXNetError,
                       match="MXNET_FLEET_SWAP_DRAIN_TIMEOUT"):
        Router([rep])


# ---------------------------------------------------------------------------
# rolling weight swap
# ---------------------------------------------------------------------------


def test_swap_weights_drains_zero_requests(tmp_path):
    pub = ckpt_mod.publish_params(
        str(tmp_path / "pub"), {"w": np.arange(4.0)}, step=7)
    reps = [FakeReplica(0, service_ms=3.0), FakeReplica(1, service_ms=3.0)]
    with _router(reps, replica_depth=4) as r:
        stop = threading.Event()
        errors, answered = [], []

        def client():
            i = 0
            while not stop.is_set():
                try:
                    out = r.submit(
                        {"x": np.full((1, 2), i, np.float64)}).result(30)
                    assert np.array_equal(out[0], np.full((1, 2), i))
                    answered.append(i)
                except BaseException as exc:  # noqa: BLE001
                    errors.append(exc)
                i += 1

        threads = [threading.Thread(target=client) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.1)
        report = r.swap_weights(str(tmp_path / "pub"))
        time.sleep(0.1)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not errors, f"swap dropped/shed requests: {errors[:3]}"
        assert len(answered) > 20
        assert report["step"] == 7
        assert sorted(report["replicas"]) == [0, 1]
        for rep in reps:
            # each replica swapped exactly once, with ZERO in flight
            assert [s for s, _ in rep.swapped] == [7]
            assert [n for _, n in rep.swapped] == [0]
            assert rep.weights_step == 7
        s = r.stats()
        assert s["swaps"] == 1 and s["shed"] == 0 \
            and s["failures"] == 0
        assert s["weights_step"] == 7
        assert pub == report["path"]


def test_swap_weights_refuses_bad_checkpoint(tmp_path):
    reps = [FakeReplica(0)]
    with _router(reps) as r:
        with pytest.raises(MXNetError, match="committed"):
            r.swap_weights(str(tmp_path / "nope"))
        assert reps[0].swapped == []  # nothing was taken out of rotation


# ---------------------------------------------------------------------------
# the wire: router server + client, replica server + handle
# ---------------------------------------------------------------------------


def test_router_wire_roundtrip_and_hmac(tmp_path):
    secret = b"wire-secret"
    reps = [FakeReplica(0), FakeReplica(1)]
    with _router(reps, secret=secret) as r:
        port = r.serve()
        with FleetClient("127.0.0.1", port, secret=secret) as cl:
            # infer
            out = cl.submit({"x": np.full((2, 3), 4.5)}).result(30)
            assert np.array_equal(out[0], np.full((2, 3), 4.5))
            # decode (tokens unwrapped client-side)
            toks = cl.generate(np.asarray([1, 2, 3], np.int32),
                               max_new_tokens=4).result(30)
            assert toks.dtype == np.int32 and toks.shape == (4,)
            # stats over the signed control channel
            s = cl.stats()
            assert s["responses"] >= 2
            # swap over the wire
            ckpt_mod.publish_params(str(tmp_path / "pub"),
                                    {"w": np.zeros(2)}, step=3)
            rep = cl.swap_weights(str(tmp_path / "pub"))
            assert rep["step"] == 3
        # a client with the wrong secret: tensor traffic still works
        # (never pickled), CONTROL is refused before parsing
        with FleetClient("127.0.0.1", port, secret=b"evil") as cl2:
            out = cl2.submit({"x": np.ones((1, 2))}).result(30)
            assert np.array_equal(out[0], np.ones((1, 2)))
            with pytest.raises(MXNetError, match="HMAC"):
                cl2.stats()


def test_wire_shed_travels_typed():
    rep = FakeReplica(0, service_ms=60.0)
    with _router([rep], replica_depth=1) as r:
        _prime_cost(r)
        port = r.serve()
        with FleetClient("127.0.0.1", port) as cl:
            bg = [cl.submit({"x": np.zeros((1, 2))}) for _ in range(3)]
            fut = cl.submit({"x": np.zeros((1, 2))}, deadline_ms=1.0)
            with pytest.raises(ShedError):
                fut.result(30)
            for f in bg:
                f.result(30)


def test_replica_server_real_engine_roundtrip(tmp_path):
    """ReplicaServer over a real InferenceEngine: submit, inflight,
    drain/resume, weight swap through a published checkpoint — the
    single-replica slice of the fleet, no subprocess."""
    from tests.test_serving import _mlp_predictor

    pred, net, (arg, aux) = _mlp_predictor()
    eng = mx.InferenceEngine(pred, buckets=(1, 4), batch_timeout_ms=1.0)
    secret = b"replica-secret"
    srv = ReplicaServer(ReplicaHarness(eng), rid=0,
                        fleet_dir=str(tmp_path / "fleet"), secret=secret)
    try:
        handle = ReplicaClient(0, "127.0.0.1", srv.port, secret=secret)
        x = np.random.RandomState(3).rand(1, 6).astype(np.float32)
        pred_ref = mx.Predictor(net, {**arg, **aux}, {"data": (1, 6)})
        pred_ref.forward(data=x)
        want = pred_ref.get_output(0)
        out = handle.submit({"kind": "infer",
                             "inputs": {"data": x}}).result(60)
        np.testing.assert_allclose(out[0], want, rtol=1e-6)
        assert handle.inflight() == 0
        # heartbeat file exists (the PR-8 liveness plane)
        assert os.path.exists(str(tmp_path / "fleet" / "hb_0"))

        # weight swap: publish scaled weights, swap, outputs change
        new_params = {k: np.asarray(v.asnumpy() if hasattr(v, "asnumpy")
                                    else v) * 2.0
                      for k, v in {**arg, **aux}.items()}
        ckpt_mod.publish_params(str(tmp_path / "pub"), new_params, step=11)
        rep = handle.swap(str(tmp_path / "pub"))
        assert rep["step"] == 11
        out2 = handle.submit({"kind": "infer",
                              "inputs": {"data": x}}).result(60)
        assert not np.allclose(out2[0], want), \
            "swap did not change served weights"
        pred_ref.set_params(new_params)
        pred_ref.forward(data=x)
        np.testing.assert_allclose(out2[0], pred_ref.get_output(0),
                                   rtol=1e-5)

        # bad HMAC on control
        evil = ReplicaClient(0, "127.0.0.1", srv.port, secret=b"evil")
        with pytest.raises(MXNetError, match="HMAC"):
            evil.inflight()
        evil.close()
        handle.close()
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# multi-process: spawn real replicas, kill -9 one (slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.skipif((os.cpu_count() or 1) < 2, reason="needs >= 2 cores")
def test_fleet_kill9_drill_loses_nothing(tmp_path):
    """The acceptance drill, in-repo: 2 real replica processes under
    closed-loop load, kill -9 one mid-stream — zero lost requests,
    answers match, then a rolling swap with zero sheds."""
    drill = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "tools", "bench_fleet.py"),
         "--drill", "--replicas", "2", "--requests", "40",
         "--fleet-dir", str(tmp_path / "fleet")],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "MXNET_DEAD_RANK_TIMEOUT": "3.0",
             "MXNET_HEARTBEAT_INTERVAL": "0.2"})
    assert drill.returncode == 0, drill.stderr[-4000:]
    verdict = json.loads(drill.stdout.strip().splitlines()[-1])
    assert verdict["lost"] == 0
    assert verdict["mismatched"] == 0
    assert verdict["replica_deaths"] == 1
    assert verdict["swap_ok"]
    assert verdict["swap_shed"] == 0
