"""SSD detection + spatial op tests (numpy references inline, the
reference's test_operator.py style)."""

import numpy as np
import pytest

import mxnet_tpu as mx


# ---------------------------------------------------------------- priors
def test_multibox_prior():
    data = mx.nd.zeros((1, 3, 2, 2))
    out = mx.nd.MultiBoxPrior(data, sizes="(0.5, 0.25)", ratios="(1, 2)")
    # apx = 2 sizes + 2 ratios - 1 = 3; 2x2 pixels
    assert out.shape == (1, 2 * 2 * 3, 4)
    a = out.asnumpy()[0]
    # first anchor: center (0.25, 0.25), size 0.5 -> [0, 0, 0.5, 0.5]
    np.testing.assert_allclose(a[0], [0.0, 0.0, 0.5, 0.5], atol=1e-6)
    # second: size 0.25 -> [.125, .125, .375, .375]
    np.testing.assert_allclose(a[1], [0.125, 0.125, 0.375, 0.375], atol=1e-6)
    # third: size .5, ratio 2 -> w = .5*sqrt2/2, h = .5/sqrt2/2
    r = np.sqrt(2.0)
    np.testing.assert_allclose(
        a[2], [0.25 - 0.25 * r, 0.25 - 0.25 / r,
               0.25 + 0.25 * r, 0.25 + 0.25 / r], atol=1e-6)


def test_multibox_prior_clip():
    data = mx.nd.zeros((1, 3, 1, 1))
    out = mx.nd.MultiBoxPrior(data, sizes="(1.5,)", clip="True").asnumpy()
    assert out.min() >= 0.0 and out.max() <= 1.0


# ---------------------------------------------------------------- target
def _iou_np(a, b):
    iw = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
    ih = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
    i = iw * ih
    u = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) - i
    return i / u if u > 0 else 0.0


def test_multibox_target_basic():
    # 3 anchors, 1 gt that overlaps anchor 0 strongly
    anchors = np.array([[[0.0, 0.0, 0.5, 0.5],
                         [0.5, 0.5, 1.0, 1.0],
                         [0.0, 0.5, 0.5, 1.0]]], np.float32)
    # one gt: class 2, box ~ anchor 0
    label = np.array([[[2, 0.05, 0.05, 0.45, 0.55],
                       [-1, -1, -1, -1, -1]]], np.float32)
    cls_pred = np.zeros((1, 4, 3), np.float32)
    loc_t, loc_m, cls_t = [o.asnumpy() for o in mx.nd.MultiBoxTarget(
        mx.nd.array(anchors), mx.nd.array(label), mx.nd.array(cls_pred),
        overlap_threshold="0.5")]
    cls_t = cls_t[0]
    assert cls_t[0] == 3.0  # class 2 + 1 (0 reserved for background)
    assert cls_t[1] == 0.0 and cls_t[2] == 0.0  # negatives
    m = loc_m[0].reshape(3, 4)
    assert m[0].sum() == 4 and m[1].sum() == 0
    # loc target encodes (gt - anchor) / variance
    t = loc_t[0].reshape(3, 4)
    np.testing.assert_allclose(
        t[0], [0.0 / 0.5 / 0.1, 0.05 / 0.5 / 0.1,
               np.log(0.4 / 0.5) / 0.2, np.log(0.5 / 0.5) / 0.2],
        atol=1e-5)


def test_multibox_target_no_gt():
    anchors = np.array([[[0.0, 0.0, 0.5, 0.5],
                         [0.5, 0.5, 1.0, 1.0]]], np.float32)
    label = np.full((1, 2, 5), -1.0, np.float32)
    cls_pred = np.zeros((1, 3, 2), np.float32)
    _, loc_m, cls_t = [o.asnumpy() for o in mx.nd.MultiBoxTarget(
        mx.nd.array(anchors), mx.nd.array(label), mx.nd.array(cls_pred))]
    assert (cls_t == -1.0).all()  # everything ignored
    assert (loc_m == 0).all()


def test_multibox_target_negative_mining():
    rng = np.random.RandomState(0)
    anchors = np.zeros((1, 20, 4), np.float32)
    # grid of anchors
    for i in range(20):
        x = (i % 5) * 0.2
        y = (i // 5) * 0.25
        anchors[0, i] = [x, y, x + 0.2, y + 0.25]
    label = np.array([[[1, 0.0, 0.0, 0.2, 0.25],
                       [-1, -1, -1, -1, -1]]], np.float32)
    cls_pred = rng.randn(1, 3, 20).astype(np.float32)
    _, _, cls_t = [o.asnumpy() for o in mx.nd.MultiBoxTarget(
        mx.nd.array(anchors), mx.nd.array(label), mx.nd.array(cls_pred),
        negative_mining_ratio="3", negative_mining_thresh="0.5")]
    cls_t = cls_t[0]
    assert (cls_t == 2.0).sum() == 1           # one positive (class 1 + 1)
    assert (cls_t == 0.0).sum() == 3           # ratio 3 -> 3 negatives
    assert (cls_t == -1.0).sum() == 16         # rest ignored


# ------------------------------------------------------------- detection
def test_multibox_detection_decode_and_nms():
    anchors = np.array([[[0.1, 0.1, 0.3, 0.3],
                         [0.11, 0.11, 0.31, 0.31],
                         [0.6, 0.6, 0.9, 0.9]]], np.float32)
    # class probs (B, C, A): anchor0/1 -> class 1, anchor2 -> class 2
    cls_prob = np.array([[[0.1, 0.2, 0.1],
                          [0.8, 0.7, 0.1],
                          [0.1, 0.1, 0.8]]], np.float32)
    loc_pred = np.zeros((1, 12), np.float32)  # no regression offsets
    out = mx.nd.MultiBoxDetection(
        mx.nd.array(cls_prob), mx.nd.array(loc_pred), mx.nd.array(anchors),
        nms_threshold="0.5", threshold="0.3").asnumpy()[0]
    # sorted by score: anchor0 (0.8 cls0), anchor2 (0.8 cls1), anchor1 nms'd
    kept = out[out[:, 0] >= 0]
    assert len(kept) == 2
    assert set(kept[:, 0]) == {0.0, 1.0}
    # decoded box of anchor2 (no offsets -> anchor itself)
    row = kept[kept[:, 0] == 1.0][0]
    np.testing.assert_allclose(row[2:], [0.6, 0.6, 0.9, 0.9], atol=1e-5)


def test_multibox_detection_force_suppress():
    anchors = np.array([[[0.1, 0.1, 0.3, 0.3],
                         [0.11, 0.11, 0.31, 0.31]]], np.float32)
    cls_prob = np.array([[[0.1, 0.2],
                          [0.8, 0.1],
                          [0.1, 0.7]]], np.float32)  # different classes
    loc_pred = np.zeros((1, 8), np.float32)
    keep_per_class = mx.nd.MultiBoxDetection(
        mx.nd.array(cls_prob), mx.nd.array(loc_pred), mx.nd.array(anchors),
        nms_threshold="0.5").asnumpy()[0]
    assert (keep_per_class[:, 0] >= 0).sum() == 2  # different class: kept
    forced = mx.nd.MultiBoxDetection(
        mx.nd.array(cls_prob), mx.nd.array(loc_pred), mx.nd.array(anchors),
        nms_threshold="0.5", force_suppress="True").asnumpy()[0]
    assert (forced[:, 0] >= 0).sum() == 1  # cross-class suppression


# ------------------------------------------------------------- smooth_l1
def test_smooth_l1():
    x = np.array([-2.0, -0.5, 0.0, 0.3, 1.5], np.float32)
    out = mx.nd.smooth_l1(mx.nd.array(x), scalar="1.0").asnumpy()
    expect = np.where(np.abs(x) < 1, 0.5 * x * x, np.abs(x) - 0.5)
    np.testing.assert_allclose(out, expect, rtol=1e-6)
    # sigma = 2: threshold at 1/4
    out = mx.nd.smooth_l1(mx.nd.array(x), scalar="2.0").asnumpy()
    expect = np.where(np.abs(x) < 0.25, 0.5 * 4 * x * x,
                      np.abs(x) - 0.125)
    np.testing.assert_allclose(out, expect, rtol=1e-6)


# ------------------------------------------------------------ ROIPooling
def test_roi_pooling():
    data = np.arange(1 * 1 * 6 * 6, dtype=np.float32).reshape(1, 1, 6, 6)
    rois = np.array([[0, 0, 0, 5, 5]], np.float32)  # whole image
    out = mx.nd.ROIPooling(mx.nd.array(data), mx.nd.array(rois),
                           pooled_size="(2, 2)", spatial_scale="1.0")
    assert out.shape == (1, 1, 2, 2)
    o = out.asnumpy()[0, 0]
    # max of each 3x3 quadrant
    np.testing.assert_allclose(o, [[14, 17], [32, 35]])


def test_roi_pooling_scale_and_batch_index():
    data = np.stack([np.zeros((1, 4, 4), np.float32),
                     np.ones((1, 4, 4), np.float32)])
    rois = np.array([[1, 0, 0, 7, 7]], np.float32)  # second image, scale .5
    out = mx.nd.ROIPooling(mx.nd.array(data), mx.nd.array(rois),
                           pooled_size="(1, 1)", spatial_scale="0.5")
    np.testing.assert_allclose(out.asnumpy(), [[[[1.0]]]])


def test_roi_pooling_gradient_flows():
    data = mx.sym.Variable("data")
    rois = mx.sym.Variable("rois")
    pooled = mx.sym.ROIPooling(data, rois, pooled_size="(2, 2)",
                               spatial_scale="1.0", name="roi")
    loss = mx.sym.MakeLoss(mx.sym.sum(pooled))
    exe = loss.simple_bind(mx.cpu(), grad_req={"data": "write",
                                               "rois": "null"},
                           data=(1, 1, 4, 4), rois=(1, 5))
    exe.arg_dict["data"][:] = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    exe.arg_dict["rois"][:] = np.array([[0, 0, 0, 3, 3]], np.float32)
    exe.forward(is_train=True)
    exe.backward()
    g = exe.grad_dict["data"].asnumpy()[0, 0]
    # max elements of each 2x2 bin get gradient 1
    assert g.sum() == 4 and g[1, 1] == 1 and g[3, 3] == 1


# --------------------------------------------- SpatialTransformer / Grid
def test_spatial_transformer_identity():
    rng = np.random.RandomState(0)
    x = rng.rand(2, 3, 5, 7).astype(np.float32)
    theta = np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32), (2, 1))
    out = mx.nd.SpatialTransformer(
        mx.nd.array(x), mx.nd.array(theta), target_shape="(5, 7)",
        transform_type="affine", sampler_type="bilinear")
    np.testing.assert_allclose(out.asnumpy(), x, atol=1e-5)


def test_spatial_transformer_shift():
    x = np.zeros((1, 1, 3, 3), np.float32)
    x[0, 0, 1, 1] = 1.0
    # translate by one pixel right: x' = x + 2/(W-1)
    theta = np.array([[1, 0, -1.0, 0, 1, 0]], np.float32)
    out = mx.nd.SpatialTransformer(
        mx.nd.array(x), mx.nd.array(theta), target_shape="(3, 3)",
        transform_type="affine", sampler_type="bilinear").asnumpy()
    assert out[0, 0, 1, 2] == 1.0  # peak moved right


def test_grid_generator_affine_plus_sampler():
    rng = np.random.RandomState(1)
    x = rng.rand(1, 2, 4, 4).astype(np.float32)
    theta = np.array([[1, 0, 0, 0, 1, 0]], np.float32)
    grid = mx.nd.GridGenerator(mx.nd.array(theta), transform_type="affine",
                               target_shape="(4, 4)")
    assert grid.shape == (1, 2, 4, 4)
    out = mx.nd.BilinearSampler(mx.nd.array(x), grid)
    np.testing.assert_allclose(out.asnumpy(), x, atol=1e-5)


def test_grid_generator_warp_zero_flow():
    flow = np.zeros((1, 2, 3, 3), np.float32)
    grid = mx.nd.GridGenerator(mx.nd.array(flow),
                               transform_type="warp").asnumpy()
    np.testing.assert_allclose(grid[0, 0, 0], [-1, 0, 1], atol=1e-6)
    np.testing.assert_allclose(grid[0, 1, :, 0], [-1, 0, 1], atol=1e-6)


# ------------------------------------------------------------ Correlation
def test_correlation_identity():
    rng = np.random.RandomState(2)
    x = rng.rand(1, 4, 5, 5).astype(np.float32)
    out = mx.nd.Correlation(mx.nd.array(x), mx.nd.array(x),
                            kernel_size="1", max_displacement="1",
                            stride1="1", stride2="1", pad_size="1",
                            is_multiply="True")
    # D = 3 -> 9 channels; center channel (4) = mean over C of x*x
    assert out.shape == (1, 9, 5, 5)
    center = out.asnumpy()[0, 4]
    np.testing.assert_allclose(center, (x[0] ** 2).mean(axis=0), rtol=1e-5)


def test_correlation_shifted_match():
    x = np.zeros((1, 1, 4, 4), np.float32)
    x[0, 0, 2, 2] = 1.0
    y = np.zeros((1, 1, 4, 4), np.float32)
    y[0, 0, 2, 3] = 1.0  # shifted one to the right
    out = mx.nd.Correlation(mx.nd.array(x), mx.nd.array(y),
                            kernel_size="1", max_displacement="1",
                            stride1="1", stride2="1", pad_size="1").asnumpy()
    # channel for displacement (dy=0, dx=+1) is index 5 in the 3x3 grid
    assert out[0, 5, 2, 2] == 1.0
    assert out[0, 4, 2, 2] == 0.0
