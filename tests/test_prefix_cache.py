"""Prefix-shared, quantized KV cache tests: ref-counted allocator
semantics (share/park/revive, loud free-of-shared), the radix
PrefixIndex, LRU eviction determinism, engine-level hit→attach→
diverge→evict behavior (bit-identity preserved under sharing — shared
pages are the same bytes), copy-on-write isolation, preemption of
shared pages, and the int8/fp8 quantized storage paths.

Tier-1 keeps one fast engine smoke per contract; the wide
quantization matrix and long shared-prefix sweeps are ``slow``.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models
from mxnet_tpu.executor import build_graph_fn
from mxnet_tpu.kv_cache import (BlockAllocator, blocks_for_tokens,
                                bucket_ladder, kv_storage_dtype)
from mxnet_tpu.models.transformer import transformer_lm_prefill
from mxnet_tpu.prefix_cache import PrefixCache, PrefixIndex

V, KVB, L, H, DM, MAXLEN = 61, 4, 2, 2, 32, 32


# ---------------------------------------------------------------------------
# edge contracts: the 0-token path
# ---------------------------------------------------------------------------


def test_zero_token_edge_contracts():
    """A fully prefix-cached prompt has an EMPTY uncached suffix:
    blocks_for_tokens(0) is 0 new pages (and alloc(0) == []), while a
    zero-topped bucket ladder is a sizing bug and raises loudly."""
    assert blocks_for_tokens(0, 4) == 0
    assert blocks_for_tokens(0, 1) == 0
    with pytest.raises(mx.MXNetError, match="negative"):
        blocks_for_tokens(-1, 4)
    a = BlockAllocator(5, 4)
    assert a.alloc(0, owner="s") == []
    assert a.free_blocks == 4
    with pytest.raises(mx.MXNetError, match="positive"):
        bucket_ladder(0)
    with pytest.raises(mx.MXNetError, match="positive"):
        bucket_ladder(-3)


# ---------------------------------------------------------------------------
# ref-counted allocator
# ---------------------------------------------------------------------------


def test_allocator_share_release_park_revive():
    a = BlockAllocator(6, 4)  # 5 usable
    (p,) = a.alloc(1, owner="A")
    assert a.refcount(p) == 1 and a.used_blocks == 1
    assert a.share(p) == 2
    assert a.shared_blocks == 1
    # a page referenced by two streams counts ONCE
    assert a.used_blocks == 1 and a.free_blocks == 4
    assert a.release(p) == 1
    assert a.shared_blocks == 0
    # last holder parks it (the index still maps its bytes)
    assert a.release(p, park=True) == 0
    assert a.is_parked(p) and a.parked_blocks == 1
    # parked pages count as reclaimable capacity, not as used
    assert a.free_blocks == 5 and a.used_blocks == 0
    # a prefix hit revives it at refcount 1
    a.revive(p, owner="B")
    assert a.refcount(p) == 1 and not a.is_parked(p)
    # reclaim only applies to parked pages
    with pytest.raises(mx.MXNetError, match="non-parked"):
        a.reclaim(p)
    a.release(p, park=True)
    a.reclaim(p)
    assert a.free_blocks == 5 and a.parked_blocks == 0


def test_allocator_free_of_shared_page_raises():
    """The satellite contract: free() of a page another stream still
    references raises loudly instead of corrupting the free list."""
    a = BlockAllocator(6, 4)
    (p,) = a.alloc(1, owner="A")
    a.share(p)
    with pytest.raises(mx.MXNetError, match="live references"):
        a.free([p])
    assert a.refcount(p) == 2  # nothing changed
    a.release(p)
    a.free([p])  # exclusive again: terminal free works
    assert a.free_blocks == 5
    with pytest.raises(mx.MXNetError, match="double free|foreign"):
        a.free([p])
    # freeing a parked page is a plain reclaim
    (q,) = a.alloc(1, owner="B")
    a.release(q, park=True)
    a.free([q])
    assert a.free_blocks == 5


# ---------------------------------------------------------------------------
# radix index
# ---------------------------------------------------------------------------


def _toks(*vals):
    return np.asarray(vals, np.int32)


def test_prefix_index_match_insert_remove():
    ix = PrefixIndex(4)
    t = _toks(*range(1, 13))  # 3 full blocks
    assert ix.match(t) == []
    created = ix.insert(t, [5, 6, 7], 3)
    assert len(created) == 3 and len(ix) == 3
    # longest-prefix match: full chain, then a diverging suffix
    chain = ix.match(t)
    assert [n.page for n in chain] == [5, 6, 7]
    t2 = np.concatenate([t[:8], _toks(99, 98, 97, 96)])
    chain = ix.match(t2)
    assert [n.page for n in chain] == [5, 6]
    # a 7-token prompt only has one FULL block
    assert [n.page for n in ix.match(t[:7])] == [5]
    # duplicate insert keeps the incumbent pages
    assert ix.insert(t, [50, 60, 70], 3) == []
    assert [n.page for n in ix.match(t)] == [5, 6, 7]
    # interior removal refuses; leaf removal unlinks
    with pytest.raises(mx.MXNetError, match="interior"):
        ix.remove(chain[0])
    leaf = ix.match(t)[-1]
    ix.remove(leaf)
    assert [n.page for n in ix.match(t)] == [5, 6]


def test_prefix_cache_attach_register_release_evict_lru():
    a = BlockAllocator(8, 4)  # 7 usable
    pc = PrefixCache(a, policy="lru")
    t = _toks(*range(1, 11))  # 10 tokens: 2 full blocks + tail
    pages = pc.alloc(3, owner="A")
    pc.register(t, pages)  # only the 2 FULL blocks index
    assert pc.stats()["indexed_blocks"] == 2
    # B attaches the cached prefix: refcounts bump, ONE hit counted
    cached, got = pc.attach(t, owner="B")
    assert cached == 8 and got == pages[:2]
    assert a.refcount(pages[0]) == 2
    assert pc.hits == 1 and pc.hit_tokens == 8
    # "preemption frees only its private refs": B releases — A's refs
    # survive, nothing parks, nothing frees
    pc.release(got)
    assert a.refcount(pages[0]) == 1
    # A retires: indexed pages park, the private tail frees
    pc.release(pages)
    assert a.parked_blocks == 2 and a.free_blocks == 7
    # a fresh attach revives parked pages
    cached, got = pc.attach(t, owner="C")
    assert cached == 8 and a.refcount(pages[0]) == 1
    pc.release(got)
    # pressure: 7 usable, 2 parked — asking for 6 must evict LRU
    out = pc.alloc(6, owner="D")
    assert out is not None and len(out) == 6
    assert pc.evictions >= 1
    assert pc.stats()["indexed_blocks"] < 2


def test_prefix_cache_eviction_lru_order_deterministic():
    a = BlockAllocator(10, 4)  # 9 usable
    pc = PrefixCache(a, policy="lru")
    t1 = _toks(*range(1, 9))     # chain A: 2 blocks
    t2 = _toks(*range(21, 29))   # chain B: 2 blocks
    pa = pc.alloc(2, "A")
    pc.register(t1, pa)
    pb = pc.alloc(2, "B")
    pc.register(t2, pb)
    pc.release(pa)
    pc.release(pb)
    # touch chain A (a peek does NOT touch; an attach does)
    cached, got = pc.attach(t1, "C")
    pc.release(got)
    # eviction must take chain B first (least recently used), leaf
    # before parent — deepest page of B goes first
    assert pc.evict(1) == 1
    assert [n.page for n in pc.index.match(t2, touch=False)] == [pb[0]]
    assert pc.evict(1) == 1
    assert pc.index.match(t2, touch=False) == []
    # chain A survived both evictions
    assert [n.page for n in pc.index.match(t1, touch=False)] == pa
    assert pc.evictions == 2


def test_prefix_cache_policy_off_frees_immediately():
    a = BlockAllocator(6, 4)
    pc = PrefixCache(a, policy="off")
    t = _toks(*range(1, 9))
    pages = pc.alloc(2, "A")
    pc.register(t, pages)
    assert pc.needs_cow(pages[0])  # indexed while live
    pc.release(pages)
    # no retention: pages free, index entries dropped
    assert a.parked_blocks == 0 and a.free_blocks == 5
    assert pc.stats()["indexed_blocks"] == 0
    with pytest.raises(mx.MXNetError):
        PrefixCache(a, policy="banana")


def test_needs_cow_semantics():
    a = BlockAllocator(6, 4)
    pc = PrefixCache(a, policy="lru")
    (private,) = pc.alloc(1, "A")
    assert not pc.needs_cow(private)       # exclusive, unindexed
    (shared,) = pc.alloc(1, "A")
    a.share(shared)
    assert pc.needs_cow(shared)            # two holders
    t = _toks(1, 2, 3, 4)
    (indexed,) = pc.alloc(1, "B")
    pc.register(t, [indexed])
    assert pc.needs_cow(indexed)           # ref 1 but index-mapped


# ---------------------------------------------------------------------------
# engine integration: the tiny-LM fixture (test_decode's pattern)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def lm():
    import jax
    import jax.numpy as jnp

    sym = models.transformer_lm(V, MAXLEN, num_layers=L, num_heads=H,
                                d_model=DM, block_size=KVB)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, MAXLEN))],
             label_shapes=[("softmax_label", (2, MAXLEN))],
             for_training=False)
    mod.init_params(mx.initializer.Xavier(factor_type="in",
                                          magnitude=2.0))
    arg, aux = mod.get_params()
    params = {**arg, **aux}

    ps = transformer_lm_prefill(V, num_layers=L, num_heads=H,
                                d_model=DM, kv_block=KVB, paged=False)
    gfn = build_graph_fn(ps)
    base = {n: jnp.asarray(params[n].asnumpy())
            for n in ps.list_arguments() if n in params}
    key = jax.random.PRNGKey(0)

    def full_logits(seq):
        T = len(seq)
        a = dict(base)
        a.update(data=jnp.asarray(np.asarray(seq, np.int32)[None]),
                 positions=jnp.asarray(
                     np.arange(T, dtype=np.int32)[None]),
                 lengths=jnp.asarray(np.asarray([T], np.int32)))
        outs, _ = gfn(a, {}, key, False)
        return np.asarray(outs[0][0])

    def naive_generate(prompt, n):
        seq = list(np.asarray(prompt))
        out = []
        for _ in range(n):
            out.append(int(np.argmax(full_logits(seq)[-1])))
            seq.append(out[-1])
        return np.asarray(out, np.int32)

    return params, naive_generate


def _engine(params, **kw):
    args = dict(vocab_size=V, num_layers=L, num_heads=H, d_model=DM,
                max_len=MAXLEN, kv_block=KVB, max_streams=4,
                decode_buckets=[1, 2, 4], temperature=0.0)
    args.update(kw)
    return mx.DecodeEngine(params, **args)


def test_engine_smoke_hit_attach_diverge_evict(lm):
    """The tier-1 smoke (<5s): miss → suffix-only hit → full hit
    (COW) → diverge → evict under pressure → repeat the first prompt
    and get the SAME tokens back — engine-only, no full-forward
    recompiles (the naive bit-identity lives in its own test)."""
    params, _ = lm
    shared = np.arange(1, 9, dtype=np.int32)        # 2 full blocks
    pa = np.concatenate([shared, [11, 12, 13]])     # 11 tokens
    pb = np.concatenate([shared, [21, 22]])         # diverges after 8
    with _engine(params, cache_blocks=7) as eng:    # 6 usable pages
        a1 = eng.generate(pa, 4)                    # miss
        st = eng.stats()
        assert st["prefix_hits"] == 0
        assert st["prefill_tokens"] == 11
        assert st["cache_blocks_cached"] == 2       # parked, bytes kept
        b1 = eng.generate(pb, 4)                    # suffix-only hit
        st = eng.stats()
        assert st["prefix_hits"] == 1
        assert st["prefix_hit_tokens"] == 8
        assert st["prefill_tokens"] == 11 + 2       # suffix only
        assert st["ttft_hit_p50_ms"] is not None
        assert st["ttft_miss_p50_ms"] is not None
        assert b1.shape == (4,)  # diverged suffix decoded fine
        # full hit: block-aligned prompt == the cached chain → prefill
        # SKIPPED entirely; the replayed tail write triggers ONE COW
        eng.generate(shared, 4)
        st = eng.stats()
        assert st["prefix_full_hits"] == 1
        assert st["prefills"] == 2            # unchanged by the hit
        assert st["prefill_tokens"] == 13     # no new prefill tokens
        assert st["cow_copies"] == 1
        # pressure: a disjoint prompt needing the whole pool — its
        # decode growth drains the free list and evicts the parked
        # chain LRU
        big = np.arange(40, 56, dtype=np.int32)  # 16 tokens, 4 pages
        eng.generate(big, 4)
        st = eng.stats()
        assert st["evictions"] >= 1
        assert st["cache_util"] == 0.0        # truthful: all retired
    assert st["generations"] == 4
    assert a1.shape == (4,)


def test_engine_prefix_hit_bitwise_vs_full_forward(lm):
    """Bit-identity PRESERVED with the prefix cache on: a suffix-only
    hit's generation equals the naive full-causal-forward chain to
    the last bit (shared pages are the same bytes)."""
    params, naive = lm
    shared = np.arange(1, 9, dtype=np.int32)
    pa = np.concatenate([shared, [11, 12, 13]])
    pb = np.concatenate([shared, [21, 22]])
    with _engine(params) as eng:
        a = eng.generate(pa, 4)                # miss
        b = eng.generate(pb, 4)                # suffix-only hit
        st = eng.stats()
    assert st["prefix_hits"] == 1
    np.testing.assert_array_equal(a, naive(pa, 4))
    np.testing.assert_array_equal(b, naive(pb, 4))


def test_engine_cow_isolation_diverging_streams(lm):
    """Two streams sharing a full-hit prefix then sampling with
    different seeds never see each other's tokens: each bit-matches
    its own solo run."""
    params, _ = lm
    shared = np.arange(2, 10, dtype=np.int32)  # block-aligned 8
    solo = {}
    for sd in (7, 8):
        with _engine(params, seed=3) as eng:
            solo[sd] = eng.generate(shared, 6, temperature=0.8,
                                    seed=sd)
    with _engine(params, seed=3) as eng:
        eng.generate(shared, 2)  # seed the cache (greedy, retires)
        f1 = eng.submit(shared, 6, temperature=0.8, seed=7)
        f2 = eng.submit(shared, 6, temperature=0.8, seed=8)
        g1, g2 = f1.result(120), f2.result(120)
        st = eng.stats()
    np.testing.assert_array_equal(g1, solo[7])
    np.testing.assert_array_equal(g2, solo[8])
    assert st["prefix_hits"] >= 2
    assert st["cow_copies"] >= 2  # each full hit COWed its tail page


def test_engine_preemption_frees_only_private_refs(lm):
    """A preempted stream holding shared pages releases only its OWN
    references — the sharer keeps decoding on the same pages, and
    every output still bit-matches the naive chain."""
    params, naive = lm
    shared = np.arange(3, 11, dtype=np.int32)
    pa = np.concatenate([shared, [31, 32, 33]])
    pb = np.concatenate([shared, [41, 42, 43]])
    # 8 usable pages: two 11-token prompts (3 pages each) only coexist
    # through sharing; growth under decode forces preemption
    with _engine(params, cache_blocks=9, max_streams=2) as eng:
        f1 = eng.submit(pa, 10)
        f2 = eng.submit(pb, 10)
        g1, g2 = f1.result(120), f2.result(120)
        st = eng.stats()
    np.testing.assert_array_equal(g1, naive(pa, 10))
    np.testing.assert_array_equal(g2, naive(pb, 10))
    assert st["prefix_hits"] >= 1
    assert st["generations"] == 2


def test_engine_prefix_cache_off_matches_legacy(lm):
    """MXNET_SERVING_PREFIX_CACHE=0: exclusive-owner behavior — no
    sharing machinery in the stats, repeated prompts re-prefill, and
    output is bit-identical to the naive chain (the acceptance gate's
    baseline path)."""
    params, naive = lm
    p = np.arange(1, 9, dtype=np.int32)
    with _engine(params, prefix_cache=0) as eng:
        np.testing.assert_array_equal(eng.generate(p, 4), naive(p, 4))
        np.testing.assert_array_equal(eng.generate(p, 4), naive(p, 4))
        st = eng.stats()
    assert st["prefix_cache"] == 0
    assert "prefix_hits" not in st
    assert st["prefill_tokens"] == 16  # both prompts fully prefilled
    assert st["cache_blocks_cached"] == 0


def test_engine_env_validation(lm, monkeypatch):
    params, _ = lm
    monkeypatch.setenv("MXNET_SERVING_KV_DTYPE", "banana")
    with pytest.raises(mx.MXNetError, match="banana"):
        _engine(params)
    monkeypatch.delenv("MXNET_SERVING_KV_DTYPE")
    monkeypatch.setenv("MXNET_SERVING_EVICT", "mru")
    with pytest.raises(mx.MXNetError, match="mru"):
        _engine(params)
    monkeypatch.delenv("MXNET_SERVING_EVICT")
    monkeypatch.setenv("MXNET_SERVING_PREFIX_CACHE", "2")
    with pytest.raises(mx.MXNetError, match="0 or 1"):
        _engine(params)
    monkeypatch.setenv("MXNET_SERVING_PREFIX_CACHE", "banana")
    with pytest.raises(mx.MXNetError, match="integer"):
        _engine(params)


# ---------------------------------------------------------------------------
# quantized KV storage
# ---------------------------------------------------------------------------


def test_kv_storage_dtype_catalog():
    assert kv_storage_dtype("fp32") == np.float32
    assert kv_storage_dtype("int8") == np.int8
    assert kv_storage_dtype("bf16").itemsize == 2
    with pytest.raises(mx.MXNetError, match="unknown"):
        kv_storage_dtype("fp4")


def test_quantized_paged_ops_tolerance():
    """Op-level: int8/fp8 paged decode matches the fp32 reference
    within the documented tolerance on the lax path, and the
    interpret-mode Pallas kernel matches the lax dequant bitwise."""
    import jax.numpy as jnp

    from mxnet_tpu.ops.attention import (paged_decode_attention,
                                         paged_decode_attention_q,
                                         paged_prefill_write,
                                         paged_prefill_write_q)

    rng = np.random.RandomState(0)
    B, Hh, D, NB, MB = 2, 2, 16, 8, 3
    k = rng.randn(B, 10, Hh, D).astype(np.float32)
    v = rng.randn(B, 10, Hh, D).astype(np.float32)
    q = rng.randn(B, 1, Hh, D).astype(np.float32)
    lengths = np.asarray([10, 7], np.int32)
    table = np.asarray([[1, 2, 3], [4, 5, 0]], np.int32)

    kp = jnp.zeros((NB, KVB, Hh, D))
    vp = jnp.zeros((NB, KVB, Hh, D))
    kp, vp = paged_prefill_write(jnp.asarray(k), jnp.asarray(v), kp, vp,
                                 jnp.asarray(table),
                                 jnp.asarray(lengths))
    ref = paged_decode_attention(jnp.asarray(q), kp, vp,
                                 jnp.asarray(table),
                                 jnp.asarray(lengths))
    for name, tol in (("int8", 0.02), ("fp8", 0.06)):
        dt = jnp.dtype(kv_storage_dtype(name))
        kq = jnp.zeros((NB, KVB, Hh, D), dt)
        vq = jnp.zeros((NB, KVB, Hh, D), dt)
        ks = jnp.ones((NB, KVB, Hh))
        vs = jnp.ones((NB, KVB, Hh))
        kq, vq, ks, vs = paged_prefill_write_q(
            jnp.asarray(k), jnp.asarray(v), kq, vq, ks, vs,
            jnp.asarray(table), jnp.asarray(lengths))
        out = paged_decode_attention_q(jnp.asarray(q), kq, vq, ks, vs,
                                       jnp.asarray(table),
                                       jnp.asarray(lengths))
        err = float(jnp.max(jnp.abs(out - ref)))
        assert err < tol, (name, err)
        # interpret-mode Pallas kernel == lax dequant, bitwise
        import os
        os.environ["MXNET_PALLAS"] = "1"
        try:
            out_pk = paged_decode_attention_q(
                jnp.asarray(q), kq, vq, ks, vs, jnp.asarray(table),
                jnp.asarray(lengths))
        finally:
            del os.environ["MXNET_PALLAS"]
        np.testing.assert_array_equal(np.asarray(out_pk),
                                      np.asarray(out))


def test_engine_int8_kv_greedy_decode(lm):
    """End-to-end: the int8-KV engine's greedy chain matches the fp32
    naive chain on a short horizon (the documented tolerance is
    logit-level; at this scale the argmax chain is stable), and
    sharing still works on top of the quantized pools.  NOTE a
    prefix-cache HIT reads the whole prompt through quantized pages
    while a miss's prefill attends raw K/V, so hit-vs-miss token
    equality is only a bit-exact guarantee for fp32 storage — for
    int8 the hit chain is checked for shape/stats, not identity."""
    params, naive = lm
    p = np.arange(1, 9, dtype=np.int32)
    with _engine(params, kv_dtype="int8") as eng:
        got = eng.generate(p, 4)
        again = eng.generate(p, 4)  # full hit over quantized pages
        st = eng.stats()
    assert st["kv_dtype"] == "int8"
    assert st["prefix_full_hits"] == 1
    assert st["cow_copies"] == 1
    np.testing.assert_array_equal(got, naive(p, 4))
    assert again.shape == (4,) and np.all(again >= 0) \
        and np.all(again < V)
    # fp32 storage: the SAME hit path IS bit-exact (shared pages are
    # the same bytes) — the contract the quantized path trades away
    with _engine(params, kv_dtype="fp32") as eng:
        a = eng.generate(p, 4)
        b = eng.generate(p, 4)
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a, naive(p, 4))


@pytest.mark.slow
def test_engine_quantized_matrix_vs_fp32(lm):
    """The kv_dtype matrix (bf16/int8/fp8) x (lax, interpret Pallas):
    greedy chains at this scale match fp32 exactly; quantized pools
    shrink the reported pool bytes."""
    import os

    params, naive = lm
    p = np.concatenate([np.arange(1, 9), [17, 23, 5]]).astype(np.int32)
    want = naive(p, 6)
    for kv in ("bf16", "int8", "fp8"):
        for pallas in ("0", "1"):
            os.environ["MXNET_PALLAS"] = pallas
            try:
                with _engine(params, kv_dtype=kv) as eng:
                    got = eng.generate(p, 6)
                    bytes_kv = eng._pool_bytes
            finally:
                del os.environ["MXNET_PALLAS"]
            np.testing.assert_array_equal(got, want, err_msg=f"{kv}")
        with _engine(params, kv_dtype="fp32") as eng:
            assert bytes_kv < eng._pool_bytes


@pytest.mark.slow
def test_engine_long_shared_prefix_sweep(lm):
    """Many clients over an 80%-shared-prefix workload: everything
    retires, accounting stays truthful (shared pages once), outputs
    all bit-match naive."""
    params, naive = lm
    rng = np.random.RandomState(11)
    shared = np.arange(5, 17, dtype=np.int32)  # 12 tokens
    reqs = []
    for i in range(12):
        if rng.rand() < 0.8:
            suffix = rng.randint(1, V, size=rng.randint(1, 5))
            reqs.append(np.concatenate([shared, suffix])
                        .astype(np.int32))
        else:
            reqs.append(rng.randint(
                1, V, size=rng.randint(6, 14)).astype(np.int32))
    with _engine(params, cache_blocks=25) as eng:
        futs = [(p, eng.submit(p, 5)) for p in reqs]
        outs = [(p, f.result(240)) for p, f in futs]
        st = eng.stats()
    for p, got in outs:
        np.testing.assert_array_equal(got, naive(p, 5))
    assert st["prefix_hits"] >= 6
    assert st["generations"] == 12
    assert st["cache_util"] == 0.0
