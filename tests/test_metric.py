"""Metric tests."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import metric


def test_accuracy():
    m = metric.Accuracy()
    preds = [mx.nd.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])]
    labels = [mx.nd.array([1, 0, 0])]
    m.update(labels, preds)
    name, acc = m.get()
    assert name == "accuracy"
    assert abs(acc - 2.0 / 3) < 1e-6


def test_topk():
    m = metric.TopKAccuracy(top_k=2)
    preds = [mx.nd.array([[0.1, 0.5, 0.4], [0.7, 0.2, 0.1]])]
    labels = [mx.nd.array([2, 1])]
    m.update(labels, preds)
    assert abs(m.get()[1] - 1.0) < 1e-6  # both labels within top-2
    m2 = metric.TopKAccuracy(top_k=2)
    m2.update([mx.nd.array([0, 1])], preds)  # row0 label 0 not in top-2
    assert abs(m2.get()[1] - 0.5) < 1e-6


def test_mse_mae_rmse():
    pred = [mx.nd.array([[1.0], [2.0]])]
    label = [mx.nd.array([1.5, 2.5])]
    m = metric.MSE(); m.update(label, pred)
    assert abs(m.get()[1] - 0.25) < 1e-6
    m = metric.MAE(); m.update(label, pred)
    assert abs(m.get()[1] - 0.5) < 1e-6
    m = metric.RMSE(); m.update(label, pred)
    assert abs(m.get()[1] - 0.5) < 1e-6


def test_cross_entropy_f1():
    pred = [mx.nd.array([[0.9, 0.1], [0.2, 0.8]])]
    label = [mx.nd.array([0, 1])]
    m = metric.CrossEntropy()
    m.update(label, pred)
    expected = -(np.log(0.9) + np.log(0.8)) / 2
    assert abs(m.get()[1] - expected) < 1e-5
    f = metric.F1()
    f.update(label, pred)
    assert f.get()[1] == 1.0


def test_composite_and_custom():
    comp = metric.CompositeEvalMetric(metrics=["acc", "mse"])
    pred = [mx.nd.array([[0.1, 0.9]])]
    label = [mx.nd.array([1])]
    comp.update(label, pred)
    names, vals = comp.get()
    assert len(names) == 2

    def my_metric(label, pred):
        return float(np.abs(label - pred.argmax(1)).sum())

    cm = metric.np(my_metric)
    cm.update([np.array([1])], [np.array([[0.9, 0.1]])])
    assert cm.get()[1] == 1.0


def test_create_factory():
    assert isinstance(metric.create("acc"), metric.Accuracy)
    assert isinstance(metric.create(["acc", "ce"]), metric.CompositeEvalMetric)
    m = metric.create(lambda l, p: 0.0)
    assert isinstance(m, metric.CustomMetric)


def test_perplexity():
    m = metric.Perplexity(ignore_label=None)
    pred = [mx.nd.array([[0.5, 0.5], [0.25, 0.75]])]
    label = [mx.nd.array([0, 1])]
    m.update(label, pred)
    expected = np.exp(-(np.log(0.5) + np.log(0.75)) / 2)
    assert abs(m.get()[1] - expected) < 1e-5


def test_accuracy_device_accumulation_matches_numpy():
    """NDArray inputs score on device; result identical to the numpy path."""
    rng = np.random.RandomState(0)
    m_dev = mx.metric.Accuracy()
    m_np = mx.metric.Accuracy()
    for _ in range(3):
        pred = rng.rand(16, 5).astype(np.float32)
        label = rng.randint(0, 5, 16).astype(np.float32)
        m_dev.update([mx.nd.array(label)], [mx.nd.array(pred)])
        m_np.update([label], [pred])
    assert m_dev._dev_sum is not None  # really accumulated on device
    assert m_dev.get() == m_np.get()
    # reset clears the device accumulator
    m_dev.reset()
    assert m_dev._dev_sum is None and m_dev.num_inst == 0


def test_perplexity_device_accumulation_matches_numpy():
    rng = np.random.RandomState(1)
    m_dev = mx.metric.Perplexity(ignore_label=0)
    m_np = mx.metric.Perplexity(ignore_label=0)
    for _ in range(3):
        pred = rng.rand(24, 7).astype(np.float32)
        pred /= pred.sum(axis=1, keepdims=True)
        label = rng.randint(0, 7, 24).astype(np.float32)
        m_dev.update([mx.nd.array(label).reshape((4, 6))],
                     [mx.nd.array(pred)])
        m_np.update([label], [pred])
    assert m_dev._dev_sum is not None
    name, a = m_dev.get()
    _, b = m_np.get()
    np.testing.assert_allclose(a, b, rtol=1e-5)
    m_dev.reset()
    assert m_dev._dev_sum is None and m_dev.num_inst == 0


def test_perplexity_device_all_ignored_batch():
    """An all-padding batch contributes nothing (no NaN poisoning)."""
    m = mx.metric.Perplexity(ignore_label=0)
    pred = np.full((4, 3), 1 / 3, np.float32)
    m.update([mx.nd.zeros((4,))], [mx.nd.array(pred)])  # all ignored
    label = np.array([1, 2, 1, 2], np.float32)
    m.update([mx.nd.array(label)], [mx.nd.array(pred)])
    _, v = m.get()
    assert np.isfinite(v) and abs(v - 3.0) < 1e-4  # uniform over 3


def test_perplexity_multi_pair_uses_combined_exp():
    """Multiple (label, pred) pairs keep the host combined-exp formula."""
    rng = np.random.RandomState(2)
    p1 = rng.rand(8, 4).astype(np.float32); p1 /= p1.sum(1, keepdims=True)
    p2 = rng.rand(8, 4).astype(np.float32); p2 /= p2.sum(1, keepdims=True)
    l1 = rng.randint(0, 4, 8).astype(np.float32)
    l2 = rng.randint(0, 4, 8).astype(np.float32)
    m_nd = mx.metric.Perplexity()
    m_np = mx.metric.Perplexity()
    m_nd.update([mx.nd.array(l1), mx.nd.array(l2)],
                [mx.nd.array(p1), mx.nd.array(p2)])
    m_np.update([l1, l2], [p1, p2])
    np.testing.assert_allclose(m_nd.get()[1], m_np.get()[1], rtol=1e-6)


def test_perplexity_honors_axis():
    # axis=1 on 3D predictions: class axis in the middle (ADVICE r3)
    rng = np.random.RandomState(3)
    p = rng.rand(4, 7, 5).astype(np.float32)  # (batch, classes, time)
    p /= p.sum(axis=1, keepdims=True)
    l = rng.randint(0, 7, size=(4, 5)).astype(np.float32)
    m_ax = mx.metric.Perplexity(ignore_label=None, axis=1)
    m_ax.update([l], [p])
    m_ref = mx.metric.Perplexity(ignore_label=None)
    m_ref.update([l], [np.moveaxis(p, 1, -1)])
    np.testing.assert_allclose(m_ax.get()[1], m_ref.get()[1], rtol=1e-6)
    # device path with axis=1 agrees too
    m_dev = mx.metric.Perplexity(ignore_label=None, axis=1)
    m_dev.update([mx.nd.array(l)], [mx.nd.array(p)])
    np.testing.assert_allclose(m_dev.get()[1], m_ref.get()[1], rtol=1e-5)


def test_accuracy_fields_coherent_mid_epoch():
    # sum_metric/num_inst must be mutually coherent before get() (ADVICE r3)
    m = mx.metric.Accuracy()
    l = mx.nd.array(np.array([0.0, 1.0, 1.0, 0.0]))
    p = mx.nd.array(np.eye(2)[[0, 1, 0, 0]].astype(np.float32))
    m.update([l], [p])
    # public fields read together mid-epoch: either both updated or neither
    assert (m.num_inst == 0) == (m.sum_metric == 0.0)
    assert m.get()[1] == 0.75
