"""Metric tests."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import metric


def test_accuracy():
    m = metric.Accuracy()
    preds = [mx.nd.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])]
    labels = [mx.nd.array([1, 0, 0])]
    m.update(labels, preds)
    name, acc = m.get()
    assert name == "accuracy"
    assert abs(acc - 2.0 / 3) < 1e-6


def test_topk():
    m = metric.TopKAccuracy(top_k=2)
    preds = [mx.nd.array([[0.1, 0.5, 0.4], [0.7, 0.2, 0.1]])]
    labels = [mx.nd.array([2, 1])]
    m.update(labels, preds)
    assert abs(m.get()[1] - 1.0) < 1e-6  # both labels within top-2
    m2 = metric.TopKAccuracy(top_k=2)
    m2.update([mx.nd.array([0, 1])], preds)  # row0 label 0 not in top-2
    assert abs(m2.get()[1] - 0.5) < 1e-6


def test_mse_mae_rmse():
    pred = [mx.nd.array([[1.0], [2.0]])]
    label = [mx.nd.array([1.5, 2.5])]
    m = metric.MSE(); m.update(label, pred)
    assert abs(m.get()[1] - 0.25) < 1e-6
    m = metric.MAE(); m.update(label, pred)
    assert abs(m.get()[1] - 0.5) < 1e-6
    m = metric.RMSE(); m.update(label, pred)
    assert abs(m.get()[1] - 0.5) < 1e-6


def test_cross_entropy_f1():
    pred = [mx.nd.array([[0.9, 0.1], [0.2, 0.8]])]
    label = [mx.nd.array([0, 1])]
    m = metric.CrossEntropy()
    m.update(label, pred)
    expected = -(np.log(0.9) + np.log(0.8)) / 2
    assert abs(m.get()[1] - expected) < 1e-5
    f = metric.F1()
    f.update(label, pred)
    assert f.get()[1] == 1.0


def test_composite_and_custom():
    comp = metric.CompositeEvalMetric(metrics=["acc", "mse"])
    pred = [mx.nd.array([[0.1, 0.9]])]
    label = [mx.nd.array([1])]
    comp.update(label, pred)
    names, vals = comp.get()
    assert len(names) == 2

    def my_metric(label, pred):
        return float(np.abs(label - pred.argmax(1)).sum())

    cm = metric.np(my_metric)
    cm.update([np.array([1])], [np.array([[0.9, 0.1]])])
    assert cm.get()[1] == 1.0


def test_create_factory():
    assert isinstance(metric.create("acc"), metric.Accuracy)
    assert isinstance(metric.create(["acc", "ce"]), metric.CompositeEvalMetric)
    m = metric.create(lambda l, p: 0.0)
    assert isinstance(m, metric.CustomMetric)


def test_perplexity():
    m = metric.Perplexity(ignore_label=None)
    pred = [mx.nd.array([[0.5, 0.5], [0.25, 0.75]])]
    label = [mx.nd.array([0, 1])]
    m.update(label, pred)
    expected = np.exp(-(np.log(0.5) + np.log(0.75)) / 2)
    assert abs(m.get()[1] - expected) < 1e-5


def test_accuracy_device_accumulation_matches_numpy():
    """NDArray inputs score on device; result identical to the numpy path."""
    rng = np.random.RandomState(0)
    m_dev = mx.metric.Accuracy()
    m_np = mx.metric.Accuracy()
    for _ in range(3):
        pred = rng.rand(16, 5).astype(np.float32)
        label = rng.randint(0, 5, 16).astype(np.float32)
        m_dev.update([mx.nd.array(label)], [mx.nd.array(pred)])
        m_np.update([label], [pred])
    assert m_dev._dev_sum is not None  # really accumulated on device
    assert m_dev.get() == m_np.get()
    # reset clears the device accumulator
    m_dev.reset()
    assert m_dev._dev_sum is None and m_dev.num_inst == 0
