"""Parameter-server unit tests (in-process, no launcher): wire
protocol, key sharding + big-array splitting (reference:
kvstore_dist.h:264-302, nightly dist_sync_kvstore.py big_shape), the
HMAC gate on the optimizer payload, and server-side sync rounds
(kvstore_dist_server.h:136-219)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.ps import (ParameterServer, PSClient, ShardedPSClient,
                          server_of, split_sizes)


def _cluster(n=2, secret=b"s3cret", sync=False, num_workers=1,
             big_bound=100):
    servers = [ParameterServer(secret=secret, sync=sync,
                               num_workers=num_workers) for _ in range(n)]
    client = ShardedPSClient([("127.0.0.1", s.port) for s in servers],
                             secret=secret, big_bound=big_bound)
    return servers, client


def test_split_sizes_balanced():
    assert split_sizes(10, 3) == [3, 4, 3]
    assert sum(split_sizes(1999, 7)) == 1999
    assert split_sizes(4, 4) == [1, 1, 1, 1]


def test_small_key_hash_matches_reference_heuristic():
    # (key * 9973) % S — kvstore_dist.h:276
    assert server_of(0, 2) == 0
    assert server_of(1, 2) == 1
    assert server_of(7, 4) == (7 * 9973) % 4


def test_wire_roundtrip_dtypes():
    servers, cl = _cluster(n=1)
    try:
        for dt in (np.float32, np.float64, np.int32, np.uint8):
            key = f"k_{np.dtype(dt).name}"
            v = (np.arange(12).reshape(3, 4) % 7).astype(dt)
            cl.init(key, v)
            out = cl.pull(key)
            assert out.dtype == dt
            np.testing.assert_array_equal(out, v)
        # 0-d scalar
        cl.init("scalar", np.float32(3.5))
        assert cl.pull("scalar") == np.float32(3.5)
    finally:
        cl.close()
        [s.close() for s in servers]


def test_big_array_splits_across_servers():
    servers, cl = _cluster(n=2, big_bound=100)
    try:
        big = np.arange(50 * 40, dtype=np.float32).reshape(50, 40)
        cl.init("big", np.zeros_like(big))
        cl.push("big", big)
        out = cl.pull("big", shape=big.shape, dtype=big.dtype)
        np.testing.assert_array_equal(out, big)
        # both shards actually hold a chunk (the point of splitting)
        assert servers[0]._store and servers[1]._store
        sizes = [sum(v.size for v in s._store.values()) for s in servers]
        assert sizes == [1000, 1000]
        # small key stays whole on its hashed shard
        cl.init(3, np.ones(5, np.float32))
        owner = server_of(3, 2)
        assert 3 in servers[owner]._store
        assert 3 not in servers[1 - owner]._store
    finally:
        cl.close()
        [s.close() for s in servers]


def test_optimizer_blob_requires_valid_hmac():
    servers, _good = _cluster(n=1, secret=b"right")
    try:
        bad = ShardedPSClient([("127.0.0.1", servers[0].port)],
                              secret=b"wrong")
        with pytest.raises(MXNetError, match="HMAC"):
            bad.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
        bad.close()
        # the good client's blob is accepted
        _good.set_optimizer(mx.optimizer.SGD(learning_rate=0.1,
                                             rescale_grad=1.0, wd=0.0))
        assert servers[0]._updater is not None
    finally:
        _good.close()
        [s.close() for s in servers]


def test_sync_round_applies_once_after_all_workers():
    """Server-side sync: N pushes merge, ONE updater application, pulls
    wait for the round — workers stateless (kvstore_dist_server.h:
    136-198)."""
    servers, _ = _cluster(n=1, sync=True, num_workers=2)
    try:
        w0 = PSClient("127.0.0.1", servers[0].port, secret=b"s3cret",
                      worker=0)
        w1 = PSClient("127.0.0.1", servers[0].port, secret=b"s3cret",
                      worker=1)
        w0.init("w", np.zeros(4, np.float32))
        w1.init("w", np.ones(4, np.float32))  # later init is a no-op
        w0.set_optimizer(mx.optimizer.SGD(learning_rate=0.5,
                                          rescale_grad=1.0, wd=0.0))
        import threading

        got = {}

        def worker(cl, name, grad):
            cl.push_sync("w", grad)
            got[name] = cl.pull("w", min_round=1)  # waits for the round

        t0 = threading.Thread(target=worker,
                              args=(w0, "w0", np.ones(4, np.float32)))
        t1 = threading.Thread(target=worker,
                              args=(w1, "w1", 2 * np.ones(4, np.float32)))
        t0.start()
        t1.start()
        t0.join(30)
        t1.join(30)
        # one SGD step on the SUM of both grads: 0 - 0.5*(1+2) = -1.5
        np.testing.assert_allclose(got["w0"], -1.5 * np.ones(4), rtol=1e-6)
        np.testing.assert_allclose(got["w1"], got["w0"])
        assert servers[0]._applied["w"] == 1  # applied ONCE, not twice
        w0.close()
        w1.close()
    finally:
        [s.close() for s in servers]


def test_sync_duplicate_push_joins_next_round():
    """A worker double-pushing must NOT complete the round in place of
    its peer: the duplicate queues for the next round, so the round
    still waits for every distinct worker's gradient."""
    import threading
    import time

    servers, _ = _cluster(n=1, sync=True, num_workers=2)
    try:
        w0 = PSClient("127.0.0.1", servers[0].port, secret=b"s3cret",
                      worker=0)
        w0b = PSClient("127.0.0.1", servers[0].port, secret=b"s3cret",
                       worker=0)  # same worker, second connection
        w1 = PSClient("127.0.0.1", servers[0].port, secret=b"s3cret",
                      worker=1)
        w0.init("w", np.zeros(4, np.float32))
        w0.push_sync("w", np.ones(4, np.float32))
        dup_done = threading.Event()

        def dup():
            w0b.push_sync("w", 8 * np.ones(4, np.float32))  # duplicate
            dup_done.set()

        t = threading.Thread(target=dup, daemon=True)
        t.start()
        time.sleep(0.3)
        # the duplicate is queued, NOT merged: round 1 has not applied
        assert servers[0]._applied.get("w", 0) == 0
        assert not dup_done.is_set()
        w1.push_sync("w", 2 * np.ones(4, np.float32))  # completes round 1
        # no updater installed: round 1 assigns the sum of w0+w1 only
        np.testing.assert_allclose(w0.pull("w", min_round=1),
                                   3 * np.ones(4), rtol=1e-6)
        assert dup_done.wait(10)  # duplicate unblocked into round 2
        w1.push_sync("w", np.zeros(4, np.float32))  # completes round 2
        np.testing.assert_allclose(w0.pull("w", min_round=2),
                                   8 * np.ones(4), rtol=1e-6)
        assert servers[0]._applied["w"] == 2
        for cl in (w0, w0b, w1):
            cl.close()
    finally:
        [s.close() for s in servers]


def test_no_pickle_for_tensor_ops():
    """The tensor path must never unpickle network bytes: a frame
    carrying a pickle of a malicious object through push would need the
    server to call pickle.loads — assert the opcode surface for
    init/push/pull is raw-buffer only by checking a pickled payload is
    rejected as a malformed tensor, not executed."""
    import pickle

    servers, cl = _cluster(n=1)
    try:
        evil = pickle.dumps({"boom": 1})
        sock_client = cl.clients[0]
        from mxnet_tpu.ps import _pack_key, _send_frame, _recv_frame

        with sock_client._lock:
            _send_frame(sock_client._sock,
                        bytes([2]) + _pack_key("w") + evil)
            resp = _recv_frame(sock_client._sock)
        assert resp[0] != 0  # error frame, server thread alive
        # server still serves valid requests afterwards
        cl.init("ok", np.ones(3, np.float32))
        np.testing.assert_array_equal(cl.pull("ok"), np.ones(3))
    finally:
        cl.close()
        [s.close() for s in servers]
