"""User-kernel (RTC) API tests — reference capability:
python/mxnet/rtc.py user kernels from Python, re-expressed as Pallas /
jax kernels registered as first-class ops (mxnet_tpu/rtc.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError


def _unique(name):
    # registry is process-global; keep test op names collision-free
    import uuid

    return f"{name}_{uuid.uuid4().hex[:8]}"


def test_register_op_imperative_and_symbolic():
    name = _unique("axpb")

    def axpb(x):
        return 2.0 * x + 1.0

    mx.rtc.register_op(name, axpb, arg_names=("data",))
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = getattr(mx.nd, name)(mx.nd.array(x)).asnumpy()
    np.testing.assert_allclose(out, 2 * x + 1, rtol=1e-6)

    sym = getattr(mx.sym, name)(mx.sym.Variable("data"))
    ex = sym.bind(mx.cpu(), {"data": mx.nd.array(x)})
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), 2 * x + 1,
                               rtol=1e-6)


def test_register_op_duplicate_rejected():
    with pytest.raises(MXNetError, match="already registered"):
        mx.rtc.register_op("FullyConnected", lambda x: x)


def test_pallas_kernel_with_vjp_trains():
    """A raw Pallas kernel + user VJP: forward parity, gradient parity
    against the jnp formulation, and symbolic backward."""
    name = _unique("psilu")

    def kern(x_ref, o_ref):
        import jax.numpy as jnp

        x = x_ref[...]
        o_ref[...] = x / (1.0 + jnp.exp(-x))

    def vjp(inputs, out_grads):
        import jax.numpy as jnp

        (x,) = inputs
        (g,) = out_grads
        s = 1.0 / (1.0 + jnp.exp(-x))
        return (g * (s + x * s * (1.0 - s)),)

    mx.rtc.pallas_op(name, kern, arg_names=("data",), vjp=vjp)

    x = np.linspace(-3, 3, 24, dtype=np.float32).reshape(4, 6)
    out = getattr(mx.nd, name)(mx.nd.array(x)).asnumpy()
    sig = 1.0 / (1.0 + np.exp(-x))
    np.testing.assert_allclose(out, x * sig, rtol=1e-5)

    # symbolic backward through the user VJP
    sym = getattr(mx.sym, name)(mx.sym.Variable("data"))
    xe = mx.nd.array(x)
    ge = mx.nd.zeros(x.shape)
    ex = sym.bind(mx.cpu(), {"data": xe}, args_grad={"data": ge})
    ex.forward(is_train=True)
    ex.backward(out_grads=[mx.nd.ones(x.shape)])
    want = sig + x * sig * (1 - sig)
    np.testing.assert_allclose(ge.asnumpy(), want, rtol=1e-5, atol=1e-6)


def test_pallas_op_out_like_callable_and_shape_infer():
    """out_like as a ShapeDtypeStruct fn + custom shape inference: a
    reduction kernel whose output shape differs from the input."""
    import jax

    name = _unique("rowsum")

    def kern(x_ref, o_ref):
        o_ref[...] = x_ref[...].sum(axis=1, keepdims=True)

    mx.rtc.pallas_op(
        name, kern, arg_names=("data",),
        out_like=lambda x: jax.ShapeDtypeStruct((x.shape[0], 1), x.dtype),
        infer_shape=lambda s: (s[0], 1))

    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    out = getattr(mx.nd, name)(mx.nd.array(x)).asnumpy()
    np.testing.assert_allclose(out, x.sum(1, keepdims=True), rtol=1e-6)

    # shape inference feeds simple_bind
    sym = getattr(mx.sym, name)(mx.sym.Variable("data"))
    _, out_shapes, _ = sym.infer_shape(data=(3, 4))
    assert out_shapes == [(3, 1)]


def test_user_kernel_example_end_to_end():
    """The worked example trains a net through the user kernel."""
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "examples", "user_pallas_kernel.py")
    spec = importlib.util.spec_from_file_location("user_pallas_kernel", path)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    m.main()


def test_pallas_op_custom_grid_and_specs():
    """grid/in_specs/out_specs pass through to pl.pallas_call: a tiled
    row-scaling kernel over a (256, 256) input."""
    import jax
    from jax.experimental import pallas as pl

    name = _unique("tiledscale")

    def kern(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 3.0

    mx.rtc.pallas_op(
        name, kern, arg_names=("data",),
        out_like=lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=(2, 2),
        in_specs=[pl.BlockSpec((128, 128), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((128, 128), lambda i, j: (i, j)))

    x = np.arange(256 * 256, dtype=np.float32).reshape(256, 256) % 97
    out = getattr(mx.nd, name)(mx.nd.array(x)).asnumpy()
    np.testing.assert_allclose(out, x * 3.0, rtol=1e-6)
