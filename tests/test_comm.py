"""Gradient-comm scheduler tests: deterministic bucketing (pack → sum
→ unpack bitwise-identical to per-key sums), priority ordering,
failure propagation, the windowed PS pipeline + multi-key wire frames,
bf16 wire compression with fp32 accumulation (convergence-tolerance
"small fit"), the kvstore rescale hook, and a bench_comm smoke run."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import comm
from mxnet_tpu.base import MXNetError
from mxnet_tpu.ps import ParameterServer, ShardedPSClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _entries(arrays, priority=0):
    out, off = [], 0
    for i, a in enumerate(arrays):
        out.append(comm.BucketEntry(i, a.shape, a.dtype, a.size, off,
                                    priority))
        off += a.size
    return out


# -- deterministic bucketing --------------------------------------------
def test_pack_unpack_roundtrip_bitwise():
    rng = np.random.RandomState(0)
    arrays = [rng.randn(*s).astype(np.float32)
              for s in [(3, 4), (7,), (2, 2, 2), (1,)]]
    flat = np.asarray(comm.pack_bucket(arrays))
    assert flat.shape == (sum(a.size for a in arrays),)
    out = [np.asarray(x) for x in comm.unpack_bucket(flat, _entries(arrays))]
    for a, b in zip(arrays, out):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert a.tobytes() == b.tobytes()  # bitwise


def test_bucketed_sum_bitwise_equals_per_key_sum():
    """The sync-semantics invariant: pack → elementwise sum over
    workers → unpack must be BITWISE identical to the per-key sums the
    blocking path computed, and stable across repeated runs."""
    import jax.numpy as jnp

    rng = np.random.RandomState(7)
    w0 = [rng.randn(64, 3).astype(np.float32) * 10,
          rng.randn(17).astype(np.float32) * 1e-3]
    w1 = [rng.randn(64, 3).astype(np.float32),
          rng.randn(17).astype(np.float32)]
    entries = _entries(w0)
    # per-key reference: exactly the old blocking path's reduction
    ref = [np.asarray(jnp.sum(jnp.stack([jnp.asarray(a), jnp.asarray(b)]),
                              axis=0)) for a, b in zip(w0, w1)]
    runs = []
    for _ in range(2):
        summed = jnp.sum(jnp.stack([comm.pack_bucket(w0),
                                    comm.pack_bucket(w1)]), axis=0)
        out = [np.asarray(x) for x in comm.unpack_bucket(summed, entries)]
        runs.append(out)
        for r, o in zip(ref, out):
            assert r.tobytes() == o.tobytes()
    for a, b in zip(*runs):  # run-to-run bitwise stability
        assert a.tobytes() == b.tobytes()


# -- scheduler behavior --------------------------------------------------
def _wait_depth_zero(s, timeout=5.0):
    t0 = time.time()
    while s.depth > 0 and time.time() - t0 < timeout:
        time.sleep(0.01)


def test_scheduler_seals_by_bucket_bytes():
    buckets = []

    def launch(b):
        buckets.append([e.key for e in b.entries])

    s = comm.CommScheduler(launch, strict_order=True, max_bucket_bytes=40)
    try:
        for i in range(5):
            s.submit(i, np.ones(4, np.float32))  # 16 B each
        s.flush()
        s.drain()
    finally:
        s.close()
    assert buckets == [[0, 1], [2, 3], [4]]


def test_scheduler_priority_heap_order():
    order = []
    gate = threading.Event()

    def launch(b):
        gate.wait(10)
        order.append(b.entries[0].key)

    s = comm.CommScheduler(launch, strict_order=False, max_bucket_bytes=1)
    try:
        s.submit("first", np.ones(4, np.float32), priority=0)
        _wait_depth_zero(s)  # comm thread holds 'first' at the gate
        s.submit("a", np.ones(4, np.float32), priority=-3)
        s.submit("b", np.ones(4, np.float32), priority=5)
        s.submit("c", np.ones(4, np.float32), priority=1)
        gate.set()
        s.drain()
    finally:
        s.close()
    assert order[0] == "first"
    assert order[1:] == ["b", "c", "a"]  # higher priority first


def test_scheduler_strict_order_is_submission_order():
    order = []
    gate = threading.Event()

    def launch(b):
        gate.wait(10)
        order.append(b.entries[0].key)

    s = comm.CommScheduler(launch, strict_order=True, max_bucket_bytes=1)
    try:
        s.submit("first", np.ones(4, np.float32), priority=0)
        _wait_depth_zero(s)
        s.submit("a", np.ones(4, np.float32), priority=-3)
        s.submit("b", np.ones(4, np.float32), priority=5)
        s.submit("c", np.ones(4, np.float32), priority=1)
        gate.set()
        s.drain()
    finally:
        s.close()
    # collective transports must launch in submission order on every
    # rank regardless of priority
    assert order == ["first", "a", "b", "c"]


def test_scheduler_dtype_groups_split_buckets():
    buckets = []

    def launch(b):
        buckets.append({e.key: e.dtype for e in b.entries})

    s = comm.CommScheduler(launch, strict_order=True,
                           max_bucket_bytes=1 << 20)
    try:
        s.submit("f32", np.ones(4, np.float32))
        s.submit("f64", np.ones(4, np.float64))
        s.submit("i32", np.ones(4, np.int32))
        s.flush()
        s.drain()
    finally:
        s.close()
    assert len(buckets) == 3  # one bucket per dtype group
    for b in buckets:
        assert len(set(b.values())) == 1


def test_scheduler_failure_surfaces_at_wait_and_poisons_submit():
    def launch(b):
        raise RuntimeError("transport down")

    s = comm.CommScheduler(launch, strict_order=True, max_bucket_bytes=1)
    s.submit("k", np.ones(2, np.float32))
    with pytest.raises(RuntimeError, match="transport down"):
        s.wait("k")
    with pytest.raises(MXNetError, match="comm thread failed"):
        s.submit("k2", np.ones(2, np.float32))


def test_scheduler_wait_unknown_key_is_noop():
    s = comm.CommScheduler(lambda b: None, strict_order=True)
    try:
        s.wait("never-pushed")
        s.drain()
    finally:
        s.close()


# -- windowed PS pipeline + multi-key frames ----------------------------
def _cluster(n=2, secret=b"s3cret", big_bound=100, **kw):
    servers = [ParameterServer(secret=secret, **kw) for _ in range(n)]
    client = ShardedPSClient([("127.0.0.1", s.port) for s in servers],
                             secret=secret, big_bound=big_bound, worker=0)
    return servers, client


def test_psclient_windowed_inflight_pipeline():
    from mxnet_tpu.ps import _body_pull, _unpack_tensor

    servers, cl = _cluster(n=1)
    try:
        c = cl.clients[0]
        for i in range(4):
            cl.init(f"k{i}", np.full(3, float(i), np.float32))
        # 4 requests on the wire before the first response is collected
        fins = [c._begin(_body_pull(f"k{i}", 0)) for i in range(4)]
        assert c._sent - c._recvd == 4
        for i, fin in enumerate(fins):
            arr, _ = _unpack_tensor(fin(), 1 + 8)
            np.testing.assert_array_equal(arr, np.full(3, float(i)))
        assert c._sent == c._recvd
    finally:
        cl.close()
        [s.close() for s in servers]


def test_psclient_out_of_order_finish_waits_for_turn():
    from mxnet_tpu.ps import _body_pull, _unpack_tensor

    servers, cl = _cluster(n=1)
    try:
        c = cl.clients[0]
        cl.init("a", np.ones(2, np.float32))
        cl.init("b", 2 * np.ones(2, np.float32))
        fin_a = c._begin(_body_pull("a", 0))
        fin_b = c._begin(_body_pull("b", 0))
        got_b = {}

        def later():
            arr, _ = _unpack_tensor(fin_b(), 1 + 8)
            got_b["v"] = np.array(arr)

        t = threading.Thread(target=later, daemon=True)
        t.start()
        time.sleep(0.2)
        assert "v" not in got_b  # ticket b must wait for ticket a
        arr, _ = _unpack_tensor(fin_a(), 1 + 8)
        np.testing.assert_array_equal(arr, np.ones(2))
        t.join(10)
        np.testing.assert_array_equal(got_b["v"], 2 * np.ones(2))
    finally:
        cl.close()
        [s.close() for s in servers]


def test_push_pull_multi_roundtrip_with_split_key():
    servers, cl = _cluster(n=2, big_bound=100)
    try:
        rng = np.random.RandomState(3)
        smalls = {f"s{i}": rng.randn(5).astype(np.float32)
                  for i in range(6)}
        big = rng.randn(30, 10).astype(np.float32)  # 300 > big_bound
        for k in smalls:
            cl.init(k, np.zeros(5, np.float32))
        cl.init("big", np.zeros_like(big))
        entries = list(smalls.items()) + [("big", big)]
        cl.push_multi(entries)  # no updater: servers assign the values
        specs = [(k, v.shape, v.dtype, 0) for k, v in smalls.items()]
        specs.append(("big", big.shape, big.dtype, 0))
        outs = cl.pull_multi(specs)
        for (k, v), got in zip(entries, outs):
            np.testing.assert_array_equal(got, v, err_msg=k)
        # the split key really landed on both shards
        assert sum("part" in str(kk) for s in servers
                   for kk in s._store) == 2
    finally:
        cl.close()
        [s.close() for s in servers]


def test_bf16_tensor_wire_roundtrip():
    import ml_dtypes

    servers, cl = _cluster(n=1)
    try:
        v32 = np.linspace(-3, 3, 16, dtype=np.float32)
        v = v32.astype(ml_dtypes.bfloat16)
        cl.init("b", np.zeros(16, np.float32))
        cl.push("b", v)  # bf16 payload on the wire; server stores fp32
        out = cl.pull("b")
        np.testing.assert_array_equal(out, v.astype(np.float32))
    finally:
        cl.close()
        [s.close() for s in servers]


# -- wire compression: bf16 "small fit" ---------------------------------
def _fit_quadratic(wire, steps=60, lr=0.1):
    """Server-side SGD descends 0.5*||w - target||^2; gradients travel
    through the bucketed scheduler with the given wire dtype."""
    rng = np.random.RandomState(13)
    targets = {"w0": rng.uniform(-1, 1, 48).astype(np.float32),
               "w1": rng.uniform(-1, 1, 9).astype(np.float32)}
    old = os.environ.get("MXNET_KVSTORE_GRAD_DTYPE")
    os.environ["MXNET_KVSTORE_GRAD_DTYPE"] = wire
    servers, cl = _cluster(n=2, big_bound=10**6)
    sched = comm.CommScheduler(comm.make_ps_launch(cl), strict_order=False,
                               max_bucket_bytes=1 << 20)
    try:
        ws = {k: np.zeros_like(t) for k, t in targets.items()}
        for k in targets:
            cl.init(k, ws[k])
        cl.set_optimizer(mx.optimizer.SGD(learning_rate=lr,
                                          rescale_grad=1.0, wd=0.0))
        for _ in range(steps):
            for k, t in targets.items():
                sched.submit(k, ws[k] - t)  # dL/dw
            sched.flush()
            sched.drain()
            for k in targets:
                ws[k] = cl.pull(k)
        return ws, targets
    finally:
        sched.close()
        cl.close()
        [s.close() for s in servers]
        if old is None:
            os.environ.pop("MXNET_KVSTORE_GRAD_DTYPE", None)
        else:
            os.environ["MXNET_KVSTORE_GRAD_DTYPE"] = old


def test_bf16_wire_converges_within_tolerance():
    w32, targets = _fit_quadratic("fp32")
    wbf, _ = _fit_quadratic("bf16")
    for k, t in targets.items():
        # fp32 wire: tight convergence
        np.testing.assert_allclose(w32[k], t, atol=2e-3, err_msg=k)
        # bf16 wire: converges to the same optimum within the bf16
        # noise floor (~0.4% relative), nowhere near divergence
        np.testing.assert_allclose(wbf[k], t, atol=2e-2, err_msg=k)
        np.testing.assert_allclose(wbf[k], w32[k], atol=2e-2, err_msg=k)


def test_wire_dtype_knob_parses():
    old = os.environ.get("MXNET_KVSTORE_GRAD_DTYPE")
    try:
        for val, want in [("fp32", None), ("bf16", "bfloat16"),
                          ("fp16", "float16")]:
            os.environ["MXNET_KVSTORE_GRAD_DTYPE"] = val
            got = comm.wire_dtype()
            assert (got is None) == (want is None)
            if want:
                assert got.name == want
        os.environ["MXNET_KVSTORE_GRAD_DTYPE"] = "int7"
        with pytest.raises(MXNetError):
            comm.wire_dtype()
    finally:
        if old is None:
            os.environ.pop("MXNET_KVSTORE_GRAD_DTYPE", None)
        else:
            os.environ["MXNET_KVSTORE_GRAD_DTYPE"] = old


# -- kvstore satellites --------------------------------------------------
def test_set_rescale_scales_pushes_once():
    kv = mx.kv.create("local")
    kv.init(0, mx.nd.zeros((4,)))
    kv.set_rescale(0.5)
    kv.push(0, mx.nd.ones((4,)) * 4)
    out = mx.nd.empty((4,))
    kv.pull(0, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((4,), 2.0))
    # applied BEFORE the updater (the wire-side scale), exactly once
    kv2 = mx.kv.create("local")
    kv2.init(0, mx.nd.ones((4,)))
    kv2.set_optimizer(mx.optimizer.SGD(learning_rate=1.0,
                                       rescale_grad=1.0, wd=0.0))
    kv2.set_rescale(0.25)
    kv2.push(0, mx.nd.ones((4,)) * 4)  # updater sees 4*0.25 = 1
    out2 = mx.nd.empty((4,))
    kv2.pull(0, out=out2)
    np.testing.assert_allclose(out2.asnumpy(), np.zeros((4,)))  # 1 - 1*1


def test_get_num_dead_node_unified_default():
    import inspect

    from mxnet_tpu.kvstore import DistKVStore, KVStore

    # the staleness threshold is unified in the config catalog
    # (MXNET_DEAD_RANK_TIMEOUT): every consumer defaults to None and
    # resolves through it — no scattered literals
    for cls in (KVStore, DistKVStore):
        sig = inspect.signature(cls.get_num_dead_node)
        assert sig.parameters["timeout"].default is None, cls
    assert inspect.signature(
        DistKVStore.dead_ranks).parameters["timeout"].default is None
    from mxnet_tpu import config

    assert config.describe("MXNET_DEAD_RANK_TIMEOUT").default == 60.0
    assert config.describe("MXNET_HEARTBEAT_INTERVAL").default == 1.0
    assert mx.kv.create("local").get_num_dead_node() == 0


# -- bench tooling -------------------------------------------------------
def test_bench_comm_tool_beats_serial():
    """tools/bench_comm.py must run, emit the shared JSON schema, and
    show the bucketed+async path beating per-key blocking on a
    many-small-keys workload (the acceptance number)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               COMM_KEYS="64", COMM_KEY_BYTES="8192", COMM_ROUNDS="6",
               COMM_BUCKET_KB="1024", COMM_COMPUTE_MS="1.0")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_comm.py")],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    res = json.loads(r.stdout.strip().splitlines()[-1])
    for field in ("bytes_s", "p50_ms", "p90_ms", "p99_ms",
                  "overlap_ratio", "vs_serial", "sweep"):
        assert field in res, field
    assert res["metric"] == "comm_throughput"
    assert res["vs_serial"] > 1.0, res
    assert 0.0 <= res["overlap_ratio"] <= 1.0
