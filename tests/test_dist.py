"""Multi-process dist_sync tests: tools/launch.py spawns 2 real
processes sharing one JAX distributed runtime (the reference tests
multi-node the same way: ``tools/launch.py -n 3 --launcher local``,
``tests/nightly/dist_sync_kvstore.py``)."""

import os
import subprocess
import sys
import time

import pytest

import mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_launch_two_process_dist_sync():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--cpu",
         sys.executable, os.path.join(REPO, "tests", "dist_worker.py")],
        capture_output=True, text=True, timeout=600,
        cwd=REPO)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out
    assert "worker 0/2: dist_sync kvstore OK" in out
    assert "worker 1/2: dist_sync kvstore OK" in out


def test_heartbeat_dead_node_detection(tmp_path, monkeypatch):
    """A stale heartbeat file counts as a dead worker."""
    hb = tmp_path / "hb"
    hb.mkdir()
    monkeypatch.setenv("MXNET_KVSTORE_HEARTBEAT_DIR", str(hb))
    monkeypatch.setenv("MXNET_KVSTORE_HEARTBEAT_INTERVAL", "0.2")
    kv = mx.kv.create("dist_sync")  # single-process: no coordinator env

    class TwoWorkerView(type(kv)):
        @property
        def num_workers(self):
            return 2

    kv.__class__ = TwoWorkerView
    time.sleep(0.5)  # our own heartbeat fires
    # rank 0 (us) alive, rank 1 never wrote -> 1 dead
    assert kv.get_num_dead_node(timeout=5) == 1
    # a fresh rank-1 heartbeat brings it back
    (hb / "hb_1").write_text(str(time.time()))
    assert kv.get_num_dead_node(timeout=5) == 0
    # stale rank-1 heartbeat dies again
    old = time.time() - 100
    os.utime(hb / "hb_1", (old, old))
    assert kv.get_num_dead_node(timeout=5) == 1


def test_launcher_propagates_failure():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--cpu", sys.executable, "-c", "import sys; sys.exit(3)"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert r.returncode == 1
    assert "failed" in r.stderr


def test_bandwidth_tool_runs():
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bandwidth.py"),
         "--sizes", "1", "--iters", "2"],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "psum GB/s" in r.stdout
