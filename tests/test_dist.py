"""Multi-process dist_sync tests: tools/launch.py spawns 2 real
processes sharing one JAX distributed runtime (the reference tests
multi-node the same way: ``tools/launch.py -n 3 --launcher local``,
``tests/nightly/dist_sync_kvstore.py``)."""

import os
import subprocess
import sys
import time

import pytest

import mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _worker_env():
    """Env for launcher-spawned workers: one CPU device per process
    (the realistic per-process topology).  conftest.py's 8-virtual-
    device XLA_FLAGS would otherwise be inherited — 16 virtual devices
    across 2 processes plus the PS handler thread oversubscribe this
    sandbox's single core to a crawl."""
    env = dict(os.environ, PYTHONPATH=REPO)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    return env


def test_launch_two_process_dist_sync():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--cpu",
         sys.executable, os.path.join(REPO, "tests", "dist_worker.py")],
        capture_output=True, text=True, timeout=600,
        cwd=REPO, env=_worker_env())
    out = r.stdout + r.stderr
    assert r.returncode == 0, out
    assert "worker 0/2: dist_sync kvstore OK" in out
    assert "worker 1/2: dist_sync kvstore OK" in out


def test_heartbeat_dead_node_detection(tmp_path, monkeypatch):
    """A stale heartbeat file counts as a dead worker."""
    hb = tmp_path / "hb"
    hb.mkdir()
    monkeypatch.setenv("MXNET_KVSTORE_HEARTBEAT_DIR", str(hb))
    monkeypatch.setenv("MXNET_KVSTORE_HEARTBEAT_INTERVAL", "0.2")
    kv = mx.kv.create("dist_sync")  # single-process: no coordinator env

    class TwoWorkerView(type(kv)):
        @property
        def num_workers(self):
            return 2

    kv.__class__ = TwoWorkerView
    time.sleep(0.5)  # our own heartbeat fires
    # rank 0 (us) alive, rank 1 never wrote -> 1 dead
    assert kv.get_num_dead_node(timeout=5) == 1
    # a fresh rank-1 heartbeat brings it back
    (hb / "hb_1").write_text(str(time.time()))
    assert kv.get_num_dead_node(timeout=5) == 0
    # stale rank-1 heartbeat dies again
    old = time.time() - 100
    os.utime(hb / "hb_1", (old, old))
    assert kv.get_num_dead_node(timeout=5) == 1


def test_launcher_propagates_failure():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--cpu", sys.executable, "-c", "import sys; sys.exit(3)"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert r.returncode == 1
    assert "failed" in r.stderr


def test_bandwidth_tool_runs():
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bandwidth.py"),
         "--sizes", "1", "--iters", "2"],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "psum GB/s" in r.stdout


def test_launch_module_fit_dist_sync(tmp_path):
    """Module.fit across 2 real processes (kvstore='dist_sync',
    update_on_kvstore) must produce the same final weights as a
    single-process run on the union data — the reference's
    tests/nightly/dist_lenet.py check."""
    import numpy as np

    out = str(tmp_path / "dist_params")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--cpu",
         sys.executable, os.path.join(REPO, "tests", "dist_module_worker.py"),
         out],
        capture_output=True, text=True, timeout=900, cwd=REPO)
    o = r.stdout + r.stderr
    assert r.returncode == 0, o
    assert "worker 0/2: module fit dist_sync OK" in o
    assert "worker 1/2: module fit dist_sync OK" in o

    # single-process reference: same data, global batch, local updater
    sys.path.insert(0, os.path.join(REPO, "tests"))
    import dist_module_worker as W
    X, y = W.make_data()
    single = W.train(X, y, W.GLOBAL_BATCH, kvstore=None)

    d0 = dict(np.load(out + ".rank0.npz"))
    d1 = dict(np.load(out + ".rank1.npz"))
    assert set(d0) == set(single)
    for k in single:
        # both workers identical (replicated updater)
        np.testing.assert_allclose(d0[k], d1[k], rtol=1e-6, atol=1e-7,
                                   err_msg=f"worker disagreement on {k}")
        # and equal to the single-process run
        np.testing.assert_allclose(d0[k], single[k], rtol=1e-4, atol=1e-5,
                                   err_msg=f"dist != single for {k}")


def test_launch_module_fit_tpu_mesh(tmp_path):
    """The north star's execution model: Module.fit(kvstore='tpu') jits
    the fused step over ONE global mesh spanning 2 processes × 4
    virtual devices (dp=8).  Each process supplies only its host-local
    batch (staged via host_local_array_to_global_array); gradients are
    psum'd INSIDE the jitted program across the process boundary.
    Final weights must equal a single-process dp=8 run on the union
    data (reference: kvstore_dist.h:28-318 multi-node story +
    tests/nightly/dist_lenet.py check)."""
    import numpy as np

    out = str(tmp_path / "mesh_params")
    env = dict(os.environ, PYTHONPATH=REPO)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--cpu",
         sys.executable, os.path.join(REPO, "tests", "dist_tpu_mesh_worker.py"),
         out],
        capture_output=True, text=True, timeout=900, cwd=REPO, env=env)
    o = r.stdout + r.stderr
    assert r.returncode == 0, o
    assert "worker 0/2: module fit tpu mesh OK" in o
    assert "worker 1/2: module fit tpu mesh OK" in o
    # dp=4 x tp=2 phase: both ranks train through the tensor-sharded
    # weight and read back identical replicated weights
    import re as _re
    tp_digests = _re.findall(r"tp mesh OK digest=(-?[\d.]+)", o)
    assert len(tp_digests) == 2, o
    assert tp_digests[0] == tp_digests[1], tp_digests

    # single-process reference: same union data, global batch, dp=8 mesh
    sys.path.insert(0, os.path.join(REPO, "tests"))
    import dist_tpu_mesh_worker as W
    X, y = W.make_data()
    single = W.train(X, y, W.GLOBAL_BATCH, kvstore="tpu", seed=7)

    d0 = dict(np.load(out + ".rank0.npz"))
    d1 = dict(np.load(out + ".rank1.npz"))
    assert set(d0) == set(single)
    for k in single:
        # both workers read identical replicated weights off the mesh
        np.testing.assert_allclose(d0[k], d1[k], rtol=1e-6, atol=1e-7,
                                   err_msg=f"worker disagreement on {k}")
        # and equal to the single-process dp=8 run
        np.testing.assert_allclose(d0[k], single[k], rtol=1e-4, atol=1e-5,
                                   err_msg=f"mesh != single for {k}")

    # tp phase ground truth: the 2-process dp=4×tp=2 weights must also
    # equal a single-process dp=4×tp=2 run on the union data in the
    # staged global order — rank agreement alone can't catch a
    # consistently-wrong sharded matmul
    _, tp_single = W.train_tp(None)
    t0 = dict(np.load(out + ".tp.rank0.npz"))
    t1 = dict(np.load(out + ".tp.rank1.npz"))
    assert set(t0) == set(tp_single)
    for k in tp_single:
        np.testing.assert_allclose(t0[k], t1[k], rtol=1e-6, atol=1e-7,
                                   err_msg=f"tp worker disagreement on {k}")
        np.testing.assert_allclose(t0[k], tp_single[k], rtol=1e-4, atol=1e-5,
                                   err_msg=f"tp mesh != single for {k}")


def test_launch_module_fit_dist_sync_on_server(tmp_path):
    """Server-side sync updates (MXNET_KVSTORE_SYNC_ON_SERVER=1): the
    optimizer runs on the sharded servers once NumWorkers pushes arrive,
    workers stateless, pulls wait for the round; FC weights exceed the
    (lowered) big-array bound so split keys are exercised in training.
    Final weights must equal the replicated-path single-process run
    (reference: kvstore_dist_server.h:136-219)."""
    import numpy as np

    out = str(tmp_path / "srv_params")
    env = _worker_env()
    env["MXNET_KVSTORE_SYNC_ON_SERVER"] = "1"
    env["MXNET_KVSTORE_BIGARRAY_BOUND"] = "1000"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--cpu",
         sys.executable,
         os.path.join(REPO, "tests", "dist_sync_server_worker.py"), out],
        capture_output=True, text=True, timeout=900, cwd=REPO, env=env)
    o = r.stdout + r.stderr
    assert r.returncode == 0, o
    assert "worker 0/2: module fit dist_sync on-server OK" in o
    assert "worker 1/2: module fit dist_sync on-server OK" in o

    sys.path.insert(0, os.path.join(REPO, "tests"))
    import dist_module_worker as W
    X, y = W.make_data()
    single = W.train(X, y, W.GLOBAL_BATCH, kvstore=None)

    d0 = dict(np.load(out + ".rank0.npz"))
    d1 = dict(np.load(out + ".rank1.npz"))
    assert set(d0) == set(single)
    for k in single:
        np.testing.assert_allclose(d0[k], d1[k], rtol=1e-6, atol=1e-7,
                                   err_msg=f"worker disagreement on {k}")
        np.testing.assert_allclose(d0[k], single[k], rtol=1e-4, atol=1e-5,
                                   err_msg=f"server-sync != single for {k}")


def test_telemetry_traces_and_watchdog(tmp_path):
    """The observability acceptance path: 2 real processes trace their
    kvstore traffic, dump per-rank Chrome traces, tools/trace_merge.py
    merges them into ONE valid timeline with both pids — and a
    deliberately delayed worker is NAMED by the barrier watchdog log
    within the deadline (instead of the job hanging silently)."""
    import json

    trace_dir = str(tmp_path / "traces")
    env = _worker_env()
    env["MXNET_WATCHDOG_DEADLINE"] = "1"
    env["STRAGGLER_SLEEP_S"] = "4"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--cpu",
         sys.executable,
         os.path.join(REPO, "tests", "dist_telemetry_worker.py"), trace_dir],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out
    assert "worker 0/2: telemetry OK" in out
    assert "worker 1/2: telemetry OK" in out

    # the watchdog named the straggler while rank 1 was still sleeping
    assert "[watchdog] kvstore barrier" in out, out
    assert "waiting on ranks [1]" in out, out

    # per-rank traces exist and merge into one valid Chrome trace
    for rank in (0, 1):
        assert os.path.isfile(
            os.path.join(trace_dir, f"trace_rank{rank}.json"))
    merged = str(tmp_path / "merged.json")
    rm = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_merge.py"),
         trace_dir, "-o", merged],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert rm.returncode == 0, rm.stdout + rm.stderr
    with open(merged) as f:
        trace = json.load(f)
    evs = trace["traceEvents"]
    pids = {e["pid"] for e in evs}
    assert pids == {0, 1}, pids  # both ranks present, rank-keyed pids
    names = {e["name"] for e in evs if e.get("ph") == "X"}
    assert any("kvstore" in n for n in names), names
    for pid in (0, 1):  # both ranks contributed real span events
        assert any(e.get("ph") == "X" and e["pid"] == pid for e in evs)
    # spans carry args (bytes moved) for the trace viewer detail pane
    assert any(e.get("args", {}).get("bytes")
               for e in evs if e.get("ph") == "X"), names


def test_comm_overlap_trace(tmp_path):
    """The bucketed-async-comm acceptance path: 2 real processes push
    through the comm scheduler under a small bucket cap; the merged
    trace must show ``kvstore.bucket`` spans (comm thread) running
    WHILE the main thread is inside compute spans — the explicit
    overlap.compute window first (impossible on the blocking path,
    where every allgather completes before push() returns), then under
    Module.fit's fit.step timeline — and both ranks end with identical
    weights.  A bf16-wire phase inside the worker checks compressed
    payloads still sum exactly."""
    import json
    import re

    trace_dir = str(tmp_path / "traces")
    env = _worker_env()
    env["MXNET_KVSTORE_BUCKET_BYTES"] = "65536"  # force several buckets
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--cpu",
         sys.executable,
         os.path.join(REPO, "tests", "dist_overlap_worker.py"), trace_dir],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out
    digests = re.findall(r"comm overlap OK digest=([\d.]+)", out)
    assert len(digests) == 2, out
    assert digests[0] == digests[1], f"weight digests differ: {digests}"

    merged = str(tmp_path / "merged.json")
    rm = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_merge.py"),
         trace_dir, "-o", merged],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert rm.returncode == 0, rm.stdout + rm.stderr
    with open(merged) as f:
        evs = [e for e in json.load(f)["traceEvents"]
               if e.get("ph") == "X"]

    def spans(pid, name):
        return [(e["ts"], e["ts"] + e["dur"], e.get("tid"))
                for e in evs if e["pid"] == pid and e["name"] == name]

    for pid in (0, 1):
        buckets = spans(pid, "kvstore.bucket")
        assert buckets, f"rank {pid}: no kvstore.bucket spans"
        # bucket spans carry byte counts for the viewer detail pane
        assert any(e.get("args", {}).get("bytes")
                   for e in evs if e["pid"] == pid
                   and e["name"] == "kvstore.bucket")
        # (1) comm runs on another thread DURING the explicit compute
        # window issued after the pushes already returned
        (c0, c1, ctid), = spans(pid, "overlap.compute")
        overlapping = [b for b in buckets
                       if b[0] < c1 and b[1] > c0 and b[2] != ctid]
        assert overlapping, (
            f"rank {pid}: no comm-thread kvstore.bucket span inside "
            f"the overlap.compute window [{c0}, {c1}]: {buckets}")
        # (2) comm rides under the training-step timeline too
        steps = spans(pid, "fit.step")
        assert steps, f"rank {pid}: no fit.step spans"
        assert any(b[0] < s1 and b[1] > s0
                   for b in buckets for (s0, s1, _t) in steps), (
            f"rank {pid}: no kvstore.bucket span overlaps any fit.step")


def test_launch_two_process_dist_async():
    """Real async consistency: unequal push rates, pulls without
    rendezvous, every push applied on arrival (reference:
    kvstore_dist_server.h:199-207)."""
    env = _worker_env()
    env["MXNET_KVSTORE_BIGARRAY_BOUND"] = "5000"  # (120,120) must split
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--cpu",
         sys.executable, os.path.join(REPO, "tests", "dist_async_worker.py")],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env=env)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out
    assert "worker 0/2: dist_async update-on-arrival OK" in out
    assert "worker 1/2: dist_async update-on-arrival OK" in out


def test_launch_module_fit_dist_async():
    """Module.fit over the async parameter server: 2 workers at
    different cadences, both converge, and after the final barrier both
    pull identical server weights (digest printed and compared)."""
    import re

    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--cpu",
         sys.executable,
         os.path.join(REPO, "tests", "dist_async_module_worker.py")],
        capture_output=True, text=True, timeout=900, cwd=REPO,
        env=_worker_env())
    out = r.stdout + r.stderr
    assert r.returncode == 0, out
    digests = re.findall(r"dist_async Module\.fit OK acc=[\d.]+ "
                         r"digest=([\d.]+)", out)
    assert len(digests) == 2, out
    assert digests[0] == digests[1], f"worker weight digests differ: {digests}"


@pytest.mark.slow
def test_elastic_chaos_drill_2_1_2(tmp_path):
    """ISSUE 8 acceptance: the 2→1→2 elastic drill.  Rank 1 SIGKILLed
    mid-epoch; rank 0 must reach the DeadRankError verdict within the
    dead-rank timeout, re-mesh to dp'=1, re-scatter the last committed
    checkpoint onto the surviving shard, resume with no dropped or
    duplicated samples, re-admit the restarted rank at a checkpoint
    boundary, and converge to an uninterrupted run — zero operator
    actions (tier-1 runs the single-process smoke instead:
    tests/test_elastic.py::test_dead_rank_rollback_resume_bitexact)."""
    import json

    out = str(tmp_path / "drill")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_drill.py"),
         "--out", out, "--kill-step", "10"],
        capture_output=True, text=True, timeout=900, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    verdict = json.loads(r.stdout.strip().splitlines()[-1])
    assert verdict["converged"], verdict
    assert verdict["rebuilds"] >= 1, verdict       # a re-mesh happened
    assert verdict["rejoined"], verdict            # scale back up 1→2
    # rollback replay is bounded by the checkpoint cadence (plus the
    # admission re-shard of the joiner counting from its restore point)
    assert 0 <= verdict["steps_lost"] <= 2 * verdict["ckpt_every_n_steps"], \
        verdict
    # no barrier/sync hung past its deadline: downtime (the largest
    # step-to-step gap on the survivor) stays within detection +
    # recovery bounds
    assert verdict["downtime_s"] < 3 * verdict["dead_timeout_s"], verdict


def test_ckpt_kill_and_resume(tmp_path):
    """Acceptance: kill -9 both workers of a 2-proc dist_sync fit
    EXACTLY between the checkpoint barrier and rank 0's COMMIT, then
    relaunch with resume='auto' — the torn checkpoint must be ignored,
    and the resumed run's final weights (params + replicated-updater
    momentum + iterator position all restored) must bit-match an
    uninterrupted 2-proc run."""
    import numpy as np

    worker = os.path.join(REPO, "tests", "dist_ckpt_worker.py")
    launch = [sys.executable, os.path.join(REPO, "tools", "launch.py"),
              "-n", "2", "--cpu", sys.executable, worker]

    # uninterrupted reference
    ckpt_a, out_a = str(tmp_path / "ckpt_a"), str(tmp_path / "a")
    r = subprocess.run(launch + [ckpt_a, out_a], capture_output=True,
                      text=True, timeout=600, cwd=REPO, env=_worker_env())
    o = r.stdout + r.stderr
    assert r.returncode == 0, o
    assert "worker 0/2: ckpt dist fit OK" in o

    # crash run: all ranks die after the barrier, before COMMIT, on the
    # 2nd save (step 8 of 16)
    ckpt_b, out_b = str(tmp_path / "ckpt_b"), str(tmp_path / "b")
    env = _worker_env()
    env["MXNET_CKPT_CRASH"] = "before_commit:2"
    r = subprocess.run(launch + [ckpt_b, out_b], capture_output=True,
                      text=True, timeout=600, cwd=REPO, env=env)
    assert r.returncode != 0, r.stdout + r.stderr

    from mxnet_tpu import checkpoint as C
    infos = C.list_checkpoints(ckpt_b)
    committed = [i.step for i in infos if i.committed]
    torn = [i.step for i in infos if not i.committed]
    assert committed == [4], infos   # step-8 attempt never committed
    assert torn == [8], infos        # ...and its shards are all there
    # both ranks' shards made it to durable storage before the kill —
    # the crash window is precisely barrier -> COMMIT
    torn_dir = [i.path for i in infos if not i.committed][0]
    assert sorted(f for f in os.listdir(torn_dir) if f.endswith(".ok")) == \
        ["shard-00000.ok", "shard-00001.ok"]
    assert "COMMIT" not in os.listdir(torn_dir)

    # resume run: picks the last committed checkpoint (step 4),
    # replays, and lands on the uninterrupted run's exact weights
    r = subprocess.run(launch + [ckpt_b, out_b], capture_output=True,
                      text=True, timeout=600, cwd=REPO, env=_worker_env())
    o = r.stdout + r.stderr
    assert r.returncode == 0, o
    assert "resuming from" in o and "step 4" in o

    for rank in (0, 1):
        ref = dict(np.load(out_a + f".rank{rank}.npz"))
        res = dict(np.load(out_b + f".rank{rank}.npz"))
        assert set(ref) == set(res)
        for k in ref:
            np.testing.assert_array_equal(
                ref[k], res[k],
                err_msg=f"rank{rank} {k}: resume diverged from the "
                        "uninterrupted run")
