"""Model-parallel serving tests: the tp(+pp) DecodeEngine on the
virtual 8-device CPU mesh must be BIT-IDENTICAL (fp32/lax) to the
single-device engine — same tokens for the same (engine seed, stream
seed, position) triples — with the prefix cache, speculative decoding,
int8 KV storage and preemption composing unchanged on top.

Tier-1 carries one fast tp=2 smoke plus the at-construction env
validation; the full (tp, pp) x feature matrix is ``slow``.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError

V, KVB, L, H, DM, DFF, MAXLEN = 61, 4, 2, 2, 32, 128, 32


def _mesh_devices(n):
    import jax

    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} virtual devices")


@pytest.fixture(scope="module")
def lm_params():
    rng = np.random.RandomState(0)
    p = {"tok_embed_weight":
         (rng.randn(V, DM) * 0.1).astype(np.float32),
         "pos_embed_weight":
         (rng.randn(MAXLEN, DM) * 0.1).astype(np.float32)}
    for i in range(L):
        p[f"layer{i}_ln1_gamma"] = np.ones(DM, np.float32)
        p[f"layer{i}_ln1_beta"] = np.zeros(DM, np.float32)
        p[f"layer{i}_qkv_weight"] = \
            (rng.randn(3 * DM, DM) * 0.1).astype(np.float32)
        p[f"layer{i}_qkv_bias"] = \
            (rng.randn(3 * DM) * 0.1).astype(np.float32)
        p[f"layer{i}_proj_weight"] = \
            (rng.randn(DM, DM) * 0.1).astype(np.float32)
        p[f"layer{i}_proj_bias"] = \
            (rng.randn(DM) * 0.1).astype(np.float32)
        p[f"layer{i}_ln2_gamma"] = np.ones(DM, np.float32)
        p[f"layer{i}_ln2_beta"] = np.zeros(DM, np.float32)
        p[f"layer{i}_ff1_weight"] = \
            (rng.randn(DFF, DM) * 0.1).astype(np.float32)
        p[f"layer{i}_ff1_bias"] = \
            (rng.randn(DFF) * 0.1).astype(np.float32)
        p[f"layer{i}_ff2_weight"] = \
            (rng.randn(DM, DFF) * 0.1).astype(np.float32)
        p[f"layer{i}_ff2_bias"] = \
            (rng.randn(DM) * 0.1).astype(np.float32)
    p["ln_f_gamma"] = np.ones(DM, np.float32)
    p["ln_f_beta"] = np.zeros(DM, np.float32)
    p["head_weight"] = (rng.randn(V, DM) * 0.1).astype(np.float32)
    p["head_bias"] = (rng.randn(V) * 0.1).astype(np.float32)
    return p


def _engine(params, **kw):
    args = dict(vocab_size=V, num_layers=L, num_heads=H, d_model=DM,
                d_ff=DFF, max_len=MAXLEN, kv_block=KVB, max_streams=2,
                decode_buckets=[1, 2], temperature=0.8, seed=7,
                prefix_cache=0, spec_tokens=0, prefill_chunk=0)
    args.update(kw)
    return mx.DecodeEngine(params, **args)


_PROMPTS = [np.array([3, 7, 1, 9, 2], np.int32),
            np.array([11, 4], np.int32)]


def _generate_all(eng, prompts=_PROMPTS, n=5):
    futs = [eng.submit(p, n, seed=i) for i, p in enumerate(prompts)]
    return [np.asarray(f.result(timeout=300)) for f in futs]


@pytest.fixture(scope="module")
def ref_run(lm_params):
    """One single-device reference run shared by the fast tests:
    (expected tokens, tp=1 per-device pool bytes)."""
    with _engine(lm_params) as ref:
        return _generate_all(ref), ref.stats()["pool_bytes_per_device"]


# ---------------------------------------------------------------------------
# tier-1 smoke: tp=2 equals single-device, stats tell the truth
# ---------------------------------------------------------------------------


def test_tp2_bit_identical_smoke(lm_params, ref_run):
    """tp=2 engine decodes BIT-IDENTICAL tokens to the single-device
    engine (greedy + temperature sampling), reports the mesh shape,
    and each device holds half the tp=1 pool."""
    _mesh_devices(2)
    expect, pool_tp1 = ref_run
    with _engine(lm_params, tp=2) as eng:
        got = _generate_all(eng)
        st = eng.stats()
    for a, b in zip(expect, got):
        np.testing.assert_array_equal(a, b)
    assert st["mesh"]["tp"] == 2 and st["mesh"]["pp"] == 1
    assert len(st["mesh"]["devices"]) == 2
    assert st["mesh"]["sharded"]["heads"]
    assert st["pool_bytes_per_device"] == pool_tp1 // 2
    assert st["kv_dtype"] == "fp32"


def test_mesh_params_roundtrip_and_swap(lm_params, ref_run):
    """get_params returns the checkpoint layout (qkv rows restored);
    swap_params re-shards and decode stays bit-identical."""
    _mesh_devices(2)
    expect = ref_run[0]
    with _engine(lm_params, tp=2) as eng:
        host = eng.get_params()
        for k, v in lm_params.items():
            np.testing.assert_array_equal(host[k], v)
        eng.swap_params(host)
        got = _generate_all(eng)
    for a, b in zip(expect, got):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# at-construction validation: bad tp/pp/devices raise loudly
# ---------------------------------------------------------------------------


def test_env_tp_garbage_raises(lm_params, monkeypatch):
    monkeypatch.setenv("MXNET_SERVING_TP", "banana")
    with pytest.raises(MXNetError, match="MXNET_SERVING_TP"):
        _engine(lm_params)


def test_env_tp_negative_raises(lm_params, monkeypatch):
    monkeypatch.setenv("MXNET_SERVING_TP", "-1")
    with pytest.raises(MXNetError, match="MXNET_SERVING_TP"):
        _engine(lm_params)


def test_env_pp_garbage_raises(lm_params, monkeypatch):
    monkeypatch.setenv("MXNET_SERVING_PP", "0")
    with pytest.raises(MXNetError, match="MXNET_SERVING_PP"):
        _engine(lm_params)


def test_tp_not_dividing_heads_raises(lm_params):
    with pytest.raises(MXNetError, match="num_heads"):
        _engine(lm_params, tp=H + 1)


def test_pp_not_dividing_layers_raises(lm_params):
    with pytest.raises(MXNetError, match="num_layers"):
        _engine(lm_params, pp=L + 1)


def test_devices_wrong_count_raises(lm_params):
    _mesh_devices(2)
    with pytest.raises(MXNetError, match="MXNET_SERVING_DEVICES"):
        _engine(lm_params, tp=2, devices=[0])


def test_devices_duplicate_raises(lm_params):
    _mesh_devices(2)
    with pytest.raises(MXNetError, match="repeats"):
        _engine(lm_params, tp=2, devices=[1, 1])


def test_devices_env_garbage_raises(lm_params, monkeypatch):
    monkeypatch.setenv("MXNET_SERVING_DEVICES", "0,banana")
    with pytest.raises(MXNetError, match="MXNET_SERVING_DEVICES"):
        _engine(lm_params, tp=2)


def test_devices_out_of_range_raises(lm_params):
    with pytest.raises(MXNetError, match="out of"):
        _engine(lm_params, tp=2, devices=[0, 4096])


def test_explicit_devices_select_mesh(lm_params, ref_run):
    """An explicit non-default device set serves identically (mesh
    placement is positional, not ordinal-dependent)."""
    _mesh_devices(4)
    expect = ref_run[0]
    with _engine(lm_params, tp=2, devices=[2, 3]) as eng:
        got = _generate_all(eng)
        assert len(eng.stats()["mesh"]["devices"]) == 2
    for a, b in zip(expect, got):
        np.testing.assert_array_equal(a, b)


def test_spawn_replica_exports_device_set(monkeypatch, tmp_path):
    """fleet.spawn_replica(devices=...) hands the replica its mesh
    slice through MXNET_SERVING_DEVICES."""
    from mxnet_tpu import fleet

    seen = {}

    class _FakeProc:
        def __init__(self, cmd, env=None):
            seen["env"] = env

    monkeypatch.setattr(fleet.subprocess, "Popen",
                        lambda cmd, env=None: _FakeProc(cmd, env))
    fleet.spawn_replica(0, str(tmp_path), "mod:fn", devices=[2, 3])
    assert seen["env"]["MXNET_SERVING_DEVICES"] == "2,3"


# ---------------------------------------------------------------------------
# the slow matrix: (tp, pp) x serving feature, all bit-identical
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("tp,pp", [(2, 1), (2, 2), (1, 2)])
@pytest.mark.parametrize("feature", ["plain", "prefix", "spec",
                                     "int8kv", "chunked", "all"])
def test_mesh_matrix_bit_identical(lm_params, tp, pp, feature):
    """Every serving feature composes with the mesh unchanged: the
    sharded engine's tokens equal the single-device engine's tokens
    bitwise, including resubmission (prefix hits) of the first
    prompt."""
    _mesh_devices(tp * pp)
    kw = {"prefix": dict(prefix_cache=1),
          "spec": dict(spec_tokens=3),
          "int8kv": dict(kv_dtype="int8"),
          "chunked": dict(prefill_chunk=4),
          "all": dict(prefix_cache=1, spec_tokens=3, kv_dtype="int8",
                      prefill_chunk=4),
          "plain": {}}[feature]
    with _engine(lm_params, **kw) as ref:
        expect = _generate_all(ref)
        expect += [np.asarray(
            ref.submit(_PROMPTS[0], 5, seed=0).result(timeout=300))]
    with _engine(lm_params, tp=tp, pp=pp, **kw) as eng:
        got = _generate_all(eng)
        got += [np.asarray(
            eng.submit(_PROMPTS[0], 5, seed=0).result(timeout=300))]
    for a, b in zip(expect, got):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_mesh_preemption_bit_identical(lm_params):
    """A pool too small for all streams forces preemption under the
    mesh too; preempted streams re-prefill and still emit exactly the
    single-device tokens."""
    _mesh_devices(2)
    prompts = [np.arange(1, 6, dtype=np.int32),
               np.arange(7, 12, dtype=np.int32),
               np.arange(13, 18, dtype=np.int32)]
    kw = dict(max_streams=3, decode_buckets=[1, 2, 4], cache_blocks=10,
              temperature=0.0)
    with _engine(lm_params, **kw) as ref:
        futs = [ref.submit(p, 14) for p in prompts]
        expect = [np.asarray(f.result(timeout=300)) for f in futs]
    with _engine(lm_params, tp=2, **kw) as eng:
        futs = [eng.submit(p, 14) for p in prompts]
        got = [np.asarray(f.result(timeout=300)) for f in futs]
        st = eng.stats()
    assert st["preempted"] > 0
    for a, b in zip(expect, got):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_mesh_warmup_compiles_full_matrix(lm_params):
    """warmup() under the mesh AOT-compiles every bucket executable
    (pools donated) without touching the scheduler."""
    _mesh_devices(4)
    with _engine(lm_params, tp=2, pp=2, prefix_cache=1,
                 spec_tokens=2) as eng:
        eng.warmup()
        compiled = set(k.split("'")[1] for k in
                       eng.stats()["compiles"])
    assert {"decode", "prefill", "verify", "prefix_prefill"} <= compiled
