"""Fake-replica worker for the two-process trace-stitching test.

Serves ONE in-process fake harness (no engine, no compile — a
deterministic x*2 forward with a real span) on the fleet wire, with
the flight recorder ring-filing into the shared fleet dir.  The
parent test routes a traced request through a real Router →
ReplicaClient → this process, then stitches both processes' flight
rings into one tree.

Usage (spawned by tests/test_tracing.py):
    MXNET_WORKER_ID=1 MXNET_FLIGHT_RECORDER_DIR=<fleet_dir> \
        python tests/fleet_trace_worker.py <fleet_dir>
"""

import os
import sys
import time
from concurrent.futures import Future

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


class FakeHarness:
    """The ReplicaServer duck type, minus the engine: submit_infer
    answers inputs['data'] * 2 and stamps a replica-side span so the
    stitched tree crosses the process boundary."""

    def submit_infer(self, inputs, trace=None):
        from mxnet_tpu import profiler

        fut = Future()
        with profiler.trace_span("replica.exec", trace, cat="serving",
                                 args={"pid": os.getpid()}):
            time.sleep(0.01)  # a visible span, wider than clock jitter
            out = [np.asarray(inputs["data"], np.float32) * 2.0]
        fut.set_result(out)
        return fut

    def submit_decode(self, *a, **kw):
        raise RuntimeError("fake replica serves infer only")

    def inflight(self):
        return 0

    def drain(self, timeout=30.0):
        return 0

    def resume(self):
        pass

    def stats(self):
        return {"kind": "fake"}

    def swap(self, ckpt_dir, drain_timeout=60.0):
        raise RuntimeError("fake replica has no weights")

    def close(self, timeout=30.0):
        pass


def main():
    fleet_dir = sys.argv[1]
    from mxnet_tpu import profiler
    from mxnet_tpu.checkpoint import atomic_write_bytes
    from mxnet_tpu.fleet import ReplicaServer, read_secret

    profiler.init_flight_recorder(fleet_dir)
    server = ReplicaServer(FakeHarness(), rid=0, fleet_dir=fleet_dir,
                           secret=read_secret(fleet_dir))
    atomic_write_bytes(os.path.join(fleet_dir, "ep_0"),
                       f"127.0.0.1:{server.port}".encode())
    parent = os.getppid()
    while not server.wait_closed(timeout=0.5):
        if os.getppid() != parent:
            break  # orphaned: the test died
    profiler.flight_recorder().sync()
    return 0


if __name__ == "__main__":
    sys.exit(main())
