"""Pallas kernel tests — run in interpreter mode on CPU (same kernel
code path as TPU) and compare against the scan/fori formulations."""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops import pallas_kernels as pk

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _np_lstm(xw, h0, c0, ut):
    T, B, G = xw.shape
    H = G // 4
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    h, c = h0.copy(), c0.copy()
    ys = np.zeros((T, B, H), np.float64)
    for t in range(T):
        pre = xw[t] + h @ ut
        i, f, g, o = [pre[:, k * H:(k + 1) * H] for k in range(4)]
        c = sig(f) * c + sig(i) * np.tanh(g)
        h = sig(o) * np.tanh(c)
        ys[t] = h
    return ys, h, c


def test_lstm_scan_kernel_matches_numpy(monkeypatch):
    monkeypatch.setenv("MXNET_PALLAS", "1")
    rng = np.random.RandomState(0)
    T, B, H = 5, 4, 8
    xw = rng.randn(T, B, 4 * H).astype(np.float32) * 0.5
    h0 = rng.randn(B, H).astype(np.float32) * 0.1
    c0 = rng.randn(B, H).astype(np.float32) * 0.1
    ut = rng.randn(H, 4 * H).astype(np.float32) * 0.2
    y, hT, cT = pk.lstm_scan(xw, h0, c0, ut)
    ey, eh, ec = _np_lstm(xw.astype(np.float64), h0, c0, ut)
    np.testing.assert_allclose(np.asarray(y), ey, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(hT), eh, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(cT), ec, rtol=1e-4, atol=1e-5)


def test_lstm_scan_kernel_gradients(monkeypatch):
    """custom_vjp (remat through the scan) == direct scan gradients."""
    import jax
    import jax.numpy as jnp

    monkeypatch.setenv("MXNET_PALLAS", "1")
    rng = np.random.RandomState(1)
    T, B, H = 4, 3, 6
    xw = jnp.asarray(rng.randn(T, B, 4 * H).astype(np.float32) * 0.4)
    h0 = jnp.zeros((B, H), jnp.float32)
    c0 = jnp.zeros((B, H), jnp.float32)
    ut = jnp.asarray(rng.randn(H, 4 * H).astype(np.float32) * 0.3)

    def loss_pallas(xw, ut):
        y, hT, cT = pk.lstm_scan(xw, h0, c0, ut)
        return jnp.sum(y ** 2) + jnp.sum(hT * cT)

    def loss_scan(xw, ut):
        y, hT, cT = pk._lstm_reference(xw, h0, c0, ut)
        return jnp.sum(y ** 2) + jnp.sum(hT * cT)

    gp = jax.grad(loss_pallas, argnums=(0, 1))(xw, ut)
    gs = jax.grad(loss_scan, argnums=(0, 1))(xw, ut)
    for a, b in zip(gp, gs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_rnn_op_uses_pallas_same_result():
    """mx.nd.RNN under MXNET_PALLAS=1 equals MXNET_PALLAS=0 (subprocess
    so the op caches can't mix the two modes)."""
    script = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, %r)
import jax
import jax.numpy as jnp
import numpy as np
import mxnet_tpu as mx
rng = np.random.RandomState(0)
T, B, I, H = 6, 4, 5, 8
from mxnet_tpu.ops.rnn import rnn_param_size
x = rng.randn(T, B, I).astype(np.float32)
p = rng.randn(rnn_param_size(1, I, H, 1, "lstm")).astype(np.float32) * 0.2
s = np.zeros((1, B, H), np.float32)
out = mx.nd.RNN(mx.nd.array(x), mx.nd.array(p), mx.nd.array(s),
                mx.nd.array(s.copy()), state_size=H, num_layers=1,
                mode="lstm")
np.save(sys.argv[1], out.asnumpy())
"""
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        outs = []
        for flag in ("0", "1"):
            path = os.path.join(d, f"o{flag}.npy")
            env = dict(os.environ, JAX_PLATFORMS="cpu", MXNET_PALLAS=flag,
                       PYTHONPATH=REPO)  # drop .axon_site overrides
            r = subprocess.run([sys.executable, "-c", script % REPO, path],
                               capture_output=True, text=True, env=env,
                               timeout=300)
            assert r.returncode == 0, r.stderr
            outs.append(np.load(path))
        np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5, atol=1e-6)


def test_nms_kernel_matches_fallback(monkeypatch):
    rng = np.random.RandomState(2)
    B, A = 2, 32
    # random sorted-by-score rows with clustered boxes
    rows = np.zeros((B, A, 6), np.float32)
    for b in range(B):
        score = np.sort(rng.rand(A))[::-1]
        cls = rng.randint(0, 3, size=A).astype(np.float32)
        cls[score < 0.2] = -1.0
        centers = rng.rand(A, 2) * 0.6 + 0.2
        wh = rng.rand(A, 2) * 0.3 + 0.05
        rows[b, :, 0] = cls
        rows[b, :, 1] = score
        rows[b, :, 2:4] = centers - wh / 2
        rows[b, :, 4:6] = centers + wh / 2

    import jax.numpy as jnp

    monkeypatch.setenv("MXNET_PALLAS", "1")
    got = np.asarray(pk.nms(jnp.asarray(rows), 0.4, False))

    # python reference of the reference's greedy loop
    expect = rows.copy()
    for b in range(B):
        r = expect[b]
        for i in range(A):
            if r[i, 0] < 0:
                continue
            for j in range(i + 1, A):
                if r[j, 0] < 0 or r[j, 0] != r[i, 0]:
                    continue
                l = max(r[i, 2], r[j, 2]); t = max(r[i, 3], r[j, 3])
                rr = min(r[i, 4], r[j, 4]); bb = min(r[i, 5], r[j, 5])
                inter = max(rr - l, 0) * max(bb - t, 0)
                u = ((r[i, 4] - r[i, 2]) * (r[i, 5] - r[i, 3])
                     + (r[j, 4] - r[j, 2]) * (r[j, 5] - r[j, 3]) - inter)
                if u > 0 and inter / u >= 0.4:
                    r[j, 0] = -1.0
    np.testing.assert_allclose(got[:, :, 0], expect[:, :, 0])
    np.testing.assert_allclose(got[:, :, 1:], expect[:, :, 1:], rtol=1e-6)


def test_multibox_detection_pallas_parity():
    """MultiBoxDetection output identical with and without the kernel."""
    script = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, %r)
import jax
import jax.numpy as jnp
import numpy as np
import mxnet_tpu as mx
rng = np.random.RandomState(3)
B, C, A = 2, 4, 24
anchors = np.zeros((1, A, 4), np.float32)
c = rng.rand(A, 2) * 0.6 + 0.2; wh = rng.rand(A, 2) * 0.2 + 0.1
anchors[0, :, :2] = c - wh / 2; anchors[0, :, 2:] = c + wh / 2
cls_prob = rng.rand(B, C, A).astype(np.float32)
cls_prob /= cls_prob.sum(axis=1, keepdims=True)
loc = (rng.rand(B, A * 4).astype(np.float32) - 0.5) * 0.1
out = mx.nd.MultiBoxDetection(mx.nd.array(cls_prob), mx.nd.array(loc),
                              mx.nd.array(anchors), nms_threshold="0.45")
np.save(sys.argv[1], out.asnumpy())
"""
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        outs = []
        for flag in ("0", "1"):
            path = os.path.join(d, f"d{flag}.npy")
            env = dict(os.environ, JAX_PLATFORMS="cpu", MXNET_PALLAS=flag,
                       PYTHONPATH=REPO)  # drop .axon_site overrides
            r = subprocess.run([sys.executable, "-c", script % REPO, path],
                               capture_output=True, text=True, env=env,
                               timeout=300)
            assert r.returncode == 0, r.stderr
            outs.append(np.load(path))
        np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_kernel_parity(monkeypatch, causal):
    """The Pallas flash kernel (interpret mode on CPU, native on TPU)
    matches the lax.scan blockwise formulation — outputs AND the
    un-normalized partial state used by ring attention, including a
    nonzero kv_offset (the ring's rotated-shard masking)."""
    monkeypatch.setenv("MXNET_PALLAS", "1")
    from mxnet_tpu.ops import attention as A

    rng = np.random.RandomState(0)
    B, T, H, D = 2, 96, 3, 48
    q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    for koff in (0, -32):
        o1, m1, l1 = A._blockwise_attention_partial_lax(
            q, k, v, causal, 64, koff)
        o2, m2, l2 = A.blockwise_attention_partial(
            q, k, v, causal=causal, block_size=64, kv_offset=koff)
        out1 = A.normalize_attention_state(o1, m1, l1, q.dtype)
        out2 = A.normalize_attention_state(o2, m2, l2, q.dtype)
        np.testing.assert_allclose(np.asarray(out2), np.asarray(out1),
                                   rtol=1e-5, atol=1e-5)


def test_flash_attention_kernel_grad(monkeypatch):
    """custom_vjp backward (remat through lax.scan) equals the pure
    lax path's gradient."""
    monkeypatch.setenv("MXNET_PALLAS", "1")
    from mxnet_tpu.ops import attention as A

    rng = np.random.RandomState(1)
    B, T, H, D = 1, 64, 2, 32
    q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))

    def loss_kernel(q, k, v):
        return A.blockwise_attention(q, k, v, causal=True,
                                     block_size=64).sum()

    def loss_lax(q, k, v):
        o, m, l = A._blockwise_attention_partial_lax(q, k, v, True, 64, 0)
        return A.normalize_attention_state(o, m, l, q.dtype).sum()

    g1 = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_lax, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
