"""Each example script must run end-to-end — single device and on the
virtual 8-CPU mesh (the driver's multi-chip validation model)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EX = os.path.join(REPO, "examples")


def run_example(script, *args, mesh=False, timeout=600):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8" if mesh \
        else ""
    env["PYTHONPATH"] = REPO
    r = subprocess.run([sys.executable, os.path.join(EX, script), *args],
                       capture_output=True, text=True, env=env, cwd=EX,
                       timeout=timeout)
    assert r.returncode == 0, f"{script} failed:\n{r.stdout}\n{r.stderr}"
    return r.stdout + r.stderr  # logging output lands on stderr


def test_train_mnist():
    out = run_example("train_mnist.py", "--network", "mlp",
                      "--num-epochs", "2", "--batch-size", "64",
                      "--disp-batches", "10")
    line = [l for l in out.splitlines() if "final validation" in l][-1]
    acc = float(line.split(":")[1])
    assert acc > 0.9, out


def test_train_mnist_mesh_kvstore_tpu():
    out = run_example("train_mnist.py", "--network", "mlp",
                      "--num-epochs", "1", "--kv-store", "tpu",
                      "--batch-size", "64", mesh=True)
    line = [l for l in out.splitlines() if "final validation" in l][-1]
    acc = float(line.split(":")[1])
    assert acc > 0.7, out


def test_train_imagenet_benchmark():
    out = run_example("train_imagenet.py", "--network", "resnet-18",
                      "--benchmark", "1", "--batch-size", "4",
                      "--image-shape", "3,64,64", "--num-classes", "64",
                      "--num-batches", "4", "--num-epochs", "1",
                      "--disp-batches", "2")
    assert "Epoch[0]" in out and "Speed:" in out


def test_benchmark_score():
    out = run_example("benchmark_score.py", "--networks", "lenet",
                      "--batch-sizes", "1,4", "--num-batches", "2")
    assert "img/s" in out


def test_lstm_bucketing():
    out = run_example("lstm_bucketing.py", "--num-epochs", "3",
                      "--batch-size", "16", "--num-hidden", "32",
                      "--num-embed", "16")
    import re

    lines = [l for l in out.splitlines()
             if re.search(r"Epoch\[\d+\] Train-Perplexity=", l)]
    assert len(lines) == 3, out
    first = float(lines[0].rsplit("=", 1)[1])
    last = float(lines[-1].rsplit("=", 1)[1])
    assert last < first, out  # learning


def test_model_parallel_lstm_mesh():
    out = run_example("model_parallel_lstm.py", "--tp", "2",
                      "--num-epochs", "2", "--batch-size", "8",
                      "--seq-len", "8", "--num-hidden", "32",
                      "--num-embed", "16", mesh=True)
    lines = [l for l in out.splitlines() if "loss=" in l]
    assert "tp=2" in lines[-1], out
    first = float(lines[0].rsplit("=", 1)[1])
    last = float(lines[-1].rsplit("=", 1)[1])
    assert last < first, out


def test_ssd_example():
    out = run_example("ssd.py", "--num-epochs", "2", "--batch-size", "4")
    assert "detections per image" in out


@pytest.mark.slow
def test_train_transformer_lm_3d_mesh():
    """The transformer-LM example: full dp×tp×pp from the rules table,
    zero per-op shard attrs (README '3D parallelism').  Slow marker:
    a fresh-process compile of the pipelined step; the same semantics
    run in-process in tests/test_pp.py::test_transformer_lm_rules_3d."""
    out = run_example("train_transformer_lm.py", "--num-steps", "8",
                      mesh=True)
    assert "train_transformer_lm OK" in out
    assert "dp=2 tp=2 pp=2" in out
