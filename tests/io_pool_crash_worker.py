"""Subprocess worker for the pool-mode kill-and-resume test.

Trains a tiny conv net on packed JPEG RecordIO through the FULL
tentpole path — ``ImageRecordIter(workers=2, device_augment=1)`` (a
2-process decode pool feeding raw uint8 batches to the fused device
prologue) — with a CheckpointManager attached.  The test harness runs
it as a subprocess, kills it (kill -9 via the MXNET_CKPT_CRASH hook or
externally), reruns with ``resume='auto'``, and asserts the final
weights bit-match an uninterrupted run: the proof that the exact-resume
contract survives worker processes and device-side augmentation."""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import recordio

N_IMAGES = 48
BATCH = 8
CLASSES = 4
HW = 40          # packed JPEG size; decoded+resized to 36 (pre) -> 32 (crop)
DATA_SHAPE = (3, 32, 32)


def pack_dataset(path):
    import cv2

    rec = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    rng = np.random.RandomState(0)
    for i in range(N_IMAGES):
        img = (rng.rand(HW, HW, 3) * 255).astype(np.uint8)
        ok, buf = cv2.imencode(".jpg", img)
        assert ok
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i % CLASSES), i, 0), buf.tobytes()))
    rec.close()


def build_sym():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, num_filter=8, kernel=(3, 3),
                             stride=(2, 2), name="c1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def train(rec_path, ckpt_dir=None, num_epoch=2, every_n=2, workers=2,
          sleep=0.0, progress=False):
    mx.random.seed(11)
    np.random.seed(11)
    it = mx.io.ImageRecordIter(
        path_imgrec=rec_path + ".rec", path_imgidx=rec_path + ".idx",
        data_shape=DATA_SHAPE, batch_size=BATCH, shuffle=True, seed=7,
        rand_crop=True, rand_mirror=True, workers=workers,
        device_augment=1)
    mod = mx.mod.Module(build_sym(), context=mx.cpu())
    mgr = None
    if ckpt_dir is not None:
        mgr = mx.CheckpointManager(ckpt_dir, every_n_steps=every_n,
                                   async_save=True, keep=10)
    cb = None
    if sleep > 0 or progress:
        def cb(param):
            if progress:
                print(f"BATCH {param.nbatch}", flush=True)
            if sleep > 0:
                time.sleep(sleep)
    mod.fit(it, num_epoch=num_epoch, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
            initializer=mx.initializer.Xavier(rnd_type="gaussian"),
            eval_metric="acc", checkpoint=mgr,
            resume="auto" if mgr is not None else None,
            batch_end_callback=cb)
    if mgr is not None:
        mgr.close()
    it.close()
    args_, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args_.items()}


def main():
    import logging

    logging.basicConfig(level=logging.INFO)  # surface "resuming from"
    ap = argparse.ArgumentParser()
    ap.add_argument("--rec", required=True)
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--every-n", type=int, default=2)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--sleep", type=float, default=0.0)
    ap.add_argument("--progress", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    if not os.path.isfile(args.rec + ".rec"):
        pack_dataset(args.rec)
    params = train(args.rec, args.ckpt_dir, num_epoch=args.epochs,
                   every_n=args.every_n, workers=args.workers,
                   sleep=args.sleep, progress=args.progress)
    if args.out:
        np.savez(args.out, **params)
    print("io pool ckpt worker done", flush=True)


if __name__ == "__main__":
    main()
