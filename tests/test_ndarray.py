"""NDArray tests (modeled on tests/python/unittest/test_ndarray.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal, reldiff


def test_ndarray_creation():
    a = mx.nd.zeros((3, 4))
    assert a.shape == (3, 4)
    assert a.dtype == np.float32
    assert a.asnumpy().sum() == 0
    b = mx.nd.ones((2, 2), dtype=np.int32)
    assert b.dtype == np.int32
    assert b.asnumpy().sum() == 4
    c = mx.nd.full((2,), 7.5)
    assert c.asnumpy()[0] == 7.5
    d = mx.nd.array([[1, 2], [3, 4]])
    assert d.shape == (2, 2)
    e = mx.nd.arange(0, 10, 2)
    assert_almost_equal(e.asnumpy(), np.arange(0, 10, 2))


def test_ndarray_elementwise():
    rng = np.random.RandomState(0)
    for shape in [(4,), (3, 5), (2, 3, 4)]:
        a_np = rng.rand(*shape).astype(np.float32)
        b_np = rng.rand(*shape).astype(np.float32) + 0.1
        a, b = mx.nd.array(a_np), mx.nd.array(b_np)
        assert_almost_equal((a + b).asnumpy(), a_np + b_np, rtol=1e-5)
        assert_almost_equal((a - b).asnumpy(), a_np - b_np, rtol=1e-5)
        assert_almost_equal((a * b).asnumpy(), a_np * b_np, rtol=1e-5)
        assert_almost_equal((a / b).asnumpy(), a_np / b_np, rtol=1e-5)
        assert_almost_equal((a + 2).asnumpy(), a_np + 2, rtol=1e-5)
        assert_almost_equal((2 - a).asnumpy(), 2 - a_np, rtol=1e-5)
        assert_almost_equal((-a).asnumpy(), -a_np, rtol=1e-5)


def test_ndarray_inplace():
    a = mx.nd.ones((2, 2))
    a += 1
    assert (a.asnumpy() == 2).all()
    a *= 3
    assert (a.asnumpy() == 6).all()
    a[:] = 0.5
    assert (a.asnumpy() == 0.5).all()


def test_ndarray_setitem():
    a = mx.nd.zeros((3, 3))
    a[1] = 2.0
    expected = np.zeros((3, 3))
    expected[1] = 2.0
    assert_almost_equal(a.asnumpy(), expected)
    a[0, 2] = 5.0
    expected[0, 2] = 5.0
    assert_almost_equal(a.asnumpy(), expected)


def test_ndarray_slice_reshape():
    a_np = np.arange(24, dtype=np.float32).reshape(4, 6)
    a = mx.nd.array(a_np)
    assert_almost_equal(a.slice(1, 3).asnumpy(), a_np[1:3])
    assert_almost_equal(a[2].asnumpy(), a_np[2])
    assert_almost_equal(a.reshape((2, 12)).asnumpy(), a_np.reshape(2, 12))
    assert_almost_equal(a.reshape((-1, 4)).asnumpy(), a_np.reshape(-1, 4))
    assert_almost_equal(a.T.asnumpy(), a_np.T)


def test_ndarray_copy():
    a = mx.nd.ones((2, 2))
    b = mx.nd.zeros((2, 2))
    a.copyto(b)
    assert (b.asnumpy() == 1).all()
    c = a.copyto(mx.cpu(0))
    assert (c.asnumpy() == 1).all()
    d = a.as_in_context(mx.cpu(0))
    assert d.context.device_type == "cpu"


def test_ndarray_saveload():
    import tempfile, os

    rng = np.random.RandomState(0)
    arrays = [mx.nd.array(rng.rand(3, 4)), mx.nd.array(rng.rand(5))]
    with tempfile.TemporaryDirectory() as d:
        fname = os.path.join(d, "t.params")
        mx.nd.save(fname, arrays)
        loaded = mx.nd.load(fname)
        for a, b in zip(arrays, loaded):
            assert_almost_equal(a.asnumpy(), b.asnumpy())
        named = {"x": arrays[0], "y": arrays[1]}
        mx.nd.save(fname, named)
        loaded = mx.nd.load(fname)
        assert set(loaded) == {"x", "y"}
        assert_almost_equal(loaded["x"].asnumpy(), arrays[0].asnumpy())


def test_ndarray_functions():
    rng = np.random.RandomState(0)
    a_np = rng.rand(3, 4).astype(np.float32)
    a = mx.nd.array(a_np)
    assert_almost_equal(mx.nd.exp(a).asnumpy(), np.exp(a_np), rtol=1e-5)
    assert_almost_equal(mx.nd.square(a).asnumpy(), a_np ** 2, rtol=1e-5)
    assert_almost_equal(mx.nd.sum(a).asnumpy(), a_np.sum().reshape(1), rtol=1e-5)
    assert_almost_equal(mx.nd.sum(a, axis=0).asnumpy(), a_np.sum(0), rtol=1e-5)
    assert_almost_equal(mx.nd.max(a, axis=1).asnumpy(), a_np.max(1), rtol=1e-5)
    b_np = rng.rand(4, 5).astype(np.float32)
    assert_almost_equal(mx.nd.dot(a, mx.nd.array(b_np)).asnumpy(), a_np @ b_np, rtol=1e-4)
    assert_almost_equal(mx.nd.transpose(a).asnumpy(), a_np.T)
    assert_almost_equal(mx.nd.clip(a, a_min=0.2, a_max=0.8).asnumpy(),
                        np.clip(a_np, 0.2, 0.8), rtol=1e-6)


def test_ndarray_onehot():
    idx = mx.nd.array([0, 2, 1])
    out = mx.nd.zeros((3, 3))
    mx.nd.onehot_encode(idx, out)
    assert_almost_equal(out.asnumpy(), np.eye(3)[[0, 2, 1]])


def test_ndarray_astype_scalar():
    a = mx.nd.array([1.5])
    assert a.astype(np.int32).dtype == np.int32
    assert a.asscalar() == 1.5
    assert float(a.asscalar()) == 1.5


def test_ndarray_random():
    mx.random.seed(0)
    a = mx.nd.uniform(low=-1, high=1, shape=(100,))
    assert a.shape == (100,)
    assert -1 <= a.asnumpy().min() and a.asnumpy().max() < 1
    mx.random.seed(7)
    x = mx.nd.normal(loc=0, scale=1, shape=(50,)).asnumpy()
    mx.random.seed(7)
    y = mx.nd.normal(loc=0, scale=1, shape=(50,)).asnumpy()
    assert np.allclose(x, y)


def test_ndarray_waitall():
    a = mx.nd.ones((10, 10))
    b = a * 2
    mx.nd.waitall()
    b.wait_to_read()
    assert (b.asnumpy() == 2).all()


def test_gather_global_local_fast_paths():
    """gather_global: the explicit bulk-synchronous collective that
    asnumpy() refuses to hide.  Single-process arrays are fully
    addressable, so both fast paths must return without communication."""
    a = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_array_equal(mx.nd.gather_global(a), a.asnumpy())
    np.testing.assert_array_equal(mx.nd.gather_global(np.ones(3)),
                                  np.ones(3))
