"""Worker for the server-side-update dist_sync proof.

Launched by ``tools/launch.py -n 2 --cpu python
tests/dist_sync_server_worker.py <out>`` with
``MXNET_KVSTORE_SYNC_ON_SERVER=1`` and a small
``MXNET_KVSTORE_BIGARRAY_BOUND`` (so the FC weights exercise the
split-key path too): the optimizer runs ON the server shards after
NumWorkers pushes, workers stay stateless, and each pull waits for the
round (the reference's dist_sync architecture,
``kvstore_dist_server.h:136-219`` + pickled-optimizer
``python/mxnet/kvstore.py:232-252``).

tests/test_dist.py::test_launch_module_fit_dist_sync_on_server asserts
the final weights equal the replicated-updater single-process run —
same check as the plain dist_sync test, different update architecture.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
import dist_module_worker as W


def main():
    assert os.environ.get("MXNET_KVSTORE_SYNC_ON_SERVER") == "1"
    out_path = sys.argv[1]
    kv = mx.kv.create("dist_sync")
    assert kv._server_sync and kv._ps is not None
    assert kv._updater is None, "workers must be stateless in server mode"
    rank, nw = kv.rank, kv.num_workers
    X, y = W.make_data()
    Xs, ys = W.shard(X, y, rank, nw)
    params = W.train(Xs, ys, W.GLOBAL_BATCH // nw, kv)
    assert kv._updater is None, "optimizer must have stayed server-side"
    np.savez(out_path + f".rank{rank}", **params)
    kv.barrier()
    print(f"worker {rank}/{nw}: module fit dist_sync on-server OK",
          flush=True)


if __name__ == "__main__":
    main()
