"""Worker for the Module-level multi-process training proof.

Launched by ``tools/launch.py -n 2 --cpu python
tests/dist_module_worker.py <out.npz>`` (model:
``/root/reference/tests/nightly/dist_lenet.py`` — a real model trained
across processes through the kvstore, not just raw push/pull).

Each worker runs ``Module.fit`` with ``kvstore='dist_sync'`` on its
shard of a deterministic dataset.  Gradients are per-row sums
(SoftmaxOutput normalization='null'), so the cross-worker allgather-sum
equals the single-process gradient over the union batch and the final
weights must match a single-process run bit-for-bit-ish — asserted by
tests/test_dist.py::test_launch_module_fit_dist_sync.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx

GLOBAL_BATCH = 8
N_SAMPLES = 64
EPOCHS = 2


def build_sym():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, num_filter=8, kernel=(3, 3), name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def make_data():
    rng = np.random.RandomState(5)
    X = rng.randn(N_SAMPLES, 1, 8, 8).astype(np.float32)
    y = rng.randint(0, 10, size=N_SAMPLES).astype(np.float32)
    return X, y


def shard(X, y, rank, num_workers):
    """Worker r takes rows [g*G + r*B, g*G + (r+1)*B) of every global
    batch g, so the union over workers of batch k equals the
    single-process batch k exactly."""
    B = GLOBAL_BATCH // num_workers
    idx = []
    for g in range(N_SAMPLES // GLOBAL_BATCH):
        start = g * GLOBAL_BATCH + rank * B
        idx.extend(range(start, start + B))
    return X[idx], y[idx]


def train(X, y, batch_size, kvstore):
    mx.random.seed(7)
    it = mx.io.NDArrayIter(X, y, batch_size=batch_size, shuffle=False,
                           label_name="softmax_label")
    mod = mx.mod.Module(build_sym(), context=mx.cpu())
    mod.fit(it, num_epoch=EPOCHS, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                              "rescale_grad": 1.0 / GLOBAL_BATCH},
            kvstore=kvstore,
            initializer=mx.initializer.Xavier(rnd_type="gaussian"),
            eval_metric="acc")
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args.items()}


def main():
    out_path = sys.argv[1]
    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    X, y = make_data()
    Xs, ys = shard(X, y, rank, nw)
    params = train(Xs, ys, GLOBAL_BATCH // nw, kv)
    np.savez(out_path + f".rank{rank}", **params)
    kv.barrier()
    print(f"worker {rank}/{nw}: module fit dist_sync OK", flush=True)


if __name__ == "__main__":
    main()
