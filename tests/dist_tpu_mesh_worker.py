"""Worker for the process-spanning-mesh training proof — the north
star's actual execution model (BASELINE: "v5e-64 with kvstore='tpu'",
8 hosts × 8 chips = ONE global mesh).

Launched by ``tools/launch.py -n 2 --cpu python
tests/dist_tpu_mesh_worker.py <out>`` with 4 virtual CPU devices per
process: ``Module.fit(kvstore='tpu')`` jits the fused training step
over a GLOBAL dp=8 mesh spanning both processes.  Each worker feeds
only its host-local batch (staged via
``multihost_utils.host_local_array_to_global_array`` inside
``MeshPlan.stage_input``); the gradient reduction is the in-program
psum XLA inserts from the replicated-parameter vjp — riding gloo here,
ICI/DCN on real hardware (reference multi-node role:
src/kvstore/kvstore_dist.h:28-318, tests/nightly/dist_lenet.py).

tests/test_dist.py::test_launch_module_fit_tpu_mesh asserts the final
weights equal a single-process dp=8 run on the union data.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx

GLOBAL_BATCH = 8
N_SAMPLES = 64
EPOCHS = 2


def build_sym():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, num_filter=8, kernel=(3, 3), name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def make_data():
    rng = np.random.RandomState(5)
    X = rng.randn(N_SAMPLES, 1, 8, 8).astype(np.float32)
    y = rng.randint(0, 10, size=N_SAMPLES).astype(np.float32)
    return X, y


def shard(X, y, rank, num_workers):
    """Worker r takes rows [g*G + r*B, g*G + (r+1)*B) of every global
    batch g: the staged global batch (proc-0 rows ‖ proc-1 rows along
    'dp') then equals the single-process batch g exactly."""
    B = GLOBAL_BATCH // num_workers
    idx = []
    for g in range(N_SAMPLES // GLOBAL_BATCH):
        start = g * GLOBAL_BATCH + rank * B
        idx.extend(range(start, start + B))
    return X[idx], y[idx]


def train(X, y, batch_size, kvstore, seed=7):
    mx.random.seed(seed)
    it = mx.io.NDArrayIter(X, y, batch_size=batch_size, shuffle=False,
                           label_name="softmax_label")
    mod = mx.mod.Module(build_sym(), context=mx.cpu())
    mod.fit(it, num_epoch=EPOCHS, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                              "rescale_grad": 1.0 / GLOBAL_BATCH},
            kvstore=kvstore,
            initializer=mx.initializer.Xavier(rnd_type="gaussian"),
            eval_metric="acc")
    # exercise the plain (non-fused) forward path too: score() pairs
    # host-local labels with the localized slice of the global outputs
    it.reset()
    res = dict(mod.score(it, mx.metric.Accuracy()))
    assert 0.0 <= res["accuracy"] <= 1.0
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args.items()}


def tp_union_order(X, y, num_workers=2, global_batch=16):
    """The single-process row order equivalent to the 2-process tp run:
    each global batch is [proc0's rows ‖ proc1's rows] along 'dp', and
    proc p's rows are X[p::num_workers] — i.e. window g reorders to
    evens-then-odds."""
    idx = []
    for g in range(len(X) // global_batch):
        base = g * global_batch
        for p in range(num_workers):
            idx.extend(range(base + p, base + global_batch, num_workers))
    return X[idx], y[idx]


def train_tp(rank):
    """dp=4 × tp=2 over the SAME process-spanning mesh: each host owns
    two whole dp rows (tp pairs stay within a host — the layout
    MeshPlan.batch_scale enforces); the fc1 weight is tensor-sharded
    over 'tp'.

    ``rank=None`` = the single-process ground truth: same dp=4×tp=2
    mesh over 8 local devices, fed the union data in the staged global
    order (``tp_union_order``) at the full global batch.
    test_dist.py::test_launch_module_fit_tpu_mesh compares final
    weights between the two, the way the dp=8 phase does."""
    import jax

    from mxnet_tpu import parallel

    mx.random.seed(11 + (rank or 0))  # broadcast must still unify
    rng = np.random.RandomState(9)
    X = rng.randn(32, 16).astype(np.float32)
    y = rng.randint(0, 4, size=32).astype(np.float32)
    if rank is None:
        Xs, ys = tp_union_order(X, y)
        batch = 16
    else:
        Xs, ys = X[rank::2], y[rank::2]
        batch = 8
    it = mx.io.NDArrayIter(Xs, ys, batch_size=batch, shuffle=False,
                           label_name="softmax_label")
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=64, name="fc1",
                                attr=parallel.shard_attr("tp", 0))
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params(mx.initializer.Xavier())
    mod.set_mesh_plan(parallel.MeshPlan(jax.devices(), tp=2))
    losses = []

    class CE(mx.metric.EvalMetric):
        def __init__(self):
            super().__init__("ce")

        def update(self, labels, preds):
            p = preds[0].asnumpy()
            lab = labels[0].asnumpy().astype(int)
            self.sum_metric += float(-np.log(np.maximum(
                p[np.arange(len(lab)), lab], 1e-9)).mean())
            self.num_inst += 1

    # no explicit rescale_grad: init_optimizer must default it to
    # 1/GLOBAL batch (local × batch_scale) on a process-spanning mesh
    # — this run is the regression test for that default
    mod.fit(it, num_epoch=6, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            initializer=mx.initializer.Xavier(), eval_metric=CE(),
            batch_end_callback=lambda p: losses.append(
                p.eval_metric.get()[1]))
    args, _ = mod.get_params()
    # gather_global, not asnumpy: fc1 weight/bias are genuinely
    # tp-sharded across the mesh; every rank calls this in lockstep
    params = {k: mx.nd.gather_global(v) for k, v in args.items()}
    digest = sum(float(v.sum()) for v in params.values())
    assert losses[-1] < losses[0], (losses[0], losses[-1])
    return digest, params


def main():
    out_path = sys.argv[1]
    kv = mx.kv.create("tpu")  # wires jax.distributed from launcher env
    import jax

    rank, nw = jax.process_index(), jax.process_count()
    assert nw == int(os.environ["MXNET_NUM_WORKERS"])
    assert len(jax.devices()) == 8, \
        f"want global 8-device mesh, got {len(jax.devices())}"
    # seed differs per rank ON PURPOSE: the mesh plan must broadcast
    # rank 0's initialization (first-init-wins) for workers to agree
    X, y = make_data()
    Xs, ys = shard(X, y, rank, nw)
    params = train(Xs, ys, GLOBAL_BATCH // nw, kv, seed=7 + rank)
    np.savez(out_path + f".rank{rank}", **params)
    kv.barrier()
    print(f"worker {rank}/{nw}: module fit tpu mesh OK", flush=True)

    # phase 2: dp=4 x tp=2 (tensor parallelism within each host) over
    # the same process-spanning mesh; full weights saved so the test
    # can compare against the single-process dp=4×tp=2 ground truth
    digest, tp_params = train_tp(rank)
    np.savez(out_path + f".tp.rank{rank}", **tp_params)
    print(f"worker {rank}/{nw}: tp mesh OK digest={digest:.6f}",
          flush=True)


if __name__ == "__main__":
    main()
