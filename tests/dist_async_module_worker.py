"""Worker for the dist_async Module.fit proof.

Launched by ``tools/launch.py -n 2 --cpu python
tests/dist_async_module_worker.py``.  Each worker runs Module.fit with
``kvstore='dist_async'`` on its own shard at its own pace (worker 1
sleeps between batches): the server applies updates on arrival, so the
fast worker never waits.  Both workers must converge on the shared
model and end with the same weights (final pull after a barrier).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx


def main():
    kv = mx.kv.create("dist_async")
    rank, nw = kv.rank, kv.num_workers

    rng = np.random.RandomState(5)
    X = rng.randn(128, 16).astype(np.float32)
    W = rng.randn(16, 3)
    y = np.argmax(X @ W, axis=1).astype(np.float32)
    Xs, ys = X[rank::nw], y[rank::nw]

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    mx.random.seed(7)
    it = mx.io.NDArrayIter(Xs, ys, batch_size=16, shuffle=False,
                           label_name="softmax_label")

    class SlowIter(mx.io.DataIter):
        """Worker-1 drip-feeds batches: unequal worker cadence."""

        def __init__(self, inner, delay):
            super().__init__(inner.batch_size)
            self._inner, self._delay = inner, delay

        @property
        def provide_data(self):
            return self._inner.provide_data

        @property
        def provide_label(self):
            return self._inner.provide_label

        def reset(self):
            self._inner.reset()

        def next(self):
            if self._delay:
                time.sleep(self._delay)
            return self._inner.next()

    metric = mx.metric.Accuracy()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(SlowIter(it, 0.02 * rank), num_epoch=10, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "rescale_grad": 1.0 / 16},
            kvstore=kv, eval_metric=metric,
            initializer=mx.initializer.Xavier(rnd_type="gaussian"))
    name, acc = metric.get()
    assert acc > 0.8, f"rank {rank} final epoch accuracy {acc}"

    kv.barrier()
    # after the barrier both workers pull identical server weights
    args, _ = mod.get_params()
    # pull by the kvstore's integer keys (init order = param order)
    out = {n: mx.nd.zeros(args[n].shape) for n in args}
    for idx, n in enumerate(mod._param_names):
        kv.pull(idx, out=out[n])
    digest = float(sum(np.abs(out[n].asnumpy()).sum() for n in out))
    print(f"worker {rank}/{nw}: dist_async Module.fit OK "
          f"acc={acc:.3f} digest={digest:.6f}", flush=True)


if __name__ == "__main__":
    main()
