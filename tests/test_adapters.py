"""Multi-tenant serving tests: the paged LoRA adapter pool, per-stream
adapter gather inside the fused decode program, tenant quotas,
SLO-tiered admission, and the draft-LM proposer.

The contracts, in order of appearance:

* :class:`AdapterPool` lifecycle — publish parks, acquire
  revives/shares, eviction is strict LRU over PARKED slots only (a
  held slot id never changes under a stream), retire defers to the
  last holder, an evicted adapter re-installs from the host copy
  (a countable miss, never a failure);
* :class:`TenantQuota` token buckets shed with the TYPED
  :class:`QuotaExceededError` and refill against an injectable clock;
* bit-identity — a no-adapter stream through an adapter-enabled
  engine is BIT-identical to the pre-adapter engine; an adapter
  stream greedy-matches a merged-weights (``W + scale·(A@B)ᵀ``)
  reference run, solo and in mixed-tenant batches, composed with
  prefix cache, speculation, quantized KV, and preemption;
* hot publish/retire under load sheds nothing;
* interactive admission jumps the batch queue;
* per-tenant cost attribution obeys the same conservation the
  per-class records do;
* the draft-LM proposer is deterministic, greedy-safe, and validates
  its env loudly.

Fast variants run in tier-1; the wide sweeps are marked ``slow``.
"""

import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models
from mxnet_tpu.adapters import (AdapterPool, QuotaExceededError,
                                TenantQuota, adapters_enabled,
                                pool_from_env, quota_from_env)
from mxnet_tpu.base import MXNetError
from mxnet_tpu.executor import build_graph_fn
from mxnet_tpu.models.transformer import transformer_lm_prefill
from mxnet_tpu.speculative import DraftLMProposer, make_proposer

V, KVB, L, H, DM, MAXLEN = 61, 4, 2, 2, 32, 32


# ---------------------------------------------------------------------------
# pool unit tests (no engine)
# ---------------------------------------------------------------------------


def _pool(**kw):
    args = dict(num_layers=L, d_model=DM, slots=2, rank_buckets=(4,))
    args.update(kw)
    return AdapterPool(**args)


def _ab(rng, r=4, scale=0.1):
    return (rng.randn(L, DM, r).astype(np.float32) * scale,
            rng.randn(L, r, 3 * DM).astype(np.float32) * scale)


def test_pool_lifecycle_refcounts():
    rng = np.random.RandomState(0)
    p = _pool()
    a, b = _ab(rng)
    slot = p.publish("x", a, b)
    assert slot >= 1  # slot 0 is the reserved null adapter
    assert p.refcount("x") == 0  # published = parked, not held
    bk, s1 = p.acquire("x")
    assert (bk, s1) == (4, slot) and p.refcount("x") == 1
    bk2, s2 = p.acquire("x")  # second stream shares the slot
    assert s2 == s1 and p.refcount("x") == 2
    p.release("x")
    p.release("x")
    assert p.refcount("x") == 0
    st = p.stats()
    assert st["publishes"] == 1 and st["hits"] == 2
    assert st["buckets"]["r4"]["parked"] == 1
    # retire of a parked adapter frees the slot NOW
    assert p.retire("x") is True
    with pytest.raises(MXNetError, match="unknown adapter"):
        p.acquire("x")


def test_pool_lru_eviction_is_deterministic_and_misses_reinstall():
    rng = np.random.RandomState(1)
    p = _pool(slots=2)
    for name in ("a", "b"):
        p.publish(name, *_ab(rng))
    # touch "a" so "b" is the LRU parked slot
    p.acquire("a")
    p.release("a")
    p.publish("c", *_ab(rng))  # pool full: evicts parked LRU = "b"
    assert p.stats()["evictions"] == 1
    # "b" re-installs from the host copy — a miss, not an error
    misses0 = p.stats()["misses"]
    p.acquire("b")
    assert p.stats()["misses"] == misses0 + 1
    p.release("b")


def test_pool_live_slots_never_evict():
    rng = np.random.RandomState(2)
    p = _pool(slots=1)
    p.publish("x", *_ab(rng))
    p.acquire("x")  # held: the only slot is live
    with pytest.raises(MXNetError, match="held by live streams"):
        p.publish("y", *_ab(rng))
    p.release("x")
    p.publish("y", *_ab(rng))  # parked "x" is now evictable


def test_pool_retire_defers_to_last_holder():
    rng = np.random.RandomState(3)
    p = _pool()
    p.publish("x", *_ab(rng))
    p.acquire("x")
    assert p.retire("x") is False  # deferred: a stream holds it
    with pytest.raises(MXNetError, match="retiring"):
        p.acquire("x")  # no NEW streams during a deferred retire
    p.release("x")  # last holder out -> slot freed, name gone
    with pytest.raises(MXNetError, match="unknown adapter"):
        p.bucket_of("x")


def test_pool_rank_buckets_and_validation():
    rng = np.random.RandomState(4)
    p = _pool(rank_buckets=(4, 8))
    a, b = _ab(rng, r=3)
    p.publish("r3", a, b)
    assert p.bucket_of("r3") == 4  # rank 3 pads into bucket 4
    a, b = _ab(rng, r=8)
    p.publish("r8", a, b)
    assert p.bucket_of("r8") == 8
    with pytest.raises(MXNetError, match="exceeds the largest"):
        p.publish("r9", *_ab(rng, r=9))
    with pytest.raises(MXNetError, match="already published"):
        p.publish("r3", *_ab(rng, r=3))
    with pytest.raises(MXNetError, match="A must be"):
        p.publish("bad", np.zeros((L, DM + 1, 4), np.float32),
                  np.zeros((L, 4, 3 * DM), np.float32))
    with pytest.raises(MXNetError, match="B must be"):
        p.publish("bad", np.zeros((L, DM, 4), np.float32),
                  np.zeros((L, 5, 3 * DM), np.float32))
    with pytest.raises(MXNetError, match="retire of unknown"):
        p.retire("nope")


def test_quota_typed_shed_refund_and_refill():
    q = TenantQuota(10)
    q.charge("t", 6)
    with pytest.raises(QuotaExceededError) as ei:
        q.charge("t", 6)
    assert ei.value.reason == "tenant_quota"
    assert ei.value.tenant == "t" and ei.value.needed == 6
    q.refund("t", 4)
    q.charge("t", 6)  # 4 left + 4 refunded = 8 >= 6
    st = q.stats()
    assert st["t"]["shed"] == 1 and st["t"]["charged"] == 12
    # refill against a pinned clock
    now = [0.0]
    q2 = TenantQuota(10, refill_rate=2.0, clock=lambda: now[0])
    q2.charge("u", 10)
    now[0] = 3.0  # 6 tokens refilled
    assert q2.balance("u") == pytest.approx(6.0)
    q2.charge("u", 6)
    # capacity 0 = quotas off: never charges, never sheds
    TenantQuota(0).charge("v", 10 ** 9)


def test_adapter_env_validation(monkeypatch):
    monkeypatch.setenv("MXNET_ADAPTER_SLOTS", "banana")
    with pytest.raises(MXNetError, match="MXNET_ADAPTER_SLOTS"):
        pool_from_env(L, DM)
    monkeypatch.setenv("MXNET_ADAPTER_SLOTS", "0")
    with pytest.raises(MXNetError, match="MXNET_ADAPTER_SLOTS"):
        pool_from_env(L, DM)
    monkeypatch.setenv("MXNET_ADAPTER_SLOTS", "3")
    monkeypatch.setenv("MXNET_ADAPTER_RANK_BUCKETS", "8,4")
    with pytest.raises(MXNetError, match="MXNET_ADAPTER_RANK_BUCKETS"):
        pool_from_env(L, DM)
    monkeypatch.setenv("MXNET_ADAPTER_RANK_BUCKETS", "4,8")
    p = pool_from_env(L, DM)
    assert p.slots == 3 and p.rank_buckets == (4, 8)
    monkeypatch.setenv("MXNET_ADAPTER_ENABLE", "2")
    with pytest.raises(MXNetError, match="MXNET_ADAPTER_ENABLE"):
        adapters_enabled()
    monkeypatch.setenv("MXNET_TENANT_QUOTA_TOKENS", "-1")
    with pytest.raises(MXNetError, match="MXNET_TENANT_QUOTA_TOKENS"):
        quota_from_env()
    monkeypatch.setenv("MXNET_TENANT_QUOTA_TOKENS", "0")
    assert quota_from_env() is None
    monkeypatch.setenv("MXNET_TENANT_QUOTA_TOKENS", "100")
    monkeypatch.setenv("MXNET_TENANT_QUOTA_REFILL", "nope")
    with pytest.raises(MXNetError, match="MXNET_TENANT_QUOTA_REFILL"):
        quota_from_env()
    monkeypatch.setenv("MXNET_TENANT_QUOTA_REFILL", "2.5")
    q = quota_from_env()
    assert q.capacity == 100 and q.refill_rate == 2.5


# ---------------------------------------------------------------------------
# engine integration
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def lm():
    sym = models.transformer_lm(V, MAXLEN, num_layers=L, num_heads=H,
                                d_model=DM, block_size=KVB)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, MAXLEN))],
             label_shapes=[("softmax_label", (2, MAXLEN))],
             for_training=False)
    mod.init_params(mx.initializer.Xavier(factor_type="in",
                                          magnitude=2.0))
    arg, aux = mod.get_params()
    return {**arg, **aux}


def _engine(params, **kw):
    args = dict(vocab_size=V, num_layers=L, num_heads=H, d_model=DM,
                max_len=MAXLEN, kv_block=KVB, max_streams=4,
                decode_buckets=[1, 2, 4], temperature=0.0)
    args.update(kw)
    return mx.DecodeEngine(params, **args)


def _adapters(rng, n=4):
    """N distinct adapters spanning both rank buckets."""
    out = {}
    for i, r in zip(range(n), (2, 4, 4, 8, 3, 8)):
        a = rng.randn(L, DM, r).astype(np.float32) * 0.25
        b = rng.randn(L, r, 3 * DM).astype(np.float32) * 0.25
        out[f"ad{i}"] = (a, b, 2.0 * r)  # alpha -> scale 2.0
    return out


def _merged(params, a, b, alpha):
    """The merged-weights reference: ``W' = W + scale·(A_i @ B_i)ᵀ``
    on each layer's fused QKV projection — what serving adapter
    streams must greedy-match."""
    r = a.shape[2]
    scale = float(alpha) / r
    out = {k: v for k, v in params.items()}
    for i in range(L):
        w = np.asarray(out[f"layer{i}_qkv_weight"].asnumpy()
                       if hasattr(out[f"layer{i}_qkv_weight"],
                                  "asnumpy")
                       else out[f"layer{i}_qkv_weight"])
        delta = (a[i] @ b[i]) * scale        # (DM, 3DM)
        out[f"layer{i}_qkv_weight"] = (w + delta.T).astype(w.dtype)
    return out


@pytest.fixture(scope="module")
def naive(lm):
    """Greedy reference through the UNPAGED prefill symbol with
    arbitrary (possibly merged) params."""
    import jax
    import jax.numpy as jnp

    ps = transformer_lm_prefill(V, num_layers=L, num_heads=H,
                                d_model=DM, kv_block=KVB, paged=False)
    gfn = build_graph_fn(ps)
    names = [n for n in ps.list_arguments() if n in lm]
    key = jax.random.PRNGKey(0)

    def generate(params, prompt, n):
        base = {m: jnp.asarray(params[m].asnumpy()
                               if hasattr(params[m], "asnumpy")
                               else params[m]) for m in names}
        seq = list(np.asarray(prompt))
        out = []
        for _ in range(n):
            t = len(seq)
            a = dict(base)
            a.update(data=jnp.asarray(np.asarray(seq, np.int32)[None]),
                     positions=jnp.asarray(
                         np.arange(t, dtype=np.int32)[None]),
                     lengths=jnp.asarray(np.asarray([t], np.int32)))
            outs, _ = gfn(a, {}, key, False)
            out.append(int(np.argmax(np.asarray(outs[0][0, t - 1]))))
            seq.append(out[-1])
        return np.asarray(out, np.int32)

    return generate


def test_no_adapter_streams_bit_identical_to_pre_adapter_engine(lm):
    """An adapter-enabled engine must not perturb a single bit for
    streams that name no adapter — slot 0 where-selects base bits."""
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, V, size=n).astype(np.int32)
               for n in (3, 7, 11)]
    e0 = _engine(lm)  # the pre-adapter engine
    pool = AdapterPool(num_layers=L, d_model=DM, slots=4,
                       rank_buckets=(4, 8))
    e1 = _engine(lm, adapters=pool)
    # a published (unused) adapter must not change anything either
    r8 = np.random.RandomState(8)
    e1.publish_adapter("idle",
                       r8.randn(L, DM, 4).astype(np.float32),
                       r8.randn(L, 4, 3 * DM).astype(np.float32))
    try:
        for i, p in enumerate(prompts):
            for temp in (0.0, 0.9):
                g0 = e0.generate(p, max_new_tokens=6,
                                 temperature=temp, seed=100 + i)
                g1 = e1.generate(p, max_new_tokens=6,
                                 temperature=temp, seed=100 + i)
                assert np.array_equal(g0, g1), (temp, i)
    finally:
        e0.close()
        e1.close()


def test_adapter_streams_match_merged_weights_solo_and_mixed(lm, naive):
    """THE acceptance contract: N=4 adapters over one base, each
    stream greedy-equal to a merged-weights solo reference — solo and
    in mixed-tenant batches (batch composition never changes tokens),
    with the no-adapter stream untouched."""
    rng = np.random.RandomState(11)
    ads = _adapters(rng, n=4)
    pool = AdapterPool(num_layers=L, d_model=DM, slots=4,
                       rank_buckets=(4, 8))
    eng = _engine(lm, adapters=pool)
    prompt = rng.randint(1, V, size=5).astype(np.int32)
    NEW = 6
    try:
        refs = {}
        for name, (a, b, alpha) in ads.items():
            eng.publish_adapter(name, a, b, alpha=alpha)
            refs[name] = naive(_merged(lm, a, b, alpha), prompt, NEW)
        refs[None] = naive(lm, prompt, NEW)
        # solo runs
        solo = {}
        for name in list(ads) + [None]:
            solo[name] = eng.generate(prompt, max_new_tokens=NEW,
                                      tenant=name and f"tn-{name}",
                                      adapter=name)
            assert np.array_equal(solo[name], refs[name]), name
        # mixed batch: all four adapters + the plain stream at once
        futs = {name: eng.submit(prompt, NEW,
                                 tenant=name and f"tn-{name}",
                                 adapter=name)
                for name in list(ads) + [None]}
        for name, f in futs.items():
            assert np.array_equal(f.result(timeout=60), solo[name]), \
                f"mixed batch changed stream {name!r}"
        st = eng.stats()
        assert st["adapters"]["published"] == 4
        assert set(st["cost_by_tenant"]) == {f"tn-{n}" for n in ads}
        assert st["tenants"][f"tn-ad0"]["requests"] == 2
    finally:
        eng.close()


def test_adapters_compose_with_prefix_spec_and_preemption(lm, naive):
    """Adapter gather composed with the rest of the serving stack:
    prefix cache + n-gram speculation + a pool small enough to force
    preemption — greedy outputs still match the merged reference."""
    rng = np.random.RandomState(13)
    a = rng.randn(L, DM, 4).astype(np.float32) * 0.25
    b = rng.randn(L, 4, 3 * DM).astype(np.float32) * 0.25
    pool = AdapterPool(num_layers=L, d_model=DM, slots=2,
                       rank_buckets=(4,))
    eng = _engine(lm, adapters=pool, prefix_cache=1, spec_tokens=2,
                  cache_blocks=12)
    prompt = np.asarray([3, 9, 3, 9, 3, 9, 4, 4], np.int32)
    NEW = 5
    try:
        eng.publish_adapter("x", a, b, alpha=8.0)
        ref = naive(_merged(lm, a, b, 8.0), prompt, NEW)
        base = naive(lm, prompt, NEW)
        # twice: the second run rides prefix-cache hits
        for _ in range(2):
            got = eng.generate(prompt, max_new_tokens=NEW,
                               tenant="t", adapter="x")
            assert np.array_equal(got, ref)
            assert np.array_equal(
                eng.generate(prompt, max_new_tokens=NEW), base)
        # saturate the tiny pool to force preemption mid-decode
        futs = [eng.submit(rng.randint(1, V, size=9).astype(np.int32),
                           12, adapter="x" if i % 2 else None,
                           tenant="t" if i % 2 else None)
                for i in range(4)]
        for f in futs:
            f.result(timeout=120)
        # the adapter stream survives preemption with its slot pinned
        got = eng.generate(prompt, max_new_tokens=NEW,
                           tenant="t", adapter="x")
        assert np.array_equal(got, ref)
    finally:
        eng.close()


def test_prefix_cache_is_adapter_namespaced(lm, naive):
    """REGRESSION (found by the merged-weights acceptance test): the
    prefix radix index is salted by adapter name — a prompt prefilled
    plain must not satisfy an adapter stream (its K/V lacks the
    delta), and retire-then-republish of the SAME name must not serve
    chains prefilled under the old weights."""
    rng = np.random.RandomState(31)
    a1 = rng.randn(L, DM, 4).astype(np.float32) * 0.25
    b1 = rng.randn(L, 4, 3 * DM).astype(np.float32) * 0.25
    a2 = rng.randn(L, DM, 4).astype(np.float32) * 0.25
    b2 = rng.randn(L, 4, 3 * DM).astype(np.float32) * 0.25
    pool = AdapterPool(num_layers=L, d_model=DM, slots=2,
                       rank_buckets=(4,))
    eng = _engine(lm, adapters=pool, prefix_cache=1)
    prompt = rng.randint(1, V, size=9).astype(np.int32)
    NEW = 5
    try:
        ref1 = naive(_merged(lm, a1, b1, 4.0), prompt, NEW)
        ref2 = naive(_merged(lm, a2, b2, 4.0), prompt, NEW)
        base = naive(lm, prompt, NEW)
        eng.publish_adapter("x", a1, b1, alpha=4.0)
        # seed the UNSALTED tree first: the adapter stream right after
        # must not ride the plain stream's registered pages
        assert np.array_equal(
            eng.generate(prompt, max_new_tokens=NEW), base)
        assert np.array_equal(
            eng.generate(prompt, max_new_tokens=NEW, adapter="x"),
            ref1)
        # and the salted chains must not leak back into plain streams
        assert np.array_equal(
            eng.generate(prompt, max_new_tokens=NEW), base)
        # retire + republish the SAME name with different weights:
        # the old salted chains must be invalidated, not re-matched
        assert eng.retire_adapter("x") is True
        eng.publish_adapter("x", a2, b2, alpha=4.0)
        assert np.array_equal(
            eng.generate(prompt, max_new_tokens=NEW, adapter="x"),
            ref2)
    finally:
        eng.close()


def test_adapter_with_quantized_kv_token_equal_to_merged_engine(lm):
    """int8 KV pools quantize the adapter stream and the merged
    reference identically, so the engines must emit the same
    tokens."""
    rng = np.random.RandomState(17)
    a = rng.randn(L, DM, 4).astype(np.float32) * 0.25
    b = rng.randn(L, 4, 3 * DM).astype(np.float32) * 0.25
    pool = AdapterPool(num_layers=L, d_model=DM, slots=2,
                       rank_buckets=(4,))
    e1 = _engine(lm, adapters=pool, kv_dtype="int8")
    e2 = _engine(_merged(lm, a, b, 8.0), kv_dtype="int8")
    prompt = rng.randint(1, V, size=6).astype(np.int32)
    try:
        e1.publish_adapter("x", a, b, alpha=8.0)
        got = e1.generate(prompt, max_new_tokens=6, adapter="x")
        ref = e2.generate(prompt, max_new_tokens=6)
        assert np.array_equal(got, ref)
    finally:
        e1.close()
        e2.close()


def test_hot_publish_retire_under_load_sheds_nothing(lm):
    """Publish and retire adapters while a background load runs: no
    request fails, no shed, no drain — and streams submitted against
    each new adapter resolve."""
    rng = np.random.RandomState(19)
    pool = AdapterPool(num_layers=L, d_model=DM, slots=3,
                       rank_buckets=(4,))
    eng = _engine(lm, adapters=pool)
    stop = threading.Event()
    failures = []

    def load():
        i = 0
        while not stop.is_set():
            try:
                eng.generate(rng.randint(1, V, size=4).astype(np.int32),
                             max_new_tokens=4, seed=i)
            except BaseException as exc:  # noqa: BLE001
                failures.append(exc)
                return
            i += 1

    t = threading.Thread(target=load, daemon=True)
    t.start()
    try:
        prompt = np.asarray([5, 4, 3, 2], np.int32)
        for gen in range(4):
            name = f"gen{gen}"
            a = rng.randn(L, DM, 4).astype(np.float32) * 0.2
            b = rng.randn(L, 4, 3 * DM).astype(np.float32) * 0.2
            eng.publish_adapter(name, a, b, alpha=4.0)
            out = eng.generate(prompt, max_new_tokens=4, adapter=name,
                               tenant="hot")
            assert out.size == 4
            eng.retire_adapter(name)
            with pytest.raises(MXNetError):
                eng.generate(prompt, max_new_tokens=4, adapter=name)
    finally:
        stop.set()
        t.join(timeout=30)
        st = eng.stats()
        eng.close()
    assert not failures
    assert st["shed"] == 0 and st["shed_tenant_quota"] == 0


def test_tenant_quota_sheds_typed_with_fairness_counters(lm):
    q = TenantQuota(20)
    eng = _engine(lm, tenant_quota=q)
    prompt = np.asarray([1, 2, 3], np.int32)  # 3 + 5 = 8 tokens/req
    try:
        eng.generate(prompt, max_new_tokens=5, tenant="small")
        eng.generate(prompt, max_new_tokens=5, tenant="small")
        with pytest.raises(QuotaExceededError) as ei:
            eng.submit(prompt, 5, tenant="small")
        assert ei.value.reason == "tenant_quota"
        # another tenant's bucket is untouched — per-tenant fairness
        eng.generate(prompt, max_new_tokens=5, tenant="big")
        st = eng.stats()
        assert st["shed_tenant_quota"] == 1
        assert st["tenants"]["small"]["shed"] == 1
        assert st["tenants"]["small"]["requests"] == 2
        assert st["tenants"]["big"]["shed"] == 0
        assert st["tenants"]["small"]["balance"] == 4
    finally:
        eng.close()


def test_interactive_admission_jumps_batch_queue(lm):
    """With one decode seat, a queued interactive request is admitted
    before batch requests that were enqueued AHEAD of it."""
    eng = _engine(lm, max_streams=1, decode_buckets=[1])
    prompt = np.asarray([2, 4, 6], np.int32)
    order = []
    lock = threading.Lock()

    def tag(name):
        def cb(_f):
            with lock:
                order.append(name)
        return cb

    try:
        f0 = eng.submit(prompt, 10)  # occupies the only seat
        time.sleep(0.05)
        fb = eng.submit(prompt, 2, slo_class="batch")
        fb2 = eng.submit(prompt, 2, slo_class="batch")
        fi = eng.submit(prompt, 2, slo_class="interactive")
        for f, n in ((fb, "batch1"), (fb2, "batch2"), (fi, "inter")):
            f.add_done_callback(tag(n))
        for f in (f0, fb, fb2, fi):
            f.result(timeout=60)
    finally:
        eng.close()
    assert order.index("inter") < order.index("batch1")
    assert order.index("inter") < order.index("batch2")


def test_cost_records_carry_tenant_and_conserve(lm):
    eng = _engine(lm)
    prompt = np.asarray([1, 2, 3, 4], np.int32)
    try:
        eng.generate(prompt, max_new_tokens=4, tenant="a")
        eng.generate(prompt, max_new_tokens=6, tenant="a")
        eng.generate(prompt, max_new_tokens=4, tenant="b")
        eng.generate(prompt, max_new_tokens=4)  # unattributed
        recs = eng.cost_records()
        by_tenant = eng.stats()["cost_by_tenant"]
    finally:
        eng.close()
    assert {r.get("tenant") for r in recs} == {"a", "b", None}
    for ten in ("a", "b"):
        mine = [r for r in recs if r.get("tenant") == ten]
        assert by_tenant[ten]["requests"] == len(mine)
        for field in ("tokens", "decode_steps", "flops_est"):
            assert by_tenant[ten][field] == pytest.approx(
                sum(r[field] for r in mine)), (ten, field)
    # the unattributed stream appears in NO tenant bucket
    assert None not in by_tenant and "None" not in by_tenant


def test_adapter_id_rides_cost_records(lm):
    rng = np.random.RandomState(23)
    pool = AdapterPool(num_layers=L, d_model=DM, slots=2,
                       rank_buckets=(4,))
    eng = _engine(lm, adapters=pool)
    try:
        eng.publish_adapter("x", *(_ab(rng)[:2]), alpha=4.0)
        eng.generate(np.asarray([1, 2], np.int32), max_new_tokens=3,
                     tenant="t", adapter="x")
        rec = eng.cost_records()[-1]
    finally:
        eng.close()
    assert rec["tenant"] == "t" and rec["adapter_id"] == "x"


def test_engine_rejects_adapter_without_pool_and_bad_geometry(lm):
    eng = _engine(lm)
    try:
        with pytest.raises(MXNetError, match="no adapter pool"):
            eng.submit(np.asarray([1, 2], np.int32), 2, adapter="x")
        with pytest.raises(MXNetError, match="publish_adapter"):
            eng.publish_adapter("x", np.zeros((L, DM, 4), np.float32),
                                np.zeros((L, 4, 3 * DM), np.float32))
    finally:
        eng.close()
    bad = AdapterPool(num_layers=L + 1, d_model=DM)
    with pytest.raises(MXNetError, match="geometry"):
        _engine(lm, adapters=bad)


# ---------------------------------------------------------------------------
# draft-LM proposer
# ---------------------------------------------------------------------------


def test_draft_lm_proposer_deterministic_and_greedy(lm, naive):
    prop = DraftLMProposer(lm, num_heads=H, kv_block=KVB)
    assert prop.vocab_size == V
    ctx = np.asarray([3, 1, 4, 1, 5], np.int32)
    d1 = prop.propose(ctx, 4)
    d2 = prop.propose(ctx, 4)
    assert np.array_equal(d1, d2)  # a pure function of the context
    # greedy drafts ARE the model's greedy continuation
    assert np.array_equal(d1, naive(lm, ctx, 4))


def test_draft_lm_speculation_bit_identical_and_accepts(lm):
    """Draft == target here, so speculation must accept nearly every
    draft AND stay bit-identical to the non-speculative engine (the
    verify-op contract extends to the draft-LM proposer)."""
    rng = np.random.RandomState(29)
    prompt = rng.randint(1, V, size=6).astype(np.int32)
    e0 = _engine(lm, spec_tokens=0)
    try:
        ref = e0.generate(prompt, max_new_tokens=10)
    finally:
        e0.close()
    prop = DraftLMProposer(lm, num_heads=H, kv_block=KVB)
    e1 = _engine(lm, spec_tokens=3, proposer=prop)
    try:
        got = e1.generate(prompt, max_new_tokens=10)
        st = e1.stats()
    finally:
        e1.close()
    assert np.array_equal(got, ref)
    assert st["spec_proposed"] > 0
    # identical draft/target: acceptance far above the 12-19% n-gram
    # noise floor recorded in PERF.md
    assert st["accepted_token_rate"] > 0.5


def test_draft_lm_env_and_vocab_validation(lm, monkeypatch, tmp_path):
    monkeypatch.delenv("MXNET_SERVING_DRAFT_CKPT", raising=False)
    with pytest.raises(MXNetError, match="MXNET_SERVING_DRAFT_CKPT"):
        make_proposer("draft_lm")
    with pytest.raises(MXNetError, match="MXNET_SERVING_DRAFT_HEADS"):
        DraftLMProposer(lm, num_heads=0)
    with pytest.raises(MXNetError, match="MXNET_SERVING_DRAFT_HEADS"):
        DraftLMProposer(lm, num_heads=3)  # does not divide d_model
    missing = {k: v for k, v in lm.items() if k != "tok_embed_weight"}
    with pytest.raises(MXNetError, match="MXNET_SERVING_DRAFT_CKPT"):
        DraftLMProposer(missing, num_heads=H)
    # a draft over a DIFFERENT vocab is refused at engine construction
    bigger = {}
    for k, v in lm.items():
        arr = np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v)
        if k in ("tok_embed_weight", "head_weight"):
            arr = np.concatenate([arr, arr[-1:]], axis=0)
        elif k == "head_bias":
            arr = np.concatenate([arr, arr[-1:]])
        bigger[k] = arr
    prop = DraftLMProposer(bigger, num_heads=H, kv_block=KVB)
    assert prop.vocab_size == V + 1
    with pytest.raises(MXNetError, match="vocab"):
        _engine(lm, spec_tokens=2, proposer=prop)


# ---------------------------------------------------------------------------
# fleet layer
# ---------------------------------------------------------------------------


def test_wire_spec_roundtrips_tenancy_fields():
    from mxnet_tpu.fleet import _pack_spec, _unpack_spec

    spec = {"kind": "decode", "prompt": np.asarray([1, 2, 3], np.int32),
            "max_new": 4, "temperature": None, "eos": None, "seed": 9,
            "phase": 0, "slo_class": "batch", "tenant": "acme",
            "adapter": "fr-legal"}
    got = _unpack_spec(memoryview(_pack_spec(spec)), 0)
    assert got["slo_class"] == "batch"
    assert got["tenant"] == "acme" and got["adapter"] == "fr-legal"
    spec.update(tenant=None, adapter=None, slo_class="interactive")
    got = _unpack_spec(memoryview(_pack_spec(spec)), 0)
    assert got["tenant"] is None and got["adapter"] is None
    assert got["slo_class"] == "interactive"


class _FakeAdapterReplica:
    """Minimal in-process replica with the adapter surface."""

    def __init__(self, rid, fail_publish=False):
        self.rid = rid
        self.fail_publish = fail_publish
        self.published = []
        self.retired = []

    def publish_adapter(self, name, a, b, alpha=None):
        if self.fail_publish:
            raise MXNetError("no pool here")
        self.published.append(name)
        return len(self.published)

    def retire_adapter(self, name):
        self.retired.append(name)
        return True

    def submit(self, spec):
        from concurrent.futures import Future

        fut = Future()
        fut.set_result([np.zeros(int(spec["max_new"]), np.int32)])
        return fut

    def inflight(self):
        return 0

    def drain(self, timeout=30.0):
        return 0

    def resume(self):
        pass

    def stats(self):
        return {}

    def close(self):
        pass


def test_router_broadcasts_publish_and_rolls_back_on_failure():
    from mxnet_tpu.fleet import Router

    reps = [_FakeAdapterReplica(0), _FakeAdapterReplica(1)]
    r = Router(reps, default_deadline_ms=0)
    try:
        a = np.zeros((L, DM, 4), np.float32)
        b = np.zeros((L, 4, 3 * DM), np.float32)
        out = r.publish_adapter("x", a, b, alpha=4.0)
        assert set(out["slots"]) == {0, 1}
        assert all(rep.published == ["x"] for rep in reps)
        assert r.stats()["adapters_published"] == ["x"]
        out = r.retire_adapter("x")
        assert out["freed"] == {0: True, 1: True}
        assert r.stats()["adapters_published"] == []
        # partial failure: the success is rolled back, the call raises
        reps[1].fail_publish = True
        with pytest.raises(MXNetError, match="rolled back"):
            r.publish_adapter("y", a, b)
        assert "y" in reps[0].retired
        assert r.stats()["adapters_published"] == []
    finally:
        r.close()


def test_router_tenant_quota_sheds_typed_at_accept():
    from mxnet_tpu.fleet import Router, ShedError

    reps = [_FakeAdapterReplica(0)]
    r = Router(reps, default_deadline_ms=0,
               tenant_quota=TenantQuota(20))
    prompt = np.asarray([1, 2, 3], np.int32)
    try:
        r.generate(prompt, max_new_tokens=5,
                   tenant="small").result(timeout=30)
        r.generate(prompt, max_new_tokens=5,
                   tenant="small").result(timeout=30)
        with pytest.raises(ShedError) as ei:
            r.generate(prompt, max_new_tokens=5, tenant="small")
        assert ei.value.reason == "tenant_quota"
        r.generate(prompt, max_new_tokens=5,
                   tenant="big").result(timeout=30)
        st = r.stats()
        assert st["shed_tenant_quota"] == 1
        assert st["tenants"]["small"]["shed"] == 1
        assert st["tenants"]["small"]["requests"] == 2
        assert st["tenants"]["big"]["requests"] == 1
    finally:
        r.close()
