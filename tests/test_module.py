"""Module tests (modeled on tests/python/unittest/test_module.py +
tests/python/train/test_mlp.py convergence check)."""

import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx


def _make_data(n=400, d=16, k=3, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = np.argmax(X @ rng.randn(d, k), axis=1).astype(np.float32)
    return X, y


def _mlp_sym(k=3):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=k, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_module_states_and_shapes():
    sym = _mlp_sym()
    mod = mx.mod.Module(sym, context=mx.cpu())
    assert not mod.binded
    mod.bind(data_shapes=[("data", (8, 16))], label_shapes=[("softmax_label", (8,))])
    assert mod.binded
    assert mod.data_shapes == [("data", (8, 16))]
    mod.init_params()
    assert mod.params_initialized
    arg_params, aux_params = mod.get_params()
    assert set(arg_params) == {"fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"}


def test_module_fit_convergence():
    X, y = _make_data()
    train = mx.io.NDArrayIter(X, y, batch_size=20, shuffle=True)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9},
            num_epoch=6)
    score = mod.score(mx.io.NDArrayIter(X, y, batch_size=20), "acc")
    assert score[0][1] > 0.9, f"accuracy {score} too low"


def test_module_predict():
    X, y = _make_data(n=64)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=False)
    mod.init_params()
    out = mod.predict(it)
    assert out.shape == (64, 3)
    np.testing.assert_allclose(out.asnumpy().sum(1), np.ones(64), rtol=1e-4)


def test_module_checkpoint_roundtrip():
    X, y = _make_data(n=100)
    train = mx.io.NDArrayIter(X, y, batch_size=10)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            num_epoch=2)
    ref = mod.score(mx.io.NDArrayIter(X, y, batch_size=10), "acc")[0][1]
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "model")
        mod.save_checkpoint(prefix, 2, save_optimizer_states=True)
        assert os.path.exists(prefix + "-symbol.json")
        assert os.path.exists(prefix + "-0002.params")
        mod2 = mx.mod.Module.load(prefix, 2)
        it = mx.io.NDArrayIter(X, y, batch_size=10)
        mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
                  for_training=False)
        got = mod2.score(it, "acc")[0][1]
        assert abs(got - ref) < 1e-6


def test_module_input_grads():
    sym = _mlp_sym()
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 16))], label_shapes=[("softmax_label", (4,))],
             inputs_need_grad=True)
    mod.init_params()
    X, y = _make_data(n=4)
    batch = mx.io.DataBatch(data=[mx.nd.array(X)], label=[mx.nd.array(y)])
    mod.forward(batch, is_train=True)
    mod.backward()
    (din,) = mod.get_input_grads()
    assert din.shape == (4, 16)
    assert np.abs(din.asnumpy()).sum() > 0


def test_module_fixed_params():
    sym = _mlp_sym()
    mod = mx.mod.Module(sym, context=mx.cpu(), fixed_param_names=["fc1_weight"])
    mod.bind(data_shapes=[("data", (4, 16))], label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd", optimizer_params={"learning_rate": 0.5})
    w_before = mod._exec.arg_dict["fc1_weight"].asnumpy().copy()
    X, y = _make_data(n=4)
    batch = mx.io.DataBatch(data=[mx.nd.array(X)], label=[mx.nd.array(y)])
    mod.forward_backward(batch)
    mod.update()
    w_after = mod._exec.arg_dict["fc1_weight"].asnumpy()
    np.testing.assert_array_equal(w_before, w_after)


def test_module_kvstore_local():
    X, y = _make_data()
    train = mx.io.NDArrayIter(X, y, batch_size=20)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, optimizer="sgd", kvstore="local",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9}, num_epoch=4)
    score = mod.score(mx.io.NDArrayIter(X, y, batch_size=20), "acc")
    assert score[0][1] > 0.85


def test_module_bucketing_shared():
    # shared-module rebinding path used by BucketingModule
    sym = _mlp_sym()
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 16))], label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    mod2 = mx.mod.Module(sym, context=mx.cpu())
    mod2.bind(data_shapes=[("data", (4, 16))], label_shapes=[("softmax_label", (4,))],
              shared_module=mod)
    a1, _ = mod.get_params()
    a2, _ = mod2.get_params()
    np.testing.assert_allclose(a1["fc1_weight"].asnumpy(), a2["fc1_weight"].asnumpy())
