"""Module tests (modeled on tests/python/unittest/test_module.py +
tests/python/train/test_mlp.py convergence check)."""

import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx


def _make_data(n=400, d=16, k=3, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = np.argmax(X @ rng.randn(d, k), axis=1).astype(np.float32)
    return X, y


def _mlp_sym(k=3):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=k, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_module_states_and_shapes():
    sym = _mlp_sym()
    mod = mx.mod.Module(sym, context=mx.cpu())
    assert not mod.binded
    mod.bind(data_shapes=[("data", (8, 16))], label_shapes=[("softmax_label", (8,))])
    assert mod.binded
    assert mod.data_shapes == [("data", (8, 16))]
    mod.init_params()
    assert mod.params_initialized
    arg_params, aux_params = mod.get_params()
    assert set(arg_params) == {"fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"}


def test_module_fit_convergence():
    np.random.seed(42)  # NDArrayIter shuffle draws from the global RNG
    X, y = _make_data()
    train = mx.io.NDArrayIter(X, y, batch_size=20, shuffle=True)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9},
            num_epoch=6)
    score = mod.score(mx.io.NDArrayIter(X, y, batch_size=20), "acc")
    assert score[0][1] > 0.9, f"accuracy {score} too low"


def test_module_predict():
    X, y = _make_data(n=64)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=False)
    mod.init_params()
    out = mod.predict(it)
    assert out.shape == (64, 3)
    np.testing.assert_allclose(out.asnumpy().sum(1), np.ones(64), rtol=1e-4)


def test_module_checkpoint_roundtrip():
    X, y = _make_data(n=100)
    train = mx.io.NDArrayIter(X, y, batch_size=10)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            num_epoch=2)
    ref = mod.score(mx.io.NDArrayIter(X, y, batch_size=10), "acc")[0][1]
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "model")
        mod.save_checkpoint(prefix, 2, save_optimizer_states=True)
        assert os.path.exists(prefix + "-symbol.json")
        assert os.path.exists(prefix + "-0002.params")
        mod2 = mx.mod.Module.load(prefix, 2)
        it = mx.io.NDArrayIter(X, y, batch_size=10)
        mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
                  for_training=False)
        got = mod2.score(it, "acc")[0][1]
        assert abs(got - ref) < 1e-6


def test_module_input_grads():
    sym = _mlp_sym()
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 16))], label_shapes=[("softmax_label", (4,))],
             inputs_need_grad=True)
    mod.init_params()
    X, y = _make_data(n=4)
    batch = mx.io.DataBatch(data=[mx.nd.array(X)], label=[mx.nd.array(y)])
    mod.forward(batch, is_train=True)
    mod.backward()
    (din,) = mod.get_input_grads()
    assert din.shape == (4, 16)
    assert np.abs(din.asnumpy()).sum() > 0


def test_module_fixed_params():
    sym = _mlp_sym()
    mod = mx.mod.Module(sym, context=mx.cpu(), fixed_param_names=["fc1_weight"])
    mod.bind(data_shapes=[("data", (4, 16))], label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd", optimizer_params={"learning_rate": 0.5})
    w_before = mod._exec.arg_dict["fc1_weight"].asnumpy().copy()
    X, y = _make_data(n=4)
    batch = mx.io.DataBatch(data=[mx.nd.array(X)], label=[mx.nd.array(y)])
    mod.forward_backward(batch)
    mod.update()
    w_after = mod._exec.arg_dict["fc1_weight"].asnumpy()
    np.testing.assert_array_equal(w_before, w_after)


def test_module_kvstore_local():
    X, y = _make_data()
    train = mx.io.NDArrayIter(X, y, batch_size=20)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, optimizer="sgd", kvstore="local",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9}, num_epoch=4)
    score = mod.score(mx.io.NDArrayIter(X, y, batch_size=20), "acc")
    assert score[0][1] > 0.85


def test_module_bucketing_shared():
    # shared-module rebinding path used by BucketingModule
    sym = _mlp_sym()
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 16))], label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    mod2 = mx.mod.Module(sym, context=mx.cpu())
    mod2.bind(data_shapes=[("data", (4, 16))], label_shapes=[("softmax_label", (4,))],
              shared_module=mod)
    a1, _ = mod.get_params()
    a2, _ = mod2.get_params()
    np.testing.assert_allclose(a1["fc1_weight"].asnumpy(), a2["fc1_weight"].asnumpy())


def test_module_tied_param_buffers_train():
    """Two trainable params sharing one buffer must not break the fused
    (donating) step — regression for 'donate the same buffer twice'."""
    data = mx.sym.Variable("data")
    a = mx.sym.FullyConnected(data, num_hidden=16, no_bias=True, name="enc")
    a = mx.sym.Activation(a, act_type="tanh")
    out = mx.sym.FullyConnected(a, num_hidden=16, no_bias=True, name="dec")
    net = mx.sym.LinearRegressionOutput(out, name="lro")

    rng = np.random.RandomState(3)
    X = rng.randn(64, 16).astype(np.float32)
    it = mx.io.NDArrayIter(X, X[:, :16], batch_size=16, label_name="lro_label")
    mod = mx.mod.Module(net, context=mx.cpu(), label_names=("lro_label",))
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params(mx.initializer.Uniform(0.1))
    # tie: both weights literally share one jax buffer
    w = mod._exec.arg_dict["enc_weight"]
    mod._exec.arg_dict["dec_weight"]._set_data(w._data)
    assert mod._exec.arg_dict["dec_weight"]._data is w._data
    mod.init_optimizer(kvstore=None, optimizer="adam",
                       optimizer_params={"learning_rate": 0.01})
    for b in it:
        mod.forward_backward(b)
        mod.update()
    out = mod.get_outputs()[0].asnumpy()
    assert np.all(np.isfinite(out))


def test_module_copy_initialized_states_train():
    """arg_params built from an array and its .copy() (the RNN-state
    pattern) must produce distinct donated buffers and train."""
    z = mx.nd.zeros((4, 4))
    z2 = z.copy()
    assert z2._data is not z._data

    data = mx.sym.Variable("data")
    a = mx.sym.Variable("a_weight")
    b = mx.sym.Variable("b_weight")
    net = mx.sym.FullyConnected(data, weight=a, num_hidden=4, no_bias=True,
                                name="fa")
    net = mx.sym.FullyConnected(net, weight=b, num_hidden=4, no_bias=True,
                                name="fb")
    net = mx.sym.LinearRegressionOutput(mx.sym.sum(net, axis=1), name="lro")
    rng = np.random.RandomState(0)
    X = rng.randn(32, 4).astype(np.float32)
    it = mx.io.NDArrayIter(X, X.sum(axis=1), batch_size=8, label_name="lro_label")
    mod = mx.mod.Module(net, context=mx.cpu(), label_names=("lro_label",))
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    w = mx.nd.array(rng.randn(4, 4).astype(np.float32) * 0.1)
    mod.init_params(arg_params={"a_weight": w, "b_weight": w.copy()},
                    allow_missing=True)
    mod.init_optimizer(optimizer="sgd", optimizer_params={"learning_rate": 0.01})
    for _ in range(2):
        it.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
    out = mod.get_outputs()[0].asnumpy()
    assert np.all(np.isfinite(out))


def test_module_param_aliased_to_frozen_buffer_train():
    """A trainable param sharing a buffer with a frozen (grad_req null)
    param must not get the shared buffer deleted by donation."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, no_bias=True, name="enc")
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, num_hidden=16, no_bias=True, name="dec")
    net = mx.sym.LinearRegressionOutput(net, name="lro")
    rng = np.random.RandomState(1)
    X = rng.randn(64, 16).astype(np.float32)
    it = mx.io.NDArrayIter(X, X, batch_size=16, label_name="lro_label")
    mod = mx.mod.Module(net, context=mx.cpu(), label_names=("lro_label",),
                        fixed_param_names=["dec_weight"])
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params(mx.initializer.Uniform(0.1))
    # frozen dec_weight shares the trainable enc_weight's buffer
    w = mod._exec.arg_dict["enc_weight"]
    mod._exec.arg_dict["dec_weight"]._set_data(w._data)
    mod.init_optimizer(optimizer="sgd", optimizer_params={"learning_rate": 0.01})
    for _ in range(3):  # >1 step: step 2 re-reads the frozen buffer
        it.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
    out = mod.get_outputs()[0].asnumpy()
    assert np.all(np.isfinite(out))
    # the frozen param's buffer must still be alive and unchanged shape
    assert mod._exec.arg_dict["dec_weight"].asnumpy().shape == (16, 16)


def test_resnet_s2d_stem_equivalence():
    """The space-to-depth stem with an embedded 7x7 weight computes the
    identical function to the reference conv7 stem (models/resnet.py
    _s2d_stem / conv7_to_s2d_weight)."""
    import importlib
    R = importlib.import_module("mxnet_tpu.models.resnet")

    rng = np.random.RandomState(0)
    batch, hw = 2, 64  # >32 so the imagenet stem is selected
    X = rng.randn(batch, 3, hw, hw).astype(np.float32)
    outs = {}
    for stem in ("conv7", "s2d"):
        sym = R.get_symbol(num_classes=10, num_layers=50,
                           image_shape=(3, hw, hw), stem=stem)
        mod = mx.mod.Module(sym, context=mx.cpu())
        mod.bind(data_shapes=[mx.io.DataDesc("data", (batch, 3, hw, hw))],
                 label_shapes=[mx.io.DataDesc("softmax_label", (batch,))],
                 for_training=False)
        mod.init_params(mx.initializer.Xavier(rnd_type="gaussian"))
        if stem == "conv7":
            arg_params, aux_params = mod.get_params()
            saved = ({k: v.asnumpy() for k, v in arg_params.items()},
                     {k: v.asnumpy() for k, v in aux_params.items()})
        else:
            args, auxs = saved
            args = dict(args)
            args["conv0_weight"] = R.conv7_to_s2d_weight(
                args["conv0_weight"])
            mod.set_params({k: mx.nd.array(v) for k, v in args.items()},
                           {k: mx.nd.array(v) for k, v in auxs.items()})
        mod.forward(mx.io.DataBatch(
            [mx.nd.array(X)], [mx.nd.array(np.zeros(batch, np.float32))]),
            is_train=False)
        outs[stem] = mod.get_outputs()[0].asnumpy()
    np.testing.assert_allclose(outs["s2d"], outs["conv7"],
                               rtol=1e-4, atol=1e-5)
