"""Worker for the 2-process telemetry proof (test_dist.py::
test_telemetry_traces_and_watchdog):

* both ranks run dist_sync kvstore traffic with the profiler on and
  dump a per-rank Chrome trace into the shared dir (argv[1]) for
  ``tools/trace_merge.py``;
* rank 1 deliberately sleeps past MXNET_WATCHDOG_DEADLINE before the
  barrier, so rank 0's straggler watchdog must NAME rank 1 in its log
  while the barrier is still open.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx

SHAPE = (4, 5)


def main():
    trace_dir = sys.argv[1]
    mx.profiler.profiler_set_config(mode="all", filename="")
    mx.profiler.profiler_set_state("run")

    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers

    kv.init(3, mx.nd.zeros(SHAPE))
    kv.push(3, mx.nd.ones(SHAPE) * (rank + 1))
    out = mx.nd.zeros(SHAPE)
    kv.pull(3, out=out)
    expected = sum(r + 1 for r in range(nw))
    np.testing.assert_allclose(out.asnumpy(), np.full(SHAPE, float(expected)))

    # the deliberate straggler: rank 1 arrives late at the barrier, so
    # rank 0's watchdog (deadline < this sleep) fires and names it
    if rank == 1:
        time.sleep(float(os.environ.get("STRAGGLER_SLEEP_S", "3")))
    kv.barrier()

    path = mx.profiler.dump_rank_trace(trace_dir)
    assert os.path.isfile(path), path
    print(f"worker {rank}/{nw}: telemetry OK", flush=True)


if __name__ == "__main__":
    main()
