"""Custom op tests (reference: tests/python/unittest/test_operator.py
test_custom_op — sigmoid forward/backward through the Custom op)."""

import numpy as np

import mxnet_tpu as mx
import mxnet_tpu.operator as mxop


@mxop.register("test_sigmoid")
class SigmoidProp(mxop.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, in_shapes, in_dtypes=None):
        return Sigmoid()


class Sigmoid(mxop.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        y = 1.0 / (1.0 + np.exp(-x))
        self.assign(out_data[0], req[0], mx.nd.array(y))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0].asnumpy()
        gy = out_grad[0].asnumpy()
        self.assign(in_grad[0], req[0], mx.nd.array(gy * y * (1.0 - y)))


def test_custom_imperative_forward():
    x = np.random.RandomState(0).randn(4, 5).astype(np.float32)
    out = mx.nd.Custom(mx.nd.array(x), op_type="test_sigmoid")
    np.testing.assert_allclose(out.asnumpy(), 1 / (1 + np.exp(-x)),
                               rtol=1e-5)


def test_custom_symbol_forward_backward():
    x = np.random.RandomState(1).randn(3, 4).astype(np.float32)
    data = mx.sym.Variable("data")
    sym = mx.sym.Custom(data, op_type="test_sigmoid", name="sig")
    # shape inference through the prop
    arg_shapes, out_shapes, _ = sym.infer_shape(data=(3, 4))
    assert out_shapes == [(3, 4)]
    exe = sym.simple_bind(mx.cpu(), grad_req="write", data=(3, 4))
    exe.arg_dict["data"][:] = x
    out = exe.forward(is_train=True)[0].asnumpy()
    y = 1 / (1 + np.exp(-x))
    np.testing.assert_allclose(out, y, rtol=1e-5)
    head = np.ones_like(x)
    exe.backward(out_grads=[mx.nd.array(head)])
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(),
                               y * (1 - y), rtol=1e-4)


def test_custom_in_module_training():
    """Custom op inside a trained graph: gradients flow through it."""
    # the default Uniform initializer draws from the GLOBAL numpy
    # stream; pin it so the outcome doesn't depend on suite order
    np.random.seed(2)
    mx.random.seed(2)
    rng = np.random.RandomState(2)
    X = rng.randn(80, 6).astype(np.float32)
    yv = (X.sum(axis=1) > 0).astype(np.float32)
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Custom(net, op_type="test_sigmoid", name="act")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    it = mx.io.NDArrayIter(X, yv, batch_size=20)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=10, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5})
    acc = mod.score(mx.io.NDArrayIter(X, yv, batch_size=20), "acc")[0][1]
    assert acc > 0.85, acc


@mxop.register("test_scale2")
class Scale2Prop(mxop.CustomOpProp):
    """Two inputs, one output, an aux counter state."""

    def __init__(self, factor="2.0"):
        super().__init__(need_top_grad=True)
        self.factor = float(factor)

    def list_arguments(self):
        return ["a", "b"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return ["count"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], [[1]]

    def create_operator(self, ctx, in_shapes, in_dtypes=None):
        factor = self.factor

        class Scale2(mxop.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                self.assign(out_data[0], req[0],
                            (in_data[0] + in_data[1]) * factor)
                aux[0][:] = aux[0] + 1.0  # mutation round-trips

            def backward(self, req, out_grad, in_data, out_data,
                         in_grad, aux):
                self.assign(in_grad[0], req[0], out_grad[0] * factor)
                self.assign(in_grad[1], req[1], out_grad[0] * factor)

        return Scale2()


def test_custom_multi_input_attrs_and_aux():
    a = np.full((2, 3), 1.0, np.float32)
    b = np.full((2, 3), 2.0, np.float32)
    sym = mx.sym.Custom(mx.sym.Variable("a"), mx.sym.Variable("b"),
                        op_type="test_scale2", factor="3.0", name="s2")
    exe = sym.simple_bind(mx.cpu(), grad_req="write", a=(2, 3), b=(2, 3))
    exe.arg_dict["a"][:] = a
    exe.arg_dict["b"][:] = b
    out = exe.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(out, (a + b) * 3.0)
    # aux mutated by the host op is visible after the run
    assert float(exe.aux_dict["s2_count"].asnumpy()[0]) >= 1.0
    exe.forward(is_train=True)
    exe.backward(out_grads=[mx.nd.ones((2, 3))])
    np.testing.assert_allclose(exe.grad_dict["a"].asnumpy(),
                               np.full((2, 3), 3.0))


def test_custom_imperative_accepts_name():
    x = np.ones((2, 2), np.float32)
    out = mx.nd.Custom(mx.nd.array(x), op_type="test_sigmoid", name="act")
    np.testing.assert_allclose(out.asnumpy(), 1 / (1 + np.exp(-x)))


@mxop.register("test_aux_bwd")
class AuxBwdProp(mxop.CustomOpProp):
    """Backward reads aux state that forward wrote."""

    def __init__(self):
        super().__init__(need_top_grad=True)

    def list_auxiliary_states(self):
        return ["state"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], [[1]]

    def create_operator(self, ctx, in_shapes, in_dtypes=None):
        class AuxBwd(mxop.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                self.assign(out_data[0], req[0], in_data[0])
                aux[0][:] = mx.nd.array(np.array([7.0], np.float32))

            def backward(self, req, out_grad, in_data, out_data,
                         in_grad, aux):
                scale = float(aux[0].asnumpy()[0])
                self.assign(in_grad[0], req[0], out_grad[0] * scale)

        return AuxBwd()


def test_custom_backward_sees_forward_aux():
    sym = mx.sym.Custom(mx.sym.Variable("data"), op_type="test_aux_bwd",
                        name="ab")
    exe = sym.simple_bind(mx.cpu(), grad_req="write", data=(2, 2))
    exe.arg_dict["data"][:] = np.ones((2, 2), np.float32)
    exe.forward(is_train=True)
    exe.backward(out_grads=[mx.nd.ones((2, 2))])
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(),
                               np.full((2, 2), 7.0))


@mxop.register("test_custom_loss")
class CustomLossProp(mxop.CustomOpProp):
    """need_top_grad=False: the op is a loss head producing its own grad."""

    def __init__(self):
        super().__init__(need_top_grad=False)

    def create_operator(self, ctx, in_shapes, in_dtypes=None):
        class L(mxop.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                self.assign(out_data[0], req[0], in_data[0])

            def backward(self, req, out_grad, in_data, out_data,
                         in_grad, aux):
                # d/dx of 0.5*x^2 — ignores out_grad like reference
                # loss-style custom ops
                self.assign(in_grad[0], req[0], in_data[0])

        return L()


def test_custom_loss_head_backward_without_out_grads():
    """The reference custom-loss workflow: backward() with no out_grads."""
    sym = mx.sym.Custom(mx.sym.Variable("data"),
                        op_type="test_custom_loss", name="loss")
    exe = sym.simple_bind(mx.cpu(), grad_req="write", data=(2, 3))
    x = np.random.RandomState(0).randn(2, 3).astype(np.float32)
    exe.arg_dict["data"][:] = x
    exe.forward(is_train=True)
    exe.backward()  # no out_grads: op is recognized as a loss head
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(), x,
                               rtol=1e-5)
