import numpy as np
import jax, jax.numpy as jnp
import pytest
import mxnet_tpu as mx
from mxnet_tpu.ops import attention as att, pallas_kernels as pk

@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(2, 384, 3, 64), (1, 256, 2, 128)])
def test_flash_mha_parity(monkeypatch, causal, shape):
    monkeypatch.setenv("MXNET_PALLAS", "1")
    assert pk.enabled()
    B, T, H, D = shape
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B,T,H,D).astype(np.float32))
    k = jnp.asarray(rng.randn(B,T,H,D).astype(np.float32))
    v = jnp.asarray(rng.randn(B,T,H,D).astype(np.float32))
    def f_kern(q,k,v): return att.blockwise_attention(q,k,v,causal=causal,block_size=256)
    def f_lax(q,k,v):
        o,m,l = att._blockwise_attention_partial_lax(q,k,v,causal,256,0)
        return att.normalize_attention_state(o,m,l,q.dtype)
    ok, ol = f_kern(q,k,v), f_lax(q,k,v)
    assert float(jnp.abs(ok-ol).max()) < 1e-5
    gk = jax.grad(lambda q,k,v: jnp.sum(jnp.sin(f_kern(q,k,v))), argnums=(0,1,2))(q,k,v)
    gl = jax.grad(lambda q,k,v: jnp.sum(jnp.sin(f_lax(q,k,v))), argnums=(0,1,2))(q,k,v)
    for a, b in zip(gk, gl):
        assert float(jnp.abs(a-b).max()) < 1e-5

@pytest.mark.parametrize("causal", [False, True])
def test_packed_qkv_parity(monkeypatch, causal):
    monkeypatch.setenv("MXNET_PALLAS", "1")
    from mxnet_tpu.ops import pallas_kernels as pk2
    B, T, H, D = 2, 384, 3, 64
    rng = np.random.RandomState(1)
    qkv = jnp.asarray(rng.randn(B, T, 3*H*D).astype(np.float32))
    def f_kern(qkv):
        return pk2.flash_mha_packed(qkv, H, causal=causal, block_size=256)
    def f_lax(qkv):
        q, k, v = (jnp.reshape(x, (B, T, H, D)) for x in jnp.split(qkv, 3, -1))
        o, m, l = att._blockwise_attention_partial_lax(q, k, v, causal, 256, 0)
        return jnp.reshape(att.normalize_attention_state(o, m, l, qkv.dtype), (B, T, H*D))
    ok, ol = f_kern(qkv), f_lax(qkv)
    assert float(jnp.abs(ok - ol).max()) < 1e-5
    gk = jax.grad(lambda x: jnp.sum(jnp.sin(f_kern(x))))(qkv)
    gl = jax.grad(lambda x: jnp.sum(jnp.sin(f_lax(x))))(qkv)
    assert float(jnp.abs(gk - gl).max()) < 1e-5


def test_softmax_ce_loss_head():
    """SoftmaxCELoss: forward loss parity with SoftmaxOutput-derived CE
    and the (p - onehot) backward, without materializing probs."""
    rng = np.random.RandomState(3)
    B, T, V = 2, 8, 32
    logits = rng.randn(B, T, V).astype(np.float32)
    label = rng.randint(0, V, size=(B, T)).astype(np.float32)
    sym = mx.sym.SoftmaxCELoss(mx.sym.Variable("data"),
                               mx.sym.Variable("label"))
    ld = mx.nd.array(logits)
    gd = mx.nd.zeros(logits.shape)
    ex = sym.bind(mx.cpu(), {"data": ld, "label": mx.nd.array(label)},
                  args_grad={"data": gd})
    out = ex.forward(is_train=True)[0].asnumpy()
    # reference CE
    x = logits - logits.max(-1, keepdims=True)
    lse = np.log(np.exp(x).sum(-1)) + logits.max(-1)
    ll = np.take_along_axis(logits, label[..., None].astype(int), -1)[..., 0]
    np.testing.assert_allclose(out, lse - ll, rtol=1e-5, atol=1e-5)
    ex.backward(out_grads=[mx.nd.ones(out.shape)])
    p = np.exp(logits - lse[..., None])
    onehot = np.eye(V)[label.astype(int)]
    np.testing.assert_allclose(gd.asnumpy(), p - onehot, rtol=1e-4,
                               atol=1e-5)


def test_transformer_ce_head_trains():
    import mxnet_tpu.models as models
    sym = models.transformer_lm(vocab_size=64, seq_len=16, num_layers=1,
                                num_heads=2, d_model=32, head="ce")
    rng = np.random.RandomState(0)
    X = rng.randint(1, 64, size=(4, 16)).astype(np.float32)
    Y = rng.randint(1, 64, size=(4, 16)).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=4, label_name="softmax_label")

    class MeanLoss(mx.metric.EvalMetric):
        def __init__(self):
            super().__init__("mean_loss")

        def update(self, labels, preds):
            self.sum_metric += float(preds[0].asnumpy().mean())
            self.num_inst += 1

    mod = mx.mod.Module(sym, context=mx.cpu())
    mx.random.seed(0)
    losses = []
    mod.fit(it, num_epoch=30, optimizer="adam",
            optimizer_params={"learning_rate": 1e-2},
            initializer=mx.initializer.Xavier(), eval_metric=MeanLoss(),
            batch_end_callback=lambda p: losses.append(
                p.eval_metric.get()[1]))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_softmax_ce_loss_ignore_label():
    rng = np.random.RandomState(4)
    logits = rng.randn(2, 6, 16).astype(np.float32)
    label = rng.randint(1, 16, size=(2, 6)).astype(np.float32)
    label[0, 2] = 0  # padding
    sym = mx.sym.SoftmaxCELoss(mx.sym.Variable("data"),
                               mx.sym.Variable("label"),
                               use_ignore=True, ignore_label=0)
    ld, gd = mx.nd.array(logits), mx.nd.zeros(logits.shape)
    ex = sym.bind(mx.cpu(), {"data": ld, "label": mx.nd.array(label)},
                  args_grad={"data": gd})
    out = ex.forward(is_train=True)[0].asnumpy()
    assert out[0, 2] == 0.0 and out[0, 3] > 0.0
    ex.backward(out_grads=[mx.nd.ones(out.shape)])
    g = gd.asnumpy()
    np.testing.assert_allclose(g[0, 2], 0.0, atol=1e-8)
    assert np.abs(g[0, 3]).max() > 0


def test_qkv_packing_validation():
    """_qkv_infer rejects a last dim that is a multiple of 3 but not of
    3*num_heads (the weaker % 3 check waved these through), and a
    zero-width qkv; the message names the expected packing."""
    sym = mx.sym.QKVSelfAttention(mx.sym.Variable("qkv"), num_heads=4)
    with pytest.raises(mx.base.MXNetError, match=r"3\*num_heads\*d_head"):
        sym.infer_shape(qkv=(2, 8, 6))  # 6 % 3 == 0 but 6 % 12 != 0
    with pytest.raises(mx.base.MXNetError, match="positive multiple"):
        sym.infer_shape(qkv=(2, 8, 0))  # d_head = 0
    _, out, _ = sym.infer_shape(qkv=(2, 8, 24))
    assert tuple(out[0]) == (2, 8, 8)
