"""InferenceEngine tests: bucket-cache behavior, padding correctness,
flush policy, concurrent-client correctness, export serving.

All CPU-fast (small MLP): the smoke path the tier-1 gate runs."""

import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import profiler


def _mlp_predictor(batch=1, with_label=False, seed=0):
    """Tiny MLP Predictor (logits head — no label input unless asked)."""
    net = mx.sym.FullyConnected(
        mx.sym.Activation(
            mx.sym.FullyConnected(mx.sym.Variable("data"),
                                  num_hidden=16, name="fc1"),
            act_type="relu"),
        num_hidden=4, name="fc2")
    if with_label:
        net = mx.sym.SoftmaxOutput(net, name="softmax")
    shapes = {"data": (batch, 6)}
    if with_label:
        shapes["softmax_label"] = (batch,)
    mod = mx.mod.Module(net, context=mx.cpu(),
                        label_names=["softmax_label"] if with_label else [])
    mod.bind(data_shapes=[("data", (2, 6))],
             label_shapes=[("softmax_label", (2,))] if with_label else None,
             for_training=False)
    mod.init_params(mx.initializer.Xavier(rnd_type="gaussian"))
    arg, aux = mod.get_params()
    return mx.Predictor(net, {**arg, **aux}, shapes), net, (arg, aux)


def _per_request_ref(pred_b1, X, label=None):
    """Reference outputs: each sample alone through a batch-1 forward."""
    outs = []
    for i in range(len(X)):
        kwargs = {"data": X[i:i + 1]}
        if label is not None:
            kwargs["softmax_label"] = label[i:i + 1]
        pred_b1.forward(**kwargs)
        outs.append(pred_b1.get_output(0))
    return np.concatenate(outs, axis=0)


def test_bucket_cache_compiles_each_bucket_at_most_once():
    pred, _, _ = _mlp_predictor()
    rng = np.random.RandomState(1)
    with mx.InferenceEngine(pred, buckets=(1, 4, 8),
                            batch_timeout_ms=1.0) as eng:
        # hammer two bucket sizes repeatedly
        for _ in range(6):
            eng.infer(rng.randn(1, 6).astype(np.float32))
        for _ in range(6):
            eng.infer(rng.randn(3, 6).astype(np.float32))  # pads to 4
        st = eng.stats()
    assert st["compiles"] == {1: 1, 4: 1}, st["compiles"]
    assert st["cache_hits"] >= 10
    assert st["cache_misses"] == 2


def test_prewarm_compiles_everything_up_front():
    pred, _, _ = _mlp_predictor()
    eng = mx.InferenceEngine(pred, buckets=(1, 4), prewarm=True)
    try:
        assert eng.stats()["compiles"] == {1: 1, 4: 1}
        eng.infer(np.zeros((1, 6), np.float32))
        assert eng.stats()["compiles"] == {1: 1, 4: 1}  # no recompiles
    finally:
        eng.close()


def test_padding_rows_do_not_leak_into_real_outputs():
    """A 3-sample request pads to bucket 4; the real rows must be
    bit-identical no matter WHAT the pad lane holds — proven by running
    the engine's own bucket executable with zero pad vs garbage pad.
    (Bit-exactness across *different* executables — batch-4 vs batch-1
    programs — is not an XLA guarantee; row independence within one
    executable is what padding correctness requires.)"""
    from mxnet_tpu.io import stage_array

    pred, _, _ = _mlp_predictor()
    rng = np.random.RandomState(2)
    X = rng.randn(3, 6).astype(np.float32)
    with mx.InferenceEngine(pred, buckets=(4,), batch_timeout_ms=1.0) as eng:
        (out,) = eng.infer(X)
        assert out.shape == (3, 4)
        exe = eng._cache[4]
        dev = eng._model.device
        zero_pad = np.zeros((4, 6), np.float32)
        zero_pad[:3] = X
        junk_pad = np.full((4, 6), 1e6, np.float32)
        junk_pad[:3] = X
        a = np.asarray(exe({"data": stage_array(zero_pad, dev)})[0])
        b = np.asarray(exe({"data": stage_array(junk_pad, dev)})[0])
    np.testing.assert_array_equal(out, a[:3])  # engine == its executable
    np.testing.assert_array_equal(a[:3], b[:3])  # pad content can't leak
    # numerical sanity vs the per-request batch-1 program
    np.testing.assert_allclose(out, _per_request_ref(pred, X),
                               rtol=2e-6, atol=2e-6)


def test_same_bucket_resubmission_is_deterministic():
    """The cached executable is pure: the same request twice through the
    same bucket returns bit-identical results."""
    pred, _, _ = _mlp_predictor()
    X = np.random.RandomState(6).randn(3, 6).astype(np.float32)
    with mx.InferenceEngine(pred, buckets=(4,), batch_timeout_ms=1.0) as eng:
        (a,) = eng.infer(X)
        (b,) = eng.infer(X)
    np.testing.assert_array_equal(a, b)


def test_full_batch_flush_vs_timeout_flush():
    pred, _, _ = _mlp_predictor()
    X = np.zeros((1, 6), np.float32)
    # long timeout: 4 rapid singles coalesce into ONE full-batch flush —
    # the deadline never fires because the batch fills first
    with mx.InferenceEngine(pred, buckets=(4,), max_batch=4,
                            batch_timeout_ms=10_000,
                            idle_timeout_ms=10_000, prewarm=True) as eng:
        futs = [eng.submit(X) for _ in range(4)]
        for f in futs:
            f.result(timeout=30)
        st = eng.stats()
        assert st["flush_full"] == 1 and st["flush_timeout"] == 0, st
        assert st["batches"] == 1
    # short timeout: a lone request leaves on the deadline path
    with mx.InferenceEngine(pred, buckets=(4,), max_batch=4,
                            batch_timeout_ms=20, idle_timeout_ms=20,
                            prewarm=True) as eng:
        t0 = time.perf_counter()
        eng.infer(X)
        waited = time.perf_counter() - t0
        st = eng.stats()
    assert st["flush_timeout"] == 1 and st["flush_full"] == 0, st
    assert waited >= 0.02  # it did hold the deadline open


def test_short_timeout_flushes_partial_batch():
    pred, _, _ = _mlp_predictor()
    with mx.InferenceEngine(pred, buckets=(8,), batch_timeout_ms=5,
                            prewarm=True) as eng:
        (out,) = eng.infer(np.zeros((2, 6), np.float32))
        assert out.shape == (2, 4)
        st = eng.stats()
    assert st["flush_timeout"] == 1
    assert st["batch_fill_ratio"] == pytest.approx(2 / 8)


def test_concurrent_clients_bit_exact():
    """N client threads × M single-sample requests: every result equals
    the per-request batch-1 forward bit-exactly, regardless of how the
    batcher coalesced/padded them."""
    pred, _, _ = _mlp_predictor()
    rng = np.random.RandomState(3)
    N, M = 8, 12
    X = rng.randn(N, M, 6).astype(np.float32)
    results = {}
    with mx.InferenceEngine(pred, buckets=(1, 4, 8, 16),
                            batch_timeout_ms=2.0,
                            idle_timeout_ms=2.0) as eng:
        def client(c):
            outs = []
            for i in range(M):
                outs.append(eng.infer(X[c, i:i + 1])[0])
            results[c] = np.concatenate(outs, axis=0)

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(N)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        st = eng.stats()
    assert st["images"] == N * M
    for c in range(N):
        ref = _per_request_ref(pred, X[c])
        # tight allclose, not array_equal: a request may land in any
        # bucket and XLA's batch-1 vs batch-8 programs round differently
        # in the last ulp (see the padding test for the bit-exact
        # same-executable guarantee)
        np.testing.assert_allclose(results[c], ref, rtol=2e-6, atol=2e-6)
    # dynamic batching actually batched: fewer dispatches than requests
    assert st["batches"] < N * M
    # each bucket compiled at most once, whatever mix of sizes ran
    assert all(v == 1 for v in st["compiles"].values()), st["compiles"]


def test_multi_input_requests_and_label_input():
    pred, _, _ = _mlp_predictor(with_label=True)
    rng = np.random.RandomState(4)
    X = rng.randn(2, 6).astype(np.float32)
    lab = np.zeros((2,), np.float32)
    with mx.InferenceEngine(pred, buckets=(4,), batch_timeout_ms=1.0) as eng:
        (out,) = eng.infer({"data": X, "softmax_label": lab})
    assert out.shape == (2, 4)
    ref = _per_request_ref(pred, X, label=lab)
    np.testing.assert_allclose(out, ref, rtol=2e-6, atol=2e-6)


def test_submit_validation_errors():
    pred, _, _ = _mlp_predictor()
    eng = mx.InferenceEngine(pred, buckets=(1, 4))
    try:
        with pytest.raises(mx.MXNetError, match="shape"):
            eng.submit(np.zeros((1, 7), np.float32))
        with pytest.raises(mx.MXNetError, match="max_batch"):
            eng.submit(np.zeros((5, 6), np.float32))
        with pytest.raises(mx.MXNetError, match="empty"):
            eng.submit(np.zeros((0, 6), np.float32))
        with pytest.raises(mx.MXNetError, match="bucket"):
            mx.InferenceEngine(pred, buckets=(4,), max_batch=8)
    finally:
        eng.close()
    with pytest.raises(mx.MXNetError, match="closed"):
        eng.submit(np.zeros((1, 6), np.float32))


def test_bare_sample_auto_batches():
    pred, _, _ = _mlp_predictor()
    with mx.InferenceEngine(pred, buckets=(1,),
                            batch_timeout_ms=1.0) as eng:
        (out,) = eng.infer(np.zeros((6,), np.float32))  # per-sample shape
    assert out.shape == (1, 4)


def test_serving_exported_artifact(tmp_path):
    """from_exported: single frozen bucket, everything pads to it."""
    pred, net, (arg, aux) = _mlp_predictor()
    path = str(tmp_path / "m.mxtpu")
    mx.predictor.export_model(net, arg, aux, {"data": (4, 6)}, path=path)
    rng = np.random.RandomState(5)
    X = rng.randn(2, 6).astype(np.float32)
    with mx.InferenceEngine.from_exported(path,
                                          batch_timeout_ms=1.0) as eng:
        assert eng.stats()["buckets"] == [4]
        (out,) = eng.infer(X)
    assert out.shape == (2, 4)
    ref = _per_request_ref(pred, X)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_metrics_surface_through_profiler():
    profiler.reset_metrics()
    pred, _, _ = _mlp_predictor()
    with mx.InferenceEngine(pred, buckets=(1,),
                            batch_timeout_ms=1.0) as eng:
        eng.infer(np.zeros((1, 6), np.float32))
    summ = profiler.metrics_summary()
    assert summ["counters"]["serving.requests"] >= 1
    assert summ["counters"]["serving.images"] >= 1
    lat = summ["histograms"]["serving.latency_ms"]
    assert lat["count"] >= 1 and lat["p99"] >= lat["p50"] > 0
    fill = summ["histograms"]["serving.batch_fill"]
    assert 0 < fill["mean"] <= 1


def test_batch_reducing_output_fails_loudly():
    """An output that reduces over the batch axis can't be sliced back
    per-request — the engine must fail the futures, not hand one client
    a value computed over another client's rows."""
    net = mx.sym.sum(mx.sym.FullyConnected(mx.sym.Variable("data"),
                                           num_hidden=4, name="fc"))
    mod = mx.mod.Module(net, context=mx.cpu(), label_names=[])
    mod.bind(data_shapes=[("data", (2, 6))], for_training=False)
    mod.init_params(mx.initializer.Xavier())
    arg, aux = mod.get_params()
    pred = mx.Predictor(net, {**arg, **aux}, {"data": (1, 6)})
    with mx.InferenceEngine(pred, buckets=(4,), batch_timeout_ms=1.0) as eng:
        fut = eng.submit(np.ones((1, 6), np.float32))
        with pytest.raises(mx.MXNetError, match="batch axis"):
            fut.result(timeout=30)


def test_boundary_flush_cost_model():
    """The learned per-bucket cost model: grow across a bucket boundary
    only when the measured rate of the bigger bucket wins; always grow
    (explore) when the bigger bucket has never been measured."""
    pred, _, _ = _mlp_predictor()
    eng = mx.InferenceEngine(pred, buckets=(8, 32))
    try:
        # CPU-like scaling: b32 costs ~4x b8 — a 9th sample with an
        # empty backlog projects 9/190 img/ms < 8/50: flush at 8
        eng._bucket_ms = {8: 50.0, 32: 190.0}
        assert eng._boundary_flush(8, 1)
        # TPU-like flat cost: the bigger bucket is nearly free — grow
        eng._bucket_ms = {8: 50.0, 32: 55.0}
        assert not eng._boundary_flush(8, 1)
        # bigger bucket never measured: explore (also compiles it)
        eng._bucket_ms = {8: 50.0}
        assert not eng._boundary_flush(8, 1)
        # not at a boundary: adding stays inside the current bucket
        eng._bucket_ms = {8: 50.0, 32: 190.0}
        assert not eng._boundary_flush(4, 1)
    finally:
        eng.close()


def test_boundary_flush_reason_counted():
    """End-to-end: with a poisoned cost model making the big bucket look
    terrible, coalescing two requests across the boundary flushes the
    first at its bucket and counts a 'boundary' flush."""
    pred, _, _ = _mlp_predictor()
    with mx.InferenceEngine(pred, buckets=(2, 32), max_batch=32,
                            batch_timeout_ms=10_000,
                            idle_timeout_ms=500,
                            prewarm=True) as eng:
        eng._bucket_ms = {2: 1.0, 32: 1e6}  # never worth growing
        f1 = eng.submit(np.zeros((2, 6), np.float32))   # fills bucket 2
        f2 = eng.submit(np.zeros((1, 6), np.float32))   # would cross
        f1.result(timeout=30)  # flushed at the boundary, not the 10s deadline
        st = eng.stats()
        assert st["flush_boundary"] >= 1, st
        f2.result(timeout=30)  # the carried request still gets served


# ---------------------------------------------------------------------------
# fleet hooks: inflight snapshot, drain/resume, live weight swap
# ---------------------------------------------------------------------------


def test_inflight_snapshot_drain_and_resume():
    pred, _, _ = _mlp_predictor()
    eng = mx.InferenceEngine(pred, buckets=(1, 8), batch_timeout_ms=250.0,
                             idle_timeout_ms=250.0)
    try:
        assert eng.inflight() == 0
        futs = [eng.submit(np.zeros((1, 6), np.float32))
                for _ in range(3)]
        # the 250 ms coalesce window holds them: all still owned
        assert eng.inflight() == 3
        left = eng.drain(timeout=30.0)
        assert left == 0 and eng.inflight() == 0
        for f in futs:
            assert f.result(1)[0].shape == (1, 4)  # drained = SERVED
        with pytest.raises(mx.MXNetError, match="draining"):
            eng.submit(np.zeros((1, 6), np.float32))
        eng.resume()
        out = eng.infer(np.zeros((1, 6), np.float32))
        assert out[0].shape == (1, 4)
    finally:
        eng.close()


def test_loop_death_poisoned_count_matches_inflight():
    """The drain-path contract the fleet router reads: what inflight()
    reported before the engine died is exactly how many futures get
    poisoned, and the snapshot empties once they are failed."""
    pred, _, _ = _mlp_predictor()
    eng = mx.InferenceEngine(pred, buckets=(8,), batch_timeout_ms=500.0,
                             idle_timeout_ms=500.0)
    try:
        futs = [eng.submit(np.zeros((1, 6), np.float32))
                for _ in range(3)]
        n_before = eng.inflight()
        assert n_before == 3
        # sabotage the coalescing loop (the test_decode poison recipe)
        eng._timeout_s = eng._idle_timeout_s = None
        fut4 = eng.submit(np.zeros((1, 6), np.float32))
        poisoned = 0
        for f in futs + [fut4]:
            with pytest.raises(mx.EngineClosedError, match="died"):
                f.result(timeout=30)
            poisoned += 1
        assert poisoned == n_before + 1
        assert eng.inflight() == 0
    finally:
        eng._queue.put(None)
        eng.close(timeout=5)


def test_swap_params_guard_and_new_weights_served():
    pred, net, (arg, aux) = _mlp_predictor()
    eng = mx.InferenceEngine(pred, buckets=(1, 4), batch_timeout_ms=250.0,
                             idle_timeout_ms=250.0)
    try:
        rng = np.random.RandomState(5)
        x = rng.rand(1, 6).astype(np.float32)
        base = eng.infer({"data": x})[0]
        new_params = {k: np.asarray(v.asnumpy()
                                    if hasattr(v, "asnumpy") else v) * 2.0
                      for k, v in {**arg, **aux}.items()}
        # guard: swapping with a request in flight refuses
        fut = eng.submit({"data": x})  # sits in the 250 ms window
        with pytest.raises(mx.MXNetError, match="in flight"):
            eng.swap_params(new_params)
        assert eng.drain(timeout=30.0) == 0
        fut.result(1)
        eng.swap_params(new_params)
        eng.warmup()
        eng.resume()
        out = eng.infer({"data": x})[0]
        assert not np.allclose(out, base), "old weights still served"
        ref = mx.Predictor(net, new_params, {"data": (1, 6)})
        ref.forward(data=x)
        np.testing.assert_allclose(out, ref.get_output(0), rtol=1e-5)
    finally:
        eng.close()
