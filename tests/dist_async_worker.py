"""Worker for the dist_async update-on-arrival proof.

Launched by ``tools/launch.py -n 2 --cpu python
tests/dist_async_worker.py``.  Workers push at DIFFERENT rates with no
barrier between pushes (reference semantics:
``kvstore_dist_server.h:199-207`` — the server applies each push the
moment it arrives; pulls return whatever the weights currently are).
The final weight must reflect every push exactly once:
w = -lr * total_pushes for SGD on all-ones gradients.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx

SHAPE = (4, 4)
LR = 0.5


def main():
    kv = mx.kv.create("dist_async")
    rank, nw = kv.rank, kv.num_workers
    pushes = 5 * (rank + 1)  # deliberately unequal
    total = sum(5 * (r + 1) for r in range(nw))

    kv.init("w", mx.nd.zeros(SHAPE))
    if rank == 0:
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=LR, rescale_grad=1.0,
                                          wd=0.0, momentum=0.0))
    kv.barrier()  # optimizer installed before anyone pushes

    seen = []
    for i in range(pushes):
        kv.push("w", mx.nd.ones(SHAPE))
        # interleaved pulls must return CURRENT (possibly mid-flight)
        # weights without any rendezvous with the other worker
        out = mx.nd.zeros(SHAPE)
        kv.pull("w", out=out)
        v = out.asnumpy()
        assert np.isfinite(v).all()
        assert np.allclose(v, v.flat[0]), "server state must be uniform"
        seen.append(float(v.flat[0]))
        time.sleep(0.01 * (rank + 1))  # different worker cadences

    # pulls observed monotonically decreasing weights (each applied
    # push subtracts lr) — evidence updates landed on arrival, not at
    # a barrier at the end
    assert all(b <= a + 1e-6 for a, b in zip(seen, seen[1:])), seen
    # this worker's own pushes must each have been applied by now: after
    # our i-th push the weight is at most -lr*(i+1) (other worker only
    # subtracts more)
    assert seen[-1] <= -LR * pushes + 1e-5, seen

    kv.barrier()  # end-of-test rendezvous only
    out = mx.nd.zeros(SHAPE)
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full(SHAPE, -LR * total),
                               rtol=1e-6)
    applied = kv._ps.num_applied("w")
    assert applied == total, f"server applied {applied} != {total} pushes"

    # --- big-array path: split flat across ALL server shards ----------
    # (reference: kvstore_dist.h:286-296 partition + the nightly
    # dist_sync_kvstore.py big_shape check).  The launching test sets
    # MXNET_KVSTORE_BIGARRAY_BOUND small so BIG_SHAPE splits.
    BIG_SHAPE = (120, 120)
    big = np.arange(np.prod(BIG_SHAPE), dtype=np.float32).reshape(BIG_SHAPE)
    if int(os.environ.get("MXNET_KVSTORE_BIGARRAY_BOUND", "1000000")) \
            < big.size:
        assert len(kv._ps._plan("big", big.size)) == nw, \
            "big key must split across every server shard"
    kv.init("big", mx.nd.zeros(BIG_SHAPE))
    kv.barrier()
    # the server-side SGD updater is store-wide: each worker's push of
    # `big` lands as one -LR*big step on the zero-initialized weight
    kv.push("big", mx.nd.array(big))
    kv.barrier()
    out = mx.nd.zeros(BIG_SHAPE)
    kv.pull("big", out=out)
    np.testing.assert_allclose(out.asnumpy(), -LR * nw * big, rtol=1e-6)

    kv.barrier()
    print(f"worker {rank}/{nw}: dist_async update-on-arrival OK "
          f"({pushes} pushes, {total} applied)", flush=True)


if __name__ == "__main__":
    main()
