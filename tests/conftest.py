"""Test configuration.

Runs the whole suite on a virtual 8-device CPU mesh (the reference
tests multi-GPU semantics on CPU the same way — SURVEY §4
"Multi-device without a cluster").  Must set flags before jax import.

Note: the environment ships with JAX_PLATFORMS=axon (the TPU tunnel),
so this must *override*, not setdefault — finite-difference gradient
tests need CPU float32 matmul precision, and the suite must not
monopolize the real chip.  Set MXNET_TEST_TPU=1 to run the suite on
the TPU instead.
"""

import os
import sys

if os.environ.get("MXNET_TEST_TPU", "0") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# pytest plugins (hypothesis) import jax before this file runs; backends
# initialize lazily, so pushing the config through jax.config still works.
if "jax" in sys.modules and os.environ.get("MXNET_TEST_TPU", "0") != "1":
    import jax

    jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-process / long-running tests excluded from the "
        "tier-1 `-m 'not slow'` gate (decode-pool fan-out, kill-and-"
        "resume subprocess drills)")
