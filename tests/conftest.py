"""Test configuration.

Runs the whole suite on a virtual 8-device CPU mesh (the reference
tests multi-GPU semantics on CPU the same way — SURVEY §4
"Multi-device without a cluster").  Must set flags before jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
