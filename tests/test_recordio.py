"""RecordIO + image pipeline tests.

Models the reference's ``tests/python/unittest/test_recordio.py`` and
``test_io.py`` ImageRecordIter coverage (SURVEY §4), plus an
end-to-end train-on-packed-records check.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio as rio

cv2 = pytest.importorskip("cv2")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "t.rec")
    recs = [b"hello", b"x" * 7, b"", b"\xce\xd7\x23\x0a" * 5, b"a" * 1025]
    w = rio.MXRecordIO(path, "w")
    for r in recs:
        w.write(r)
    w.close()
    r = rio.MXRecordIO(path, "r")
    out = []
    while True:
        b = r.read()
        if b is None:
            break
        out.append(b)
    r.close()
    assert out == recs
    assert len(rio.list_records(path)) == len(recs)


def test_recordio_native_python_identical_bytes(tmp_path):
    """The C++ writer and the Python fallback must produce identical files."""
    from mxnet_tpu import _native
    if _native.lib() is None:
        pytest.skip("native library unavailable")
    recs = [b"abc", b"1234", b"\x00" * 9]
    pn = str(tmp_path / "n.rec")
    w = rio.MXRecordIO(pn, "w")
    for r in recs:
        w.write(r)
    w.close()
    # force the python path
    pp = str(tmp_path / "p.rec")
    wp = rio.MXRecordIO.__new__(rio.MXRecordIO)
    wp.uri, wp.flag, wp.is_open = pp, "w", False
    wp._native, wp._fp = None, open(pp, "wb")
    wp.writable, wp.is_open = True, True
    for r in recs:
        wp.write(r)
    wp._fp.close()
    wp.is_open = False
    with open(pn, "rb") as a, open(pp, "rb") as b:
        assert a.read() == b.read()


def test_indexed_recordio(tmp_path):
    prefix = str(tmp_path / "i")
    w = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(20):
        w.write_idx(i, f"record-{i}".encode())
    w.close()
    r = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    assert r.keys == list(range(20))
    for i in (13, 0, 19, 7):
        assert r.read_idx(i) == f"record-{i}".encode()
    r.close()


def test_irheader_pack_unpack():
    h = rio.IRHeader(0, 2.5, 11, 0)
    hdr, body = rio.unpack(rio.pack(h, b"payload"))
    assert hdr.label == 2.5 and hdr.id == 11 and body == b"payload"
    # vector label goes through the flag field
    hdr, body = rio.unpack(rio.pack(rio.IRHeader(0, [1.0, 2.0], 3, 0), b"x"))
    assert hdr.flag == 2 and list(hdr.label) == [1.0, 2.0] and body == b"x"


def test_pack_img_roundtrip():
    img = (np.arange(40 * 60 * 3) % 255).astype(np.uint8).reshape(40, 60, 3)
    s = rio.pack_img(rio.IRHeader(0, 1.0, 7, 0), img, img_fmt=".png")
    hdr, img2 = rio.unpack_img(s)
    assert hdr.label == 1.0 and np.array_equal(img, img2)


def _make_color_dataset(tmp_path, n=40, size=36):
    """Two classes distinguishable by mean brightness."""
    prefix = str(tmp_path / "ds")
    rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        label = i % 2
        base = 60 if label == 0 else 190
        img = np.clip(rng.randn(size, size, 3) * 15 + base, 0,
                      255).astype(np.uint8)
        rec.write_idx(i, rio.pack_img(rio.IRHeader(0, float(label), i, 0),
                                      img, img_fmt=".png"))
    rec.close()
    return prefix


def test_image_record_iter_shapes_and_epoch(tmp_path):
    prefix = _make_color_dataset(tmp_path, n=30)
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", path_imgidx=prefix + ".idx",
        data_shape=(3, 32, 32), batch_size=8, shuffle=True, rand_crop=True,
        rand_mirror=True, preprocess_threads=2, seed=7)
    pads = [b.pad for b in it]
    assert len(pads) == 4 and pads == [0, 0, 0, 2]
    it.reset()
    b = next(iter(it))
    assert b.data[0].shape == (8, 3, 32, 32)
    assert b.label[0].shape == (8,)
    it.close()


def test_image_record_iter_sharding(tmp_path):
    prefix = _make_color_dataset(tmp_path, n=30)
    counts = []
    for pi in range(3):
        it = mx.io.ImageRecordIter(
            path_imgrec=prefix + ".rec", data_shape=(3, 32, 32),
            batch_size=5, num_parts=3, part_index=pi, preprocess_threads=1)
        counts.append(it.num_data)
        it.close()
    assert counts == [10, 10, 10]


def test_image_record_iter_mean_img_cache(tmp_path):
    prefix = _make_color_dataset(tmp_path, n=16)
    mean_path = str(tmp_path / "mean.bin")
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", data_shape=(3, 32, 32), batch_size=8,
        mean_img=mean_path, preprocess_threads=1)
    assert os.path.isfile(mean_path)
    b = next(iter(it))
    assert abs(float(b.data[0].asnumpy().mean())) < 30  # roughly centered
    it.close()
    # second open loads the cached file
    it2 = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", data_shape=(3, 32, 32), batch_size=8,
        mean_img=mean_path, preprocess_threads=1)
    next(iter(it2))
    it2.close()


def test_train_on_image_records(tmp_path):
    """End-to-end: pack images -> ImageRecordIter -> Module.fit learns."""
    prefix = _make_color_dataset(tmp_path, n=40)
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", data_shape=(3, 32, 32), batch_size=10,
        shuffle=True, rand_mirror=True, mean_r=123, mean_g=123, mean_b=123,
        scale=1.0 / 58.0, preprocess_threads=2, seed=3)
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, num_filter=8, kernel=(3, 3), name="c1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(8, 8), stride=(8, 8), pool_type="avg")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=4, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            initializer=mx.initializer.Xavier(),
            eval_metric="acc", batch_end_callback=None)
    it.reset()
    score = mod.score(it, mx.metric.Accuracy())
    acc = dict(score)["accuracy"]
    assert acc > 0.9, f"accuracy {acc} too low — pipeline not learnable"


def test_im2rec_tool(tmp_path):
    """make_list + pack from an image directory, then read back."""
    root = tmp_path / "imgs"
    for cls in ("cat", "dog"):
        (root / cls).mkdir(parents=True)
        for i in range(4):
            img = np.full((20, 24, 3),
                          40 if cls == "cat" else 200, np.uint8)
            cv2.imwrite(str(root / cls / f"{i}.png"), img)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    lst = tmp_path / "data.lst"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "im2rec.py"),
         "--make-list", str(lst), str(root)],
        capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    assert len(open(lst).readlines()) == 8
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "im2rec.py"),
         str(lst), str(root), "--encoding", ".png", "--num-thread", "2"],
        capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    rec = rio.MXIndexedRecordIO(str(tmp_path / "data.idx"),
                                str(tmp_path / "data.rec"), "r")
    assert len(rec.keys) == 8
    hdr, img = rio.unpack_img(rec.read_idx(rec.keys[0]))
    assert img.shape == (20, 24, 3) and hdr.label in (0.0, 1.0)
    rec.close()


def test_recordio_empty_first_record(tmp_path):
    """Zero-length record at position 0 must not read as EOF (native path)."""
    path = str(tmp_path / "e.rec")
    w = rio.MXRecordIO(path, "w")
    w.write(b"")
    w.write(b"after-empty")
    w.close()
    r = rio.MXRecordIO(path, "r")
    assert r.read() == b""
    assert r.read() == b"after-empty"
    assert r.read() is None
    r.close()


def test_image_record_iter_tiny_shard_wrap(tmp_path):
    """batch_size > 2*num_data: round_batch must still emit full batches."""
    prefix = _make_color_dataset(tmp_path, n=3)
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", data_shape=(3, 32, 32), batch_size=8,
        round_batch=True, preprocess_threads=1)
    b = next(iter(it))
    assert b.data[0].shape == (8, 3, 32, 32) and b.pad == 5
    it.close()


def test_image_record_iter_seed_reproducible(tmp_path):
    """Same seed -> identical augmented batches across fresh iterators."""
    prefix = _make_color_dataset(tmp_path, n=12)
    def run():
        it = mx.io.ImageRecordIter(
            path_imgrec=prefix + ".rec", data_shape=(3, 28, 28),
            batch_size=6, shuffle=True, rand_crop=True, rand_mirror=True,
            random_h=20, preprocess_threads=3, seed=5)
        out = np.concatenate([b.data[0].asnumpy() for b in it])
        it.close()
        return out
    a, b = run(), run()
    assert np.array_equal(a, b)


def test_image_record_iter_grayscale(tmp_path):
    """data_shape channel count drives decode: (1, H, W) yields 1-channel."""
    prefix = str(tmp_path / "g")
    rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    rng = np.random.RandomState(0)
    for i in range(12):
        img = (rng.rand(28, 28) * 255).astype(np.uint8)
        rec.write_idx(i, rio.pack_img(rio.IRHeader(0, float(i % 2), i, 0),
                                      img, img_fmt=".png"))
    rec.close()
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", data_shape=(1, 28, 28), batch_size=4,
        preprocess_threads=1)
    b = next(iter(it))
    assert b.data[0].shape == (4, 1, 28, 28)
    it.close()


def test_image_record_iter_rejects_unknown_kwargs(tmp_path):
    prefix = _make_color_dataset(tmp_path, n=4)
    with pytest.raises(TypeError, match="rand_miror"):
        mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                              data_shape=(3, 32, 32), batch_size=2,
                              rand_miror=True)


def test_image_record_iter_grayscale_resize(tmp_path):
    """cv2 ops drop the channel dim of (H,W,1); the pipeline must restore it."""
    prefix = str(tmp_path / "gr")
    rec = rio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    rng = np.random.RandomState(0)
    for i in range(8):
        img = (rng.rand(20, 20) * 255).astype(np.uint8)  # != data_shape
        rec.write_idx(i, rio.pack_img(rio.IRHeader(0, float(i % 2), i, 0),
                                      img, img_fmt=".png"))
    rec.close()
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", data_shape=(1, 28, 28), batch_size=4,
        rand_crop=True, rand_mirror=True, max_rotate_angle=10,
        preprocess_threads=2)
    b = next(iter(it))
    assert b.data[0].shape == (4, 1, 28, 28)
    it.close()
    with pytest.raises(Exception, match="HSL"):
        mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                              data_shape=(1, 28, 28), batch_size=4,
                              random_h=10)


def test_read_batch_native(tmp_path):
    """Batched native reads return the same payloads as sequential reads."""
    path = str(tmp_path / "b.rec")
    w = rio.MXRecordIO(path, "w")
    recs = [bytes([i]) * (i * 7 + 1) for i in range(20)]
    for r in recs:
        w.write(r)
    w.close()
    offsets = rio.list_records(path)
    # arbitrary order incl. duplicates
    order = [3, 0, 19, 7, 7, 12]
    out = rio.read_batch(path, [offsets[i] for i in order], threads=3)
    assert out == [recs[i] for i in order]
    with pytest.raises(Exception, match="corrupt|open"):
        rio.read_batch(path, [5], threads=1)  # misaligned offset


def test_read_batch_empty_records(tmp_path):
    path = str(tmp_path / "e2.rec")
    w = rio.MXRecordIO(path, "w")
    for r in (b"", b"", b"x"):
        w.write(r)
    w.close()
    offsets = rio.list_records(path)
    assert rio.read_batch(path, offsets[:2]) == [b"", b""]  # all-empty batch
    assert rio.read_batch(path, offsets) == [b"", b"", b"x"]


def test_image_record_iter_label_map(tmp_path):
    """path_imglist relabels records without repacking (reference:
    image_recordio.h:24-30)."""
    prefix = _make_color_dataset(tmp_path, n=8)
    lst = tmp_path / "relabel.lst"
    # flip every label: id i -> 1 - (i % 2)
    lst.write_text("".join(f"{i}\t{1 - (i % 2)}\t-\n" for i in range(8)))
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", path_imglist=str(lst),
        data_shape=(3, 32, 32), batch_size=8, preprocess_threads=1)
    b = next(iter(it))
    idxs = b.index
    labels = b.label[0].asnumpy()
    for pos, i in enumerate(idxs):
        assert labels[pos] == 1 - (int(i) % 2)
    it.close()


def test_image_record_iter_label_map_missing_id(tmp_path):
    prefix = _make_color_dataset(tmp_path, n=4)
    lst = tmp_path / "partial.lst"
    lst.write_text("0\t1\t-\n")  # only id 0 remapped
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", path_imglist=str(lst),
        data_shape=(3, 32, 32), batch_size=4, preprocess_threads=1)
    with pytest.raises(Exception, match="not found in path_imglist"):
        next(iter(it))
    it.close()


def test_image_record_iter_state_resume(tmp_path):
    """Mid-epoch restore reproduces the remaining batches bit-exactly —
    per-epoch shuffle order, the epoch-keyed augmentation RNG, and the
    cursor all travel in state_dict."""
    prefix = _make_color_dataset(tmp_path, n=24)
    kw = dict(path_imgrec=prefix + ".rec", path_imgidx=prefix + ".idx",
              data_shape=(3, 32, 32), batch_size=4, shuffle=True,
              rand_mirror=True, preprocess_threads=1, seed=13)
    it = mx.io.ImageRecordIter(**kw)
    it.reset()  # epoch 2: a reshuffle has happened
    for _ in range(2):
        next(it)
    state = it.state_dict()
    rest_ref = [(b.data[0].asnumpy(), b.label[0].asnumpy()) for b in it]
    assert len(rest_ref) == 4
    it.close()

    it2 = mx.io.ImageRecordIter(**dict(kw, seed=99))  # different seed!
    it2.set_state(state)
    rest = [(b.data[0].asnumpy(), b.label[0].asnumpy()) for b in it2]
    assert len(rest) == 4
    for (d1, l1), (d2, l2) in zip(rest_ref, rest):
        np.testing.assert_array_equal(d1, d2)
        np.testing.assert_array_equal(l1, l2)
    # the restored rng stream drives the NEXT epoch's reshuffle too
    it2.reset()
    b = next(it2)
    assert b.data[0].shape == (4, 3, 32, 32)
    it2.close()


def test_image_record_iter_state_resume_at_epoch_end(tmp_path):
    """Restoring a snapshot taken exactly at the epoch's end must NOT
    swallow the next epoch's reshuffle (review finding: a rewind latch
    leaked into the genuine epoch-advance reset)."""
    prefix = _make_color_dataset(tmp_path, n=16)
    kw = dict(path_imgrec=prefix + ".rec", data_shape=(3, 32, 32),
              batch_size=4, shuffle=True, preprocess_threads=1, seed=21)
    it = mx.io.ImageRecordIter(**kw)
    for _ in it:
        pass  # exhaust epoch 1 -> _seen_epoch_end
    state = it.state_dict()
    it.reset()
    epoch2_ref = [b.label[0].asnumpy() for b in it]
    it.close()

    it2 = mx.io.ImageRecordIter(**dict(kw, seed=5))
    it2.set_state(state)
    with np.testing.assert_raises(StopIteration):
        next(it2)  # restored position IS the epoch end
    it2.reset()  # a genuine epoch advance: must reshuffle like the ref
    epoch2 = [b.label[0].asnumpy() for b in it2]
    assert len(epoch2) == len(epoch2_ref)
    for a, b in zip(epoch2_ref, epoch2):
        np.testing.assert_array_equal(a, b)
    it2.close()
