"""Behavior-parity tests pinning the from-spec rewrites of
Speedometer/ProgressBar (callback.py) and the lr schedulers'
edge semantics (round-5 copy findings: the previous bodies were
line-for-line reference copies)."""

import logging
import math
from collections import namedtuple

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.callback import ProgressBar, Speedometer
from mxnet_tpu.lr_scheduler import FactorScheduler, MultiFactorScheduler

Param = namedtuple("Param", ["epoch", "nbatch", "eval_metric"])


class _FakeMetric:
    def __init__(self):
        self.resets = 0

    def get_name_value(self):
        return [("acc", 0.5), ("ce", 1.25)]

    def reset(self):
        self.resets += 1


def test_speedometer_report_cadence(caplog):
    """First call only opens the window; reports fire on every multiple
    of `frequent`, one line per metric, with a positive rate."""
    m = _FakeMetric()
    s = Speedometer(batch_size=4, frequent=2, auto_reset=True)
    with caplog.at_level(logging.INFO):
        for nb in range(1, 7):
            s(Param(epoch=0, nbatch=nb, eval_metric=m))
    msgs = [r.getMessage() for r in caplog.records if "Speed:" in r.getMessage()]
    # nbatch 2 primes nothing (window opened at nbatch=1); reports at
    # 2, 4, 6 → 3 reports × 2 metric lines
    assert len(msgs) == 6, msgs
    assert all("Epoch[0]" in m_ for m_ in msgs)
    assert any("Train-acc=0.5" in m_ for m_ in msgs)
    speed = float(msgs[0].split("Speed: ")[1].split(" ")[0])
    assert speed > 0
    assert m.resets == 3  # auto_reset fires once per report


def test_speedometer_no_autoreset_and_epoch_rewind(caplog):
    m = _FakeMetric()
    s = Speedometer(batch_size=4, frequent=2, auto_reset=False)
    with caplog.at_level(logging.INFO):
        for nb in (1, 2, 3, 4):
            s(Param(epoch=0, nbatch=nb, eval_metric=m))
        n_epoch0 = len(caplog.records)
        # epoch boundary: counter rewinds; the first call must only
        # re-open the window (no report even on a multiple of frequent)
        s(Param(epoch=1, nbatch=2, eval_metric=m))
        assert len(caplog.records) == n_epoch0
        s(Param(epoch=1, nbatch=4, eval_metric=m))
        assert len(caplog.records) == n_epoch0 + 2
    assert m.resets == 0


def test_speedometer_no_metric(caplog):
    s = Speedometer(batch_size=8, frequent=1)
    with caplog.at_level(logging.INFO):
        s(Param(epoch=2, nbatch=1, eval_metric=None))  # primes only
        s(Param(epoch=2, nbatch=2, eval_metric=None))
    msgs = [r.getMessage() for r in caplog.records]
    assert len(msgs) == 1 and "Epoch[2]" in msgs[0] and "Speed:" in msgs[0]


def test_progress_bar_frames(capsys):
    bar = ProgressBar(total=4, length=8)
    bar(Param(epoch=0, nbatch=2, eval_metric=None))
    out = capsys.readouterr().out
    assert out == "[====----] 50%\r"
    bar(Param(epoch=0, nbatch=3, eval_metric=None))
    assert capsys.readouterr().out == "[======--] 75%\r"
    bar(Param(epoch=0, nbatch=4, eval_metric=None))
    assert capsys.readouterr().out == "[========] 100%\r"


def test_progress_bar_ceil_percent(capsys):
    bar = ProgressBar(total=3, length=6)
    bar(Param(epoch=0, nbatch=1, eval_metric=None))
    out = capsys.readouterr().out
    # 1/3 → 33.33% ceils to 34, bar rounds to 2 of 6 cells
    assert out == "[==----] 34%\r"
    assert math.ceil(100.0 * 1 / 3.0) == 34


# -- lr scheduler parity (the reference's exact decay boundaries) -------


def test_factor_scheduler_boundaries():
    s = FactorScheduler(step=10, factor=0.5)
    s.base_lr = 1.0
    assert s(10) == 1.0          # boundary itself does not decay
    assert s(11) == 0.5          # first update past it does
    assert s.count == 10
    assert s(20) == 0.5
    assert s(21) == 0.25


def test_factor_scheduler_lazy_catchup():
    s = FactorScheduler(step=10, factor=0.5)
    s.base_lr = 1.0
    # one call far ahead applies every overdue decay at once
    assert s(31) == 0.125
    assert s.count == 30


def test_factor_scheduler_floor():
    s = FactorScheduler(step=1, factor=0.1, stop_factor_lr=0.05)
    s.base_lr = 1.0
    assert abs(s(2) - 0.1) < 1e-12
    assert s(3) == 0.05          # 0.01 < floor → clamps
    assert s(50) == 0.05         # and stays clamped


def test_factor_scheduler_validation():
    import pytest

    with pytest.raises(ValueError):
        FactorScheduler(step=0)
    with pytest.raises(ValueError):
        FactorScheduler(step=5, factor=1.5)


def test_multifactor_scheduler_boundaries():
    m = MultiFactorScheduler(step=[5, 15], factor=0.1)
    m.base_lr = 1.0
    assert m(5) == 1.0           # milestone itself does not decay
    assert abs(m(6) - 0.1) < 1e-12
    assert m.count == 5
    assert abs(m(15) - 0.1) < 1e-12
    assert abs(m(16) - 0.01) < 1e-12
    assert abs(m(1000) - 0.01) < 1e-12  # past the last milestone


def test_multifactor_scheduler_catchup_and_validation():
    import pytest

    m = MultiFactorScheduler(step=[5, 15], factor=0.1)
    m.base_lr = 1.0
    assert abs(m(16) - 0.01) < 1e-12  # both milestones in one call
    with pytest.raises(ValueError):
        MultiFactorScheduler(step=[5, 5], factor=0.1)
    with pytest.raises(ValueError):
        MultiFactorScheduler(step=[0, 5], factor=0.1)
    with pytest.raises(ValueError):
        MultiFactorScheduler(step=[5, 15], factor=2.0)


def test_scheduler_drives_training_lr():
    """End-to-end: the scheduler's lr reaches the fused update (the lr
    device-scalar cache must track scheduler changes)."""
    sched = FactorScheduler(step=2, factor=0.5)
    opt = mx.optimizer.create("sgd", learning_rate=0.8, lr_scheduler=sched)
    assert sched.base_lr == 0.8
    rng = np.random.RandomState(0)
    X = rng.randn(48, 8).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=8)
    mod = mx.mod.Module(
        mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                                  name="fc"), name="softmax"),
        context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params(mx.initializer.Uniform(0.1))
    mod.init_optimizer(optimizer=opt)
    for b in it:
        mod.forward_backward(b)
        mod.update()
    # 6 updates with step=2: decays after updates 3 and 5 → 0.8/4
    assert abs(opt.lr_scheduler(opt.num_update) - 0.2) < 1e-12
