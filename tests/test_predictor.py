"""Predict API + standalone export tests (reference:
c_predict_api.cc workflow + amalgamation deployability)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _trained_module(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.randn(120, 6).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(
            mx.sym.Activation(
                mx.sym.FullyConnected(mx.sym.Variable("data"),
                                      num_hidden=8, name="fc1"),
                act_type="relu"),
            num_hidden=2, name="fc2"), name="softmax")
    it = mx.io.NDArrayIter(X, y, batch_size=20)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=4, optimizer="sgd",
            optimizer_params={"learning_rate": 0.3})
    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 4)
    return mod, net, prefix, X


def test_predictor_matches_module(tmp_path):
    mod, net, prefix, X = _trained_module(tmp_path)
    batch = X[:20]
    pred = mx.Predictor.from_checkpoint(prefix, 4,
                                        {"data": (20, 6),
                                         "softmax_label": (20,)})
    pred.set_input("data", batch)
    pred.set_input("softmax_label", np.zeros((20,), np.float32))
    out = pred.forward().get_output(0)
    mod.forward(mx.io.DataBatch([mx.nd.array(batch)],
                                [mx.nd.zeros((20,))]), is_train=False)
    expect = mod.get_outputs()[0].asnumpy()
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)


def test_predictor_rejects_bad_input(tmp_path):
    _, _, prefix, _ = _trained_module(tmp_path)
    pred = mx.Predictor.from_checkpoint(prefix, 4,
                                        {"data": (4, 6),
                                         "softmax_label": (4,)})
    with pytest.raises(mx.MXNetError, match="shape"):
        pred.set_input("data", np.zeros((4, 7), np.float32))
    with pytest.raises(mx.MXNetError, match="unknown input"):
        pred.set_input("fc1_weight", np.zeros((8, 6), np.float32))
    with pytest.raises(mx.MXNetError, match="not set"):
        pred.forward(data=np.zeros((4, 6), np.float32))


def test_export_and_load(tmp_path):
    mod, net, prefix, X = _trained_module(tmp_path)
    arg_params, aux_params = mod.get_params()
    path = str(tmp_path / "model.mxtpu")
    mx.predictor.export_model(
        net, arg_params, aux_params,
        {"data": (20, 6), "softmax_label": (20,)}, path=path)
    fn, meta = mx.predictor.load_exported(path)
    assert meta["inputs"] == ["data", "softmax_label"]
    out = np.asarray(fn(X[:20], np.zeros((20,), np.float32))[0])
    mod.forward(mx.io.DataBatch([mx.nd.array(X[:20])],
                                [mx.nd.zeros((20,))]), is_train=False)
    np.testing.assert_allclose(out, mod.get_outputs()[0].asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_exported_artifact_runs_without_mxnet_tpu(tmp_path):
    """The amalgamation claim: the artifact runs with jax alone."""
    mod, net, prefix, X = _trained_module(tmp_path)
    arg_params, aux_params = mod.get_params()
    path = str(tmp_path / "model.mxtpu")
    mx.predictor.export_model(
        net, arg_params, aux_params,
        {"data": (20, 6), "softmax_label": (20,)}, path=path)
    mod.forward(mx.io.DataBatch([mx.nd.array(X[:20])],
                                [mx.nd.zeros((20,))]), is_train=False)
    expect_path = str(tmp_path / "expect.npy")
    np.save(expect_path, mod.get_outputs()[0].asnumpy())
    in_path = str(tmp_path / "in.npy")
    np.save(in_path, X[:20])
    script = f"""
import sys
import numpy as np
from jax import export
raw = open({path!r}, 'rb').read()
assert raw.startswith(b'MXTPUEXP1')
n = int.from_bytes(raw[9:17], 'little')
fn = export.deserialize(raw[17 + n:]).call
x = np.load({in_path!r})
out = np.asarray(fn(x, np.zeros((20,), np.float32))[0])
np.testing.assert_allclose(out, np.load({expect_path!r}),
                           rtol=1e-5, atol=1e-6)
forbidden = [m for m in sys.modules if m.startswith('mxnet_tpu')]
assert not forbidden, forbidden
print('standalone artifact OK')
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="")
    r = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    assert "standalone artifact OK" in r.stdout


def test_predictor_dict_params_with_aux(tmp_path):
    """In-memory params dict incl. BatchNorm aux states works."""
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(
            mx.sym.BatchNorm(mx.sym.Variable("data"), name="bn"),
            num_hidden=2, name="fc"), name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 6))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    arg_params, aux_params = mod.get_params()
    pred = mx.Predictor(net, {**arg_params, **aux_params},
                        {"data": (4, 6), "softmax_label": (4,)})
    x = np.random.RandomState(0).randn(4, 6).astype(np.float32)
    out = pred.forward(data=x,
                       softmax_label=np.zeros(4, np.float32)).get_output(0)
    mod.forward(mx.io.DataBatch([mx.nd.array(x)], [mx.nd.zeros((4,))]),
                is_train=False)
    np.testing.assert_allclose(out, mod.get_outputs()[0].asnumpy(),
                               rtol=1e-5, atol=1e-6)
