"""Repo lint: every ``MXNET_*`` environment variable mentioned in
``mxnet_tpu/`` must resolve through the ``config.py`` catalog.

The catalog is what makes configuration discoverable
(``mx.config.list_env()``) and loudly validated; an env var read that
bypasses it is folklore with silent-failure semantics.  This test
names the offender and its location, so the new observability vars —
and every future one — can't sneak in unregistered."""

import os
import re

import mxnet_tpu.config as config

_PKG = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "mxnet_tpu")

_TOKEN = re.compile(r"MXNET_[A-Z0-9_]+")

# read sites: a token on one of these lines is an actual env READ and
# must be registered EXACTLY (doc prose gets prefix tolerance below)
_READ = re.compile(r"environ|get_env|getenv|_validated_env|"
                   r"_read_env|fleet_env|describe\(")


def _catalog():
    return {v.name for v in config.list_env()}


def test_every_env_read_resolves_through_the_catalog():
    registered = _catalog()
    offenders = []
    for root, _dirs, files in os.walk(_PKG):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, os.path.dirname(_PKG))
            with open(path) as f:
                for lineno, line in enumerate(f, 1):
                    for tok in _TOKEN.findall(line):
                        name = tok.rstrip("_")
                        if name in registered:
                            continue
                        if _READ.search(line):
                            # an actual read of an unregistered var
                            offenders.append(
                                f"{rel}:{lineno}: {tok} (read)")
                        elif not any(r.startswith(name + "_")
                                     for r in registered):
                            # prose may name a family ("MXNET_CHAOS_*")
                            # — anything else is an unregistered name
                            offenders.append(
                                f"{rel}:{lineno}: {tok} (mention)")
    assert not offenders, (
        "MXNET_* env vars bypassing the config.py catalog "
        "(register_env them):\n  " + "\n  ".join(offenders))


def test_catalog_has_no_dead_entries():
    """The inverse direction: every registered var is actually
    mentioned somewhere OUTSIDE config.py (a stale catalog entry
    documents configuration that nothing reads).  tests/ and tools/
    count — some vars (MXNET_TEST_TPU) are consumed by the harness."""
    repo = os.path.dirname(_PKG)
    mentioned = set()
    for sub in ("mxnet_tpu", "tests", "tools"):
        for root, _dirs, files in os.walk(os.path.join(repo, sub)):
            for fn in files:
                if fn.endswith(".py") and fn != "config.py":
                    with open(os.path.join(root, fn)) as f:
                        mentioned.update(_TOKEN.findall(f.read()))
    dead = sorted(_catalog() - mentioned)
    assert not dead, f"catalog entries never mentioned in code: {dead}"


def test_observability_vars_are_registered():
    """The PR-12 vars specifically (the satellite's motivating case)."""
    registered = _catalog()
    for name in ("MXNET_METRICS_PORT", "MXNET_FLIGHT_RECORDER",
                 "MXNET_FLIGHT_RECORDER_SIZE",
                 "MXNET_FLIGHT_RECORDER_DIR", "MXNET_TRACE_SAMPLE",
                 "MXNET_PEAK_TFLOPS"):
        assert name in registered, name
        assert config.describe(name).doc
