"""Mesh data/tensor parallelism tests on the virtual 8-device CPU mesh.

The reference tests multi-GPU semantics on CPU the same way
(tests/python/unittest/test_kvstore.py passes N arrays per key;
test_multi_device_exec.py binds across contexts) — SURVEY §4.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import parallel


def _build_mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _train(contexts=None, kvstore=None, steps=6, batch=16, seed=7):
    mx.random.seed(seed)
    rng = np.random.RandomState(3)
    X = rng.randn(batch * steps, 8).astype(np.float32)
    y = rng.randint(0, 4, size=batch * steps).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=batch)
    mod = mx.mod.Module(_build_mlp(), context=contexts or mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params(mx.initializer.Uniform(0.1))
    mod.init_optimizer(kvstore=kvstore, optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    for b in it:
        mod.forward_backward(b)
        mod.update()
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args.items()}


def test_meshplan_shapes():
    import jax

    plan = parallel.make_plan()
    assert plan.num_devices == len(jax.devices())
    plan2 = parallel.MeshPlan(jax.devices(), tp=2)
    assert plan2.dp * 2 == len(jax.devices())
    with pytest.raises(mx.base.MXNetError):
        parallel.MeshPlan(jax.devices(), dp=3, tp=2)


def test_data_parallel_matches_single_device():
    """dp=8 must compute the same update as one device (SURVEY §2.4:
    sync data parallelism == gradient sum over shards)."""
    single = _train(contexts=[mx.cpu(0)])
    multi = _train(contexts=[mx.cpu(i) for i in range(8)])
    for k in single:
        np.testing.assert_allclose(single[k], multi[k], rtol=2e-4, atol=2e-5)


def test_kvstore_tpu_activates_mesh():
    """kvstore='tpu' on one context shards over every visible device."""
    import jax

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    X = rng.randn(32, 8).astype(np.float32)
    y = rng.randint(0, 4, size=32).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_build_mlp(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params(mx.initializer.Uniform(0.1))
    mod.init_optimizer(kvstore="tpu", optimizer="sgd")
    assert mod._mesh_plan is not None
    assert mod._mesh_plan.num_devices == len(jax.devices())
    assert mod._kvstore.mesh_plan is mod._mesh_plan
    b = next(iter(it))
    mod.forward_backward(b)
    mod.update()
    # params replicated over the whole mesh
    w = mod._exec.arg_dict["fc1_weight"]._data
    assert len(w.devices()) == len(jax.devices())
    # batch input sharded over dp
    data = mod._exec.arg_dict["data"]._data
    assert len(data.devices()) == len(jax.devices())
    out = mod.get_outputs()[0]
    assert out.shape == (16, 4)
    assert not np.any(np.isnan(out.asnumpy()))


def test_kvstore_tpu_matches_local_training():
    ref = _train(kvstore=None)
    tpu = _train(kvstore="tpu")
    for k in ref:
        np.testing.assert_allclose(ref[k], tpu[k], rtol=2e-4, atol=2e-5)


def test_tensor_parallel_shard_attr():
    """__shard__ attr shards a param dim over 'tp'; grads stay correct."""
    import jax

    mx.random.seed(1)
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("fc1_weight", attr=parallel.shard_attr("tp", 0))
    net = mx.sym.FullyConnected(data, weight=w, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    rng = np.random.RandomState(5)
    X = rng.randn(32, 8).astype(np.float32)
    y = rng.randint(0, 4, size=32).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=16)

    def run(tp):
        mx.random.seed(11)
        it.reset()
        mod = mx.mod.Module(net, context=mx.cpu())
        mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
                 for_training=True)
        mod.init_params(mx.initializer.Uniform(0.1))
        if tp:
            from mxnet_tpu.parallel import make_plan

            mod._mesh_plan = make_plan(tp=2)
            mod._apply_mesh_plan()
        mod.init_optimizer(kvstore="tpu" if tp else None, optimizer="sgd")
        for b in it:
            mod.forward_backward(b)
            mod.update()
        args, _ = mod.get_params()
        return {k: v.asnumpy() for k, v in args.items()}

    ref = run(tp=False)
    tpd = run(tp=True)
    for k in ref:
        np.testing.assert_allclose(ref[k], tpd[k], rtol=2e-4, atol=2e-5)


def test_dist_kvstore_single_process():
    kv = mx.kv.create("dist_sync")
    assert kv.rank == 0
    assert kv.num_workers == 1
    kv.barrier()  # no-op rendezvous in single process
    assert kv.get_num_dead_node() == 0


def test_batch_not_divisible_raises():
    it_shapes = [("data", (10, 8))]
    mod = mx.mod.Module(_build_mlp(), context=[mx.cpu(i) for i in range(8)])
    with pytest.raises(mx.base.MXNetError):
        mod.bind(data_shapes=it_shapes, label_shapes=[("softmax_label", (10,))],
                 for_training=True)


def test_partition_rules_first_match_wins():
    rules = parallel.PartitionRules([("foo", "tp"), ("foo|bar", "dp")])
    assert rules.axis_for("foo") == "tp"
    assert rules.axis_for("bar") == "dp"
    # ordering is the contract: flipped table flips the answer
    flipped = parallel.PartitionRules([("foo|bar", "dp"), ("foo", "tp")])
    assert flipped.axis_for("foo") == "dp"


def test_partition_rules_scalar_and_size1_unpartitioned():
    rules = parallel.PartitionRules([("hidden", "dp")])
    assert rules.spec(()) == ()
    assert rules.spec(("hidden",), shape=(1,)) == (None,)
    assert rules.spec(("hidden", None), shape=(8, 4)) == ("dp", None)


def test_partition_rules_unmatched_raises_naming_param():
    rules = parallel.PartitionRules([("batch", "dp")])
    with pytest.raises(mx.base.MXNetError, match="fc1_weight"):
        rules.spec(("mystery", "embed"), param="fc1_weight")


def test_partition_rules_duplicate_axis_rejected():
    rules = parallel.PartitionRules([("a|b", "tp")])
    with pytest.raises(mx.base.MXNetError, match="same mesh axis"):
        rules.spec(("a", "b"), shape=(4, 4), param="w")


def test_partition_rules_parse_and_validation():
    rules = parallel.PartitionRules.parse(
        "batch:dp;vocab|qkv:tp;embed|length:-")
    assert rules.axis_for("vocab") == "tp"
    assert rules.axis_for("embed") is None
    for bad in ("novalue", "", "(:dp"):
        with pytest.raises(mx.base.MXNetError):
            parallel.PartitionRules.parse(bad)
    # unknown mesh axis caught at plan construction
    import jax

    with pytest.raises(mx.base.MXNetError, match="unknown mesh axis"):
        parallel.MeshPlan(jax.devices(), rules=[("vocab", "model")])


def test_rules_resolve_params_activations_optstate_identically():
    """ONE table answers for parameters, activations and the ZeRO
    optimizer state — the single resolution point."""
    import jax
    from jax.sharding import PartitionSpec as P

    plan = parallel.MeshPlan(
        jax.devices(), dp=2, tp=2, pp=2,
        rules=[("vocab", "tp"), ("embed", None), ("length", None)])
    assert plan.param_sharding(2, axes=("vocab", "embed"),
                               shape=(32, 16)).spec == P("tp", None)
    assert plan.input_sharding(3).spec == P("dp", None, None)
    assert plan.activation_spec(("batch", "length", "embed")) \
        == P("dp", None, None)
    assert plan.opt_state_sharding().spec == P("dp")
    # user rules override the built-in tail (first match wins)
    plan2 = parallel.MeshPlan(jax.devices(), dp=2, tp=2, pp=2,
                              rules=[("zero", None), ("batch", "dp")])
    assert plan2.opt_state_sharding().spec == P(None)


def test_shard_attr_shim_matches_rules():
    """The deprecated __shard__ attr synthesizes a single-param rule:
    old annotations shard IDENTICALLY to the logical-axis path."""
    import jax

    plan = parallel.MeshPlan(jax.devices(), dp=4, tp=2,
                             rules=[("hidden", "tp")])
    legacy = plan.param_sharding(2, attr="tp:0", name="fc1_weight")
    modern = plan.param_sharding(2, axes=("hidden", None),
                                 shape=(16, 8), name="fc1_weight")
    assert legacy.spec == modern.spec
    # and the existing validation still bites
    with pytest.raises(mx.base.MXNetError):
        plan.param_sharding(2, attr="model:0")
    with pytest.raises(mx.base.MXNetError):
        plan.param_sharding(1, attr="tp:3")


def test_pp_env_validation(monkeypatch):
    """MXNET_PP / MXNET_MICROBATCHES / MXNET_PARTITION_RULES validate
    loudly at plan construction (the MXNET_CKPT_* pattern)."""
    for bad in ("banana", "-3", "0", "1.5"):
        monkeypatch.setenv("MXNET_PP", bad)
        with pytest.raises(mx.base.MXNetError):
            parallel.make_plan()
    monkeypatch.delenv("MXNET_PP")
    for bad in ("banana", "-3", "0"):
        monkeypatch.setenv("MXNET_MICROBATCHES", bad)
        with pytest.raises(mx.base.MXNetError):
            parallel.make_plan()
    monkeypatch.delenv("MXNET_MICROBATCHES")
    monkeypatch.setenv("MXNET_PARTITION_RULES", "no-colon-entry")
    with pytest.raises(mx.base.MXNetError):
        parallel.make_plan()
    monkeypatch.delenv("MXNET_PARTITION_RULES")
    # the happy path: env-driven pp plan
    monkeypatch.setenv("MXNET_PP", "2")
    monkeypatch.setenv("MXNET_MICROBATCHES", "4")
    monkeypatch.setenv("MXNET_PARTITION_RULES", "batch:dp;hidden:tp")
    plan = parallel.make_plan(tp=2)
    assert plan.pp == 2 and plan.microbatches == 4
    assert plan.rules.axis_for("hidden") == "tp"


def test_check_batch_microbatch_divisibility():
    import jax

    plan = parallel.MeshPlan(jax.devices(), dp=2, tp=2, pp=2,
                             microbatches=3)
    with pytest.raises(mx.base.MXNetError, match="microbatches"):
        plan.check_batch(8)  # 8 % (2*3) != 0
    plan.check_batch(12)
    # bind-time enforcement through the module path
    it_shapes = [("data", (8, 8))]
    mod = mx.mod.Module(_build_mlp(), context=mx.cpu())
    mod._mesh_plan = plan
    with pytest.raises(mx.base.MXNetError, match="microbatches"):
        mod.bind(data_shapes=it_shapes,
                 label_shapes=[("softmax_label", (8,))],
                 for_training=True)


def test_ctx_group_group2ctx_mesh_mapping():
    """AttrScope(ctx_group=...) + group2ctx places a layer group's
    params on a mesh axis (the reference model-parallel idiom,
    reinterpreted; graph_executor.cc:301)."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)

    data = mx.sym.Variable("data")
    with mx.AttrScope(ctx_group="body"):
        net = mx.sym.Activation(
            mx.sym.FullyConnected(data, num_hidden=32, name="fc1"),
            act_type="relu")
    with mx.AttrScope(ctx_group="head"):
        net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params(mx.initializer.Xavier())
    from mxnet_tpu import parallel
    mod.set_mesh_plan(parallel.make_plan(
        tp=2, group2ctx={"body": "tp:0", "head": "tp:1"}))
    mod.init_optimizer(kvstore="tpu", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    # fc1 weight sharded over tp on dim 0, fc2 on dim 1
    from jax.sharding import PartitionSpec as P
    assert mod._exec.arg_dict["fc1_weight"]._data.sharding.spec == P("tp", None)
    assert mod._exec.arg_dict["fc2_weight"]._data.sharding.spec == P(None, "tp")
    # and it trains
    b = next(iter(it))
    mod.forward_backward(b)
    mod.update()
    out = mod.get_outputs()[0].asnumpy()
    assert np.isfinite(out).all()
