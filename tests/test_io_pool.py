"""Parallel data-plane tests: decode pool, shared-memory ring, device
augmentation, and the exact-resume contract across worker processes.

Multi-process tests are marked ``slow`` (excluded from the tier-1
``-m 'not slow'`` gate) and skip on single-core hosts."""

import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io_pool, recordio

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

multiproc = pytest.mark.skipif(
    (os.cpu_count() or 1) < 2,
    reason="decode-pool tests need >= 2 host cores")


def _pack(path, n=40, hw=40, classes=7):
    import cv2

    rec = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        img = (rng.rand(hw, hw, 3) * 255).astype(np.uint8)
        ok, buf = cv2.imencode(".jpg", img)
        assert ok
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i % classes), i, 0), buf.tobytes()))
    rec.close()


@pytest.fixture(scope="module")
def rec_path():
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "d")
        _pack(path)
        yield path


def _make_iter(rec_path, **kw):
    base = dict(path_imgrec=rec_path + ".rec", path_imgidx=rec_path + ".idx",
                data_shape=(3, 32, 32), batch_size=8, rand_crop=True,
                rand_mirror=True, shuffle=True, seed=5)
    base.update(kw)
    return mx.io.ImageRecordIter(**base)


def _drain(it):
    return [(b.data[0].asnumpy().copy(), b.label[0].asnumpy().copy(), b.pad)
            for b in it]


# ---------------------------------------------------------------------------
# env/config validation — loud at construction (tier-1)
# ---------------------------------------------------------------------------

def test_env_validation_garbage_raises(rec_path, monkeypatch):
    monkeypatch.setenv("MXNET_IO_WORKERS", "many")
    with pytest.raises(mx.MXNetError, match="MXNET_IO_WORKERS"):
        _make_iter(rec_path)
    monkeypatch.delenv("MXNET_IO_WORKERS")

    monkeypatch.setenv("MXNET_IO_RING_SLOTS", "1")
    with pytest.raises(mx.MXNetError, match="RING_SLOTS"):
        _make_iter(rec_path, workers=0)
    monkeypatch.setenv("MXNET_IO_RING_SLOTS", "two")
    with pytest.raises(mx.MXNetError, match="RING_SLOTS"):
        _make_iter(rec_path, workers=0)
    monkeypatch.delenv("MXNET_IO_RING_SLOTS")

    monkeypatch.setenv("MXNET_IO_DEVICE_AUGMENT", "2")
    with pytest.raises(mx.MXNetError, match="DEVICE_AUGMENT"):
        _make_iter(rec_path)
    monkeypatch.setenv("MXNET_IO_DEVICE_AUGMENT", "yes")
    with pytest.raises(mx.MXNetError, match="DEVICE_AUGMENT"):
        _make_iter(rec_path)


def test_bad_kwargs_raise(rec_path):
    with pytest.raises(mx.MXNetError, match="workers"):
        _make_iter(rec_path, workers=-5)
    with pytest.raises(mx.MXNetError, match="ring_slots"):
        _make_iter(rec_path, workers=0, ring_slots=1)
    with pytest.raises(mx.MXNetError, match="mixup"):
        _make_iter(rec_path, workers=0, mixup_alpha=0.2)  # needs device aug
    with pytest.raises(mx.MXNetError, match="mixup_alpha"):
        _make_iter(rec_path, workers=0, device_augment=1, mixup_alpha=-1)
    # explicit args get the same loud 0/1 validation as the env var
    # (a CLI typo like --device-augment 10 must not silently opt in)
    with pytest.raises(mx.MXNetError, match="device_augment"):
        _make_iter(rec_path, workers=0, device_augment=2)
    with pytest.raises(mx.MXNetError, match="device_augment"):
        _make_iter(rec_path, workers=0, device_augment="yes")
    # host-only augmentations cannot move on device: refuse, don't drop
    with pytest.raises(mx.MXNetError, match="max_rotate_angle"):
        _make_iter(rec_path, workers=0, device_augment=1,
                   max_rotate_angle=10)
    with pytest.raises(mx.MXNetError, match="resize"):
        _make_iter(rec_path, workers=0, device_augment=1, resize=16)


def test_resolvers(monkeypatch):
    assert io_pool.resolve_workers(0) == 0
    assert io_pool.resolve_workers(3) == 3
    auto = io_pool.resolve_workers("auto")
    assert 1 <= auto <= 8
    # an explicitly set env var wins over 'auto', including 0
    monkeypatch.setenv("MXNET_IO_WORKERS", "0")
    assert io_pool.resolve_workers("auto") == 0
    monkeypatch.setenv("MXNET_IO_WORKERS", "3")
    assert io_pool.resolve_workers("auto") == 3
    monkeypatch.delenv("MXNET_IO_WORKERS")
    assert io_pool.resolve_ring_slots(None, 2) == 6  # 2*workers + 2
    assert io_pool.resolve_ring_slots(4, 1) == 4
    assert io_pool.epoch_num_batches(10, 4, True) == 3
    assert io_pool.epoch_num_batches(10, 4, False) == 2
    idxs = io_pool.batch_indices(np.arange(10), 2, 4, 10)
    np.testing.assert_array_equal(idxs, [8, 9, 0, 1])  # modular wrap


# ---------------------------------------------------------------------------
# device-augment raw path + prologue numerics (tier-1, workers=0)
# ---------------------------------------------------------------------------

def test_device_augment_raw_batches_and_eval_prologue(rec_path):
    it = _make_iter(rec_path, workers=0, device_augment=1)
    b = next(it)
    raw = b.data[0]
    assert raw.dtype == np.uint8
    assert raw.shape == (8, 36, 36, 3)  # 32 * 8/7 jitter margin
    (desc,) = it.raw_provide_data
    assert tuple(desc.shape) == (8, 36, 36, 3) and desc.dtype == np.uint8
    (final,) = it.provide_data  # what the module binds against
    assert tuple(final.shape) == (8, 3, 32, 32)

    import jax

    pro = it.device_prologue
    assert pro is not None
    out = pro({"data": raw._data}, jax.random.PRNGKey(0), False)
    assert out["data"].shape == (8, 3, 32, 32)
    out2 = pro({"data": raw._data}, jax.random.PRNGKey(9), False)
    # eval path is deterministic: center crop, no flip — key-independent
    np.testing.assert_array_equal(np.asarray(out["data"], np.float32),
                                  np.asarray(out2["data"], np.float32))
    # train path actually randomizes
    t1 = pro({"data": raw._data}, jax.random.PRNGKey(0), True)
    t2 = pro({"data": raw._data}, jax.random.PRNGKey(9), True)
    assert not np.array_equal(np.asarray(t1["data"], np.float32),
                              np.asarray(t2["data"], np.float32))
    it.close()


def test_device_prologue_matches_host_normalize(rec_path):
    """With no crop/flip, the device prologue must reproduce the host
    pipeline's (img - mean) / std * scale numerics exactly."""
    import jax

    norm = dict(mean_r=120.0, mean_g=110.0, mean_b=100.0,
                std_r=60.0, std_g=61.0, std_b=62.0, scale=1 / 255.0)
    common = dict(data_shape=(3, 40, 40), rand_crop=False,
                  rand_mirror=False, shuffle=False)
    host = _make_iter(rec_path, workers=0, device_augment=0,
                      **common, **norm)
    dev = _make_iter(rec_path, workers=0, device_augment=1,
                     **common, **norm)
    hb = next(host).data[0].asnumpy()
    rawb = next(dev)
    out = dev.device_prologue({"data": rawb.data[0]._data},
                              jax.random.PRNGKey(0), False)
    np.testing.assert_allclose(np.asarray(out["data"], np.float32), hb,
                               rtol=1e-6, atol=1e-6)
    host.close()
    dev.close()


def test_prefetching_iter_forwards_prologue(rec_path):
    inner = _make_iter(rec_path, workers=0, device_augment=1)
    wrapped = mx.io.PrefetchingIter(inner)
    assert wrapped.device_prologue is inner.device_prologue
    plain = mx.io.PrefetchingIter(
        mx.io.NDArrayIter(np.zeros((8, 4), np.float32), np.zeros(8),
                          batch_size=4))
    assert plain.device_prologue is None
    wrapped.close()
    plain.close()
    inner.close()


def test_prefetching_multi_iter_rejects_prologue(rec_path):
    """A multi-iterator PrefetchingIter cannot carry a per-module
    device prologue: combining a device_augment iterator must raise,
    not silently drop the prologue."""
    raw = _make_iter(rec_path, workers=0, device_augment=1)
    other = mx.io.NDArrayIter(np.zeros((40, 4), np.float32), np.zeros(40),
                              batch_size=8)
    multi = mx.io.PrefetchingIter(
        [raw, other], rename_data=[{"data": "d0"}, {"data": "d1"}],
        rename_label=[{"softmax_label": "l0"}, {"softmax_label": "l1"}])
    with pytest.raises(mx.MXNetError, match="device_augment"):
        multi.device_prologue
    multi.close()
    raw.close()


def test_device_augment_resize_preserves_aspect(tmp_path):
    """`resize=` under device_augment must keep the legacy ResizeAug
    short-edge semantics (aspect-preserving cover-resize + center crop
    into the fixed ring window), never a warping square resize."""
    import cv2

    path = str(tmp_path / "rect")
    rec = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    rng = np.random.RandomState(1)
    src = (rng.rand(30, 60, 3) * 255).astype(np.uint8)  # 2:1 landscape
    ok, buf = cv2.imencode(".png", src)  # lossless: exact reference math
    assert ok
    for i in range(8):
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, 0.0, i, 0), buf.tobytes()))
    rec.close()

    it = mx.io.ImageRecordIter(
        path_imgrec=path + ".rec", path_imgidx=path + ".idx",
        data_shape=(3, 32, 32), batch_size=8, resize=36, shuffle=False,
        workers=0, device_augment=1)
    got = next(it).data[0].asnumpy()[0]
    it.close()

    rgb = src[:, :, ::-1]
    ref = cv2.resize(rgb, (72, 36), interpolation=cv2.INTER_LINEAR)
    ref = ref[:, (72 - 36) // 2:(72 - 36) // 2 + 36]  # center 36x36
    np.testing.assert_array_equal(got, ref)


def test_mean_image_computed_once(rec_path, tmp_path):
    """The mean pass runs once in the parent; later consumers (and
    forked pool workers) reuse the cached array instead of re-reading
    or recomputing."""
    mean_path = str(tmp_path / "mean.bin")
    it1 = _make_iter(rec_path, workers=0, mean_img=mean_path)
    assert os.path.isfile(mean_path)
    os.unlink(mean_path)  # a re-read or recompute would now fail/rewrite
    it2 = _make_iter(rec_path, workers=0, mean_img=mean_path)
    assert not os.path.isfile(mean_path)  # served from the process cache
    np.testing.assert_array_equal(it1._mean, it2._mean)
    it1.close()
    it2.close()


def test_mean_image_in_device_augment_mode(rec_path, tmp_path):
    """Mean computation must work with the empty host augmenter list of
    device_augment mode: accumulate over the fixed-resize + center-crop
    window (records are 40x40, data_shape 32x32 — a naive decode would
    shape-mismatch the accumulator)."""
    mean_path = str(tmp_path / "mean_dev.bin")
    it = _make_iter(rec_path, workers=0, device_augment=1,
                    mean_img=mean_path)
    assert it._mean.shape == (3, 32, 32)
    assert os.path.isfile(mean_path)
    assert 0.0 < float(it._mean.mean()) < 255.0
    it.close()


def test_score_restores_training_prologue(rec_path):
    """fit with a device-augment train iter AND a device-augment eval
    iter of a different raw pre-shape: score() adopts the eval prologue
    for its pass only, and the next train epoch's fused step must see
    the train prologue (raw 36x36 train batches vs 40x40 eval batches
    would otherwise shape-clash, or silently lose augmentation)."""
    train_it = _make_iter(rec_path, workers=0, device_augment=1)  # pre 36x36
    val_it = _make_iter(rec_path, workers=0, device_augment=1,
                        rand_crop=False, rand_mirror=False,
                        data_shape=(3, 40, 40), shuffle=False)  # pre 40x40

    data = mx.sym.Variable("data")
    net = mx.sym.Pooling(data, kernel=(8, 8), stride=(8, 8),
                         pool_type="avg")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=7, name="fc")
    sym = mx.sym.SoftmaxOutput(net, name="softmax")

    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.fit(train_it, eval_data=None, num_epoch=1, optimizer="sgd",
            initializer=mx.initializer.Xavier(), eval_metric="acc")
    assert mod._input_prologue is train_it.device_prologue

    mod2 = mx.mod.Module(sym, context=mx.cpu())
    mod2.bind(data_shapes=val_it.provide_data,
              label_shapes=val_it.provide_label, for_training=True)
    mod2.init_params(mx.initializer.Xavier())
    mod2.init_optimizer()
    mod2.set_input_prologue(val_it.device_prologue)
    prev = mod2._input_prologue
    other = _make_iter(rec_path, workers=0, device_augment=1,
                       rand_crop=False, rand_mirror=False,
                       data_shape=(3, 40, 40), shuffle=False)
    mod2.score(other, "acc")
    assert mod2._input_prologue is prev  # restored after the pass
    for it in (train_it, val_it, other):
        it.close()


def test_fit_plain_iter_clears_stale_prologue(rec_path):
    """fit on a device-augment iterator, then fit the SAME module on a
    plain final-format iterator of a different shape (force_rebind):
    the stale prologue must be uninstalled, not left to reject the new
    batches' shape."""
    train_it = _make_iter(rec_path, workers=0, device_augment=1)
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(mx.sym.Flatten(data), num_hidden=7,
                                name="fc")
    sym = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.fit(train_it, num_epoch=1, optimizer="sgd",
            initializer=mx.initializer.Xavier(), eval_metric="acc")
    assert mod._input_prologue is train_it.device_prologue

    rng = np.random.RandomState(3)
    plain = mx.io.NDArrayIter(rng.rand(24, 5, 6, 6).astype(np.float32),
                              rng.randint(0, 7, 24).astype(np.float32),
                              batch_size=8, label_name="softmax_label")
    mod.fit(plain, num_epoch=1, optimizer="sgd",
            initializer=mx.initializer.Xavier(), eval_metric="acc",
            force_rebind=True, force_init=True)
    assert mod._input_prologue is None
    train_it.close()


def test_fit_with_eval_data_prologue_swap(rec_path):
    """End-to-end: fit(train device-augment iter, eval_data=different
    device-augment iter) across 2 epochs — epoch 2 trains fine after
    score() swapped prologues at the epoch-1 boundary."""
    train_it = _make_iter(rec_path, workers=0, device_augment=1)
    val_it = _make_iter(rec_path, workers=0, device_augment=1,
                        rand_crop=False, rand_mirror=False,
                        shuffle=False)  # same data_shape, pre 32x32
    data = mx.sym.Variable("data")
    net = mx.sym.Flatten(data)
    net = mx.sym.FullyConnected(net, num_hidden=7, name="fc")
    sym = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.fit(train_it, eval_data=val_it, num_epoch=2, optimizer="sgd",
            initializer=mx.initializer.Xavier(), eval_metric="acc")
    assert mod._input_prologue is train_it.device_prologue
    train_it.close()
    val_it.close()


# ---------------------------------------------------------------------------
# multi-process pool: determinism, resume, self-healing (slow)
# ---------------------------------------------------------------------------

@multiproc
@pytest.mark.slow
def test_pool_matches_single_process_two_epochs(rec_path):
    it0 = _make_iter(rec_path, workers=0)
    ref = [_drain(it0)]
    it0.reset()
    ref.append(_drain(it0))
    it0.close()

    it2 = _make_iter(rec_path, workers=2)
    for epoch_ref in ref:
        got = _drain(it2)
        assert len(got) == len(epoch_ref) == 5
        for (d1, l1, p1), (d2, l2, p2) in zip(epoch_ref, got):
            np.testing.assert_array_equal(d1, d2)
            np.testing.assert_array_equal(l1, l2)
            assert p1 == p2
        it2.reset()
    it2.close()


@multiproc
@pytest.mark.slow
def test_pool_state_resume_mid_epoch(rec_path):
    it = _make_iter(rec_path, workers=2)
    for _ in range(2):
        next(it)
    state = it.state_dict()
    rest_ref = _drain(it)
    it.close()

    np.random.seed(999)  # different ambient RNG must not matter
    it2 = _make_iter(rec_path, workers=2)
    next(it2)  # move somewhere else first
    it2.set_state(state)
    rest = _drain(it2)
    assert len(rest) == len(rest_ref) == 3
    for (d1, l1, p1), (d2, l2, p2) in zip(rest_ref, rest):
        np.testing.assert_array_equal(d1, d2)
        np.testing.assert_array_equal(l1, l2)
        assert p1 == p2
    # the restored epoch RNG stream continues identically
    it2.reset()
    n = sum(1 for _ in it2)
    assert n == 5
    it2.close()


@multiproc
@pytest.mark.slow
def test_pool_state_resume_epoch_boundary_rewind(rec_path):
    """rewind=True (the PrefetchingIter wrapping contract) restores the
    epoch-level state but positions at the epoch START."""
    it = _make_iter(rec_path, workers=2)
    epoch_ref = _drain(it)  # consume the whole epoch
    state = it.state_dict()
    it.close()

    it2 = _make_iter(rec_path, workers=2)
    it2.set_state(state, rewind=True)
    replay = _drain(it2)
    assert len(replay) == len(epoch_ref) == 5
    for (d1, l1, _), (d2, l2, _) in zip(epoch_ref, replay):
        np.testing.assert_array_equal(d1, d2)
        np.testing.assert_array_equal(l1, l2)
    it2.close()

    # non-rewind restore of the same end-of-epoch snapshot: positioned
    # AT the epoch end, and the next epoch proceeds normally
    it3 = _make_iter(rec_path, workers=2)
    it3.set_state(state)
    assert it3.iter_next() is False
    it3.reset()
    assert len(_drain(it3)) == 5
    it3.close()


@multiproc
@pytest.mark.slow
def test_pool_through_prefetching_iter_resume(rec_path):
    """The PR-5 contract end-to-end: PrefetchingIter(pool iter)
    state_dict/set_state round-trips (workers torn down, order
    restored, rebuilt + skipped to the consumer position)."""
    it = mx.io.PrefetchingIter(_make_iter(rec_path, workers=2))
    consumed = [next(it).data[0].asnumpy().copy() for _ in range(2)]
    state = it.state_dict()
    rest_ref = [b.data[0].asnumpy().copy() for b in it]
    it.close()

    it2 = mx.io.PrefetchingIter(_make_iter(rec_path, workers=2))
    it2.set_state(state)
    rest = [b.data[0].asnumpy().copy() for b in it2]
    assert len(rest) == len(rest_ref) == 3
    for d1, d2 in zip(rest_ref, rest):
        np.testing.assert_array_equal(d1, d2)
    it2.close()
    del consumed


@multiproc
@pytest.mark.slow
def test_pool_kill_one_worker_self_heals(rec_path):
    """SIGKILL one decode worker mid-epoch: the pool rebuilds and the
    epoch completes with no dropped or duplicated batch."""
    it0 = _make_iter(rec_path, workers=0)
    ref = _drain(it0)
    it0.close()

    # ring_slots=2 keeps producers at most one batch ahead, so the
    # killed worker is GUARANTEED to still owe a batch (batch 3 can't
    # be produced until batch 1 is consumed) — the rebuild must fire
    it = _make_iter(rec_path, workers=2, ring_slots=2)
    first = next(it)
    np.testing.assert_array_equal(first.data[0].asnumpy(), ref[0][0])
    os.kill(it._dpool.worker_pids[1], signal.SIGKILL)
    rest = _drain(it)
    assert len(rest) == len(ref) - 1
    for (d2, l2, p2), (d1, l1, p1) in zip(rest, ref[1:]):
        np.testing.assert_array_equal(d1, d2)
        np.testing.assert_array_equal(l1, l2)
        assert p1 == p2
    assert it._dpool._rebuilds == 1
    # the healed pool serves the next epoch too
    it.reset()
    assert len(_drain(it)) == len(ref)
    it.close()


@multiproc
@pytest.mark.slow
def test_pool_workers_survive_fence_lock_held_at_fork(rec_path, monkeypatch):
    """A fork taken while another trainer thread sits inside _fence()
    (e.g. a second pool's PrefetchingIter producer) must not wedge the
    fresh workers: each child re-creates _FENCE_LOCK instead of
    inheriting it in the held state."""
    import threading

    ref0 = _make_iter(rec_path, workers=0, shuffle=False)
    ref = _drain(ref0)
    ref0.close()

    # fail fast if the regression returns: wedged workers would trip
    # the stall watchdog and self-heal via a rebuild, which we detect
    monkeypatch.setattr(io_pool.DecodePool, "stall_timeout_s", 2.0)

    acquired = threading.Event()
    release = threading.Event()

    def holder():
        with io_pool._FENCE_LOCK:
            acquired.set()
            release.wait(30)

    # the pool forks lazily inside the first next(); release the lock
    # exactly when the fork is done so it is HELD across every fork
    orig_spawn = io_pool.DecodePool._spawn

    def spawn_then_release(self):
        try:
            return orig_spawn(self)
        finally:
            release.set()

    monkeypatch.setattr(io_pool.DecodePool, "_spawn", spawn_then_release)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    assert acquired.wait(5)
    it = _make_iter(rec_path, workers=2, shuffle=False)
    got = _drain(it)  # first next() forks the pool under the held lock
    release.set()  # in case the pool never spawned (construction raise)
    t.join(5)
    assert it._dpool._rebuilds == 0  # no stall-watchdog heal was needed
    assert len(got) == len(ref)
    for (d1, l1, p1), (d2, l2, p2) in zip(ref, got):
        np.testing.assert_array_equal(d1, d2)
        np.testing.assert_array_equal(l1, l2)
        assert p1 == p2
    it.close()


@multiproc
@pytest.mark.slow
def test_worker_does_not_run_inherited_sigterm_handler(rec_path, tmp_path):
    """Workers must reset SIGTERM to SIG_DFL: a trainer-installed
    handler (CheckpointManager's emergency save) run inside a forked
    decode worker would enter jax and write into the live checkpoint
    dir.  SIGTERM must simply kill the worker (and the pool heals)."""
    sentinel = tmp_path / "handler_ran"
    prev = signal.signal(
        signal.SIGTERM,
        lambda *_: sentinel.write_text("from pid %d" % os.getpid()))
    try:
        it = _make_iter(rec_path, workers=2, ring_slots=2)
        next(it)  # pool forked with the handler installed in the parent
        os.kill(it._dpool.worker_pids[1], signal.SIGTERM)
        rest = _drain(it)  # self-heal completes the epoch
        assert len(rest) == 4
        assert it._dpool._rebuilds == 1
        it.close()
        assert not sentinel.exists(), sentinel.read_text()
    finally:
        signal.signal(signal.SIGTERM, prev)


@multiproc
@pytest.mark.slow
def test_pool_wedged_alive_worker_trips_stall_watchdog(rec_path, tmp_path,
                                                       monkeypatch):
    """A worker wedged ALIVE in native code (cv2 spinning on a
    pathological JPEG) never fails is_alive(): the stall watchdog must
    rebuild instead of hanging fit.step forever.  The wedge clears
    after the first attempt (flag file), proving self-heal with no
    dropped or duplicated batch."""
    from mxnet_tpu.io_record import ImageRecordIter

    ref0 = _make_iter(rec_path, workers=0, shuffle=False)
    ref = _drain(ref0)  # before the patch: the parent must not wedge
    ref0.close()

    orig = ImageRecordIter._decode_batch_into
    flag = tmp_path / "wedged_once"
    target = set(range(8, 16))  # batch 1 of the shuffle=False order

    def wedging(self, idxs, epoch, data_out, label_out):
        if {int(i) for i in np.asarray(idxs)} == target and \
                not flag.exists():
            flag.touch()
            time.sleep(120)  # killed by the rebuild teardown long before
        return orig(self, idxs, epoch, data_out, label_out)

    # patch the CLASS before the pool forks so workers inherit it
    monkeypatch.setattr(ImageRecordIter, "_decode_batch_into", wedging)
    monkeypatch.setattr(io_pool.DecodePool, "stall_timeout_s", 2.0)
    it = _make_iter(rec_path, workers=2, shuffle=False)
    got = _drain(it)
    assert len(got) == len(ref) == 5
    for (d1, l1, p1), (d2, l2, p2) in zip(ref, got):
        np.testing.assert_array_equal(d1, d2)
        np.testing.assert_array_equal(l1, l2)
        assert p1 == p2
    assert it._dpool._rebuilds == 1
    assert flag.exists()
    it.close()


@multiproc
@pytest.mark.slow
def test_pool_poisoned_batch_raises_after_capped_rebuilds(rec_path,
                                                          monkeypatch):
    """A worker that dies deterministically on the SAME batch (e.g. a
    corrupt record segfaulting cv2) must fail the epoch loudly after
    the rebuild cap — not self-heal in an infinite loop."""
    from mxnet_tpu.io_record import ImageRecordIter

    orig = ImageRecordIter._decode_batch_into
    target = set(range(8, 16))  # batch 1 of the shuffle=False order

    def poisoned(self, idxs, epoch, data_out, label_out):
        if {int(i) for i in np.asarray(idxs)} == target:
            os._exit(17)  # simulate a native decoder crash
        return orig(self, idxs, epoch, data_out, label_out)

    # patch the CLASS before the pool forks so workers inherit it
    monkeypatch.setattr(ImageRecordIter, "_decode_batch_into", poisoned)
    it = _make_iter(rec_path, workers=1, shuffle=False)
    next(it)  # batch 0 decodes fine
    dpool = it._dpool
    with pytest.raises(mx.MXNetError, match="batch 1"):
        _drain(it)
    assert dpool._rebuilds >= 3
    # the fatal error released the fleet and the ring: no surviving
    # workers left busy-polling, no shm pinned until iterator GC
    assert dpool._procs == [] and dpool._shm_data is None
    assert it._dpool is None
    it.close()


def test_prologue_rejected_without_module_support(rec_path):
    """Module kinds that cannot host the device prologue (no
    set_input_prologue — e.g. SequentialModule) must refuse a
    device_augment iterator loudly, not silently feed raw uint8 NHWC
    batches to a final-shape executor."""
    from mxnet_tpu.module.base_module import BaseModule

    class Plain(BaseModule):
        pass

    m = Plain.__new__(Plain)
    it = _make_iter(rec_path, workers=0, device_augment=1)
    with pytest.raises(mx.MXNetError, match="device-side"):
        m._install_data_prologue(it)
    it.close()
    # a plain iterator has nothing to drop: stays a no-op
    plain = mx.io.NDArrayIter(np.zeros((8, 4), np.float32), np.zeros(8),
                              batch_size=4)
    m._install_data_prologue(plain)


def test_predict_installs_prologue(rec_path):
    """predict()/iter_predict() on a device-augment iterator must adopt
    its prologue for the pass (raw uint8 NHWC batches would otherwise
    hit the executor's final-shape arg buffers) and restore the prior
    prologue afterwards."""
    it = _make_iter(rec_path, workers=0, device_augment=1, shuffle=False)
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(mx.sym.Flatten(data), num_hidden=7,
                                name="fc")
    sym = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=False)
    mod.init_params(mx.initializer.Xavier())
    out = mod.predict(it)
    assert out.shape == (40, 7)
    assert mod._input_prologue is None  # restored to the pre-pass state
    n = sum(1 for _ in mod.iter_predict(it))
    assert n == 5
    assert mod._input_prologue is None
    it.close()


@multiproc
@pytest.mark.slow
def test_fit_device_augment_bitexact_across_worker_counts(rec_path):
    """Two full fused-step fits over the pool+device-augment path must
    produce identical weights for workers=0 and workers=2 — scheduling
    never leaks into the numerics."""
    sys.path.insert(0, os.path.join(REPO, "tests"))
    import io_pool_crash_worker as W

    with tempfile.TemporaryDirectory() as td:
        rec = os.path.join(td, "r")
        W.pack_dataset(rec)
        w0 = W.train(rec, ckpt_dir=None, num_epoch=2, workers=0)
        w2 = W.train(rec, ckpt_dir=None, num_epoch=2, workers=2)
    assert set(w0) == set(w2)
    for k in w0:
        np.testing.assert_array_equal(w0[k], w2[k], err_msg=k)


@multiproc
@pytest.mark.slow
def test_pool_fit_kill9_and_resume_bitexact(tmp_path):
    """Acceptance: kill -9 a pool-mode (workers=2, device_augment=1)
    fit mid-epoch, relaunch with resume='auto' — final weights bit-match
    an uninterrupted run.  Extends the test_dist kill-and-resume proof
    across decode worker processes and device-side augmentation."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("MXNET_CKPT_DIR", None)
    worker = os.path.join(REPO, "tests", "io_pool_crash_worker.py")
    rec = str(tmp_path / "data")

    # uninterrupted reference
    d_a, out_a = str(tmp_path / "ckpt_a"), str(tmp_path / "a.npz")
    r = subprocess.run(
        [sys.executable, worker, "--rec", rec, "--ckpt-dir", d_a,
         "--out", out_a, "--every-n", "2"],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr

    # crash run: SIGKILL the trainer after a few steps (a checkpoint
    # has committed by then at every_n=2)
    d_b, out_b = str(tmp_path / "ckpt_b"), str(tmp_path / "b.npz")
    p = subprocess.Popen(
        [sys.executable, worker, "--rec", rec, "--ckpt-dir", d_b,
         "--out", out_b, "--every-n", "2", "--sleep", "0.05",
         "--progress"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=REPO, env=env)
    try:
        deadline = time.time() + 300
        seen = []
        while time.time() < deadline:
            line = p.stdout.readline()
            if not line:
                break
            seen.append(line)
            if "BATCH 4" in line:
                break
        assert any("BATCH 4" in l for l in seen), "".join(seen)
        p.kill()  # SIGKILL: no cleanup, no emergency save
        p.wait(timeout=60)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
    assert not os.path.exists(out_b)

    from mxnet_tpu import checkpoint as C
    assert any(i.committed for i in C.list_checkpoints(d_b))

    # resume run: must land on the uninterrupted run's exact weights
    r = subprocess.run(
        [sys.executable, worker, "--rec", rec, "--ckpt-dir", d_b,
         "--out", out_b, "--every-n", "2"],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "resuming from" in (r.stdout + r.stderr)

    ref = dict(np.load(out_a))
    res = dict(np.load(out_b))
    assert set(ref) == set(res)
    for k in ref:
        np.testing.assert_array_equal(
            ref[k], res[k],
            err_msg=f"{k}: resumed weights diverge from uninterrupted run")
