"""Elastic 2→1→2 chaos-drill worker.

Launched by ``tools/chaos_drill.py`` (and the slow test in
tests/test_dist.py) with ``MXNET_ELASTIC=1``: each rank trains
``Module.fit`` with ``kvstore='dist_sync'`` (elastic mode forces the
reconnectable server-sync PS transport) on its membership-dependent
shard of a FIXED global batch layout, checkpointing synchronously
every 4 steps.  The drill kills rank 1 with ``MXNET_CHAOS_KILL_STEP``
(SIGKILL — no goodbye): rank 0's next sync round times out, the stale
heartbeat turns that into a DeadRankError verdict, and fit re-meshes
to dp'=1, re-scatters the last committed checkpoint onto the surviving
shard, rolls back, and keeps training.  The drill then respawns rank 1
with ``MXNET_ELASTIC_JOIN=1``; it files a join request, is admitted at
rank 0's next checkpoint boundary, restores from that checkpoint, and
both ranks finish together.  Because the global batch sequence is
membership-invariant and rollback replays from committed state, the
final weights must converge to an uninterrupted single-process run on
the union data (asserted by the drill within tolerance).

Prints one machine-readable line::

    ELASTIC_WORKER rank=<r> steps=<n> max_gap_s=<s> remesh=<n> \
        verdicts=<n> joins=<n> reconnects=<n>
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx

GLOBAL_BATCH = 8
N_SAMPLES = 64
EPOCHS = 3
CLASSES = 4
FEATURES = 16


def build_sym():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=24, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def make_data():
    rng = np.random.RandomState(5)
    X = rng.randn(N_SAMPLES, FEATURES).astype(np.float32)
    y = rng.randint(0, CLASSES, N_SAMPLES).astype(np.float32)
    return X, y


def elastic_iter(X, y, rank, active):
    """This rank's shard of the FIXED global batch layout under the
    given membership: global batch g is split contiguously among the
    sorted active ranks, so batch INDICES mean the same thing at any
    world size — the invariant elastic repositioning relies on."""
    active = sorted(active)
    B = GLOBAL_BATCH // len(active)
    pos = active.index(rank)
    idx = []
    for g in range(N_SAMPLES // GLOBAL_BATCH):
        start = g * GLOBAL_BATCH + pos * B
        idx.extend(range(start, start + B))
    return mx.io.NDArrayIter(X[idx], y[idx], batch_size=B, shuffle=False,
                             label_name="softmax_label")


def train_reference():
    """Uninterrupted single-process run on the union data — the
    convergence target of the drill."""
    X, y = make_data()
    mx.random.seed(7)
    np.random.seed(7)
    it = mx.io.NDArrayIter(X, y, batch_size=GLOBAL_BATCH, shuffle=False,
                           label_name="softmax_label")
    mod = mx.mod.Module(build_sym(), context=mx.cpu())
    mod.fit(it, num_epoch=EPOCHS, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05,
                              "rescale_grad": 1.0 / GLOBAL_BATCH},
            kvstore=None,
            initializer=mx.initializer.Xavier(rnd_type="gaussian"),
            eval_metric="acc")
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args.items()}


def main():
    import logging

    logging.basicConfig(level=logging.INFO)
    ckpt_dir, out_prefix = sys.argv[1], sys.argv[2]
    kv = mx.kv.create("dist_sync")
    rank = kv.rank
    X, y = make_data()
    it = elastic_iter(X, y, rank, kv.active_ranks)

    mx.random.seed(7)
    np.random.seed(7)
    mod = mx.mod.Module(build_sym(), context=mx.cpu())
    cadence = int(os.environ.get("ELASTIC_CKPT_EVERY", "4"))
    mgr = mx.CheckpointManager(ckpt_dir, every_n_steps=cadence,
                               async_save=False, keep=20, kvstore=kv)
    step_times = []
    mod.fit(it, num_epoch=EPOCHS, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05,
                              "rescale_grad": 1.0 / GLOBAL_BATCH},
            kvstore=kv,
            initializer=mx.initializer.Xavier(rnd_type="gaussian"),
            eval_metric="acc", checkpoint=mgr, resume="auto",
            elastic_data=lambda active: elastic_iter(X, y, rank, active),
            batch_end_callback=lambda p: step_times.append(time.time()))
    mgr.close()
    args_, _ = mod.get_params()
    np.savez(out_prefix + f".rank{rank}",
             **{k: v.asnumpy() for k, v in args_.items()})
    kv.barrier()

    from mxnet_tpu import profiler as prof

    counters = prof.metrics_summary().get("counters", {})

    def count(name):
        return int(counters.get(name, 0) or 0)

    gaps = [b - a for a, b in zip(step_times, step_times[1:])]
    print(f"ELASTIC_WORKER rank={rank} steps={len(step_times)} "
          f"max_gap_s={max(gaps) if gaps else 0.0:.2f} "
          f"remesh={count('elastic.remesh')} "
          f"verdicts={count('elastic.dead_rank_verdicts')} "
          f"joins={count('elastic.joins')} "
          f"reconnects={count('ps.reconnects')}", flush=True)


if __name__ == "__main__":
    main()
