"""Executor tests (modeled on tests/python/unittest/test_executor.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def test_bind_simple():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = a + b
    av = np.random.rand(3, 4).astype(np.float32)
    bv = np.random.rand(3, 4).astype(np.float32)
    ga = mx.nd.zeros((3, 4))
    gb = mx.nd.zeros((3, 4))
    ex = c.bind(mx.cpu(), {"a": mx.nd.array(av), "b": mx.nd.array(bv)},
                args_grad={"a": ga, "b": gb})
    out = ex.forward(is_train=True)
    assert_almost_equal(out[0].asnumpy(), av + bv)
    head = np.random.rand(3, 4).astype(np.float32)
    ex.backward([mx.nd.array(head)])
    assert_almost_equal(ga.asnumpy(), head)
    assert_almost_equal(gb.asnumpy(), head)


def test_grad_req_add():
    a = mx.sym.Variable("a")
    out = a * 2.0
    ga = mx.nd.zeros((2, 2))
    ex = out.bind(mx.cpu(), {"a": mx.nd.ones((2, 2))}, args_grad={"a": ga},
                  grad_req="add")
    for i in range(3):
        ex.forward(is_train=True)
        ex.backward([mx.nd.ones((2, 2))])
    assert_almost_equal(ga.asnumpy(), np.full((2, 2), 6.0))


def test_simple_bind_and_outputs():
    x = mx.sym.Variable("x")
    y = mx.sym.FullyConnected(x, num_hidden=3, name="fc")
    ex = y.simple_bind(mx.cpu(), x=(5, 7))
    assert set(ex.arg_dict) == {"x", "fc_weight", "fc_bias"}
    assert ex.arg_dict["fc_weight"].shape == (3, 7)
    ex.arg_dict["x"][:] = 1.0
    ex.arg_dict["fc_weight"][:] = 1.0
    ex.arg_dict["fc_bias"][:] = 0.5
    out = ex.forward()[0]
    assert_almost_equal(out.asnumpy(), np.full((5, 3), 7.5), rtol=1e-5)


def test_executor_reshape():
    x = mx.sym.Variable("x")
    y = mx.sym.FullyConnected(x, num_hidden=4, name="fc")
    ex = y.simple_bind(mx.cpu(), x=(2, 6))
    ex.arg_dict["fc_weight"][:] = 1.0
    ex2 = ex.reshape(partial_shaping=True, x=(8, 6))
    assert ex2.arg_dict["x"].shape == (8, 6)
    assert ex2.arg_dict["fc_weight"] is ex.arg_dict["fc_weight"]
    ex2.arg_dict["x"][:] = 1.0
    out = ex2.forward()[0]
    assert out.shape == (8, 4)
    assert_almost_equal(out.asnumpy(), np.full((8, 4), 6.0), rtol=1e-5)


def test_forward_kwargs_update():
    x = mx.sym.Variable("x")
    y = x * 3.0
    ex = y.simple_bind(mx.cpu(), grad_req="null", x=(2, 2))
    out = ex.forward(x=mx.nd.ones((2, 2)))
    assert_almost_equal(out[0].asnumpy(), np.full((2, 2), 3.0))
    out = ex.forward(x=np.full((2, 2), 2.0, np.float32))
    assert_almost_equal(out[0].asnumpy(), np.full((2, 2), 6.0))


def test_aux_state_update():
    data = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data, name="bn", momentum=0.5, fix_gamma=True)
    ex = bn.simple_bind(mx.cpu(), data=(4, 3))
    assert set(ex.aux_dict) == {"bn_moving_mean", "bn_moving_var"}
    x = np.random.rand(4, 3).astype(np.float32) + 3.0
    ex.arg_dict["bn_gamma"][:] = 1.0
    ex.forward(is_train=True, data=x)
    # moving mean moved halfway toward batch mean (momentum=0.5)
    assert_almost_equal(ex.aux_dict["bn_moving_mean"].asnumpy(),
                        0.5 * x.mean(0), rtol=1e-4)


def test_multi_output_executor():
    d = mx.sym.Variable("d")
    s = mx.sym.SliceChannel(d, num_outputs=2, axis=1, name="sp")
    grp = mx.sym.Group([s[0] * 1.0, s[1] * 2.0])
    x = np.random.rand(3, 4).astype(np.float32)
    ex = grp.bind(mx.cpu(), {"d": mx.nd.array(x)})
    o1, o2 = ex.forward()
    assert_almost_equal(o1.asnumpy(), x[:, :2])
    assert_almost_equal(o2.asnumpy(), x[:, 2:] * 2.0)


def test_monitor_callback():
    taps = {}
    x = mx.sym.Variable("x")
    y = mx.sym.FullyConnected(x, num_hidden=2, name="fc")
    ex = y.simple_bind(mx.cpu(), x=(1, 2))
    ex.set_monitor_callback(lambda name, arr: taps.setdefault(name, arr.shape))
    ex.forward()
    assert any("fc" in k for k in taps)
