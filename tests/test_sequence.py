"""Sequence/context parallelism tests on the virtual 8-device CPU mesh:
ring attention and Ulysses must equal single-device attention exactly
(same online-softmax math, different partitioning)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sequence as seq
from mxnet_tpu.ops.attention import blockwise_attention


def _np_attention(q, k, v, causal=False):
    B, T, H, D = q.shape
    q64, k64, v64 = [x.astype(np.float64) for x in (q, k, v)]
    s = np.einsum("bqhd,bkhd->bhqk", q64, k64) / np.sqrt(D)
    if causal:
        mask = np.tril(np.ones((T, T), bool))
        s = np.where(mask[None, None], s, -np.inf)
    s = s - s.max(axis=-1, keepdims=True)
    p = np.exp(s)
    p /= p.sum(axis=-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v64)


def _qkv(B=2, T=32, H=4, D=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: (rng.randn(B, T, H, D) * 0.5).astype(np.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_attention_matches_numpy(causal):
    q, k, v = _qkv()
    out = np.asarray(blockwise_attention(q, k, v, causal=causal,
                                         block_size=8))
    expect = _np_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_attention_op_symbol_and_imperative():
    q, k, v = _qkv()
    out = mx.nd.DotProductAttention(mx.nd.array(q), mx.nd.array(k),
                                    mx.nd.array(v), causal="True")
    np.testing.assert_allclose(out.asnumpy(),
                               _np_attention(q, k, v, causal=True),
                               rtol=1e-4, atol=1e-5)
    sym = mx.sym.DotProductAttention(mx.sym.Variable("q"),
                                     mx.sym.Variable("k"),
                                     mx.sym.Variable("v"))
    _, out_shapes, _ = sym.infer_shape(q=q.shape, k=k.shape, v=v.shape)
    assert out_shapes == [q.shape]


@pytest.mark.parametrize("sp", [2, 4, 8])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_equals_single_device(sp, causal):
    import jax

    if len(jax.devices()) < sp:
        pytest.skip("needs virtual device mesh")
    q, k, v = _qkv(T=40 if sp != 8 else 32)
    mesh = seq.sequence_mesh(sp=sp)
    if q.shape[1] % sp:
        pytest.skip("seq not divisible")
    out = np.asarray(seq.ring_attention(q, k, v, mesh, causal=causal,
                                        block_size=8))
    expect = _np_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_equals_single_device(causal):
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs virtual device mesh")
    q, k, v = _qkv(T=32, H=4)
    mesh = seq.sequence_mesh(sp=4)
    out = np.asarray(seq.ulysses_attention(q, k, v, mesh, causal=causal,
                                           block_size=8))
    expect = _np_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_ring_attention_gradients():
    """Differentiable through the ring: grads match single-device."""
    import jax
    import jax.numpy as jnp

    if len(jax.devices()) < 4:
        pytest.skip("needs virtual device mesh")
    q, k, v = _qkv(T=16, H=2, D=4, seed=3)
    mesh = seq.sequence_mesh(sp=4)

    def loss_ring(q, k, v):
        return jnp.sum(seq.ring_attention(q, k, v, mesh, causal=True,
                                          block_size=4) ** 2)

    def loss_local(q, k, v):
        return jnp.sum(blockwise_attention(q, k, v, causal=True,
                                           block_size=4) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gl = jax.grad(loss_local, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gl):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)


def test_long_context_memory_scaling():
    """The selling point: per-shard attention state is O(T/sp), so an
    8-shard ring handles a sequence whose full score matrix would be
    512x larger than any block it ever materializes."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs virtual device mesh")
    rng = np.random.RandomState(0)
    B, T, H, D = 1, 4096, 2, 16
    q = (rng.randn(B, T, H, D) * 0.3).astype(np.float32)
    k = (rng.randn(B, T, H, D) * 0.3).astype(np.float32)
    v = (rng.randn(B, T, H, D) * 0.3).astype(np.float32)
    mesh = seq.sequence_mesh(sp=8)
    out = np.asarray(seq.ring_attention(q, k, v, mesh, causal=True,
                                        block_size=128))
    assert out.shape == (B, T, H, D)
    assert np.isfinite(out).all()
    # spot-check a few rows against exact attention on a subset
    expect = _np_attention(q[:, :256], k[:, :256], v[:, :256], causal=True)
    np.testing.assert_allclose(out[:, :256], expect, rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("fn", ["ring", "ulysses"])
@pytest.mark.parametrize("q_offset", [0, 8, 24])
def test_decode_layout_chunk_vs_full_forward(fn, q_offset):
    """Decode-time K/V-gathered layout: q is ONE chunk of a long
    prompt at absolute offset ``q_offset`` while k/v span the whole
    gathered history — the shape the chunked-prefill state machine
    feeds when a prompt outgrows one chip's prefill ladder.  The
    chunk's rows must match the same rows of the lax full causal
    forward."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs virtual device mesh")
    T_kv, T_q, sp = 32, 8, 4
    q_full, k, v = _qkv(T=T_kv, H=4)
    q = q_full[:, q_offset:q_offset + T_q]
    mesh = seq.sequence_mesh(sp=sp)
    run = seq.ring_attention if fn == "ring" else seq.ulysses_attention
    out = np.asarray(run(q, k, v, mesh, causal=True, block_size=8,
                         q_offset=q_offset))
    full = np.asarray(blockwise_attention(q_full, k, v, causal=True,
                                          block_size=8))
    np.testing.assert_allclose(out, full[:, q_offset:q_offset + T_q],
                               rtol=1e-4, atol=1e-5)


def test_decode_layout_uneven_chunk_cover():
    """Chunks tiled over the prompt reproduce the full forward row
    range by row range (the suffix-prefill continuation contract)."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs virtual device mesh")
    T_kv, chunk, sp = 32, 16, 4
    q_full, k, v = _qkv(T=T_kv, H=4, seed=5)
    mesh = seq.sequence_mesh(sp=sp)
    full = np.asarray(blockwise_attention(q_full, k, v, causal=True,
                                          block_size=8))
    for off in range(0, T_kv, chunk):
        q = q_full[:, off:off + chunk]
        out = np.asarray(seq.ring_attention(q, k, v, mesh, causal=True,
                                            block_size=8, q_offset=off))
        np.testing.assert_allclose(out, full[:, off:off + chunk],
                                   rtol=1e-4, atol=1e-5)
