"""Speculative decoding + chunked prefill tests.

The contracts, in order of appearance:

* the n-gram proposer is a deterministic function of the context;
* the verify op scores W window positions bit-identically (lax path)
  to W sequential single-query decode steps over the same cache bytes
  — the whole greedy-bit-identity story rests on this;
* the Pallas k-query verify kernel (interpret mode) matches the lax
  fallback;
* speculative greedy engine chains are BIT-identical to
  non-speculative greedy ones, including across batch-composition
  changes and prefix-cache hits;
* temperature sampling with rejection matches the target distribution
  exactly (chi-square on a tiny vocab) and a no-draft row is
  bit-identical to the plain sampler;
* chunked prefill bit-matches monolithic prefill;
* the new MXNET_SERVING_* vars validate loudly.

Fast variants run in tier-1 (the ~5s propose→verify→accept/reject→
continue smoke); the wide multi-stream sweeps are marked ``slow``
(the PR 7/13 pattern).
"""

import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models
from mxnet_tpu.kv_cache import trim_blocks
from mxnet_tpu.speculative import NgramProposer, make_proposer

V, KVB, L, H, DM, MAXLEN = 61, 4, 2, 2, 32, 48


@pytest.fixture(scope="module")
def lm():
    sym = models.transformer_lm(V, MAXLEN, num_layers=L, num_heads=H,
                                d_model=DM, block_size=KVB)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, MAXLEN))],
             label_shapes=[("softmax_label", (2, MAXLEN))],
             for_training=False)
    mod.init_params(mx.initializer.Xavier(factor_type="in",
                                          magnitude=2.0))
    arg, aux = mod.get_params()
    return {**arg, **aux}


def _engine(params, **kw):
    args = dict(vocab_size=V, num_layers=L, num_heads=H, d_model=DM,
                max_len=MAXLEN, kv_block=KVB, max_streams=4,
                decode_buckets=[1, 2, 4], temperature=0.0)
    args.update(kw)
    return mx.DecodeEngine(params, **args)


def _repetitive_prompt(rng, n=18, motif=5):
    m = rng.randint(1, V, size=motif).astype(np.int32)
    return np.tile(m, -(-n // motif))[:n]


# ---------------------------------------------------------------------------
# proposer
# ---------------------------------------------------------------------------


def test_ngram_proposer_deterministic():
    p = NgramProposer()
    ctx = np.array([1, 2, 3, 4, 1, 2, 3], np.int32)
    # trailing [1,2,3] recurs at the start -> propose its continuation
    np.testing.assert_array_equal(p.propose(ctx, 4), [4, 1, 2, 3])
    np.testing.assert_array_equal(p.propose(ctx, 2), [4, 1])
    # same context, same proposal — determinism is what fleet decode
    # retries re-propose from
    np.testing.assert_array_equal(p.propose(ctx, 4),
                                  p.propose(ctx, 4))
    # no recurrence -> nothing proposed
    assert p.propose(np.arange(1, 9, dtype=np.int32), 4).size == 0
    # most RECENT occurrence wins: ...5,9 ... 5,7 ... 5 -> continue 7
    ctx2 = np.array([5, 9, 1, 5, 7, 2, 5], np.int32)
    np.testing.assert_array_equal(p.propose(ctx2, 2), [7, 2])
    with pytest.raises(mx.MXNetError):
        make_proposer("banana")


def test_trim_blocks_accounting():
    keep, surplus = trim_blocks([7, 9, 12], 5, 4)  # 5 tokens -> 2 pages
    assert keep == [7, 9] and surplus == [12]
    keep, surplus = trim_blocks([7, 9], 8, 4)
    assert keep == [7, 9] and surplus == []
    keep, surplus = trim_blocks([7], 9, 4)  # already short: no-op
    assert keep == [7] and surplus == []


# ---------------------------------------------------------------------------
# op-level: the verify window IS W sequential decode steps
# ---------------------------------------------------------------------------


def test_verify_op_bitwise_vs_sequential_decode():
    import jax.numpy as jnp

    from mxnet_tpu.ops.attention import (paged_cache_update,
                                         paged_decode_attention,
                                         paged_prefill_write,
                                         paged_verify_attention)

    rng = np.random.RandomState(3)
    P, B, W, start0 = 9, 2, 3, np.array([6, 3], np.int32)
    kp = jnp.asarray(rng.randn(P, KVB, H, 8).astype(np.float32))
    vp = jnp.asarray(rng.randn(P, KVB, H, 8).astype(np.float32))
    table = jnp.asarray(
        np.array([[3, 1, 7, 0], [5, 2, 0, 0]], np.int32))
    q = jnp.asarray(rng.randn(B, W, H, 8).astype(np.float32))
    kw_ = jnp.asarray(rng.randn(B, W, H, 8).astype(np.float32))
    vw = jnp.asarray(rng.randn(B, W, H, 8).astype(np.float32))
    start = jnp.asarray(start0)
    lengths = start + W

    # verify path: write the whole window, one diagonal-masked pass
    kp1, vp1 = paged_prefill_write(kw_, vw, kp, vp, table, lengths,
                                   start=start)
    out_v = np.asarray(paged_verify_attention(q, kp1, vp1, table,
                                              start))

    # sequential path: W single-token decode steps
    kp2, vp2 = kp, vp
    for i in range(W):
        li = start + i + 1
        kp2, vp2 = paged_cache_update(
            kp2, vp2, kw_[:, i:i + 1], vw[:, i:i + 1], table, li)
        out_i = np.asarray(paged_decode_attention(
            q[:, i:i + 1], kp2, vp2, table, li))
        np.testing.assert_array_equal(out_v[:, i:i + 1], out_i)
    # and the pools end up with the same bytes
    np.testing.assert_array_equal(np.asarray(kp1), np.asarray(kp2))
    np.testing.assert_array_equal(np.asarray(vp1), np.asarray(vp2))


def test_pallas_verify_kernel_interpret_matches_lax():
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_kernels as pk
    from mxnet_tpu.ops.attention import paged_verify_attention

    rng = np.random.RandomState(5)
    P, B, W, D = 7, 2, 4, 8
    kp = jnp.asarray(rng.randn(P, KVB, H, D).astype(np.float32))
    vp = jnp.asarray(rng.randn(P, KVB, H, D).astype(np.float32))
    table = jnp.asarray(
        np.array([[2, 5, 1, 0], [4, 3, 0, 0]], np.int32))
    q = jnp.asarray(rng.randn(B, W, H, D).astype(np.float32))
    start = jnp.asarray(np.array([5, 2], np.int32))
    want = np.asarray(paged_verify_attention(q, kp, vp, table, start))
    got = np.asarray(pk.paged_attention_verify(q, kp, vp, table, start))
    np.testing.assert_allclose(got, want, atol=1e-6, rtol=1e-6)


# ---------------------------------------------------------------------------
# the rejection sampler: exact target distribution, exact plain-sampler
# fallback on no-draft rows
# ---------------------------------------------------------------------------


def test_rejection_sampling_matches_target_distribution():
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.speculative import verify_sample

    Vt, N = 13, 4000
    rng = np.random.RandomState(11)
    row = rng.randn(Vt).astype(np.float32) * 1.5
    base = jax.random.PRNGKey(0)
    temp = 0.7
    draft = 4  # a mid-probability token under verification

    logits = jnp.asarray(np.tile(row, (N, 2, 1)))
    fed = jnp.asarray(
        np.tile(np.array([[0, draft]], np.int32), (N, 1)))
    wlive = jnp.full((N,), 2, jnp.int32)
    temps = jnp.full((N,), temp, jnp.float32)
    seeds = jnp.arange(N, dtype=jnp.int32)
    steps0 = jnp.zeros((N,), jnp.int32)
    emit = np.asarray(jax.jit(verify_sample, static_argnums=())(
        base, logits, fed, wlive, temps, seeds, steps0))

    p = np.exp(row / temp - np.max(row / temp))
    p /= p.sum()
    # row 0 verified `draft` by rejection sampling; its marginal must
    # still be the target distribution (chi-square, df=12; the
    # statistic is deterministic — fixed seeds — so no flake margin)
    obs = np.bincount(emit[:, 0], minlength=Vt)
    chi2 = float(np.sum((obs - N * p) ** 2 / (N * p)))
    assert chi2 < 32.9, chi2  # p=0.001 critical for df=12
    # acceptance really happens (the draft is over-represented only
    # up to its own probability): both branches exercised
    assert 0 < np.sum(emit[:, 0] == draft) < N

    # row 1 has no draft: bit-identical to the plain decode sampler's
    # categorical(key, row/temp) at position steps0+1
    def plain(sd):
        key = jax.random.fold_in(jax.random.fold_in(base, sd), 1)
        return jax.random.categorical(
            key, jnp.asarray(row) / temp).astype(jnp.int32)

    want = np.asarray(jax.vmap(plain)(seeds))
    np.testing.assert_array_equal(emit[:, 1], want)

    # greedy rows emit argmax, unconditionally
    emit_g = np.asarray(verify_sample(
        base, logits, fed, wlive, jnp.zeros((N,), jnp.float32), seeds,
        steps0))
    assert (emit_g == int(np.argmax(row))).all()

    # mixed-width batch: a stream whose window is SHORTER than W must
    # get the no-draft plain-sampler path on its bonus row — a padded
    # fed column is not a draft of token 0 (regression: the emitted
    # bits must not depend on how wide the batch's window is)
    logits3 = jnp.asarray(np.tile(row, (N, 3, 1)))
    fed3 = jnp.asarray(
        np.tile(np.array([[0, draft, 0]], np.int32), (N, 1)))
    emit3 = np.asarray(verify_sample(
        base, logits3, fed3, jnp.full((N,), 2, jnp.int32), temps,
        seeds, steps0))
    np.testing.assert_array_equal(emit3[:, 0], emit[:, 0])
    np.testing.assert_array_equal(emit3[:, 1], want)  # bonus == plain


# ---------------------------------------------------------------------------
# engine: the tier-1 propose→verify→accept/reject→continue smoke
# ---------------------------------------------------------------------------


def test_spec_greedy_smoke_bit_identical(lm):
    rng = np.random.RandomState(0)
    prompt = _repetitive_prompt(rng)
    e0 = _engine(lm, spec_tokens=0)
    try:
        ref = e0.generate(prompt, 12)
        st0 = e0.stats()
    finally:
        e0.close()
    # the non-speculative path double-buffered its (B,) fetches
    assert st0["d2h_syncs_saved"] > 0
    assert st0["d2h_syncs"] > st0["d2h_syncs_saved"]
    e1 = _engine(lm, spec_tokens=3)
    try:
        out = e1.generate(prompt, 12)
        st = e1.stats()
        e1.reset_stats()
        st2 = e1.stats()
    finally:
        e1.close()
    np.testing.assert_array_equal(ref, out)
    # the step really speculated: drafts proposed, some accepted, some
    # rejected along the way, and fewer steps than tokens
    assert st["spec_steps"] > 0
    assert st["spec_proposed"] > 0
    assert 0 < st["spec_accepted"] < st["spec_proposed"]
    assert st["accepted_token_rate"] == pytest.approx(
        st["spec_accepted"] / st["spec_proposed"], abs=1e-3)
    assert st["tokens_per_step"] > 1.0
    assert st["spec_tokens"] == 3 and st["proposer"] == "ngram"
    # reset_stats zeroes the new counters too (bench sweep contract)
    for k in ("spec_steps", "spec_proposed", "spec_accepted",
              "prefill_chunks", "d2h_syncs", "d2h_syncs_saved",
              "tokens", "steps"):
        assert st2[k] == 0, k
    assert st2["accepted_token_rate"] == 0.0


@pytest.mark.slow
def test_spec_eos_mid_window(lm):
    """An accepted token that IS eos truncates the window commit."""
    rng = np.random.RandomState(0)
    prompt = _repetitive_prompt(rng)
    e0 = _engine(lm, spec_tokens=0)
    try:
        ref = e0.generate(prompt, 12)
    finally:
        e0.close()
    eos = int(ref[5])  # eos lands mid-generation (and mid-window)
    e0 = _engine(lm, spec_tokens=0)
    try:
        want = e0.generate(prompt, 12, eos_id=eos)
    finally:
        e0.close()
    e1 = _engine(lm, spec_tokens=3)
    try:
        got = e1.generate(prompt, 12, eos_id=eos)
    finally:
        e1.close()
    np.testing.assert_array_equal(want, got)
    assert got[-1] == eos and len(got) < 12


@pytest.mark.slow
def test_d2h_pipeline_counts_saved_syncs(lm):
    """The plain decode path double-buffers the (B,) fetch when the
    next step's composition is provably stable — same output bits,
    fewer hard syncs."""
    rng = np.random.RandomState(2)
    prompt = rng.randint(1, V, size=9).astype(np.int32)
    e = _engine(lm)
    try:
        out = e.generate(prompt, 16)
        st = e.stats()
    finally:
        e.close()
    assert st["d2h_syncs_saved"] > 0
    assert st["d2h_syncs"] > st["d2h_syncs_saved"]
    e0 = _engine(lm, max_streams=1, decode_buckets=[1])
    try:
        ref = e0.generate(prompt, 16)
    finally:
        e0.close()
    np.testing.assert_array_equal(ref, out)


class _MarkerProposer:
    """Drafts only for prompts starting with the marker token — lets a
    test pin one stream to the never-drafts path while a co-rider
    keeps the engine in verify mode."""

    def __init__(self, marker):
        self.marker = marker
        self._inner = NgramProposer()

    def propose(self, ctx, k):
        if int(ctx[0]) != self.marker:
            return np.empty(0, np.int32)
        return self._inner.propose(ctx, k)


@pytest.mark.slow
def test_temperature_no_draft_stream_bits_match_plain_engine(lm):
    """Fleet decode-retry contract under temperature: a stream that
    never drafts must emit BIT-identical tokens whether it runs on a
    plain engine or rides verify batches beside a drafting stream —
    its rows take the plain categorical(key, position) path, never a
    phantom draft from window padding."""
    rng = np.random.RandomState(12)
    marker = 1
    x_prompt = rng.randint(2, V, size=9).astype(np.int32)
    y_prompt = np.concatenate(
        [[marker], np.tile(rng.randint(2, V, size=3), 6)]) \
        .astype(np.int32)[:13]
    e0 = _engine(lm, spec_tokens=0)
    try:
        want = e0.generate(x_prompt, 10, temperature=0.9, seed=5)
    finally:
        e0.close()
    e1 = _engine(lm, spec_tokens=3,
                 proposer=_MarkerProposer(marker))
    try:
        fy = e1.submit(y_prompt, 14, temperature=0.9, seed=9)
        fx = e1.submit(x_prompt, 10, temperature=0.9, seed=5)
        got = fx.result(timeout=120)
        fy.result(timeout=120)
        st = e1.stats()
    finally:
        e1.close()
    assert st["spec_proposed"] > 0  # Y really kept verify mode on
    np.testing.assert_array_equal(want, got)


# ---------------------------------------------------------------------------
# chunked prefill
# ---------------------------------------------------------------------------


def test_chunked_prefill_bitmatch_monolithic(lm):
    rng = np.random.RandomState(1)
    prompt = rng.randint(1, V, size=21).astype(np.int32)
    e0 = _engine(lm, prefill_chunk=0)
    try:
        ref = e0.generate(prompt, 8)
    finally:
        e0.close()
    e1 = _engine(lm, prefill_chunk=8)
    try:
        out = e1.generate(prompt, 8)
        st = e1.stats()
    finally:
        e1.close()
    np.testing.assert_array_equal(ref, out)
    assert st["prefill_chunks"] == 3  # 8 + 8 + 5 uncached tokens
    assert st["prefill_chunk"] == 8


@pytest.mark.slow
def test_chunked_prefill_with_prefix_hit(lm):
    """A chunked prefill registers its prompt pages; a second
    identical prompt attaches them and its chain still bit-matches."""
    rng = np.random.RandomState(4)
    prompt = rng.randint(1, V, size=20).astype(np.int32)
    e0 = _engine(lm, prefill_chunk=0, prefix_cache=0)
    try:
        ref = e0.generate(prompt, 6)
    finally:
        e0.close()
    e1 = _engine(lm, prefill_chunk=8, prefix_cache=1)
    try:
        first = e1.generate(prompt, 6)
        st1 = e1.stats()
        again = e1.generate(prompt, 6)
        st2 = e1.stats()
    finally:
        e1.close()
    np.testing.assert_array_equal(ref, first)
    np.testing.assert_array_equal(ref, again)
    assert st1["prefill_chunks"] >= 2
    # the re-submission hit the prefix cache: its uncached suffix fits
    # one chunk, so no NEW chunked prefill ran
    assert st2["prefix_hits"] >= 1
    assert st2["prefill_chunks"] == st1["prefill_chunks"]


@pytest.mark.slow
def test_chunked_prefill_beyond_prefill_ladder(lm):
    """Chunking admits prompts LONGER than the largest prefill bucket
    — each chunk buckets individually."""
    rng = np.random.RandomState(6)
    prompt = rng.randint(1, V, size=30).astype(np.int32)
    e = _engine(lm, prefill_chunk=8, prefill_buckets=[8, 16])
    try:
        out = e.generate(prompt, 4)
    finally:
        e.close()
    e0 = _engine(lm)
    try:
        ref = e0.generate(prompt, 4)
    finally:
        e0.close()
    np.testing.assert_array_equal(ref, out)
    # without chunking the same ladder refuses the prompt loudly
    e1 = _engine(lm, prefill_buckets=[8, 16])
    try:
        with pytest.raises(mx.MXNetError, match="prefill bucket"):
            e1.submit(prompt, 4)
    finally:
        e1.close()


# ---------------------------------------------------------------------------
# env validation (the loud-at-construction contract)
# ---------------------------------------------------------------------------


def test_spec_env_validation(lm, monkeypatch):
    monkeypatch.setenv("MXNET_SERVING_SPEC_TOKENS", "banana")
    with pytest.raises(mx.MXNetError, match="SPEC_TOKENS"):
        _engine(lm)
    monkeypatch.setenv("MXNET_SERVING_SPEC_TOKENS", "-1")
    with pytest.raises(mx.MXNetError, match="SPEC_TOKENS"):
        _engine(lm)
    monkeypatch.delenv("MXNET_SERVING_SPEC_TOKENS")
    monkeypatch.setenv("MXNET_SERVING_PROPOSER", "banana")
    with pytest.raises(mx.MXNetError, match="PROPOSER"):
        _engine(lm)
    monkeypatch.delenv("MXNET_SERVING_PROPOSER")
    monkeypatch.setenv("MXNET_SERVING_PREFILL_CHUNK", "-4")
    with pytest.raises(mx.MXNetError, match="PREFILL_CHUNK"):
        _engine(lm)
    monkeypatch.setenv("MXNET_SERVING_PREFILL_CHUNK", "10")
    with pytest.raises(mx.MXNetError, match="multiple of kv_block"):
        _engine(lm)  # kv_block 4 does not divide 10
    monkeypatch.delenv("MXNET_SERVING_PREFILL_CHUNK")
    with pytest.raises(mx.MXNetError, match="propose"):
        _engine(lm, spec_tokens=2, proposer=object())


# ---------------------------------------------------------------------------
# slow: batch composition, prefix hits, mixed load
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_spec_bit_identity_across_batch_composition_and_hits(lm):
    """Concurrent streams with staggered lengths (streams join and
    retire mid-flight, so every batch composition appears), plus a
    repeated prompt (a prefix-cache full hit entering verify through
    the COW replay path): every speculative greedy output equals the
    solo non-speculative one."""
    rng = np.random.RandomState(7)
    reqs = [( _repetitive_prompt(rng, n=10 + 2 * i), 6 + 3 * i)
            for i in range(4)]
    reqs.append((reqs[0][0], 8))  # exact repeat: full/partial hit
    e0 = _engine(lm, spec_tokens=0, prefix_cache=1)
    try:
        want = [e0.generate(p, n) for p, n in reqs]
    finally:
        e0.close()
    e1 = _engine(lm, spec_tokens=3, prefix_cache=1)
    try:
        futs = []
        for i, (p, n) in enumerate(reqs):
            futs.append(e1.submit(p, n))
            if i == 2:  # stagger: let the first batch shrink/grow
                futs[0].result(timeout=60)
        got = [f.result(timeout=120) for f in futs]
        st = e1.stats()
    finally:
        e1.close()
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, g)
    assert st["prefix_hits"] >= 1 and st["spec_steps"] > 0


@pytest.mark.slow
def test_chunked_prefill_interleaves_with_decode(lm):
    """While a long prompt prefills in chunks, already-active streams
    keep stepping between chunks — and both outputs stay bit-exact."""
    rng = np.random.RandomState(8)
    long_prompt = rng.randint(1, V, size=28).astype(np.int32)
    chat = rng.randint(1, V, size=6).astype(np.int32)
    e0 = _engine(lm)
    try:
        want_long = e0.generate(long_prompt, 6)
        want_chat = e0.generate(chat, 16)
    finally:
        e0.close()
    e = _engine(lm, prefill_chunk=8)
    try:
        f_chat = e.submit(chat, 16)
        # wait until the chat stream is actively decoding
        deadline = threading.Event()
        for _ in range(200):
            if e.stats()["active_streams"] >= 1:
                break
            deadline.wait(0.01)
        f_long = e.submit(long_prompt, 6)
        got_chat = f_chat.result(timeout=120)
        got_long = f_long.result(timeout=120)
        st = e.stats()
    finally:
        e.close()
    np.testing.assert_array_equal(want_chat, got_chat)
    np.testing.assert_array_equal(want_long, got_long)
    assert st["prefill_chunks"] >= 4  # 28 uncached tokens / 8


@pytest.mark.slow
def test_spec_with_quantized_kv_chains_token_equal(lm):
    """Speculation composes with the int8 KV cache: the verify window
    reads its own keys back through the quantized pools exactly like
    the sequential decode step, so spec-vs-plain chains stay
    token-equal at int8 too."""
    rng = np.random.RandomState(9)
    prompt = _repetitive_prompt(rng, n=12)
    e0 = _engine(lm, kv_dtype="int8")
    try:
        ref = e0.generate(prompt, 10)
    finally:
        e0.close()
    e1 = _engine(lm, kv_dtype="int8", spec_tokens=3)
    try:
        out = e1.generate(prompt, 10)
    finally:
        e1.close()
    np.testing.assert_array_equal(ref, out)
