"""Fused RNN op tests — numpy references per mode, shapes, gradients.

Mirrors the reference's operator test style (forward vs inline numpy,
finite-difference backward — tests/python/unittest/test_operator.py).
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops.rnn import rnn_param_size, _GATES


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _unpack_np(params, L, I, H, D, G):
    ws = []
    off = 0
    for layer in range(L):
        i_l = I if layer == 0 else H * D
        per = []
        for _ in range(D):
            w = params[off:off + G * H * i_l].reshape(G * H, i_l); off += G * H * i_l
            u = params[off:off + G * H * H].reshape(G * H, H); off += G * H * H
            per.append([w, u])
        ws.append(per)
    for layer in range(L):
        for dd in range(D):
            ws[layer][dd].append(params[off:off + G * H]); off += G * H
            ws[layer][dd].append(params[off:off + G * H]); off += G * H
    assert off == params.size
    return ws


def _np_lstm_layer(x, h0, c0, w, u, bw, bu, reverse=False):
    T, B, _ = x.shape
    H = h0.shape[-1]
    h, c = h0.copy(), c0.copy()
    ys = np.zeros((T, B, H), np.float64)
    ts = range(T - 1, -1, -1) if reverse else range(T)
    for t in ts:
        pre = x[t] @ w.T + h @ u.T + bw + bu
        i, f, g, o = np.split(pre, 4, axis=-1)
        c = _sigmoid(f) * c + _sigmoid(i) * np.tanh(g)
        h = _sigmoid(o) * np.tanh(c)
        ys[t] = h
    return ys, h, c


def _np_gru_layer(x, h0, w, u, bw, bu, reverse=False):
    T, B, _ = x.shape
    H = h0.shape[-1]
    h = h0.copy()
    u_r, u_z, u_n = np.split(u, 3, axis=0)
    b_r, b_z, b_n = np.split(bu, 3)
    ys = np.zeros((T, B, H), np.float64)
    ts = range(T - 1, -1, -1) if reverse else range(T)
    for t in ts:
        xp = x[t] @ w.T + bw
        x_r, x_z, x_n = np.split(xp, 3, axis=-1)
        r = _sigmoid(x_r + h @ u_r.T + b_r)
        z = _sigmoid(x_z + h @ u_z.T + b_z)
        n = np.tanh(x_n + r * (h @ u_n.T + b_n))
        h = (1 - z) * n + z * h
        ys[t] = h
    return ys, h


def _bind_rnn(T, B, I, H, L, mode, bidirectional=False, state_outputs=True):
    data = mx.sym.Variable("data")
    kwargs = dict(state_size=H, num_layers=L, mode=mode,
                  bidirectional=bidirectional, state_outputs=state_outputs,
                  name="rnn")
    if mode == "lstm":
        rnn = mx.sym.RNN(data=data, parameters=mx.sym.Variable("p"),
                         state=mx.sym.Variable("s"),
                         state_cell=mx.sym.Variable("c"), **kwargs)
    else:
        rnn = mx.sym.RNN(data=data, parameters=mx.sym.Variable("p"),
                         state=mx.sym.Variable("s"), **kwargs)
    return rnn.simple_bind(mx.cpu(), data=(T, B, I))


def test_param_size_matches_reference_formula():
    # reference rnn-inl.h:31-70 worked examples
    assert rnn_param_size(1, 4, 6, False, "lstm") == 6 * (6 + 4 + 2) * 4
    assert rnn_param_size(2, 4, 6, False, "gru") == \
        (6 * (6 + 4 + 2) + 6 * (6 + 6 + 2)) * 3
    assert rnn_param_size(2, 4, 6, True, "rnn_tanh") == \
        (6 * (6 + 4 + 2) + 6 * (6 + 12 + 2)) * 2


@pytest.mark.parametrize("mode", ["lstm", "gru", "rnn_tanh", "rnn_relu"])
def test_rnn_forward_matches_numpy(mode):
    T, B, I, H, L = 4, 2, 3, 5, 2
    G = _GATES[mode]
    rng = np.random.RandomState(7)
    n = rnn_param_size(L, I, H, False, mode)
    params = (rng.randn(n) * 0.2).astype(np.float32)
    x = rng.randn(T, B, I).astype(np.float32)
    h0 = rng.randn(L, B, H).astype(np.float32) * 0.1
    c0 = rng.randn(L, B, H).astype(np.float32) * 0.1

    ex = _bind_rnn(T, B, I, H, L, mode)
    ex.arg_dict["data"][:] = x
    ex.arg_dict["p"][:] = params
    ex.arg_dict["s"][:] = h0
    if mode == "lstm":
        ex.arg_dict["c"][:] = c0
    ex.forward(is_train=False)
    got = [o.asnumpy() for o in ex.outputs]

    ws = _unpack_np(params.astype(np.float64), L, I, H, 1, G)
    xx = x.astype(np.float64)
    hs, cs = [], []
    for layer in range(L):
        w, u, bw, bu = ws[layer][0]
        if mode == "lstm":
            xx, hT, cT = _np_lstm_layer(xx, h0[layer].astype(np.float64),
                                        c0[layer].astype(np.float64), w, u, bw, bu)
            cs.append(cT)
        elif mode == "gru":
            xx, hT = _np_gru_layer(xx, h0[layer].astype(np.float64), w, u, bw, bu)
        else:
            act = np.tanh if mode == "rnn_tanh" else lambda v: np.maximum(v, 0)
            h = h0[layer].astype(np.float64).copy()
            ys = np.zeros((T, B, H))
            for t in range(T):
                h = act(xx[t] @ w.T + h @ u.T + bw + bu)
                ys[t] = h
            xx, hT = ys, h
        hs.append(hT)
    np.testing.assert_allclose(got[0], xx, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(got[1], np.stack(hs), rtol=1e-4, atol=1e-5)
    if mode == "lstm":
        np.testing.assert_allclose(got[2], np.stack(cs), rtol=1e-4, atol=1e-5)


def test_rnn_bidirectional_matches_numpy():
    T, B, I, H = 4, 2, 3, 5
    rng = np.random.RandomState(3)
    n = rnn_param_size(1, I, H, True, "lstm")
    params = (rng.randn(n) * 0.2).astype(np.float32)
    x = rng.randn(T, B, I).astype(np.float32)
    h0 = np.zeros((2, B, H), np.float32)
    c0 = np.zeros((2, B, H), np.float32)

    ex = _bind_rnn(T, B, I, H, 1, "lstm", bidirectional=True)
    ex.arg_dict["data"][:] = x
    ex.arg_dict["p"][:] = params
    ex.forward(is_train=False)
    out = ex.outputs[0].asnumpy()
    assert out.shape == (T, B, 2 * H)

    ws = _unpack_np(params.astype(np.float64), 1, I, H, 2, 4)
    xx = x.astype(np.float64)
    y_f, _, _ = _np_lstm_layer(xx, h0[0].astype(np.float64),
                               c0[0].astype(np.float64), *ws[0][0])
    y_b, _, _ = _np_lstm_layer(xx, h0[1].astype(np.float64),
                               c0[1].astype(np.float64), *ws[0][1], reverse=True)
    np.testing.assert_allclose(out, np.concatenate([y_f, y_b], -1),
                               rtol=1e-4, atol=1e-5)


def test_rnn_gradient():
    """Train a tiny LSTM regressor; loss must drop (end-to-end grad path)."""
    T, B, I, H = 6, 8, 4, 8
    rng = np.random.RandomState(0)
    X = rng.randn(64, T, I).astype(np.float32)
    # predictable target: sum over time of first input dim
    Y = X[:, :, 0].sum(axis=1)

    data = mx.sym.Variable("data")
    tnc = mx.sym.transpose(data, axes=(1, 0, 2), name="tnc")
    rnn = mx.sym.RNN(data=tnc, parameters=mx.sym.Variable("rnn_parameters"),
                     state=mx.sym.Variable("rnn_s"),
                     state_cell=mx.sym.Variable("rnn_c"),
                     state_size=H, num_layers=1, mode="lstm", name="rnn")
    last = mx.sym.SequenceLast(rnn, name="last")
    pred = mx.sym.FullyConnected(last, num_hidden=1, name="pred")
    net = mx.sym.LinearRegressionOutput(mx.sym.Reshape(pred, shape=(-1,)),
                                        name="lro")

    it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="lro_label")
    mod = mx.mod.Module(net, context=mx.cpu(), label_names=("lro_label",))
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mx.random.seed(5)
    zeros_s = mx.nd.zeros((1, 16, H))
    mod.init_params(mx.initializer.Uniform(0.08),
                    arg_params={"rnn_s": zeros_s, "rnn_c": zeros_s.copy()})
    mod.init_optimizer(kvstore=None, optimizer="adam",
                       optimizer_params={"learning_rate": 0.01})
    losses = []
    for epoch in range(15):
        it.reset()
        mse = 0.0
        n = 0
        for b in it:
            mod.forward_backward(b)
            mod.update()
            out = mod.get_outputs()[0].asnumpy()
            mse += float(((out - b.label[0].asnumpy()) ** 2).sum())
            n += out.shape[0]
        losses.append(mse / n)
    assert losses[-1] < losses[0] * 0.5, losses


def test_rnn_dropout_train_vs_eval():
    T, B, I, H, L = 4, 2, 3, 5, 2
    rng = np.random.RandomState(1)
    n = rnn_param_size(L, I, H, False, "lstm")
    ex = _bind_rnn(T, B, I, H, L, "lstm", state_outputs=False)
    ex.arg_dict["data"][:] = rng.randn(T, B, I).astype(np.float32)
    ex.arg_dict["p"][:] = (rng.randn(n) * 0.2).astype(np.float32)
    # p only affects train mode; eval must be deterministic
    o1 = ex.forward(is_train=False)[0].asnumpy()
    o2 = ex.forward(is_train=False)[0].asnumpy()
    np.testing.assert_array_equal(o1, o2)
