"""Single-process worker for the checkpoint crash/preemption tests.

Trains a small deterministic MLP with a CheckpointManager attached.
The test harness runs it as a subprocess and kills it — via the
MXNET_CKPT_CRASH fault-injection hook (background writer dies
mid-shard) or SIGTERM (emergency checkpoint) — then reruns it with
``resume='auto'`` and asserts the final weights bit-match an
uninterrupted run (the test also imports :func:`train` directly for
the in-process reference)."""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx

N_SAMPLES = 48
BATCH = 4
CLASSES = 4
IN_DIM = 8


def build_sym():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def make_data():
    rng = np.random.RandomState(9)
    X = rng.randn(N_SAMPLES, IN_DIM).astype(np.float32)
    y = rng.randint(0, CLASSES, N_SAMPLES).astype(np.float32)
    return X, y


def train(ckpt_dir=None, num_epoch=2, every_n=2, sleep=0.0,
          resume="auto", async_save=True, progress=False):
    mx.random.seed(11)
    np.random.seed(11)
    X, y = make_data()
    it = mx.io.NDArrayIter(X, y, batch_size=BATCH, shuffle=True)
    mod = mx.mod.Module(build_sym(), context=mx.cpu())
    mgr = None
    if ckpt_dir is not None:
        mgr = mx.CheckpointManager(ckpt_dir, every_n_steps=every_n,
                                   async_save=async_save, keep=10)
    cb = None
    if sleep > 0 or progress:
        def cb(param):
            if progress:
                print(f"BATCH {param.nbatch}", flush=True)
            if sleep > 0:
                time.sleep(sleep)
    mod.fit(it, num_epoch=num_epoch, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
            initializer=mx.initializer.Xavier(rnd_type="gaussian"),
            eval_metric="acc", checkpoint=mgr,
            resume=resume if mgr is not None else None,
            batch_end_callback=cb)
    if mgr is not None:
        mgr.close()
    args_, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args_.items()}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--every-n", type=int, default=2)
    ap.add_argument("--sleep", type=float, default=0.0)
    ap.add_argument("--sync", action="store_true")
    ap.add_argument("--progress", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    params = train(args.ckpt_dir, num_epoch=args.epochs,
                   every_n=args.every_n, sleep=args.sleep,
                   async_save=not args.sync, progress=args.progress)
    if args.out:
        np.savez(args.out, **params)
    print("ckpt worker done", flush=True)


if __name__ == "__main__":
    main()
