"""KVStore tests (modeled on tests/python/unittest/test_kvstore.py —
multi-device semantics exercised with N arrays per key on one host)."""

import numpy as np
import pytest

import mxnet_tpu as mx

SHAPE = (4, 4)
KEYS = [5, 7, 11]


def _init_kv():
    kv = mx.kv.create("local")
    kv.init(3, mx.nd.zeros(SHAPE))
    kv.init(KEYS, [mx.nd.zeros(SHAPE)] * len(KEYS))
    return kv


def test_single_kv_pair():
    kv = _init_kv()
    kv.push(3, mx.nd.ones(SHAPE) * 4)
    out = mx.nd.empty(SHAPE)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full(SHAPE, 4.0))


def test_aggregator_multi_devices():
    # 4 "devices" push to one key → values summed (kvstore_local Reduce)
    kv = _init_kv()
    num_devs = 4
    vals = [mx.nd.ones(SHAPE)] * num_devs
    kv.push(3, vals)
    out = mx.nd.empty(SHAPE)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full(SHAPE, num_devs))
    # list keys
    kv.push(KEYS, [[mx.nd.ones(SHAPE) * 2.0] * num_devs] * len(KEYS))
    outs = [mx.nd.empty(SHAPE) for _ in KEYS]
    kv.pull(KEYS, out=outs)
    for o in outs:
        np.testing.assert_allclose(o.asnumpy(), np.full(SHAPE, 2.0 * num_devs))


def test_updater():
    kv = _init_kv()
    updates = []

    def my_updater(key, recv, stored):
        updates.append(key)
        stored += recv * 2.0

    kv._set_updater(my_updater)
    kv.push(3, mx.nd.ones(SHAPE))
    out = mx.nd.empty(SHAPE)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full(SHAPE, 2.0))
    assert updates == [3]


def test_optimizer_on_kvstore():
    kv = mx.kv.create("local")
    kv.init(0, mx.nd.ones(SHAPE))
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.5))
    kv.push(0, mx.nd.ones(SHAPE))
    out = mx.nd.empty(SHAPE)
    kv.pull(0, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full(SHAPE, 0.5))


def test_kvstore_types():
    for t in ["local", "device", "tpu", "dist_sync", "dist_async"]:
        kv = mx.kv.create(t)
        assert kv.type == t
        assert kv.rank == 0
        assert kv.num_workers == 1
    with pytest.raises(mx.MXNetError):
        mx.kv.create("bogus")


def test_errors():
    kv = _init_kv()
    with pytest.raises(mx.MXNetError):
        kv.init(3, mx.nd.zeros(SHAPE))  # duplicate
    with pytest.raises(mx.MXNetError):
        kv.push(999, mx.nd.zeros(SHAPE))
    with pytest.raises(mx.MXNetError):
        kv.pull(999, out=mx.nd.zeros(SHAPE))
