"""Operator tests vs numpy references + numeric gradients
(modeled on tests/python/unittest/test_operator.py, 71 tests)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import (assert_almost_equal, check_numeric_gradient,
                                  check_symbolic_forward, check_symbolic_backward,
                                  simple_forward)

RNG = np.random.RandomState(7)


def test_elemwise_ops_forward():
    x = RNG.rand(3, 4).astype(np.float32) + 0.5
    cases = {
        "exp": np.exp, "log": np.log, "sqrt": np.sqrt, "square": np.square,
        "abs": np.abs, "sign": np.sign, "ceil": np.ceil, "floor": np.floor,
        "sin": np.sin, "cos": np.cos, "tanh": np.tanh, "sigmoid": lambda v: 1 / (1 + np.exp(-v)),
    }
    for name, ref in cases.items():
        out = getattr(mx.nd, name)(mx.nd.array(x)).asnumpy()
        assert_almost_equal(out, ref(x), rtol=1e-4, atol=1e-5, names=(name, "np"))


def test_unary_gradients():
    x = RNG.rand(2, 3).astype(np.float32) + 0.5
    for op in ["exp", "log", "sqrt", "square", "tanh", "sigmoid"]:
        sym = getattr(mx.sym, op)(mx.sym.Variable("x"))
        check_numeric_gradient(sym, {"x": x}, rtol=5e-2)


def test_binary_broadcast():
    a = RNG.rand(2, 3, 4).astype(np.float32) + 0.5
    b = RNG.rand(1, 3, 1).astype(np.float32) + 0.5
    for name, ref in [("broadcast_add", np.add), ("broadcast_mul", np.multiply),
                      ("broadcast_sub", np.subtract), ("broadcast_div", np.divide),
                      ("broadcast_maximum", np.maximum), ("broadcast_minimum", np.minimum)]:
        out = getattr(mx.nd, name)(mx.nd.array(a), mx.nd.array(b)).asnumpy()
        assert_almost_equal(out, ref(a, b), rtol=1e-5, names=(name, "np"))
    sym = mx.sym.broadcast_mul(mx.sym.Variable("a"), mx.sym.Variable("b"))
    check_numeric_gradient(sym, {"a": a, "b": b}, rtol=5e-2)


def test_reduce_ops():
    x = RNG.rand(2, 3, 4).astype(np.float32)
    assert_almost_equal(mx.nd.sum(mx.nd.array(x), axis=1).asnumpy(), x.sum(1), rtol=1e-5)
    assert_almost_equal(mx.nd.sum(mx.nd.array(x), axis=(0, 2)).asnumpy(), x.sum((0, 2)), rtol=1e-5)
    assert_almost_equal(mx.nd.mean(mx.nd.array(x), axis=2, keepdims=True).asnumpy(),
                        x.mean(2, keepdims=True), rtol=1e-5)
    assert_almost_equal(mx.nd.argmax(mx.nd.array(x), axis=1).asnumpy(), np.argmax(x, 1))
    assert_almost_equal(mx.nd.norm(mx.nd.array(x)).asnumpy(),
                        np.array([np.sqrt((x ** 2).sum())]), rtol=1e-5)


def test_dot_ops():
    a = RNG.rand(4, 5).astype(np.float32)
    b = RNG.rand(5, 3).astype(np.float32)
    assert_almost_equal(mx.nd.dot(mx.nd.array(a), mx.nd.array(b)).asnumpy(), a @ b, rtol=1e-4)
    assert_almost_equal(
        mx.nd.dot(mx.nd.array(a), mx.nd.array(b.T), transpose_b=True).asnumpy(),
        a @ b, rtol=1e-4)
    batch_a = RNG.rand(6, 4, 5).astype(np.float32)
    batch_b = RNG.rand(6, 5, 3).astype(np.float32)
    assert_almost_equal(mx.nd.batch_dot(mx.nd.array(batch_a), mx.nd.array(batch_b)).asnumpy(),
                        np.einsum("bij,bjk->bik", batch_a, batch_b), rtol=1e-4)
    sym = mx.sym.dot(mx.sym.Variable("a"), mx.sym.Variable("b"))
    check_numeric_gradient(sym, {"a": a, "b": b}, rtol=5e-2)


def test_shape_ops():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    nd = mx.nd.array(x)
    assert_almost_equal(mx.nd.Reshape(nd, shape=(6, 4)).asnumpy(), x.reshape(6, 4))
    assert_almost_equal(mx.nd.Reshape(nd, shape=(0, -1)).asnumpy(), x.reshape(2, 12))
    assert_almost_equal(mx.nd.Reshape(nd, shape=(-1, 0), reverse=True).asnumpy(),
                        x.reshape(-1, 4))
    assert_almost_equal(mx.nd.Flatten(nd).asnumpy(), x.reshape(2, 12))
    assert_almost_equal(mx.nd.expand_dims(nd, axis=1).asnumpy(), x[:, None])
    assert_almost_equal(mx.nd.transpose(nd, axes=(2, 0, 1)).asnumpy(), x.transpose(2, 0, 1))
    assert_almost_equal(mx.nd.slice_axis(nd, axis=2, begin=1, end=3).asnumpy(), x[:, :, 1:3])
    assert_almost_equal(mx.nd.flip(nd, axis=2).asnumpy(), x[:, :, ::-1])
    assert_almost_equal(mx.nd.tile(nd, reps=(1, 2, 1)).asnumpy(), np.tile(x, (1, 2, 1)))
    assert_almost_equal(mx.nd.repeat(nd, repeats=2, axis=1).asnumpy(), np.repeat(x, 2, 1))


def test_concat_split():
    a = RNG.rand(2, 3).astype(np.float32)
    b = RNG.rand(2, 5).astype(np.float32)
    out = mx.nd.Concat(mx.nd.array(a), mx.nd.array(b), num_args=2, dim=1)
    assert_almost_equal(out.asnumpy(), np.concatenate([a, b], 1))
    x = RNG.rand(2, 6).astype(np.float32)
    outs = mx.nd.SliceChannel(mx.nd.array(x), num_outputs=3, axis=1)
    for i, o in enumerate(outs):
        assert_almost_equal(o.asnumpy(), x[:, 2 * i:2 * i + 2])
    # symbolic concat gradient
    sym = mx.sym.Concat(mx.sym.Variable("a"), mx.sym.Variable("b"), num_args=2, dim=1)
    check_numeric_gradient(sym, {"a": a, "b": b}, rtol=5e-2)


def test_fullyconnected():
    x = RNG.rand(4, 10).astype(np.float32)
    w = RNG.rand(5, 10).astype(np.float32)
    b = RNG.rand(5).astype(np.float32)
    out = mx.nd.FullyConnected(mx.nd.array(x), mx.nd.array(w), mx.nd.array(b), num_hidden=5)
    assert_almost_equal(out.asnumpy(), x @ w.T + b, rtol=1e-4)
    sym = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=5, name="fc")
    check_numeric_gradient(sym, {"data": x, "fc_weight": w, "fc_bias": b}, rtol=5e-2)


def test_activation_ops():
    x = RNG.randn(3, 4).astype(np.float32)
    nd = mx.nd.array(x)
    assert_almost_equal(mx.nd.Activation(nd, act_type="relu").asnumpy(), np.maximum(x, 0))
    assert_almost_equal(mx.nd.Activation(nd, act_type="tanh").asnumpy(), np.tanh(x), rtol=1e-5)
    assert_almost_equal(mx.nd.LeakyReLU(nd, act_type="leaky", slope=0.1).asnumpy(),
                        np.where(x > 0, x, 0.1 * x), rtol=1e-5)
    elu = mx.nd.LeakyReLU(nd, act_type="elu", slope=0.3).asnumpy()
    assert_almost_equal(elu, np.where(x > 0, x, 0.3 * np.expm1(x)), rtol=1e-5)


def test_convolution_forward():
    # compare against explicit correlation
    x = RNG.rand(2, 3, 7, 7).astype(np.float32)
    w = RNG.rand(4, 3, 3, 3).astype(np.float32)
    b = np.zeros(4, np.float32)
    out = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w), mx.nd.array(b),
                            kernel=(3, 3), num_filter=4).asnumpy()
    ref = np.zeros((2, 4, 5, 5), np.float32)
    for n in range(2):
        for f in range(4):
            for i in range(5):
                for j in range(5):
                    ref[n, f, i, j] = (x[n, :, i:i + 3, j:j + 3] * w[f]).sum()
    assert_almost_equal(out, ref, rtol=1e-3, atol=1e-4)


def test_convolution_grad():
    x = RNG.rand(1, 2, 5, 5).astype(np.float32)
    sym = mx.sym.Convolution(mx.sym.Variable("data"), kernel=(3, 3), num_filter=2,
                             pad=(1, 1), name="conv")
    check_numeric_gradient(
        sym, {"data": x,
              "conv_weight": RNG.rand(2, 2, 3, 3).astype(np.float32) * 0.1,
              "conv_bias": np.zeros(2, np.float32)}, rtol=8e-2)


def test_pooling():
    x = RNG.rand(1, 2, 6, 6).astype(np.float32)
    out = mx.nd.Pooling(mx.nd.array(x), kernel=(2, 2), stride=(2, 2), pool_type="max").asnumpy()
    ref = x.reshape(1, 2, 3, 2, 3, 2).max(axis=(3, 5))
    assert_almost_equal(out, ref)
    out = mx.nd.Pooling(mx.nd.array(x), kernel=(2, 2), stride=(2, 2), pool_type="avg").asnumpy()
    assert_almost_equal(out, x.reshape(1, 2, 3, 2, 3, 2).mean(axis=(3, 5)), rtol=1e-5)
    gout = mx.nd.Pooling(mx.nd.array(x), kernel=(1, 1), global_pool=True, pool_type="max").asnumpy()
    assert_almost_equal(gout[..., 0, 0], x.max(axis=(2, 3)))
    # full convention: 6->3 with k=2,s=2 same; try k=3,s=2: valid->2, full->3
    out_v = mx.nd.Pooling(mx.nd.array(x), kernel=(3, 3), stride=(2, 2), pool_type="max",
                          pooling_convention="valid").asnumpy()
    assert out_v.shape == (1, 2, 2, 2)
    out_f = mx.nd.Pooling(mx.nd.array(x), kernel=(3, 3), stride=(2, 2), pool_type="max",
                          pooling_convention="full").asnumpy()
    assert out_f.shape == (1, 2, 3, 3)


def test_batchnorm_train_inference():
    x = RNG.rand(8, 3, 4, 4).astype(np.float32)
    gamma = np.ones(3, np.float32)
    beta = np.zeros(3, np.float32)
    mm = np.zeros(3, np.float32)
    mv = np.ones(3, np.float32)
    g, b = mx.nd.array(gamma), mx.nd.array(beta)
    mm_nd, mv_nd = mx.nd.array(mm), mx.nd.array(mv)
    out = mx.nd.BatchNorm(mx.nd.array(x), g, b, mm_nd, mv_nd, is_train=True,
                          eps=1e-3, momentum=0.9)
    mean = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    ref = (x - mean[None, :, None, None]) / np.sqrt(var[None, :, None, None] + 1e-3)
    assert_almost_equal(out.asnumpy(), ref, rtol=1e-3, atol=1e-4)
    # moving stats updated
    assert_almost_equal(mm_nd.asnumpy(), 0.9 * mm + 0.1 * mean, rtol=1e-4)
    assert_almost_equal(mv_nd.asnumpy(), 0.9 * mv + 0.1 * var, rtol=1e-4)
    # inference uses moving stats
    out_inf = mx.nd.BatchNorm(mx.nd.array(x), g, b, mx.nd.array(mm), mx.nd.array(mv),
                              is_train=False, eps=1e-3)
    ref_inf = (x - mm[None, :, None, None]) / np.sqrt(mv[None, :, None, None] + 1e-3)
    assert_almost_equal(out_inf.asnumpy(), ref_inf, rtol=1e-3, atol=1e-4)


def test_norm_large_mean_no_cancellation():
    # |mean| >> std regime: the single-pass E[(x-s)^2] - E[x-s]^2 statistics
    # must not catastrophically cancel in f32 (round-4 advisor finding)
    x = (RNG.randn(8, 4, 6, 6).astype(np.float32) * 0.01 + 1000.0)
    out = mx.nd.BatchNorm(
        mx.nd.array(x), mx.nd.ones(4), mx.nd.zeros(4),
        mx.nd.zeros(4), mx.nd.ones(4), is_train=True, eps=1e-5).asnumpy()
    m = x.mean(axis=(0, 2, 3), keepdims=True)
    v = ((x - m) ** 2).mean(axis=(0, 2, 3), keepdims=True)
    ref = (x - m) / np.sqrt(v + 1e-5)
    # tolerance is input-representation-limited (f32 at |x|~1e3 holds ~1e-4)
    assert np.abs(out - ref).max() < 2e-2
    x2 = (RNG.randn(16, 32).astype(np.float32) * 0.01 + 1000.0)
    o2 = mx.nd.LayerNorm(mx.nd.array(x2), mx.nd.ones(32), mx.nd.zeros(32),
                         eps=1e-5).asnumpy()
    m2 = x2.mean(axis=1, keepdims=True)
    v2 = ((x2 - m2) ** 2).mean(axis=1, keepdims=True)
    r2 = (x2 - m2) / np.sqrt(v2 + 1e-5)
    assert np.abs(o2 - r2).max() < 2e-2


def test_softmax_output_grad():
    # backward = (p - onehot) * scale, ignoring head grads
    x = RNG.rand(4, 5).astype(np.float32)
    label = np.array([0, 2, 4, 1], np.float32)
    sym = mx.sym.SoftmaxOutput(mx.sym.Variable("data"), mx.sym.Variable("label"))
    p = np.exp(x - x.max(1, keepdims=True))
    p /= p.sum(1, keepdims=True)
    onehot = np.eye(5)[label.astype(int)]
    check_symbolic_forward(sym, {"data": x, "label": label}, p, rtol=1e-4)
    check_symbolic_backward(sym, {"data": x, "label": label},
                            out_grads=[np.ones((4, 5), np.float32)],
                            expected={"data": p - onehot}, rtol=1e-4)


def test_regression_outputs():
    x = RNG.rand(4, 3).astype(np.float32)
    y = RNG.rand(4, 3).astype(np.float32)
    lin = mx.sym.LinearRegressionOutput(mx.sym.Variable("data"), mx.sym.Variable("label"))
    check_symbolic_forward(lin, {"data": x, "label": y}, x)
    check_symbolic_backward(lin, {"data": x, "label": y},
                            out_grads=[np.ones_like(x)],
                            expected={"data": (x - y) / 3.0}, rtol=1e-4)
    log = mx.sym.LogisticRegressionOutput(mx.sym.Variable("data"), mx.sym.Variable("label"))
    sig = 1 / (1 + np.exp(-x))
    check_symbolic_forward(log, {"data": x, "label": y}, sig, rtol=1e-5)
    check_symbolic_backward(log, {"data": x, "label": y},
                            out_grads=[np.ones_like(x)],
                            expected={"data": (sig - y) / 3.0}, rtol=1e-4)


def test_block_grad_and_makeloss():
    x = RNG.rand(3, 3).astype(np.float32)
    v = mx.sym.Variable("x")
    blocked = mx.sym.BlockGrad(v * 2.0)
    g = mx.nd.zeros((3, 3))
    ex = blocked.bind(mx.cpu(), {"x": mx.nd.array(x)}, args_grad={"x": g})
    ex.forward(is_train=True)
    ex.backward([mx.nd.ones((3, 3))])
    assert (g.asnumpy() == 0).all()
    ml = mx.sym.MakeLoss(v * 3.0, grad_scale=2.0)
    g2 = mx.nd.zeros((3, 3))
    ex2 = ml.bind(mx.cpu(), {"x": mx.nd.array(x)}, args_grad={"x": g2})
    ex2.forward(is_train=True)
    ex2.backward()
    assert_almost_equal(g2.asnumpy(), np.full((3, 3), 6.0), rtol=1e-5)


def test_embedding_take():
    w = RNG.rand(10, 4).astype(np.float32)
    idx = np.array([1, 3, 5], np.float32)
    out = mx.nd.Embedding(mx.nd.array(idx), mx.nd.array(w), input_dim=10, output_dim=4)
    assert_almost_equal(out.asnumpy(), w[[1, 3, 5]])
    sym = mx.sym.Embedding(mx.sym.Variable("data"), input_dim=10, output_dim=4, name="emb")
    check_numeric_gradient(sym, {"data": idx, "emb_weight": w},
                           grad_nodes=["emb_weight"], rtol=5e-2)
    out2 = mx.nd.take(mx.nd.array(w), mx.nd.array(idx))
    assert_almost_equal(out2.asnumpy(), w[[1, 3, 5]])
    oh = mx.nd.one_hot(mx.nd.array(idx), depth=10)
    assert_almost_equal(oh.asnumpy(), np.eye(10)[[1, 3, 5]])


def test_dropout():
    x = np.ones((200, 200), np.float32)
    out = mx.nd.Dropout(mx.nd.array(x), p=0.5, is_train=True).asnumpy()
    frac = (out == 0).mean()
    assert 0.4 < frac < 0.6
    kept = out[out != 0]
    assert np.allclose(kept, 2.0)
    out_inf = mx.nd.Dropout(mx.nd.array(x), p=0.5, is_train=False).asnumpy()
    assert (out_inf == 1).all()


def test_ordering_ops():
    x = RNG.rand(4, 6).astype(np.float32)
    assert_almost_equal(mx.nd.sort(mx.nd.array(x), axis=1).asnumpy(), np.sort(x, 1))
    assert_almost_equal(mx.nd.argsort(mx.nd.array(x), axis=1).asnumpy(), np.argsort(x, 1))
    vals = mx.nd.topk(mx.nd.array(x), k=2, ret_typ="value", axis=1).asnumpy()
    ref = np.sort(x, 1)[:, ::-1][:, :2]
    assert_almost_equal(vals, ref, rtol=1e-5)


def test_sequence_ops():
    # (T, B, D)
    x = RNG.rand(4, 3, 2).astype(np.float32)
    seqlen = np.array([2, 4, 1], np.float32)
    last = mx.nd.SequenceLast(mx.nd.array(x), mx.nd.array(seqlen), use_sequence_length=True)
    ref = np.stack([x[1, 0], x[3, 1], x[0, 2]])
    assert_almost_equal(last.asnumpy(), ref)
    masked = mx.nd.SequenceMask(mx.nd.array(x), mx.nd.array(seqlen),
                                use_sequence_length=True, value=0.0).asnumpy()
    assert (masked[2:, 0] == 0).all() and (masked[1:, 2] == 0).all()
    assert_almost_equal(masked[:2, 0], x[:2, 0])
    rev = mx.nd.SequenceReverse(mx.nd.array(x), mx.nd.array(seqlen),
                                use_sequence_length=True).asnumpy()
    assert_almost_equal(rev[0, 0], x[1, 0])
    assert_almost_equal(rev[1, 0], x[0, 0])


def test_upsampling_pad():
    x = RNG.rand(1, 2, 3, 3).astype(np.float32)
    up = mx.nd.UpSampling(mx.nd.array(x), scale=2, sample_type="nearest").asnumpy()
    assert up.shape == (1, 2, 6, 6)
    assert_almost_equal(up[:, :, ::2, ::2], x)
    padded = mx.nd.Pad(mx.nd.array(x), mode="constant",
                       pad_width=(0, 0, 0, 0, 1, 1, 2, 2), constant_value=5).asnumpy()
    assert padded.shape == (1, 2, 5, 7)
    assert (padded[:, :, 0, :] == 5).all()


def test_lrn_l2norm():
    x = RNG.rand(2, 4, 3, 3).astype(np.float32)
    out = mx.nd.LRN(mx.nd.array(x), nsize=3, alpha=1e-4, beta=0.75, knorm=2.0).asnumpy()
    assert out.shape == x.shape
    l2 = mx.nd.L2Normalization(mx.nd.array(x), mode="instance").asnumpy()
    flat = x.reshape(2, -1)
    ref = (flat / np.sqrt((flat ** 2).sum(1, keepdims=True) + 1e-10)).reshape(x.shape)
    assert_almost_equal(l2, ref, rtol=1e-4)


def test_where_cast():
    cond = np.array([[1, 0], [0, 1]], np.float32)
    a = np.ones((2, 2), np.float32)
    b = np.zeros((2, 2), np.float32)
    out = mx.nd.where(mx.nd.array(cond), mx.nd.array(a), mx.nd.array(b)).asnumpy()
    assert_almost_equal(out, cond)
    c = mx.nd.Cast(mx.nd.array(a), dtype="int32")
    assert c.dtype == np.int32


def test_deconvolution():
    x = RNG.rand(1, 3, 4, 4).astype(np.float32)
    w = RNG.rand(3, 2, 3, 3).astype(np.float32) * 0.1
    out = mx.nd.Deconvolution(mx.nd.array(x), mx.nd.array(w), kernel=(3, 3),
                              num_filter=2, stride=(2, 2), pad=(1, 1), adj=(1, 1),
                              no_bias=True)
    assert out.shape == (1, 2, 8, 8)
    sym = mx.sym.Deconvolution(mx.sym.Variable("data"), kernel=(2, 2), num_filter=2,
                               stride=(2, 2), no_bias=True, name="dc")
    check_numeric_gradient(sym, {"data": x, "dc_weight": RNG.rand(3, 2, 2, 2).astype(np.float32) * 0.1},
                           rtol=8e-2)


def test_upsampling_bilinear_deconv_weight():
    """Bilinear UpSampling is the reference's depthwise transposed conv
    (upsampling.cc:19-35): the weight input shapes as (C,1,k,k), a
    Bilinear-initialized weight interpolates, and the weight receives a
    real (nonzero) gradient — r3 verdict weak #4."""
    C, scale = 3, 2
    data = mx.sym.Variable("data")
    up = mx.sym.UpSampling(data, scale=scale, sample_type="bilinear",
                           num_filter=C, num_args=2, name="upsampling0")
    x = np.random.RandomState(0).rand(2, C, 5, 5).astype(np.float32)
    ex = up.simple_bind(ctx=mx.cpu(), data=x.shape, grad_req="write")
    # inferred weight shape is the depthwise deconv filter
    k = 2 * scale - scale % 2
    wname = [n for n in ex.arg_dict if n.endswith("weight")][0]
    assert ex.arg_dict[wname].shape == (C, 1, k, k)
    # bilinear-seeded weight (name-triggered _init_bilinear)
    init = mx.initializer.Uniform(0.1)
    init("upsampling0_weight", ex.arg_dict[wname])
    ex.arg_dict["data"][:] = x
    out = ex.forward(is_train=True)[0].asnumpy()
    assert out.shape == (2, C, 10, 10)
    # constant input -> interior output equals the constant (borders
    # attenuate: the transposed conv's zero padding, as in the
    # reference's deconv lowering)
    ex.arg_dict["data"][:] = np.ones_like(x)
    out1 = ex.forward(is_train=True)[0].asnumpy()
    np.testing.assert_allclose(out1[:, :, 2:-2, 2:-2], 1.0, rtol=1e-5)
    # the weight trains: nonzero gradient flows to it
    ex.backward([mx.nd.ones(out.shape)])
    gw = ex.grad_dict[wname].asnumpy()
    assert np.abs(gw).sum() > 0


def test_softmax_cross_entropy_nd():
    """softmax_cross_entropy accepts any leading shape (r3 weak #5)."""
    rng = np.random.RandomState(0)
    for shape in [(4, 7), (2, 3, 7), (2, 3, 4, 7)]:
        x = rng.randn(*shape).astype(np.float32)
        lab = rng.randint(0, 7, size=shape[:-1]).astype(np.float32)
        out = mx.nd.softmax_cross_entropy(mx.nd.array(x),
                                          mx.nd.array(lab)).asnumpy()
        p = x - x.max(-1, keepdims=True)
        logp = p - np.log(np.exp(p).sum(-1, keepdims=True))
        want = -np.take_along_axis(
            logp, lab.astype(np.int64)[..., None], axis=-1).sum()
        np.testing.assert_allclose(out, [want], rtol=1e-4)
