"""BucketingModule / SequentialModule / PythonModule tests.

Models the reference's bucketing usage (example/rnn/lstm_bucketing.py +
module tests): variable-length LSTM LM over ≥3 buckets with one shared
parameter storage and one optimizer; module chaining; python loss.
"""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.io import DataBatch, DataDesc

V, E, H = 16, 8, 16
BATCH = 8
BUCKETS = [4, 6, 8]


def _lstm_lm_sym(seq_len):
    """Embedding -> LSTM -> per-step softmax over the vocab."""
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    embed = mx.sym.Embedding(data, input_dim=V, output_dim=E, name="embed")
    tnc = mx.sym.transpose(embed, axes=(1, 0, 2))
    rnn = mx.sym.RNN(data=tnc, parameters=mx.sym.Variable("rnn_parameters"),
                     state=mx.sym.Variable("rnn_s"),
                     state_cell=mx.sym.Variable("rnn_c"),
                     state_size=H, num_layers=1, mode="lstm", name="rnn")
    btc = mx.sym.transpose(rnn, axes=(1, 0, 2))
    pred = mx.sym.Reshape(btc, shape=(-1, H))
    pred = mx.sym.FullyConnected(pred, num_hidden=V, name="pred")
    flat_label = mx.sym.Reshape(label, shape=(-1,))
    sm = mx.sym.SoftmaxOutput(pred, flat_label, name="softmax")
    return sm, ("data",), ("softmax_label",)


def _bucket_batches(rng, n_per_bucket=6):
    """Synthetic LM: next token = (tok + 1) % V, variable lengths."""
    batches = []
    for seq_len in BUCKETS:
        for _ in range(n_per_bucket):
            start = rng.randint(0, V, size=(BATCH, 1))
            toks = (start + np.arange(seq_len + 1)) % V
            data = toks[:, :-1].astype(np.float32)
            label = toks[:, 1:].astype(np.float32)
            batches.append(DataBatch(
                [mx.nd.array(data)], [mx.nd.array(label)], pad=0,
                bucket_key=seq_len,
                provide_data=[DataDesc("data", (BATCH, seq_len))],
                provide_label=[DataDesc("softmax_label", (BATCH, seq_len))]))
    rng.shuffle(batches)
    return batches


def _ce_loss(mod, batch):
    out = mod.get_outputs()[0].asnumpy()  # (B*T, V)
    lab = batch.label[0].asnumpy().reshape(-1).astype(int)
    p = out[np.arange(out.shape[0]), lab]
    return float(-np.log(np.maximum(p, 1e-9)).mean())


def _make_bucketing_module():
    mod = mx.mod.BucketingModule(_lstm_lm_sym,
                                 default_bucket_key=max(BUCKETS),
                                 context=mx.cpu())
    mod.bind(data_shapes=[DataDesc("data", (BATCH, max(BUCKETS)))],
             label_shapes=[DataDesc("softmax_label", (BATCH, max(BUCKETS)))])
    mx.random.seed(7)
    zeros_s = mx.nd.zeros((1, BATCH, H))
    mod.init_params(mx.initializer.Uniform(0.1),
                    arg_params={"rnn_s": zeros_s, "rnn_c": zeros_s.copy()})
    return mod


def test_bucketing_shared_param_buffers():
    mod = _make_bucketing_module()
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    rng = np.random.RandomState(0)
    for b in _bucket_batches(rng, n_per_bucket=1):
        mod.forward(b, is_train=True)
        mod.backward()
        mod.update()
    assert set(mod._buckets) == set(BUCKETS)
    default = mod._buckets[max(BUCKETS)]
    for key in BUCKETS:
        other = mod._buckets[key]
        for pname in ("pred_weight", "embed_weight", "rnn_parameters"):
            assert other._exec.arg_dict[pname] is default._exec.arg_dict[pname], \
                f"bucket {key} param {pname} is not the shared buffer"


def test_bucketing_lstm_perplexity_drops():
    mod = _make_bucketing_module()
    mod.init_optimizer(kvstore=None, optimizer="adam",
                       optimizer_params={"learning_rate": 0.01})
    rng = np.random.RandomState(1)
    first_losses, last_losses = [], []
    for epoch in range(8):
        for b in _bucket_batches(rng, n_per_bucket=3):
            mod.forward(b, is_train=True)
            mod.backward()
            mod.update()
            loss = _ce_loss(mod, b)
            if epoch == 0:
                first_losses.append(loss)
            elif epoch == 7:
                last_losses.append(loss)
    ppl0 = np.exp(np.mean(first_losses))
    ppl1 = np.exp(np.mean(last_losses))
    assert ppl1 < ppl0 * 0.5, f"perplexity {ppl0} -> {ppl1} did not drop"
    # fused step counter is continuous across buckets: one optimizer
    t = int(np.asarray(mod._curr_module._fused_t))
    assert t == 8 * 3 * len(BUCKETS), t


def test_bucketing_inference_switches():
    mod = _make_bucketing_module()
    rng = np.random.RandomState(2)
    for b in _bucket_batches(rng, n_per_bucket=1):
        mod.forward(b, is_train=False)
        out = mod.get_outputs()[0]
        assert out.shape == (BATCH * b.bucket_key, V)


def test_sequential_module_trains():
    np.random.seed(3)
    rng = np.random.RandomState(3)
    X = rng.randn(200, 10).astype(np.float32)
    yv = np.argmax(X @ rng.randn(10, 3), axis=1).astype(np.float32)

    d1 = mx.sym.Variable("data")
    feat = mx.sym.Activation(
        mx.sym.FullyConnected(d1, num_hidden=24, name="fc1"),
        act_type="relu", name="relu1")
    m1 = mx.mod.Module(feat, label_names=None, context=mx.cpu())

    d2 = mx.sym.Variable("data")
    head = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(d2, num_hidden=3, name="fc2"), name="softmax")
    m2 = mx.mod.Module(head, context=mx.cpu())

    seq = mx.mod.SequentialModule()
    seq.add(m1).add(m2, take_labels=True, auto_wiring=True)
    it = mx.io.NDArrayIter(X, yv, batch_size=20, shuffle=True)
    seq.fit(it, num_epoch=8, optimizer="sgd",
            optimizer_params={"learning_rate": 0.3})
    score = seq.score(mx.io.NDArrayIter(X, yv, batch_size=20), "acc")
    assert score[0][1] > 0.85, score


def test_python_loss_module():
    np.random.seed(4)
    rng = np.random.RandomState(4)
    X = rng.randn(120, 6).astype(np.float32)
    yv = (X.sum(axis=1) > 0).astype(np.float32)

    d = mx.sym.Variable("data")
    logits = mx.sym.FullyConnected(d, num_hidden=2, name="fc")
    m1 = mx.mod.Module(logits, label_names=None, context=mx.cpu())

    def ce_grad(scores, labels):
        s = scores.asnumpy()
        lab = labels.asnumpy().astype(int)
        p = np.exp(s - s.max(axis=1, keepdims=True))
        p /= p.sum(axis=1, keepdims=True)
        p[np.arange(len(lab)), lab] -= 1.0
        return p / len(lab)

    loss = mx.mod.PythonLossModule(grad_func=ce_grad)
    seq = mx.mod.SequentialModule()
    seq.add(m1).add(loss, take_labels=True, auto_wiring=True)
    it = mx.io.NDArrayIter(X, yv, batch_size=20)
    seq.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    seq.init_params(mx.initializer.Uniform(0.1))
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5,
                                         "rescale_grad": 1.0})
    for _ in range(12):
        it.reset()
        for b in it:
            seq.forward(b, is_train=True)
            seq.backward()
            seq.update()
    # accuracy from the logits module
    it.reset()
    correct = n = 0
    for b in it:
        seq.forward(b, is_train=False)
        pred = seq.get_outputs()[0].asnumpy().argmax(axis=1)
        lab = b.label[0].asnumpy().astype(int)
        correct += (pred == lab).sum()
        n += len(lab)
    assert correct / n > 0.9, correct / n


def test_bucketing_force_rebind_resumes_training():
    mod = _make_bucketing_module()
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    rng = np.random.RandomState(5)
    batches = _bucket_batches(rng, n_per_bucket=1)
    for b in batches[:3]:
        mod.forward(b, is_train=True); mod.backward(); mod.update()
    w_before = mod.get_params()[0]["pred_weight"].asnumpy().copy()
    mod.bind(data_shapes=[DataDesc("data", (BATCH, max(BUCKETS)))],
             label_shapes=[DataDesc("softmax_label", (BATCH, max(BUCKETS)))],
             force_rebind=True)
    # params survived the rebind
    np.testing.assert_allclose(mod.get_params()[0]["pred_weight"].asnumpy(),
                               w_before)
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    for b in batches[:3]:
        mod.forward(b, is_train=True); mod.backward(); mod.update()
    assert not np.allclose(mod.get_params()[0]["pred_weight"].asnumpy(),
                           w_before)
