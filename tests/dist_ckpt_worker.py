"""2-process kill-and-resume acceptance worker.

Launched by ``tools/launch.py -n 2 --cpu python
tests/dist_ckpt_worker.py <ckpt_dir> <out_prefix>``.  Each rank trains
``Module.fit`` with ``kvstore='dist_sync'`` on its deterministic data
shard, checkpointing SYNCHRONOUSLY every 4 steps — the kvstore barrier
is the all-shards gate before rank 0's COMMIT, so
``MXNET_CKPT_CRASH=before_commit:<n>`` kills every rank exactly
between the barrier and the commit (the torn-checkpoint window the
protocol must survive).  With ``resume='auto'`` a relaunch restores
params + optimizer (replicated-updater momentum) + iterator position
from the last committed checkpoint and must reproduce an uninterrupted
run's final weights bit-for-bit (asserted by
tests/test_dist.py::test_ckpt_kill_and_resume)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx

GLOBAL_BATCH = 8
N_SAMPLES = 64
EPOCHS = 2
CLASSES = 10


def build_sym():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=24, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def make_data():
    rng = np.random.RandomState(5)
    X = rng.randn(N_SAMPLES, 16).astype(np.float32)
    y = rng.randint(0, CLASSES, N_SAMPLES).astype(np.float32)
    return X, y


def shard(X, y, rank, num_workers):
    B = GLOBAL_BATCH // num_workers
    idx = []
    for g in range(N_SAMPLES // GLOBAL_BATCH):
        start = g * GLOBAL_BATCH + rank * B
        idx.extend(range(start, start + B))
    return X[idx], y[idx]


def main():
    import logging

    # the test asserts on the manager's "resuming from ... step N" line
    logging.basicConfig(level=logging.INFO)
    ckpt_dir, out_prefix = sys.argv[1], sys.argv[2]
    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    X, y = make_data()
    Xs, ys = shard(X, y, rank, nw)

    mx.random.seed(7)
    np.random.seed(7)
    it = mx.io.NDArrayIter(Xs, ys, batch_size=GLOBAL_BATCH // nw,
                           shuffle=False, label_name="softmax_label")
    mod = mx.mod.Module(build_sym(), context=mx.cpu())
    mgr = mx.CheckpointManager(ckpt_dir, every_n_steps=4, async_save=False,
                               keep=8, kvstore=kv)
    mod.fit(it, num_epoch=EPOCHS, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                              "rescale_grad": 1.0 / GLOBAL_BATCH},
            kvstore=kv, initializer=mx.initializer.Xavier(rnd_type="gaussian"),
            eval_metric="acc", checkpoint=mgr, resume="auto")
    mgr.close()
    args_, _ = mod.get_params()
    np.savez(out_prefix + f".rank{rank}",
             **{k: v.asnumpy() for k, v in args_.items()})
    kv.barrier()
    print(f"worker {rank}/{nw}: ckpt dist fit OK", flush=True)


if __name__ == "__main__":
    main()
