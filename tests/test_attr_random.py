"""Attribute-scope + RNG tests (reference:
tests/python/unittest/test_attr.py + test_random.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx


# ----------------------------------------------------------------- attrs
def test_attr_basic():
    data = mx.sym.Variable("data", attr={"mood": "angry"})
    op = mx.sym.Convolution(data, name="conv", kernel=(1, 1), num_filter=1,
                            attr={"__mood__": "so so"})
    assert data.attr("mood") == "angry"
    assert op.attr("__mood__") == "so so"


def test_attr_scope_propagation():
    with mx.AttrScope(__group__="4", __data__="great"):
        data = mx.sym.Variable("data", attr={"specific": "code"})
        gdata = mx.sym.Variable("data2")
    assert gdata.attr("__group__") == "4"
    assert data.attr("__group__") == "4"
    assert data.attr("specific") == "code"
    assert data.attr("__data__") == "great"


def test_attr_scope_nesting():
    with mx.AttrScope(x="1"):
        with mx.AttrScope(y="2"):
            v = mx.sym.Variable("v")
        w = mx.sym.Variable("w")
    assert v.attr("x") == "1" and v.attr("y") == "2"
    assert w.attr("x") == "1" and w.attr("y") is None


def test_attr_survives_json_roundtrip():
    with mx.AttrScope(ctx_group="stage1"):
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    loaded = mx.sym.load_json(net.tojson())
    d = loaded.attr_dict()
    assert d["fc"].get("ctx_group") == "stage1"


# ------------------------------------------------------------------- rng
def test_random_seed_determinism():
    mx.random.seed(128)
    a = mx.nd.uniform(0, 1, shape=(100,)).asnumpy()
    mx.random.seed(128)
    b = mx.nd.uniform(0, 1, shape=(100,)).asnumpy()
    np.testing.assert_array_equal(a, b)
    c = mx.nd.uniform(0, 1, shape=(100,)).asnumpy()
    assert not np.array_equal(b, c)  # stream advances


def test_uniform_moments():
    mx.random.seed(0)
    x = mx.nd.uniform(-2.0, 6.0, shape=(50000,)).asnumpy()
    assert x.min() >= -2.0 and x.max() < 6.0
    assert abs(x.mean() - 2.0) < 0.1
    assert abs(x.std() - 8.0 / np.sqrt(12)) < 0.1


def test_normal_moments():
    mx.random.seed(1)
    x = mx.nd.normal(3.0, 2.0, shape=(50000,)).asnumpy()
    assert abs(x.mean() - 3.0) < 0.1
    assert abs(x.std() - 2.0) < 0.1


def test_dropout_uses_fresh_masks():
    """Two training forwards draw different dropout masks (the
    ResourceManager kRandom role: per-invocation PRNG)."""
    sym = mx.sym.Dropout(mx.sym.Variable("data"), p=0.5, name="drop")
    exe = sym.simple_bind(mx.cpu(), grad_req="null", data=(64, 64))
    exe.arg_dict["data"][:] = np.ones((64, 64), np.float32)
    a = exe.forward(is_train=True)[0].asnumpy()
    b = exe.forward(is_train=True)[0].asnumpy()
    assert not np.array_equal(a, b)
    # inference: identity
    c = exe.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(c, 1.0)


# ---------------------------------------------------------------- engine
def test_engine_types_same_results():
    """All engine modes compute identical results over a random
    dependency workload (reference: tests/cpp/threaded_engine_test.cc)."""
    rng = np.random.RandomState(0)
    a0 = rng.randn(16, 16).astype(np.float32)

    def workload():
        x = mx.nd.array(a0)
        for i in range(10):
            y = mx.nd.dot(x, x) * 0.01
            x = x + y - mx.nd.mean(y)
        return x.asnumpy()

    baseline = workload()
    for et in ("NaiveEngine", "ThreadedEngine", "ThreadedEnginePerDevice"):
        mx.engine.set_engine_type(et)
        try:
            np.testing.assert_allclose(workload(), baseline, rtol=1e-6)
        finally:
            mx.engine.set_engine_type("ThreadedEnginePerDevice")


def test_engine_naive_blocks_and_push():
    mx.engine.set_engine_type("NaiveEngine")
    try:
        assert mx.engine.is_naive()
        x = mx.nd.uniform(0, 1, shape=(8, 8))
        y = mx.nd.dot(x, x)  # completes synchronously under NaiveEngine
        ran = []
        mx.engine.push(lambda: ran.append(True), read_arrays=[y])
        assert ran == [True]
        mx.engine.wait_for_var(y)
        mx.engine.wait_all()
    finally:
        mx.engine.set_engine_type("ThreadedEnginePerDevice")
    with pytest.raises(mx.MXNetError):
        mx.engine.set_engine_type("TurboEngine")

