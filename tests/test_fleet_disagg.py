"""Disaggregated prefill/decode serving: live KV page migration
(engine-level splice bit-identity across pool dtypes), the Router's
replica roles + phase machinery (in-process fake replicas), role
autoscaling, and the chaos/env surface.

The real multi-process per-role kill -9 drills live in
tools/bench_fleet.py (--disagg-drill prefill|decode) and run under the
``slow`` marker here.
"""

import json
import os
import queue
import subprocess
import sys
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models, wire
from mxnet_tpu.base import MXNetError
from mxnet_tpu.fleet import REPLICA_ROLES, Router, roles_env
from mxnet_tpu.kv_cache import BlockAllocator
from mxnet_tpu.serving import ReplicaHarness

V, KVB, L, H, DM, MAXLEN = 61, 4, 2, 2, 32, 32


@pytest.fixture(scope="module")
def lm_params():
    sym = models.transformer_lm(V, MAXLEN, num_layers=L, num_heads=H,
                                d_model=DM, block_size=KVB)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, MAXLEN))],
             label_shapes=[("softmax_label", (2, MAXLEN))],
             for_training=False)
    mod.init_params(mx.initializer.Xavier(factor_type="in",
                                          magnitude=2.0))
    arg, aux = mod.get_params()
    return {**arg, **aux}


def _engine(params, **kw):
    args = dict(vocab_size=V, num_layers=L, num_heads=H, d_model=DM,
                max_len=MAXLEN, kv_block=KVB, max_streams=4,
                decode_buckets=[1, 2, 4], temperature=0.0)
    args.update(kw)
    return mx.DecodeEngine(params, **args)


def _fp8_available():
    try:
        import ml_dtypes  # noqa: F401

        np.dtype(ml_dtypes.float8_e4m3fn)
        return True
    except Exception:  # noqa: BLE001
        return False


# ---------------------------------------------------------------------------
# engine-level migration: export → import splice is bit-identical
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kv_dtype", [
    "fp32", "int8",
    pytest.param("fp8", marks=pytest.mark.skipif(
        not _fp8_available(), reason="ml_dtypes float8 unavailable")),
])
def test_migration_splice_bit_identity(lm_params, kv_dtype):
    """A stream prefilled on one engine, exported, and spliced into a
    second engine's pool decodes BIT-IDENTICALLY to the same seeds on
    a single never-migrated engine — quantized pools ship their value
    slabs at wire dtype plus their scale slabs, so the splice is exact
    regardless of pool storage."""
    prompt = np.asarray([7, 3, 11, 2, 5], np.int32)
    ref = _engine(lm_params, kv_dtype=kv_dtype)
    try:
        want = np.asarray(
            ref.submit(prompt, 10, temperature=0.9, seed=5).result(120))
    finally:
        ref.close()
    pre = _engine(lm_params, kv_dtype=kv_dtype)
    dec = _engine(lm_params, kv_dtype=kv_dtype)
    try:
        pay = pre.submit(prompt, 10, temperature=0.9, seed=5,
                         prefill_only=True).result(120)
        meta, arrays = pay["meta"], pay["kv_arrays"]
        assert meta["n_pages"] > 0 and meta["kv_dtype"] == kv_dtype
        # pages left the exporter's pool (not leaked, not still live)
        assert pre.stats()["migrations_out"] == 1
        got = np.asarray(dec.import_stream(meta, arrays).result(120))
        assert np.array_equal(got, want), (got, want)
        # the exporter produced the first token; the importer decoded
        # the rest from the spliced pages — bit-identity proves the
        # (engine seed, stream seed, position) sampling contract held
        assert dec.stats()["migrations_in"] == 1
    finally:
        pre.close()
        dec.close()


def test_migration_cost_conservation(lm_params):
    """sum(per-stream CostRecords) == stats() for the new
    migration_bytes/migration_ms fields — the PR-13 conservation
    contract extends to the migration counters (same increment site)."""
    prompt = np.asarray([9, 4, 1, 8], np.int32)
    pre = _engine(lm_params)
    try:
        pay = pre.submit(prompt, 6, temperature=0.8, seed=3,
                         prefill_only=True).result(120)
        assert pay["meta"]["migration_bytes"] > 0
        s = pre.stats()
        recs = pre.cost_records()
        assert sum(r["migration_bytes"] for r in recs) \
            == s["migration_bytes"] > 0
        assert abs(sum(r["migration_ms"] for r in recs)
                   - s["migration_ms"]) < 1e-3
        assert s["migrations_out"] == 1
        # export_ms rides the meta so the router can fold the engine-
        # side export cost into its end-to-end migration histogram
        assert pay["meta"]["export_ms"] > 0
    finally:
        pre.close()


def test_import_stream_validation_refuses_mismatches(lm_params):
    eng = _engine(lm_params)
    imp = _engine(lm_params, kv_dtype="int8")
    try:
        pay = eng.submit(np.asarray([5, 2, 7], np.int32), 6,
                         temperature=0.8, seed=2,
                         prefill_only=True).result(120)
        meta, arrays = pay["meta"], pay["kv_arrays"]
        with pytest.raises(MXNetError, match="kv_dtype"):
            imp.import_stream(meta, arrays)
        bad = dict(meta, kv_block=KVB * 2, kv_dtype="fp32")
        eng2 = _engine(lm_params)
        try:
            with pytest.raises(MXNetError, match="kv_block"):
                eng2.import_stream(bad, arrays)
            with pytest.raises(MXNetError, match="fmt"):
                eng2.import_stream(dict(meta, fmt=99), arrays)
            with pytest.raises(MXNetError):
                eng2.import_stream(meta, arrays[:-1])  # slab missing
        finally:
            eng2.close()
    finally:
        eng.close()
        imp.close()


def test_prefill_only_refused_on_meshed_engine(lm_params):
    eng = _engine(lm_params)
    try:
        eng._mesh = object()  # pretend tp/pp mesh
        with pytest.raises(MXNetError, match="mesh"):
            eng.submit(np.asarray([1, 2], np.int32), 4,
                       prefill_only=True)
    finally:
        eng._mesh = None
        eng.close()


# ---------------------------------------------------------------------------
# allocator: export/import page accounting
# ---------------------------------------------------------------------------


def test_block_allocator_export_import_pages():
    a = BlockAllocator(8, 4)  # capacity 7 (1 scratch)
    pages = a.alloc(3, owner=1)
    a.export_pages(pages)  # pages leave: slots return to the free list
    assert a.free_blocks == 7
    back = a.import_pages(3, owner=2)
    assert len(back) == 3 and a.free_blocks == 4
    with pytest.raises(MXNetError):
        a.export_pages([99])  # never allocated
    shared = a.alloc(1, owner=3)
    a.share(shared[0])  # refcount 2: a shared page must NOT export
    with pytest.raises(MXNetError, match="live references"):
        a.export_pages(shared)


# ---------------------------------------------------------------------------
# wire: signed page frames
# ---------------------------------------------------------------------------


def test_page_frame_roundtrip_and_mac():
    secret = b"s3cret"
    meta = {"fmt": 1, "sid": 4, "n_pages": 2, "kv_dtype": "int8"}
    arrays = [np.arange(6, dtype=np.int32),
              np.ones((2, 3), np.int8),
              np.full((2, 1), 0.5, np.float32)]  # scale slab
    frame = wire.pack_page_frame(secret, meta, arrays)
    m2, a2 = wire.unpack_page_frame(secret, memoryview(frame))
    assert m2 == meta and len(a2) == 3
    for x, y in zip(arrays, a2):
        assert x.dtype == y.dtype and np.array_equal(x, y)
    # the MAC covers the SLABS, not just the meta: flip one payload
    # byte and the whole frame must be refused
    tampered = bytearray(frame)
    tampered[len(frame) // 2] ^= 0xFF
    with pytest.raises(MXNetError, match="HMAC"):
        wire.unpack_page_frame(secret, memoryview(bytes(tampered)))
    with pytest.raises(MXNetError):
        wire.unpack_page_frame(b"", memoryview(frame))  # no secret


# ---------------------------------------------------------------------------
# Router roles + phase machinery (in-process fakes)
# ---------------------------------------------------------------------------


class RoleFake:
    """Role-aware in-process replica handle: phase-1 decode submits
    answer with a {"meta", "arrays"} payload, "migrate" specs continue
    deterministically from the meta — so router-level bit-identity is
    checkable without processes."""

    def __init__(self, rid, service_ms=2.0, blocks=64):
        self.rid = rid
        self.role = "mixed"
        self.service_s = service_ms / 1e3
        self.blocks = blocks
        self.served = []
        self.role_sets = []
        self._q = queue.Queue()
        self._lock = threading.Lock()
        self._inflight = set()
        self._accepting = True
        threading.Thread(target=self._run, daemon=True).start()

    def set_role(self, role):
        self.role = role
        self.role_sets.append(role)

    def submit(self, spec):
        fut = Future()
        with self._lock:
            if not self._accepting:
                raise ConnectionError(f"replica {self.rid} is down")
            self._inflight.add(fut)
        self._q.put((spec, fut))
        return fut

    def inflight(self):
        with self._lock:
            return len(self._inflight)

    def stats(self):
        return {"rid": self.rid, "role": self.role,
                "cache_blocks_free": self.blocks, "kv_block": KVB,
                "cache_util": 0.1}

    def close(self):
        pass

    def kill(self):
        with self._lock:
            self._accepting = False

    def _run(self):
        while True:
            spec, fut = self._q.get()
            time.sleep(self.service_s)
            try:
                res = self._answer(spec)
            except BaseException as exc:  # noqa: BLE001
                if fut.set_running_or_notify_cancel():
                    fut.set_exception(exc)
                continue
            with self._lock:
                self._inflight.discard(fut)
            self.served.append(spec)
            if fut.set_running_or_notify_cancel():
                fut.set_result(res)

    @staticmethod
    def _tokens(prompt_sum, seed, max_new):
        return [(prompt_sum * 7 + seed * 31 + i) % 997
                for i in range(max_new)]

    def _answer(self, spec):
        if spec["kind"] == "decode" and spec.get("phase"):
            p = np.asarray(spec["prompt"])
            toks = self._tokens(int(p.sum()), int(spec["seed"]),
                                int(spec["max_new"]))
            done = int(spec["max_new"]) <= 1
            n_pages = 0 if done else -(-(p.size + len(toks)) // KVB)
            meta = {"fmt": 1, "done": done, "n_pages": n_pages,
                    "migration_bytes": n_pages * 512, "export_ms": 0.05,
                    "seed": int(spec["seed"]),
                    "max_new": int(spec["max_new"]),
                    "prompt_sum": int(p.sum())}
            return {"meta": meta,
                    "arrays": [p.astype(np.int64),
                               np.asarray(toks[:1], np.int32)]}
        if spec["kind"] == "migrate":
            m = spec["meta"]
            return [np.asarray(self._tokens(m["prompt_sum"], m["seed"],
                                            m["max_new"]), np.int32)]
        if spec["kind"] == "decode":
            p = np.asarray(spec["prompt"])
            return [np.asarray(self._tokens(int(p.sum()),
                                            int(spec["seed"]),
                                            int(spec["max_new"])),
                               np.int32)]
        x = next(iter(spec["inputs"].values()))
        return [np.asarray(x, np.float64)]


def _expect(got, prompt, seed, max_new):
    s = int(np.asarray(prompt).sum())
    want = [(s * 7 + seed * 31 + i) % 997 for i in range(max_new)]
    assert np.array_equal(np.asarray(got), np.asarray(want, np.int32)), \
        (got, want)


def _router(reps, roles, **kw):
    kw.setdefault("retry_budget", 2)
    kw.setdefault("default_deadline_ms", 0)
    return Router(reps, roles=roles, **kw)


def test_router_disagg_routes_by_role_and_stays_bit_identical():
    reps = [RoleFake(0), RoleFake(1), RoleFake(2)]
    with _router(reps, ["prefill", "decode", "decode"]) as r:
        futs = [(i, r.generate(np.asarray([3, 5 + i], np.int32),
                               max_new_tokens=6, seed=11 + i))
                for i in range(8)]
        for i, f in futs:
            _expect(f.result(20), [3, 5 + i], 11 + i, 6)
        s = r.stats()
        assert s["migrations"] == 8 and s["migration_bytes"] > 0
        assert s["disagg"] is True and s["re_prefills"] == 0
        assert s["replicas"][0]["role"] == "prefill"
        assert s["migration_p50_ms"] is not None
        assert s["ttft_p99_ms"] is not None
        assert s["decode_per_token_p50_ms"] is not None
        # hard split: the prefill replica saw ONLY phase-1 work, the
        # decode replicas ONLY migrations
        assert all(sp.get("phase") for sp in reps[0].served)
        assert all(sp["kind"] == "migrate"
                   for sp in reps[1].served + reps[2].served)


def test_router_disagg_done_at_prefill_short_circuits():
    reps = [RoleFake(0), RoleFake(1)]
    with _router(reps, ["prefill", "decode"]) as r:
        out = r.generate(np.asarray([9], np.int32), max_new_tokens=1,
                         seed=3).result(20)
        _expect(out, [9], 3, 1)
        assert r.stats()["migrations"] == 0  # nothing shipped


def test_router_disagg_decode_death_re_prefills_exactly_once():
    reps = [RoleFake(0, service_ms=1.0), RoleFake(1, service_ms=60.0),
            RoleFake(2, service_ms=1.0)]
    with _router(reps, ["prefill", "decode", "decode"],
                 replica_depth=2) as r:
        reps[2].kill()  # all migrations pile onto slow decoder 1
        futs = [(i, r.generate(np.asarray([2, i], np.int32),
                               max_new_tokens=4, seed=7 + i))
                for i in range(6)]
        time.sleep(0.08)  # first migrations in service on replica 1,
        reps[1].kill()    # the rest queued behind its depth
        reps[2]._accepting = True  # re-prefill target lives again
        for i, f in futs:
            _expect(f.result(30), [2, i], 7 + i, 4)
        s = r.stats()
        # a dead decode replica's spliced pages are gone: delivery ran
        # through the re-prefill retry path, and still exactly once
        assert s["responses"] == 6 and s["re_prefills"] >= 1


def test_router_disagg_prefill_death_degrades_to_classic():
    reps = [RoleFake(0), RoleFake(1)]
    with _router(reps, ["prefill", "decode"]) as r:
        reps[0].kill()
        out = r.generate(np.asarray([4, 4], np.int32), max_new_tokens=3,
                         seed=5).result(30)
        # the lone decode-role survivor serves the stream end-to-end
        _expect(out, [4, 4], 5, 3)
        assert any(sp["kind"] == "decode" and not sp.get("phase")
                   for sp in reps[1].served)


def test_router_set_role_flips_and_guards():
    reps = [RoleFake(0), RoleFake(1), RoleFake(2)]
    with _router(reps, ["prefill", "decode", "decode"]) as r:
        rep = r.set_role(2, "prefill")
        assert rep["flipped"] and reps[2].role == "prefill"
        assert r.stats()["role_flips"] == 1
        assert r.stats()["replicas"][2]["role"] == "prefill"
        with pytest.raises(MXNetError, match="last"):
            r.set_role(1, "prefill")  # would strip the decode side
        with pytest.raises(MXNetError, match="must be one of"):
            r.set_role(0, "turbo")
        assert r.set_role(2, "prefill")["flipped"] is False  # no-op


def test_router_autoscale_flips_under_decode_pressure():
    """Shifting workload drill: long-prompt streams pile migrations
    onto the single slow decode replica; one autoscale evaluation must
    flip a prefill replica to decode (and shed nothing)."""
    reps = [RoleFake(0, service_ms=1.0), RoleFake(1, service_ms=1.0),
            RoleFake(2, service_ms=80.0)]
    with _router(reps, ["prefill", "prefill", "decode"],
                 replica_depth=2) as r:
        r._cost[("decode", 4)] = 2.0
        r._cost[("migrate", 4)] = 80.0
        futs = [(i, r.generate(np.asarray([6, i], np.int32),
                               max_new_tokens=4, seed=3 + i))
                for i in range(8)]
        # wait until migrations queue behind the lone decoder's depth
        deadline = time.monotonic() + 10.0
        flip = None
        while time.monotonic() < deadline:
            flip = r.autoscale_once()
            if flip is not None:
                break
            time.sleep(0.02)
        assert flip is not None and flip["role"] == "decode"
        assert flip["pressure"]["decode"] > flip["pressure"]["prefill"]
        for i, f in futs:
            _expect(f.result(60), [6, i], 3 + i, 4)
        s = r.stats()
        assert s["role_flips"] >= 1 and s["shed"] == 0


def test_roles_env_parses_and_refuses_garbage(monkeypatch):
    monkeypatch.delenv("MXNET_FLEET_ROLES", raising=False)
    assert roles_env() is None
    monkeypatch.setenv("MXNET_FLEET_ROLES", "prefill,decode,mixed")
    assert roles_env() == ["prefill", "decode", "mixed"]
    monkeypatch.setenv("MXNET_FLEET_ROLES", "prefill,turbo")
    with pytest.raises(MXNetError, match="turbo"):
        roles_env()
    monkeypatch.setenv("MXNET_FLEET_ROLES", "prefill,prefill")
    with pytest.raises(MXNetError, match="one-sided|BOTH"):
        roles_env()
    for role in REPLICA_ROLES:
        monkeypatch.setenv("MXNET_FLEET_ROLES", f"{role}" if role ==
                           "mixed" else "prefill,decode")
        assert roles_env() is not None


def test_router_roles_kwarg_validation():
    reps = [RoleFake(0), RoleFake(1)]
    with pytest.raises(MXNetError, match="every replica"):
        Router(reps, roles=["prefill"])
    for rep in reps:
        rep.close()
    reps = [RoleFake(0), RoleFake(1)]
    with pytest.raises(MXNetError, match="BOTH"):
        Router(reps, roles=["prefill", "prefill"])


def test_harness_role_surface(lm_params):
    eng = _engine(lm_params)
    h = ReplicaHarness(eng)
    try:
        assert "role" not in h.stats()  # roles never enabled
        h.set_role("prefill")
        assert h.stats()["role"] == "prefill"
        with pytest.raises(MXNetError, match="must be one of"):
            h.set_role("turbo")
        h.set_role("decode")
        with pytest.raises(MXNetError, match="prefill-role"):
            h.submit_prefill_export(np.asarray([1, 2], np.int32))
        h.set_role("prefill")
        with pytest.raises(MXNetError, match="prefill"):
            h.submit_import({"fmt": 1}, [])
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# chaos: the migration-tear fault point
# ---------------------------------------------------------------------------


def test_chaos_migration_tear_validated_and_armed(monkeypatch):
    from mxnet_tpu import chaos

    monkeypatch.setenv("MXNET_CHAOS_MIGRATION_TEAR", "garbage")
    chaos.reset_chaos()
    with pytest.raises(MXNetError, match="MXNET_CHAOS_MIGRATION_TEAR"):
        chaos.get_chaos()
    monkeypatch.setenv("MXNET_CHAOS_MIGRATION_TEAR", "0")
    chaos.reset_chaos()
    with pytest.raises(MXNetError):
        chaos.get_chaos()  # minimum is 1: the 0th frame cannot exist
    monkeypatch.setenv("MXNET_CHAOS_MIGRATION_TEAR", "2")
    chaos.reset_chaos()
    ch = chaos.get_chaos()
    assert ch.armed and ch.migration_tear == 2

    class Sock:
        def __init__(self):
            self.sent = b""
            self.dead = False

        def sendall(self, b):
            self.sent += b

        def shutdown(self, how):
            self.dead = True

        def close(self):
            pass

    frame = b"x" * 100
    s1, s2 = Sock(), Sock()
    assert ch.torn_migration_send(s1, frame) is False  # frame 1 passes
    assert ch.torn_migration_send(s2, frame) is True   # frame 2 torn
    assert s1.sent == b"" and s2.dead
    # torn = length header promising 100 bytes, only half delivered
    assert s2.sent == wire.U32.pack(100) + frame[:50]
    monkeypatch.delenv("MXNET_CHAOS_MIGRATION_TEAR")
    chaos.reset_chaos()


# ---------------------------------------------------------------------------
# multi-process per-role kill -9 drills (slow)
# ---------------------------------------------------------------------------


def _run_disagg_drill(role, tmp_path):
    drill = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "tools", "bench_fleet.py"),
         "--disagg-drill", role, "--replicas", "3", "--requests", "12",
         "--fleet-dir", str(tmp_path / "fleet")],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "MXNET_DEAD_RANK_TIMEOUT": "3.0",
             "MXNET_HEARTBEAT_INTERVAL": "0.2"})
    assert drill.returncode == 0, drill.stderr[-4000:]
    verdict = json.loads(drill.stdout.strip().splitlines()[-1])
    assert verdict["lost"] == 0
    assert verdict["mismatched"] == 0
    assert verdict["replica_deaths"] >= 1
    assert verdict["migrations"] > 0
    return verdict


@pytest.mark.slow
@pytest.mark.skipif((os.cpu_count() or 1) < 3, reason="needs >= 3 cores")
def test_disagg_kill9_decode_role_loses_nothing(tmp_path):
    """kill -9 a decode-role replica mid-stream: spliced pages die
    with it; every stream re-prefills and delivers bit-identically."""
    verdict = _run_disagg_drill("decode", tmp_path)
    assert verdict["re_prefills"] >= 0  # may be 0 if kill landed between migrations
    assert verdict["migration_edge_in_trace"]


@pytest.mark.slow
@pytest.mark.skipif((os.cpu_count() or 1) < 3, reason="needs >= 3 cores")
def test_disagg_kill9_prefill_role_loses_nothing(tmp_path):
    """kill -9 THE prefill-role replica mid-stream: in-flight prefills
    retry on the survivors (the fleet degrades to classic routing)."""
    _run_disagg_drill("prefill", tmp_path)
