"""PR 12 observability: distributed trace context, the crash flight
recorder, live goodput/MFU accounting, and the ops HTTP surface.

The centerpiece is the two-process stitching test: one traced request
routed through a real Router → wire → ReplicaServer subprocess comes
back as ONE span tree with monotonic, clock-aligned parent/child
bounds across both processes — recovered entirely from the always-on
flight-recorder ring files (no profiler needed)."""

import json
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import profiler

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))


# ---------------------------------------------------------------------------
# trace context
# ---------------------------------------------------------------------------


def test_trace_context_header_roundtrip():
    ctx = profiler.TraceContext()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    header = ctx.to_header()
    assert header == f"00-{ctx.trace_id}-{ctx.span_id}-01"
    back = profiler.TraceContext.from_header(header)
    assert back.trace_id == ctx.trace_id
    assert back.span_id == ctx.span_id  # sender's span = my parent
    child = back.child()
    assert child.trace_id == ctx.trace_id
    assert child.parent_id == ctx.span_id
    assert child.span_id != ctx.span_id
    for bad in ("", "00-zz-xx-01", "00-abc-def-01", "nonsense"):
        with pytest.raises(ValueError):
            profiler.TraceContext.from_header(bad)


def test_wire_trace_field_roundtrip():
    from mxnet_tpu import wire

    ctx = profiler.TraceContext()
    buf = memoryview(wire.pack_trace(ctx) + b"tail")
    back, off = wire.unpack_trace(buf, 0)
    assert back.trace_id == ctx.trace_id
    assert bytes(buf[off:]) == b"tail"
    # absent = one byte, parses to None
    none_buf = memoryview(wire.pack_trace(None) + b"x")
    assert len(wire.pack_trace(None)) == 1
    got, off = wire.unpack_trace(none_buf, 0)
    assert got is None and bytes(none_buf[off:]) == b"x"
    # a malformed header drops to None instead of failing the request
    raw = bytes([9]) + b"not-a-tp!" + b"y"
    got, off = wire.unpack_trace(memoryview(raw), 0)
    assert got is None and raw[off:] == b"y"


def test_trace_sampling_deterministic(monkeypatch):
    monkeypatch.setenv("MXNET_TRACE_SAMPLE", "0.0")
    profiler._TRACE_SAMPLE = None  # re-read the env
    assert profiler.make_trace(key=7) is None
    monkeypatch.setenv("MXNET_TRACE_SAMPLE", "1.0")
    profiler._TRACE_SAMPLE = None
    assert profiler.make_trace(key=7) is not None
    monkeypatch.setenv("MXNET_TRACE_SAMPLE", "0.5")
    profiler._TRACE_SAMPLE = None
    a = [profiler.make_trace(key=k) is not None for k in range(64)]
    b = [profiler.make_trace(key=k) is not None for k in range(64)]
    assert a == b  # deterministic per key: retries keep their verdict
    assert 5 < sum(a) < 60  # and it actually samples
    monkeypatch.setenv("MXNET_TRACE_SAMPLE", "banana")
    profiler._TRACE_SAMPLE = None
    with pytest.raises(mx.MXNetError):
        profiler.make_trace()
    monkeypatch.delenv("MXNET_TRACE_SAMPLE")
    profiler._TRACE_SAMPLE = None


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_ring_bounded_and_always_on():
    rec = profiler.flight_recorder()
    cap = rec.capacity
    with profiler.trace_span("flight.unit", profiler.TraceContext(),
                             args={"k": 1}):
        pass
    names = [e["name"] for e in rec.snapshot()]
    assert "flight.unit" in names  # recorded with the profiler OFF
    for i in range(cap * 2):
        rec.record({"name": f"fill{i}", "ph": "X", "ts": 0.0,
                    "dur": 0.0, "pid": 0, "tid": 0})
    assert len(rec.snapshot()) == cap  # bounded, oldest dropped
    assert rec.snapshot()[-1]["name"] == f"fill{cap * 2 - 1}"


def test_flight_ring_file_survives_and_reads_back(tmp_path):
    rec = profiler.FlightRecorder(capacity=64,
                                  file_path=str(tmp_path / "t.ring"),
                                  file_bytes=4096)
    for i in range(200):  # force several wraps of the 4 KiB data ring
        rec.record({"name": f"ev{i}", "ph": "X", "ts": float(i),
                    "dur": 1.0, "pid": 1, "tid": 2})
    rec.sync()
    doc = profiler.read_flight_file(str(tmp_path / "t.ring"))
    evs = doc["traceEvents"]
    assert evs and evs[-1]["name"] == "ev199"
    # only whole lines (the torn line at the seam is skipped)
    assert all(e["name"].startswith("ev") for e in evs)
    # newest-first contiguity: recovered ids are the trailing ones
    ids = [int(e["name"][2:]) for e in evs]
    assert ids == sorted(ids)
    assert "clock_sync" in doc["metadata"]
    # trace_merge's standalone reader agrees with the library's
    import trace_merge as tm

    doc2 = tm.load_trace(str(tmp_path / "t.ring"))
    assert [e["name"] for e in doc2["traceEvents"]] == \
        [e["name"] for e in evs]


def test_flight_dump_on_engine_loop_crash(tmp_path, monkeypatch):
    """An injected BaseException in the serving path kills the batch
    loop; the loop's crash handler must leave a post-mortem JSON with
    the recent spans before poisoning the futures."""
    monkeypatch.setenv("MXNET_FLIGHT_RECORDER_DIR", str(tmp_path))
    profiler._flight_dumped.clear()  # defeat cross-test rate limiting

    class Boom(BaseException):  # escapes `except Exception` layers
        pass

    pred = mx.Predictor(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                              name="fc"),
        {"fc_weight": np.zeros((2, 3), np.float32),
         "fc_bias": np.zeros(2, np.float32)},
        {"data": (1, 3)})
    eng = mx.InferenceEngine(pred, buckets=(1,))

    def explode(bucket, donate):
        raise Boom("injected engine-loop crash")

    monkeypatch.setattr(eng._model, "compile", explode)
    fut = eng.submit({"data": np.zeros((1, 3), np.float32)})
    # the future carries the ORIGINAL cause (not a generic closed
    # error): the dispatch failure net catches BaseException too
    with pytest.raises(Boom):
        fut.result(timeout=30)
    deadline = time.time() + 10
    dump = None
    while time.time() < deadline and dump is None:
        found = [f for f in os.listdir(tmp_path)
                 if f.startswith("flightdump_") and "engine_crash" in f
                 and f.endswith(".json")]  # not the .tmp mid-rename
        dump = found[0] if found else None
        time.sleep(0.05)
    assert dump is not None, "no post-mortem dump after loop crash"
    with open(tmp_path / dump) as f:
        doc = json.load(f)
    assert doc["metadata"]["reason"] == "engine_crash"
    assert "Boom" in doc["metadata"]["error"]
    assert "clock_sync" in doc["metadata"]
    assert isinstance(doc["traceEvents"], list)


def test_reporter_lines_carry_clock_anchor(tmp_path):
    """Satellite: Reporter JSONL, flight dumps and rank traces share
    ONE clock_sync convention, so trace_merge aligns all three."""
    path = str(tmp_path / "m.jsonl")
    reg = profiler.MetricsRegistry()
    reg.set_gauge("unit.g", 3.0)
    rep = profiler.start_reporter(path, interval=0.05, registry=reg)
    time.sleep(0.2)
    rep.stop()
    with open(path) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    anchor = profiler.clock_anchor()
    assert lines and all(ln["clock_sync"] == anchor for ln in lines)
    # and trace_merge can merge the JSONL next to a span trace
    import trace_merge as tm

    doc = tm.load_trace(path)
    assert any(e["ph"] == "C" and e["name"] == "unit.g"
               for e in doc["traceEvents"])
    merged = tm.merge_traces([doc])
    assert merged["traceEvents"]


# ---------------------------------------------------------------------------
# goodput / MFU
# ---------------------------------------------------------------------------


def test_goodput_tracker_math():
    reg = profiler.MetricsRegistry()
    g = profiler.GoodputTracker(registry=reg)
    g.set_flops_per_step(2e9)
    g.set_peak_flops(1e12)
    g.set_pp_bubble(0.25)
    for _ in range(4):
        g.add_comm(0.02)
        g.step(0.1, io_s=0.05, ckpt_s=0.01)
    s = g.summary()
    assert s["steps"] == 4
    d = s["decomposition"]
    assert sum(d.values()) == pytest.approx(1.0)
    # comm drained into the step, bubble carved out of the remainder
    assert d["comm"] == pytest.approx(0.02 / 0.16, rel=1e-6)
    assert d["pp_bubble"] == pytest.approx(0.25 * 0.08 / 0.16, rel=1e-6)
    assert d["io_wait"] == pytest.approx(0.05 / 0.16, rel=1e-6)
    # mfu = flops / step_s / peak
    assert s["mfu"] == pytest.approx(2e9 / 0.1 / 1e12, rel=1e-6)
    assert 0 < s["goodput"] <= 1.0
    gauges = reg.summary()["gauges"]
    assert gauges["training.mfu"] == pytest.approx(s["mfu"], rel=0.05)
    assert gauges["training.goodput"] == pytest.approx(s["goodput"],
                                                      rel=0.05)


def test_goodput_lost_time_attribution():
    g = profiler.GoodputTracker(registry=profiler.MetricsRegistry())
    g.step(0.1)
    g.add_lost(2.5, "remesh")
    s = g.summary()
    assert s["lost_s"] == {"remesh": 2.5}


def test_peak_flops_env_override(monkeypatch):
    monkeypatch.setenv("MXNET_PEAK_TFLOPS", "123.5")
    assert profiler.device_peak_flops() == pytest.approx(123.5e12)
    monkeypatch.setenv("MXNET_PEAK_TFLOPS", "banana")
    with pytest.raises(mx.MXNetError):
        profiler.device_peak_flops()
    monkeypatch.setenv("MXNET_PEAK_TFLOPS", "-1")
    with pytest.raises(mx.MXNetError):
        profiler.device_peak_flops()


def test_fit_exports_live_goodput(monkeypatch):
    """A real (tiny) fit exports training.goodput/mfu gauges whose
    decomposition covers ~100% of wall, with flops from the fused
    program's own cost analysis."""
    monkeypatch.setenv("MXNET_PEAK_TFLOPS", "1")
    profiler.goodput_tracker().reset()
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                              name="fc"),
        mx.sym.Variable("softmax_label"), name="softmax")
    rng = np.random.RandomState(0)
    it = mx.io.NDArrayIter(rng.rand(32, 8).astype(np.float32),
                           (np.arange(32) % 4).astype(np.float32),
                           batch_size=8)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})
    s = profiler.goodput_tracker().summary()
    assert s["steps"] == 8
    assert 0 < s["goodput"] <= 1.0
    assert s["flops_per_step"] and s["flops_per_step"] > 0
    assert s["mfu"] and s["mfu"] > 0
    assert sum(s["decomposition"].values()) == pytest.approx(1.0)
    gauges = profiler.metrics_summary()["gauges"]
    assert "training.goodput" in gauges
    assert "training.mfu" in gauges


# ---------------------------------------------------------------------------
# ops surface
# ---------------------------------------------------------------------------


def test_metrics_http_endpoints():
    profiler.set_gauge("unit.http_gauge", 7.0)
    profiler.register_statusz("unit", lambda: {"hello": "world"})
    srv = profiler.start_metrics_server(port=0)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        text = urllib.request.urlopen(f"{base}/metrics").read().decode()
        assert "mxnet_unit_http_gauge" in text
        st = json.loads(urllib.request.urlopen(f"{base}/statusz").read())
        assert st["gauges"]["unit.http_gauge"] == 7.0
        assert st["unit"] == {"hello": "world"}
        assert "training" in st and "clock_sync" in st
        profiler.observe("unit.http_ms", 1.0)
        tz = json.loads(
            urllib.request.urlopen(f"{base}/tracez?n=64").read())
        assert "traceEvents" in tz and "clock_sync" in tz
        assert urllib.request.urlopen(f"{base}/metrics").status == 200
        with pytest.raises(Exception):
            urllib.request.urlopen(f"{base}/nope")
    finally:
        profiler.unregister_statusz("unit")
        srv.close()
    # closing clears the singleton so a fresh server can bind
    srv2 = profiler.start_metrics_server(port=0)
    assert srv2 is not srv
    srv2.close()


def test_statusz_provider_errors_are_contained():
    profiler.register_statusz("bad", lambda: 1 / 0)
    try:
        doc = profiler.statusz()
        assert "error" in doc["bad"]
    finally:
        profiler.unregister_statusz("bad")


# ---------------------------------------------------------------------------
# the two-process stitch (the tier-1 acceptance test)
# ---------------------------------------------------------------------------


def _walk(nodes):
    for n in nodes:
        yield n
        yield from _walk(n["children"])


def test_two_process_trace_stitch(tmp_path):
    """One traced request through Router → wire → a fake-replica
    SUBPROCESS stitches into a single tree: the router.request root
    spans both processes' child spans with monotonic, clock-aligned
    bounds — recovered purely from the two flight-recorder ring
    files."""
    import trace_merge as tm

    from mxnet_tpu import fleet

    fleet_dir = str(tmp_path)
    fleet.write_secret(fleet_dir, b"trace-test")
    profiler.init_flight_recorder(fleet_dir)
    env = dict(os.environ, MXNET_WORKER_ID="1", JAX_PLATFORMS="cpu",
               MXNET_FLIGHT_RECORDER_DIR=fleet_dir)
    worker = os.path.join(os.path.dirname(__file__),
                          "fleet_trace_worker.py")
    proc = subprocess.Popen([sys.executable, worker, fleet_dir],
                            env=env)
    router = None
    try:
        host, port = fleet.read_endpoint(fleet_dir, 0, timeout=120)
        client = fleet.ReplicaClient(0, host, port,
                                     secret=b"trace-test")
        router = fleet.Router([client], fleet_dir=fleet_dir,
                              secret=b"trace-test")
        out = router.submit(
            {"data": np.ones((1, 2), np.float32)}).result(60)
        assert np.allclose(out[0], 2.0)
        time.sleep(0.1)  # let the delivery span land in the ring
    finally:
        if router is not None:
            router.close(stop_replicas=True)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            raise
    profiler.flight_recorder().sync()
    rings = sorted(f for f in os.listdir(fleet_dir)
                   if f.startswith("flight_") and f.endswith(".ring"))
    assert len(rings) == 2, rings
    merged = tm.merge_traces(
        [tm.load_trace(os.path.join(fleet_dir, f)) for f in rings])
    traces = tm.list_traces(merged["traceEvents"])
    roots_of = {tid: tm.trace_tree(merged["traceEvents"], tid)
                for tid in traces}
    # find OUR request: the tree rooted at router.request
    picked = None
    for tid, roots in roots_of.items():
        if len(roots) == 1 and roots[0]["event"]["name"] \
                == "router.request":
            picked = roots
    assert picked is not None, f"no router.request root in {traces}"
    root = picked[0]
    nodes = list(_walk(picked))
    names = {n["event"]["name"] for n in nodes}
    pids = {n["event"]["pid"] for n in nodes}
    # spans from BOTH processes in one tree
    assert len(pids) == 2, names
    assert {"router.request", "router.queue", "wire.send",
            "replica.exec"} <= names
    # every child's bounds sit inside its parent's, on the SHARED
    # wall-clock axis (clock-aligned: same host, sub-ms NTP error;
    # 5 ms tolerance >> observed skew, << the 10 ms replica span)
    tol_us = 5e3
    root_t0 = root["event"]["ts"]
    root_t1 = root_t0 + root["event"]["dur"]

    def check(node, lo, hi):
        ev = node["event"]
        t0, t1 = ev["ts"], ev["ts"] + ev.get("dur", 0.0)
        assert t0 >= lo - tol_us, (ev["name"], t0, lo)
        assert t1 <= hi + tol_us, (ev["name"], t1, hi)
        prev = t0
        for c in node["children"]:
            # children sorted by ts → monotonic
            assert c["event"]["ts"] >= prev - tol_us
            prev = c["event"]["ts"]
            check(c, t0, t1)

    check(root, root_t0, root_t1)
    # the replica's 10 ms exec really happened INSIDE the root span
    exec_node = next(n for n in nodes
                     if n["event"]["name"] == "replica.exec")
    assert exec_node["event"]["pid"] != root["event"]["pid"]
    assert exec_node["event"]["dur"] >= 8e3  # the worker's sleep
    # Perfetto flow arrows were attached for the cross-process edges
    assert any(e.get("cat") == "traceflow"
               for e in merged["traceEvents"])


# ---------------------------------------------------------------------------
# stitcher unit coverage (no processes)
# ---------------------------------------------------------------------------


def test_trace_tree_stitches_and_formats():
    import trace_merge as tm

    root = profiler.TraceContext()
    c1, c2 = root.child(), root.child()
    evs = [
        {"name": "root", "ph": "X", "ts": 0.0, "dur": 100.0, "pid": 1,
         "tid": 0, "args": root.args()},
        {"name": "b", "ph": "X", "ts": 50.0, "dur": 10.0, "pid": 2,
         "tid": 0, "args": c2.args()},
        {"name": "a", "ph": "X", "ts": 10.0, "dur": 10.0, "pid": 1,
         "tid": 0, "args": c1.args()},
        {"name": "other", "ph": "X", "ts": 0.0, "dur": 1.0, "pid": 1,
         "tid": 0, "args": profiler.TraceContext().args()},
    ]
    assert tm.list_traces(evs)[root.trace_id] == 3
    roots = tm.trace_tree(evs, root.trace_id)
    assert len(roots) == 1 and roots[0]["event"]["name"] == "root"
    kids = [n["event"]["name"] for n in roots[0]["children"]]
    assert kids == ["a", "b"]  # sorted by ts
    text = tm.format_tree(roots)
    assert "root" in text and "\n  a" in text
    n_flows = tm.add_flow_events(evs)
    assert n_flows == 1  # only the cross-pid edge (root→b)
