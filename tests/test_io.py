"""IO tests (modeled on tests/python/unittest/test_io.py)."""

import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx


def test_ndarray_iter_basic():
    X = np.arange(100, dtype=np.float32).reshape(25, 4)
    y = np.arange(25, dtype=np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=5)
    batches = list(it)
    assert len(batches) == 5
    assert batches[0].data[0].shape == (5, 4)
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), X[:5])
    np.testing.assert_allclose(batches[0].label[0].asnumpy(), y[:5])
    # reset works
    it.reset()
    assert len(list(it)) == 5


def test_ndarray_iter_pad_discard():
    X = np.arange(44, dtype=np.float32).reshape(11, 4)
    it = mx.io.NDArrayIter(X, np.zeros(11), batch_size=4, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].pad == 1
    it = mx.io.NDArrayIter(X, np.zeros(11), batch_size=4, last_batch_handle="discard")
    assert len(list(it)) == 2


def test_ndarray_iter_shuffle_multi_input():
    X = {"a": np.random.rand(20, 2).astype(np.float32),
         "b": np.random.rand(20, 3).astype(np.float32)}
    it = mx.io.NDArrayIter(X, np.zeros(20, np.float32), batch_size=5, shuffle=True)
    names = [d.name for d in it.provide_data]
    assert sorted(names) == ["a", "b"]
    batch = next(it)
    assert len(batch.data) == 2


def test_resize_iter():
    X = np.random.rand(12, 3).astype(np.float32)
    base = mx.io.NDArrayIter(X, np.zeros(12), batch_size=4)
    it = mx.io.ResizeIter(base, size=7)
    assert len(list(it)) == 7


def test_prefetching_iter():
    X = np.random.rand(16, 3).astype(np.float32)
    base = mx.io.NDArrayIter(X, np.zeros(16), batch_size=4)
    it = mx.io.PrefetchingIter(base)
    total = 0
    for batch in it:
        assert batch.data[0].shape == (4, 3)
        total += 1
    assert total == 4
    it.reset()
    assert len(list(it)) == 4


def test_csv_iter(tmp_path):
    data = np.random.rand(10, 3).astype(np.float32)
    labels = np.arange(10, dtype=np.float32)
    data_path = str(tmp_path / "data.csv")
    label_path = str(tmp_path / "label.csv")
    np.savetxt(data_path, data, delimiter=",")
    np.savetxt(label_path, labels, delimiter=",")
    it = mx.io.CSVIter(data_csv=data_path, data_shape=(3,), label_csv=label_path,
                       batch_size=5)
    batch = next(it)
    np.testing.assert_allclose(batch.data[0].asnumpy(), data[:5], rtol=1e-5)


def test_mnist_iter(tmp_path):
    # write tiny idx files
    import struct

    imgs = (np.random.rand(10, 28, 28) * 255).astype(np.uint8)
    labels = np.arange(10, dtype=np.uint8) % 10
    img_path = str(tmp_path / "img-idx3-ubyte")
    lbl_path = str(tmp_path / "lbl-idx1-ubyte")
    with open(img_path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 8, 3))
        f.write(struct.pack(">III", 10, 28, 28))
        f.write(imgs.tobytes())
    with open(lbl_path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 8, 1))
        f.write(struct.pack(">I", 10))
        f.write(labels.tobytes())
    it = mx.io.MNISTIter(image=img_path, label=lbl_path, batch_size=5, shuffle=False)
    batch = next(it)
    assert batch.data[0].shape == (5, 1, 28, 28)
    np.testing.assert_allclose(batch.data[0].asnumpy(),
                               imgs[:5, None].astype(np.float32) / 255.0, rtol=1e-5)
    np.testing.assert_allclose(batch.label[0].asnumpy(), labels[:5])
    flat_it = mx.io.MNISTIter(image=img_path, label=lbl_path, batch_size=5,
                              shuffle=False, flat=True)
    assert next(flat_it).data[0].shape == (5, 784)


# ---------------------------------------------------------------------------
# iterator checkpointing: state_dict/set_state batch-exact resume
# ---------------------------------------------------------------------------

def test_ndarray_iter_state_resume_mid_epoch_with_shuffle():
    """A fresh iterator (different ambient RNG!) restored from
    state_dict must continue at exactly the next batch, reproducing the
    original run's seeded shuffle order."""
    np.random.seed(123)
    X = np.arange(80, dtype=np.float32).reshape(20, 4)
    y = np.arange(20, dtype=np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=4, shuffle=True)
    seen = [next(it) for _ in range(2)]  # consume 2 of 5 batches
    state = it.state_dict()
    rest_ref = [(b.data[0].asnumpy(), b.label[0].asnumpy()) for b in it]
    assert len(rest_ref) == 3

    np.random.seed(999)  # a different shuffle would be drawn here...
    it2 = mx.io.NDArrayIter(X, y, batch_size=4, shuffle=True)
    it2.set_state(state)  # ...but set_state restores the ORIGINAL order
    rest = [(b.data[0].asnumpy(), b.label[0].asnumpy()) for b in it2]
    assert len(rest) == 3
    for (d1, l1), (d2, l2) in zip(rest_ref, rest):
        np.testing.assert_array_equal(d1, d2)
        np.testing.assert_array_equal(l1, l2)
    # ...and the restored order persists across the epoch boundary
    it.reset()
    it2.reset()
    np.testing.assert_array_equal(next(it).data[0].asnumpy(),
                                  next(it2).data[0].asnumpy())
    del seen


def test_ndarray_iter_state_mismatch_fails_loudly():
    X = np.zeros((12, 2), np.float32)
    it = mx.io.NDArrayIter(X, np.zeros(12), batch_size=4)
    state = it.state_dict()
    other = mx.io.NDArrayIter(X, np.zeros(12), batch_size=3)
    with pytest.raises(mx.MXNetError, match="batch_size"):
        other.set_state(state)
    with pytest.raises(mx.MXNetError, match="state_dict"):
        mx.io.DataIter().state_dict()


def test_resize_iter_state_resume():
    X = np.arange(36, dtype=np.float32).reshape(12, 3)
    base = mx.io.NDArrayIter(X, np.zeros(12), batch_size=4)
    it = mx.io.ResizeIter(base, size=7)
    ref = [b.data[0].asnumpy() for b in it]
    base2 = mx.io.NDArrayIter(X, np.zeros(12), batch_size=4)
    it2 = mx.io.ResizeIter(base2, size=7)
    for _ in range(3):
        next(it2)
    state = it2.state_dict()
    base3 = mx.io.NDArrayIter(X, np.zeros(12), batch_size=4)
    it3 = mx.io.ResizeIter(base3, size=7)
    it3.set_state(state)
    rest = [b.data[0].asnumpy() for b in it3]
    assert len(rest) == 4
    for a, b in zip(ref[3:], rest):
        np.testing.assert_array_equal(a, b)


def test_prefetching_iter_state_resume_mid_epoch():
    """Prefetch-ahead must not leak into the restored position: the
    state is the CONSUMED batch count, and resume re-produces the epoch
    under the restored inner shuffle order."""
    np.random.seed(7)
    X = np.arange(96, dtype=np.float32).reshape(24, 4)
    y = np.arange(24, dtype=np.float32)
    it = mx.io.PrefetchingIter(
        mx.io.NDArrayIter(X, y, batch_size=4, shuffle=True))
    consumed = [next(it).data[0].asnumpy() for _ in range(2)]
    state = it.state_dict()
    rest_ref = [b.data[0].asnumpy() for b in it]
    assert len(rest_ref) == 4
    it.close()

    np.random.seed(1234)
    it2 = mx.io.PrefetchingIter(
        mx.io.NDArrayIter(X, y, batch_size=4, shuffle=True))
    it2.set_state(state)
    rest = [b.data[0].asnumpy() for b in it2]
    assert len(rest) == 4
    for a, b in zip(rest_ref, rest):
        np.testing.assert_array_equal(a, b)
    # next epoch still works after a restore
    it2.reset()
    assert len(list(it2)) == 6
    it2.close()
    del consumed


def test_prefetching_iter_state_resume_at_epoch_end():
    """An end-of-epoch snapshot restores to the epoch end: the next
    call ends the epoch, and the following epoch proceeds normally."""
    X = np.arange(48, dtype=np.float32).reshape(12, 4)
    it = mx.io.PrefetchingIter(mx.io.NDArrayIter(X, np.zeros(12),
                                                 batch_size=4))
    n = sum(1 for _ in it)
    assert n == 3
    state = it.state_dict()
    it.close()
    it2 = mx.io.PrefetchingIter(mx.io.NDArrayIter(X, np.zeros(12),
                                                  batch_size=4))
    it2.set_state(state)
    assert it2.iter_next() is False  # restored AT the epoch end
    it2.reset()
    assert len(list(it2)) == 3  # next epoch intact
    it2.close()
