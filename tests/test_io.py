"""IO tests (modeled on tests/python/unittest/test_io.py)."""

import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx


def test_ndarray_iter_basic():
    X = np.arange(100, dtype=np.float32).reshape(25, 4)
    y = np.arange(25, dtype=np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=5)
    batches = list(it)
    assert len(batches) == 5
    assert batches[0].data[0].shape == (5, 4)
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), X[:5])
    np.testing.assert_allclose(batches[0].label[0].asnumpy(), y[:5])
    # reset works
    it.reset()
    assert len(list(it)) == 5


def test_ndarray_iter_pad_discard():
    X = np.arange(44, dtype=np.float32).reshape(11, 4)
    it = mx.io.NDArrayIter(X, np.zeros(11), batch_size=4, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].pad == 1
    it = mx.io.NDArrayIter(X, np.zeros(11), batch_size=4, last_batch_handle="discard")
    assert len(list(it)) == 2


def test_ndarray_iter_shuffle_multi_input():
    X = {"a": np.random.rand(20, 2).astype(np.float32),
         "b": np.random.rand(20, 3).astype(np.float32)}
    it = mx.io.NDArrayIter(X, np.zeros(20, np.float32), batch_size=5, shuffle=True)
    names = [d.name for d in it.provide_data]
    assert sorted(names) == ["a", "b"]
    batch = next(it)
    assert len(batch.data) == 2


def test_resize_iter():
    X = np.random.rand(12, 3).astype(np.float32)
    base = mx.io.NDArrayIter(X, np.zeros(12), batch_size=4)
    it = mx.io.ResizeIter(base, size=7)
    assert len(list(it)) == 7


def test_prefetching_iter():
    X = np.random.rand(16, 3).astype(np.float32)
    base = mx.io.NDArrayIter(X, np.zeros(16), batch_size=4)
    it = mx.io.PrefetchingIter(base)
    total = 0
    for batch in it:
        assert batch.data[0].shape == (4, 3)
        total += 1
    assert total == 4
    it.reset()
    assert len(list(it)) == 4


def test_csv_iter(tmp_path):
    data = np.random.rand(10, 3).astype(np.float32)
    labels = np.arange(10, dtype=np.float32)
    data_path = str(tmp_path / "data.csv")
    label_path = str(tmp_path / "label.csv")
    np.savetxt(data_path, data, delimiter=",")
    np.savetxt(label_path, labels, delimiter=",")
    it = mx.io.CSVIter(data_csv=data_path, data_shape=(3,), label_csv=label_path,
                       batch_size=5)
    batch = next(it)
    np.testing.assert_allclose(batch.data[0].asnumpy(), data[:5], rtol=1e-5)


def test_mnist_iter(tmp_path):
    # write tiny idx files
    import struct

    imgs = (np.random.rand(10, 28, 28) * 255).astype(np.uint8)
    labels = np.arange(10, dtype=np.uint8) % 10
    img_path = str(tmp_path / "img-idx3-ubyte")
    lbl_path = str(tmp_path / "lbl-idx1-ubyte")
    with open(img_path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 8, 3))
        f.write(struct.pack(">III", 10, 28, 28))
        f.write(imgs.tobytes())
    with open(lbl_path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 8, 1))
        f.write(struct.pack(">I", 10))
        f.write(labels.tobytes())
    it = mx.io.MNISTIter(image=img_path, label=lbl_path, batch_size=5, shuffle=False)
    batch = next(it)
    assert batch.data[0].shape == (5, 1, 28, 28)
    np.testing.assert_allclose(batch.data[0].asnumpy(),
                               imgs[:5, None].astype(np.float32) / 255.0, rtol=1e-5)
    np.testing.assert_allclose(batch.label[0].asnumpy(), labels[:5])
    flat_it = mx.io.MNISTIter(image=img_path, label=lbl_path, batch_size=5,
                              shuffle=False, flat=True)
    assert next(flat_it).data[0].shape == (5, 784)
