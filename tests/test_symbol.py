"""Symbol tests (modeled on tests/python/unittest/test_symbol.py)."""

import json

import numpy as np
import pytest

import mxnet_tpu as mx


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=10, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_symbol_basics():
    sym = _mlp()
    assert sym.list_arguments() == [
        "data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias", "softmax_label"]
    assert sym.list_outputs() == ["softmax_output"]
    internals = sym.get_internals()
    assert "fc1_output" in internals.list_outputs()
    fc1 = internals["fc1_output"]
    assert fc1.list_outputs() == ["fc1_output"]


def test_symbol_compose():
    data = mx.sym.Variable("data")
    net1 = mx.sym.FullyConnected(data, num_hidden=10, name="fc1")
    net1 = mx.sym.FullyConnected(net1, num_hidden=100, name="fc2")
    net2 = mx.sym.FullyConnected(mx.sym.Variable("data2"), num_hidden=10, name="fc3")
    net2 = mx.sym.Activation(net2, act_type="relu")
    net2 = mx.sym.FullyConnected(net2, num_hidden=20, name="fc4")
    composed = net2(data2=net1, name="composed")
    assert "fc2_weight" in composed.list_arguments()
    multi_out = mx.sym.Group([composed, net1])
    assert len(multi_out.list_outputs()) == 2


def test_symbol_infer_shape():
    sym = _mlp()
    arg_shapes, out_shapes, aux_shapes = sym.infer_shape(data=(8, 30), softmax_label=(8,))
    assert arg_shapes[1] == (10, 30)  # fc1_weight
    assert arg_shapes[3] == (4, 10)  # fc2_weight
    assert out_shapes == [(8, 4)]
    # partial
    a, o, _ = sym.infer_shape_partial(softmax_label=(8,))
    assert a[0] is None


def test_symbol_infer_shape_conv():
    data = mx.sym.Variable("data")
    conv = mx.sym.Convolution(data, kernel=(3, 3), num_filter=16, pad=(1, 1), name="c1")
    pool = mx.sym.Pooling(conv, kernel=(2, 2), stride=(2, 2), pool_type="max")
    arg_shapes, out_shapes, _ = pool.infer_shape(data=(2, 3, 8, 8))
    assert arg_shapes[1] == (16, 3, 3, 3)
    assert out_shapes == [(2, 16, 4, 4)]


def test_symbol_json_roundtrip():
    sym = _mlp()
    js = sym.tojson()
    data = json.loads(js)
    assert "nodes" in data and "heads" in data
    sym2 = mx.symbol.load_json(js)
    assert sym2.list_arguments() == sym.list_arguments()
    assert sym2.list_outputs() == sym.list_outputs()
    # numerically identical executors
    x = np.random.rand(2, 6).astype(np.float32)
    args = {n: mx.nd.array(np.random.rand(*s).astype(np.float32))
            for n, s in zip(sym.list_arguments(),
                            sym.infer_shape(data=(2, 6), softmax_label=(2,))[0])}
    e1 = sym.bind(mx.cpu(), args)
    e2 = sym2.bind(mx.cpu(), args)
    np.testing.assert_allclose(e1.forward()[0].asnumpy(),
                               e2.forward()[0].asnumpy(), rtol=1e-6)


def test_symbol_arithmetic():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = (a + b) * 2.0 - a / b + 1.5 - (-b)
    av = np.array([[2.0, 4.0]], np.float32)
    bv = np.array([[1.0, 2.0]], np.float32)
    ex = c.bind(mx.cpu(), {"a": mx.nd.array(av), "b": mx.nd.array(bv)})
    expected = (av + bv) * 2.0 - av / bv + 1.5 + bv
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), expected, rtol=1e-6)


def test_symbol_attr():
    with mx.AttrScope(ctx_group="dev1"):
        v = mx.sym.Variable("v")
    assert v.attr("ctx_group") == "dev1"
    v2 = mx.sym.Variable("w", lr_mult=2.0, wd_mult=0.5, shape=(3, 4))
    d = v2.attr_dict()["w"]
    assert d["lr_mult"] == "2.0" and d["wd_mult"] == "0.5"
    # shape hint used in inference
    fc = mx.sym.FullyConnected(v2, num_hidden=2, no_bias=True, name="fc")
    args, outs, _ = fc.infer_shape()
    assert outs == [(3, 2)]


def test_symbol_variable_dup_and_save(tmp_path):
    sym = _mlp()
    path = str(tmp_path / "m-symbol.json")
    sym.save(path)
    loaded = mx.symbol.load(path)
    assert loaded.list_arguments() == sym.list_arguments()


def test_symbol_multi_output_indexing():
    d = mx.sym.Variable("d")
    split = mx.sym.SliceChannel(d, num_outputs=3, axis=1, name="split")
    assert len(split.list_outputs()) == 3
    one = split[1]
    x = np.random.rand(2, 6).astype(np.float32)
    ex = one.bind(mx.cpu(), {"d": mx.nd.array(x)})
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), x[:, 2:4], rtol=1e-6)
