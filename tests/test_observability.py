"""Monitor / profiler / visualization / telemetry tests (reference:
monitor usage in docs, test_viz.py, profiler dump format; plus the
observability layer: trace args, metrics registry + exporters, the
straggler watchdog, and the tools/ parsers)."""

import json
import logging
import math
import os
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_monitor_collects_stats():
    np.random.seed(0)
    X = np.random.randn(40, 6).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                              name="fc"), name="softmax")
    mod = mx.mod.Module(sym, context=mx.cpu())
    it = mx.io.NDArrayIter(X, y, batch_size=10)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mon = mx.Monitor(interval=1, pattern=".*")
    mod.install_monitor(mon)
    b = next(iter(it))
    mon.tic()
    mod.forward(b, is_train=False)
    res = mon.toc()
    names = [k for _, k, _ in res]
    assert any("fc" in n for n in names), names
    assert any("weight" in n for n in names), names  # weights stat'd too
    for _, _, v in res:
        assert "nan" not in v.lower()


def test_monitor_finds_nan():
    """The NaN-hunt workflow: a poisoned weight shows up in the stats."""
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                              name="fc"), name="softmax")
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 6))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    bad = mod._exec.arg_dict["fc_weight"].asnumpy().copy()
    bad[0, 0] = np.nan
    mod._exec.arg_dict["fc_weight"][:] = bad
    mon = mx.Monitor(interval=1, pattern=".*fc.*")
    mod.install_monitor(mon)
    mon.tic()
    mod.forward(mx.io.DataBatch([mx.nd.zeros((4, 6))],
                                [mx.nd.zeros((4,))]), is_train=False)
    res = mon.toc()
    assert any("nan" in v.lower() for _, _, v in res), res


def test_profiler_chrome_trace(tmp_path):
    fname = str(tmp_path / "trace.json")
    mx.profiler.profiler_set_config(mode="all", filename=fname)
    mx.profiler.profiler_set_state("run")
    X = np.random.randn(30, 6).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=10)
    mod = mx.mod.Module(_mlp_binary(), context=mx.cpu())
    mod.fit(it, num_epoch=1, optimizer="sgd")
    mx.profiler.profiler_set_state("stop")
    assert os.path.isfile(fname)
    with open(fname) as f:
        trace = json.load(f)
    events = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert len(events) > 0
    names = {e["name"] for e in events}
    assert any("fused_step" in n or "forward" in n for n in names), names
    for e in events:
        assert "ts" in e and "dur" in e
    # process metadata + clock anchor ride every dump (trace_merge input)
    meta = [e for e in trace["traceEvents"] if e.get("ph") == "M"]
    assert any(e["name"] == "process_name" for e in meta)
    assert "clock_sync" in trace["metadata"]


def _mlp_binary():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_print_summary():
    out = mx.viz.print_summary(_mlp(), shape={"data": (8, 6)})
    assert "fc1(FullyConnected)" in out
    assert "Total params" in out
    # fc1: 6*8+8 = 56; fc2: 8*3+3 = 27
    assert "Total params: 83" in out


def test_plot_network():
    dot = mx.viz.plot_network(_mlp(), shape={"data": (8, 6)},
                              save_format="dot")
    src = dot.source
    assert "fc1" in src and "relu1" in src and "softmax" in src
    assert "fc1_weight" not in src  # weights hidden
    assert "->" in src or "--" in src


def test_xla_trace_smoke(tmp_path):
    """jax.profiler passthrough writes an XPlane trace directory."""
    logdir = str(tmp_path / "xla")
    mx.profiler.start_xla_trace(logdir)
    mx.nd.dot(mx.nd.ones((32, 32)), mx.nd.ones((32, 32))).asnumpy()
    mx.profiler.stop_xla_trace()
    found = []
    for root, _, files in os.walk(logdir):
        found.extend(files)
    assert found, "no trace files written"


def test_monitor_fires_during_training():
    """The fused path must yield to the tap: training forwards are monitored."""
    np.random.seed(1)
    X = np.random.randn(20, 6).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=10)
    mod = mx.mod.Module(_mlp_binary(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params()
    mod.init_optimizer(optimizer="sgd")
    mon = mx.Monitor(interval=1, pattern=".*output.*")
    mod.install_monitor(mon)
    b = next(iter(it))
    mon.tic()
    mod.forward(b, is_train=True)
    mod.backward()
    mod.update()
    res = mon.toc()
    assert any("output" in k for _, k, _ in res), res


def test_env_var_catalog():
    """Every env var the code reads is declared in the config catalog."""
    import re

    cat = {v.name for v in mx.config.list_env()}
    # scan the source for MXNET_* reads
    used = set()
    pkg = os.path.dirname(mx.__file__)
    for root, _, files in os.walk(pkg):
        for f in files:
            if not f.endswith(".py") or f == "config.py":
                continue
            src = open(os.path.join(root, f)).read()
            used.update(re.findall(r"MXNET_[A-Z_]+", src))
    # family-wildcard mentions in docs/comments ("MXNET_CKPT_*",
    # "MXNET_CHAOS_*") regex-capture as a trailing-underscore token —
    # they reference a declared family, not an undeclared var
    used = {u for u in used if not u.endswith("_")}
    missing = used - cat
    assert not missing, f"undeclared env vars: {sorted(missing)}"
    # catalog answers queries
    v = mx.config.describe("MXNET_BACKWARD_DO_MIRROR")
    assert v.default == 0 and "recompute" in v.doc
    cur = mx.config.current()
    assert "MXNET_FUSED_STEP" in cur


# ---------------------------------------------------------------------------
# telemetry layer: trace args, metrics registry, exporters, watchdog
# ---------------------------------------------------------------------------


def test_trace_event_args(tmp_path):
    """scope/add_event carry an args dict into the trace viewer."""
    fname = str(tmp_path / "trace.json")
    mx.profiler.profiler_set_config(mode="all", filename=fname)
    mx.profiler.profiler_set_state("run")
    with mx.profiler.scope("unit.work", "test",
                           args={"step": 7, "bytes": 128}):
        pass
    t0 = time.perf_counter()
    mx.profiler.add_event("unit.xthread", t0, 0.001, "test",
                          args={"bucket": 32})
    mx.profiler.profiler_set_state("stop")
    with open(fname) as f:
        trace = json.load(f)
    evs = {e["name"]: e for e in trace["traceEvents"] if e.get("ph") == "X"}
    assert evs["unit.work"]["args"] == {"step": 7, "bytes": 128}
    assert evs["unit.xthread"]["args"]["bucket"] == 32
    # rank metadata + the clock anchor trace_merge aligns with
    assert trace["metadata"]["rank"] == 0
    assert "wall_time_s" in trace["metadata"]["clock_sync"]
    assert "perf_counter_s" in trace["metadata"]["clock_sync"]


def test_autostart_guard(tmp_path):
    """MXNET_PROFILER_AUTOSTART must be optional-out-able: test suites
    import the package without an env var flipping global state."""
    prof = mx.profiler
    assert not prof._profiler.running
    assert not prof._env_autostart({})
    assert not prof._env_autostart({"MXNET_PROFILER_AUTOSTART": "0"})
    assert not prof._env_autostart({"MXNET_PROFILER_AUTOSTART": "1",
                                    "MXNET_PROFILER_NO_AUTOSTART": "1"})
    assert not prof._profiler.running
    prof.profiler_set_config(mode="all", filename=str(tmp_path / "a.json"))
    try:
        assert prof._env_autostart({"MXNET_PROFILER_AUTOSTART": "1"})
        assert prof._profiler.running
    finally:
        prof.profiler_set_state("stop")
    assert not prof._profiler.running


def test_metrics_summary_p90_and_rates():
    mx.profiler.reset_metrics()
    mx.profiler.inc_counter("unit.count", 5)
    for v in range(1, 101):
        mx.profiler.observe("unit.lat_ms", float(v))
    s = mx.profiler.metrics_summary()
    assert s["counters"]["unit.count"] == 5
    h = s["histograms"]["unit.lat_ms"]
    assert h["count"] == 100
    assert 88 <= h["p90"] <= 92
    assert h["p50"] <= h["p90"] <= h["p99"]
    # per-counter rate since reset (the reporter/bench shared schema)
    assert s["rates"]["unit.count"] > 0
    assert s["elapsed_s"] > 0
    mx.profiler.reset_metrics()


def test_gauges():
    mx.profiler.reset_metrics()
    mx.profiler.set_gauge("unit.depth", 3)
    mx.profiler.inc_gauge("unit.bytes", 100)
    mx.profiler.inc_gauge("unit.bytes", -40)
    g = mx.profiler.metrics_summary()["gauges"]
    assert g["unit.depth"] == 3.0
    assert g["unit.bytes"] == 60.0
    mx.profiler.reset_metrics()


def test_gauge_decrement_dropped_after_reset():
    """A delta-gauge decrement that outlives reset_metrics() (executor
    finalizer) must be dropped, not drive the gauge negative."""
    reg = mx.profiler.MetricsRegistry()
    gen = reg.inc_gauge("live.bytes", 100)  # returns the generation
    assert reg.summary()["gauges"]["live.bytes"] == 100.0
    reg.reset()
    assert reg.inc_gauge("live.bytes", -100, gen=gen) is None  # dropped
    assert reg.summary()["gauges"].get("live.bytes", 0.0) == 0.0
    gen2 = reg.inc_gauge("live.bytes", 7)
    assert gen2 == reg.generation  # current: applied
    assert reg.summary()["gauges"]["live.bytes"] == 7.0


def test_prometheus_text():
    mx.profiler.reset_metrics()
    mx.profiler.inc_counter("serving.requests", 3)
    mx.profiler.set_gauge("executor.live_buffer_bytes", 1024)
    for v in (1.0, 2.0, 3.0):
        mx.profiler.observe("serving.latency_ms", v)
    text = mx.profiler.prometheus_text()
    assert "# TYPE mxnet_serving_requests counter" in text
    assert 'mxnet_serving_requests{rank="0"} 3' in text
    assert "# TYPE mxnet_executor_live_buffer_bytes gauge" in text
    assert 'mxnet_executor_live_buffer_bytes{rank="0"} 1024' in text
    # PR 12: registry histograms export as REAL Prometheus histograms
    # (cumulative _bucket series over the fixed ladder + _sum/_count)
    assert "# TYPE mxnet_serving_latency_ms histogram" in text
    assert 'mxnet_serving_latency_ms_bucket{rank="0",le="1"} 1' in text
    assert 'mxnet_serving_latency_ms_bucket{rank="0",le="2.5"} 2' in text
    assert 'mxnet_serving_latency_ms_bucket{rank="0",le="+Inf"} 3' in text
    assert 'mxnet_serving_latency_ms_count{rank="0"} 3' in text
    assert 'mxnet_serving_latency_ms_sum{rank="0"} 6' in text
    # the one-release deprecated _pNN quantile gauges are RETIRED:
    # histogram_quantile() over the _bucket series replaces them
    assert "_p50" not in text
    assert "_p90" not in text
    assert "_p99" not in text
    # the pre-PR-12 summary form is GONE (a histogram family plus a
    # same-name summary would be an invalid exposition)
    assert "summary" not in text
    assert 'quantile=' not in text
    mx.profiler.reset_metrics()


def test_jsonl_reporter(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    reg = mx.profiler.MetricsRegistry()
    reg.inc("unit.count", 2)
    reg.observe("unit.ms", 5.0)
    rep = mx.profiler.start_reporter(path, interval=0.05, registry=reg)
    time.sleep(0.25)
    rep.stop()
    rep.stop()  # idempotent
    with open(path) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    assert len(lines) >= 2  # periodic lines + the final flush
    for ln in lines:
        assert ln["counters"]["unit.count"] == 2
        assert ln["histograms"]["unit.ms"]["p90"] == 5.0
        assert "rates" in ln and "t" in ln and "rank" in ln


def test_executor_compile_metrics():
    """First program run per executor counts as the compile; bind
    registers its buffers in the live-buffer-bytes gauge."""
    import gc

    gc.collect()  # flush pending executor finalizers from earlier tests
    mx.profiler.reset_metrics()
    before = mx.profiler.metrics_summary()["gauges"].get(
        "executor.live_buffer_bytes", 0.0)
    mod = mx.mod.Module(_mlp_binary(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 6))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    after = mx.profiler.metrics_summary()["gauges"].get(
        "executor.live_buffer_bytes", 0.0)
    assert mod._exec._buffer_bytes > 0
    assert after - before == mod._exec._buffer_bytes
    batch = mx.io.DataBatch([mx.nd.zeros((4, 6))], [mx.nd.zeros((4,))])
    mod.forward(batch, is_train=False)
    mod.forward(batch, is_train=False)
    s = mx.profiler.metrics_summary()
    # exactly one compile: the second forward hit XLA's cache
    assert s["counters"]["executor.compiles"] == 1
    assert s["histograms"]["executor.compile_ms"]["count"] == 1
    mx.profiler.reset_metrics()


def test_fit_step_timeline(tmp_path):
    """fit() emits the step timeline: io.next (input wait) and
    fit.step spans with epoch/step args."""
    fname = str(tmp_path / "trace.json")
    mx.profiler.profiler_set_config(mode="all", filename=fname)
    mx.profiler.profiler_set_state("run")
    np.random.seed(3)
    X = np.random.randn(30, 6).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=10)
    mod = mx.mod.Module(_mlp_binary(), context=mx.cpu())
    mod.fit(it, num_epoch=2, optimizer="sgd")
    mx.profiler.profiler_set_state("stop")
    with open(fname) as f:
        trace = json.load(f)
    evs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    steps = [e for e in evs if e["name"] == "fit.step"]
    waits = [e for e in evs if e["name"] == "io.next"]
    assert steps and waits
    for e in steps:
        assert "step" in e["args"] and "epoch" in e["args"]
    assert {e["args"]["epoch"] for e in steps} == {0, 1}
    # the fused step event carries its step number and compile flag:
    # some first-run-per-module compiles, then cached steady state
    # (the global profiler accumulates events across modules)
    fused = [e for e in evs if e["name"] == "Module.fused_step"]
    assert fused and all("step" in e["args"] for e in fused)
    assert any(e["args"]["compile"] for e in fused)
    assert any(not e["args"]["compile"] for e in fused)


def test_ps_sync_watchdog_names_straggler(caplog):
    """A sync round missing one worker's push past the deadline logs
    WHO is late — instead of the 600 s wait_for hanging silently."""
    from mxnet_tpu.ps import ParameterServer, PSClient

    srv = ParameterServer(num_workers=2, sync=True, watchdog_deadline=0.3)
    try:
        c0 = PSClient("127.0.0.1", srv.port, worker=0)
        c0.init("w", np.zeros((3,), np.float32))
        with caplog.at_level(logging.WARNING):
            c0.push_sync("w", np.ones((3,), np.float32))
            time.sleep(1.2)
        msgs = [r.getMessage() for r in caplog.records
                if "[watchdog]" in r.getMessage()]
        assert any("arrived workers [0]" in m
                   and "waiting on workers [1]" in m for m in msgs), msgs
        # the late worker arrives; the round completes and state clears
        c1 = PSClient("127.0.0.1", srv.port, worker=1)
        c1.push_sync("w", np.ones((3,), np.float32))
        out = c0.pull("w", min_round=1)
        np.testing.assert_allclose(out, np.full((3,), 2.0))
        assert not srv._round_open_t and not srv._arrivals
        # round spread was measured for the completed round
        spread = mx.profiler.metrics_summary()["histograms"].get(
            "ps.round_spread_ms")
        assert spread and spread["count"] >= 1
        c0.close()
        c1.close()
    finally:
        srv.close()


def test_trace_merge_clock_alignment(tmp_path):
    """Unit check of tools/trace_merge.py: wall-clock offsets applied,
    rank-keyed pids, metadata rewritten."""
    import trace_merge

    def mk(rank, wall0, ts):
        return {
            "traceEvents": [
                {"name": "process_name", "ph": "M", "pid": 12345, "tid": 0,
                 "args": {"name": f"rank {rank}"}},
                {"name": "work", "cat": "op", "ph": "X", "ts": ts,
                 "dur": 10.0, "pid": 12345, "tid": 1,
                 "args": {"step": rank}},
            ],
            "displayTimeUnit": "ms",
            "metadata": {"rank": rank, "pid": 12345,
                         "clock_sync": {"wall_time_s": wall0,
                                        "perf_counter_s": 0.0}},
        }

    p0 = tmp_path / "trace_rank0.json"
    p1 = tmp_path / "trace_rank1.json"
    p0.write_text(json.dumps(mk(0, 100.0, 5.0)))
    p1.write_text(json.dumps(mk(1, 100.5, 5.0)))
    merged = trace_merge.merge_traces([
        trace_merge.load_trace(str(p0)), trace_merge.load_trace(str(p1))])
    evs = merged["traceEvents"]
    assert {e["pid"] for e in evs} == {0, 1}
    xs = {e["pid"]: e for e in evs if e.get("ph") == "X"}
    # rank 1's wall clock was 0.5 s ahead → its events shift +0.5e6 us
    assert xs[0]["ts"] == pytest.approx(5.0)
    assert xs[1]["ts"] == pytest.approx(5.0 + 0.5e6)
    assert xs[1]["args"]["step"] == 1  # args survive the merge
    assert merged["metadata"]["merged_ranks"] == [0, 1]
    # directory input collection
    files = trace_merge.collect_inputs([str(tmp_path)])
    assert [os.path.basename(f) for f in files] == [
        "trace_rank0.json", "trace_rank1.json"]


# ---------------------------------------------------------------------------
# tools/parse_log.py + tools/xplane_parse.py
# ---------------------------------------------------------------------------


def test_parse_log_plain_scientific_and_nan(tmp_path):
    import parse_log

    lines = [
        "2026-08-03 INFO Epoch[0] Train-accuracy=0.5\n",
        "2026-08-03 INFO Epoch[0] Validation-accuracy=0.25\n",
        "2026-08-03 INFO Epoch[0] Time cost=12.5\n",
        "2026-08-03 INFO Epoch[1] Train-accuracy=1.5e-01\n",  # scientific
        "2026-08-03 INFO Epoch[1] Validation-accuracy=nan\n",  # diverged
        "2026-08-03 INFO Epoch[1] Time cost=1.2e+01\n",
        "unrelated line\n",
    ]
    data = parse_log.parse(lines)
    assert set(data) == {0, 1}
    # epoch 0: plain decimals
    assert data[0][0] == [0.5, 1]
    assert data[0][1] == [0.25, 1]
    assert data[0][2] == [12.5, 1]
    # epoch 1: scientific notation parsed, nan tolerated (not skipped)
    assert data[1][0][0] == pytest.approx(0.15)
    assert data[1][1][1] == 1 and math.isnan(data[1][1][0])
    assert data[1][2][0] == pytest.approx(12.0)


def _vint(v):
    out = b""
    while True:
        b7 = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b7 | 0x80])
        else:
            return out + bytes([b7])


def _pb(fn, payload):
    """Length-delimited field."""
    return _vint((fn << 3) | 2) + _vint(len(payload)) + payload


def _pbv(fn, v):
    """Varint field."""
    return _vint(fn << 3) + _vint(v)


def _synthetic_xspace():
    """Hand-encode a tiny XSpace: one TPU device plane, an 'XLA
    Modules' line with two executions of one module (3 ms + 1 ms)."""
    ev1 = _pbv(1, 1) + _pbv(2, 0) + _pbv(3, 3_000_000_000)  # 3e9 ps = 3 ms
    ev2 = _pbv(1, 1) + _pbv(2, 5_000_000_000) + _pbv(3, 1_000_000_000)
    line = (_pb(2, b"XLA Modules") + _pbv(3, 1234)
            + _pb(4, ev1) + _pb(4, ev2))
    emeta = _pbv(1, 1) + _pb(2, b"jit_fused_step")  # XEventMetadata
    entry = _pbv(1, 1) + _pb(2, emeta)              # map<id, metadata>
    plane = _pb(2, b"/device:TPU:0") + _pb(3, line) + _pb(4, entry)
    return _pb(1, plane)  # XSpace.planes


def test_xplane_parse_synthetic(tmp_path):
    import xplane_parse

    pb = tmp_path / "host.xplane.pb"
    pb.write_bytes(_synthetic_xspace())
    planes = xplane_parse.load_xspace(str(pb))
    assert len(planes) == 1
    p = planes[0]
    assert p.name == "/device:TPU:0"
    assert p.event_names == {1: "jit_fused_step"}
    assert len(p.lines) == 1
    ln = p.lines[0]
    assert ln.name == "XLA Modules" and ln.timestamp_ns == 1234
    assert [e.duration_ps for e in ln.events] == [
        3_000_000_000, 1_000_000_000]
    # the shared helper: dominant module = 4 ms over 2 executions
    ms, cnt = xplane_parse.dominant_module_ms(str(tmp_path))
    assert cnt == 2
    assert ms == pytest.approx(2.0)


def test_xplane_parse_real_trace(tmp_path):
    """End-to-end: parse the XSpace jax.profiler actually writes."""
    logdir = str(tmp_path / "xla")
    mx.profiler.start_xla_trace(logdir)
    mx.nd.dot(mx.nd.ones((16, 16)), mx.nd.ones((16, 16))).asnumpy()
    mx.profiler.stop_xla_trace()
    import glob

    import xplane_parse

    paths = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                      recursive=True)
    assert paths, "jax wrote no xplane.pb"
    planes = xplane_parse.load_xspace(paths[0])
    assert planes
    assert any(p.lines for p in planes)
