"""Monitor / profiler / visualization tests (reference: monitor usage
in docs, test_viz.py, profiler dump format)."""

import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_monitor_collects_stats():
    np.random.seed(0)
    X = np.random.randn(40, 6).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                              name="fc"), name="softmax")
    mod = mx.mod.Module(sym, context=mx.cpu())
    it = mx.io.NDArrayIter(X, y, batch_size=10)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mon = mx.Monitor(interval=1, pattern=".*")
    mod.install_monitor(mon)
    b = next(iter(it))
    mon.tic()
    mod.forward(b, is_train=False)
    res = mon.toc()
    names = [k for _, k, _ in res]
    assert any("fc" in n for n in names), names
    assert any("weight" in n for n in names), names  # weights stat'd too
    for _, _, v in res:
        assert "nan" not in v.lower()


def test_monitor_finds_nan():
    """The NaN-hunt workflow: a poisoned weight shows up in the stats."""
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                              name="fc"), name="softmax")
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 6))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    bad = mod._exec.arg_dict["fc_weight"].asnumpy().copy()
    bad[0, 0] = np.nan
    mod._exec.arg_dict["fc_weight"][:] = bad
    mon = mx.Monitor(interval=1, pattern=".*fc.*")
    mod.install_monitor(mon)
    mon.tic()
    mod.forward(mx.io.DataBatch([mx.nd.zeros((4, 6))],
                                [mx.nd.zeros((4,))]), is_train=False)
    res = mon.toc()
    assert any("nan" in v.lower() for _, _, v in res), res


def test_profiler_chrome_trace(tmp_path):
    fname = str(tmp_path / "trace.json")
    mx.profiler.profiler_set_config(mode="all", filename=fname)
    mx.profiler.profiler_set_state("run")
    X = np.random.randn(30, 6).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=10)
    mod = mx.mod.Module(_mlp_binary(), context=mx.cpu())
    mod.fit(it, num_epoch=1, optimizer="sgd")
    mx.profiler.profiler_set_state("stop")
    assert os.path.isfile(fname)
    with open(fname) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    assert len(events) > 0
    names = {e["name"] for e in events}
    assert any("fused_step" in n or "forward" in n for n in names), names
    for e in events:
        assert e["ph"] == "X" and "ts" in e and "dur" in e


def _mlp_binary():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_print_summary():
    out = mx.viz.print_summary(_mlp(), shape={"data": (8, 6)})
    assert "fc1(FullyConnected)" in out
    assert "Total params" in out
    # fc1: 6*8+8 = 56; fc2: 8*3+3 = 27
    assert "Total params: 83" in out


def test_plot_network():
    dot = mx.viz.plot_network(_mlp(), shape={"data": (8, 6)},
                              save_format="dot")
    src = dot.source
    assert "fc1" in src and "relu1" in src and "softmax" in src
    assert "fc1_weight" not in src  # weights hidden
    assert "->" in src or "--" in src


def test_xla_trace_smoke(tmp_path):
    """jax.profiler passthrough writes an XPlane trace directory."""
    logdir = str(tmp_path / "xla")
    mx.profiler.start_xla_trace(logdir)
    mx.nd.dot(mx.nd.ones((32, 32)), mx.nd.ones((32, 32))).asnumpy()
    mx.profiler.stop_xla_trace()
    found = []
    for root, _, files in os.walk(logdir):
        found.extend(files)
    assert found, "no trace files written"


def test_monitor_fires_during_training():
    """The fused path must yield to the tap: training forwards are monitored."""
    np.random.seed(1)
    X = np.random.randn(20, 6).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=10)
    mod = mx.mod.Module(_mlp_binary(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params()
    mod.init_optimizer(optimizer="sgd")
    mon = mx.Monitor(interval=1, pattern=".*output.*")
    mod.install_monitor(mon)
    b = next(iter(it))
    mon.tic()
    mod.forward(b, is_train=True)
    mod.backward()
    mod.update()
    res = mon.toc()
    assert any("output" in k for _, k, _ in res), res


def test_env_var_catalog():
    """Every env var the code reads is declared in the config catalog."""
    import re

    cat = {v.name for v in mx.config.list_env()}
    # scan the source for MXNET_* reads
    used = set()
    pkg = os.path.dirname(mx.__file__)
    for root, _, files in os.walk(pkg):
        for f in files:
            if not f.endswith(".py") or f == "config.py":
                continue
            src = open(os.path.join(root, f)).read()
            used.update(re.findall(r"MXNET_[A-Z_]+", src))
    used.discard("MXNET_")  # the prefix mention in base.py docs
    missing = used - cat
    assert not missing, f"undeclared env vars: {sorted(missing)}"
    # catalog answers queries
    v = mx.config.describe("MXNET_BACKWARD_DO_MIRROR")
    assert v.default == 0 and "recompute" in v.doc
    cur = mx.config.current()
    assert "MXNET_FUSED_STEP" in cur
