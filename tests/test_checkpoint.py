"""Fault-tolerant checkpointing tests: atomic writes, the sharded
commit protocol, corruption fallback, auto-resume bit-exactness,
fault injection (writer killed mid-shard), SIGTERM preemption, env-var
validation, and the inspect/bench tools."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import checkpoint as C

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tests"))

import ckpt_crash_worker as W  # noqa: E402


def _subproc_env():
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("MXNET_CKPT_CRASH", None)
    return env


# ---------------------------------------------------------------------------
# satellite: atomic model.save_checkpoint / clear load_checkpoint errors
# ---------------------------------------------------------------------------

def _small_sym():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_save_checkpoint_atomic_and_loadable(tmp_path):
    prefix = str(tmp_path / "model")
    args = {"fc_weight": mx.nd.ones((4, 3)), "fc_bias": mx.nd.zeros((4,))}
    mx.model.save_checkpoint(prefix, 3, _small_sym(), args, {})
    # no temp litter: a crash mid-write must never shadow the real files
    leftovers = [f for f in os.listdir(tmp_path) if ".part." in f]
    assert leftovers == []
    sym, args2, aux2 = mx.model.load_checkpoint(prefix, 3)
    np.testing.assert_array_equal(args2["fc_weight"].asnumpy(),
                                  args["fc_weight"].asnumpy())
    assert aux2 == {}


def test_load_checkpoint_missing_file_names_it(tmp_path):
    prefix = str(tmp_path / "nope")
    with pytest.raises(mx.MXNetError, match="missing symbol file.*nope"):
        mx.model.load_checkpoint(prefix, 0)
    # symbol present, params missing
    _small_sym().save(prefix + "-symbol.json")
    with pytest.raises(mx.MXNetError, match=r"missing params file.*0007"):
        mx.model.load_checkpoint(prefix, 7)


def test_load_checkpoint_corrupt_params_names_file(tmp_path):
    prefix = str(tmp_path / "model")
    args = {"fc_weight": mx.nd.ones((4, 3)), "fc_bias": mx.nd.zeros((4,))}
    mx.model.save_checkpoint(prefix, 1, _small_sym(), args, {})
    pfile = prefix + "-0001.params"
    blob = open(pfile, "rb").read()
    with open(pfile, "wb") as f:
        f.write(blob[:len(blob) // 2])  # truncate: crash-mid-write relic
    with pytest.raises(mx.MXNetError, match="0001.params"):
        mx.model.load_checkpoint(prefix, 1)
    with open(pfile, "wb") as f:
        f.write(b"garbage not a params file")
    with pytest.raises(mx.MXNetError, match="0001.params"):
        mx.model.load_checkpoint(prefix, 1)


# ---------------------------------------------------------------------------
# manager: roundtrip, commit protocol, GC, corruption fallback
# ---------------------------------------------------------------------------

def test_manager_roundtrip_and_gc(tmp_path):
    d = str(tmp_path / "ckpt")
    params = W.train(ckpt_dir=d, num_epoch=2, every_n=3)
    infos = [i for i in C.list_checkpoints(d) if i.committed]
    # 24 steps, every 3 -> saves at 3..24; keep=10 in the worker
    assert [i.step for i in infos] == [3, 6, 9, 12, 15, 18, 21, 24]
    assert C.verify_checkpoint(infos[-1].path) == []
    state = C.load_shard(infos[-1].path, 0)
    assert state["step"] == 24 and state["epoch"] == 1
    assert state["nbatch"] == 11  # 12 batches/epoch
    for k, v in state["arg_params"].items():
        np.testing.assert_array_equal(v, params[k])
    assert state["optimizer"]["kind"] == "fused"
    assert "fc1_weight" in state["optimizer"]["states"]
    assert state["iter_state"]["kind"] == "NDArrayIter"
    assert state["rng"] is not None


def test_manager_keep_gc(tmp_path):
    d = str(tmp_path / "ckpt")
    mgr = mx.CheckpointManager(d, keep=2, async_save=False)

    class FakeModule:
        optimizer_initialized = False

        def get_params(self):
            return {"w": mx.nd.ones((2, 2))}, {}

    mod = FakeModule()
    for s in range(1, 6):
        mgr.save(module=mod, epoch=0, nbatch=s, step=s)
    infos = [i for i in C.list_checkpoints(d) if i.committed]
    assert [i.step for i in infos] == [4, 5]


def test_restore_falls_back_on_corruption(tmp_path, caplog):
    d = str(tmp_path / "ckpt")
    W.train(ckpt_dir=d, num_epoch=1, every_n=6)  # commits steps 6, 12
    infos = [i for i in C.list_checkpoints(d) if i.committed]
    assert [i.step for i in infos] == [6, 12]
    # corrupt the NEWEST shard (bit flip)
    shard = os.path.join(infos[-1].path, "shard-00000.bin")
    blob = bytearray(open(shard, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(shard, "wb") as f:
        f.write(bytes(blob))
    assert C.verify_checkpoint(infos[-1].path) != []
    mgr = mx.CheckpointManager(d)
    state = mgr.load_latest()
    assert state is not None and state["step"] == 6  # fell back


def test_restore_ignores_torn_tmp(tmp_path):
    d = str(tmp_path / "ckpt")
    W.train(ckpt_dir=d, num_epoch=1, every_n=12)  # commits step 12
    # a torn, never-committed attempt with a HIGHER step
    torn = os.path.join(d, "ckpt-000000000099.tmp")
    os.makedirs(torn)
    with open(os.path.join(torn, "shard-00000.bin"), "wb") as f:
        f.write(b"half a shard")
    mgr = mx.CheckpointManager(d)
    state = mgr.load_latest()
    assert state["step"] == 12
    # restore-side GC retired the torn attempt
    assert not os.path.isdir(torn)


def test_uncommitted_dir_without_marker_is_not_latest(tmp_path):
    d = str(tmp_path / "ckpt")
    W.train(ckpt_dir=d, num_epoch=1, every_n=12)
    # a renamed dir whose COMMIT marker is missing (e.g. deleted)
    good = [i for i in C.list_checkpoints(d) if i.committed][0]
    fake = os.path.join(d, "ckpt-000000000050")
    os.makedirs(fake)
    state = mx.CheckpointManager(d).load_latest()
    assert state["step"] == good.step


# ---------------------------------------------------------------------------
# auto-resume bit-exactness (single process, fused path)
# ---------------------------------------------------------------------------

def test_fit_resume_auto_bitexact_mid_epoch(tmp_path):
    ref = W.train(ckpt_dir=None, num_epoch=2)

    d = str(tmp_path / "ckpt")

    class Stop(Exception):
        pass

    # interrupted run: dies mid-epoch 0 (after batch 7; ckpt at step 6)
    mx.random.seed(11)
    np.random.seed(11)
    X, y = W.make_data()
    it = mx.io.NDArrayIter(X, y, batch_size=W.BATCH, shuffle=True)
    mod = mx.mod.Module(W.build_sym(), context=mx.cpu())
    mgr = mx.CheckpointManager(d, every_n_steps=6, async_save=True, keep=10)

    def boom(param):
        if param.epoch == 0 and param.nbatch == 7:
            raise Stop()

    with pytest.raises(Stop):
        mod.fit(it, num_epoch=2, optimizer="sgd",
                optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
                initializer=mx.initializer.Xavier(rnd_type="gaussian"),
                eval_metric="acc", checkpoint=mgr, resume="auto",
                batch_end_callback=boom)
    mgr.close()
    committed = [i.step for i in C.list_checkpoints(d) if i.committed]
    assert committed == [6]

    # resumed run: DIFFERENT ambient seeds — everything that matters
    # (params, momentum, shuffle order, RNG key, batch position) must
    # come from the checkpoint
    mx.random.seed(555)
    np.random.seed(555)
    resumed = W.train(ckpt_dir=d, num_epoch=2, every_n=6)
    for k in ref:
        np.testing.assert_array_equal(
            ref[k], resumed[k],
            err_msg=f"{k}: resumed weights diverge from uninterrupted run")


def test_fit_resume_requires_manager():
    X, y = W.make_data()
    it = mx.io.NDArrayIter(X, y, batch_size=W.BATCH)
    mod = mx.mod.Module(W.build_sym(), context=mx.cpu())
    with pytest.raises(mx.MXNetError, match="resume"):
        mod.fit(it, num_epoch=1, resume="auto")


# ---------------------------------------------------------------------------
# fault injection: writer killed mid-shard; SIGTERM preemption
# ---------------------------------------------------------------------------

def test_kill_background_writer_mid_shard_then_resume(tmp_path):
    """The background writer dies HALFWAY through a shard write; the
    torn attempt must be invisible to restore, and the resumed run must
    bit-match an uninterrupted one."""
    d = str(tmp_path / "ckpt")
    env = _subproc_env()
    env["MXNET_CKPT_CRASH"] = "mid_shard:2"  # 2nd save (step 12) tears
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tests", "ckpt_crash_worker.py"),
         "--ckpt-dir", d, "--epochs", "2", "--every-n", "6"],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env)
    assert r.returncode == 9, r.stdout + r.stderr  # the injected exit
    infos = C.list_checkpoints(d)
    committed = [i for i in infos if i.committed]
    torn = [i for i in infos if not i.committed]
    assert [i.step for i in committed] == [6]
    assert [i.step for i in torn] == [12]
    assert C.verify_checkpoint(committed[0].path) == []

    # restore picks the committed step-6 checkpoint, ignoring the torn
    # one, and replays to the same final weights as an untouched run
    mx.random.seed(321)
    np.random.seed(321)
    resumed = W.train(ckpt_dir=d, num_epoch=2, every_n=6)
    ref = W.train(ckpt_dir=None, num_epoch=2)
    for k in ref:
        np.testing.assert_array_equal(ref[k], resumed[k])


def test_sigterm_triggers_emergency_checkpoint(tmp_path):
    """Preemption notice: SIGTERM mid-fit must produce a committed
    emergency checkpoint and still kill the process with SIGTERM
    semantics."""
    d = str(tmp_path / "ckpt")
    p = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests", "ckpt_crash_worker.py"),
         "--ckpt-dir", d, "--epochs", "50", "--every-n", "0",
         "--sleep", "0.05", "--progress"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=REPO, env=_subproc_env())
    out_lines = []
    try:
        # wait for a few completed steps, then deliver the preemption
        deadline = time.time() + 180
        while time.time() < deadline:
            line = p.stdout.readline()
            if not line:
                break
            out_lines.append(line)
            if "BATCH 3" in line:
                break
        assert any("BATCH 3" in l for l in out_lines), "".join(out_lines)
        p.send_signal(signal.SIGTERM)
        out, _ = p.communicate(timeout=120)
        out_lines.append(out or "")
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
    out = "".join(out_lines)
    assert p.returncode == -signal.SIGTERM, out
    infos = [i for i in C.list_checkpoints(d) if i.committed]
    assert len(infos) == 1, out
    state = C.load_shard(infos[0].path, 0)
    assert state["reason"] == "preempt"
    assert state["step"] > 0


# ---------------------------------------------------------------------------
# env-var catalog + loud validation
# ---------------------------------------------------------------------------

def test_ckpt_env_vars_registered():
    names = {v.name for v in mx.config.list_env()}
    for var in ("MXNET_CKPT_DIR", "MXNET_CKPT_EVERY_N_STEPS",
                "MXNET_CKPT_KEEP", "MXNET_CKPT_ASYNC",
                "MXNET_CKPT_COMMIT_TIMEOUT", "MXNET_CKPT_CRASH"):
        assert var in names
        assert mx.config.describe(var).doc


@pytest.mark.parametrize("var,bad,msg", [
    ("MXNET_CKPT_EVERY_N_STEPS", "banana", "expected int"),
    ("MXNET_CKPT_EVERY_N_STEPS", "-3", "must be >="),
    ("MXNET_CKPT_KEEP", "0", "must be >="),
    ("MXNET_CKPT_KEEP", "2.5", "expected int"),
    ("MXNET_CKPT_COMMIT_TIMEOUT", "soon", "expected float"),
    ("MXNET_CKPT_CRASH", "sometimes", "MXNET_CKPT_CRASH"),
    ("MXNET_CKPT_CRASH", "mid_shard:x", "MXNET_CKPT_CRASH"),
])
def test_invalid_ckpt_env_fails_loudly(tmp_path, monkeypatch, var, bad, msg):
    monkeypatch.setenv(var, bad)
    with pytest.raises(mx.MXNetError, match=msg):
        mx.CheckpointManager(str(tmp_path / "c"))


def test_explicit_args_override_env(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_CKPT_EVERY_N_STEPS", "7")
    monkeypatch.setenv("MXNET_CKPT_KEEP", "9")
    mgr = mx.CheckpointManager(str(tmp_path / "c"), every_n_steps=2)
    assert mgr.every_n_steps == 2  # arg wins
    assert mgr.keep == 9           # env fills the rest


# ---------------------------------------------------------------------------
# metrics + tools
# ---------------------------------------------------------------------------

def test_ckpt_metrics_recorded(tmp_path):
    mx.profiler.reset_metrics()
    W.train(ckpt_dir=str(tmp_path / "c"), num_epoch=1, every_n=12)
    s = mx.profiler.metrics_summary()
    assert s["counters"]["ckpt.saves"] >= 1
    assert s["counters"]["ckpt.bytes"] > 0
    assert s["gauges"]["ckpt.last_step"] == 12.0
    assert s["histograms"]["ckpt.blocking_ms"]["count"] >= 1
    assert s["histograms"]["ckpt.save_ms"]["count"] >= 1


def test_ckpt_inspect_tool(tmp_path):
    d = str(tmp_path / "ckpt")
    W.train(ckpt_dir=d, num_epoch=1, every_n=6)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ckpt_inspect.py"),
         d, "--verify"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
        env=_subproc_env())
    assert r.returncode == 0, r.stdout + r.stderr
    assert "step=6 committed" in r.stdout
    assert "checksums=OK" in r.stdout
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ckpt_inspect.py"),
         d, "--manifest"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
        env=_subproc_env())
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "fc1_weight" in r2.stdout
    assert "kind=fused" in r2.stdout
    # corrupt a shard -> --verify exits non-zero and says CORRUPT
    info = [i for i in C.list_checkpoints(d) if i.committed][-1]
    shard = os.path.join(info.path, "shard-00000.bin")
    with open(shard, "r+b") as f:
        f.seek(10)
        f.write(b"\xff\xff\xff")
    r3 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ckpt_inspect.py"),
         d, "--verify"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
        env=_subproc_env())
    assert r3.returncode == 1
    assert "CORRUPT" in r3.stdout


def test_bench_ckpt_smoke():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_ckpt.py"),
         "--mb", "8", "--iters", "2"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env=_subproc_env())
    assert r.returncode == 0, r.stdout + r.stderr
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["sync_ms"] > 0 and out["async_blocking_ms"] > 0
    # the whole point: async blocks (much) less than a synchronous save
    assert out["blocking_ratio"] < 1.0


def test_bucketing_module_optimizer_snapshot_roundtrip():
    """BucketingModule delegates the checkpoint payload to the active
    bucket (which owns the adopted fused state)."""
    from mxnet_tpu.io import DataBatch, DataDesc

    def sym_gen(key):
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, num_hidden=8, name="fc")
        return (mx.sym.SoftmaxOutput(net, name="softmax"),
                ("data",), ("softmax_label",))

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8)
    mod.bind(data_shapes=[DataDesc("data", (4, 6))],
             label_shapes=[DataDesc("softmax_label", (4,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    rng = np.random.RandomState(0)
    for _ in range(2):
        batch = DataBatch(
            [mx.nd.array(rng.randn(4, 6).astype(np.float32))],
            [mx.nd.array(rng.randint(0, 8, 4).astype(np.float32))],
            pad=0, bucket_key=8,
            provide_data=[DataDesc("data", (4, 6))],
            provide_label=[DataDesc("softmax_label", (4,))])
        mod.forward_backward(batch)
        mod.update()
    payload = mod._optimizer_states_to_host()
    assert payload["kind"] == "fused"
    assert "fc_weight" in payload["states"]
    import jax

    before = np.asarray(
        jax.tree_util.tree_leaves(payload["states"]["fc_weight"])[0])
    assert np.abs(before).sum() > 0  # real momentum, not zeros
    from mxnet_tpu.checkpoint import _to_host_tree
    mod._install_optimizer_states(_to_host_tree(payload))
    after = mod._optimizer_states_to_host(lazy=False)
    np.testing.assert_allclose(
        np.asarray(jax.tree_util.tree_leaves(after["states"]["fc_weight"])[0]),
        before)
