"""Elastic fault-tolerant training (ISSUE 8): failure verdicts,
membership epochs, chaos injection, rollback-resume, and the ZeRO
re-scatter across MeshPlans — everything single-process so tier-1
stays fast; the real 2→1→2 process drill lives in test_dist.py
(slow) / tools/chaos_drill.py."""

import os
import signal
import tempfile
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.chaos import Chaos, get_chaos, reset_chaos
from mxnet_tpu.elastic import DeadRankError, Membership


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for var in ("MXNET_ELASTIC", "MXNET_ELASTIC_JOIN",
                "MXNET_HEARTBEAT_INTERVAL", "MXNET_DEAD_RANK_TIMEOUT",
                "MXNET_CHAOS_KILL_STEP", "MXNET_CHAOS_DEAD_RANK_STEP",
                "MXNET_CHAOS_DEAD_RANKS", "MXNET_CHAOS_HEARTBEAT_STALL",
                "MXNET_CHAOS_TORN_SOCKET", "MXNET_CHAOS_SLOW_RANK",
                "MXNET_CHAOS_RANK", "MXNET_CKPT_DIR"):
        monkeypatch.delenv(var, raising=False)
    reset_chaos()
    yield
    reset_chaos()


# ---------------------------------------------------------------------------
# satellite: unified liveness config with loud validation
# ---------------------------------------------------------------------------

def test_liveness_env_validation(monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_KVSTORE_HEARTBEAT_DIR", str(tmp_path))
    monkeypatch.setenv("MXNET_HEARTBEAT_INTERVAL", "banana")
    with pytest.raises(MXNetError, match="MXNET_HEARTBEAT_INTERVAL"):
        mx.kv.create("dist_sync")
    monkeypatch.setenv("MXNET_HEARTBEAT_INTERVAL", "-1")
    with pytest.raises(MXNetError, match="MXNET_HEARTBEAT_INTERVAL"):
        mx.kv.create("dist_sync")
    monkeypatch.setenv("MXNET_HEARTBEAT_INTERVAL", "0.25")
    monkeypatch.setenv("MXNET_ELASTIC", "1")
    monkeypatch.setenv("MXNET_DEAD_RANK_TIMEOUT", "0")
    with pytest.raises(MXNetError, match="MXNET_DEAD_RANK_TIMEOUT"):
        mx.kv.create("dist_sync")
    monkeypatch.setenv("MXNET_DEAD_RANK_TIMEOUT", "5")
    kv = mx.kv.create("dist_sync")  # valid values construct fine
    assert kv._hb_interval == 0.25


def test_both_consumers_read_unified_vars(monkeypatch, tmp_path):
    """The heartbeat writer cadence AND the staleness scan resolve
    through the new config vars (not the old scattered literals)."""
    hb = tmp_path / "hb"
    hb.mkdir()
    monkeypatch.setenv("MXNET_KVSTORE_HEARTBEAT_DIR", str(hb))
    monkeypatch.setenv("MXNET_HEARTBEAT_INTERVAL", "0.1")
    monkeypatch.setenv("MXNET_DEAD_RANK_TIMEOUT", "2.5")
    kv = mx.kv.create("dist_sync")
    assert kv._hb_interval == 0.1
    time.sleep(0.3)
    assert os.path.exists(hb / "hb_0")  # writer running on the cadence

    class TwoWorkerView(type(kv)):
        @property
        def num_workers(self):
            return 2

    kv.__class__ = TwoWorkerView
    # a peer 3s stale is dead under the 2.5s default resolved from env
    old = time.time() - 3
    (hb / "hb_1").write_text("x")
    os.utime(hb / "hb_1", (old, old))
    assert kv.dead_ranks(ranks=[0, 1]) == [1]
    assert kv.get_num_dead_node() == 1


# ---------------------------------------------------------------------------
# satellite: heartbeat-based death detection as a unit
# ---------------------------------------------------------------------------

def test_dead_ranks_stale_never_wrote_and_clock_skew(monkeypatch, tmp_path):
    hb = tmp_path / "hb"
    hb.mkdir()
    monkeypatch.setenv("MXNET_KVSTORE_HEARTBEAT_DIR", str(hb))
    kv = mx.kv.create("dist_sync")

    class FourWorkerView(type(kv)):
        @property
        def num_workers(self):
            return 4

    kv.__class__ = FourWorkerView
    now = time.time()
    # rank 1: fresh -> alive
    (hb / "hb_1").write_text("x")
    # rank 2: stale mtime -> dead
    (hb / "hb_2").write_text("x")
    os.utime(hb / "hb_2", (now - 100, now - 100))
    # rank 3: never wrote -> dead
    assert kv.dead_ranks(timeout=5, ranks=range(4)) == [2, 3]
    # clock skew: a peer whose filesystem clock runs AHEAD of ours must
    # never be accused — future mtimes count as fresh
    (hb / "hb_3").write_text("x")
    os.utime(hb / "hb_3", (now + 50, now + 50))
    assert kv.dead_ranks(timeout=5, ranks=range(4)) == [2]
    # our own rank is alive by construction even with no file
    assert 0 not in kv.dead_ranks(timeout=0.5, ranks=range(4))
    # check_peers raises the verdict form
    monkeypatch.setenv("MXNET_ELASTIC", "1")
    kv._elastic = True
    kv._active = [0, 1, 2]
    with pytest.raises(DeadRankError) as ei:
        kv.check_peers()
    assert ei.value.dead_ranks == [2]


# ---------------------------------------------------------------------------
# membership ledger + epoch consensus
# ---------------------------------------------------------------------------

def test_membership_bootstrap_remesh_and_join(tmp_path):
    m0 = Membership(str(tmp_path), rank=0)
    m1 = Membership(str(tmp_path), rank=1)
    rec = m0.bootstrap(active=[0, 1], world=2,
                       addrs={0: ("127.0.0.1", 1), 1: ("127.0.0.1", 2)},
                       secret=b"\x01\x02")
    assert rec["epoch"] == 0
    assert m1.read()["active"] == [0, 1]
    # bootstrap is idempotent — a second call can't fork the ledger
    assert m1.bootstrap([1], 1, {}, b"")["epoch"] == 0

    # scale-down consensus: the lone survivor (rank 0) convicts rank 1
    new = m0.remesh(dead=[1], is_alive=lambda r: r == 0, timeout=5)
    assert new["epoch"] == 1 and new["active"] == [0]
    assert list(new["addrs"]) == ["0"]  # dead shard dropped
    assert new["secret"] == rec["secret"]

    # scale-up: the returned rank requests, the survivor admits
    m1.request_join()
    assert m0.pending_joins() == [1]
    admitted = m0.admit([1])
    assert admitted["epoch"] == 2 and admitted["active"] == [0, 1]
    got = m1.await_epoch(1, timeout=5)
    assert got["epoch"] == 2 and 1 in got["active"]
    m1.clear_join()
    assert m0.pending_joins() == []


def test_membership_remesh_excluded_survivor_refuses(tmp_path):
    """A live rank that the committed epoch excludes must stop, not
    keep training against a world that fenced it out."""
    m0 = Membership(str(tmp_path), rank=0)
    m1 = Membership(str(tmp_path), rank=1)
    m0.bootstrap(active=[0, 1], world=2, addrs={}, secret=b"")
    m0.remesh(dead=[1], is_alive=lambda r: r == 0, timeout=5)
    with pytest.raises(MXNetError, match="declared us dead|considers us"):
        m1.remesh(dead=[0], is_alive=lambda r: True, timeout=1)


# ---------------------------------------------------------------------------
# chaos harness
# ---------------------------------------------------------------------------

def test_chaos_validation_is_loud(monkeypatch):
    monkeypatch.setenv("MXNET_CHAOS_KILL_STEP", "banana")
    with pytest.raises(MXNetError, match="MXNET_CHAOS_KILL_STEP"):
        Chaos()
    monkeypatch.setenv("MXNET_CHAOS_KILL_STEP", "-3")
    with pytest.raises(MXNetError, match="MXNET_CHAOS_KILL_STEP"):
        Chaos()
    monkeypatch.delenv("MXNET_CHAOS_KILL_STEP")
    monkeypatch.setenv("MXNET_CHAOS_DEAD_RANKS", "1,x")
    with pytest.raises(MXNetError, match="MXNET_CHAOS_DEAD_RANKS"):
        Chaos()
    monkeypatch.delenv("MXNET_CHAOS_DEAD_RANKS")
    monkeypatch.setenv("MXNET_CHAOS_TORN_SOCKET", "0")
    with pytest.raises(MXNetError, match="MXNET_CHAOS_TORN_SOCKET"):
        Chaos()


def test_chaos_dead_rank_injection_fires_once(monkeypatch):
    monkeypatch.setenv("MXNET_CHAOS_DEAD_RANK_STEP", "3")
    monkeypatch.setenv("MXNET_CHAOS_DEAD_RANKS", "2,5")
    ch = Chaos()
    for s in range(3):
        ch.on_step(s)
    with pytest.raises(DeadRankError) as ei:
        ch.on_step(3)
    assert ei.value.dead_ranks == [2, 5]
    ch.on_step(4)  # one-shot: training continues after recovery


def test_chaos_rank_filter_and_slow(monkeypatch):
    monkeypatch.setenv("MXNET_CHAOS_DEAD_RANK_STEP", "0")
    monkeypatch.setenv("MXNET_CHAOS_RANK", "1")
    ch = Chaos()
    ch.on_step(0, rank=0)  # filtered: no raise
    with pytest.raises(DeadRankError):
        ch.on_step(0, rank=1)
    monkeypatch.setenv("MXNET_CHAOS_SLOW_RANK", "0.05")
    monkeypatch.delenv("MXNET_CHAOS_DEAD_RANK_STEP")
    ch = Chaos()
    t0 = time.perf_counter()
    ch.on_step(0, rank=1)
    assert time.perf_counter() - t0 >= 0.05


def test_chaos_heartbeat_stall_consumed_once(monkeypatch):
    monkeypatch.setenv("MXNET_CHAOS_HEARTBEAT_STALL", "2.5")
    ch = Chaos()
    assert ch.heartbeat_stall_s() == 2.5
    assert ch.heartbeat_stall_s() == 0.0  # one-shot fault


def test_elastic_barrier_dead_peer_raises_fast(monkeypatch, tmp_path):
    """The tentpole's hang-to-verdict promotion: an elastic barrier
    whose missing peer is heartbeat-stale raises DeadRankError within
    the dead-rank timeout instead of waiting forever."""
    hb = tmp_path / "hb"
    hb.mkdir()
    monkeypatch.setenv("MXNET_KVSTORE_HEARTBEAT_DIR", str(hb))
    monkeypatch.setenv("MXNET_ELASTIC", "1")
    monkeypatch.setenv("MXNET_DEAD_RANK_TIMEOUT", "0.5")
    kv = mx.kv.create("dist_sync")
    kv._active = [0, 1]
    (hb / "hb_1").write_text("x")
    old = time.time() - 10
    os.utime(hb / "hb_1", (old, old))
    t0 = time.perf_counter()
    with pytest.raises(DeadRankError) as ei:
        kv._elastic_barrier()
    assert ei.value.dead_ranks == [1]
    assert time.perf_counter() - t0 < 5.0


def test_elastic_barrier_straggler_is_not_a_death(monkeypatch, tmp_path):
    """A live-but-slow peer (fresh heartbeat, late stamp) must NOT be
    convicted: the barrier completes once the stamp lands."""
    import threading

    hb = tmp_path / "hb"
    hb.mkdir()
    monkeypatch.setenv("MXNET_KVSTORE_HEARTBEAT_DIR", str(hb))
    monkeypatch.setenv("MXNET_ELASTIC", "1")
    monkeypatch.setenv("MXNET_DEAD_RANK_TIMEOUT", "1.0")
    monkeypatch.setenv("MXNET_WATCHDOG_DEADLINE", "0.2")
    kv = mx.kv.create("dist_sync")
    kv._active = [0, 1]

    # the peer's heartbeat must exist BEFORE the barrier's first
    # staleness scan (never-wrote = dead is the correct verdict for a
    # peer with no heartbeat) — on a loaded single-core box the thread
    # may not get scheduled before the scan, which is a test race, not
    # a straggler conviction
    (hb / "hb_1").write_text("x")

    def late_peer():
        # keep the peer's heartbeat fresh, stamp the barrier late
        for _ in range(6):
            (hb / "hb_1").write_text("x")
            time.sleep(0.1)
        (hb / "eb_0_1_1").write_text("x")

    t = threading.Thread(target=late_peer)
    t.start()
    kv._elastic_barrier()  # waits past the watchdog log, no verdict
    t.join()


def test_chaos_singleton_tracks_env(monkeypatch):
    a = get_chaos()
    assert not a.armed
    monkeypatch.setenv("MXNET_CHAOS_SLOW_RANK", "0.01")
    b = get_chaos()
    assert b is not a and b.armed


# ---------------------------------------------------------------------------
# parameter-server epoch fencing + bounded reconnect
# ---------------------------------------------------------------------------

def test_ps_epoch_fencing_and_remesh():
    from mxnet_tpu.ps import ParameterServer, ShardedPSClient

    srv = ParameterServer(secret=b"s" * 32, num_workers=2, sync=True,
                          sync_wait_timeout=2.0)
    try:
        cl = ShardedPSClient([("127.0.0.1", srv.port)], secret=b"s" * 32,
                             worker=0)
        cl.init("w", np.zeros(4, np.float32))
        cl.push_sync("w", np.ones(4, np.float32))
        # a frame from ANOTHER membership epoch is rejected — the fence
        # that keeps a dead/returning rank's stale traffic out
        stale = ShardedPSClient([("127.0.0.1", srv.port)],
                                secret=b"s" * 32, worker=1)
        stale.set_epoch(7)
        with pytest.raises(MXNetError, match="stale membership epoch"):
            stale.push_sync("w", np.ones(4, np.float32))
        # remesh: epoch advances, quorum shrinks to 1, store resets
        cl.remesh(epoch=1, num_workers=1, reset=True)
        with pytest.raises(MXNetError, match="uninitialized"):
            cl.pull("w", shape=(4,), dtype=np.float32)
        cl.init("w", np.full(4, 5.0, np.float32))
        cl.push_sync("w", np.ones(4, np.float32))  # 1-worker round closes
        out = cl.pull("w", shape=(4,), dtype=np.float32, min_round=1)
        np.testing.assert_allclose(out, np.ones(4))
        # duplicate remesh (another survivor) is a no-op; regression is
        # refused
        cl.remesh(epoch=1, num_workers=1)
        with pytest.raises(MXNetError, match="refused"):
            cl.remesh(epoch=0, num_workers=2)
    finally:
        srv.close()


def test_ps_client_reconnects_with_backoff(monkeypatch):
    """A torn frame mid-send (chaos injection) must be healed by the
    bounded reconnect: the op retries on a fresh socket, the server
    never sees a half-applied push, and ps.reconnects counts it."""
    from mxnet_tpu import profiler as prof
    from mxnet_tpu.ps import ParameterServer, ShardedPSClient

    srv = ParameterServer(secret=b"s" * 32, num_workers=1)
    try:
        monkeypatch.setenv("MXNET_CHAOS_TORN_SOCKET", "3")
        reset_chaos()
        before = prof.metrics_summary().get("counters", {}).get(
            "ps.reconnects", 0)
        cl = ShardedPSClient([("127.0.0.1", srv.port)], secret=b"s" * 32,
                             worker=0)
        cl.init("w", np.zeros(4, np.float32))        # frame 1
        cl.push("w", np.ones(4, np.float32))          # frame 2
        cl.push("w", np.ones(4, np.float32))          # frame 3: torn
        out = cl.pull("w", shape=(4,), dtype=np.float32)
        np.testing.assert_allclose(out, np.ones(4))   # applied ONCE
        assert cl.clients[0].num_applied("w") == 2
        after = prof.metrics_summary().get("counters", {}).get(
            "ps.reconnects", 0)
        assert after == before + 1
    finally:
        srv.close()


def test_ps_reconnect_budget_validation(monkeypatch):
    from mxnet_tpu.ps import reconnect_budget

    monkeypatch.setenv("MXNET_KVSTORE_RECONNECTS", "banana")
    with pytest.raises(MXNetError, match="MXNET_KVSTORE_RECONNECTS"):
        reconnect_budget()
    monkeypatch.setenv("MXNET_KVSTORE_RECONNECTS", "-1")
    with pytest.raises(MXNetError, match="MXNET_KVSTORE_RECONNECTS"):
        reconnect_budget()
    monkeypatch.setenv("MXNET_KVSTORE_RECONNECTS", "2")
    assert reconnect_budget() == 2


# ---------------------------------------------------------------------------
# satellite: CheckpointManager emergency-save vs rollback re-entrancy
# ---------------------------------------------------------------------------

def test_sigterm_during_rollback_defers_emergency_save(tmp_path,
                                                       monkeypatch):
    mgr = mx.CheckpointManager(str(tmp_path), every_n_steps=0, keep=2,
                               rank=0, num_shards=1)
    calls = []
    monkeypatch.setattr(mgr, "save",
                        lambda *a, **k: calls.append(k.get("reason")))
    monkeypatch.setattr(os, "kill", lambda *a: calls.append("exit"))
    mgr._module = object()
    mgr._step = 3
    with mgr.rollback():
        mgr._on_signal(signal.SIGTERM, None)
        # mid-rollback: the handler must only latch, never save half-
        # restored state (the re-entrancy race this guard closes)
        assert calls == []
        assert mgr._preempted
    # at the guard's exit — a consistent boundary — exactly one
    # emergency save runs, then the signal is re-raised
    assert calls == ["preempt", "exit"]


def test_emergency_exit_is_reentrancy_safe(tmp_path, monkeypatch):
    mgr = mx.CheckpointManager(str(tmp_path), every_n_steps=0, keep=2,
                               rank=0, num_shards=1)
    calls = []

    def fake_save(*a, **k):
        calls.append("save")
        if len(calls) == 1:
            # a second SIGTERM lands while the first emergency save is
            # still writing — must NOT start a second save
            mgr._on_signal(signal.SIGTERM, None)

    monkeypatch.setattr(mgr, "save", fake_save)
    monkeypatch.setattr(os, "kill", lambda *a: calls.append("exit"))
    mgr._module = object()
    mgr._step = 1
    mgr._on_signal(signal.SIGTERM, None)
    assert calls == ["save", "exit"]


def test_step_abandoned_unlatches_deferred_save(tmp_path, monkeypatch):
    mgr = mx.CheckpointManager(str(tmp_path), every_n_steps=0, keep=2,
                               rank=0, num_shards=1)
    calls = []
    monkeypatch.setattr(mgr, "save",
                        lambda *a, **k: calls.append("save"))
    monkeypatch.setattr(os, "kill", lambda *a: calls.append("exit"))
    mgr._module = object()
    mgr._step = 1
    mgr.step_begin()
    mgr._on_signal(signal.SIGTERM, None)
    assert calls == []  # mid-step: deferred
    # the step dies to a DeadRankError — without step_abandoned the
    # latch would park the emergency save forever
    mgr.step_abandoned()
    assert not mgr._in_step


# ---------------------------------------------------------------------------
# resume-in-place: injected DeadRankError → rollback → resume (tier-1
# smoke for the multi-process chaos drill)
# ---------------------------------------------------------------------------

def _sym():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _train_once(ckpt_dir, monkeypatch, chaos_step=None):
    if chaos_step is not None:
        monkeypatch.setenv("MXNET_ELASTIC", "1")
        monkeypatch.setenv("MXNET_CHAOS_DEAD_RANK_STEP", str(chaos_step))
    else:
        monkeypatch.delenv("MXNET_ELASTIC", raising=False)
        monkeypatch.delenv("MXNET_CHAOS_DEAD_RANK_STEP", raising=False)
    reset_chaos()
    rng = np.random.RandomState(3)
    X = rng.randn(32, 8).astype(np.float32)
    y = rng.randint(0, 4, 32).astype(np.float32)
    mx.random.seed(11)
    np.random.seed(11)
    it = mx.io.NDArrayIter(X, y, batch_size=8, shuffle=False,
                           label_name="softmax_label")
    mod = mx.mod.Module(_sym(), context=mx.cpu())
    mgr = mx.CheckpointManager(ckpt_dir, every_n_steps=2,
                               async_save=False, keep=10)
    mod.fit(it, num_epoch=3, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
            initializer=mx.initializer.Xavier(rnd_type="gaussian"),
            eval_metric="acc", checkpoint=mgr)
    mgr.close()
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args.items()}


def test_dead_rank_rollback_resume_bitexact(tmp_path, monkeypatch):
    """The single-process smoke of the chaos drill: an injected
    DeadRankError mid-epoch makes fit roll back to the last committed
    checkpoint (params + momentum + RNG + data position) and resume —
    the final weights must BIT-match an uninterrupted run (replay from
    committed state is deterministic)."""
    ref = _train_once(str(tmp_path / "a"), monkeypatch)
    got = _train_once(str(tmp_path / "b"), monkeypatch, chaos_step=5)
    assert set(ref) == set(got)
    for k in ref:
        np.testing.assert_array_equal(ref[k], got[k], err_msg=k)


def test_dead_rank_without_checkpoint_is_loud(monkeypatch):
    monkeypatch.setenv("MXNET_ELASTIC", "1")
    monkeypatch.setenv("MXNET_CHAOS_DEAD_RANK_STEP", "1")
    reset_chaos()
    rng = np.random.RandomState(3)
    X = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, 16).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=8, label_name="softmax_label")
    mod = mx.mod.Module(_sym(), context=mx.cpu())
    with pytest.raises(MXNetError, match="CheckpointManager"):
        mod.fit(it, num_epoch=1, optimizer="sgd")


# ---------------------------------------------------------------------------
# tentpole: ZeRO-1 shard re-scatter across MeshPlans (dp' < dp)
# ---------------------------------------------------------------------------

def _make_mesh_mod(ndev, data):
    import jax

    from mxnet_tpu import parallel

    X, y = data
    mx.random.seed(7)
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_sym(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params(mx.initializer.Uniform(0.1))
    mod.set_mesh_plan(parallel.MeshPlan(jax.devices()[:ndev]))
    mod.init_optimizer(kvstore="tpu", optimizer="adam",
                       optimizer_params={"learning_rate": 0.05})
    return mod, it


def _run_steps(mod, it, skip, n):
    it.reset()
    done = 0
    for b in it:
        if done >= skip + n:
            break
        if done >= skip:
            mod.forward_backward(b)
            mod.update()
        done += 1
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args.items()}


def test_module_remesh_rescatters_zero_shards(monkeypatch):
    """Train on a dp=4 mesh (ZeRO state sharded 4-way), lose half the
    devices, Module.remesh to dp'=2: the optimizer state re-scatters
    through the layout-independent gather/load path and training
    continues — final weights must match a never-interrupted dp=4 run
    (the update math is layout-independent up to fp reassociation)."""
    import jax

    from mxnet_tpu import parallel

    monkeypatch.setenv("MXNET_ZERO", "1")
    rng = np.random.RandomState(3)
    data = (rng.randn(16 * 8, 8).astype(np.float32),
            rng.randint(0, 4, 16 * 8).astype(np.float32))

    mod_a, it_a = _make_mesh_mod(4, data)
    _run_steps(mod_a, it_a, 0, 4)
    ref = _run_steps(mod_a, it_a, 4, 4)

    mod_b, it_b = _make_mesh_mod(4, data)
    _run_steps(mod_b, it_b, 0, 4)
    assert mod_b._zero and mod_b._fused_state is not None
    mod_b.remesh(parallel.MeshPlan(jax.devices()[:2]))
    assert mod_b._mesh_plan.dp == 2
    got = _run_steps(mod_b, it_b, 4, 4)
    assert mod_b._zero, "ZeRO must re-arm on the new plan"
    for k in ref:
        np.testing.assert_allclose(ref[k], got[k], rtol=2e-5, atol=1e-6,
                                   err_msg=k)
    # the re-scattered state really is dp'=2-sharded on device
    leaf = jax.tree_util.tree_leaves(mod_b._fused_state)[0]
    shard = leaf.sharding.shard_shape(tuple(leaf.shape))
    assert shard[0] * 2 == leaf.shape[0], (shard, leaf.shape)


def test_module_remesh_scale_back_up(monkeypatch):
    """dp=2 → dp'=4 (the regained-devices direction) re-scatters the
    other way and keeps training equivalent."""
    import jax

    from mxnet_tpu import parallel

    monkeypatch.setenv("MXNET_ZERO", "1")
    rng = np.random.RandomState(4)
    data = (rng.randn(16 * 6, 8).astype(np.float32),
            rng.randint(0, 4, 16 * 6).astype(np.float32))
    mod_a, it_a = _make_mesh_mod(2, data)
    _run_steps(mod_a, it_a, 0, 3)
    ref = _run_steps(mod_a, it_a, 3, 3)

    mod_b, it_b = _make_mesh_mod(2, data)
    _run_steps(mod_b, it_b, 0, 3)
    mod_b.remesh(parallel.MeshPlan(jax.devices()[:4]))
    got = _run_steps(mod_b, it_b, 3, 3)
    for k in ref:
        np.testing.assert_allclose(ref[k], got[k], rtol=2e-5, atol=1e-6,
                                   err_msg=k)


def test_module_remesh_refuses_update_on_kvstore(tmp_path):
    import jax

    from mxnet_tpu import parallel

    rng = np.random.RandomState(3)
    X = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, 16).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=8, label_name="softmax_label")
    mod = mx.mod.Module(_sym(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params(mx.initializer.Uniform(0.1))
    kv = mx.kv.create("local")
    mod.init_optimizer(kvstore=kv, optimizer="sgd")
    mod._update_on_kvstore = True
    with pytest.raises(MXNetError, match="DistKVStore.remesh"):
        mod.remesh(parallel.MeshPlan(jax.devices()[:2]))
