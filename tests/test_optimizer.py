"""Optimizer tests (modeled on tests/python/unittest/test_optimizer.py —
each optimizer compared against a numpy reference implementation)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt
from mxnet_tpu.test_utils import assert_almost_equal


def _run_steps(optimizer, w0, grads):
    w = mx.nd.array(w0.copy())
    state = optimizer.create_state(0, w)
    for g in grads:
        state = optimizer.update(0, w, mx.nd.array(g), state)
    return w.asnumpy()


def test_sgd_matches_numpy():
    rng = np.random.RandomState(0)
    w0 = rng.rand(4, 5).astype(np.float32)
    grads = [rng.rand(4, 5).astype(np.float32) for _ in range(5)]
    got = _run_steps(opt.create("sgd", learning_rate=0.1, momentum=0.9, wd=0.01,
                                rescale_grad=0.5), w0, grads)
    w = w0.copy()
    mom = np.zeros_like(w)
    for g in grads:
        g2 = g * 0.5 + 0.01 * w
        mom = 0.9 * mom - 0.1 * g2
        w = w + mom
    assert_almost_equal(got, w, rtol=1e-5)


def test_sgd_no_momentum_clip():
    w0 = np.ones((3,), np.float32)
    g = np.array([10.0, -10.0, 0.1], np.float32)
    got = _run_steps(opt.create("sgd", learning_rate=1.0, clip_gradient=1.0), w0, [g])
    assert_almost_equal(got, w0 - np.clip(g, -1, 1), rtol=1e-6)


def test_adam_matches_numpy():
    rng = np.random.RandomState(1)
    w0 = rng.rand(6).astype(np.float32)
    grads = [rng.rand(6).astype(np.float32) for _ in range(4)]
    got = _run_steps(opt.create("adam", learning_rate=0.01, beta1=0.9, beta2=0.999,
                                epsilon=1e-8), w0, grads)
    w = w0.copy().astype(np.float64)
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    for t, g in enumerate(grads, 1):
        g = g.astype(np.float64)
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        lr_t = 0.01 * np.sqrt(1 - 0.999 ** t) / (1 - 0.9 ** t)
        w -= lr_t * m / (np.sqrt(v) + 1e-8)
    assert_almost_equal(got, w, rtol=1e-4)


def test_adagrad_rmsprop_adadelta_run():
    rng = np.random.RandomState(2)
    w0 = rng.rand(8).astype(np.float32)
    grads = [rng.rand(8).astype(np.float32) for _ in range(3)]
    for name in ["adagrad", "rmsprop", "adadelta", "nag", "dcasgd", "test"]:
        got = _run_steps(opt.create(name), w0, grads)
        assert got.shape == w0.shape
        assert not np.allclose(got, w0), f"{name} did not update weights"
        assert np.isfinite(got).all()


def test_lr_scheduler():
    from mxnet_tpu.lr_scheduler import FactorScheduler, MultiFactorScheduler

    sched = FactorScheduler(step=10, factor=0.5)
    sched.base_lr = 1.0
    assert sched(1) == 1.0
    assert sched(11) == 0.5
    assert sched(21) == 0.25
    m = MultiFactorScheduler(step=[5, 15], factor=0.1)
    m.base_lr = 1.0
    assert m(1) == 1.0
    assert abs(m(6) - 0.1) < 1e-12
    assert abs(m(16) - 0.01) < 1e-12


def test_lr_wd_mult():
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("w", lr_mult=0.0)
    net = mx.sym.FullyConnected(data, weight=w, num_hidden=2, no_bias=True, name="fc")
    o = opt.create("sgd", learning_rate=1.0, sym=net,
                   param_idx2name={0: "w"})
    wt = mx.nd.ones((2, 3))
    state = o.create_state(0, wt)
    o.update(0, wt, mx.nd.ones((2, 3)), state)
    # lr_mult 0 → no change
    np.testing.assert_allclose(wt.asnumpy(), np.ones((2, 3)))


def test_updater_states_roundtrip():
    o = opt.create("sgd", learning_rate=0.1, momentum=0.9)
    upd = opt.get_updater(o)
    w = mx.nd.ones((4,))
    upd(0, mx.nd.ones((4,)), w)
    blob = upd.get_states()
    upd2 = opt.get_updater(opt.create("sgd", learning_rate=0.1, momentum=0.9))
    upd2.set_states(blob)
    w2 = mx.nd.array(w.asnumpy())
    upd(0, mx.nd.ones((4,)), w)
    upd2(0, mx.nd.ones((4,)), w2)
    np.testing.assert_allclose(w.asnumpy(), w2.asnumpy(), rtol=1e-6)
