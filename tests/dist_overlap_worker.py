"""Worker for the 2-process comm-overlap proof (test_dist.py::
test_comm_overlap_trace).  Launched with a SMALL
MXNET_KVSTORE_BUCKET_BYTES so a burst of pushes seals several buckets.

Three phases, all traced:

* explicit overlap proof — push K keys (enqueue-only: the async
  scheduler returns immediately), then run real host compute inside an
  ``overlap.compute`` span, then pull.  The merged trace must show
  ``kvstore.bucket`` spans (comm thread) running DURING the compute
  span — impossible on the old blocking path, where every allgather
  completed before push() returned;
* bf16 wire check — MXNET_KVSTORE_GRAD_DTYPE=bf16 for one push/pull:
  the compressed payload must still sum exactly (small integers are
  exact in bf16) across ranks;
* a tiny Module.fit over the same kvstore — ``fit.step`` spans with
  kvstore comm under them, and both ranks must end with identical
  weights (digest compared by the launching test).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx

K = 16
SHAPE = (256, 32)  # 32 KiB per key → several buckets at 64 KiB cap


def build_sym():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def main():
    trace_dir = sys.argv[1]
    mx.profiler.profiler_set_config(mode="all", filename="")
    mx.profiler.profiler_set_state("run")

    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    expected = float(sum(r + 1 for r in range(nw)))
    assert kv._comm is not None, "overlap scheduler must be active"

    # --- phase 1: async pushes overlap explicit compute --------------
    for i in range(K):
        kv.init(1000 + i, mx.nd.zeros(SHAPE))
    t0 = time.perf_counter()
    for i in range(K):
        kv.push(1000 + i, mx.nd.ones(SHAPE) * (rank + 1), priority=-i)
    t_push = time.perf_counter() - t0
    with mx.profiler.scope("overlap.compute", "exec"):
        # real host work — the window the bucket allgathers hide under
        a = np.random.rand(128, 128)
        t_end = time.perf_counter() + 1.0
        while time.perf_counter() < t_end:
            a = a @ a
            a /= np.abs(a).max() + 1e-9
    outs = [mx.nd.zeros(SHAPE) for _ in range(K)]
    for i in range(K):
        kv.pull(1000 + i, out=outs[i])
    for o in outs:
        np.testing.assert_allclose(o.asnumpy(), np.full(SHAPE, expected))
    # enqueue-only pushes return far faster than K blocking allgathers
    print(f"worker {rank}: push enqueue took {t_push * 1e3:.1f} ms",
          flush=True)

    # --- phase 2: bf16 wire with fp32 accumulation --------------------
    os.environ["MXNET_KVSTORE_GRAD_DTYPE"] = "bf16"
    try:
        kv.init(2000, mx.nd.zeros((32, 8)))
        kv.push(2000, mx.nd.ones((32, 8)) * (rank + 1))
        out = mx.nd.zeros((32, 8))
        kv.pull(2000, out=out)
        # small integers are exact in bf16 — the compressed sum is exact
        np.testing.assert_allclose(out.asnumpy(),
                                   np.full((32, 8), expected))
    finally:
        os.environ["MXNET_KVSTORE_GRAD_DTYPE"] = "fp32"

    # --- phase 3: Module.fit over the same kvstore --------------------
    rng = np.random.RandomState(5)  # same data on both ranks is fine
    X = rng.randn(64, 8).astype(np.float32)
    y = rng.randint(0, 4, size=64).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=8, shuffle=False,
                           label_name="softmax_label")
    mx.random.seed(7)
    mod = mx.mod.Module(build_sym(), context=mx.cpu())
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "rescale_grad": 1.0 / 8},
            kvstore=kv, initializer=mx.initializer.Xavier(),
            eval_metric="acc")
    args, _ = mod.get_params()
    digest = float(sum(np.abs(v.asnumpy()).sum() for v in args.values()))

    kv.barrier()
    path = mx.profiler.dump_rank_trace(trace_dir)
    assert os.path.isfile(path), path
    print(f"worker {rank}/{nw}: comm overlap OK digest={digest:.6f}",
          flush=True)


if __name__ == "__main__":
    main()
