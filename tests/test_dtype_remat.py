"""Low-precision training + gradient-mirroring + optimizer-op tests
(reference: tests/python/train/test_dtype.py; MXNET_BACKWARD_DO_MIRROR
graph_executor.cc:199-212; optimizer_op.cc)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _data(n=300, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 8).astype(np.float32)
    y = np.argmax(X @ rng.randn(8, 3), axis=1).astype(np.float32)
    return X, y


@pytest.mark.parametrize("dtype", ["float16", "bfloat16"])
def test_low_precision_training(dtype):
    """infer_type propagates the input dtype into every param and the
    model still converges (reference test_dtype.py fp16 check)."""
    import jax.numpy as jnp

    X, y = _data()
    sym = _mlp()
    arg_types, out_types, _ = sym.infer_type(data=dtype)
    named = dict(zip(sym.list_arguments(), arg_types))
    assert str(named["fc1_weight"]) == dtype
    assert str(named["fc2_bias"]) == dtype
    assert str(named["softmax_label"]) == "float32"

    exe = sym.simple_bind(mx.cpu(), grad_req="write",
                          type_dict={"data": dtype},
                          data=(20, 8), softmax_label=(20,))
    assert str(exe.arg_dict["fc1_weight"].dtype) == dtype

    mod = mx.mod.Module(sym, context=mx.cpu())
    it = mx.io.NDArrayIter(X.astype(dtype), y, batch_size=20)
    # the iterator's DataDesc carries the dtype; bind propagates it
    # into the parameters via infer_type
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    assert str(mod._exec.arg_dict["fc1_weight"].dtype) == dtype
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.2,
                                         "momentum": 0.9})
    for _ in range(8):
        it.reset()
        for b in it:
            mod.forward_backward(b)
            mod.update()
    assert str(mod._exec.arg_dict["fc1_weight"].dtype) == dtype
    score = mod.score(mx.io.NDArrayIter(X.astype(dtype), y, batch_size=20),
                      "acc")
    assert score[0][1] > 0.8, score


def test_backward_do_mirror_same_numerics():
    """Remat changes memory, not math: loss trajectory identical."""
    script = r"""
import os, sys
import numpy as np
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, %r)
import mxnet_tpu as mx
rng = np.random.RandomState(0)
X = rng.randn(100, 8).astype(np.float32)
y = np.argmax(X @ rng.randn(8, 3), axis=1).astype(np.float32)
data = mx.sym.Variable("data")
net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
net = mx.sym.Activation(net, act_type="relu")
net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
net = mx.sym.SoftmaxOutput(net, name="softmax")
it = mx.io.NDArrayIter(X, y, batch_size=20)
mod = mx.mod.Module(net, context=mx.cpu())
mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
         for_training=True)
mx.random.seed(3)
mod.init_params(mx.initializer.Xavier())
mod.init_optimizer(optimizer="sgd", optimizer_params={"learning_rate": 0.1})
for _ in range(3):
    it.reset()
    for b in it:
        mod.forward_backward(b); mod.update()
w = mod.get_params()[0]["fc1_weight"].asnumpy()
np.save(sys.argv[1], w)
"""
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        outs = []
        for mirror in ("0", "1"):
            out = os.path.join(d, f"w{mirror}.npy")
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       MXNET_BACKWARD_DO_MIRROR=mirror)
            r = subprocess.run([sys.executable, "-c", script % REPO, out],
                               capture_output=True, text=True, env=env,
                               timeout=300)
            assert r.returncode == 0, r.stderr
            outs.append(np.load(out))
        np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5, atol=1e-6)


def test_sgd_update_op():
    rng = np.random.RandomState(0)
    w = rng.randn(4, 5).astype(np.float32)
    g = rng.randn(4, 5).astype(np.float32)
    out = mx.nd.sgd_update(mx.nd.array(w), mx.nd.array(g), lr="0.1",
                           wd="0.01", rescale_grad="0.5").asnumpy()
    np.testing.assert_allclose(out, w - 0.1 * (0.5 * g + 0.01 * w),
                               rtol=1e-5)
    # clipping
    out = mx.nd.sgd_update(mx.nd.array(w), mx.nd.array(g * 100), lr="0.1",
                           clip_gradient="1.0").asnumpy()
    np.testing.assert_allclose(out, w - 0.1 * np.clip(g * 100, -1, 1),
                               rtol=1e-5)


def test_sgd_mom_update_op():
    rng = np.random.RandomState(1)
    w = rng.randn(6).astype(np.float32)
    g = rng.randn(6).astype(np.float32)
    mom = rng.randn(6).astype(np.float32)
    new_w, new_mom = mx.nd.sgd_mom_update(
        mx.nd.array(w), mx.nd.array(g), mx.nd.array(mom),
        lr="0.1", momentum="0.9")
    expect_mom = 0.9 * mom - 0.1 * g
    np.testing.assert_allclose(new_mom.asnumpy(), expect_mom, rtol=1e-5)
    np.testing.assert_allclose(new_w.asnumpy(), w + expect_mom, rtol=1e-5)


def test_adam_update_op():
    rng = np.random.RandomState(2)
    w = rng.randn(8).astype(np.float32)
    g = rng.randn(8).astype(np.float32)
    mean = np.zeros(8, np.float32)
    var = np.zeros(8, np.float32)
    new_w, new_mean, new_var = mx.nd.adam_update(
        mx.nd.array(w), mx.nd.array(g), mx.nd.array(mean), mx.nd.array(var),
        lr="0.01", beta1="0.9", beta2="0.999", epsilon="1e-8")
    em = 0.1 * g
    ev = 0.001 * g * g
    np.testing.assert_allclose(new_mean.asnumpy(), em, rtol=1e-5)
    np.testing.assert_allclose(new_var.asnumpy(), ev, rtol=1e-4)
    np.testing.assert_allclose(
        new_w.asnumpy(), w - 0.01 * em / (np.sqrt(ev) + 1e-8), rtol=1e-5)


def test_variable_dtype_pin():
    """Variable(dtype=...) pins propagate through infer_type."""
    data = mx.sym.Variable("data", dtype="float16")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    at, ot, _ = net.infer_type()
    named = dict(zip(net.list_arguments(), at))
    assert str(named["data"]) == "float16"
    assert str(named["fc_weight"]) == "float16"
    assert str(ot[0]) == "float16"


def test_adam_update_op_with_wd():
    """wd applies as decoupled decay, moments stay wd-free (reference
    optimizer_op-inl.h:160-176)."""
    rng = np.random.RandomState(3)
    w = rng.randn(5).astype(np.float32)
    g = rng.randn(5).astype(np.float32)
    mean = rng.randn(5).astype(np.float32) * 0.1
    var = np.abs(rng.randn(5)).astype(np.float32) * 0.1
    new_w, new_mean, new_var = mx.nd.adam_update(
        mx.nd.array(w), mx.nd.array(g), mx.nd.array(mean), mx.nd.array(var),
        lr="0.01", wd="0.01", beta1="0.9", beta2="0.999", epsilon="1e-8")
    em = 0.9 * mean + 0.1 * g
    ev = 0.999 * var + 0.001 * g * g
    np.testing.assert_allclose(new_mean.asnumpy(), em, rtol=1e-5)
    np.testing.assert_allclose(new_var.asnumpy(), ev, rtol=1e-4)
    np.testing.assert_allclose(
        new_w.asnumpy(),
        (1 - 0.01 * 0.01) * w - 0.01 * em / (np.sqrt(ev) + 1e-8), rtol=1e-5)


def test_infer_type_multi_branch():
    """A known output dtype flows back into untyped branches."""
    a = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                              name="fca")
    b = mx.sym.FullyConnected(mx.sym.Variable("side"), num_hidden=4,
                              name="fcb")
    out = a + b
    at, _, _ = out.infer_type(data="float16")
    named = dict(zip(out.list_arguments(), at))
    assert str(named["fcb_weight"]) == "float16", named
    assert str(named["side"]) == "float16", named
