"""Autoregressive serving tests: KV-cache decode correctness (the
bit-identity contract), paged block tables under fragmentation, the
Pallas gather kernel vs the lax fallback, and the continuous-batching
DecodeEngine (join/retire, preemption, admission, close-drain).

Fast variants run in tier-1; the long decode loops and wide
multi-stream sweeps are marked ``slow``.
"""

import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models, profiler
from mxnet_tpu.executor import build_graph_fn
from mxnet_tpu.kv_cache import (BlockAllocator, blocks_for_tokens,
                                bucket_ladder)
from mxnet_tpu.models.transformer import (transformer_lm_decode,
                                          transformer_lm_prefill)

V, KVB, L, H, DM, MAXLEN = 61, 4, 2, 2, 32, 32


@pytest.fixture(scope="module")
def lm():
    """Tiny trained-shape transformer: params + a greedy full-forward
    reference that goes through the TRAINING symbol (SoftmaxOutput
    head), so decode is checked against the genuine serving target."""
    import jax
    import jax.numpy as jnp

    sym = models.transformer_lm(V, MAXLEN, num_layers=L, num_heads=H,
                                d_model=DM, block_size=KVB)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, MAXLEN))],
             label_shapes=[("softmax_label", (2, MAXLEN))],
             for_training=False)
    mod.init_params(mx.initializer.Xavier(factor_type="in",
                                          magnitude=2.0))
    arg, aux = mod.get_params()
    params = {**arg, **aux}

    ps = transformer_lm_prefill(V, num_layers=L, num_heads=H,
                                d_model=DM, kv_block=KVB, paged=False)
    gfn = build_graph_fn(ps)
    base = {n: jnp.asarray(params[n].asnumpy())
            for n in ps.list_arguments() if n in params}
    key = jax.random.PRNGKey(0)

    def full_logits(seq):
        """Full-sequence causal forward at the natural length."""
        T = len(seq)
        a = dict(base)
        a.update(data=jnp.asarray(np.asarray(seq, np.int32)[None]),
                 positions=jnp.asarray(
                     np.arange(T, dtype=np.int32)[None]),
                 lengths=jnp.asarray(np.asarray([T], np.int32)))
        outs, _ = gfn(a, {}, key, False)
        return np.asarray(outs[0][0])  # (T, V)

    def naive_generate(prompt, n):
        seq = list(np.asarray(prompt))
        out = []
        for _ in range(n):
            out.append(int(np.argmax(full_logits(seq)[-1])))
            seq.append(out[-1])
        return np.asarray(out, np.int32)

    return params, full_logits, naive_generate


def _engine(params, **kw):
    args = dict(vocab_size=V, num_layers=L, num_heads=H, d_model=DM,
                max_len=MAXLEN, kv_block=KVB, max_streams=4,
                decode_buckets=[1, 2, 4], temperature=0.0)
    args.update(kw)
    return mx.DecodeEngine(params, **args)


# ---------------------------------------------------------------------------
# bit-identity of prefill + incremental decode vs the full forward
# ---------------------------------------------------------------------------


def test_prefill_decode_logits_bitwise_contiguous(lm):
    """Op-level contract: prefill + N contiguous decode steps produce
    logits BIT-IDENTICAL to the full-sequence causal forward row, at
    every step, across a cache-length bucket boundary (the cache here
    is padded to C > T like a bucketed executable would)."""
    import jax
    import jax.numpy as jnp

    params, full_logits, _ = lm
    ds = transformer_lm_decode(V, num_layers=L, num_heads=H,
                               d_model=DM, kv_block=KVB, paged=False)
    gfn = build_graph_fn(ds)
    base = {n: jnp.asarray(params[n].asnumpy())
            for n in ds.list_arguments() if n in params}
    key = jax.random.PRNGKey(0)

    rng = np.random.RandomState(0)
    seq = rng.randint(1, V, size=18).astype(np.int32)
    p0 = 5
    full = full_logits(seq)

    # prefill via the prefill symbol (contiguous: caches come back as
    # (B, T, H, D)); re-home them into a C=24-slot cache (crosses the
    # 8->16->24 block boundaries as decode proceeds)
    ps = transformer_lm_prefill(V, num_layers=L, num_heads=H,
                                d_model=DM, kv_block=KVB, paged=False)
    pgfn = build_graph_fn(ps)
    a = dict(base)
    a.update(data=jnp.asarray(seq[None, :p0]),
             positions=jnp.asarray(np.arange(p0, dtype=np.int32)[None]),
             lengths=jnp.asarray(np.asarray([p0], np.int32)))
    pouts, _ = pgfn(a, {}, key, False)
    np.testing.assert_array_equal(np.asarray(pouts[0][0]), full[:p0])

    C = 24
    caches = []
    for kv in pouts[1:]:
        c = np.zeros((1, C, H, DM // H), np.float32)
        c[:, :p0] = np.asarray(kv)
        caches.append(jnp.asarray(c))
    for t in range(p0, len(seq)):
        a = dict(base)
        a.update(data=jnp.asarray(seq[None, t:t + 1]),
                 positions=jnp.asarray(
                     np.asarray([[t]], np.int32)),
                 lengths=jnp.asarray(np.asarray([t + 1], np.int32)))
        for i in range(L):
            a[f"layer{i}_kcache"] = caches[2 * i]
            a[f"layer{i}_vcache"] = caches[2 * i + 1]
        outs, _ = gfn(a, {}, key, False)
        np.testing.assert_array_equal(
            np.asarray(outs[0][0, 0]), full[t],
            err_msg=f"decode step t={t} not bit-identical")
        caches = [jnp.asarray(x) for x in outs[1:]]


def test_paged_decode_bitwise_under_fragmentation(lm):
    """The paged path with a DELIBERATELY fragmented block table
    (pages interleaved/allocated out of order, stale data in freed
    pages) is bit-identical to the full forward."""
    import jax
    import jax.numpy as jnp

    params, full_logits, _ = lm
    ds = transformer_lm_decode(V, num_layers=L, num_heads=H,
                               d_model=DM, kv_block=KVB, paged=True)
    ps = transformer_lm_prefill(V, num_layers=L, num_heads=H,
                                d_model=DM, kv_block=KVB, paged=True)
    dfn, pfn = build_graph_fn(ds), build_graph_fn(ps)
    base = {n: jnp.asarray(params[n].asnumpy())
            for n in ds.list_arguments() if n in params}
    key = jax.random.PRNGKey(0)

    rng = np.random.RandomState(1)
    seq = rng.randint(1, V, size=15).astype(np.int32)
    p0 = 6
    full = full_logits(seq)

    P = 12
    # stale garbage in the pool: a previous tenant's values must not
    # leak through the masks (finite garbage — K/V are activations)
    pools = [jnp.asarray(rng.randn(P, KVB, H, DM // H)
                         .astype(np.float32)) for _ in range(2 * L)]
    # fragmented page order from interleaved alloc/free
    table = np.zeros((1, 4), np.int32)
    table[0] = [7, 2, 11, 5]
    a = dict(base)
    a.update(data=jnp.asarray(seq[None, :p0]),
             positions=jnp.asarray(np.arange(8, dtype=np.int32)[None]),
             lengths=jnp.asarray(np.asarray([p0], np.int32)),
             block_table=jnp.asarray(table[:, :2]))
    a["data"] = jnp.asarray(
        np.pad(seq[:p0], (0, 2))[None])  # prompt padded to bucket 8
    for i in range(L):
        a[f"layer{i}_kpool"] = pools[2 * i]
        a[f"layer{i}_vpool"] = pools[2 * i + 1]
    pouts, _ = pfn(a, {}, key, False)
    np.testing.assert_array_equal(np.asarray(pouts[0][0, :p0]),
                                  full[:p0])
    pools = [jnp.asarray(x) for x in pouts[1:]]
    for t in range(p0, len(seq)):
        a = dict(base)
        a.update(data=jnp.asarray(seq[None, t:t + 1]),
                 positions=jnp.asarray(np.asarray([[t]], np.int32)),
                 lengths=jnp.asarray(np.asarray([t + 1], np.int32)),
                 block_table=jnp.asarray(table))
        for i in range(L):
            a[f"layer{i}_kpool"] = pools[2 * i]
            a[f"layer{i}_vpool"] = pools[2 * i + 1]
        outs, _ = dfn(a, {}, key, False)
        np.testing.assert_array_equal(
            np.asarray(outs[0][0, 0]), full[t],
            err_msg=f"paged decode t={t} not bit-identical")
        pools = [jnp.asarray(x) for x in outs[1:]]


def test_paged_pallas_kernel_matches_lax(monkeypatch):
    """The gather-by-block-table Pallas kernel (interpret mode on CPU
    — the same kernel code path as TPU) matches the lax gather
    fallback at dtype tolerance."""
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_kernels as pk
    from mxnet_tpu.ops.attention import decode_attention

    rng = np.random.RandomState(3)
    B, nH, D, P, MB = 3, 2, 8, 10, 3
    q = rng.randn(B, 1, nH, D).astype(np.float32)
    kp = rng.randn(P, KVB, nH, D).astype(np.float32)
    vp = rng.randn(P, KVB, nH, D).astype(np.float32)
    table = np.array([[5, 2, 9], [1, 7, 3], [0, 0, 0]], np.int32)
    lengths = np.array([9, 5, 0], np.int32)

    monkeypatch.setenv("MXNET_PALLAS", "1")
    assert pk.enabled()
    out = np.asarray(pk.paged_attention_decode(
        jnp.asarray(q[:, 0]), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(table), jnp.asarray(lengths)))
    kg = kp[table].reshape(B, MB * KVB, nH, D)
    vg = vp[table].reshape(B, MB * KVB, nH, D)
    ref = np.asarray(decode_attention(
        jnp.asarray(q), jnp.asarray(kg), jnp.asarray(vg),
        jnp.asarray(lengths), KVB))[:, 0]
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)
    # a fully-masked (inactive) stream produces zeros, not NaN
    assert np.all(np.isfinite(out))
    np.testing.assert_array_equal(out[2], np.zeros_like(out[2]))


def test_paged_op_pallas_vs_lax_path(monkeypatch, lm):
    """QKVPagedAttentionDecode end to end: the kernel path equals the
    lax path at tolerance on identical pools/tables."""
    import jax.numpy as jnp

    from mxnet_tpu.ops.registry import invoke

    rng = np.random.RandomState(4)
    B, nH, D, P = 2, 2, 8, 8
    qkv = rng.randn(B, 1, 3 * nH * D).astype(np.float32)
    kp = rng.randn(P, KVB, nH, D).astype(np.float32)
    vp = rng.randn(P, KVB, nH, D).astype(np.float32)
    table = np.array([[3, 6], [1, 4]], np.int32)
    lengths = np.array([6, 3], np.int32)
    ins = [jnp.asarray(x) for x in (qkv, kp, vp, table, lengths)]

    monkeypatch.setenv("MXNET_PALLAS", "0")
    (o_lax, k_lax, v_lax), _ = invoke("QKVPagedAttentionDecode", ins,
                                      {"num_heads": nH})
    monkeypatch.setenv("MXNET_PALLAS", "1")
    (o_pal, k_pal, v_pal), _ = invoke("QKVPagedAttentionDecode", ins,
                                      {"num_heads": nH})
    np.testing.assert_allclose(np.asarray(o_pal), np.asarray(o_lax),
                               rtol=1e-6, atol=1e-6)
    # the cache write is the same scatter on both paths
    np.testing.assert_array_equal(np.asarray(k_pal), np.asarray(k_lax))
    np.testing.assert_array_equal(np.asarray(v_pal), np.asarray(v_lax))


# ---------------------------------------------------------------------------
# block allocator
# ---------------------------------------------------------------------------


def test_block_allocator_alloc_free_fragmentation():
    a = BlockAllocator(9, 4)  # 1 scratch + 8 usable
    assert a.capacity == 8 and a.free_blocks == 8
    x = a.alloc(3, owner="x")
    y = a.alloc(2, owner="y")
    assert len(set(x) | set(y)) == 5 and 0 not in x + y
    assert a.used_blocks == 5
    a.free(x)  # interleaved free fragments the id space
    with pytest.raises(mx.MXNetError, match="double free|foreign"):
        a.free([x[0]])
    z = a.alloc(4, owner="z")
    assert z is not None and 0 not in z
    assert set(z).isdisjoint(y)
    # all-or-nothing: 3 left, asking 4 takes nothing
    assert a.alloc(4) is None
    assert a.free_blocks == 2
    assert a.alloc(2) is not None
    assert a.utilization() == 1.0
    with pytest.raises(mx.MXNetError, match="scratch"):
        a.free([0])
    with pytest.raises(mx.MXNetError, match=">= 2"):
        BlockAllocator(1, 4)


def test_blocks_for_tokens_and_ladder():
    assert blocks_for_tokens(1, 4) == 1
    assert blocks_for_tokens(4, 4) == 1
    assert blocks_for_tokens(5, 4) == 2
    assert bucket_ladder(8) == [1, 2, 4, 8]
    assert bucket_ladder(6) == [1, 2, 4, 6]
    assert bucket_ladder(1) == [1]


# ---------------------------------------------------------------------------
# DecodeEngine: the tier-1 smoke (4-token decode on the tiny model)
# ---------------------------------------------------------------------------


def test_engine_smoke_greedy_decode(lm):
    """4-token greedy decode on a tiny model equals the full-forward
    argmax chain — the tier-1-visible variant of the slow loops."""
    params, _, naive_generate = lm
    prompt = np.array([3, 17, 42, 5, 9], np.int32)
    with _engine(params) as eng:
        got = eng.generate(prompt, 4)
        st = eng.stats()
    np.testing.assert_array_equal(got, naive_generate(prompt, 4))
    assert st["generations"] == 1 and st["tokens"] == 4
    assert st["prefill_tokens"] == 5


def test_engine_admission_and_cache_accounting(lm):
    params, _, _ = lm
    with _engine(params, cache_blocks=33) as eng:
        f = eng.submit(np.arange(1, 6, dtype=np.int32), 3)
        f.result(timeout=120)
        st = eng.stats()
        # everything retired: all pages back in the pool
        assert st["cache_util"] == 0.0
        assert st["cache_blocks_free"] == 32
        assert st["preempted"] == 0


def test_engine_submit_validation(lm):
    params, _, _ = lm
    with _engine(params) as eng:
        with pytest.raises(mx.MXNetError, match="non-empty 1-D"):
            eng.submit(np.zeros((2, 3), np.int32), 4)
        with pytest.raises(mx.MXNetError, match="max_len"):
            eng.submit(np.arange(30, dtype=np.int32), 10)
        with pytest.raises(mx.MXNetError, match="max_new_tokens"):
            eng.submit(np.arange(3, dtype=np.int32), 0)
    with pytest.raises(mx.EngineClosedError):
        eng.submit(np.arange(3, dtype=np.int32), 2)


def test_engine_eos_stops_early(lm):
    """Greedy chains revisit tokens; use the first generated token as
    eos so generation must stop right after producing it again."""
    params, _, naive_generate = lm
    prompt = np.array([3, 17, 42, 5, 9], np.int32)
    ref = naive_generate(prompt, 6)
    eos = int(ref[2])
    with _engine(params) as eng:
        got = eng.generate(prompt, 6, eos_id=eos)
    stop = int(np.argmax(ref == eos)) + 1
    np.testing.assert_array_equal(got, ref[:stop])
    assert got[-1] == eos


def test_engine_close_fails_inflight_with_named_error(lm):
    """The drain test: close() during an in-flight decode fails the
    outstanding futures with EngineClosedError at wait — never a
    hang."""
    params, _, _ = lm
    eng = _engine(params)
    futs = [eng.submit(np.arange(1, 5, dtype=np.int32), 25)
            for _ in range(3)]
    time.sleep(0.05)  # let the scheduler pick them up
    t0 = time.perf_counter()
    eng.close(timeout=60)
    assert time.perf_counter() - t0 < 60
    for f in futs:
        with pytest.raises(mx.EngineClosedError, match="closed"):
            f.result(timeout=10)


def test_inference_engine_batch_loop_death_poisons_futures():
    """InferenceEngine: a dying batch loop fails queued futures with
    the named error instead of stranding them (failure poisoning
    raises at wait instead of hanging)."""
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                                name="fc")
    mod = mx.mod.Module(net, context=mx.cpu(), label_names=[])
    mod.bind(data_shapes=[("data", (2, 6))], for_training=False)
    mod.init_params(mx.initializer.Xavier())
    arg, aux = mod.get_params()
    pred = mx.Predictor(net, {**arg, **aux}, {"data": (1, 6)})
    eng = mx.InferenceEngine(pred, buckets=(4,), batch_timeout_ms=1.0)
    try:
        # sabotage the coalescing loop itself (outside _dispatch's
        # per-batch try/except): `t_first + None` raises TypeError
        eng._timeout_s = eng._idle_timeout_s = None
        fut = eng.submit(np.zeros((1, 6), np.float32))
        with pytest.raises(mx.EngineClosedError, match="died"):
            fut.result(timeout=30)
    finally:
        eng._queue.put(None)  # loop is dead; unblock close's join
        eng.close(timeout=5)


# ---------------------------------------------------------------------------
# env-var validation (MXNET_CKPT_* convention: garbage raises loudly)
# ---------------------------------------------------------------------------


def test_env_validation_garbage_raises(monkeypatch, lm):
    params, _, _ = lm
    monkeypatch.setenv("MXNET_SERVING_KV_BLOCK", "banana")
    with pytest.raises(mx.MXNetError, match="MXNET_SERVING_KV_BLOCK"):
        mx.DecodeEngine(params, vocab_size=V, num_layers=L,
                        num_heads=H, d_model=DM, max_len=MAXLEN)
    monkeypatch.setenv("MXNET_SERVING_KV_BLOCK", "-4")
    with pytest.raises(mx.MXNetError, match="MXNET_SERVING_KV_BLOCK"):
        mx.DecodeEngine(params, vocab_size=V, num_layers=L,
                        num_heads=H, d_model=DM, max_len=MAXLEN)
    monkeypatch.delenv("MXNET_SERVING_KV_BLOCK")
    monkeypatch.setenv("MXNET_SERVING_MAX_STREAMS", "0")
    with pytest.raises(mx.MXNetError,
                       match="MXNET_SERVING_MAX_STREAMS"):
        mx.DecodeEngine(params, vocab_size=V, num_layers=L,
                        num_heads=H, d_model=DM, max_len=MAXLEN)
    monkeypatch.delenv("MXNET_SERVING_MAX_STREAMS")
    monkeypatch.setenv("MXNET_SERVING_DECODE_BUCKETS", "4,2,1")
    with pytest.raises(mx.MXNetError, match="increasing"):
        mx.DecodeEngine(params, vocab_size=V, num_layers=L,
                        num_heads=H, d_model=DM, max_len=MAXLEN)
    monkeypatch.setenv("MXNET_SERVING_DECODE_BUCKETS", "1,zebra")
    with pytest.raises(mx.MXNetError, match="comma-separated"):
        mx.DecodeEngine(params, vocab_size=V, num_layers=L,
                        num_heads=H, d_model=DM, max_len=MAXLEN)
    monkeypatch.delenv("MXNET_SERVING_DECODE_BUCKETS")
    monkeypatch.setenv("MXNET_SERVING_PREFILL_BUCKETS", "3,7")
    with pytest.raises(mx.MXNetError, match="multiple of"):
        mx.DecodeEngine(params, vocab_size=V, num_layers=L,
                        num_heads=H, d_model=DM, max_len=MAXLEN)
    # registered in the config catalog
    for name in ("MXNET_SERVING_KV_BLOCK", "MXNET_SERVING_MAX_STREAMS",
                 "MXNET_SERVING_DECODE_BUCKETS",
                 "MXNET_SERVING_CACHE_BUCKETS",
                 "MXNET_SERVING_PREFILL_BUCKETS"):
        assert mx.config.describe(name).name == name


def test_ladder_coverage_validated_at_construction(lm):
    """A ladder that doesn't cover the configured maxima would kill the
    serving loop mid-flight (a _bucket miss poisons every outstanding
    future) — it must raise at construction instead.  Explicit
    prefill_buckets get the same strictly-increasing check as the
    other ladders."""
    params, _, _ = lm
    with pytest.raises(mx.MXNetError, match="does not cover"):
        _engine(params, max_streams=8, decode_buckets=[1, 2, 4])
    with pytest.raises(mx.MXNetError, match="does not cover"):
        _engine(params, cache_buckets=[1, 2])  # MAXLEN/KVB = 8 pages
    with pytest.raises(mx.MXNetError, match="bad prefill_buckets"):
        _engine(params, prefill_buckets=[16, 8])


def test_reset_stats_isolates_measurement_points(lm):
    """bench_serving sweeps one engine across load points; reset_stats
    must zero counters AND histogram reservoirs so a point's
    percentiles don't blend earlier points' samples."""
    params, _, _ = lm
    with _engine(params) as eng:
        eng.generate(np.arange(1, 5, dtype=np.int32), 4)
        st = eng.stats()
        assert st["tokens"] >= 4 and st["p50_ms"] is not None
        eng.reset_stats()
        st = eng.stats()
        assert st["tokens"] == 0 and st["p50_ms"] is None


# ---------------------------------------------------------------------------
# continuous batching: join/retire and preemption (slow variants)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_multi_stream_join_retire_outputs_unchanged(lm):
    """Streams joining and retiring mid-loop (staggered submits,
    different lengths) leave every stream's output identical to its
    single-stream generation."""
    params, _, naive_generate = lm
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, V, size=n).astype(np.int32)
               for n in (5, 3, 7, 1, 6, 4)]
    lens = [12, 5, 9, 17, 2, 8]
    with _engine(params, max_streams=4) as eng:
        futs = []
        for i, (p, n) in enumerate(zip(prompts, lens)):
            futs.append(eng.submit(p, n))
            if i == 2:
                time.sleep(0.1)  # stagger: join mid-loop
        outs = [f.result(timeout=300) for f in futs]
        st = eng.stats()
    for p, n, o in zip(prompts, lens, outs):
        np.testing.assert_array_equal(o, naive_generate(p, n))
    assert st["generations"] == len(prompts)
    # continuous batching actually batched: fewer steps than tokens
    assert st["steps"] < st["tokens"]


@pytest.mark.slow
def test_preemption_recompute_outputs_unchanged(lm):
    """A pool too small for all streams forces preemption; preempted
    streams re-prefill their progress and still produce exactly their
    single-stream outputs."""
    params, _, naive_generate = lm
    prompts = [np.arange(1, 6, dtype=np.int32),
               np.arange(7, 12, dtype=np.int32),
               np.arange(13, 18, dtype=np.int32)]
    with _engine(params, max_streams=3, cache_blocks=10) as eng:
        futs = [eng.submit(p, 14) for p in prompts]
        outs = [f.result(timeout=300) for f in futs]
        st = eng.stats()
    assert st["preempted"] > 0
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(o, naive_generate(p, 14))


@pytest.mark.slow
def test_temperature_sampling_reproducible_across_batching(lm):
    """Per-stream PRNG keys are (engine seed, stream id, position):
    the same request sampled alone and sampled inside a busy batch
    yields the same tokens."""
    params, _, _ = lm
    prompt = np.array([3, 17, 42], np.int32)
    with _engine(params, seed=11) as eng:
        alone = eng.generate(prompt, 8, temperature=0.8)
    with _engine(params, seed=11) as eng:
        futs = [eng.submit(prompt, 8, temperature=0.8),
                eng.submit(np.array([9, 9], np.int32), 8,
                           temperature=0.5)]
        batched = futs[0].result(timeout=300)
    np.testing.assert_array_equal(alone, batched)


@pytest.mark.slow
def test_long_decode_loop_across_cache_buckets(lm):
    """A generation long enough to cross several cache-length buckets
    (block-table growth mid-stream) stays bit-exact."""
    params, _, naive_generate = lm
    prompt = np.array([2, 4], np.int32)
    n = 28  # 30 tokens total = 8 blocks: crosses 1->2->4->8 buckets
    with _engine(params, cache_buckets=[1, 2, 4, 8]) as eng:
        got = eng.generate(prompt, n)
    np.testing.assert_array_equal(got, naive_generate(prompt, n))


def test_capacity_edge_request_admits(lm):
    """A request whose lifetime page need is EXACTLY the pool capacity
    must still be served — admission's +1 decode headroom is capped at
    the lifetime need (review finding: it used to hold the FIFO line
    forever while the scheduler spun)."""
    params, _, naive_generate = lm
    # capacity 4 pages = 16 tokens; 15-token prompt + 1 token fills it
    prompt = np.arange(1, 16, dtype=np.int32)
    with _engine(params, cache_blocks=5, max_streams=1) as eng:
        out = eng.submit(prompt, 1).result(timeout=120)
    np.testing.assert_array_equal(out, naive_generate(prompt, 1))


def test_prefill_failure_fails_the_admitted_future(lm):
    """A stream popped from pending whose prefill dies must get the
    poison error like everyone else, not hang (review finding: it was
    invisible to _fail_outstanding between pop and activation)."""
    params, _, _ = lm
    eng = _engine(params)
    try:
        def boom(tp):
            raise RuntimeError("injected prefill failure")

        eng._prefill_exe = boom
        fut = eng.submit(np.arange(1, 5, dtype=np.int32), 4)
        with pytest.raises(mx.EngineClosedError, match="died"):
            fut.result(timeout=60)
        # the dead loop also shut the door: a later submit raises
        # instead of queueing work nothing will ever process
        with pytest.raises(mx.EngineClosedError):
            eng.submit(np.arange(1, 5, dtype=np.int32), 4)
    finally:
        eng.close(timeout=10)


def test_multi_token_decode_qkv_rejected():
    """Both decode ops refuse a multi-token qkv instead of silently
    attending only the first token."""
    import jax.numpy as jnp

    from mxnet_tpu.ops.registry import invoke

    rng = np.random.RandomState(9)
    nH, D = 2, 8
    qkv2 = jnp.asarray(rng.randn(1, 2, 3 * nH * D).astype(np.float32))
    kp = jnp.zeros((4, KVB, nH, D))
    table = jnp.zeros((1, 2), jnp.int32)
    lengths = jnp.asarray([3], jnp.int32)
    with pytest.raises(mx.MXNetError, match="ONE query position"):
        invoke("QKVPagedAttentionDecode", [qkv2, kp, kp, table, lengths],
               {"num_heads": nH})
    ck = jnp.zeros((1, 8, nH, D))
    with pytest.raises(mx.MXNetError, match="ONE query position"):
        invoke("QKVSelfAttentionDecode", [qkv2, ck, ck, lengths],
               {"num_heads": nH})


def test_decode_telemetry_surfaces(lm):
    profiler.reset_metrics()
    params, _, _ = lm
    with _engine(params) as eng:
        eng.generate(np.arange(1, 5, dtype=np.int32), 4)
    summ = profiler.metrics_summary()
    assert summ["counters"]["serving.tokens"] >= 4
    assert summ["counters"]["serving.prefills"] >= 1
    assert "serving.time_per_token_ms" in summ["histograms"]
    assert "serving.cache_util" in summ["gauges"]
    assert "serving.active_streams" in summ["gauges"]


# ---------------------------------------------------------------------------
# fleet hooks: inflight snapshot, drain/resume, seed override, swap
# ---------------------------------------------------------------------------


def test_drain_path_inflight_matches_poisoned_count(lm):
    """The router's view of what died with an engine: inflight() BEFORE
    the close equals the number of futures poisoned with
    EngineClosedError, and the count falls to 0 once they are failed
    (no phantom ownership after the drain)."""
    params, _, _ = lm
    eng = _engine(params)
    futs = [eng.submit(np.arange(1, 5, dtype=np.int32), 25)
            for _ in range(3)]
    time.sleep(0.05)
    n_before = eng.inflight()
    assert n_before == 3
    eng.close(timeout=60)
    poisoned = 0
    for f in futs:
        with pytest.raises(mx.EngineClosedError):
            f.result(timeout=10)
        poisoned += 1
    assert poisoned == n_before
    assert eng.inflight() == 0


def test_decode_drain_resume_and_inflight(lm):
    params, _, _ = lm
    eng = _engine(params)
    try:
        assert eng.inflight() == 0
        futs = [eng.submit(np.arange(1, 5, dtype=np.int32), 6)
                for _ in range(2)]
        left = eng.drain(timeout=120)
        assert left == 0 and eng.inflight() == 0
        for f in futs:
            assert f.result(10).shape == (6,)  # drained, not dropped
        with pytest.raises(mx.EngineClosedError, match="draining"):
            eng.submit(np.arange(1, 5, dtype=np.int32), 4)
        eng.resume()
        out = eng.submit(np.arange(1, 5, dtype=np.int32), 4).result(60)
        assert out.shape == (4,)
    finally:
        eng.close(timeout=30)


def test_submit_seed_override_reproduces_across_engines(lm):
    """Fleet retry determinism: the same (prompt, seed) sampled at
    temperature > 0 yields identical tokens on a DIFFERENT engine with
    different stream-id history — the property that lets a survivor
    re-generate a dead replica's request bit-exactly."""
    params, _, _ = lm
    p = np.arange(1, 5, dtype=np.int32)
    e1 = _engine(params)
    try:
        a = e1.submit(p, 6, temperature=0.7, seed=123).result(120)
    finally:
        e1.close(timeout=30)
    e2 = _engine(params)
    try:
        e2.submit(p, 3).result(120)  # shift e2's stream-id history
        b = e2.submit(p, 6, temperature=0.7, seed=123).result(120)
        c = e2.submit(p, 6, temperature=0.7, seed=124).result(120)
    finally:
        e2.close(timeout=30)
    assert np.array_equal(a, b)
    assert not np.array_equal(b, c)  # the seed really keys sampling


def test_decode_swap_params_identity_and_validation(lm):
    """swap_params installs new weights without recompiling (params
    are runtime args): identical weights → identical generation;
    missing/mis-shaped params refuse loudly."""
    params, _, naive = lm
    eng = _engine(params)
    try:
        p = np.arange(1, 6, dtype=np.int32)
        before = eng.submit(p, 5).result(120)
        # warm the prefix-hit path too (a repeated prompt lazily
        # compiles the suffix-prefill bucket on its first hit — that
        # compile belongs to the hit, not to the swap under test)
        assert np.array_equal(eng.submit(p, 5).result(120), before)
        eng.swap_params(params)  # same weights, full round-trip
        compiles_before = dict(eng.compiles)
        after = eng.submit(p, 5).result(120)
        assert np.array_equal(before, after)
        assert dict(eng.compiles) == compiles_before  # no recompile
        name = eng._param_names[0]
        with pytest.raises(mx.MXNetError, match="missing"):
            eng.swap_params({name: params[name]})
        bad = {k: v for k, v in params.items()}
        bad[name] = np.zeros((3, 3), np.float32)
        with pytest.raises(mx.MXNetError, match="shape"):
            eng.swap_params(bad)
        # the failed swaps never installed anything
        assert np.array_equal(eng.submit(p, 5).result(120), before)
    finally:
        eng.close(timeout=30)
