"""Transformer LM model family (models/transformer.py) — the
long-context flagship built on DotProductAttention/LayerNorm/GELU."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models


def _markov_batches(vocab, B, T, n_batches, seed=0):
    rng = np.random.RandomState(seed)
    trans = rng.randint(1, vocab, size=(vocab, 2))
    out = []
    for _ in range(n_batches):
        toks = np.empty((B, T + 1), np.int64)
        toks[:, 0] = rng.randint(1, vocab, size=B)
        for t in range(T):
            toks[:, t + 1] = trans[toks[:, t], rng.randint(0, 2, size=B)]
        out.append((toks[:, :T].astype(np.float32),
                    toks[:, 1:].astype(np.float32)))
    return out


def _ppl(probs, labels):
    p = np.asarray(probs, np.float32).reshape(-1, probs.shape[-1])
    lab = np.asarray(labels, np.int64).reshape(-1)
    picked = p[np.arange(len(lab)), lab]
    return float(np.exp(-np.log(np.maximum(picked, 1e-12)).mean()))


def _build(vocab=64, T=16, B=8, layers=2, heads=2, d=32, causal=True):
    sym = models.transformer_lm(vocab_size=vocab, seq_len=T,
                                num_layers=layers, num_heads=heads,
                                d_model=d, causal=causal)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[mx.io.DataDesc("data", (B, T))],
             label_shapes=[mx.io.DataDesc("softmax_label", (B, T))],
             for_training=True)
    mx.random.seed(0)
    mod.init_params(mx.initializer.Xavier(rnd_type="gaussian"))
    return mod


def test_transformer_lm_trains():
    """Perplexity falls on a Markov corpus (the LM learns the
    transition structure)."""
    vocab, B, T = 64, 8, 16
    mod = _build(vocab=vocab, T=T, B=B)
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.01})
    batches = _markov_batches(vocab, B, T, 4)
    first = None
    for epoch in range(30):
        for X, Y in batches:
            mod.forward_backward(mx.io.DataBatch([mx.nd.array(X)],
                                                 [mx.nd.array(Y)]))
            mod.update()
        if first is None:
            first = _ppl(mod.get_outputs()[0].asnumpy(), batches[-1][1])
    last = _ppl(mod.get_outputs()[0].asnumpy(), batches[-1][1])
    assert last < first / 3, (first, last)


def test_transformer_lm_causal():
    """Causal masking: perturbing future tokens must not change the
    distribution at earlier positions."""
    vocab, B, T = 64, 2, 16
    mod = _build(vocab=vocab, T=T, B=B)
    rng = np.random.RandomState(1)
    X = rng.randint(1, vocab, (B, T)).astype(np.float32)
    Y = np.zeros((B, T), np.float32)

    def fwd(x):
        mod.forward(mx.io.DataBatch([mx.nd.array(x)], [mx.nd.array(Y)]),
                    is_train=False)
        return mod.get_outputs()[0].asnumpy()

    base = fwd(X)
    cut = 7
    X2 = X.copy()
    X2[:, cut + 1:] = rng.randint(1, vocab, (B, T - cut - 1))
    pert = fwd(X2)
    np.testing.assert_allclose(pert[:, :cut + 1], base[:, :cut + 1],
                               rtol=1e-4, atol=1e-5)
    # and the non-causal variant DOES change (sanity that the test bites)
    mod_nc = _build(vocab=vocab, T=T, B=B, causal=False)

    def fwd_nc(m, x):
        m.forward(mx.io.DataBatch([mx.nd.array(x)], [mx.nd.array(Y)]),
                  is_train=False)
        return m.get_outputs()[0].asnumpy()

    b0 = fwd_nc(mod_nc, X)
    b1 = fwd_nc(mod_nc, X2)
    assert np.abs(b1[:, :cut + 1] - b0[:, :cut + 1]).max() > 1e-6


def test_fc_flatten_false_nd():
    """flatten=False FullyConnected contracts only the last dim and the
    inferred weight/out shapes agree with the computation."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=5, flatten=False,
                                name="fc")
    ex = net.simple_bind(ctx=mx.cpu(), data=(2, 3, 4))
    assert ex.arg_dict["fc_weight"].shape == (5, 4)
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 4).astype(np.float32)
    w = rng.randn(5, 4).astype(np.float32)
    b = rng.randn(5).astype(np.float32)
    ex.arg_dict["data"][:] = x
    ex.arg_dict["fc_weight"][:] = w
    ex.arg_dict["fc_bias"][:] = b
    out = ex.forward(is_train=False)[0].asnumpy()
    assert out.shape == (2, 3, 5)
    np.testing.assert_allclose(out, x @ w.T + b, rtol=1e-5)


def test_layer_norm_matches_numpy():
    x = np.random.RandomState(0).randn(3, 4, 8).astype(np.float32)
    g = np.random.RandomState(1).rand(8).astype(np.float32) + 0.5
    b = np.random.RandomState(2).randn(8).astype(np.float32)
    out = mx.nd.LayerNorm(mx.nd.array(x), mx.nd.array(g), mx.nd.array(b),
                          eps=1e-5).asnumpy()
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    want = (x - mu) / np.sqrt(var + 1e-5) * g + b
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_gelu_activation():
    from scipy.special import erf as _erf  # scipy ships in the image
    x = np.linspace(-3, 3, 11).astype(np.float32)
    out = mx.nd.Activation(mx.nd.array(x), act_type="gelu").asnumpy()
    want = x * 0.5 * (1 + _erf(x / np.sqrt(2)))
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)
