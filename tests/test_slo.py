"""SLO engine tests: burn-rate math against hand-computed windows,
the alert-before-conviction contract under injected latency
(``MXNET_CHAOS_SLOW_RANK``), canary exclusion from the request
counters, EXACT per-request cost-record conservation against the
engine counters across a mixed prefix-hit/speculative/chunked run,
and a perf_sentinel smoke (identical runs pass, a doctored 2x-worse
run fails naming the metric).
"""

import importlib.util
import json
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import chaos, models, profiler, slo
from mxnet_tpu.elastic import dead_rank_timeout

V, KVB, L, H, DM, MAXLEN = 61, 4, 2, 2, 32, 32

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(**kw):
    """Explicit SloConfig (no env): budget = 1 - 0.8 = 0.2."""
    args = dict(ttft_ms={"interactive": 100.0, "batch": 1000.0},
                tpt_ms={"interactive": 10.0, "batch": 100.0},
                objective=0.8, fast_window_s=60.0,
                slow_window_s=600.0, burn_alert=4.0, min_events=5)
    args.update(kw)
    return slo.SloConfig(**args)


@pytest.fixture(autouse=True)
def _fresh_slo_state():
    """Every test gets a fresh process-wide tracker + metrics slate
    (the tracker is built from the env at first use)."""
    profiler.reset_metrics()
    slo.reset_tracker()
    chaos.reset_chaos()
    yield
    profiler.reset_metrics()
    slo.reset_tracker()
    chaos.reset_chaos()


# ---------------------------------------------------------------------------
# burn-rate math vs hand-computed windows
# ---------------------------------------------------------------------------


def test_burn_rate_hand_computed_windows():
    """10 TTFT events, 1 bad, budget 0.2: fast burn = (1/10)/0.2 =
    0.5 and slow-window budget_remaining = 1 - 0.5 = 0.5 — checked
    with explicit timestamps, no wall clock involved."""
    tr = slo.SloTracker(_cfg(), source="test")
    t0 = 1000.0
    for i in range(10):
        ms = 150.0 if i == 0 else 50.0  # 1 bad of 10 vs 100ms target
        tr.observe_ttft("interactive", ms, now=t0 + i * 0.01)
    now = t0 + 1.0
    assert tr.burn_rate("interactive", "ttft", "fast",
                        now=now) == pytest.approx(0.5)
    assert tr.burn_rate("interactive", "ttft", "slow",
                        now=now) == pytest.approx(0.5)
    assert tr.budget_remaining("interactive", "ttft",
                               now=now) == pytest.approx(0.5)
    # untouched objective: zero burn, full budget
    assert tr.burn_rate("batch", "ttft", now=now) == 0.0
    assert tr.budget_remaining("batch", "ttft", now=now) == 1.0

    # the fast window forgets first: at t0+61 every event has left
    # the 60s fast window but all still sit in the 600s slow window
    late = t0 + 61.0
    assert tr.burn_rate("interactive", "ttft", "fast", now=late) == 0.0
    assert tr.burn_rate("interactive", "ttft", "slow",
                        now=late) == pytest.approx(0.5)
    # ... and at t0+601 the slow window is empty too: full budget
    assert tr.budget_remaining("interactive", "ttft",
                               now=t0 + 601.0) == 1.0


def test_burn_rate_availability_objective():
    """Availability rides the same windows: 2 failed deliveries of 8
    → bad fraction 0.25, burn 1.25 against the 0.2 budget."""
    tr = slo.SloTracker(_cfg(), source="test")
    t0 = 5000.0
    for i in range(8):
        tr.observe_avail("interactive", ok=i >= 2, now=t0 + i * 0.01)
    assert tr.burn_rate("interactive", "avail",
                        now=t0 + 1) == pytest.approx(1.25)


def test_alert_fires_once_with_hysteresis_and_rearms():
    """5 bad TTFTs (burn 5.0 >= alert 4.0, min_events met) fire ONE
    typed alert; it clears only under half the threshold and re-arms
    after the window forgets."""
    tr = slo.SloTracker(_cfg(), source="test")
    # anchored at the real clock: stats() prunes with perf_counter()
    t0 = time.perf_counter()
    for i in range(5):
        tr.observe_ttft("interactive", 500.0, now=t0 + i * 0.01)
    fired = tr.check(now=t0 + 1.0)
    assert len(fired) == 1
    a = fired[0]
    assert (a.slo_class, a.metric, a.window) == ("interactive",
                                                 "ttft", "fast")
    assert a.burn_rate == pytest.approx(5.0)  # bad_frac 1.0 / 0.2
    assert a.threshold == 4.0
    assert "interactive/ttft" in a.message
    assert tr.alert_active()
    # no flap: a second check does not re-fire
    assert tr.check(now=t0 + 1.1) == []
    # exported judgment surface: gauges + counter + statusz section
    summ = profiler.metrics_summary()
    assert summ["counters"]["slo.alerts"] == 1
    assert summ["gauges"]["slo.alerts_active"] == 1
    st = tr.stats()
    assert st["worst"]["class"] == "interactive"
    assert st["worst"]["metric"] == "ttft"
    assert st["alerts_active"] and st["alerts_recent"]
    assert st["classes"]["interactive"]["ttft"]["fast_burn"] \
        == pytest.approx(5.0)
    # hysteresis: 10 good events → burn 5/15/0.2 ≈ 1.67 < 4/2 → clear
    for i in range(10):
        tr.observe_ttft("interactive", 10.0, now=t0 + 2 + i * 0.01)
    tr.check(now=t0 + 3.0)
    assert not tr.alert_active()
    # re-arm: after the fast window forgets, a fresh burst re-fires
    t1 = t0 + 120.0
    for i in range(5):
        tr.observe_ttft("interactive", 500.0, now=t1 + i * 0.01)
    assert len(tr.check(now=t1 + 1.0)) == 1
    assert len(tr.alerts) == 2


def test_alert_min_events_gate():
    """4 bad events with min_events=5: burn 5.0 but NO alert — a
    tiny sample must not page anyone."""
    tr = slo.SloTracker(_cfg(), source="test")
    t0 = 3000.0
    for i in range(4):
        tr.observe_ttft("interactive", 500.0, now=t0 + i * 0.01)
    assert tr.check(now=t0 + 1.0) == []
    assert not tr.alert_active()


# ---------------------------------------------------------------------------
# configuration: loud validation + env round-trip
# ---------------------------------------------------------------------------


def test_config_validation_is_loud():
    with pytest.raises(mx.MXNetError, match="unknown SLO class"):
        slo.check_class("premium")
    with pytest.raises(mx.MXNetError, match="missing SLO class"):
        slo._parse_class_map("X", "interactive=5", minimum=0.0)
    with pytest.raises(mx.MXNetError, match="unknown SLO class"):
        slo._parse_class_map("X", "interactive=5,gold=1", minimum=0.0)
    with pytest.raises(mx.MXNetError, match="not a number"):
        slo._parse_class_map("X", "interactive=fast,batch=1",
                             minimum=0.0)
    with pytest.raises(mx.MXNetError, match="zero error budget"):
        _cfg(objective=1.0)
    with pytest.raises(mx.MXNetError, match="must exceed"):
        _cfg(fast_window_s=600.0, slow_window_s=60.0)


def test_config_from_env(monkeypatch):
    monkeypatch.setenv("MXNET_SLO_TTFT_MS", "interactive=123,batch=456")
    monkeypatch.setenv("MXNET_SLO_TPT_MS", "interactive=7,batch=77")
    monkeypatch.setenv("MXNET_SLO_OBJECTIVE", "0.95")
    monkeypatch.setenv("MXNET_SLO_FAST_WINDOW", "30")
    monkeypatch.setenv("MXNET_SLO_SLOW_WINDOW", "300")
    monkeypatch.setenv("MXNET_SLO_BURN_ALERT", "7")
    monkeypatch.setenv("MXNET_SLO_MIN_EVENTS", "3")
    cfg = slo.SloConfig.from_env()
    assert cfg.ttft_ms == {"interactive": 123.0, "batch": 456.0}
    assert cfg.tpt_ms == {"interactive": 7.0, "batch": 77.0}
    assert cfg.budget == pytest.approx(0.05)
    assert (cfg.fast_window_s, cfg.slow_window_s) == (30.0, 300.0)
    assert (cfg.burn_alert, cfg.min_events) == (7.0, 3)
    # garbage raises naming the variable (the MXNET_CKPT_* pattern)
    monkeypatch.setenv("MXNET_SLO_OBJECTIVE", "1.5")
    with pytest.raises(mx.MXNetError, match="MXNET_SLO_OBJECTIVE"):
        slo.SloConfig.from_env()
    monkeypatch.setenv("MXNET_SLO_OBJECTIVE", "0.99")
    monkeypatch.setenv("MXNET_SLO_TPT_MS", "interactive=-1,batch=5")
    with pytest.raises(mx.MXNetError, match="MXNET_SLO_TPT_MS"):
        slo.SloConfig.from_env()


# ---------------------------------------------------------------------------
# canary prober (unit: fake probe)
# ---------------------------------------------------------------------------


def test_canary_prober_books_metrics_and_failures():
    tr = slo.SloTracker(_cfg(), source="test")
    seen = []

    def probe(trace):
        seen.append(trace)
        if len(seen) == 2:
            raise RuntimeError("boom")  # a failed probe is a data point

    p = slo.CanaryProber(probe, 0.02, tracker=tr, name="test")
    deadline = time.time() + 10.0
    while len(seen) < 3 and time.time() < deadline:
        time.sleep(0.02)
    p.stop()
    assert len(seen) >= 3
    assert all(t is not None for t in seen)  # trace-stamped probes
    summ = profiler.metrics_summary()
    assert summ["counters"]["slo.canary_probes"] >= 3
    assert summ["counters"]["slo.canary_failures"] >= 1
    assert summ["histograms"]["slo.canary_ms"]["count"] >= 3
    # outcomes fed the availability objective (1 bad in the window)
    assert tr.burn_rate("interactive", "avail") > 0.0
    # statusz canary section reads the same counters
    st = tr.stats()
    assert st["canary"]["probes"] >= 3
    assert st["canary"]["failures"] >= 1
    assert st["canary"]["p50_ms"] is not None


def test_canary_prober_rejects_zero_interval():
    with pytest.raises(mx.MXNetError, match="canary interval"):
        slo.CanaryProber(lambda trace: None, 0.0)


# ---------------------------------------------------------------------------
# engine integration (real decode path)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def lm():
    sym = models.transformer_lm(V, MAXLEN, num_layers=L, num_heads=H,
                                d_model=DM, block_size=KVB)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, MAXLEN))],
             label_shapes=[("softmax_label", (2, MAXLEN))],
             for_training=False)
    mod.init_params(mx.initializer.Xavier(factor_type="in",
                                          magnitude=2.0))
    arg, aux = mod.get_params()
    return {**arg, **aux}


def _engine(params, **kw):
    args = dict(vocab_size=V, num_layers=L, num_heads=H, d_model=DM,
                max_len=MAXLEN, kv_block=KVB, max_streams=4,
                decode_buckets=[1, 2, 4], temperature=0.0)
    args.update(kw)
    return mx.DecodeEngine(params, **args)


def test_cost_records_conserve_engine_counters(lm):
    """The tentpole reconciliation contract: across a mixed
    prefix-hit + speculative + chunked-prefill run, the per-stream
    cost records sum EXACTLY (==, not approx) to the engine counters
    for tokens / prefill_tokens / cow_copies — both sides increment
    at the same program points, so any drift is a wiring bug."""
    shared = np.arange(1, 9, dtype=np.int32)        # 2 full blocks
    pa = np.concatenate([shared, [11, 12, 13]]).astype(np.int32)
    pb = np.concatenate([shared, [21, 22]]).astype(np.int32)
    with _engine(lm, cache_blocks=12, prefix_cache=1, spec_tokens=2,
                 prefill_chunk=4) as eng:
        eng.generate(pa, 4)                         # miss (chunked)
        eng.generate(pb, 4, slo_class="batch")      # suffix-only hit
        eng.generate(shared, 4)                     # full hit → COW
        recs = eng.cost_records()
        st = eng.stats()
    assert len(recs) == 3
    assert sum(r["tokens"] for r in recs) == st["tokens"]
    assert sum(r["prefill_tokens"] for r in recs) \
        == st["prefill_tokens"]
    assert sum(r["cow_copies"] for r in recs) == st["cow_copies"]
    assert st["cow_copies"] >= 1                    # the run COWed
    assert sum(r["spec_accepted"] for r in recs) == st["spec_accepted"]
    # d2h: records attribute one sync per DELIVERED step per stream;
    # with sequential single-stream traffic that equals the engine's
    # per-program count (a batch of riders shares one fetch)
    assert sum(r["d2h_syncs"] for r in recs) == st["d2h_syncs"]
    # per-record shape: prompt accounting + live resource integrals
    assert [r["prompt_tokens"] for r in recs] == [11, 10, 8]
    assert [r["slo_class"] for r in recs] == ["interactive", "batch",
                                              "interactive"]
    for r in recs:
        assert r["tokens"] >= 4 and r["decode_steps"] >= 1
        assert r["page_s"] > 0.0 and r["wall_s"] > 0.0
        assert not r["canary"]
    # the by-class aggregation in stats() carries the same sums
    by_cls = st["cost_by_class"]
    assert by_cls["interactive"]["requests"] == 2
    assert by_cls["batch"]["requests"] == 1
    assert by_cls["interactive"]["tokens"] \
        + by_cls["batch"]["tokens"] == st["tokens"]
    # ... and the Reporter-visible slo.cost.* counters agree
    c = profiler.metrics_summary()["counters"]
    assert c["slo.cost.interactive.tokens"] \
        + c["slo.cost.batch.tokens"] == st["tokens"]


def test_engine_rejects_unknown_slo_class(lm):
    with _engine(lm) as eng:
        with pytest.raises(mx.MXNetError, match="unknown SLO class"):
            eng.generate(np.arange(1, 5, dtype=np.int32), 2,
                         slo_class="gold")


def test_engine_canary_excluded_from_request_counters(lm, monkeypatch):
    """With MXNET_CANARY_INTERVAL set the engine probes itself
    through the full submit path, yet ``requests`` counts ONLY the 2
    real generations while ``slo.canary_*`` proves probes ran."""
    monkeypatch.setenv("MXNET_CANARY_INTERVAL", "0.05")
    monkeypatch.setenv("MXNET_CANARY_TOKENS", "2")
    with _engine(lm) as eng:
        eng.generate(np.arange(1, 6, dtype=np.int32), 3)
        eng.generate(np.arange(2, 7, dtype=np.int32), 3)
        deadline = time.time() + 15.0
        while time.time() < deadline:
            summ = profiler.metrics_summary()
            if summ["counters"].get("slo.canary_probes", 0) >= 1:
                break
            time.sleep(0.05)
        st = eng.stats()
        recs = eng.cost_records()
    assert summ["counters"]["slo.canary_probes"] >= 1
    assert st["requests"] == 2            # canaries excluded
    assert st["generations"] >= 3         # ... but they DID decode
    # canary cost records are flagged (quota layers can drop them)
    assert any(r["canary"] for r in recs)


def test_slow_rank_alert_fires_before_conviction(lm, monkeypatch,
                                                 tmp_path):
    """THE timing contract: an injected per-step latency fault
    (MXNET_CHAOS_SLOW_RANK) trips the fast-window burn alert in
    seconds — long before MXNET_DEAD_RANK_TIMEOUT could convict the
    replica, which never stops heartbeating.  The alert lands in the
    tracker, /statusz and a flight-recorder dump."""
    monkeypatch.setenv("MXNET_CHAOS_SLOW_RANK", "0.12")
    monkeypatch.setenv("MXNET_SLO_TPT_MS", "interactive=5,batch=50")
    monkeypatch.setenv("MXNET_SLO_MIN_EVENTS", "4")
    monkeypatch.setenv("MXNET_FLIGHT_RECORDER_DIR", str(tmp_path))
    slo.reset_tracker()
    chaos.reset_chaos()
    t_fault = time.perf_counter()
    with _engine(lm) as eng:
        eng.generate(np.arange(1, 6, dtype=np.int32), 8)
        tracker = slo.get_tracker()
        tracker.check()
        assert tracker.alert_active()
        alert = list(tracker.alerts)[-1]
    t_alert = alert.monotonic_s
    assert alert.metric == "tpt"
    assert alert.burn_rate >= tracker.config.burn_alert
    # the whole point: alert latency << the conviction window
    assert t_alert - t_fault < dead_rank_timeout()
    assert t_alert - t_fault < 30.0
    # surfaced in the statusz section ...
    st = tracker.stats()
    assert st["alerts_recent"]
    assert st["alerts_recent"][-1]["metric"] == "tpt"
    # ... and in a flight-recorder dump tagged with the alert
    dumps = list(tmp_path.iterdir())
    assert dumps, "slo_alert flight-recorder dump missing"
    assert any("slo_alert" in d.name for d in dumps)


# ---------------------------------------------------------------------------
# perf_sentinel smoke (tier-1 safe: stdlib-only module, no jax)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def sentinel():
    spec = importlib.util.spec_from_file_location(
        "perf_sentinel", os.path.join(_REPO, "tools",
                                      "perf_sentinel.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bench_file(path, tok_s, p99_ms):
    path.write_text(
        "[bench] log noise the parser must skip\n"
        + json.dumps({"metric": "toy_throughput", "value": tok_s,
                      "unit": "tokens/s/chip"}) + "\n"
        + json.dumps({"metric": "toy_p99", "value": p99_ms,
                      "unit": "ms"}) + "\n")
    return str(path)


def test_perf_sentinel_repeat_passes_regression_fails(
        sentinel, tmp_path, capsys):
    hist = str(tmp_path / "hist.jsonl")
    good = _bench_file(tmp_path / "run_a.json", 100.0, 20.0)
    assert sentinel.main(["--record", good, "--history", hist]) == 0
    # an identical repeat run sits inside the noise band
    assert sentinel.main(["--check", good, "--history", hist]) == 0
    # a 2x-worse run fails with non-zero exit, NAMING the metrics —
    # in both directions (throughput down, latency up)
    bad = _bench_file(tmp_path / "run_bad.json", 50.0, 40.0)
    capsys.readouterr()
    assert sentinel.main(["--check", bad, "--history", hist]) == 1
    out = capsys.readouterr()
    assert "REGRESSED" in out.out
    assert "toy_throughput" in out.err and "toy_p99" in out.err
    # direction inference: ms is lower-better, /s is higher-better
    assert sentinel.lower_is_better("ms")
    assert not sentinel.lower_is_better("tokens/s/chip")
    # unknown metrics pass by default, fail under --strict
    new = _bench_file(tmp_path / "run_new.json", 1.0, 1.0)
    hist2 = str(tmp_path / "empty.jsonl")
    assert sentinel.main(["--check", new, "--history", hist2]) == 0
    assert sentinel.main(["--check", new, "--history", hist2,
                          "--strict"]) == 1


def test_perf_sentinel_noise_band_uses_median_and_mad(
        sentinel, tmp_path):
    """5 recorded points around 100 (MAD 2): with sigma=5 the band is
    max(5*1.4826*2, 10) ≈ 14.8, so 90 passes and 80 fails."""
    hist = str(tmp_path / "h.jsonl")
    for v in (97.0, 99.0, 100.0, 102.0, 104.0):
        sentinel.main(["--record",
                       _bench_file(tmp_path / "r.json", v, 20.0),
                       "--history", hist])
    b = sentinel.baseline(sentinel.load_history(hist),
                          "toy_throughput")
    assert b["median"] == 100.0 and b["mad"] == 2.0
    ok = _bench_file(tmp_path / "ok.json", 90.0, 20.0)
    assert sentinel.main(["--check", ok, "--history", hist]) == 0
    sag = _bench_file(tmp_path / "sag.json", 80.0, 20.0)
    assert sentinel.main(["--check", sag, "--history", hist]) == 1


def test_perf_sentinel_committed_history_parses(sentinel):
    """The committed BENCH_HISTORY.jsonl stays loadable and every
    recorded metric yields a usable baseline."""
    hist = sentinel.load_history(os.path.join(_REPO,
                                              "BENCH_HISTORY.jsonl"))
    assert hist, "committed BENCH_HISTORY.jsonl is empty"
    for metric in {h["metric"] for h in hist}:
        b = sentinel.baseline(hist, metric)
        assert b["n"] >= 1 and b["median"] > 0
