#!/usr/bin/env python
"""Train an ImageNet-class network — BASELINE config 2.

Parity with ``example/image-classification/train_imagenet.py``: the
same CLI over RecordIO data (``--data-train`` .rec packed by
``tools/im2rec.py``) or synthetic benchmark mode (``--benchmark 1``,
the reference's throughput-measurement path).  ``--kv-store tpu`` runs
mesh data parallelism over every visible chip.

    # throughput benchmark, synthetic data (reference --benchmark 1)
    python examples/train_imagenet.py --network resnet-50 --benchmark 1

    # real data packed with tools/im2rec.py
    python examples/train_imagenet.py --data-train train.rec
"""

import argparse

from common.util import add_fit_args, fit, synthetic_image_iter

import mxnet_tpu as mx
from mxnet_tpu import models


def main():
    parser = argparse.ArgumentParser(
        description="train imagenet",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--data-train", type=str, default=None)
    parser.add_argument("--data-val", type=str, default=None)
    parser.add_argument("--image-shape", type=str, default="3,224,224")
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--benchmark", type=int, default=0,
                        help="1 = synthetic data throughput run")
    parser.add_argument("--num-batches", type=int, default=40,
                        help="benchmark batches per epoch")
    parser.add_argument("--rgb-mean", type=str, default="123.68,116.779,103.939")
    parser.add_argument("--io-workers", type=int, default=0,
                        help="decode-pool processes (0 = in-process)")
    parser.add_argument("--device-augment", type=int, default=0,
                        help="1 = uint8 wire batches + fused on-device "
                             "crop/flip/normalize")
    add_fit_args(parser)
    parser.set_defaults(network="resnet-50", batch_size=32, num_epochs=1,
                        lr=0.1)
    args = parser.parse_args()

    image_shape = tuple(int(x) for x in args.image_shape.split(","))
    net = models.get_symbol(args.network, num_classes=args.num_classes,
                            image_shape=image_shape)

    if args.benchmark or not args.data_train:
        train = synthetic_image_iter(args.batch_size, image_shape,
                                     args.num_classes, args.num_batches)
        val = None
    else:
        mean = [float(x) for x in args.rgb_mean.split(",")]
        train = mx.io.ImageRecordIter(
            path_imgrec=args.data_train, data_shape=image_shape,
            batch_size=args.batch_size, shuffle=True, rand_crop=True,
            rand_mirror=True, mean_r=mean[0], mean_g=mean[1], mean_b=mean[2],
            preprocess_threads=8, workers=args.io_workers,
            device_augment=args.device_augment)
        val = None
        if args.data_val:
            val = mx.io.ImageRecordIter(
                path_imgrec=args.data_val, data_shape=image_shape,
                batch_size=args.batch_size, mean_r=mean[0], mean_g=mean[1],
                mean_b=mean[2], preprocess_threads=8,
                workers=args.io_workers,
                device_augment=args.device_augment)

    fit(args, net, train, val)


if __name__ == "__main__":
    main()
