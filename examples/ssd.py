#!/usr/bin/env python
"""SSD single-shot detector — BASELINE config 4.

Parity with ``example/ssd/``: a conv backbone with multi-scale heads,
MultiBoxPrior anchors, MultiBoxTarget-driven joint classification +
smooth-L1 localization loss, MultiBoxDetection decode + NMS at
inference.  Trains on a synthetic shapes dataset (bright squares on
noise, class = brightness band) so the script runs anywhere; plug a
RecordIO detection dataset in the same way as train_imagenet.

    python examples/ssd.py --num-epochs 8
"""

import argparse

from common.util import add_fit_args, get_device  # noqa: F401  (path bootstrap)

import numpy as np

import mxnet_tpu as mx

NUM_CLASSES = 3  # background + 2 object classes in cls space


def ssd_symbol(num_classes=NUM_CLASSES, apx=3):
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    body = data
    for i, nf in enumerate((16, 32)):
        body = mx.sym.Convolution(body, num_filter=nf, kernel=(3, 3),
                                  pad=(1, 1), name=f"conv{i}")
        body = mx.sym.Activation(body, act_type="relu")
        body = mx.sym.Pooling(body, kernel=(2, 2), stride=(2, 2),
                              pool_type="max")
    # one detection head on the 8x8 map
    anchors = mx.sym.MultiBoxPrior(body, sizes="(0.3, 0.6)", ratios="(1, 2)",
                                   name="anchors")
    loc = mx.sym.Convolution(body, num_filter=apx * 4, kernel=(3, 3),
                             pad=(1, 1), name="loc_head")
    loc_preds = mx.sym.Flatten(mx.sym.transpose(loc, axes=(0, 2, 3, 1)))
    cls = mx.sym.Convolution(body, num_filter=apx * num_classes,
                             kernel=(3, 3), pad=(1, 1), name="cls_head")
    cls = mx.sym.Reshape(mx.sym.transpose(cls, axes=(0, 2, 3, 1)),
                         shape=(0, -1, num_classes))
    cls_preds = mx.sym.transpose(cls, axes=(0, 2, 1))  # (B, C, A)

    tgt = mx.sym.MultiBoxTarget(anchors, label, cls_preds,
                                overlap_threshold="0.5",
                                negative_mining_ratio="3", name="tgt")
    loc_target, loc_mask, cls_target = tgt[0], tgt[1], tgt[2]
    cls_prob = mx.sym.SoftmaxOutput(cls_preds, cls_target, multi_output=True,
                                    use_ignore=True, ignore_label=-1,
                                    name="cls_prob")
    loc_loss = mx.sym.MakeLoss(
        mx.sym.smooth_l1(loc_mask * (loc_preds - loc_target), scalar="1.0"),
        grad_scale=1.0, name="loc_loss")
    train_sym = mx.sym.Group([cls_prob, loc_loss])

    det_sym = mx.sym.MultiBoxDetection(cls_prob, loc_preds, anchors,
                                       nms_threshold="0.5", threshold="0.4",
                                       name="det")
    return train_sym, det_sym


def synthetic_shapes(num, size=32, seed=0):
    """Squares on noise: class 0 = dim square, class 1 = bright square."""
    rng = np.random.RandomState(seed)
    X = rng.rand(num, 3, size, size).astype(np.float32) * 0.2
    Y = np.full((num, 2, 5), -1.0, np.float32)
    for i in range(num):
        cls = rng.randint(0, 2)
        w = rng.randint(size // 3, size // 2)
        x0 = rng.randint(0, size - w)
        y0 = rng.randint(0, size - w)
        X[i, :, y0:y0 + w, x0:x0 + w] = 0.5 if cls == 0 else 1.0
        Y[i, 0] = [cls, x0 / size, y0 / size, (x0 + w) / size,
                   (y0 + w) / size]
    return X, Y


def main():
    parser = argparse.ArgumentParser(description="train toy SSD")
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--num-epochs", type=int, default=8)
    parser.add_argument("--lr", type=float, default=0.005)
    args = parser.parse_args()

    train_sym, det_sym = ssd_symbol()
    X, Y = synthetic_shapes(32 * args.batch_size)
    it = mx.io.NDArrayIter(X, Y, batch_size=args.batch_size, shuffle=True,
                           label_name="label", last_batch_handle="discard")
    dev = get_device()
    mod = mx.mod.Module(train_sym, label_names=("label",), context=dev)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})
    for epoch in range(args.num_epochs):
        it.reset()
        accs = []
        for b in it:
            mod.forward(b, is_train=True)
            mod.backward()
            mod.update()
            prob = mod.get_outputs()[0].asnumpy()  # (B, C, A)
            accs.append(float(prob.max(axis=1).mean()))
        print(f"Epoch[{epoch}] mean max cls_prob={np.mean(accs):.3f}")

    # detection pass with the trained weights
    det_mod = mx.mod.Module(det_sym, label_names=("label",), context=dev)
    det_mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
                 for_training=False)
    det_mod.set_params(*mod.get_params())
    it.reset()
    b = next(iter(it))
    det_mod.forward(b, is_train=False)
    det = det_mod.get_outputs()[0].asnumpy()
    valid = (det[:, :, 0] >= 0).sum(axis=1)
    print(f"detections per image (batch 0..{args.batch_size - 1}): {valid}")
    return det


if __name__ == "__main__":
    main()
