#!/usr/bin/env python
"""Train the decoder-only transformer LM with full 3D parallelism
(dp × tp × pp) from ONE logical-axis rules table — the declarative
sharding path (README "3D parallelism").

The model (models/transformer.py) carries logical axis names on every
weight (('vocab', 'embed'), ('qkv', 'embed'), ...) and __pp_block__
annotations on every residual block; NOTHING here names a device or an
op-level shard — the rules table plus MeshPlan(dp, tp, pp) is the whole
parallelism configuration:

  python train_transformer_lm.py --dp 2 --tp 2 --pp 2 --microbatches 4

On a machine without accelerators the script builds the 8-device
virtual CPU mesh (set XLA_FLAGS=--xla_force_host_platform_device_count=8
before launch, as tests/conftest.py does).
"""

import argparse
import logging
import os

if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu.models import transformer


def synthetic_lm_iter(vocab, seq_len, batch, steps, seed=7):
    """Next-token data over a random-walk token stream (a learnable
    synthetic language: token t+1 is correlated with token t)."""
    rng = np.random.RandomState(seed)
    walk = np.cumsum(rng.randint(-2, 3, size=batch * steps * seq_len + 1))
    toks = (np.abs(walk) % (vocab - 1) + 1).astype(np.float32)
    X = toks[:-1].reshape(batch * steps, seq_len)
    y = toks[1:].reshape(batch * steps, seq_len)
    return mx.io.NDArrayIter(X, y, batch_size=batch,
                             label_name="softmax_label")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--pp", type=int, default=2)
    ap.add_argument("--microbatches", type=int, default=4)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--d-model", type=int, default=32)
    ap.add_argument("--num-heads", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--num-steps", type=int, default=12)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    import jax

    devices = jax.devices()
    need = args.dp * args.tp * args.pp
    assert len(devices) >= need, \
        f"need {need} devices for dp{args.dp} x tp{args.tp} x pp{args.pp}"

    sym = transformer.transformer_lm(
        args.vocab, args.seq_len, num_layers=args.num_layers,
        num_heads=args.num_heads, d_model=args.d_model)
    # the whole parallelism config: one mesh + one rules table
    plan = parallel.MeshPlan(
        devices[:need], dp=args.dp, tp=args.tp, pp=args.pp,
        microbatches=args.microbatches,
        rules=transformer.lm_partition_rules())

    it = synthetic_lm_iter(args.vocab, args.seq_len, args.batch_size,
                           args.num_steps)
    mx.random.seed(3)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params(mx.initializer.Uniform(0.05))
    mod.set_mesh_plan(plan)
    mod.init_optimizer(kvstore="tpu", optimizer="sgd",
                       optimizer_params={"learning_rate": args.lr,
                                         "momentum": 0.9})

    losses = []
    for b in it:
        mod.forward_backward(b)
        mod.update()
        p = mod.get_outputs()[0].asnumpy()
        lab = b.label[0].asnumpy().astype(int)
        rows = np.take_along_axis(p, lab[..., None], axis=-1)[..., 0]
        losses.append(float(-np.log(np.maximum(rows, 1e-9)).mean()))
    sched = mod._pp_schedule
    logging.info("3D mesh dp=%d tp=%d pp=%d microbatches=%d: "
                 "schedule=%s ticks=%d bubble=%.3f",
                 plan.dp, plan.tp, plan.pp, plan.microbatches,
                 sched.kind, sched.num_ticks, sched.bubble_fraction)
    logging.info("loss first=%.4f last=%.4f", losses[0], losses[-1])
    assert losses[-1] < losses[0], "LM loss did not fall"
    print(f"train_transformer_lm OK: loss {losses[0]:.4f} -> "
          f"{losses[-1]:.4f}")


if __name__ == "__main__":
    main()
