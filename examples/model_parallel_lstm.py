#!/usr/bin/env python
"""Model-parallel LSTM — BASELINE config 5, re-expressed for TPU.

The reference splits LSTM layers across GPUs with ``ctx_group`` +
``group2ctx`` and relies on the async engine to pipeline timesteps
(``example/model-parallel-lstm/lstm.py:48-66``).  The TPU-native
equivalent is a device mesh: the big projection matrices are
tensor-parallel over the 'tp' mesh axis (``annotate_shard``) and the
batch is data-parallel over 'dp' — XLA inserts the collectives and
overlaps them with compute, which is what the reference's pipelining
bought.

Run on one chip (degenerate 1-device mesh) or a virtual mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/model_parallel_lstm.py --tp 2
"""

import argparse

from common.util import add_fit_args, get_device  # noqa: F401  (path bootstrap)

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import parallel


def lstm_lm(vocab_size, num_embed, num_hidden, num_layers, tp_shard):
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    embed = mx.sym.Embedding(data, input_dim=vocab_size,
                             output_dim=num_embed, name="embed")
    rnn = mx.sym.RNN(data=mx.sym.transpose(embed, axes=(1, 0, 2)),
                     parameters=mx.sym.Variable("rnn_parameters"),
                     state=mx.sym.Variable("rnn_state"),
                     state_cell=mx.sym.Variable("rnn_state_cell"),
                     state_size=num_hidden, num_layers=num_layers,
                     mode="lstm", name="rnn")
    out = mx.sym.Reshape(mx.sym.transpose(rnn, axes=(1, 0, 2)),
                         shape=(-1, num_hidden))
    pred = mx.sym.FullyConnected(out, num_hidden=vocab_size, name="pred")
    sm = mx.sym.SoftmaxOutput(pred, mx.sym.Reshape(label, shape=(-1,)),
                              name="softmax")
    if tp_shard:
        # tensor-parallel: vocabulary projection split over 'tp'
        # (the model-parallel axis of config 5)
        parallel.annotate_shard(sm, "pred_weight", "tp", 0)
        parallel.annotate_shard(sm, "embed_weight", "tp", 1)
    return sm


def main():
    parser = argparse.ArgumentParser(description="model-parallel LSTM")
    parser.add_argument("--batch-size", type=int, default=16)
    parser.add_argument("--seq-len", type=int, default=16)
    parser.add_argument("--vocab-size", type=int, default=64)
    parser.add_argument("--num-hidden", type=int, default=128)
    parser.add_argument("--num-embed", type=int, default=64)
    parser.add_argument("--num-layers", type=int, default=2)
    parser.add_argument("--num-epochs", type=int, default=3)
    parser.add_argument("--tp", type=int, default=1,
                        help="tensor-parallel ways (mesh axis size)")
    parser.add_argument("--lr", type=float, default=0.01)
    args = parser.parse_args()

    import jax

    n_dev = len(jax.devices())
    if args.tp < 1:
        parser.error(f"--tp must be >= 1, got {args.tp}")
    tp = args.tp if n_dev % args.tp == 0 else 1
    if tp != args.tp:
        print(f"--tp {args.tp} does not divide {n_dev} devices; using tp=1")
    sym = lstm_lm(args.vocab_size, args.num_embed, args.num_hidden,
                  args.num_layers, tp_shard=tp > 1)

    # synthetic next-token corpus
    rng = np.random.RandomState(0)
    n = 40 * args.batch_size
    start = rng.randint(0, args.vocab_size, size=(n, 1))
    toks = (start + np.arange(args.seq_len + 1)) % args.vocab_size
    X = toks[:, :-1].astype(np.float32)
    Y = toks[:, 1:].astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=args.batch_size, shuffle=True,
                           last_batch_handle="discard")

    dev = get_device()
    mod = mx.mod.Module(sym, context=dev)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mx.random.seed(0)
    zeros = mx.nd.zeros((args.num_layers, args.batch_size, args.num_hidden))
    mod.init_params(mx.initializer.Uniform(0.08),
                    arg_params={"rnn_state": zeros,
                                "rnn_state_cell": zeros.copy()})
    if tp > 1:
        mod.set_mesh_plan(parallel.make_plan(tp=tp))
        kv = "tpu"
    else:
        kv = None
    mod.init_optimizer(kvstore=kv, optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})

    losses = []  # last epoch's per-batch losses (empty if 0 epochs)
    for epoch in range(args.num_epochs):
        it.reset()
        losses = []  # noqa: it intentionally holds only the last epoch
        for b in it:
            mod.forward(b, is_train=True)
            mod.backward()
            mod.update()
            out = mod.get_outputs()[0].asnumpy()
            lab = b.label[0].asnumpy().reshape(-1).astype(int)
            p = out[np.arange(len(lab)), lab]
            losses.append(float(-np.log(np.maximum(p, 1e-9)).mean()))
        print(f"Epoch[{epoch}] mesh(dp={n_dev // tp},tp={tp}) "
              f"loss={np.mean(losses):.3f}")
    return float(np.mean(losses)) if losses else None


if __name__ == "__main__":
    main()
