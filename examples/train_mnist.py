#!/usr/bin/env python
"""Train LeNet/MLP on MNIST — BASELINE config 1.

Parity with ``example/image-classification/train_mnist.py``: same CLI
surface over the Module.fit path.  Uses real MNIST idx files under
``--data-dir`` when present, otherwise a synthetic learnable digit set
(so the script always runs end-to-end).

    python examples/train_mnist.py --network lenet --num-epochs 3
    python examples/train_mnist.py --kv-store tpu     # mesh data-parallel
"""

import argparse

from common.util import add_fit_args, fit, mnist_iters

import mxnet_tpu as mx
from mxnet_tpu import models


def main():
    parser = argparse.ArgumentParser(
        description="train MNIST",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--data-dir", type=str, default="data/mnist")
    parser.add_argument("--num-classes", type=int, default=10)
    add_fit_args(parser)
    parser.set_defaults(network="lenet", batch_size=64, num_epochs=3,
                        lr=0.05)
    args = parser.parse_args()

    net = models.get_symbol(args.network, num_classes=args.num_classes)
    train, val = mnist_iters(args, args.data_dir)
    mod = fit(args, net, train, val,
              epoch_size=train.num_data // args.batch_size
              if hasattr(train, "num_data") else None)
    score = mod.score(val, "acc")
    print(f"final validation accuracy: {score[0][1]:.4f}")
    return score[0][1]


if __name__ == "__main__":
    main()
