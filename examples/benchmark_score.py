#!/usr/bin/env python
"""Inference throughput sweep — parity with
``example/image-classification/benchmark_score.py``: scores every
network at batch sizes 1..32 on synthetic data and prints img/s.

    python examples/benchmark_score.py --networks lenet,resnet-18
"""

import argparse
import time

from common.util import get_device, synthetic_image_iter  # noqa: F401  (path bootstrap)

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import models


def score(network, batch_size, image_shape, num_classes, dev, num_batches=10):
    sym = models.get_symbol(network, num_classes=num_classes,
                            image_shape=image_shape)
    data_shape = (batch_size,) + image_shape
    # the zoo symbols end in SoftmaxOutput, so declare the label input
    # (zero-filled at bind; unused by inference forward)
    mod = mx.mod.Module(sym, context=dev)
    mod.bind(data_shapes=[("data", data_shape)],
             label_shapes=[("softmax_label", (batch_size,))],
             for_training=False)
    mod.init_params(mx.initializer.Xavier(magnitude=2.0))
    rng = np.random.RandomState(0)
    batch = mx.io.DataBatch([mx.nd.array(
        rng.rand(*data_shape).astype(np.float32))], [])
    # warmup (compile)
    for _ in range(2):
        mod.forward(batch, is_train=False)
        mod.get_outputs()[0].wait_to_read()
    tic = time.time()
    for _ in range(num_batches):
        mod.forward(batch, is_train=False)
    mod.get_outputs()[0].wait_to_read()
    return num_batches * batch_size / (time.time() - tic)


def main():
    parser = argparse.ArgumentParser(description="inference benchmark")
    parser.add_argument("--networks", type=str,
                        default="lenet,alexnet,resnet-18,resnet-50")
    parser.add_argument("--batch-sizes", type=str, default="1,2,4,8,16,32")
    parser.add_argument("--image-shape", type=str, default="3,224,224")
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--num-batches", type=int, default=10)
    args = parser.parse_args()

    dev = get_device()
    image_shape = tuple(int(x) for x in args.image_shape.split(","))
    for net in args.networks.split(","):
        shape = (1, 28, 28) if net in ("lenet", "mlp") else image_shape
        classes = 10 if net in ("lenet", "mlp") else args.num_classes
        for b in (int(x) for x in args.batch_sizes.split(",")):
            ips = score(net, b, shape, classes, dev, args.num_batches)
            print(f"network: {net:16s} batch: {b:3d}  {ips:10.1f} img/s")


if __name__ == "__main__":
    main()
