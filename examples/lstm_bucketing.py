#!/usr/bin/env python
"""PTB-style LSTM language model with bucketing — BASELINE config 3.

Parity with ``example/rnn/lstm_bucketing.py`` + ``bucket_io.py``:
variable-length sentences bucketed to a few lengths, one
BucketingModule sharing parameters across per-bucket programs,
Perplexity metric.  Reads a PTB-format text file (one sentence per
line) via ``--data``; without one it generates a synthetic Markov
corpus so the script always runs and the perplexity drop is real.

    python examples/lstm_bucketing.py --num-epochs 5
    python examples/lstm_bucketing.py --data ptb.train.txt
"""

import argparse
import os

from common.util import add_fit_args, get_device, setup_logging  # noqa: F401  (path bootstrap)

import numpy as np

import mxnet_tpu as mx

BUCKETS = [8, 16, 24, 32]


def tokenize(path, vocab=None):
    sentences = []
    vocab = vocab if vocab is not None else {"<pad>": 0, "<eos>": 1}
    with open(path) as f:
        for line in f:
            words = line.strip().split()
            if not words:
                continue
            ids = []
            for w in words:
                if w not in vocab:
                    vocab[w] = len(vocab)
                ids.append(vocab[w])
            ids.append(vocab["<eos>"])
            sentences.append(ids)
    return sentences, vocab


def synthetic_corpus(num_sentences=600, vocab_size=64, seed=0):
    """Markov-chain corpus: next token strongly depends on current."""
    rng = np.random.RandomState(seed)
    trans = rng.randint(2, vocab_size, size=(vocab_size, 2))
    sentences = []
    for _ in range(num_sentences):
        n = rng.randint(5, BUCKETS[-1] + 1)
        tok = rng.randint(2, vocab_size)
        s = [tok]
        for _ in range(n - 1):
            tok = trans[tok, rng.randint(0, 2)]
            s.append(int(tok))
        sentences.append(s)
    return sentences, vocab_size


class BucketSentenceIter(mx.io.DataIter):
    """reference: example/rnn/bucket_io.py BucketSentenceIter — pads
    each sentence up to its bucket, batches per bucket."""

    def __init__(self, sentences, batch_size, buckets=BUCKETS,
                 data_name="data", label_name="softmax_label", seed=1):
        super().__init__(batch_size)
        self.data_name, self.label_name = data_name, label_name
        self.buckets = sorted(buckets)
        self.default_bucket_key = max(buckets)
        self._rng = np.random.RandomState(seed)
        per_bucket = {b: [] for b in self.buckets}
        discarded = 0
        for s in sentences:
            for b in self.buckets:
                if len(s) <= b:
                    per_bucket[b].append(
                        np.pad(s, (0, b - len(s)))[:b])
                    break
            else:
                discarded += 1
        if discarded:
            print(f"discarded {discarded} sentences longer than "
                  f"{self.default_bucket_key}")
        skipped = {b: len(v) for b, v in per_bucket.items()
                   if 0 < len(v) < batch_size}
        if skipped:
            print(f"skipping under-filled buckets (< batch_size): {skipped}")
        self._data = {b: np.asarray(v, np.float32)
                      for b, v in per_bucket.items() if len(v) >= batch_size}
        if not self._data:
            raise ValueError(
                f"no bucket has at least batch_size={batch_size} sentences "
                f"({len(sentences)} sentences total) — lower --batch-size")
        self.reset()

    @property
    def provide_data(self):
        return [mx.io.DataDesc(self.data_name,
                               (self.batch_size, self.default_bucket_key))]

    @property
    def provide_label(self):
        return [mx.io.DataDesc(self.label_name,
                               (self.batch_size, self.default_bucket_key))]

    def reset(self):
        self._plan = []
        for b, arr in self._data.items():
            idx = self._rng.permutation(len(arr))
            for i in range(0, len(arr) - self.batch_size + 1,
                           self.batch_size):
                self._plan.append((b, idx[i:i + self.batch_size]))
        self._rng.shuffle(self._plan)
        self._cur = 0

    def next(self):
        if self._cur >= len(self._plan):
            raise StopIteration
        b, idx = self._plan[self._cur]
        self._cur += 1
        sent = self._data[b][idx]
        data = sent
        label = np.concatenate([sent[:, 1:], np.zeros((len(sent), 1),
                                                      np.float32)], axis=1)
        return mx.io.DataBatch(
            [mx.nd.array(data)], [mx.nd.array(label)], pad=0, bucket_key=b,
            provide_data=[mx.io.DataDesc(self.data_name,
                                         (self.batch_size, b))],
            provide_label=[mx.io.DataDesc(self.label_name,
                                          (self.batch_size, b))])


def make_sym_gen(vocab_size, num_embed, num_hidden, num_layers):
    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab_size,
                                 output_dim=num_embed, name="embed")
        rnn = mx.sym.RNN(data=mx.sym.transpose(embed, axes=(1, 0, 2)),
                         parameters=mx.sym.Variable("rnn_parameters"),
                         state=mx.sym.Variable("rnn_state"),
                         state_cell=mx.sym.Variable("rnn_state_cell"),
                         state_size=num_hidden, num_layers=num_layers,
                         mode="lstm", name="rnn")
        out = mx.sym.Reshape(mx.sym.transpose(rnn, axes=(1, 0, 2)),
                             shape=(-1, num_hidden))
        pred = mx.sym.FullyConnected(out, num_hidden=vocab_size, name="pred")
        flat_label = mx.sym.Reshape(label, shape=(-1,))
        sm = mx.sym.SoftmaxOutput(pred, flat_label, ignore_label=0,
                                  use_ignore=True, name="softmax")
        return sm, ("data",), ("softmax_label",)
    return sym_gen


def main():
    parser = argparse.ArgumentParser(description="LSTM bucketing LM")
    parser.add_argument("--data", type=str, default=None,
                        help="PTB-format text file (one sentence per line)")
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--num-epochs", type=int, default=4)
    parser.add_argument("--num-hidden", type=int, default=128)
    parser.add_argument("--num-embed", type=int, default=64)
    parser.add_argument("--num-layers", type=int, default=1)
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--kv-store", type=str, default=None)
    args = parser.parse_args()

    if args.data and os.path.exists(args.data):
        sentences, vocab = tokenize(args.data)
        vocab_size = len(vocab)
    else:
        print("no --data file — using a synthetic Markov corpus")
        sentences, vocab_size = synthetic_corpus()

    setup_logging()
    it = BucketSentenceIter(sentences, args.batch_size)
    dev = get_device()
    mod = mx.mod.BucketingModule(
        make_sym_gen(vocab_size, args.num_embed, args.num_hidden,
                     args.num_layers),
        default_bucket_key=it.default_bucket_key, context=dev)
    mx.random.seed(0)
    zeros = mx.nd.zeros((args.num_layers, args.batch_size, args.num_hidden))
    metric = mx.metric.Perplexity(ignore_label=0)
    # the reference workflow: BucketingModule straight through fit()
    # (example/rnn/lstm_bucketing.py), batches routed per bucket_key
    mod.fit(it, num_epoch=args.num_epochs,
            kvstore=args.kv_store,
            optimizer="adam",
            optimizer_params={"learning_rate": args.lr},
            eval_metric=metric,
            initializer=mx.initializer.Uniform(0.08),
            arg_params={"rnn_state": zeros,
                        "rnn_state_cell": zeros.copy()},
            allow_missing=True,
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, 50, auto_reset=False))
    name, ppl = metric.get()
    print(f"final Train-{name}={ppl:.2f}")
    return ppl


if __name__ == "__main__":
    main()
