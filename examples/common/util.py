"""Common example plumbing: repo path bootstrap, fit argument group,
synthetic datasets (the zero-egress stand-ins for MNIST/ImageNet/PTB).

Reference analogue: ``example/image-classification/common/fit.py`` +
``common/data.py`` (argument groups, kvstore/optimizer wiring, data
iterators).  Synthetic data keeps every script runnable end-to-end on
a machine with no datasets while still being *learnable* (class-
dependent signal), so accuracy/perplexity improvements are real.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import numpy as np

import mxnet_tpu as mx


def setup_logging():
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")


def get_device():
    """The training device: the TPU when one is visible, else whatever
    JAX exposes (mx.tpu() already falls back to the default backend)."""
    return mx.tpu()


def add_fit_args(parser: argparse.ArgumentParser):
    """reference: common/fit.py add_fit_args"""
    train = parser.add_argument_group("Training")
    train.add_argument("--network", type=str, default="lenet")
    train.add_argument("--batch-size", type=int, default=64)
    train.add_argument("--num-epochs", type=int, default=3)
    train.add_argument("--lr", type=float, default=0.05)
    train.add_argument("--lr-factor", type=float, default=0.1)
    train.add_argument("--lr-step-epochs", type=str, default="")
    train.add_argument("--optimizer", type=str, default="sgd")
    train.add_argument("--mom", type=float, default=0.9)
    train.add_argument("--wd", type=float, default=0.0001)
    train.add_argument("--kv-store", type=str, default="local",
                       help="local | device | tpu | dist_sync | dist_async")
    train.add_argument("--disp-batches", type=int, default=20)
    train.add_argument("--model-prefix", type=str, default=None)
    train.add_argument("--load-epoch", type=int, default=None)
    train.add_argument("--monitor", type=int, default=0,
                       help="monitor interval (0 = off)")
    train.add_argument("--profile", type=str, default=None,
                       help="write a Chrome trace to this file")
    return train


def lr_scheduler(args, epoch_size):
    if not args.lr_step_epochs:
        return None
    steps = [int(x) for x in args.lr_step_epochs.split(",") if x]
    return mx.lr_scheduler.MultiFactorScheduler(
        step=[max(1, epoch_size * s) for s in steps], factor=args.lr_factor)


def fit(args, network, train_iter, val_iter=None, label_names=None,
        initializer=None, epoch_size=None):
    """reference: common/fit.py fit — the standard training run."""
    setup_logging()
    kv = args.kv_store
    devs = get_device()
    mod = mx.mod.Module(network, context=devs,
                        label_names=label_names or ("softmax_label",))
    if args.load_epoch is not None and args.model_prefix:
        _, arg_params, aux_params = mx.model.load_checkpoint(
            args.model_prefix, args.load_epoch)
    else:
        arg_params = aux_params = None
    epoch_size = epoch_size or 1000
    optimizer_params = {
        "learning_rate": args.lr,
        "wd": args.wd,
    }
    if args.optimizer in ("sgd", "nag"):
        optimizer_params["momentum"] = args.mom
    sched = lr_scheduler(args, epoch_size)
    if sched is not None:
        optimizer_params["lr_scheduler"] = sched
    monitor = mx.Monitor(args.monitor, pattern=".*") if args.monitor > 0 \
        else None
    if args.profile:
        mx.profiler.profiler_set_config(mode="all", filename=args.profile)
        mx.profiler.profiler_set_state("run")
    checkpoint = mx.callback.do_checkpoint(args.model_prefix) \
        if args.model_prefix else None
    mod.fit(train_iter,
            eval_data=val_iter,
            begin_epoch=args.load_epoch or 0,
            num_epoch=args.num_epochs,
            eval_metric="acc",
            kvstore=kv,
            optimizer=args.optimizer,
            optimizer_params=optimizer_params,
            initializer=initializer or mx.initializer.Xavier(
                rnd_type="gaussian", factor_type="in", magnitude=2),
            arg_params=arg_params,
            aux_params=aux_params,
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, args.disp_batches),
            epoch_end_callback=checkpoint,
            monitor=monitor)
    if args.profile:
        mx.profiler.profiler_set_state("stop")
        print(f"wrote profile to {args.profile}")
    return mod


# ---------------------------------------------------------------------------
# Synthetic datasets (learnable, deterministic)
# ---------------------------------------------------------------------------

def synthetic_mnist(num=2048, seed=0):
    """28x28 digit-like data: class k = bright kxk-ish block pattern."""
    rng = np.random.RandomState(seed)
    X = rng.rand(num, 1, 28, 28).astype(np.float32) * 0.25
    y = rng.randint(0, 10, size=num).astype(np.float32)
    for i in range(num):
        k = int(y[i])
        r, c = divmod(k, 4)
        X[i, 0, 2 + r * 8:8 + r * 8, 2 + c * 6:8 + c * 6] += 0.75
    return X, y


def mnist_iters(args, data_dir=None):
    """Real MNIST idx files when present, else synthetic."""
    if data_dir:
        timg = os.path.join(data_dir, "train-images-idx3-ubyte")
        tlbl = os.path.join(data_dir, "train-labels-idx1-ubyte")
        vimg = os.path.join(data_dir, "t10k-images-idx3-ubyte")
        vlbl = os.path.join(data_dir, "t10k-labels-idx1-ubyte")
        if all(os.path.exists(p) or os.path.exists(p + ".gz")
               for p in (timg, tlbl, vimg, vlbl)):
            fix = lambda p: p if os.path.exists(p) else p + ".gz"
            train = mx.io.MNISTIter(image=fix(timg), label=fix(tlbl),
                                    batch_size=args.batch_size, shuffle=True)
            val = mx.io.MNISTIter(image=fix(vimg), label=fix(vlbl),
                                  batch_size=args.batch_size, shuffle=False)
            return train, val
    logging.info("MNIST files not found — using a synthetic learnable set")
    X, y = synthetic_mnist(4096)
    Xv, yv = synthetic_mnist(512, seed=7)
    train = mx.io.NDArrayIter(X, y, batch_size=args.batch_size, shuffle=True,
                              last_batch_handle="discard")
    val = mx.io.NDArrayIter(Xv, yv, batch_size=args.batch_size,
                            last_batch_handle="discard")
    return train, val


def synthetic_image_iter(batch_size, image_shape, num_classes=1000,
                         num_batches=50):
    """The reference's --benchmark 1 path: random device-side batches."""
    c, h, w = image_shape
    rng = np.random.RandomState(0)
    n = batch_size * 2
    X = rng.rand(n, c, h, w).astype(np.float32)
    y = rng.randint(0, num_classes, size=n).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=batch_size)
    return mx.io.ResizeIter(it, num_batches, reset_internal=False)
