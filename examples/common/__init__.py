"""Shared helpers for the example scripts (reference:
example/image-classification/common/)."""
