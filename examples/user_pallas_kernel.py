"""User-authored Pallas kernel as a framework operator — the RTC story.

The reference let users write CUDA kernel bodies from Python and launch
them on NDArrays (python/mxnet/rtc.py + src/common/mxrtc.cc:13-76).
The TPU-native equivalent: write a Pallas kernel, register it with
``mx.rtc.pallas_op`` (or any jax function with ``mx.rtc.register_op``),
and use it imperatively, in symbols, and inside ``Module.fit`` — with a
user-supplied VJP so the op trains.

Run: JAX_PLATFORMS=cpu python examples/user_pallas_kernel.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx


# --- 1. the kernel: fused x*sigmoid(x) (SiLU), written ref-style -------
def silu_kernel(x_ref, o_ref):
    import jax.numpy as jnp

    x = x_ref[...]
    o_ref[...] = x / (1.0 + jnp.exp(-x))


# its VJP — also supplied by the user, recomputing from inputs
# (rematerialization, the TPU-first default) instead of saving
# activations
def silu_vjp(inputs, out_grads):
    import jax.numpy as jnp

    (x,) = inputs
    (g,) = out_grads
    s = 1.0 / (1.0 + jnp.exp(-x))
    return (g * (s + x * s * (1.0 - s)),)


def main():
    import jax

    mx.rtc.pallas_op("user_silu", silu_kernel, arg_names=("data",),
                     vjp=silu_vjp)

    # on a TPU host run the kernel natively on the chip; elsewhere the
    # Pallas interpreter runs it on CPU — same user code either way
    ctx = mx.tpu() if jax.default_backend() == "tpu" else mx.cpu()
    with ctx:
        _run(ctx)


def _run(ctx):
    # --- imperative: mx.nd.user_silu ----------------------------------
    x = mx.nd.array(np.linspace(-4, 4, 12, dtype=np.float32))
    y = mx.nd.user_silu(x).asnumpy()
    ref = x.asnumpy() / (1.0 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-6)
    print("imperative user_silu OK:", y[:3])

    # --- symbolic + training: the user op inside Module.fit -----------
    rng = np.random.RandomState(0)
    X = rng.randn(128, 16).astype(np.float32)
    w = rng.randn(16).astype(np.float32)
    labels = (X @ w > 0).astype(np.float32)

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.user_silu(net)          # <-- the user kernel in-graph
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    it = mx.io.NDArrayIter(X, labels, batch_size=16,
                           label_name="softmax_label")
    mod = mx.mod.Module(net, context=ctx)
    mx.random.seed(0)
    accs = []
    mod.fit(it, num_epoch=10, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            initializer=mx.initializer.Xavier(), eval_metric="acc",
            epoch_end_callback=lambda e, s, a, x: None,
            batch_end_callback=lambda p: accs.append(
                p.eval_metric.get()[1]))
    assert accs[-1] > 0.85, f"user-kernel net failed to train: {accs[-1]}"
    print(f"Module.fit through the user Pallas kernel OK: acc {accs[-1]:.3f}")


if __name__ == "__main__":
    main()
