// Native RecordIO framing: the byte-level record reader/writer behind
// mxnet_tpu.recordio (Python falls back to a struct-based
// implementation when this library is not built).
//
// Role parity: dmlc-core recordio (used by the reference via
// src/io/iter_image_recordio.cc and python/mxnet/recordio.py).  The
// on-disk framing keeps the reference's header layout — little-endian
// u32 magic 0xced7230a, then u32 lrec whose upper 3 bits are a
// continuation flag and lower 29 bits the payload length, then the
// payload padded to a 4-byte boundary — but this is a clean-room
// implementation: records are always written whole (cflag=0), and the
// reader rejects multipart flags instead of re-assembling them.
//
// C ABI only (consumed from Python via ctypes).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

struct Writer {
  FILE* fp;
};

struct Reader {
  FILE* fp;
  std::vector<char> buf;
};

}  // namespace

extern "C" {

void* MXTPURecordIOWriterCreate(const char* path) {
  FILE* fp = std::fopen(path, "wb");
  if (!fp) return nullptr;
  return new Writer{fp};
}

// Returns 0 on success, -1 on error (payload too large / io failure).
int MXTPURecordIOWriterWrite(void* h, const char* data, uint64_t size) {
  auto* w = static_cast<Writer*>(h);
  if (size > kLenMask) return -1;
  uint32_t header[2] = {kMagic, static_cast<uint32_t>(size)};
  if (std::fwrite(header, sizeof(header), 1, w->fp) != 1) return -1;
  if (size && std::fwrite(data, 1, size, w->fp) != size) return -1;
  static const char pad[4] = {0, 0, 0, 0};
  uint64_t rem = size & 3u;
  if (rem && std::fwrite(pad, 1, 4 - rem, w->fp) != 4 - rem) return -1;
  return 0;
}

int64_t MXTPURecordIOWriterTell(void* h) {
  return std::ftell(static_cast<Writer*>(h)->fp);
}

void MXTPURecordIOWriterFree(void* h) {
  auto* w = static_cast<Writer*>(h);
  if (w) {
    std::fclose(w->fp);
    delete w;
  }
}

void* MXTPURecordIOReaderCreate(const char* path) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return nullptr;
  return new Reader{fp, {}};
}

// Reads the next record.  Returns a pointer (valid until the next call
// on this handle) and fills *size; returns nullptr with *size=0 at
// EOF and nullptr with *size=(uint64_t)-1 on a framing error.
const char* MXTPURecordIOReaderRead(void* h, uint64_t* size) {
  auto* r = static_cast<Reader*>(h);
  uint32_t header[2];
  size_t got = std::fread(header, sizeof(uint32_t), 2, r->fp);
  if (got == 0) {
    *size = 0;
    return nullptr;  // clean EOF
  }
  if (got != 2 || header[0] != kMagic || (header[1] >> 29) != 0) {
    *size = static_cast<uint64_t>(-1);
    return nullptr;
  }
  uint32_t len = header[1] & kLenMask;
  uint32_t padded = (len + 3u) & ~3u;
  if (len == 0) {
    // zero-length record: must return non-null (null + *size=0 means EOF)
    static const char kEmpty = '\0';
    *size = 0;
    return &kEmpty;
  }
  r->buf.resize(padded);
  if (padded && std::fread(r->buf.data(), 1, padded, r->fp) != padded) {
    *size = static_cast<uint64_t>(-1);
    return nullptr;
  }
  *size = len;
  return r->buf.data();
}

int MXTPURecordIOReaderSeek(void* h, int64_t offset) {
  return std::fseek(static_cast<Reader*>(h)->fp, offset, SEEK_SET);
}

int64_t MXTPURecordIOReaderTell(void* h) {
  return std::ftell(static_cast<Reader*>(h)->fp);
}

void MXTPURecordIOReaderFree(void* h) {
  auto* r = static_cast<Reader*>(h);
  if (r) {
    std::fclose(r->fp);
    delete r;
  }
}

// Scans a record file and writes start-of-record byte offsets into
// `offsets` (up to `cap` entries).  Returns the total number of
// records, or -1 on a framing error.  Call with cap=0 to count.
int64_t MXTPURecordIOScan(const char* path, int64_t* offsets, int64_t cap) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return -1;
  int64_t n = 0;
  for (;;) {
    int64_t pos = std::ftell(fp);
    uint32_t header[2];
    size_t got = std::fread(header, sizeof(uint32_t), 2, fp);
    if (got == 0) break;
    if (got != 2 || header[0] != kMagic || (header[1] >> 29) != 0) {
      std::fclose(fp);
      return -1;
    }
    uint32_t padded = ((header[1] & kLenMask) + 3u) & ~3u;
    if (std::fseek(fp, padded, SEEK_CUR) != 0) {
      std::fclose(fp);
      return -1;
    }
    if (n < cap) offsets[n] = pos;
    ++n;
  }
  std::fclose(fp);
  return n;
}

// ---------------------------------------------------------------------------
// Batched random-access read: fetch n records (given their start
// offsets) with an internal thread pool — one native call per batch
// instead of n Python seek+read round trips.  Each worker owns its own
// FILE* so reads are position-independent.
// ---------------------------------------------------------------------------

struct BatchBuffer {
  std::vector<char> data;        // payloads, concatenated
  std::vector<int64_t> sizes;    // per-record payload sizes (-1 = error)
  std::vector<int64_t> starts;   // offsets of payloads inside data
};

// Reads the records at `offsets[0..n)` of `path` using `threads`
// workers.  Returns an opaque handle (free with MXTPUBatchFree), or
// nullptr when the file cannot be opened.  Per-record framing errors
// are reported as size -1 for that record only.
void* MXTPUBatchRead(const char* path, const int64_t* offsets, int64_t n,
                     int threads) {
  // pass 1: read headers to learn payload sizes (cheap, sequential)
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return nullptr;
  auto* out = new BatchBuffer;
  out->sizes.assign(n, -1);
  out->starts.assign(n, 0);
  std::vector<uint32_t> lens(n, 0);
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) {
    uint32_t header[2];
    if (std::fseek(fp, offsets[i], SEEK_SET) != 0 ||
        std::fread(header, sizeof(uint32_t), 2, fp) != 2 ||
        header[0] != kMagic || (header[1] >> 29) != 0) {
      continue;  // sizes[i] stays -1
    }
    lens[i] = header[1] & kLenMask;
    out->sizes[i] = lens[i];
    out->starts[i] = total;
    total += lens[i];
  }
  std::fclose(fp);
  out->data.resize(total);

  // pass 2: parallel payload reads
  if (threads < 1) threads = 1;
  if (threads > n) threads = static_cast<int>(n > 0 ? n : 1);
  std::vector<std::thread> pool;
  pool.reserve(threads);
  for (int t = 0; t < threads; ++t) {
    pool.emplace_back([&, t]() {
      FILE* f = std::fopen(path, "rb");
      if (!f) {
        // no fd: this worker's records must not pass as zero-filled data
        for (int64_t i = t; i < n; i += threads) out->sizes[i] = -1;
        return;
      }
      for (int64_t i = t; i < n; i += threads) {
        if (out->sizes[i] < 0 || lens[i] == 0) continue;
        if (std::fseek(f, offsets[i] + 8, SEEK_SET) != 0 ||
            std::fread(out->data.data() + out->starts[i], 1, lens[i], f)
                != lens[i]) {
          out->sizes[i] = -1;
        }
      }
      std::fclose(f);
    });
  }
  for (auto& th : pool) th.join();
  return out;
}

const char* MXTPUBatchData(void* h) {
  return static_cast<BatchBuffer*>(h)->data.data();
}

const int64_t* MXTPUBatchSizes(void* h) {
  return static_cast<BatchBuffer*>(h)->sizes.data();
}

const int64_t* MXTPUBatchStarts(void* h) {
  return static_cast<BatchBuffer*>(h)->starts.data();
}

void MXTPUBatchFree(void* h) {
  delete static_cast<BatchBuffer*>(h);
}

}  // extern "C"
