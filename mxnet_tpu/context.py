"""Device context.

Parity with the reference's ``python/mxnet/context.py`` and the C++
``Context`` struct (include/mxnet/base.h).  On the TPU build, ``tpu(i)``
maps to the i-th JAX accelerator device; ``cpu(i)`` maps to a host
device.  ``gpu(i)`` is accepted as an alias for ``tpu(i)`` so that
reference user scripts run unchanged.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_devices"]


class Context:
    """Device context: (device_type, device_id).

    Usable as a ``with`` scope exactly like the reference
    (python/mxnet/context.py:12-87).
    """

    # matches reference devtype2str {1:'cpu', 2:'gpu', 3:'cpu_pinned'} with tpu added
    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "tpu": 4}

    _default = threading.local()

    def __init__(self, device_type, device_id: int = 0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx: Optional[Context] = None
        self._jax_device = None

    @property
    def device_type(self) -> str:
        return Context.devtype2str[self.device_typeid]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __str__(self):
        return f"{self.device_type}({self.device_id})"

    __repr__ = __str__

    def __enter__(self):
        self._old_ctx = getattr(Context._default, "ctx", None)
        Context._default.ctx = self
        return self

    def __exit__(self, *args):
        Context._default.ctx = self._old_ctx
        return False

    # ------------------------------------------------------------------
    # JAX device resolution
    # ------------------------------------------------------------------
    def jax_device(self):
        """Resolve to a concrete jax.Device.

        gpu/tpu both resolve to the accelerator backend (alias so
        reference scripts with ``mx.gpu()`` work); falls back to CPU
        when no accelerator is present.
        """
        if self._jax_device is not None:
            return self._jax_device
        if self.device_type in ("cpu", "cpu_pinned"):
            # this process's devices: in a multi-process runtime the
            # global list contains peers' unaddressable devices
            devs = _local_cpu_devices()
            self._jax_device = devs[self.device_id % len(devs)]
        else:
            devs = _accelerator_devices()
            self._jax_device = devs[self.device_id % len(devs)]
        return self._jax_device


def _local_cpu_devices():
    try:
        return jax.local_devices(backend="cpu")
    except RuntimeError:  # no cpu backend registered (rare)
        devs = [d for d in jax.local_devices() if d.platform == "cpu"]
        return devs or jax.devices("cpu")


def _accelerator_devices(local_only: bool = True):
    devs = jax.local_devices() if local_only else jax.devices()
    accel = [d for d in devs if d.platform != "cpu"]
    return accel if accel else devs


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Alias for the accelerator device (TPU on this build)."""
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def num_devices(device_type: str = "tpu") -> int:
    """Per-process (addressable) device count."""
    if device_type in ("cpu", "cpu_pinned"):
        return len(_local_cpu_devices())
    return len(_accelerator_devices())


def current_context() -> Context:
    """The ambient default context (reference: context.py:81-87)."""
    ctx = getattr(Context._default, "ctx", None)
    if ctx is None:
        ctx = Context("cpu", 0)
    return ctx
