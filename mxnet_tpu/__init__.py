"""mxnet_tpu — a TPU-native deep learning framework.

Capability parity with MXNet v0.9.1 (the NNVM-era reference at
/root/reference), re-designed TPU-first on JAX/XLA/Pallas/pjit:

* ``mxnet_tpu.ndarray`` (``mx.nd``)  — imperative tensors, async via XLA dispatch
* ``mxnet_tpu.symbol`` (``mx.sym``)  — symbolic graphs lowered to single XLA programs
* ``mxnet_tpu.module``               — Module / BucketingModule training API
* ``mxnet_tpu.kvstore``              — data-parallel comm via mesh collectives
* ``mxnet_tpu.io``                   — data iterators (NDArray/MNIST/CSV/ImageRecord)
* ``mxnet_tpu.optimizer/metric/initializer/lr_scheduler/callback``
"""

__version__ = "0.1.0"

from . import base
from .base import MXNetError
from . import config
from . import engine
from . import context
from .context import Context, cpu, gpu, tpu, current_context
from . import ndarray
from . import ndarray as nd
from . import random
from . import name
from . import attribute
from .attribute import AttrScope
from . import symbol
from . import symbol as sym
from .symbol import Variable, Group
from . import executor
from .executor import Executor
from . import initializer
from .initializer import Xavier, Uniform, Normal
from . import optimizer
from . import metric
from . import lr_scheduler
from . import callback
from . import io
from . import recordio
from . import image
from . import comm
from . import kvstore
from . import kvstore as kv
from . import model
from . import checkpoint
from .checkpoint import CheckpointManager
from . import elastic
from .elastic import DeadRankError, Membership
from . import chaos
from . import module
from . import module as mod
from . import operator
from . import rtc
from . import predictor
from .predictor import Predictor
from . import slo
from .slo import SloTracker, SloAlert, CanaryProber
from . import serving
from .serving import (InferenceEngine, DecodeEngine, EngineClosedError,
                      ReplicaHarness)
from . import wire
from . import fleet
from .fleet import Router, FleetClient, ShedError
from . import kv_cache
from . import prefix_cache
from . import parallel
from . import pp
from . import sequence
from . import monitor
from .monitor import Monitor
from . import profiler
from . import visualization
from . import visualization as viz

__all__ = [
    "MXNetError", "Context", "cpu", "gpu", "tpu", "current_context",
    "nd", "ndarray", "random", "name", "attribute", "AttrScope",
    "symbol", "sym", "Variable", "Group", "executor", "Executor",
]
