"""Executor — binds a Symbol to buffers and runs it.

Parity with ``include/mxnet/executor.h`` + ``src/executor/graph_executor.cc``
and ``python/mxnet/executor.py``.

TPU-first design (the BASELINE north star): instead of creating one
engine op per graph node (graph_executor.cc:518-648) and pushing them
through a dependency engine, the whole graph is lowered to **one pure
JAX function** and jitted into a **single XLA program**:

* forward (inference)        → ``fwd_infer``  program
* forward+backward (training)→ ``fused``      program — outputs, aux
  updates and all gradients in one XLA computation, so XLA fuses the
  backward with the forward and schedules everything on-chip.  This
  subsumes the reference's Gradient pass, PlanMemory, AttachOpExecs,
  inplace-addto detection and the engine's topo scheduling.

The gradient comes from ``jax.vjp`` over the composed function; MXNet's
"backward ignores head gradients on loss layers" semantics live in the
ops' custom VJPs (ops/nn.py).

grad_req semantics ('write'/'add'/'null') match executor.py /
OpReqType (include/mxnet/op_attr_types.h).
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import engine as _engine
from . import profiler as _prof
from .base import MXNetError
from .context import Context
from .ndarray import NDArray, zeros as nd_zeros
from .ops.registry import OpContext
from . import random as _random

__all__ = ["Executor", "simple_bind"]


def build_graph_fn(symbol):
    """Lower a Symbol DAG into a pure function
    ``f(arg_dict, aux_dict, rng, is_train) -> (outputs, new_aux_dict)``.

    This is the NNVM-graph → XLA lowering (replaces per-node engine
    dispatch, SURVEY §3.1 RunOps)."""
    nodes = symbol._topo()
    node_index = {id(n): i for i, n in enumerate(nodes)}
    out_refs = [(id(n), i) for n, i in symbol._outputs]

    def fn(arg_dict, aux_dict, rng, is_train: bool):
        vals: Dict[tuple, Any] = {}
        new_aux: Dict[str, Any] = {}
        for n in nodes:
            if n.is_variable:
                vals[(id(n), 0)] = arg_dict[n.name]
                continue
            op = n.opdef()
            inputs = [vals[(id(i), ix)] for i, ix in n.inputs]
            aux_names = n.aux_names()
            aux_in = [aux_dict[a] for a in aux_names]
            key = None
            if op.needs_rng:
                key = jax.random.fold_in(rng, node_index[id(n)])
            op_ctx = OpContext(is_train=is_train, rng=key)
            if aux_names:
                outs, aux_out = op.compute(op_ctx, n.attrs, inputs, aux_in)
                for a, v in zip(aux_names, aux_out):
                    new_aux[a] = v
            else:
                outs = op.compute(op_ctx, n.attrs, inputs, [])
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
            for i, o in enumerate(outs):
                vals[(id(n), i)] = o
        outputs = [vals[r] for r in out_refs]
        return outputs, new_aux

    return fn


class Executor:
    """Executable bound graph (reference: python/mxnet/executor.py)."""

    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, group2ctx=None, shared_exec=None):
        self._symbol = symbol
        self._ctx = ctx if isinstance(ctx, Context) else Context(ctx)
        self._group2ctx = group2ctx or {}
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.output_names = symbol.list_outputs()

        self.arg_dict: Dict[str, NDArray] = self._to_dict(args, self.arg_names, "args")
        self.arg_arrays: List[NDArray] = [self.arg_dict[n] for n in self.arg_names]

        self.aux_dict: Dict[str, NDArray] = self._to_dict(aux_states, self.aux_names, "aux_states") \
            if self.aux_names else {}
        self.aux_arrays: List[NDArray] = [self.aux_dict[n] for n in self.aux_names]

        # grad_req normalization (reference: executor_group / simple_bind)
        if isinstance(grad_req, str):
            self.grad_req = {n: grad_req for n in self.arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self.grad_req = dict(zip(self.arg_names, grad_req))
        else:
            self.grad_req = {n: grad_req.get(n, "null") for n in self.arg_names}

        if args_grad is None:
            self.grad_dict: Dict[str, NDArray] = {}
        else:
            self.grad_dict = self._to_dict(args_grad, self.arg_names, "args_grad",
                                           allow_missing=True)
        for n in self.arg_names:
            if n not in self.grad_dict:
                self.grad_req[n] = "null"
        self.grad_arrays: List[Optional[NDArray]] = [
            self.grad_dict.get(n) for n in self.arg_names]

        self._grad_names = [n for n in self.arg_names if self.grad_req.get(n, "null") != "null"]
        # gradient mirroring / rematerialization: trade FLOPs for memory
        # by recomputing activations in backward (reference:
        # MXNET_BACKWARD_DO_MIRROR, graph_executor.cc:199-212 → here a
        # jax.checkpoint over the whole forward)
        from .base import get_env
        self._do_mirror = bool(get_env("MXNET_BACKWARD_DO_MIRROR", 0, int))
        self._monitor_callback = None
        self._graph_fn = build_graph_fn(symbol)
        self._jit_fwd = jax.jit(functools.partial(self._fwd, is_train=False))
        self._jit_fwd_train = jax.jit(functools.partial(self._fwd, is_train=True))
        self._jit_fused = jax.jit(self._fused)
        self._jit_fused_ones = jax.jit(self._fused_ones)
        self.outputs_cache: List[NDArray] = []
        self._train_snapshot = None
        self._cached_grads = None
        self._internals_fns: Dict[bool, Any] = {}
        # programs this executor has already run once: first run per
        # tag = trace+compile+run (XLA caches after), telemetered as a
        # compile event.  Shapes are fixed per executor, so a reshape
        # (new Executor) naturally restarts the compile accounting.
        self._warm_programs: set = set()
        # live-buffer-bytes gauge: what this bind pinned on device
        # (args + grads + aux); decremented when the executor dies so
        # bucketed/reshaped executor churn shows up as a sawtooth.
        # Arrays reused from a shared_exec donor (the bucketed shared
        # arena) are the donor's storage — counting them again would
        # overstate live memory by the bucket count.
        import weakref

        donor_ids = set()
        if shared_exec is not None:
            donor_ids = {id(x) for x in (
                list(shared_exec.arg_dict.values())
                + list(shared_exec.grad_dict.values())
                + list(shared_exec.aux_dict.values()))}
        self._buffer_bytes = sum(
            int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
            for a in {id(x): x for x in (
                list(self.arg_dict.values()) + list(self.grad_dict.values())
                + list(self.aux_dict.values()))}.values()
            if id(a) not in donor_ids)
        # generation-stamped (returned by the increment itself, so the
        # stamp is atomic with it): a decrement that outlives
        # reset_metrics() must be dropped, not drive the gauge negative
        gen = _prof.inc_gauge("executor.live_buffer_bytes",
                              self._buffer_bytes)
        weakref.finalize(self, _prof.inc_gauge,
                         "executor.live_buffer_bytes", -self._buffer_bytes,
                         gen=gen)

    def _record_program(self, tag, start_s, dur_s, args=None):
        """Telemeter one program dispatch: first run per tag counts as
        the compile (trace+compile+run — XLA caches afterwards)."""
        compiled = tag not in self._warm_programs
        if compiled:
            self._warm_programs.add(tag)
        ev_args = {"program": tag}
        if args:
            ev_args.update(args)
        _prof.record_program(
            f"Executor.compile+{tag}" if compiled else f"Executor.{tag}",
            start_s, dur_s, compiled, args=ev_args)

    # ------------------------------------------------------------------
    def _to_dict(self, values, names, what, allow_missing=False) -> Dict[str, NDArray]:
        if values is None:
            raise MXNetError(f"{what} must be provided")
        if isinstance(values, dict):
            d = {}
            for n in names:
                if n in values:
                    d[n] = values[n]
                elif not allow_missing:
                    raise MXNetError(f"{what} missing entry for {n!r}")
            return d
        values = list(values)
        if len(values) != len(names):
            raise MXNetError(f"{what} length {len(values)} != expected {len(names)}")
        return {n: v for n, v in zip(names, values) if v is not None}

    # pure functions to be jitted --------------------------------------
    def _fwd(self, arg_vals, aux_vals, rng, is_train):
        outs, new_aux = self._graph_fn(arg_vals, aux_vals, rng, is_train)
        return outs, new_aux

    def _fused(self, arg_vals, aux_vals, rng, heads):
        grad_names = self._grad_names

        def f(grad_args):
            full = dict(arg_vals)
            full.update(grad_args)
            outs, new_aux = self._graph_fn(full, aux_vals, rng, True)
            return tuple(outs), new_aux

        if self._do_mirror:
            f = jax.checkpoint(f)
        grad_args = {n: arg_vals[n] for n in grad_names}
        (outs, vjp_fn, new_aux) = jax.vjp(f, grad_args, has_aux=True)
        grads = vjp_fn(tuple(heads))[0]
        return list(outs), new_aux, grads

    def _fused_ones(self, arg_vals, aux_vals, rng):
        """Fused fwd+bwd with the default all-ones head gradients (the
        loss-head convention: custom VJPs of loss ops ignore the head).
        One XLA program yields outputs, aux updates and grads."""
        grad_names = self._grad_names

        def f(grad_args):
            full = dict(arg_vals)
            full.update(grad_args)
            outs, new_aux = self._graph_fn(full, aux_vals, rng, True)
            return tuple(outs), new_aux

        if self._do_mirror:
            f = jax.checkpoint(f)
        grad_args = {n: arg_vals[n] for n in grad_names}
        (outs, vjp_fn, new_aux) = jax.vjp(f, grad_args, has_aux=True)
        heads = tuple(jnp.ones(o.shape, o.dtype) for o in outs)
        grads = vjp_fn(heads)[0]
        return list(outs), new_aux, grads

    def _outputs_all_loss_heads(self) -> bool:
        """True when default all-ones head gradients are safe: every
        output is a loss head (custom VJP ignores the head) or a
        BlockGrad (VJP is zero)."""
        from .ops.registry import get_op

        for node, _ in self._symbol._outputs:
            if node.is_variable:
                return False
            op = get_op(node.op)
            if not op.loss_head(node.attrs) and op.name != "BlockGrad":
                return False
        return True

    # ------------------------------------------------------------------
    @property
    def outputs(self) -> List[NDArray]:
        return self.outputs_cache

    def forward(self, is_train: bool = False, **kwargs):
        """reference: MXExecutorForward → GraphExecutor::Forward"""
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError(f"unknown forward argument {k!r}")
            if isinstance(v, NDArray):
                self.arg_dict[k]._set_data(v._data.astype(self.arg_dict[k].dtype))
            else:
                self.arg_dict[k][:] = v
        arg_vals = {n: a._data for n, a in self.arg_dict.items()}
        aux_vals = {n: a._data for n, a in self.aux_dict.items()}
        rng = _random.next_key()
        self._train_snapshot = None
        self._cached_grads = None

        if self._monitor_callback is not None:
            self._run_monitor(arg_vals, aux_vals, rng, is_train)

        t_start = time.perf_counter()
        if is_train and self._grad_names and self._outputs_all_loss_heads():
            # training step on a loss-head graph: run the single fused
            # fwd+bwd program now and cache the grads — backward() then
            # just writes them out, so fwd+bwd costs ONE program run
            tag = "fused_fwd_bwd"
            outs, new_aux, grads = self._jit_fused_ones(arg_vals, aux_vals, rng)
            self._cached_grads = grads
            self._train_snapshot = (arg_vals, aux_vals, rng)
        else:
            tag = "forward/train" if is_train else "forward"
            fn = self._jit_fwd_train if is_train else self._jit_fwd
            outs, new_aux = fn(arg_vals, aux_vals, rng)
            if is_train and self._grad_names:
                # stash the *pristine* inputs + rng so a later
                # backward(out_grads) reproduces this forward exactly
                # (same dropout masks, same pre-update aux)
                self._train_snapshot = (arg_vals, aux_vals, rng)
        if _prof._profiler.running:
            jax.block_until_ready(outs)  # real span, not dispatch time
        self._record_program(tag, t_start, time.perf_counter() - t_start)
        for name, val in new_aux.items():
            self.aux_dict[name]._set_data(val)
        self.outputs_cache = [NDArray(o, self._ctx) for o in outs]
        _engine.sync_if_naive(self.outputs_cache)
        return self.outputs_cache

    def backward(self, out_grads=None):
        """reference: MXExecutorBackward; writes grads per grad_req.

        With no ``out_grads``, consumes the gradients already computed by
        the fused program ``forward(is_train=True)`` ran — fwd+bwd is ONE
        XLA program run.  With explicit ``out_grads``, re-runs the fused
        program on the snapshotted inputs with those head gradients (same
        PRNG key; aux updates discarded — already applied by forward)."""
        if not self._grad_names:
            return
        if self._train_snapshot is None:
            raise MXNetError("backward() called before forward(is_train=True)")
        if out_grads is None:
            grads = self._cached_grads
            if grads is None:
                # graph has non-loss outputs: all-ones heads would sum
                # unrelated gradients into the params (the reference only
                # attaches gradient to loss heads, graph_executor.cc:167)
                raise MXNetError(
                    "backward() without out_grads requires every output to be "
                    "a loss head (SoftmaxOutput/*RegressionOutput/MakeLoss/"
                    "SVMOutput); pass explicit out_grads for non-loss outputs")
        else:
            arg_vals, aux_vals, rng = self._train_snapshot
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            heads = [g._data if isinstance(g, NDArray) else jnp.asarray(g)
                     for g in out_grads]
            if len(heads) != len(self.output_names):
                raise MXNetError(
                    f"out_grads has {len(heads)} entries for "
                    f"{len(self.output_names)} outputs")
            t_start = time.perf_counter()
            _, _, grads = self._jit_fused(arg_vals, aux_vals, rng, heads)
            if _prof._profiler.running:
                jax.block_until_ready(grads)
            self._record_program("backward", t_start,
                                 time.perf_counter() - t_start)
        for name in self._grad_names:
            g = grads[name]
            dst = self.grad_dict[name]
            if self.grad_req[name] == "add":
                dst._set_data(dst._data + g.astype(dst.dtype))
            else:
                dst._set_data(g.astype(dst.dtype))
        _engine.sync_if_naive([self.grad_dict[n] for n in self._grad_names])

    def forward_backward(self, **kwargs):
        """Fused one-program training step (TPU fast path)."""
        outs = self.forward(is_train=True, **kwargs)
        self.backward()
        return outs

    # ------------------------------------------------------------------
    def set_monitor_callback(self, callback):
        """reference: MXExecutorSetMonitorCallback (monitor.py tap)"""
        self._monitor_callback = callback

    def _run_monitor(self, arg_vals, aux_vals, rng, is_train):
        internals = self._symbol.get_internals()
        fn = self._internals_fns.get(bool(is_train))
        if fn is None:
            gfn = build_graph_fn(internals)
            fn = jax.jit(functools.partial(gfn, is_train=bool(is_train)))
            self._internals_fns[bool(is_train)] = fn
        outs, _ = fn(arg_vals, aux_vals, rng)
        for name, val in zip(internals.list_outputs(), outs):
            self._monitor_callback(name, NDArray(val, self._ctx))

    # ------------------------------------------------------------------
    def copy_params_from(self, arg_params, aux_params=None, allow_extra_params=False):
        """reference: executor.py copy_params_from"""
        for k, v in arg_params.items():
            if k in self.arg_dict:
                self.arg_dict[k]._set_data(v._data.astype(self.arg_dict[k].dtype)
                                           if isinstance(v, NDArray) else jnp.asarray(v))
            elif not allow_extra_params:
                raise MXNetError(f"Found name {k!r} not in executor arguments")
        if aux_params:
            for k, v in aux_params.items():
                if k in self.aux_dict:
                    self.aux_dict[k]._set_data(v._data if isinstance(v, NDArray) else jnp.asarray(v))
                elif not allow_extra_params:
                    raise MXNetError(f"Found name {k!r} not in executor aux states")

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Return a new executor with new input shapes (weights shared).
        reference: executor.py reshape.  XLA recompiles per shape and
        caches — the per-bucket executor pattern."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        if arg_shapes is None:
            raise MXNetError("insufficient shapes for reshape")
        new_args = {}
        new_grads = {}
        for name, sh in zip(self.arg_names, arg_shapes):
            old = self.arg_dict[name]
            if tuple(old.shape) == tuple(sh):
                new_args[name] = old
                if name in self.grad_dict:
                    new_grads[name] = self.grad_dict[name]
            else:
                if not partial_shaping and name not in kwargs:
                    raise MXNetError(
                        f"reshape changed shape of {name!r}; pass partial_shaping=True")
                new_args[name] = nd_zeros(sh, self._ctx, old.dtype)
                if name in self.grad_dict:
                    new_grads[name] = nd_zeros(sh, self._ctx, old.dtype)
        new_aux = {}
        for name, sh in zip(self.aux_names, aux_shapes or []):
            old = self.aux_dict[name]
            new_aux[name] = old if tuple(old.shape) == tuple(sh) else nd_zeros(sh, self._ctx, old.dtype)
        return Executor(self._symbol, self._ctx, new_args, new_grads or None,
                        self.grad_req, new_aux or None, group2ctx=self._group2ctx)

    def debug_str(self):
        return self._symbol.debug_str()


def simple_bind(symbol, ctx, grad_req="write", type_dict=None, group2ctx=None,
                shared_exec=None, **kwargs) -> Executor:
    """Allocate all buffers from inferred shapes and bind.

    reference: MXExecutorSimpleBind path used by Module
    (graph_executor.cc:697 Bind + InitArguments).
    """
    arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**kwargs)
    if arg_shapes is None:
        raise MXNetError(f"cannot infer shapes from {kwargs}")
    arg_types, _, aux_types = symbol.infer_type(**(type_dict or {}))
    arg_names = symbol.list_arguments()
    aux_names = symbol.list_auxiliary_states()
    ctx = ctx if isinstance(ctx, Context) else Context(ctx)

    args = {}
    args_grad = {}
    if isinstance(grad_req, str):
        req = {n: grad_req for n in arg_names}
    elif isinstance(grad_req, (list, tuple)):
        req = dict(zip(arg_names, grad_req))
    else:
        req = {n: grad_req.get(n, "null") for n in arg_names}

    # shared_exec: reuse the donor executor's arrays where name+shape+dtype
    # match — the same NDArray *objects*, so params/grads stay one storage
    # across bucketed executors (the reference's shared memory pool,
    # graph_executor.cc:330-334/423-515; inputs differ in shape and get
    # fresh buffers)
    def _reusable(pool, name, shape, dt):
        old = pool.get(name) if pool else None
        if old is not None and tuple(old.shape) == tuple(shape) \
                and old.dtype == np.dtype(dt):
            return old
        return None

    sh_args = shared_exec.arg_dict if shared_exec is not None else None
    sh_grads = shared_exec.grad_dict if shared_exec is not None else None
    sh_aux = shared_exec.aux_dict if shared_exec is not None else None
    for name, shape, dt in zip(arg_names, arg_shapes, arg_types):
        shared = _reusable(sh_args, name, shape, dt)
        args[name] = shared if shared is not None else nd_zeros(shape, ctx, dt)
        if req.get(name, "null") != "null":
            shared = _reusable(sh_grads, name, shape, dt)
            args_grad[name] = (shared if shared is not None
                               else nd_zeros(shape, ctx, dt))
    aux = {}
    for name, shape, dt in zip(aux_names, aux_shapes, aux_types):
        shared = _reusable(sh_aux, name, shape, dt)
        aux[name] = shared if shared is not None else nd_zeros(shape, ctx, dt)
    return Executor(symbol, ctx, args, args_grad or None, req, aux or None,
                    group2ctx=group2ctx, shared_exec=shared_exec)
