"""Custom operator API — write ops in Python, use them in graphs.

Parity with ``python/mxnet/operator.py:396-580`` (CustomOp /
CustomOpProp / register): subclass ``CustomOpProp`` for metadata +
shape/type inference, subclass ``CustomOp`` for forward/backward over
NDArrays, register under a name, then build symbols with
``mx.sym.Custom(..., op_type=name)`` or call ``mx.nd.Custom`` —
exactly the reference workflow.

The execution mapping is TPU-native (``ops/custom.py``): host code
enters the compiled XLA program through ``jax.pure_callback`` and the
gradient flows through ``jax.custom_vjp`` — no C trampoline needed.
"""

from __future__ import annotations

import numpy as np

from .base import MXNetError
from .ops import custom as _custom

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered"]


class CustomOp:
    """Base class for operators implemented in Python (reference:
    operator.py:396 CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        """Override: compute ``out_data`` from ``in_data``.

        req entries are 'null'/'write'/'add'; use ``self.assign``."""
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        """Override: compute ``in_grad`` from ``out_grad``."""
        raise NotImplementedError

    @staticmethod
    def assign(dst, req, src):
        """Assign ``src`` to ``dst`` per the write request."""
        if req == "null":
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src
        else:
            raise MXNetError(f"invalid req {req!r}")


class CustomOpProp:
    """Base class for custom-op metadata (reference: operator.py:442
    CustomOpProp).

    Parameters
    ----------
    need_top_grad : bool
        Whether backward needs the gradient from above (False for
        loss-style ops that produce their own gradient).
    """

    def __init__(self, need_top_grad=False):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        """Default: all inputs/outputs share the first input's shape."""
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        """Default: everything takes the first input's dtype."""
        return ([in_type[0]] * len(self.list_arguments()),
                [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        """Which tensors backward needs (informational here — the
        TPU build always saves inputs+outputs for the VJP)."""
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes=None):
        """Override: return the CustomOp instance."""
        raise NotImplementedError


def register(reg_name):
    """Class decorator registering a CustomOpProp subclass under
    ``reg_name`` (reference: operator.py:554 register)."""

    def do_register(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register() requires a CustomOpProp subclass")
        _custom._PROPS[reg_name] = prop_cls
        # re-registration must not serve stale cached prop instances
        _custom._cached_prop.cache_clear()
        return prop_cls

    return do_register


def get_all_registered():
    return dict(_custom._PROPS)
