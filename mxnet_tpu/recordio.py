"""RecordIO: sequential + indexed record files and the packed-image
record format.

Capability parity with ``python/mxnet/recordio.py`` (273 LoC) and the
dmlc recordio framing it wraps (SURVEY §2.5, §2.9):

- ``MXRecordIO(uri, flag)`` — sequential read/write of length-framed
  byte records (magic 0xced7230a + 29-bit length + 4-byte padding).
- ``MXIndexedRecordIO(idx_path, uri, flag)`` — random access via a
  text index file of ``key\\tbyte_offset`` lines.
- ``IRHeader`` / ``pack`` / ``unpack`` — the image-record header
  ``(flag:u32, label:f32, id:u64, id2:u64)``; ``flag > 0`` means
  ``flag`` float32 labels follow the header
  (``src/io/image_recordio.h:16-78``).
- ``pack_img`` / ``unpack_img`` — JPEG/PNG encode/decode via cv2.

The byte-level framing runs in native C++ (``native/recordio.cc``)
when built, with an identical pure-Python fallback; both produce the
same bytes.
"""

from __future__ import annotations

import ctypes
import os
import struct
from collections import namedtuple

import numpy as np

from . import _native
from .base import MXNetError

try:
    import cv2
except ImportError:  # pragma: no cover - cv2 is in the base image
    cv2 = None

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader",
           "pack", "unpack", "pack_img", "unpack_img"]

_MAGIC = 0xced7230a
_LEN_MASK = (1 << 29) - 1


class MXRecordIO:
    """Sequential record file reader/writer.

    Parameters
    ----------
    uri : str
        Path to the record file.
    flag : str
        'r' for reading, 'w' for writing.
    """

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.is_open = False
        self._native = None   # ctypes handle when the C++ library is used
        self._fp = None       # python-fallback file object
        self.open()

    def open(self):
        if self.flag == "w":
            self.writable = True
        elif self.flag == "r":
            self.writable = False
        else:
            raise ValueError(f"Invalid flag {self.flag}")
        lib = _native.lib()
        if lib is not None:
            create = (lib.MXTPURecordIOWriterCreate if self.writable
                      else lib.MXTPURecordIOReaderCreate)
            h = create(self.uri.encode())
            if not h:
                raise MXNetError(f"cannot open {self.uri!r}")
            self._native = h
        else:
            self._fp = open(self.uri, "wb" if self.writable else "rb")
        self.is_open = True

    def close(self):
        if not self.is_open:
            return
        if self._native is not None:
            lib = _native.lib()
            free = (lib.MXTPURecordIOWriterFree if self.writable
                    else lib.MXTPURecordIOReaderFree)
            free(self._native)
            self._native = None
        if self._fp is not None:
            self._fp.close()
            self._fp = None
        self.is_open = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def reset(self):
        """Reposition to the first record ('w' truncates the file)."""
        self.close()
        self.open()

    def tell(self):
        if self._native is not None:
            lib = _native.lib()
            fn = (lib.MXTPURecordIOWriterTell if self.writable
                  else lib.MXTPURecordIOReaderTell)
            return fn(self._native)
        return self._fp.tell()

    def seek(self, offset):
        assert not self.writable
        if self._native is not None:
            if _native.lib().MXTPURecordIOReaderSeek(self._native, offset) != 0:
                raise MXNetError(f"seek({offset}) failed on {self.uri!r}")
        else:
            self._fp.seek(offset)

    def write(self, buf):
        """Append one record (bytes)."""
        assert self.writable
        if len(buf) > _LEN_MASK:
            raise MXNetError("record too large (max 2^29-1 bytes)")
        if self._native is not None:
            rc = _native.lib().MXTPURecordIOWriterWrite(
                self._native, buf, len(buf))
            if rc != 0:
                raise MXNetError(f"write failed on {self.uri!r}")
            return
        self._fp.write(struct.pack("<II", _MAGIC, len(buf)))
        self._fp.write(buf)
        pad = (-len(buf)) % 4
        if pad:
            self._fp.write(b"\x00" * pad)

    def read(self):
        """Read the next record; None at end of file."""
        assert not self.writable
        if self._native is not None:
            size = ctypes.c_uint64()
            ptr = _native.lib().MXTPURecordIOReaderRead(
                self._native, ctypes.byref(size))
            if not ptr:
                if size.value == ctypes.c_uint64(-1).value:
                    raise MXNetError(f"corrupt record file {self.uri!r}")
                return None
            return ctypes.string_at(ptr, size.value)
        head = self._fp.read(8)
        if not head:
            return None
        if len(head) != 8:
            raise MXNetError(f"corrupt record file {self.uri!r}")
        magic, lrec = struct.unpack("<II", head)
        if magic != _MAGIC or (lrec >> 29) != 0:
            raise MXNetError(f"corrupt record file {self.uri!r}")
        length = lrec & _LEN_MASK
        padded = (length + 3) & ~3
        body = self._fp.read(padded)
        if len(body) != padded:
            raise MXNetError(f"corrupt record file {self.uri!r}")
        return body[:length]


def read_idx_file(idx_path, key_type=int):
    """Parse a ``key\\toffset`` index file → (keys list, {key: offset})."""
    keys, idx = [], {}
    with open(idx_path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) != 2:
                continue
            key = key_type(parts[0])
            idx[key] = int(parts[1])
            keys.append(key)
    return keys, idx


class MXIndexedRecordIO(MXRecordIO):
    """Record file with a ``key\\toffset`` text index for random access."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if not self.writable and os.path.isfile(idx_path):
            self.keys, self.idx = read_idx_file(idx_path, key_type)

    def close(self):
        if self.is_open and self.writable:
            with open(self.idx_path, "w") as f:
                for key in self.keys:
                    f.write(f"{key}\t{self.idx[key]}\n")
        super().close()

    def seek_idx(self, idx):
        """Position the reader at record ``idx``."""
        self.seek(self.idx[idx])

    def read_idx(self, idx):
        """Random-access read of record ``idx``."""
        self.seek_idx(idx)
        return self.read()

    def write_idx(self, idx, buf):
        """Append a record under key ``idx``."""
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


# Image-record header; layout matches src/io/image_recordio.h:16-40.
IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack ``IRHeader`` + byte payload into one record string.

    header.label may be a scalar or a float vector; a vector is stored
    after the header with ``flag`` set to its length
    (``image_recordio.h:61-78`` Load()).
    """
    label = header.label
    if not isinstance(label, (int, float, np.floating, np.integer)):
        label = np.asarray(label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0.0)
        s = label.tobytes() + s
    return struct.pack(_IR_FORMAT, *header) + s


def unpack(s):
    """Inverse of :func:`pack` → (IRHeader, payload bytes)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:4 * header.flag], dtype=np.float32)
        header = header._replace(label=label)
        s = s[4 * header.flag:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode an HWC uint8 image and pack it with the header."""
    assert cv2 is not None, "pack_img requires cv2"
    if img_fmt in (".jpg", ".jpeg"):
        encode_params = [cv2.IMWRITE_JPEG_QUALITY, quality]
    elif img_fmt == ".png":
        encode_params = [cv2.IMWRITE_PNG_COMPRESSION, quality]
    else:
        encode_params = None
    ret, buf = cv2.imencode(img_fmt, img, encode_params)
    assert ret, "failed to encode image"
    return pack(header, buf.tobytes())


def unpack_img(s, iscolor=-1):
    """Inverse of :func:`pack_img` → (IRHeader, HWC uint8 image)."""
    assert cv2 is not None, "unpack_img requires cv2"
    header, s = unpack(s)
    img = cv2.imdecode(np.frombuffer(s, dtype=np.uint8), iscolor)
    return header, img


def read_batch(uri, offsets, threads=4):
    """Read the records at the given byte offsets in one native call.

    The C++ side fetches all payloads with an internal thread pool
    (one call per batch instead of per-record Python seek+read).
    Returns a list of ``bytes``; raises on a corrupt record.  Falls
    back to per-record Python reads without the native library.
    """
    lib = _native.lib()
    n = len(offsets)
    if lib is not None and n:
        arr = (ctypes.c_int64 * n)(*[int(o) for o in offsets])
        h = lib.MXTPUBatchRead(uri.encode(), arr, n, int(threads))
        if not h:
            raise MXNetError(f"cannot open {uri!r}")
        try:
            sizes = lib.MXTPUBatchSizes(h)
            starts = lib.MXTPUBatchStarts(h)
            data = lib.MXTPUBatchData(h)
            out = []
            for i in range(n):
                if sizes[i] < 0:
                    raise MXNetError(
                        f"corrupt record at offset {offsets[i]} in {uri!r}")
                if sizes[i] == 0:
                    out.append(b"")  # data ptr may be null when all-empty
                else:
                    out.append(ctypes.string_at(data + starts[i], sizes[i]))
            return out
        finally:
            lib.MXTPUBatchFree(h)
    rec = MXRecordIO(uri, "r")
    try:
        out = []
        for o in offsets:
            rec.seek(int(o))
            s = rec.read()
            if s is None:
                raise MXNetError(
                    f"corrupt record at offset {o} in {uri!r}")
            out.append(s)
        return out
    finally:
        rec.close()


def list_records(uri):
    """Byte offsets of every record in ``uri`` (native fast path)."""
    lib = _native.lib()
    if lib is not None:
        n = lib.MXTPURecordIOScan(uri.encode(), None, 0)
        if n < 0:
            raise MXNetError(f"corrupt record file {uri!r}")
        buf = (ctypes.c_int64 * max(n, 1))()
        lib.MXTPURecordIOScan(uri.encode(), buf, n)
        return list(buf[:n])
    offsets = []
    rec = MXRecordIO(uri, "r")
    try:
        while True:
            pos = rec.tell()
            if rec.read() is None:
                break
            offsets.append(pos)
    finally:
        rec.close()
    return offsets
