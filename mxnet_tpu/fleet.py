"""Multi-replica serving fleet: elastic router, admission control,
zero-downtime weight swap.

One engine process serves one chip's worth of streams and dies whole:
a crash drops every in-flight request, and a weight update means
downtime.  This module is the fleet tier above ``serving.py`` —
Orca-style iteration-level serving extended from one scheduler to a
routed fleet:

* the **Router** speaks the ``wire.py`` length-prefixed frame protocol
  (the ``ps.py`` wire — shared primitives, shared HMAC discipline for
  structured payloads) to clients, and spreads requests over N engine
  **replicas**, each a process wrapping an ``InferenceEngine`` or
  ``DecodeEngine`` behind a :class:`ReplicaHarness`;
* **health** is the PR-8 heartbeat-file machinery re-used verbatim:
  every replica runs an ``elastic.HeartbeatWriter``, the router's
  monitor runs the ``elastic.stale_ids`` staleness scan (missing or
  stale = dead, future mtimes = alive), and a transport failure is
  cross-checked against staleness before conviction;
* a dead replica's in-flight requests are transparently **retried** on
  a survivor.  Exactly-once is the PR-3 ticket discipline applied at
  the delivery edge: a ticket retires only when its response reaches
  the client, a retry is dispatched only for unretired tickets, and a
  zombie's late answer finds its ticket retired and is dropped
  (counted, never double-delivered).  Decode retries are **bit-exact**:
  the router stamps every decode request with a deterministic sampling
  seed, and replicas share the engine seed, so a survivor re-samples
  exactly the tokens the dead replica would have produced — no
  already-delivered token is ever re-sampled differently;
* **admission control + deadline shedding**: the router tracks
  per-replica queue depth and a PR-1-style learned per-bucket cost
  model (EMA of measured service time per work-unit bucket).  A
  request that provably cannot meet its deadline fails with a typed
  :class:`ShedError`; under overload the pending queue sheds
  oldest-deadline-first instead of letting p99 run away;
* :meth:`Router.swap_weights` is the **zero-downtime rolling update**:
  replicas drain one at a time (the rest keep serving), load the
  newest committed, checksum-verified checkpoint
  (``checkpoint.load_latest_params`` — a training run's checkpoint
  root or a ``checkpoint.publish_params`` output), warm up, and
  re-admit.  A swap drops zero requests.

Wire security matches ``ps.py``: tensor frames are never pickled, and
every structured control payload (drain/swap/stop) carries an
HMAC-SHA256 keyed by the launcher-distributed secret, verified before
parsing.

See README "Multi-replica serving" for the architecture diagram and
failure model; ``tools/bench_fleet.py`` runs the closed-loop sweep and
the kill-one-replica acceptance drill.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import socketserver
import struct
import subprocess
import sys
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import adapters as _adapters
from . import profiler
from . import slo as _slo
from . import wire
from .base import MXNetError
from .elastic import (HeartbeatWriter, dead_rank_timeout,
                      heartbeat_interval, stale_ids, _validated_env)

__all__ = ["Router", "FleetClient", "ShedError", "ReplicaClient",
           "ReplicaServer", "spawn_replica", "launch_local_fleet",
           "read_endpoint", "write_secret", "read_secret"]

# fleet wire ops (a separate op space from ps.py: different servers,
# same framing)
(_F_SUBMIT, _F_RESULT, _F_CTRL, _F_CTRL_RESULT,
 _F_MIGRATE, _F_ADAPTER) = range(101, 107)

# disaggregated-serving replica roles ("mixed" = the classic
# do-everything replica); the fleet is DISAGGREGATED the moment both
# specialized roles are present
REPLICA_ROLES = ("prefill", "decode", "mixed")

# result status bytes
_ST_OK, _ST_ERR, _ST_SHED = 0, 1, 2

_K_INFER, _K_DECODE = 0, 1
_NO_EOS = -(1 << 62)

_log = logging.getLogger("mxnet_tpu.fleet")

# ShedError-burst flight-recorder trigger: this many sheds inside the
# window = one post-mortem dump (rate-limited in dump_flight_record)
_SHED_BURST_COUNT = 32
_SHED_BURST_WINDOW_S = 10.0


class ShedError(MXNetError):
    """Typed admission-control rejection: the router determined this
    request cannot (or should not) be served within its deadline —
    shed NOW so the client can fail over / degrade, instead of
    discovering the miss after the deadline already passed.  Carries
    ``reason`` ('deadline' | 'expired' | 'overload')."""

    def __init__(self, msg: str, reason: str = "deadline"):
        self.reason = reason
        super().__init__(msg)


def fleet_env(name: str):
    """MXNET_FLEET_* with loud at-construction validation (the
    MXNET_CKPT_* pattern): garbage raises, defaults resolve through
    the config catalog."""
    minima = {"MXNET_FLEET_REPLICAS": 1,
              "MXNET_FLEET_SHED_DEADLINE_MS": 0.0,
              "MXNET_FLEET_RETRY_BUDGET": 0,
              "MXNET_FLEET_SWAP_DRAIN_TIMEOUT": 0.1,
              "MXNET_FLEET_AUTOSCALE": 0,
              "MXNET_FLEET_AUTOSCALE_INTERVAL": 0.05}
    return _validated_env(name, minimum=minima[name])


def roles_env() -> Optional[List[str]]:
    """``MXNET_FLEET_ROLES`` — comma-separated initial role per replica
    (by rid order), e.g. ``prefill,decode,decode``.  Empty/unset =
    roles never enabled (the classic mixed fleet).  Garbage raises at
    construction, and a split that names one specialized role without
    its counterpart is refused: a prefill-only fleet can never decode,
    and vice versa."""
    raw = os.environ.get("MXNET_FLEET_ROLES", "").strip()
    if not raw:
        return None
    roles = [tok.strip() for tok in raw.split(",")]
    for tok in roles:
        if tok not in REPLICA_ROLES:
            raise MXNetError(
                f"MXNET_FLEET_ROLES={raw!r}: role {tok!r} must be one "
                f"of {REPLICA_ROLES}")
    if ("prefill" in roles) != ("decode" in roles):
        raise MXNetError(
            f"MXNET_FLEET_ROLES={raw!r}: a disaggregated fleet needs "
            "BOTH a prefill and a decode role (or neither) — a "
            "one-sided split cannot serve a single request end to end")
    return roles


# ---------------------------------------------------------------------------
# spec <-> wire
# ---------------------------------------------------------------------------


def _pack_spec(spec: Dict[str, Any]) -> bytes:
    """Request payload: tensors ride the wire encoding, never pickle."""
    if spec["kind"] == "infer":
        inputs = spec["inputs"]
        if len(inputs) > 0xFFFF:
            raise MXNetError("too many inputs for one request")
        body = bytearray([_K_INFER])
        body += struct.pack("!H", len(inputs))
        for name, arr in inputs.items():
            body += wire.pack_key(name)
            body += wire.pack_tensor(np.asarray(arr))
        return bytes(body)
    if spec["kind"] == "decode":
        body = bytearray([_K_DECODE])
        body += wire.U32.pack(int(spec["max_new"]))
        temp = spec.get("temperature")
        body += struct.pack("!d", -1.0 if temp is None else float(temp))
        eos = spec.get("eos")
        body += wire.I64.pack(_NO_EOS if eos is None else int(eos))
        body += wire.U64.pack(int(spec.get("seed", 0)))
        # disagg phase byte: 0 = classic end-to-end decode, 1 =
        # prefill-export (the response is a signed KV page frame)
        body += struct.pack("!B", 1 if spec.get("phase") == 1 else 0)
        # tenancy triplet (PR 20): SLO class rides to the replica so
        # engine-side admission can tier; tenant/adapter name the
        # quota bucket and the LoRA slot ("" = not set)
        body += wire.pack_key(spec.get("slo_class") or "interactive")
        body += wire.pack_key(spec.get("tenant") or "")
        body += wire.pack_key(spec.get("adapter") or "")
        body += wire.pack_tensor(
            np.asarray(spec["prompt"], dtype=np.int32))
        return bytes(body)
    raise MXNetError(f"unknown request kind {spec['kind']!r}")


def _unpack_spec(buf: memoryview, off: int) -> Dict[str, Any]:
    kind = buf[off]
    off += 1
    if kind == _K_INFER:
        (n,) = struct.unpack_from("!H", buf, off)
        off += 2
        inputs = {}
        for _ in range(n):
            name, off = wire.unpack_key(buf, off)
            arr, off = wire.unpack_tensor(buf, off)
            inputs[name] = np.array(arr)  # own the buffer
        return {"kind": "infer", "inputs": inputs}
    if kind == _K_DECODE:
        (max_new,) = wire.U32.unpack_from(buf, off)
        off += 4
        (temp,) = struct.unpack_from("!d", buf, off)
        off += 8
        (eos,) = wire.I64.unpack_from(buf, off)
        off += 8
        (seed,) = wire.U64.unpack_from(buf, off)
        off += 8
        phase = buf[off]
        off += 1
        slo_class, off = wire.unpack_key(buf, off)
        tenant, off = wire.unpack_key(buf, off)
        adapter, off = wire.unpack_key(buf, off)
        prompt, off = wire.unpack_tensor(buf, off)
        return {"kind": "decode", "prompt": np.array(prompt),
                "max_new": int(max_new),
                "temperature": None if temp < 0 else float(temp),
                "eos": None if eos == _NO_EOS else int(eos),
                "seed": int(seed), "phase": int(phase),
                "slo_class": slo_class or "interactive",
                "tenant": tenant or None,
                "adapter": adapter or None}
    raise MXNetError(f"unknown wire request kind {kind}")


def _pack_result(result) -> bytes:
    """infer → list of output arrays; decode → one int32 token array."""
    if isinstance(result, np.ndarray):
        result = [result]
    if len(result) > 0xFFFF:
        raise MXNetError("too many outputs for one response")
    body = bytearray(struct.pack("!H", len(result)))
    for arr in result:
        body += wire.pack_tensor(np.asarray(arr))
    return bytes(body)


def _unpack_result(buf: memoryview, off: int) -> List[np.ndarray]:
    (n,) = struct.unpack_from("!H", buf, off)
    off += 2
    out = []
    for _ in range(n):
        arr, off = wire.unpack_tensor(buf, off)
        out.append(np.array(arr))
    return out


# ---------------------------------------------------------------------------
# duplex connection: frames tagged by request id, responses out of order
# ---------------------------------------------------------------------------


class _Duplex:
    """One socket, many in-flight requests.  Unlike the PS client's
    FIFO ticket pipeline (one server thread per connection answers in
    order), fleet responses complete OUT of order — a decode retires
    whenever its stream does — so every frame carries a request id and
    a reader thread matches responses to futures."""

    def __init__(self, sock: socket.socket, name: str):
        self._sock = sock
        self._name = name
        self._wlock = threading.Lock()
        self._lock = threading.Lock()
        self._futures: Dict[int, Future] = {}
        self._next_id = 0
        self._dead: Optional[BaseException] = None
        self._on_death = None  # callback(exc), set before start()
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"mxnet_tpu-fleet-{name}")

    def start(self):
        self._reader.start()

    def begin(self, op: int, body: bytes, parse, tear=None) -> Future:
        """Send ``op | req_id | body``; the Future resolves with
        ``parse(status, payload_view)`` when the matching response
        arrives.  A dead connection fails ALL outstanding futures.

        ``tear``: optional chaos hook ``tear(sock, frame) -> bool`` —
        when it returns True it has destroyed the connection mid-frame
        (half the bytes sent, socket shut down); the send is treated
        as a transport death, exactly like a peer crashing mid-write."""
        fut: Future = Future()
        with self._lock:
            if self._dead is not None:
                raise MXNetError(
                    f"fleet connection {self._name} is dead: "
                    f"{self._dead}") from self._dead
            rid = self._next_id
            self._next_id += 1
            self._futures[rid] = fut
        fut._fleet_parse = parse  # type: ignore[attr-defined]
        frame = bytes([op]) + wire.U64.pack(rid) + body
        try:
            with self._wlock:
                if tear is not None and tear(self._sock, frame):
                    raise ConnectionError(
                        "chaos: migration frame torn mid-send")
                wire.send_frame(self._sock, frame)
        except BaseException as exc:
            self._poison(exc)
            raise
        return fut

    def _read_loop(self):
        try:
            while True:
                resp = wire.recv_frame(self._sock)
                (rid,) = wire.U64.unpack_from(resp, 1)
                status = resp[9]
                with self._lock:
                    fut = self._futures.pop(rid, None)
                if fut is None:
                    continue  # cancelled/unknown — drop
                parse = getattr(fut, "_fleet_parse", None)
                try:
                    val = parse(status, memoryview(resp)[10:])
                except BaseException as exc:  # noqa: BLE001
                    if fut.set_running_or_notify_cancel():
                        fut.set_exception(exc)
                    continue
                if fut.set_running_or_notify_cancel():
                    if isinstance(val, BaseException):
                        fut.set_exception(val)
                    else:
                        fut.set_result(val)
        except BaseException as exc:  # noqa: BLE001 — poison and exit
            self._poison(exc)

    def _poison(self, exc: BaseException):
        with self._lock:
            if self._dead is None:
                self._dead = exc
            futures, self._futures = self._futures, {}
        for fut in futures.values():
            if fut.set_running_or_notify_cancel():
                fut.set_exception(ConnectionError(
                    f"fleet connection {self._name} died: {exc}"))
        cb = self._on_death
        if cb is not None:
            try:
                cb(exc)
            except Exception:  # noqa: BLE001 — observer only
                pass

    @property
    def dead(self) -> Optional[BaseException]:
        return self._dead

    def close(self):
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


def _parse_submit_response(status: int, payload: memoryview):
    if status == _ST_OK:
        return _unpack_result(payload, 0)
    msg = bytes(payload).decode(errors="replace")
    if status == _ST_SHED:
        head, _, detail = msg.partition(":")
        return ShedError(detail.strip() or msg, reason=head or "deadline")
    return MXNetError(msg)


def _make_page_frame_parser(secret: bytes):
    """Response parser for a phase-1 (prefill-export) submit: the ok
    payload is one signed KV page frame.  The router verifies it here
    and keeps the RAW bytes too — the forward to the decode replica
    ships the already-signed frame verbatim (same fleet secret), so a
    megabyte of page slabs is never re-encoded in the hot handoff."""

    def parse(status: int, payload: memoryview):
        if status == _ST_OK:
            frame = bytes(payload)
            meta, arrays = wire.unpack_page_frame(
                secret, memoryview(frame), "migration frame (prefill)")
            return {"meta": meta, "arrays": arrays, "frame": frame}
        return _parse_submit_response(status, payload)

    return parse


# ---------------------------------------------------------------------------
# replica side: TCP server over a ReplicaHarness
# ---------------------------------------------------------------------------


class ReplicaServer:
    """Serve ONE :class:`serving.ReplicaHarness` on the fleet wire.

    SUBMIT frames feed the engine; the response frame is written from
    the engine future's done-callback (out-of-order completion — a
    per-connection write lock keeps frames whole).  CTRL frames
    (signed JSON: drain / resume / swap / inflight / stats / stop) run
    on a worker thread so a long drain never stalls the response
    stream it is waiting on.  The server heartbeats
    ``<fleet_dir>/hb_<rid>`` — the PR-8 liveness plane."""

    def __init__(self, harness, rid: int, fleet_dir: Optional[str] = None,
                 secret: bytes = b"", host: str = "127.0.0.1",
                 port: int = 0):
        self.harness = harness
        self.rid = int(rid)
        self._secret = secret
        self._closing = threading.Event()
        self._hb = None
        if fleet_dir:
            self._hb = HeartbeatWriter(fleet_dir, self.rid,
                                       chaos_ident=self.rid)
        server_self = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                wlock = threading.Lock()
                try:
                    while True:
                        req = wire.recv_frame(self.request)
                        server_self._dispatch(req, self.request, wlock)
                except (ConnectionError, EOFError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"mxnet_tpu-fleet-replica-{rid}")
        self._thread.start()

    def _send(self, sock, wlock, op: int, rid: int, status: int,
              payload: bytes):
        frame = bytes([op]) + wire.U64.pack(rid) + bytes([status]) \
            + payload
        try:
            with wlock:
                wire.send_frame(sock, frame)
        except OSError:
            pass  # connection died; the router convicts via heartbeat

    def _dispatch(self, buf: memoryview, sock, wlock):
        op = buf[0]
        (rid,) = wire.U64.unpack_from(buf, 1)
        if op == _F_SUBMIT:
            try:
                # optional trace field first (PR 12): the router's
                # span becomes the parent of this replica's spans
                trace, off = wire.unpack_trace(buf, 9)
                if trace is not None:
                    profiler.trace_point(
                        "wire.recv", trace.child(), cat="fleet",
                        args={"rid": self.rid})
                spec = _unpack_spec(buf, off)
                prefill = spec["kind"] == "decode" and spec.get("phase")
                if spec["kind"] == "infer":
                    fut = self.harness.submit_infer(spec["inputs"],
                                                    trace=trace)
                elif prefill:
                    fut = self.harness.submit_prefill_export(
                        spec["prompt"], spec["max_new"],
                        temperature=spec["temperature"],
                        eos_id=spec["eos"], seed=spec["seed"],
                        trace=trace,
                        slo_class=spec.get("slo_class",
                                           "interactive"),
                        tenant=spec.get("tenant"),
                        adapter=spec.get("adapter"))
                else:
                    fut = self.harness.submit_decode(
                        spec["prompt"], spec["max_new"],
                        temperature=spec["temperature"],
                        eos_id=spec["eos"], seed=spec["seed"],
                        trace=trace,
                        slo_class=spec.get("slo_class",
                                           "interactive"),
                        tenant=spec.get("tenant"),
                        adapter=spec.get("adapter"))
            except BaseException as exc:  # noqa: BLE001 — to the wire
                self._send(sock, wlock, _F_RESULT, rid, _ST_ERR,
                           f"{type(exc).__name__}: {exc}".encode())
                return

            def done(f, _rid=rid, _prefill=prefill):
                exc = f.exception()
                if exc is not None:
                    self._send(sock, wlock, _F_RESULT, _rid, _ST_ERR,
                               f"{type(exc).__name__}: {exc}".encode())
                elif _prefill:
                    # the result is a migration payload: sign it whole
                    # (meta AND slabs) — the router forwards these
                    # bytes verbatim to the decode-role replica
                    pay = f.result()
                    self._send(sock, wlock, _F_RESULT, _rid, _ST_OK,
                               wire.pack_page_frame(
                                   self._secret, pay["meta"],
                                   pay["kv_arrays"]))
                else:
                    self._send(sock, wlock, _F_RESULT, _rid, _ST_OK,
                               _pack_result(f.result()))

            fut.add_done_callback(done)
            return
        if op == _F_MIGRATE:
            try:
                trace, off = wire.unpack_trace(buf, 9)
                if trace is not None:
                    profiler.trace_point(
                        "wire.recv", trace.child(), cat="fleet",
                        args={"rid": self.rid, "op": "migrate"})
                meta, arrays = wire.unpack_page_frame(
                    self._secret, buf[off:], "migration frame (import)")
                fut = self.harness.submit_import(meta, arrays,
                                                 trace=trace)
            except BaseException as exc:  # noqa: BLE001 — to the wire
                self._send(sock, wlock, _F_RESULT, rid, _ST_ERR,
                           f"{type(exc).__name__}: {exc}".encode())
                return

            def mig_done(f, _rid=rid):
                exc = f.exception()
                if exc is not None:
                    self._send(sock, wlock, _F_RESULT, _rid, _ST_ERR,
                               f"{type(exc).__name__}: {exc}".encode())
                else:
                    self._send(sock, wlock, _F_RESULT, _rid, _ST_OK,
                               _pack_result(f.result()))

            fut.add_done_callback(mig_done)
            return
        if op == _F_ADAPTER:
            # hot LoRA publish: tensors ride the signed page-frame
            # encoding (never pickle, same HMAC discipline as
            # migration payloads); runs inline — a slab write is
            # milliseconds and must not race a second publish of the
            # same name through another thread
            try:
                _trace, off = wire.unpack_trace(buf, 9)
                meta, arrays = wire.unpack_page_frame(
                    self._secret, buf[off:], "adapter frame (publish)")
                if len(arrays) != 2:
                    raise MXNetError(
                        f"adapter frame carries {len(arrays)} arrays; "
                        "expected [a, b]")
                slot = self.harness.publish_adapter(
                    meta["name"], arrays[0], arrays[1],
                    alpha=meta.get("alpha"))
                self._send(sock, wlock, _F_RESULT, rid, _ST_OK,
                           json.dumps({"slot": int(slot)}).encode())
            except BaseException as exc:  # noqa: BLE001 — to the wire
                self._send(sock, wlock, _F_RESULT, rid, _ST_ERR,
                           f"{type(exc).__name__}: {exc}".encode())
            return
        if op == _F_CTRL:
            try:
                _trace, off = wire.unpack_trace(buf, 9)
                spec, _ = wire.unpack_signed_json(
                    self._secret, buf, off, "fleet control frame")
            except BaseException as exc:  # noqa: BLE001 — to the wire
                self._send(sock, wlock, _F_CTRL_RESULT, rid, _ST_ERR,
                           f"{type(exc).__name__}: {exc}".encode())
                return
            threading.Thread(
                target=self._ctrl, args=(spec, rid, sock, wlock),
                daemon=True,
                name=f"mxnet_tpu-fleet-ctrl-{spec.get('op')}").start()
            return
        self._send(sock, wlock, _F_RESULT, rid, _ST_ERR,
                   f"unknown fleet op {op}".encode())

    def _ctrl(self, spec: Dict, rid: int, sock, wlock):
        try:
            op = spec.get("op")
            if op == "inflight":
                out: Any = {"inflight": self.harness.inflight()}
            elif op == "stats":
                out = self.harness.stats()
            elif op == "drain":
                out = {"inflight": self.harness.drain(
                    timeout=float(spec.get("timeout", 30.0)))}
            elif op == "resume":
                self.harness.resume()
                out = {"ok": True}
            elif op == "swap":
                out = self.harness.swap(
                    spec["ckpt_dir"],
                    drain_timeout=float(spec.get("drain_timeout", 60.0)))
            elif op == "role":
                self.harness.set_role(spec["role"])
                out = {"ok": True, "role": spec["role"]}
            elif op == "retire_adapter":
                out = {"freed": bool(
                    self.harness.retire_adapter(spec["name"]))}
            elif op == "stop":
                out = {"ok": True}
                self._closing.set()
            else:
                raise MXNetError(f"unknown fleet control op {op!r}")
            self._send(sock, wlock, _F_CTRL_RESULT, rid, _ST_OK,
                       json.dumps(out).encode())
        except BaseException as exc:  # noqa: BLE001 — to the wire
            self._send(sock, wlock, _F_CTRL_RESULT, rid, _ST_ERR,
                       f"{type(exc).__name__}: {exc}".encode())
        if self._closing.is_set():
            self.close()

    def wait_closed(self, timeout: Optional[float] = None) -> bool:
        return self._closing.wait(timeout)

    def close(self):
        self._closing.set()
        threading.Thread(target=self._server.shutdown,
                         daemon=True).start()
        self._server.server_close()
        if self._hb is not None:
            self._hb.stop(remove=True)
        self.harness.close()


class ReplicaClient:
    """Router-side handle to a (remote) replica: the duck type the
    Router schedules over — in-process fakes in the tests implement
    the same surface without a socket."""

    def __init__(self, rid: int, host: str, port: int,
                 secret: bytes = b"", timeout: float = 30.0):
        self.rid = int(rid)
        t0 = time.monotonic()
        while True:
            try:
                sock = socket.create_connection((host, port), timeout=10)
                break
            except OSError:
                if time.monotonic() - t0 > timeout:
                    raise MXNetError(
                        f"cannot reach replica {rid} at {host}:{port}")
                time.sleep(0.1)
        sock.settimeout(None)
        self._secret = secret
        self._dx = _Duplex(sock, f"replica-{rid}")
        self._dx.start()

    def set_on_death(self, cb):
        self._dx._on_death = cb

    @property
    def transport_dead(self) -> Optional[BaseException]:
        return self._dx.dead

    def submit(self, spec: Dict[str, Any]) -> Future:
        # "trace" is router metadata, not request payload: it rides
        # the frame's optional trace field, never the spec encoding
        trace = spec.get("trace")
        if trace is not None:
            spec = {k: v for k, v in spec.items() if k != "trace"}
        if spec["kind"] == "migrate":
            return self._submit_migrate(spec, trace)
        body = wire.pack_trace(trace) + _pack_spec(spec)
        parse = (_make_page_frame_parser(self._secret)
                 if spec["kind"] == "decode" and spec.get("phase")
                 else _parse_submit_response)
        t0 = time.perf_counter()
        fut = self._dx.begin(_F_SUBMIT, body, parse)
        if trace is not None:
            profiler.add_trace_event(
                "wire.send", t0, time.perf_counter() - t0,
                trace.child(), cat="fleet",
                args={"rid": self.rid, "bytes": len(body)})
        return fut

    def _submit_migrate(self, spec: Dict[str, Any], trace) -> Future:
        """Phase 2: forward the prefill replica's already-signed page
        frame to this (decode-role) replica.  The Future resolves to
        the FULL generated token list once the migrated stream retires
        there.  ``MXNET_CHAOS_MIGRATION_TEAR`` hooks THIS send — the
        drill tears the Nth migration frame mid-flight and the ticket
        must resolve through the exactly-once retry (re-prefill)."""
        from . import chaos as _chaos

        body = wire.pack_trace(trace) + spec["frame"]
        t0 = time.perf_counter()
        ch = _chaos.get_chaos()
        tear = ch.torn_migration_send if ch is not None else None
        fut = self._dx.begin(_F_MIGRATE, body, _parse_submit_response,
                             tear=tear)
        if trace is not None:
            profiler.add_trace_event(
                "wire.send", t0, time.perf_counter() - t0,
                trace.child(), cat="fleet",
                args={"rid": self.rid, "bytes": len(body),
                      "op": "migrate"})
        return fut

    def set_role(self, role: str) -> Dict:
        return self._ctrl({"op": "role", "role": role})

    def publish_adapter(self, name, a, b, alpha=None) -> int:
        """Hot LoRA publish over the wire: the (A, B) slabs ride the
        signed page-frame encoding (no drain on the replica — see
        :meth:`ReplicaHarness.publish_adapter`).  Returns the slot."""
        meta = {"name": str(name),
                "alpha": None if alpha is None else float(alpha)}
        body = wire.pack_trace(None) + wire.pack_page_frame(
            self._secret, meta, [np.asarray(a), np.asarray(b)])

        def parse(status, payload):
            if status != _ST_OK:
                return MXNetError(
                    bytes(payload).decode(errors="replace"))
            return json.loads(bytes(payload).decode())

        return int(self._dx.begin(_F_ADAPTER, body, parse)
                   .result(300.0)["slot"])

    def retire_adapter(self, name) -> bool:
        return bool(self._ctrl({"op": "retire_adapter",
                                "name": str(name)})["freed"])

    def _ctrl(self, obj: Dict, timeout: float = 120.0) -> Dict:
        def parse(status, payload):
            if status != _ST_OK:
                return MXNetError(bytes(payload).decode(errors="replace"))
            return json.loads(bytes(payload).decode())

        body = wire.pack_trace(None) \
            + wire.pack_signed_json(self._secret, obj)
        return self._dx.begin(_F_CTRL, body, parse).result(timeout)

    def inflight(self) -> int:
        return int(self._ctrl({"op": "inflight"})["inflight"])

    def drain(self, timeout: float = 30.0) -> int:
        return int(self._ctrl({"op": "drain", "timeout": timeout},
                              timeout=timeout + 30.0)["inflight"])

    def resume(self):
        self._ctrl({"op": "resume"})

    def swap(self, ckpt_dir: str, drain_timeout: float = 60.0) -> Dict:
        # warmup recompiles every bucket — allow it generous wall time
        return self._ctrl({"op": "swap", "ckpt_dir": ckpt_dir,
                           "drain_timeout": drain_timeout},
                          timeout=drain_timeout + 1800.0)

    def stats(self) -> Dict:
        return self._ctrl({"op": "stats"})

    def stop(self):
        try:
            self._ctrl({"op": "stop"}, timeout=10.0)
        except Exception:  # noqa: BLE001 — best effort
            pass

    def close(self):
        self._dx.close()


# ---------------------------------------------------------------------------
# replica process launch
# ---------------------------------------------------------------------------


def write_secret(fleet_dir: str, secret: bytes) -> str:
    """Persist the wire secret for replica processes (0600 — the
    membership-ledger convention for key material)."""
    os.makedirs(fleet_dir, exist_ok=True)
    path = os.path.join(fleet_dir, "secret")
    from .checkpoint import atomic_write_bytes

    atomic_write_bytes(path, secret.hex().encode())
    try:
        os.chmod(path, 0o600)
    except OSError:
        pass
    return path


def read_secret(fleet_dir: str) -> bytes:
    try:
        with open(os.path.join(fleet_dir, "secret")) as f:
            return bytes.fromhex(f.read().strip())
    except (OSError, ValueError):
        return b""


def read_endpoint(fleet_dir: str, rid: int,
                  timeout: float = 120.0) -> Tuple[str, int]:
    """Wait for replica ``rid``'s endpoint file (written once its
    server is listening) → (host, port)."""
    path = os.path.join(fleet_dir, f"ep_{rid}")
    deadline = time.monotonic() + timeout
    while True:
        try:
            with open(path) as f:
                host, port = f.read().strip().rsplit(":", 1)
                return host, int(port)
        except (OSError, ValueError):
            if time.monotonic() > deadline:
                raise MXNetError(
                    f"replica {rid} never announced an endpoint in "
                    f"{fleet_dir} within {timeout:.0f}s")
            time.sleep(0.1)


def spawn_replica(rid: int, fleet_dir: str, builder: str,
                  builder_kwargs: Optional[Dict] = None,
                  env: Optional[Dict[str, str]] = None,
                  devices: Optional[Sequence[int]] = None
                  ) -> subprocess.Popen:
    """Start one replica process: ``python -m mxnet_tpu.fleet`` imports
    ``builder`` ("pkg.module:function"), calls it with
    ``builder_kwargs`` to construct the engine, wraps it in a
    ReplicaHarness, and serves until stopped (or until its parent
    dies — replicas watch getppid, the io_pool orphan rule).

    ``devices``: device ordinals this replica's engine meshes over —
    exported as ``MXNET_SERVING_DEVICES`` so a model-parallel replica
    (MXNET_SERVING_TP / MXNET_SERVING_PP > 1) binds its tp x pp slice
    of the host's chips while its siblings bind theirs."""
    spec = {"rid": int(rid), "fleet_dir": fleet_dir, "builder": builder,
            "kwargs": builder_kwargs or {}, "parent": os.getpid()}
    child_env = dict(os.environ)
    child_env.update(env or {})
    if devices is not None:
        child_env["MXNET_SERVING_DEVICES"] = \
            ",".join(str(int(d)) for d in devices)
    return subprocess.Popen(
        [sys.executable, "-m", "mxnet_tpu.fleet", json.dumps(spec)],
        env=child_env)


def _replica_main(spec: Dict) -> int:
    from .serving import ReplicaHarness
    from .checkpoint import atomic_write_bytes

    rid = int(spec["rid"])
    fleet_dir = spec["fleet_dir"]
    # flight recorder: point the mmap ring file at the shared fleet
    # dir (unless the operator chose one) so a kill -9'd replica's
    # last-N-seconds record survives WHERE THE DRILL LOOKS
    if not os.environ.get("MXNET_FLIGHT_RECORDER_DIR"):
        profiler.init_flight_recorder(fleet_dir)
    mod_name, _, fn_name = spec["builder"].partition(":")
    import importlib

    if mod_name.endswith(".py"):
        # a script builder (tools/bench_fleet.py) — load by file path
        import importlib.util

        mspec = importlib.util.spec_from_file_location(
            "_fleet_builder", mod_name)
        module = importlib.util.module_from_spec(mspec)
        mspec.loader.exec_module(module)
    else:
        module = importlib.import_module(mod_name)
    builder = getattr(module, fn_name)
    engine = builder(**spec.get("kwargs", {}))
    harness = engine if isinstance(engine, ReplicaHarness) \
        else ReplicaHarness(engine)
    server = ReplicaServer(harness, rid, fleet_dir=fleet_dir,
                           secret=read_secret(fleet_dir))
    atomic_write_bytes(os.path.join(fleet_dir, f"ep_{rid}"),
                       f"127.0.0.1:{server.port}".encode())
    # ops endpoint: replicas always bind an EPHEMERAL port (N replicas
    # on one host can't share MXNET_METRICS_PORT) and publish it as
    # mz_<rid> — tools/fleet_top.py polls these /statusz endpoints
    try:
        mz = profiler.start_metrics_server(port=0)
        profiler.register_statusz(
            "replica", lambda: {"rid": rid, "pid": os.getpid(),
                                "port": server.port})
        atomic_write_bytes(os.path.join(fleet_dir, f"mz_{rid}"),
                           f"127.0.0.1:{mz.port}".encode())
    except Exception:  # noqa: BLE001 — ops surface must not kill serving
        pass
    _log.warning("[fleet] replica %d serving on :%d (pid %d)",
                 rid, server.port, os.getpid())
    parent = int(spec.get("parent", 0))
    while not server.wait_closed(timeout=1.0):
        if parent and os.getppid() != parent:
            _log.warning("[fleet] replica %d: parent died; exiting", rid)
            server.close()
            return 0
    return 0


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------


class _Ticket:
    """One client request's life in the router: assigned → (retried)* →
    delivered exactly once."""

    __slots__ = ("tid", "spec", "deadline", "units", "attempts",
                 "rid", "t_submit", "t_dispatch", "future", "delivered",
                 "queued", "trace", "t_enqueue", "tp_submit",
                 "tp_dispatch", "trace_owned", "slo_class", "canary",
                 "phase", "spec0", "failures", "prefill_rid",
                 "tp_prefill_done", "mig_pages", "tenant")

    def __init__(self, tid, spec, deadline, units, future, trace=None,
                 slo_class="interactive", canary=False, tenant=None):
        self.tid = tid
        self.spec = spec
        self.deadline = deadline      # absolute monotonic, or None
        self.units = units            # work units (samples / new tokens)
        self.attempts = 0
        self.rid = None               # replica currently owning it
        self.t_submit = time.monotonic()
        self.t_dispatch = 0.0
        self.future = future          # resolves toward the client
        self.delivered = False        # retired: exactly-once latch
        self.queued = True            # sitting in Router._pending
        self.trace = trace            # TraceContext | None
        # perf_counter twins of the monotonic stamps — span timestamps
        # share the clock every other span in the process uses
        self.tp_submit = time.perf_counter()
        self.t_enqueue = self.tp_submit  # (re)joined the queue
        self.tp_dispatch = 0.0
        self.trace_owned = False  # router created the root span
        self.slo_class = slo_class  # validated at _accept()
        self.canary = canary        # excluded from request counters
        self.tenant = tenant        # quota bucket / fairness key
        # disaggregated serving: 0 = classic end-to-end dispatch,
        # 1 = prefill-export in flight, 2 = page migration / decode
        # continuation in flight.  ANY retry resets to 1 with spec0
        # (decode death re-prefills; prefill death retries prefill).
        self.phase = 0
        self.spec0 = None             # pristine spec for phase resets
        self.failures = 0             # replica failures (retry budget)
        self.prefill_rid = None       # who ran phase 1 (migration edge)
        self.tp_prefill_done = 0.0    # phase-1 completion (disagg TTFT)
        self.mig_pages = 0            # pages riding the phase-2 frame


class _ReplicaState:
    __slots__ = ("handle", "outstanding", "draining", "dead", "swaps",
                 "role", "free_blocks", "kv_block", "cache_util",
                 "role_flips")

    def __init__(self, handle):
        self.handle = handle
        self.outstanding: Dict[int, _Ticket] = {}
        self.draining = False
        self.dead = False
        self.swaps = 0
        self.role = "mixed"           # disagg role (roles off = mixed)
        # decode-capacity ledger: refreshed from handle.stats() by the
        # monitor loop, decremented optimistically at phase-2 dispatch.
        # None = never measured → admit and measure (the PR-1 rule).
        self.free_blocks: Optional[int] = None
        self.kv_block: Optional[int] = None
        self.cache_util: Optional[float] = None
        self.role_flips = 0


class Router:
    """Spread requests over N replicas; survive replica death; shed by
    deadline; roll weight swaps with zero dropped requests.

    Parameters
    ----------
    replicas : list
        Replica handles (:class:`ReplicaClient` or any in-process
        object with the same surface: ``rid``, ``submit(spec) ->
        Future``, ``inflight()``, ``drain()``, ``resume()``,
        ``swap()``, ``stats()``, ``close()``).
    fleet_dir : str, optional
        The shared heartbeat directory replicas write ``hb_<rid>``
        into; enables the staleness scan.  Without it only transport
        failures convict a replica.
    secret : bytes
        HMAC key for structured control payloads (and the client
        wire's server, when :meth:`serve` is called).
    retry_budget : int
        Re-dispatches a ticket survives before its client sees the
        failure (env ``MXNET_FLEET_RETRY_BUDGET``).
    default_deadline_ms : float
        Deadline applied to requests that carry none; 0 = unbounded
        (env ``MXNET_FLEET_SHED_DEADLINE_MS``).
    replica_depth : int
        Max tickets outstanding on one replica; beyond it requests
        queue in the router (where they can still be shed/retried).
    max_pending : int
        Router queue bound; above it the pending queue sheds
        oldest-deadline-first.
    dead_timeout : float
        Heartbeat staleness threshold (``MXNET_DEAD_RANK_TIMEOUT``).
    """

    def __init__(self, replicas, fleet_dir: Optional[str] = None,
                 secret: bytes = b"", retry_budget: Optional[int] = None,
                 default_deadline_ms: Optional[float] = None,
                 replica_depth: int = 8, max_pending: int = 1024,
                 dead_timeout: Optional[float] = None,
                 roles: Optional[Sequence[str]] = None,
                 autoscale: Optional[bool] = None,
                 tenant_quota=None):
        if not replicas:
            raise MXNetError("Router needs at least one replica")
        self._fleet_dir = fleet_dir
        self._secret = secret
        self._retry_budget = int(
            fleet_env("MXNET_FLEET_RETRY_BUDGET")
            if retry_budget is None else retry_budget)
        dl = (fleet_env("MXNET_FLEET_SHED_DEADLINE_MS")
              if default_deadline_ms is None else default_deadline_ms)
        self._default_deadline_s = float(dl) / 1e3 if dl else None
        self._replica_depth = int(replica_depth)
        self._max_pending = int(max_pending)
        self._dead_timeout = (dead_rank_timeout() if dead_timeout is None
                              else float(dead_timeout))
        self._swap_drain_timeout = float(
            fleet_env("MXNET_FLEET_SWAP_DRAIN_TIMEOUT"))

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._replicas: Dict[int, _ReplicaState] = {}
        for h in replicas:
            rid = int(h.rid)
            if rid in self._replicas:
                raise MXNetError(f"duplicate replica id {rid}")
            self._replicas[rid] = _ReplicaState(h)
            cb = getattr(h, "set_on_death", None)
            if cb is not None:
                cb(lambda exc, _rid=rid: self._replica_failed(_rid, exc))
        # disaggregated prefill/decode roles: kwarg wins, else the
        # MXNET_FLEET_ROLES split (by rid order), else roles stay off
        role_list = list(roles) if roles is not None else roles_env()
        self._roles_on = role_list is not None
        if role_list is not None:
            rids = sorted(self._replicas)
            if len(role_list) != len(rids):
                raise MXNetError(
                    f"{len(role_list)} role(s) for {len(rids)} "
                    f"replica(s) — the role split must name every "
                    f"replica (rid order: {rids})")
            for role in role_list:
                if role not in REPLICA_ROLES:
                    raise MXNetError(
                        f"replica role {role!r} must be one of "
                        f"{REPLICA_ROLES}")
            if ("prefill" in role_list) != ("decode" in role_list):
                raise MXNetError(
                    "a disaggregated fleet needs BOTH a prefill and a "
                    "decode role (or neither)")
            for rid, role in zip(rids, role_list):
                state = self._replicas[rid]
                state.role = role
                if role != "mixed":
                    setter = getattr(state.handle, "set_role", None)
                    if setter is None:
                        raise MXNetError(
                            f"replica {rid} handle has no set_role() — "
                            "it cannot take a disaggregated role")
                    setter(role)
        self._pending: List[_Ticket] = []
        self._next_tid = 0
        self._alive = True
        import collections as _collections

        self._shed_times = _collections.deque(maxlen=_SHED_BURST_COUNT)
        self._last_shed_dump = 0.0
        # multi-tenancy: accept-side token quotas (kwarg wins, else
        # MXNET_TENANT_QUOTA_TOKENS/_REFILL) + per-tenant fairness
        # counters the /statusz tenants section renders
        self._quota = tenant_quota if tenant_quota is not None \
            else _adapters.quota_from_env()
        self._tenants: Dict[str, Dict[str, float]] = {}
        self._adapters: set = set()  # names published via this router
        self._swap_lock = threading.Lock()  # one rolling swap at a time
        self._weights_step = -1

        # PR-1-style learned cost model: (kind, bucket) -> EMA ms of
        # dispatch->delivery wall for one request in that bucket.  The
        # shed verdict leans on it: no measurement yet = nothing is
        # provable = admit (measure instead of assume).
        self._cost: Dict[Tuple[str, int], float] = {}
        self._metrics = profiler.MetricsRegistry()
        # assigned BEFORE the worker threads exist: both loops book
        # delivery/shed outcomes into the process-wide tracker
        self._slo = _slo.get_tracker()

        self._server = None
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name="mxnet_tpu-fleet-dispatch")
        self._dispatcher.start()
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name="mxnet_tpu-fleet-monitor")
        self._monitor.start()
        # role autoscaler: periodically re-evaluate the prefill/decode
        # split from live telemetry (queue depths, cache_util ledger,
        # per-kind cost EMAs) — MXNET_FLEET_AUTOSCALE gates the thread;
        # autoscale_once() stays callable for deterministic drills
        self._autoscale_on = bool(
            int(fleet_env("MXNET_FLEET_AUTOSCALE"))
            if autoscale is None else autoscale)
        self._autoscale_interval = float(
            fleet_env("MXNET_FLEET_AUTOSCALE_INTERVAL"))
        if self._autoscale_on and self._roles_on:
            threading.Thread(
                target=self._autoscale_loop, daemon=True,
                name="mxnet_tpu-fleet-autoscale").start()
        self._set_alive_gauge()
        # ops surface: /statusz grows a router section; the HTTP
        # endpoint itself is MXNET_METRICS_PORT-gated
        profiler.maybe_start_metrics_server()
        profiler.register_statusz("router", self.stats)
        # optional canary prober: keeps availability and latency
        # observable at zero traffic (MXNET_CANARY_INTERVAL=0 leaves
        # it off).  The probe rides the FULL routed path — accept →
        # dispatch → replica → deliver — as a canary ticket.
        self._canary = None
        interval = _slo.canary_interval_s()
        if interval > 0:
            def _probe(trace):
                self.generate(
                    _slo.canary_prompt(4),
                    max_new_tokens=_slo.canary_tokens(),
                    trace=trace, canary=True).result(timeout=60.0)

            self._canary = _slo.CanaryProber(
                _probe, interval, tracker=self._slo, name="router")

    # -- metrics --------------------------------------------------------
    def _count(self, name, value=1.0):
        self._metrics.inc(name, value)
        profiler.inc_counter(f"fleet.{name}", value)

    def _tenant_count(self, tenant, name, value=1):
        with self._lock:
            d = self._tenants.setdefault(tenant, {})
            d[name] = d.get(name, 0) + value

    def _set_alive_gauge(self):
        profiler.set_gauge(
            "fleet.replicas_alive",
            sum(not s.dead for s in self._replicas.values()))

    # -- client surface -------------------------------------------------
    def submit(self, inputs, deadline_ms: Optional[float] = None,
               trace=None) -> Future:
        """Route one inference request; the Future resolves to the list
        of output arrays (or raises :class:`ShedError` /
        the replica's error).  ``trace``: the caller's
        :class:`profiler.TraceContext` (the served wire passes the
        client's through); None = a sampled root context."""
        return self._accept({"kind": "infer", "inputs": dict(inputs)},
                            deadline_ms,
                            units=self._infer_units(inputs),
                            trace=trace)

    def generate(self, prompt, max_new_tokens=32, temperature=None,
                 eos_id=None, deadline_ms: Optional[float] = None,
                 seed: Optional[int] = None, trace=None,
                 slo_class: str = "interactive",
                 canary: bool = False, tenant=None,
                 adapter=None) -> Future:
        """Route one generation; the Future resolves to the np.int32
        generated tokens.  ``slo_class`` keys the burn-rate windows the
        delivery outcome lands in; ``canary=True`` marks a synthetic
        probe (full routed path, excluded from ``fleet.requests``).
        ``tenant`` names the quota/fairness bucket (sheds typed
        ``tenant_quota`` when its token budget runs dry); ``adapter``
        names a published LoRA adapter the replicas apply to this
        stream."""
        spec = {"kind": "decode",
                "prompt": np.asarray(prompt, dtype=np.int32),
                "max_new": int(max_new_tokens), "temperature": temperature,
                "eos": eos_id, "seed": 0, "slo_class": slo_class,
                "tenant": None if tenant is None else str(tenant),
                "adapter": None if adapter is None else str(adapter)}
        return self._accept(spec, deadline_ms, units=int(max_new_tokens),
                            seed=seed, trace=trace, slo_class=slo_class,
                            canary=canary, tenant=spec["tenant"])

    @staticmethod
    def _infer_units(inputs) -> int:
        for v in inputs.values():
            shape = np.shape(v)
            return max(1, int(shape[0]) if len(shape) else 1)
        return 1

    def _accept(self, spec, deadline_ms, units, seed=None,
                trace=None, slo_class="interactive",
                canary=False, tenant=None) -> Future:
        _slo.check_class(slo_class)
        if self._quota is not None and tenant is not None \
                and not canary:
            # accept-side quota: shed BEFORE the ticket takes queue
            # space — typed, so clients and dashboards can tell a
            # budget problem from an overload problem
            tokens = int(units)
            if spec["kind"] == "decode":
                tokens += int(np.asarray(spec["prompt"]).size)
            try:
                self._quota.charge(tenant, tokens)
            except _adapters.QuotaExceededError as exc:
                self._count("shed")
                self._count("shed_tenant_quota")
                self._tenant_count(tenant, "shed")
                self._note_shed()
                raise ShedError(
                    f"request shed (tenant_quota): {exc}",
                    reason="tenant_quota") from None
        fut: Future = Future()
        with self._cond:
            if not self._alive:
                raise MXNetError("Router is closed")
            tid = self._next_tid
            self._next_tid += 1
            if spec["kind"] == "decode":
                # the deterministic retry seed: stable across replicas
                # AND across re-dispatches of this ticket
                spec["seed"] = int(seed) if seed is not None \
                    else tid + 1
            if deadline_ms is None:
                deadline = (None if self._default_deadline_s is None
                            else time.monotonic()
                            + self._default_deadline_s)
            else:
                deadline = time.monotonic() + float(deadline_ms) / 1e3
            owned = False
            if trace is None:
                # direct (in-process) callers get a sampled root; the
                # tid key keeps the verdict stable across retries
                trace = profiler.make_trace(key=tid)
                owned = trace is not None
            t = _Ticket(tid, spec, deadline, max(1, units), fut,
                        trace=trace, slo_class=slo_class, canary=canary,
                        tenant=tenant)
            t.trace_owned = owned
            self._pending.append(t)
            profiler.set_gauge("fleet.pending", len(self._pending))
            self._cond.notify_all()
        if not canary:  # probes keep request counters honest
            self._count("requests")
            if tenant is not None:
                self._tenant_count(tenant, "requests")
        return fut

    # -- cost model -----------------------------------------------------
    @staticmethod
    def _bucket_of(units: int) -> int:
        b = 1
        while b < units:
            b <<= 1
        return b

    def _est_ms(self, t: _Ticket) -> Optional[float]:
        return self._cost.get((t.spec["kind"], self._bucket_of(t.units)))

    def _observe_cost(self, t: _Ticket, ms: float):
        key = (t.spec["kind"], self._bucket_of(t.units))
        old = self._cost.get(key)
        self._cost[key] = ms if old is None else 0.5 * old + 0.5 * ms

    def _predicted_wait_ms(self, state: _ReplicaState,
                           t: _Ticket) -> Optional[float]:
        """Projected dispatch→done wall on this replica: the measured
        cost of everything it already owns plus this ticket.  None =
        no measurement for some bucket → nothing provable."""
        total = 0.0
        for o in state.outstanding.values():
            est = self._est_ms(o)
            if est is None:
                return None
            total += est
        est = self._est_ms(t)
        if est is None:
            return None
        return total + est

    # -- dispatch -------------------------------------------------------
    def _disagg_live(self) -> bool:
        """Both specialized roles present among live, non-draining
        replicas (lock held).  When one side is gone — died, or all
        flipped away — the fleet degrades to classic mixed routing
        instead of wedging."""
        if not self._roles_on:
            return False
        has_p = has_d = False
        for s in self._replicas.values():
            if s.dead or s.draining:
                continue
            has_p = has_p or s.role == "prefill"
            has_d = has_d or s.role in ("decode", "mixed")
        return has_p and has_d

    def _decode_room(self, need_blocks: int) -> bool:
        """Role-aware admission (lock held): does SOME decode-capable
        replica have room for this stream's eventual KV pages?  An
        unmeasured ledger admits (measure instead of assume)."""
        for s in self._replicas.values():
            if s.dead or s.draining or s.role == "prefill":
                continue
            if s.free_blocks is None or s.free_blocks >= need_blocks:
                return True
        return False

    def _need_blocks(self, t: _Ticket, kv_block: Optional[int]) -> int:
        """Worst-case pages a decode ticket will hold: prompt+max_new
        over the page grid (phase-2 tickets carry the exact count)."""
        if t.phase == 2:
            return t.mig_pages
        if not kv_block:
            return 0  # page size never measured → gate on nothing
        spec = t.spec0 if t.spec0 is not None else t.spec
        tokens = int(np.asarray(spec["prompt"]).size) \
            + int(spec["max_new"])
        return -(-tokens // int(kv_block))

    def _eligible(self, t: _Ticket):
        """(best replica or None, provably_unmeetable) under the lock.

        'Provably unmeetable' requires EVERY live replica's measured
        projected wait to exceed the remaining deadline — a replica
        that is merely at depth (can't take the ticket NOW but could
        meet the deadline once a slot frees) keeps the request
        admitted, and any unmeasured bucket makes nothing provable
        (the PR-1 rule: explore/measure instead of assume).

        Disaggregated routing (both roles live): fresh decode work
        lands on prefill-role or mixed replicas, phase-2 migrations
        land on decode-role or mixed replicas WITH free pool pages for
        the spliced stream, and a prefill-role replica only takes a
        fresh stream when some decode-capable replica has room for its
        eventual pages — admission keys on free decode blocks on the
        TARGET role, not just queue depth."""
        best, best_wait = None, None
        provable = t.deadline is not None
        meetable = False  # some live replica could finish in time
        remaining_ms = (None if t.deadline is None
                        else (t.deadline - time.monotonic()) * 1e3)
        # routing estimate for unmeasured buckets: the mean of the
        # measured ones (commensurable with real waits — a raw
        # outstanding COUNT would always undercut millisecond keys and
        # pile work onto whichever replica holds unmeasured requests)
        fallback = (sum(self._cost.values()) / len(self._cost)
                    if self._cost else 1.0)
        disagg = t.spec["kind"] != "infer" and self._disagg_live()
        for state in self._replicas.values():
            if state.dead or state.draining:
                continue
            if disagg:
                if t.phase == 2:
                    if state.role == "prefill":
                        continue  # pages splice into DECODE pools
                    if state.free_blocks is not None \
                            and state.free_blocks < t.mig_pages:
                        continue  # no room to splice (yet)
                else:
                    if state.role == "decode":
                        continue  # fresh prefills stay off decoders
                    if state.role == "prefill" and not self._decode_room(
                            self._need_blocks(t, state.kv_block)):
                        continue  # prefilling now would strand the KV
            wait = self._predicted_wait_ms(state, t)
            if wait is None:
                provable = False  # unmeasured bucket: admit, measure
                meetable = True
                wait_key = fallback * (len(state.outstanding) + 1)
            else:
                if remaining_ms is not None and wait > remaining_ms:
                    continue  # this replica provably misses
                meetable = True
                wait_key = wait
            if len(state.outstanding) >= self._replica_depth:
                continue  # meetable, just not dispatchable yet
            if best is None or wait_key < best_wait:
                best, best_wait = state, wait_key
        return best, (best is None and provable and not meetable
                      and self._any_live_not_draining())

    def _any_live_not_draining(self) -> bool:
        return any(not s.dead and not s.draining
                   for s in self._replicas.values())

    def _dispatch_loop(self):
        while True:
            todo = []
            with self._cond:
                while self._alive and not self._pending:
                    self._cond.wait(timeout=0.2)
                if not self._alive:
                    return
                now = time.monotonic()
                # 1) shed what already missed: serving it late only
                #    poisons p99 and steals capacity from the living
                keep = []
                for t in self._pending:
                    if t.delivered:  # zombie answered while queued
                        t.queued = False
                        continue
                    if t.deadline is not None and now > t.deadline:
                        self._shed_locked(
                            t, "expired",
                            f"deadline passed while queued "
                            f"({(now - t.t_submit) * 1e3:.0f} ms in "
                            f"queue)")
                    else:
                        keep.append(t)
                self._pending = keep
                # 2) overload: shed oldest-deadline-first down to the
                #    bound (no-deadline requests shed last, oldest
                #    submit first among them)
                while len(self._pending) > self._max_pending:
                    victim = min(
                        self._pending,
                        key=lambda t: (t.deadline
                                       if t.deadline is not None
                                       else float("inf"), t.t_submit))
                    self._pending.remove(victim)
                    self._shed_locked(
                        victim, "overload",
                        f"router queue over {self._max_pending}; "
                        "oldest-deadline-first shed")
                # 3) assign FIFO within an SLO tier: the first
                #    interactive ticket jumps the batch queue
                #    (admission-level preemption); a head that no
                #    replica can take means the fleet is at depth —
                #    hold the line
                while self._pending:
                    pick = 0
                    for i, cand in enumerate(self._pending):
                        if cand.slo_class == "interactive":
                            pick = i
                            break
                    t = self._pending[pick]
                    state, unmeetable = self._eligible(t)
                    if state is None:
                        if unmeetable:
                            self._pending.pop(pick)
                            t.queued = False
                            self._shed_locked(
                                t, "deadline",
                                "no replica can finish inside the "
                                f"deadline (remaining "
                                f"{(t.deadline - now) * 1e3:.0f} ms, "
                                "per-bucket cost model)")
                            continue
                        break
                    self._pending.pop(pick)
                    t.queued = False
                    t.rid = state.handle.rid
                    t.attempts += 1
                    t.t_dispatch = time.monotonic()
                    now_p = t.tp_dispatch = time.perf_counter()
                    if t.spec["kind"] == "decode":
                        # phase is decided by the TARGET's role: a
                        # prefill-role replica runs phase 1 (export
                        # after TTFT); a mixed replica runs the classic
                        # end-to-end decode even on a re-dispatch
                        if state.role == "prefill":
                            if t.spec0 is None:
                                t.spec0 = dict(t.spec)
                            t.phase = 1
                            t.spec = dict(t.spec0)
                            t.spec["phase"] = 1
                        elif t.spec0 is not None:
                            t.phase = 0
                            t.spec = dict(t.spec0)
                    elif t.phase == 2:
                        # page splice: burn the target's block ledger
                        # optimistically (the monitor re-measures) and
                        # book the migration window — export + handoff
                        # queue — the instant the pages leave limbo
                        if state.free_blocks is not None:
                            state.free_blocks = max(
                                0, state.free_blocks - t.mig_pages)
                        mig_ms = (now_p - t.tp_prefill_done) * 1e3 \
                            + float(t.spec.get("meta", {})
                                    .get("export_ms", 0.0))
                        self._metrics.observe("migration_ms", mig_ms)
                        profiler.observe("fleet.migration_ms", mig_ms)
                        self._count("migration_ms_total", mig_ms)
                    wait_ms = (now_p - t.t_enqueue) * 1e3
                    self._metrics.observe("queue_wait_ms", wait_ms)
                    profiler.observe("fleet.queue_wait_ms", wait_ms)
                    if t.attempts == 1:
                        # admission latency: submit → first dispatch
                        # (eligibility + depth gating, incl. queue)
                        adm = (now_p - t.tp_submit) * 1e3
                        self._metrics.observe("admission_ms", adm)
                        profiler.observe("fleet.admission_ms", adm)
                    if t.trace is not None:
                        profiler.add_trace_event(
                            "router.queue", t.t_enqueue,
                            now_p - t.t_enqueue, t.trace.child(),
                            cat="fleet",
                            args={"tid": t.tid, "attempt": t.attempts,
                                  "rid": t.rid})
                    state.outstanding[t.tid] = t
                    profiler.set_gauge(
                        f"fleet.queue_depth.r{t.rid}",
                        len(state.outstanding))
                    todo.append((t, state.handle, t.attempts, t.phase))
                profiler.set_gauge("fleet.pending", len(self._pending))
                if not todo and self._pending:
                    # head can't be placed (fleet at depth / draining):
                    # wait for a completion to free a slot instead of
                    # spinning the shed/assign scan at 100% CPU
                    self._cond.wait(timeout=0.05)
            for t, handle, attempt, phase in todo:
                # the replica sees the ticket's trace context as its
                # parent ("trace" rides the spec to ReplicaClient,
                # which ships it as the wire's optional field;
                # in-process fakes just ignore the key)
                t.spec["trace"] = t.trace
                try:
                    rfut = handle.submit(t.spec)
                except BaseException as exc:  # noqa: BLE001
                    self._replica_failed(handle.rid, exc)
                    continue
                rfut.add_done_callback(
                    lambda f, _t=t, _a=attempt, _r=handle.rid, _p=phase:
                    self._on_done(_t, f, _a, _r, _p))

    def _shed_locked(self, t: _Ticket, reason: str, detail: str):
        t.delivered = True
        t.queued = False
        self._count("shed")
        self._count(f"shed_{reason}")
        if t.tenant is not None:
            # caller holds the router lock; bump inline rather than
            # through _tenant_count (which would re-acquire it)
            d = self._tenants.setdefault(t.tenant, {})
            d["shed"] = d.get("shed", 0) + 1
        if not t.canary:  # a shed request spent availability budget
            self._slo.observe_avail(t.slo_class, False)
        if t.trace is not None:
            profiler.trace_point(
                "router.shed", t.trace.child(), cat="fleet",
                args={"tid": t.tid, "reason": reason})
        self._note_shed()
        exc = ShedError(f"request shed ({reason}): {detail}",
                        reason=reason)
        if t.future.set_running_or_notify_cancel():
            t.future.set_exception(exc)

    def _note_shed(self):
        """Shed-burst detector: a storm of rejections is exactly the
        moment to capture what the router was doing — one flight-
        recorder dump per burst window.  Callers hold the router
        condition lock, so only DETECT here; the dump (ring
        serialization + file write) runs on a throwaway daemon thread
        — blocking every submitter at peak overload would deepen the
        very storm being recorded."""
        now = time.monotonic()
        self._shed_times.append(now)
        if (len(self._shed_times) == self._shed_times.maxlen
                and now - self._shed_times[0] <= _SHED_BURST_WINDOW_S
                and now - self._last_shed_dump >= 2.0):
            self._last_shed_dump = now
            n = len(self._shed_times)
            threading.Thread(
                target=profiler.dump_flight_record,
                args=("shed_burst",),
                kwargs={"extra": {"sheds_in_window": n,
                                  "window_s": _SHED_BURST_WINDOW_S}},
                daemon=True,
                name="mxnet_tpu-fleet-shed-dump").start()

    def _requeue_retry_locked(self, t: _Ticket, rid_from, why: str):
        """Front-of-queue requeue of a retried ticket; books the retry
        histogram and the ``router.retry`` span — whose bounds ARE the
        conviction window (failed dispatch → requeue), so a stitched
        trace shows the dead replica's window explicitly."""
        now_p = time.perf_counter()
        t.t_enqueue = now_p
        self._pending.insert(0, t)  # oldest first
        self._count("retries")
        if t.tp_dispatch:
            retry_ms = (now_p - t.tp_dispatch) * 1e3
            self._metrics.observe("retry_ms", retry_ms)
            profiler.observe("fleet.retry_ms", retry_ms)
            if t.trace is not None:
                profiler.add_trace_event(
                    "router.retry", t.tp_dispatch,
                    now_p - t.tp_dispatch, t.trace.child(),
                    cat="fleet",
                    args={"tid": t.tid, "attempt": t.attempts,
                          "from_rid": rid_from,
                          "error": str(why)[:200]})

    # -- completion -----------------------------------------------------
    def _reset_phase_locked(self, t: _Ticket):
        """ANY retry of a disagg ticket restarts from phase 1 with the
        pristine spec: a dead decode replica's spliced pages are gone
        (re-prefill — the same recompute path preemption uses) and a
        dead prefill replica's frame never materialized."""
        if t.spec0 is not None:
            if t.phase == 2:
                self._count("re_prefills")
            t.phase = 0  # the next dispatch's target role re-decides
            t.spec = dict(t.spec0)
            t.mig_pages = 0
            t.tp_prefill_done = 0.0
            t.prefill_rid = None

    def _on_done(self, t: _Ticket, rfut: Future, attempt: int,
                 rid_disp: int, phase_disp: int = 0):
        """A replica's future resolved for dispatch #``attempt`` of
        this ticket.  Exactly-once lives here: the ``delivered`` latch
        retires the ticket on FIRST delivery; a late/stale completion
        (the ticket was already retried elsewhere, or already answered)
        is dropped, never double-delivered and never double-retried.

        ``phase_disp`` is the phase THIS dispatch ran: a phase-1
        success is not a delivery — it converts the ticket into a
        phase-2 page migration and front-requeues it (the stream is
        past its prefill; the splice must not wait behind fresh
        admissions)."""
        exc = rfut.exception()
        retry = False
        override = None
        with self._cond:
            current = (t.attempts == attempt)
            if current:
                state = self._replicas.get(rid_disp)
                if state is not None:
                    state.outstanding.pop(t.tid, None)
                    if not state.dead:
                        profiler.set_gauge(
                            f"fleet.queue_depth.r{rid_disp}",
                            len(state.outstanding))
            if t.delivered:
                # late answer from a dispatch we already gave up on:
                # the ticket is retired — exactly-once means DROP it
                self._count("duplicates")
                self._cond.notify_all()
                return
            if exc is None and phase_disp == 1:
                if not current or t.queued:
                    # a stale page frame (the live attempt re-prefills
                    # or already moved on): splicing it ANYWHERE could
                    # race the live stream — drop it, exactly once
                    self._count("duplicates")
                    self._cond.notify_all()
                    return
                res = rfut.result()
                meta = res["meta"]
                now_p = time.perf_counter()
                t.tp_prefill_done = now_p
                t.prefill_rid = rid_disp
                self._observe_cost(
                    t, (time.monotonic() - t.t_dispatch) * 1e3)
                # disaggregated TTFT: the first token exists the
                # moment prefill completes — the decode tail can no
                # longer move this number
                ttft = (now_p - t.tp_submit) * 1e3
                self._metrics.observe("ttft_ms", ttft)
                profiler.observe("fleet.ttft_ms", ttft)
                if meta.get("done"):
                    # finished at prefill (max_new == 1 / instant
                    # eos): nothing to migrate — deliver directly
                    t.delivered = True
                    override = [np.asarray(res["arrays"][1], np.int32)]
                else:
                    t.phase = 2
                    t.mig_pages = int(meta.get("n_pages", 0))
                    t.spec = {"kind": "migrate", "meta": meta,
                              "frame": res.get("frame"),
                              "arrays": res.get("arrays")}
                    t.queued = True
                    t.t_enqueue = now_p
                    self._pending.insert(0, t)
                    nbytes = int(meta.get("migration_bytes", 0))
                    self._count("migrations")
                    self._count("migration_bytes", nbytes)
                    if t.trace is not None:
                        # the migration edge of the span tree: ties
                        # the prefill replica's migrate_out to the
                        # decode replica's migrate_in across processes
                        profiler.trace_point(
                            "router.migrate", t.trace.child(),
                            cat="fleet",
                            args={"tid": t.tid,
                                  "from_rid": rid_disp,
                                  "pages": t.mig_pages,
                                  "bytes": nbytes})
                    self._cond.notify_all()
                    return
            elif exc is None:
                # even a STALE success delivers (the convicted replica
                # answered after all — first answer wins; the live
                # retry's answer will hit the latch above).  If
                # _replica_failed already requeued the ticket, pull it
                # back out: a delivered ticket left in _pending would
                # be re-dispatched (wasted work) and later shed/close
                # passes would trip on its finished future.
                t.delivered = True
                if t.queued:
                    t.queued = False
                    try:
                        self._pending.remove(t)
                    except ValueError:
                        pass
                if current:
                    self._observe_cost(
                        t, (time.monotonic() - t.t_dispatch) * 1e3)
            elif not current or t.queued:
                # stale failure, or _replica_failed already requeued
                # this ticket: the live dispatch owns the outcome
                self._cond.notify_all()
                return
            elif self._is_replica_failure(exc):
                t.failures += 1
                if t.failures <= self._retry_budget:
                    retry = True
                    t.queued = True
                    self._reset_phase_locked(t)
                    self._requeue_retry_locked(t, rid_disp, str(exc))
                else:
                    t.delivered = True
            else:
                t.delivered = True  # the request itself is bad
            self._cond.notify_all()
        if retry:
            return
        lat_ms = (time.monotonic() - t.t_submit) * 1e3
        self._metrics.observe("latency_ms", lat_ms)
        profiler.observe("fleet.latency_ms", lat_ms)
        if not t.canary:
            # the delivery outcome feeds the availability objective; a
            # canary ticket's outcome is the PROBER's to book (it also
            # sees probe failures this path never reaches)
            self._slo.observe_avail(t.slo_class, exc is None)
        if t.trace is not None:
            now_p = time.perf_counter()
            # the router-residency span (submit → delivery).  When the
            # router MINTED the trace (no wire client upstream) this
            # span IS the root — every queue/retry/replica span nests
            # under it; with a FleetClient upstream it is a child of
            # the client.request root instead.
            profiler.add_trace_event(
                "router.request", t.tp_submit, now_p - t.tp_submit,
                t.trace if t.trace_owned else t.trace.child(),
                cat="fleet",
                args={"tid": t.tid, "attempts": t.attempts,
                      "rid": t.rid, "ok": exc is None})
            profiler.trace_point(
                "router.deliver", t.trace.child(), cat="fleet",
                args={"tid": t.tid, "ok": exc is None})
        if exc is None and t.tp_prefill_done:
            # disagg decode tail: per-token latency AFTER the handoff
            # (the number the prefill/decode isolation bench bounds)
            res_peek = rfut.result() if override is None else override
            toks = res_peek[0] if isinstance(res_peek, (list, tuple)) \
                else res_peek
            n = max(1, int(np.asarray(toks).size) - 1)
            dms = ((time.perf_counter() - t.tp_prefill_done) * 1e3) / n
            self._metrics.observe("decode_ms_per_token", dms)
            profiler.observe("fleet.decode_ms_per_token", dms)
        if t.future.set_running_or_notify_cancel():
            if exc is None:
                self._count("responses")
                res = rfut.result() if override is None else override
                # handle contract: a LIST of output arrays (decode =
                # one token tensor) — unwrap for generate() callers
                if t.spec["kind"] in ("decode", "migrate") \
                        and isinstance(res, (list, tuple)):
                    res = res[0]
                t.future.set_result(res)
            else:
                self._count("failures")
                t.future.set_exception(exc)

    @staticmethod
    def _is_replica_failure(exc: BaseException) -> bool:
        """Failures that indict the REPLICA (retry elsewhere), vs the
        request (fail the client: validation, bad shapes...)."""
        from .serving import EngineClosedError

        if isinstance(exc, (EngineClosedError, ConnectionError)):
            return True
        if isinstance(exc, MXNetError):
            msg = str(exc)
            return any(tok in msg for tok in
                       ("connection", "died", "closed", "reset",
                        "peer", "draining"))
        return isinstance(exc, OSError)

    # -- health ---------------------------------------------------------
    def _monitor_loop(self):
        interval = min(heartbeat_interval(), self._dead_timeout / 4.0)
        while True:
            with self._lock:
                if not self._alive:
                    return
                rids = [r for r, s in self._replicas.items()
                        if not s.dead]
            if self._fleet_dir:
                for rid in stale_ids(self._fleet_dir, rids,
                                     timeout=self._dead_timeout):
                    self._replica_failed(
                        rid, MXNetError("heartbeat went stale"))
            for rid in rids:
                dead = getattr(self._replicas[rid].handle,
                               "transport_dead", None)
                if dead is not None:
                    self._replica_failed(rid, dead)
            if self._roles_on:
                self._refresh_ledger(rids)
            time.sleep(max(0.02, interval))

    def _refresh_ledger(self, rids):
        """Re-measure each replica's decode-capacity ledger (free pool
        blocks / page size / cache_util) from its stats — the signals
        role-aware admission and the autoscaler route on.  Best-effort:
        a replica that cannot answer keeps its last measurement (a
        dying one gets convicted by the passes above, not here)."""
        for rid in rids:
            state = self._replicas.get(rid)
            if state is None or state.dead:
                continue
            try:
                st = state.handle.stats()
            except Exception:  # noqa: BLE001 — measurement only
                continue
            with self._lock:
                if st.get("cache_blocks_free") is not None:
                    state.free_blocks = int(st["cache_blocks_free"])
                if st.get("kv_block"):
                    state.kv_block = int(st["kv_block"])
                if st.get("cache_util") is not None:
                    state.cache_util = float(st["cache_util"])
                role = st.get("role")
                if role in REPLICA_ROLES:
                    state.role = role

    def _replica_failed(self, rid: int, exc: BaseException):
        """Convict one replica: mark dead, re-queue its unretired
        tickets on the survivors (the transparent-retry path)."""
        with self._cond:
            if not self._alive:
                return  # teardown closes sockets; not a conviction
            state = self._replicas.get(rid)
            if state is None or state.dead:
                return
            state.dead = True
            orphans = [t for t in state.outstanding.values()
                       if not t.delivered and not t.queued]
            state.outstanding.clear()
            self._count("replica_deaths")
            _log.warning(
                "[fleet] replica %d convicted dead (%s); retrying %d "
                "in-flight request(s) on the survivors", rid, exc,
                len(orphans))
            for t in orphans:
                t.failures += 1
                if t.failures <= self._retry_budget:
                    t.queued = True
                    self._reset_phase_locked(t)
                    self._requeue_retry_locked(t, rid, exc)
                else:
                    t.delivered = True
                    if t.future.set_running_or_notify_cancel():
                        t.future.set_exception(MXNetError(
                            f"request failed on {t.attempts} replica(s); "
                            f"retry budget {self._retry_budget} "
                            f"exhausted (last: {exc})"))
            self._cond.notify_all()
        profiler.del_gauge(f"fleet.queue_depth.r{rid}")
        self._set_alive_gauge()
        # post-mortem: what the ROUTER saw in the seconds before the
        # conviction (the dead replica's own ring file tells its side)
        profiler.dump_flight_record(
            "replica_conviction",
            extra={"rid": rid, "error": str(exc),
                   "retried": len(orphans)})
        try:
            state.handle.close()
        except Exception:  # noqa: BLE001 — already convicted
            pass

    def alive_replicas(self) -> List[int]:
        with self._lock:
            return sorted(r for r, s in self._replicas.items()
                          if not s.dead)

    # -- rolling weight swap --------------------------------------------
    def swap_weights(self, ckpt_dir: str,
                     drain_timeout: Optional[float] = None) -> Dict:
        """Zero-downtime rolling update: one replica at a time —
        stop routing to it, wait for its in-flight tickets to deliver,
        ``swap`` (drain → load committed+checksum-verified manifest →
        warmup) on the replica, re-admit — while the rest of the fleet
        keeps serving.  No request is dropped: traffic redistributes
        around the draining replica, and a swap failure resumes the
        replica on its OLD weights and aborts the roll (replicas
        already swapped stay swapped — re-run to converge).
        """
        from .checkpoint import load_latest_params

        drain_timeout = (self._swap_drain_timeout
                         if drain_timeout is None else float(drain_timeout))
        # verify ONCE router-side before touching any replica: a bad
        # checkpoint must not take even one replica out of rotation
        _params, step, path = load_latest_params(ckpt_dir)
        del _params
        with self._swap_lock:
            t0 = time.monotonic()
            reports: Dict[int, Dict] = {}
            for rid in self.alive_replicas():
                with self._cond:
                    state = self._replicas.get(rid)
                    if state is None or state.dead:
                        continue
                    state.draining = True
                try:
                    deadline = time.monotonic() + drain_timeout
                    while True:
                        with self._lock:
                            left = len(state.outstanding)
                        if left == 0:
                            break
                        if time.monotonic() > deadline:
                            raise MXNetError(
                                f"swap aborted: replica {rid} still has "
                                f"{left} ticket(s) in flight after "
                                f"{drain_timeout:.0f}s")
                        time.sleep(0.005)
                    reports[rid] = state.handle.swap(
                        path, drain_timeout=drain_timeout)
                    state.swaps += 1
                finally:
                    with self._cond:
                        state.draining = False
                        self._cond.notify_all()
            self._weights_step = step
            self._count("swaps")
            profiler.set_gauge("fleet.weights_step", float(step))
            return {"step": step, "path": path,
                    "replicas": reports,
                    "total_ms": (time.monotonic() - t0) * 1e3}

    # -- multi-tenant adapters ------------------------------------------
    def publish_adapter(self, name, a, b, alpha=None) -> Dict:
        """Broadcast one LoRA adapter to every live replica — HOT,
        unlike :meth:`swap_weights`: no drain, no dispatch pause (each
        engine's publish is a slab write plus one atomic reference
        swap; in-flight streams are untouched).  Returns the per-rid
        slot map.  If ANY replica refuses, the successes are rolled
        back (retired) and the error raises — an adapter is routable
        only when the whole fleet can serve it."""
        name = str(name)
        a = np.asarray(a)
        b = np.asarray(b)
        with self._cond:
            handles = {rid: s.handle
                       for rid, s in self._replicas.items()
                       if not s.dead}
        slots: Dict[int, int] = {}
        errors: Dict[int, BaseException] = {}
        for rid, handle in sorted(handles.items()):
            try:
                slots[rid] = int(handle.publish_adapter(
                    name, a, b, alpha=alpha))
            except BaseException as exc:  # noqa: BLE001 — collected
                errors[rid] = exc
        if errors:
            for rid in slots:  # roll the partial publish back
                try:
                    handles[rid].retire_adapter(name)
                except BaseException:  # noqa: BLE001 — best effort
                    pass
            detail = "; ".join(f"rid {rid}: {exc}"
                               for rid, exc in sorted(errors.items()))
            raise MXNetError(
                f"publish_adapter({name!r}) failed on "
                f"{len(errors)}/{len(handles)} replica(s) — rolled "
                f"back: {detail}")
        with self._lock:
            self._adapters.add(name)
        self._count("adapter_publishes")
        return {"name": name, "slots": slots}

    def retire_adapter(self, name) -> Dict:
        """Broadcast an adapter retire — also hot.  Replicas with live
        references defer the actual free to the last holder's
        retirement; the name stops being acquirable fleet-wide
        immediately.  Returns {rid: freed-now bool}."""
        name = str(name)
        with self._cond:
            handles = {rid: s.handle
                       for rid, s in self._replicas.items()
                       if not s.dead}
        freed: Dict[int, bool] = {}
        errors: Dict[int, BaseException] = {}
        for rid, handle in sorted(handles.items()):
            try:
                freed[rid] = bool(handle.retire_adapter(name))
            except BaseException as exc:  # noqa: BLE001 — collected
                errors[rid] = exc
        with self._lock:
            self._adapters.discard(name)
        self._count("adapter_retires")
        if errors:
            detail = "; ".join(f"rid {rid}: {exc}"
                               for rid, exc in sorted(errors.items()))
            raise MXNetError(
                f"retire_adapter({name!r}) failed on "
                f"{len(errors)}/{len(handles)} replica(s): {detail}")
        return {"name": name, "freed": freed}

    # -- disaggregated roles --------------------------------------------
    def set_role(self, rid: int, role: str,
                 drain_timeout: Optional[float] = None) -> Dict:
        """Flip one replica's disaggregated role through the same
        quiesce machinery the rolling weight swap uses: stop routing
        to it, wait for its in-flight tickets to deliver, flip, warm,
        re-admit.  Traffic redistributes around it meanwhile; a flip
        that would leave the fleet without a prefill or a decode side
        is refused (the last replica of a role never flips away)."""
        if role not in REPLICA_ROLES:
            raise MXNetError(
                f"replica role {role!r} must be one of {REPLICA_ROLES}")
        drain_timeout = (self._swap_drain_timeout if drain_timeout
                         is None else float(drain_timeout))
        with self._cond:
            state = self._replicas.get(int(rid))
            if state is None or state.dead:
                raise MXNetError(f"no live replica {rid} to re-role")
            if state.role == role:
                return {"rid": int(rid), "role": role, "flipped": False}
            if self._roles_on:
                for side in ("prefill", "decode"):
                    if state.role == side and role != side and not any(
                            s is not state and not s.dead
                            and s.role == side
                            for s in self._replicas.values()):
                        raise MXNetError(
                            f"refusing to flip replica {rid} off "
                            f"{side!r}: it is the last {side} replica "
                            "— a one-sided fleet cannot serve")
            old = state.role
            state.draining = True
        t0 = time.monotonic()
        try:
            deadline = t0 + drain_timeout
            while True:
                with self._lock:
                    left = len(state.outstanding)
                if left == 0:
                    break
                if time.monotonic() > deadline:
                    raise MXNetError(
                        f"role flip aborted: replica {rid} still has "
                        f"{left} ticket(s) in flight after "
                        f"{drain_timeout:.0f}s")
                time.sleep(0.005)
            drain_ms = (time.monotonic() - t0) * 1e3
            setter = getattr(state.handle, "set_role", None)
            if setter is None:
                raise MXNetError(
                    f"replica {rid} handle has no set_role() — it "
                    "cannot take a disaggregated role")
            setter(role)
            with self._lock:
                state.role = role
                state.role_flips += 1
            self._count("role_flips")
            _log.warning("[fleet] replica %d role %s -> %s "
                         "(drained in %.0f ms)", rid, old, role,
                         drain_ms)
            return {"rid": int(rid), "role": role, "from": old,
                    "flipped": True, "drain_ms": drain_ms,
                    "total_ms": (time.monotonic() - t0) * 1e3}
        finally:
            with self._cond:
                state.draining = False
                self._cond.notify_all()

    def autoscale_once(self) -> Optional[Dict]:
        """One evaluation of the prefill/decode split; returns the flip
        report or None.  Pressure per role = queued + in-flight work,
        weighted by the measured per-kind cost EMAs, normalized by the
        role's replica count — plus decode-pool fullness (a nearly
        full decode pool is decode pressure even at shallow queues)
        and the interactive SLO burn (a burning TTFT objective is
        prefill starvation; a burning per-token objective is decode
        starvation).  A flip needs a 2x imbalance (hysteresis — the
        drain it triggers is not free), moves ONE replica per call,
        and never strips the last replica of a role."""
        with self._lock:
            if not self._roles_on or not self._alive:
                return None
            pre = [s for s in self._replicas.values()
                   if not s.dead and s.role == "prefill"]
            dec = [s for s in self._replicas.values()
                   if not s.dead and s.role == "decode"]
            if not pre or not dec:
                return None
            # cost-EMA weights: ms of work one queued item represents
            w_pre = [v for (k, _), v in self._cost.items()
                     if k == "decode"]
            w_dec = [v for (k, _), v in self._cost.items()
                     if k == "migrate"]
            w_pre = sum(w_pre) / len(w_pre) if w_pre else 1.0
            w_dec = sum(w_dec) / len(w_dec) if w_dec else 1.0
            q_pre = sum(len(s.outstanding) for s in pre) \
                + sum(1 for t in self._pending
                      if t.spec["kind"] == "decode" and t.phase != 2)
            q_dec = sum(len(s.outstanding) for s in dec) \
                + sum(1 for t in self._pending if t.phase == 2)
            p_pre = q_pre * w_pre / len(pre)
            p_dec = q_dec * w_dec / len(dec)
            utils = [s.cache_util for s in dec
                     if s.cache_util is not None]
            if utils and max(utils) > 0.85:
                # decode pools nearly full: migrations are about to
                # stall on admission regardless of queue depth
                p_dec *= 2.0
            burn_ttft = self._slo.burn_rate("interactive", "ttft")
            burn_tpt = self._slo.burn_rate("interactive", "tpt")
            if burn_ttft > 1.0 >= burn_tpt:
                p_pre *= 2.0
            elif burn_tpt > 1.0 >= burn_ttft:
                p_dec *= 2.0
            flip_to = None
            if p_pre > 2.0 * max(p_dec, 1e-9) and len(dec) > 1:
                flip_to = "prefill"
                victim = min(dec, key=lambda s: len(s.outstanding))
            elif p_dec > 2.0 * max(p_pre, 1e-9) and len(pre) > 1:
                flip_to = "decode"
                victim = min(pre, key=lambda s: len(s.outstanding))
            if flip_to is None:
                return None
            vrid = victim.handle.rid
        report = self.set_role(vrid, flip_to)
        report["pressure"] = {"prefill": round(p_pre, 3),
                              "decode": round(p_dec, 3)}
        return report

    def _autoscale_loop(self):
        while True:
            time.sleep(self._autoscale_interval)
            with self._lock:
                if not self._alive:
                    return
            try:
                self.autoscale_once()
            except Exception as exc:  # noqa: BLE001 — keep evaluating
                _log.warning("[fleet] autoscale pass failed: %s", exc)

    # -- stats ----------------------------------------------------------
    def stats(self) -> Dict:
        summ = self._metrics.summary()
        c = summ["counters"]
        out = {k: int(c.get(k, 0)) for k in
               ("requests", "responses", "failures", "shed", "retries",
                "duplicates", "replica_deaths", "swaps")}
        lat = summ["histograms"].get("latency_ms")
        out["p50_ms"] = lat["p50"] if lat else None
        out["p90_ms"] = lat["p90"] if lat else None
        out["p99_ms"] = lat["p99"] if lat else None
        out["requests_per_s"] = summ["rates"].get("requests", 0.0)
        out["shed_rate"] = (out["shed"] / out["requests"]
                            if out["requests"] else 0.0)
        # disaggregation: migration counters + the phase-isolated
        # latency split (TTFT from the prefill side, per-token from
        # the decode side — the isolation the role split buys)
        for k in ("migrations", "migration_bytes", "re_prefills",
                  "role_flips"):
            out[k] = int(c.get(k, 0))
        out["migration_ms_total"] = round(
            float(c.get("migration_ms_total", 0.0)), 6)
        for key, hist in (("migration", "migration_ms"),
                          ("ttft", "ttft_ms"),
                          ("decode_per_token", "decode_ms_per_token")):
            h = summ["histograms"].get(hist)
            out[f"{key}_p50_ms"] = h["p50"] if h else None
            out[f"{key}_p99_ms"] = h["p99"] if h else None
        out["migrations_per_s"] = summ["rates"].get("migrations", 0.0)
        with self._lock:
            out["pending"] = len(self._pending)
            out["replicas"] = {
                rid: {"dead": s.dead, "draining": s.draining,
                      "outstanding": len(s.outstanding),
                      "swaps": s.swaps, "role": s.role,
                      "role_flips": s.role_flips,
                      "free_blocks": s.free_blocks,
                      "cache_util": s.cache_util}
                for rid, s in self._replicas.items()}
            out["disagg"] = self._roles_on and self._disagg_live()
        out["alive"] = self.alive_replicas()
        out["weights_step"] = self._weights_step
        # multi-tenancy: per-tenant fairness (requests/shed at the
        # router's own increment sites) + quota balances; fleet_top
        # renders this section only when it is non-empty
        out["shed_tenant_quota"] = int(c.get("shed_tenant_quota", 0))
        with self._lock:
            out["tenants"] = {t: dict(d)
                              for t, d in self._tenants.items()}
        if self._quota is not None:
            for t, q in self._quota.stats().items():
                out["tenants"].setdefault(t, {}).update(q)
        out["adapters_published"] = sorted(self._adapters)
        out["cost_model_ms"] = {f"{k}:{b}": round(v, 3)
                                for (k, b), v in sorted(self._cost.items())}
        out["latency_breakdown"] = self.latency_breakdown()
        # the one-glance judgment bit (full detail: /statusz "slo")
        out["slo_alert_active"] = self._slo.alert_active()
        return out

    def latency_breakdown(self) -> Dict:
        """Router-side phase percentiles from the per-request spans'
        histograms: queue_wait (per-dispatch pending wait), admission
        (submit → first dispatch), retry (failed dispatch → requeue =
        the conviction window), total (submit → delivery).  The
        engines' stats() add prefill/decode; the benches merge both
        into the JSON latency-breakdown object."""
        from .serving import _phase_breakdown

        return _phase_breakdown(
            self._metrics.summary(),
            {"queue_wait": "queue_wait_ms",
             "admission": "admission_ms",
             "retry": "retry_ms",
             "total": "latency_ms"})

    def reset_stats(self):
        """Per-sweep-point percentiles for the bench (the DecodeEngine
        convention)."""
        self._metrics.reset()

    # -- client wire ----------------------------------------------------
    def serve(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Expose the router on the fleet wire; returns the bound port.
        Clients speak :class:`FleetClient`."""
        router = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                wlock = threading.Lock()
                try:
                    while True:
                        req = wire.recv_frame(self.request)
                        router._client_dispatch(req, self.request, wlock)
                except (ConnectionError, EOFError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="mxnet_tpu-fleet-router").start()
        return port

    def _client_dispatch(self, buf: memoryview, sock, wlock):
        op = buf[0]
        (rid,) = wire.U64.unpack_from(buf, 1)

        def send(fop, status, payload: bytes):
            frame = bytes([fop]) + wire.U64.pack(rid) \
                + bytes([status]) + payload
            try:
                with wlock:
                    wire.send_frame(sock, frame)
            except OSError:
                pass  # client went away; nothing to deliver to

        if op == _F_SUBMIT:
            try:
                # client SUBMIT: optional trace field, then a deadline
                # budget, then the request spec (0 = none → the router
                # default applies)
                trace, off = wire.unpack_trace(buf, 9)
                (deadline_us,) = wire.U64.unpack_from(buf, off)
                off += 8
                deadline_ms = deadline_us / 1e3 if deadline_us else None
                spec = _unpack_spec(buf, off)
                if spec["kind"] == "infer":
                    fut = self.submit(spec["inputs"],
                                      deadline_ms=deadline_ms,
                                      trace=trace)
                else:
                    # wire seed 0 = router-assigned (the deterministic
                    # ticket seed); explicit seeds pass through
                    fut = self.generate(
                        spec["prompt"], spec["max_new"],
                        temperature=spec["temperature"],
                        eos_id=spec["eos"],
                        deadline_ms=deadline_ms,
                        seed=spec["seed"] or None,
                        trace=trace,
                        slo_class=spec.get("slo_class",
                                           "interactive"),
                        tenant=spec.get("tenant"),
                        adapter=spec.get("adapter"))
            except ShedError as exc:
                send(_F_RESULT, _ST_SHED, f"{exc.reason}: {exc}".encode())
                return
            except BaseException as exc:  # noqa: BLE001 — to the wire
                send(_F_RESULT, _ST_ERR,
                     f"{type(exc).__name__}: {exc}".encode())
                return

            def done(f):
                exc = f.exception()
                if exc is None:
                    send(_F_RESULT, _ST_OK, _pack_result(f.result()))
                elif isinstance(exc, ShedError):
                    send(_F_RESULT, _ST_SHED,
                         f"{exc.reason}: {exc}".encode())
                else:
                    send(_F_RESULT, _ST_ERR,
                         f"{type(exc).__name__}: {exc}".encode())

            fut.add_done_callback(done)
            return
        if op == _F_CTRL:
            # control ops run OFF the connection's read thread: a
            # rolling swap takes minutes of drain+warmup and must not
            # stall this client's subsequent submits (the ReplicaServer
            # ctrl-thread rule)
            def ctrl():
                try:
                    _trace, off = wire.unpack_trace(buf, 9)
                    spec, _ = wire.unpack_signed_json(
                        self._secret, buf, off, "fleet control frame")
                    if spec.get("op") == "stats":
                        out = self.stats()
                    elif spec.get("op") == "swap":
                        out = self.swap_weights(spec["ckpt_dir"])
                    else:
                        raise MXNetError(
                            f"unknown router control op "
                            f"{spec.get('op')!r}")
                    send(_F_CTRL_RESULT, _ST_OK, json.dumps(out).encode())
                except BaseException as exc:  # noqa: BLE001
                    send(_F_CTRL_RESULT, _ST_ERR,
                         f"{type(exc).__name__}: {exc}".encode())

            threading.Thread(target=ctrl, daemon=True,
                             name="mxnet_tpu-fleet-router-ctrl").start()
            return
        send(_F_RESULT, _ST_ERR, f"unknown fleet op {op}".encode())

    # -- lifecycle ------------------------------------------------------
    def close(self, stop_replicas: bool = False):
        canary = getattr(self, "_canary", None)
        if canary is not None:  # stop probing BEFORE the door shuts
            canary.stop()
            self._canary = None
        with self._cond:
            if not self._alive:
                return
            self._alive = False
            pending, self._pending = self._pending, []
            self._cond.notify_all()
        for t in pending:
            if not t.delivered \
                    and t.future.set_running_or_notify_cancel():
                t.future.set_exception(MXNetError("Router closed"))
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
        for state in self._replicas.values():
            try:
                if stop_replicas and not state.dead:
                    stop = getattr(state.handle, "stop", None)
                    if stop is not None:
                        stop()
                state.handle.close()
            except Exception:  # noqa: BLE001 — teardown
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class FleetClient:
    """Client of a served :class:`Router` (the ``ps.py`` wire: length-
    prefixed frames, tensors never pickled, control payloads HMAC'd).
    Any number of requests may be in flight; responses match by id."""

    def __init__(self, host: str, port: int, secret: bytes = b"",
                 timeout: float = 30.0):
        t0 = time.monotonic()
        while True:
            try:
                sock = socket.create_connection((host, port), timeout=10)
                break
            except OSError:
                if time.monotonic() - t0 > timeout:
                    raise MXNetError(
                        f"cannot reach fleet router at {host}:{port}")
                time.sleep(0.1)
        sock.settimeout(None)
        self._secret = secret
        self._dx = _Duplex(sock, "client")
        self._dx.start()

    def submit(self, inputs: Dict[str, Any],
               deadline_ms: Optional[float] = None,
               trace=None) -> Future:
        spec = {"kind": "infer", "inputs": inputs}
        return self._begin_submit(spec, deadline_ms, trace)

    def generate(self, prompt, max_new_tokens=32, temperature=None,
                 eos_id=None, deadline_ms: Optional[float] = None,
                 trace=None, slo_class="interactive", tenant=None,
                 adapter=None) -> Future:
        spec = {"kind": "decode", "prompt": prompt,
                "max_new": max_new_tokens, "temperature": temperature,
                "eos": eos_id, "seed": 0, "slo_class": slo_class,
                "tenant": tenant, "adapter": adapter}
        fut = self._begin_submit(spec, deadline_ms, trace)
        # decode result is ONE token tensor, not a list
        out: Future = Future()

        def unwrap(f):
            exc = f.exception()
            if out.set_running_or_notify_cancel():
                if exc is not None:
                    out.set_exception(exc)
                else:
                    out.set_result(f.result()[0])

        fut.add_done_callback(unwrap)
        return out

    def _begin_submit(self, spec, deadline_ms, trace=None) -> Future:
        deadline_us = 0 if deadline_ms is None \
            else max(1, int(float(deadline_ms) * 1e3))
        # the root of the distributed trace lives HERE: the client's
        # submit→result span; everything the router and replicas stamp
        # hangs under it via the wire's optional trace field
        ctx = trace if trace is not None else profiler.make_trace()
        body = (wire.pack_trace(ctx) + wire.U64.pack(deadline_us)
                + _pack_spec(spec))
        t0 = time.perf_counter()
        fut = self._dx.begin(_F_SUBMIT, body, _parse_submit_response)
        if ctx is not None:
            def end_root(f, _t0=t0, _ctx=ctx):
                profiler.add_trace_event(
                    "client.request", _t0,
                    time.perf_counter() - _t0, _ctx, cat="fleet",
                    args={"kind": spec["kind"],
                          "ok": f.exception() is None})

            fut.add_done_callback(end_root)
        return fut

    def stats(self) -> Dict:
        return self._ctrl({"op": "stats"})

    def swap_weights(self, ckpt_dir: str) -> Dict:
        return self._ctrl({"op": "swap", "ckpt_dir": ckpt_dir},
                          timeout=3600.0)

    def _ctrl(self, obj: Dict, timeout: float = 60.0) -> Dict:
        def parse(status, payload):
            if status != _ST_OK:
                return MXNetError(bytes(payload).decode(errors="replace"))
            return json.loads(bytes(payload).decode())

        body = wire.pack_trace(None) \
            + wire.pack_signed_json(self._secret, obj)
        return self._dx.begin(_F_CTRL, body, parse).result(timeout)

    def close(self):
        self._dx.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# local fleet launcher (bench + chaos drill)
# ---------------------------------------------------------------------------


def launch_local_fleet(num_replicas: Optional[int], fleet_dir: str,
                       builder: str, builder_kwargs: Optional[Dict] = None,
                       secret: bytes = b"fleet-local", **router_kw):
    """Spawn N replica processes on this host, connect handles, return
    ``(router, procs)``.  The chaos drill's entry point: ``kill -9``
    any of ``procs`` and the router carries on."""
    n = int(fleet_env("MXNET_FLEET_REPLICAS")
            if num_replicas is None else num_replicas)
    os.makedirs(fleet_dir, exist_ok=True)
    write_secret(fleet_dir, secret)
    procs = [spawn_replica(rid, fleet_dir, builder, builder_kwargs)
             for rid in range(n)]
    handles = []
    try:
        for rid in range(n):
            host, port = read_endpoint(fleet_dir, rid)
            handles.append(ReplicaClient(rid, host, port, secret=secret))
    except BaseException:
        for p in procs:
            p.kill()
        raise
    router = Router(handles, fleet_dir=fleet_dir, secret=secret,
                    **router_kw)
    return router, procs


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m mxnet_tpu.fleet '<replica spec json>'",
              file=sys.stderr)
        return 2
    return _replica_main(json.loads(argv[0]))


if __name__ == "__main__":
    sys.exit(main())
