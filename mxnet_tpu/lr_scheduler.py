"""Learning-rate schedulers.

Behavior parity with ``python/mxnet/lr_scheduler.py`` (135 LoC):
LRScheduler, FactorScheduler, MultiFactorScheduler.  The schedules are
re-derived from the spec (pinned by tests/test_optimizer.py): a
scheduler maps ``num_update`` → lr, mutating ``base_lr`` as decay
boundaries are crossed so an external rebase of ``base_lr`` (the
optimizer writes it at construction) restarts the decay chain from the
current position.
"""

from __future__ import annotations

import logging

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler"]


class LRScheduler:
    """Base: maps num_update → lr (reference: lr_scheduler.py:8)."""

    def __init__(self, base_lr=0.01):
        self.base_lr = base_lr

    def __call__(self, num_update: int) -> float:
        raise NotImplementedError("must override this")


class FactorScheduler(LRScheduler):
    """lr *= factor once per ``step`` updates, floored at
    ``stop_factor_lr`` (reference: lr_scheduler.py:33).

    A decay fires the first time ``num_update`` strictly exceeds
    ``count + step``; ``count`` then advances by ``step``.  Calls are
    lazy — one call may apply several overdue decays at once.
    """

    def __init__(self, step, factor=1, stop_factor_lr=1e-8):
        super().__init__()
        if step < 1:
            raise ValueError(
                f"FactorScheduler: step must be a positive update count, "
                f"got {step}")
        if factor > 1.0:
            raise ValueError(
                f"FactorScheduler: factor {factor} > 1 would GROW the lr; "
                f"use a factor in (0, 1]")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr
        self.count = 0

    def __call__(self, num_update):
        # boundaries crossed since the last applied decay: each window
        # of `step` updates past `count` owes one multiplication
        overdue = max(0, num_update - self.count - 1) // self.step
        for _ in range(overdue):
            self.count += self.step
            decayed = self.base_lr * self.factor
            if decayed < self.stop_factor_lr:
                self.base_lr = self.stop_factor_lr
                logging.info(
                    "update %d: lr hit the stop_factor_lr floor %.5e; "
                    "no further decay", num_update, self.base_lr)
            else:
                self.base_lr = decayed
                logging.info("update %d: lr decayed to %.5e",
                             num_update, self.base_lr)
        return self.base_lr


class MultiFactorScheduler(LRScheduler):
    """lr *= factor as each milestone in ``step`` is passed
    (reference: lr_scheduler.py:83).  A milestone ``s`` fires the first
    time ``num_update`` strictly exceeds ``s``; like FactorScheduler,
    several overdue milestones apply in one call."""

    def __init__(self, step, factor=1):
        super().__init__()
        assert isinstance(step, list) and step, \
            "MultiFactorScheduler: step must be a non-empty list of " \
            "update milestones"
        for i, s in enumerate(step):
            if s < 1:
                raise ValueError(
                    f"MultiFactorScheduler: milestone {s} is not a "
                    f"positive update count")
            if i and s <= step[i - 1]:
                raise ValueError(
                    f"MultiFactorScheduler: milestones must be strictly "
                    f"increasing, got {step[i - 1]} before {s}")
        if factor > 1.0:
            raise ValueError(
                f"MultiFactorScheduler: factor {factor} > 1 would GROW "
                f"the lr; use a factor in (0, 1]")
        self.step = step
        self.cur_step_ind = 0
        self.factor = factor
        self.count = 0

    def __call__(self, num_update):
        while self.cur_step_ind < len(self.step) \
                and num_update > self.step[self.cur_step_ind]:
            self.count = self.step[self.cur_step_ind]
            self.cur_step_ind += 1
            self.base_lr *= self.factor
            logging.info("update %d: lr decayed to %.5e (milestone %d)",
                         num_update, self.base_lr, self.count)
        return self.base_lr
