"""Compiled-HLO inspection — structural proof of comm/compute overlap.

The fused training step's collectives only overlap compute if the
COMPILED program says so: on TPU/GPU the async-collective passes split
each collective into ``<op>-start`` / ``<op>-done`` pairs and the
latency-hiding scheduler moves real compute between them; on backends
that emit synchronous collectives (this sandbox's CPU build) the same
property shows up as per-bucket collectives *interleaved* with compute
in the scheduled instruction order instead of one monolithic clump at
the end of backward.

This module parses the scheduled HLO text (``is_scheduled=true``
modules, the form ``jitted.lower(...).compile().as_text()`` returns)
and answers both questions, so the bench tools, the dryrun and the
tests can gate on structure rather than on wall-clock luck:

- :func:`collective_summary` — ordered per-op classification of the
  entry computation;
- :func:`overlap_report` — async start/done pairs with compute between
  them, and the sync-collective interleaving measure (how many
  collective groups are separated by compute);
- :func:`collective_bytes` — bytes written by collective ops (the
  numerator of the in-program comm fraction the GoodputTracker books);
- :func:`shape_bytes` — size of one HLO shape literal.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

__all__ = ["collective_summary", "overlap_report", "collective_bytes",
           "shape_bytes", "COLLECTIVE_OPS"]

# synchronous collective op names (scheduled HLO, SPMD-partitioned)
COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute", "all-to-all")

# ops that represent real device compute in a scheduled module (fusions
# subsume elementwise chains; dot/convolution are the MXU work)
_COMPUTE_OPS = ("fusion", "dot", "convolution", "custom-call")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2,
    "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
    "f64": 8, "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1,
}

# one scheduled-HLO instruction: "%name = <shape> <op>(...)" — the
# shape may be a tuple for -start/-done/tuple-output ops
_INSTR = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(\(?[a-z0-9]+\[[^=]*?)\s+"
    r"([a-z][a-z0-9\-]*(?:-start|-done)?)\(")


def shape_bytes(shape_text: str) -> int:
    """Total bytes of every array literal in an HLO shape string
    (handles tuple shapes: sums the components)."""
    total = 0
    for dtype, dims in re.findall(r"([a-z0-9]+)\[([0-9,]*)\]",
                                  shape_text):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def _entry_lines(hlo_text: str) -> List[str]:
    """Lines of the ENTRY computation only (in schedule order for an
    ``is_scheduled=true`` module)."""
    lines = hlo_text.splitlines()
    out: List[str] = []
    depth = 0
    in_entry = False
    for line in lines:
        if not in_entry and line.lstrip().startswith("ENTRY "):
            in_entry = True
        if in_entry:
            out.append(line)
            depth += line.count("{") - line.count("}")
            if depth <= 0 and len(out) > 1:
                break
    return out


def collective_summary(hlo_text: str) -> List[Tuple[str, str, int]]:
    """Ordered (op_kind, shape_text, line_index) classification of the
    entry computation's collective and compute instructions.

    ``op_kind`` is the HLO opcode (``all-gather``,
    ``all-gather-start``, ``fusion``, ...).  Only collective ops, their
    async start/done forms, and compute ops are returned — the rest of
    the schedule (copies, bitcasts, parameters) is noise for the
    overlap question."""
    rows: List[Tuple[str, str, int]] = []
    for i, line in enumerate(_entry_lines(hlo_text)):
        m = _INSTR.match(line)
        if not m:
            continue
        shape, op = m.group(1), m.group(2)
        base = re.sub(r"-(start|done)$", "", op)
        if base in COLLECTIVE_OPS or op in ("async-start", "async-done"):
            rows.append((op, shape, i))
        elif op in _COMPUTE_OPS:
            rows.append((op, shape, i))
    return rows


def overlap_report(hlo_text: str) -> Dict[str, object]:
    """Structural overlap evidence from one scheduled HLO module.

    Returns a dict with:

    - ``collectives``: {opcode: count} over the entry computation;
    - ``async_pairs``: number of ``*-start`` instructions whose
      matching ``*-done`` appears later with >= 1 compute op scheduled
      between them — the literal async-overlap proof on TPU/GPU
      toolchains;
    - ``interleaved_groups``: number of maximal runs of collective ops
      separated by at least one compute op, counting only collectives
      AFTER the first compute (so a leading all-gather of an input
      doesn't count as a group).  >= 2 means the collectives are
      distributed through the compute schedule instead of fused into
      one monolithic clump;
    - ``compute_between``: compute ops scheduled strictly between the
      first and last collective;
    - ``overlapped``: the verdict — async pairs exist, or the sync
      schedule interleaves >= 2 collective groups with compute between
      them.
    """
    rows = collective_summary(hlo_text)
    counts: Dict[str, int] = {}
    coll_idx: List[int] = []
    starts: List[Tuple[str, int]] = []
    async_pairs = 0
    for pos, (op, _shape, _line) in enumerate(rows):
        if op in _COMPUTE_OPS:
            continue
        counts[op] = counts.get(op, 0) + 1
        coll_idx.append(pos)
        if op.endswith("-start"):
            starts.append((op[:-6], pos))
        elif op.endswith("-done"):
            base = op[:-5]
            for j, (b, spos) in enumerate(starts):
                if b == base:
                    between = [r for r in rows[spos + 1:pos]
                               if r[0] in _COMPUTE_OPS]
                    if between:
                        async_pairs += 1
                    starts.pop(j)
                    break
    # interleaving measure on the (possibly sync) schedule
    first_compute = next((i for i, r in enumerate(rows)
                          if r[0] in _COMPUTE_OPS), None)
    groups = 0
    prev_was_coll = False
    compute_between = 0
    if coll_idx:
        lo, hi = coll_idx[0], coll_idx[-1]
        compute_between = sum(1 for r in rows[lo + 1:hi]
                              if r[0] in _COMPUTE_OPS)
    for pos, (op, _s, _l) in enumerate(rows):
        is_coll = op not in _COMPUTE_OPS
        if is_coll and first_compute is not None and pos > first_compute:
            if not prev_was_coll:
                groups += 1
        prev_was_coll = is_coll
    return {
        "collectives": counts,
        "async_pairs": async_pairs,
        "interleaved_groups": groups,
        "compute_between": compute_between,
        "overlapped": bool(async_pairs > 0
                           or (groups >= 2 and compute_between > 0)),
    }


def collective_bytes(hlo_text: str) -> int:
    """Bytes produced by collective instructions in the entry
    computation — the static numerator of the in-program communication
    fraction (``GoodputTracker.set_program_comm_fraction``).  Each
    collective's OUTPUT shape is counted once; start/done pairs count
    the start only (the done re-states the same transfer), and a
    start's tuple shape ``(operand..., result)`` counts only its LAST
    component — summing the whole tuple would double-count the
    operand buffers the async form carries along."""
    total = 0
    for op, shape, _line in collective_summary(hlo_text):
        if op in _COMPUTE_OPS or op.endswith("-done") \
                or op == "async-done":
            continue
        if op.endswith("-start") or op == "async-start":
            parts = re.findall(r"[a-z0-9]+\[[0-9,]*\]", shape)
            if parts:
                total += shape_bytes(parts[-1])
                continue
        total += shape_bytes(shape)
    return total
