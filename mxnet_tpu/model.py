"""Model helpers: checkpointing + kvstore setup + legacy FeedForward.

Parity with ``python/mxnet/model.py`` (933 LoC): BatchEndParam,
_create_kvstore (update_on_kvstore heuristic, model.py:39-76),
_initialize_kvstore, _update_params(_on_kvstore) (push-then-pull with
priority, model.py:88-115), save/load_checkpoint, FeedForward.
"""

from __future__ import annotations

import logging
from collections import namedtuple
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import ndarray as nd
from . import symbol as sym_mod
from .base import MXNetError
from .ndarray import NDArray

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint", "FeedForward"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """Create kvstore + decide update_on_kvstore (reference: model.py:39-76)."""
    from . import kvstore as kvs

    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        # 'tpu' always creates (it activates the mesh even on one
        # context); reference rule otherwise: single device local → None
        if num_device == 1 and "dist" not in kvstore and kvstore != "tpu":
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(np.prod(p.shape) for p in arg_params.values())
                if max_size < 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is not None and kv.type.startswith("tpu"):
        # mesh kvstore: the optimizer update runs inside the fused
        # program (the sharded-update analogue of update_on_kvstore)
        update_on_kvstore = False
    # dist_* keeps update_on_kvstore=True (reference rule, model.py:64:
    # the optimizer runs store-side — here a replicated updater fed by
    # the cross-process allgather-sum, or the async parameter server)
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    """reference: model.py:78-86"""
    for idx, param_on_devs in enumerate(param_arrays):
        kvstore.init(idx, arg_params[param_names[idx]])
        if update_on_kvstore:
            kvstore.pull(idx, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore):
    """push grads, pull weights (reference: model.py:88-97).

    Two phases, not per-key push-then-pull: EVERY key's push is issued
    first (an async kvstore enqueues them into its comm scheduler and
    returns immediately), then the pulls.  On a store exposing
    ``pull_async`` the pulls are deferred all the way to the true
    dependency point — the Module drains them right before parameters
    are next consumed — so the gradient round-trips overlap the end of
    the step, the metric update and the next batch's input pipeline
    instead of serializing inside update()."""
    live = []
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list is None or (isinstance(grad_list, list) and grad_list[0] is None):
            continue
        kvstore.push(index, grad_list, priority=-index)
        live.append((index, arg_list))
    pull_async = getattr(kvstore, "pull_async", None)
    for index, arg_list in live:
        if pull_async is not None:
            pull_async(index, arg_list, priority=-index)
        else:
            kvstore.pull(index, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device, kvstore=None):
    """reference: model.py:99-115.

    Pushes are issued for every key before the first (synchronous)
    pull: the pulled values feed the local updater below, so this path
    waits per key — but an async kvstore still overlaps key k's
    round-trip with key k+1..N's pushes and earlier keys' updates."""
    live = []
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if not isinstance(arg_list, list):
            arg_list, grad_list = [arg_list], [grad_list]
        if grad_list[0] is None:
            continue
        if kvstore:
            kvstore.push(index, grad_list, priority=-index)
        live.append((index, arg_list, grad_list))
    for index, arg_list, grad_list in live:
        if kvstore:
            kvstore.pull(index, grad_list, priority=-index)
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            updater(index * num_device + k, g, w)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Save symbol json + params (reference: model.py save_checkpoint;
    format: prefix-symbol.json + prefix-%04d.params).

    Parameters are saved LAYOUT-INDEPENDENTLY: a tensor- or pipeline-
    sharded (tp/pp mesh) array is gathered to its full host value
    first — on a process-spanning mesh every rank must call this in
    lockstep (the gather is a collective).  The checkpoint then loads
    under ANY mesh layout, matching the PR-4 optimizer-state contract.

    Both files are written crash-safely (tmp file + fsync +
    ``os.replace``): a kill at any point leaves either the previous
    checkpoint or the new one on disk, never a truncated hybrid."""
    from .checkpoint import atomic_save

    if symbol is not None:
        atomic_save(f"{prefix}-symbol.json", symbol.save)

    def full(v):
        d = getattr(v, "_data", None)
        if d is not None and not getattr(d, "is_fully_addressable", True):
            return nd.array(nd.gather_global(v))
        return v

    save_dict = {f"arg:{k}": full(v) for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": full(v) for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    atomic_save(param_name, lambda tmp: nd.save(tmp, save_dict))
    logging.info('Saved checkpoint to "%s"', param_name)


def _load_checkpoint_file(path, what, loader):
    import os

    if not os.path.exists(path):
        raise MXNetError(f"load_checkpoint: missing {what} file {path!r}")
    try:
        return loader(path)
    except MXNetError:
        raise
    except Exception as exc:
        raise MXNetError(
            f"load_checkpoint: corrupt or truncated {what} file {path!r}: "
            f"{exc}")


def load_checkpoint(prefix, epoch):
    """reference: model.py load_checkpoint — with errors that NAME the
    missing or corrupt file instead of surfacing a raw parse failure."""
    symbol = _load_checkpoint_file(f"{prefix}-symbol.json", "symbol",
                                   sym_mod.load)
    param_path = "%s-%04d.params" % (prefix, epoch)

    def load_params(path):
        d = nd.load(path)
        if not isinstance(d, dict):
            raise MXNetError("params file holds a list, not a name->array "
                             "dict")
        return d

    save_dict = _load_checkpoint_file(param_path, "params", load_params)
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return symbol, arg_params, aux_params


class FeedForward:
    """Legacy scikit-style model API (reference: model.py:386 FeedForward).

    Thin adapter over Module — kept for script parity.
    """

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from .initializer import Uniform

        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer if initializer is not None else Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.kwargs = kwargs
        self._module = None

    def _get_module(self, data, label_name="softmax_label"):
        from .module import Module

        if self._module is None:
            self._module = Module(self.symbol, context=self.ctx,
                                  label_names=(label_name,))
        return self._module

    def fit(self, X, y=None, eval_data=None, eval_metric="acc", epoch_end_callback=None,
            batch_end_callback=None, kvstore="local", logger=None,
            work_load_list=None, monitor=None, eval_end_callback=None,
            eval_batch_end_callback=None):
        train_data = self._as_iter(X, y)
        mod = self._get_module(train_data)
        mod.fit(train_data, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer, optimizer_params=self.kwargs,
                initializer=self.initializer,
                arg_params=self.arg_params, aux_params=self.aux_params,
                begin_epoch=self.begin_epoch, num_epoch=self.num_epoch,
                monitor=monitor)
        self.arg_params, self.aux_params = mod.get_params()

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        data = self._as_iter(X, None)
        mod = self._get_module(data)
        if not mod.binded:
            mod.bind(data_shapes=data.provide_data, for_training=False)
            mod.init_params(self.initializer, arg_params=self.arg_params,
                            aux_params=self.aux_params, allow_missing=False)
        outs = mod.predict(data, num_batch=num_batch, reset=reset)
        out = outs.asnumpy() if isinstance(outs, NDArray) else [o.asnumpy() for o in outs]
        return out

    def score(self, X, y=None, eval_metric="acc", num_batch=None, reset=True):
        data = self._as_iter(X, y)
        mod = self._get_module(data)
        res = mod.score(data, eval_metric, num_batch=num_batch)
        return res[0][1]

    def _as_iter(self, X, y):
        from .io import DataIter, NDArrayIter

        if isinstance(X, DataIter):
            return X
        return NDArrayIter(X, y, batch_size=self.numpy_batch_size)

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch or 0
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, epoch_size=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None, batch_end_callback=None,
               kvstore="local", logger=None, work_load_list=None, **kwargs):
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger, work_load_list=work_load_list)
        return model
