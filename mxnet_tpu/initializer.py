"""Weight initializers.

Parity with ``python/mxnet/initializer.py`` (286 LoC): name-based
dispatch (bias→0, gamma→1, beta→0, moving stats) + Uniform, Normal,
Orthogonal, Xavier, MSRAPrelu, Load, Mixed, Zero, One, Constant.
"""

from __future__ import annotations

import json
import re
from typing import Dict

import numpy as np

from .base import MXNetError
from .ndarray import NDArray, array

__all__ = [
    "Initializer", "Uniform", "Normal", "Orthogonal", "Xavier", "MSRAPrelu",
    "Load", "Mixed", "Zero", "One", "Constant", "InitDesc",
]


class InitDesc(str):
    """Name + attrs descriptor (forward-compat with later reference versions)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base: dispatch on parameter name (reference: initializer.py:15-77)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self) -> str:
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, name, arr: NDArray):
        if not isinstance(name, str):
            raise TypeError("name must be a string")
        if not isinstance(arr, NDArray):
            raise TypeError("arr must be NDArray")
        if name.startswith("upsampling"):
            self._init_bilinear(name, arr)
        elif name.endswith("bias"):
            self._init_bias(name, arr)
        elif name.endswith("gamma"):
            self._init_gamma(name, arr)
        elif name.endswith("beta"):
            self._init_beta(name, arr)
        elif name.endswith("weight"):
            self._init_weight(name, arr)
        elif name.endswith("parameters"):
            # fused RNN packed parameter vector ({name}_parameters,
            # rnn-inl.h); weight-like init over the flat vector
            self._init_weight(name, arr)
        elif name.endswith("moving_mean") or name.endswith("running_mean"):
            self._init_zero(name, arr)
        elif name.endswith("moving_var") or name.endswith("running_var"):
            self._init_one(name, arr)
        elif name.endswith("moving_inv_var"):
            self._init_zero(name, arr)
        elif name.endswith("moving_avg"):
            self._init_zero(name, arr)
        else:
            self._init_default(name, arr)

    def _init_bilinear(self, _, arr):
        shape = arr.shape
        weight = np.zeros(int(np.prod(shape)), dtype="float32")
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)

    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    def _init_bias(self, _, arr):
        arr[:] = 0.0

    def _init_gamma(self, _, arr):
        arr[:] = 1.0

    def _init_beta(self, _, arr):
        arr[:] = 0.0

    def _init_weight(self, name, arr):
        raise NotImplementedError("must override _init_weight")

    def _init_default(self, name, arr):
        raise MXNetError(
            f"Unknown initialization pattern for {name!r}. Default initialization "
            "is now limited to weight/bias/gamma/beta/moving_* names")


class Load:
    """Init from saved dict, falling back to default_init (reference:
    initializer.py Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            from .ndarray import load as nd_load

            param = nd_load(param)
        self.param = {}
        for name, arr in param.items():
            self.param[name.replace("arg:", "").replace("aux:", "")] = arr
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            if self.param[name].shape != arr.shape:
                raise MXNetError(f"Parameter {name} shape mismatch: "
                                 f"{self.param[name].shape} vs {arr.shape}")
            arr[:] = self.param[name]
        else:
            if self.default_init is None:
                raise MXNetError(f"Cannot init parameter {name} — not in loaded file "
                                 "and no default_init given")
            self.default_init(name, arr)


class Mixed:
    """Pattern-matched initializer list (reference: initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise MXNetError("patterns and initializers must have same length")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise MXNetError(f"Parameter {name} did not match any pattern")


class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 0.0


class One(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 1.0


class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr[:] = self.value


class Uniform(Initializer):
    """U(-scale, scale) (reference: initializer.py Uniform)."""

    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        from . import ndarray as nd

        nd.uniform(low=-self.scale, high=self.scale, shape=arr.shape, out=arr)


class Normal(Initializer):
    """N(0, sigma) (reference: initializer.py Normal)."""

    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        from . import ndarray as nd

        nd.normal(loc=0.0, scale=self.sigma, shape=arr.shape, out=arr)


class Orthogonal(Initializer):
    """Orthogonal init (reference: initializer.py Orthogonal)."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        res = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * res).reshape(arr.shape)


class Xavier(Initializer):
    """Xavier/Glorot (reference: initializer.py Xavier)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError("Incorrect factor type")
        scale = np.sqrt(self.magnitude / factor)
        from . import ndarray as nd

        if self.rnd_type == "uniform":
            nd.uniform(low=-scale, high=scale, shape=arr.shape, out=arr)
        elif self.rnd_type == "gaussian":
            nd.normal(loc=0.0, scale=scale, shape=arr.shape, out=arr)
        else:
            raise MXNetError("Unknown random type")


class MSRAPrelu(Xavier):
    """MSRA/He init for PReLU nets (reference: initializer.py MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}
