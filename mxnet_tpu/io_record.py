"""ImageRecordIter: the packed-image training data pipeline.

Capability parity with the reference's C++ chain
``ImageRecordIOParser → ImageAugmenter → ImageNormalizeIter →
BatchLoader → PrefetcherIter`` (``src/io/iter_image_recordio.cc:29-120``,
``image_aug_default.cc``, ``iter_normalize.h``, ``iter_batchloader.h``;
SURVEY §2.5), including ``num_parts``/``part_index`` sharding for
distributed workers and mean-image caching.

TPU-first design: record framing is native C++ (``native/recordio.cc``),
JPEG decode + augmentation run in a thread pool (cv2 releases the GIL),
normalization is vectorized per batch, and device staging/overlap comes
from wrapping in ``PrefetchingIter(ctx=...)`` rather than a bespoke
prefetch thread — one prefetch mechanism for every iterator.

Scaling past one process (the 7x real-vs-synthetic gap, PERF.md "Input
pipeline"): ``workers=N`` fans the decode out to N processes writing a
zero-copy shared-memory ring (``mxnet_tpu.io_pool.DecodePool``), and
``device_augment=1`` moves crop/flip/normalize/mixup onto the device as
a fused jitted prologue of the training step — the iterator then yields
raw uint8 NHWC batches (4x fewer H2D bytes) plus a ``device_prologue``
that ``Module.fit`` installs automatically.  ``workers=0`` (default)
keeps the original single-process path; both modes preserve the exact
``state_dict``/``set_state`` resume contract (the pool is torn down,
rebuilt under the restored order, and skipped to the consumer
position).
"""

from __future__ import annotations

import logging
import os
import random as _pyrandom
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from . import image as _image
from . import io_pool as _iopool
from . import ndarray as nd
from . import recordio as rio
from .base import MXNetError
from .io import DataBatch, DataDesc, DataIter

__all__ = ["ImageRecordIter"]

# mean images already computed/loaded this process, keyed by absolute
# path: N consumers (or a parent about to fork a decode pool) pay the
# full-dataset pass / file read ONCE — workers then inherit the array
# through fork for free
_MEAN_CACHE = {}
_MEAN_CACHE_LOCK = threading.Lock()


def _stage_batch(arr):
    """Freshly assembled batch buffer -> NDArray via ``io.stage_array``:
    the transfer starts asynchronously, the bytes land in the
    ``io.h2d_bytes`` counter (the uint8-vs-f32 wire saving is a
    first-class metric), and — unlike ``nd.array`` — no defensive copy
    is made, because the buffer is this iterator's own and never
    reused."""
    from .io import stage_array
    from .ndarray import NDArray, _device

    ctx, dev = _device(None)
    return NDArray(stage_array(arr, dev), ctx)


class ImageRecordIter(DataIter):
    """Iterate packed-image records as augmented NCHW float batches.

    Parameters mirror the reference iterator's
    (``iter_image_recordio.cc:93-120`` + augmenter/normalize params):
    ``path_imgrec``, ``path_imgidx``, ``data_shape`` (CHW), ``batch_size``,
    ``label_width``, ``shuffle``, ``num_parts``/``part_index`` (worker
    sharding), ``round_batch`` (wrap the last partial batch and report
    ``pad``), ``preprocess_threads``, mean/std/scale normalization
    (``mean_img`` file caching like iter_normalize.h), and the
    augmentation knobs (resize, rand_crop, rand_mirror, rotate/shear/
    scale/aspect, HSL).

    TPU data-plane extensions:

    ``workers``
        0 (default): decode in-process.  N > 0: delegate decode to an
        N-process ``DecodePool`` over a shared-memory ring; ``'auto'``
        sizes it ``min(cpu_count, 8)``.  ``None`` reads
        ``MXNET_IO_WORKERS``.
    ``device_augment``
        1: the iterator yields raw uint8 NHWC batches (host does decode
        + one fixed resize only) and exposes ``device_prologue`` — the
        fused jitted crop/flip/normalize/mixup that runs inside the
        training step under the per-step PRNG key.  ``None`` reads
        ``MXNET_IO_DEVICE_AUGMENT``.
    ``ring_slots``
        Ring depth in batches (``None``: ``MXNET_IO_RING_SLOTS`` or
        ``2*workers + 2``).
    ``mixup_alpha``
        Beta(alpha, alpha) batch mixup in the device prologue
        (requires ``device_augment=1``).
    """

    def __init__(self, path_imgrec, data_shape, batch_size,
                 path_imgidx=None, path_imglist=None, label_width=1,
                 shuffle=False, seed=0,
                 mean_img=None, mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 std_r=0.0, std_g=0.0, std_b=0.0, scale=1.0,
                 resize=0, rand_crop=False, rand_resize=False,
                 rand_mirror=False, max_rotate_angle=0, max_shear_ratio=0,
                 max_aspect_ratio=0, min_random_scale=1.0,
                 max_random_scale=1.0, random_h=0, random_s=0, random_l=0,
                 fill_value=255, inter_method=None,
                 num_parts=1, part_index=0, round_batch=True,
                 preprocess_threads=4, data_name="data",
                 label_name="softmax_label", dtype="float32",
                 workers=None, device_augment=None, ring_slots=None,
                 mixup_alpha=0.0, **kwargs):
        super().__init__(batch_size)
        if kwargs:
            # the reference C++ iterator rejects unknown parameters too
            raise TypeError("unsupported ImageRecordIter parameters: "
                            f"{sorted(kwargs)}")
        if not os.path.isfile(path_imgrec):
            raise MXNetError(f"ImageRecordIter: no such file {path_imgrec!r}")
        assert len(data_shape) == 3, "data_shape must be (C, H, W)"
        assert 0 <= part_index < num_parts
        if data_shape[0] == 1 and (random_h or random_s or random_l):
            raise MXNetError("HSL jitter (random_h/s/l) requires 3-channel "
                             "data_shape")
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.round_batch = round_batch
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = np.dtype(dtype)
        self._seed = seed
        self._epoch = 0
        self._rng = np.random.RandomState(seed)
        self._path_imgrec = path_imgrec
        # one reader per decode thread: seek+read is stateful.  All
        # created readers are also tracked here so close() can release
        # the file handles without waiting for thread-local GC.
        self._tls = threading.local()
        self._readers = []
        self._readers_lock = threading.Lock()

        # --- data-plane mode (validated loudly AT CONSTRUCTION, like
        # the checkpoint knobs: garbage env values raise here) --------
        self._workers = _iopool.resolve_workers(workers)
        self._device_augment = _iopool.resolve_device_augment(device_augment)
        self._ring_slots = _iopool.resolve_ring_slots(ring_slots,
                                                      self._workers)
        self._mixup_alpha = float(mixup_alpha)
        if self._mixup_alpha < 0:
            raise MXNetError(f"mixup_alpha={mixup_alpha!r} must be >= 0")
        if self._mixup_alpha and not self._device_augment:
            raise MXNetError("mixup_alpha needs device_augment=1 (mixup "
                             "runs in the device prologue)")
        self._rand_crop = bool(rand_crop)
        self._rand_mirror = bool(rand_mirror)
        self._dpool = None
        self._dpool_epoch_sent = False
        self._prologue = None
        if self._device_augment:
            unsupported = {
                "rand_resize": rand_resize, "max_rotate_angle": max_rotate_angle,
                "max_shear_ratio": max_shear_ratio,
                "max_aspect_ratio": max_aspect_ratio,
                "random_h": random_h, "random_s": random_s,
                "random_l": random_l,
                "min_random_scale": (min_random_scale
                                     if min_random_scale != 1.0 else 0),
                "max_random_scale": (max_random_scale
                                     if max_random_scale != 1.0 else 0)}
            bad = sorted(k for k, v in unsupported.items() if v)
            if bad:
                raise MXNetError(
                    "device_augment=1 supports crop/flip/normalize/mixup "
                    f"on device; unsupported host augmentations set: {bad} "
                    "(use device_augment=0 for those)")
            self._pre_shape = _iopool.default_pre_shape(
                self.data_shape, resize=resize, rand_crop=rand_crop)
            # the one host-side resize honors the user's interpolation
            self._inter_method = inter_method

        # --- optional label map: image id -> fresh labels, overriding
        # the labels packed in the records (reference: "supply a list
        # file that maps image id to new labels",
        # src/io/image_recordio.h:24-30 + iter_image_recordio.cc:29-90)
        self._label_map = None
        if path_imglist:
            self._label_map = {}
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) < 2:
                        continue
                    self._label_map[int(parts[0])] = np.asarray(
                        [float(x) for x in parts[1:1 + label_width]],
                        np.float32)

        # --- record offsets, sharded across workers -------------------
        if path_imgidx and os.path.isfile(path_imgidx):
            keys, idx = rio.read_idx_file(path_imgidx)
            offsets = [idx[k] for k in keys]
        else:
            offsets = rio.list_records(path_imgrec)
        if not offsets:
            raise MXNetError(f"ImageRecordIter: {path_imgrec!r} is empty")
        # strided partition: same per-worker count (±1) without needing
        # the byte-balanced InputSplit machinery of dmlc-core
        self._offsets = np.asarray(offsets[part_index::num_parts], np.int64)
        self.num_data = len(self._offsets)
        if self.num_data < batch_size and not round_batch:
            raise MXNetError("fewer records than batch_size in this part")

        # --- augmentation pipeline ------------------------------------
        if self._device_augment:
            # host side does decode + ONE fixed resize; crop/flip/
            # normalize/mixup run on device in the fused prologue
            self._auglist = []
        else:
            self._auglist = _image.CreateAugmenter(
                self.data_shape, resize=resize, rand_crop=rand_crop,
                rand_resize=rand_resize, rand_mirror=rand_mirror,
                random_h=random_h, random_s=random_s, random_l=random_l,
                max_rotate_angle=max_rotate_angle,
                max_shear_ratio=max_shear_ratio,
                max_aspect_ratio=max_aspect_ratio,
                min_random_scale=min_random_scale,
                max_random_scale=max_random_scale,
                fill_value=fill_value, inter_method=inter_method)

        # --- normalization (iter_normalize.h behavior) ----------------
        c = self.data_shape[0]
        self._scale = float(scale)
        self._mean = None   # (C,1,1) or full CHW image
        self._std = None
        if any((mean_r, mean_g, mean_b)):
            self._mean = np.array([mean_r, mean_g, mean_b][:c],
                                  np.float32).reshape(c, 1, 1)
        if any((std_r, std_g, std_b)):
            self._std = np.array([std_r or 1, std_g or 1, std_b or 1][:c],
                                 np.float32).reshape(c, 1, 1)
        if mean_img:
            self._mean = self._load_or_compute_mean(mean_img)

        self._preprocess_threads = max(1, preprocess_threads)
        self._pool = None  # in-process decode executor, created lazily
        self._order = np.arange(self.num_data)
        self._cursor = 0
        self._seen_epoch_end = False
        self.reset()

    # ------------------------------------------------------------------
    def _executor(self):
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self._preprocess_threads)
        return self._pool

    def _read_at(self, offset):
        rec = getattr(self._tls, "record", None)
        if rec is None:
            rec = rio.MXRecordIO(self._path_imgrec, "r")
            self._tls.record = rec
            with self._readers_lock:
                self._readers.append(rec)
        rec.seek(int(offset))
        s = rec.read()
        if s is None:
            raise MXNetError("truncated record file")
        return s

    def _decode_one(self, offset, payload=None, out=None, epoch=None):
        c = self.data_shape[0]
        if payload is None:
            payload = self._read_at(offset)
        header, img = rio.unpack_img(payload, iscolor=0 if c == 1 else 1)
        if c == 1:
            img = img[:, :, None]  # HW -> HW1
        else:
            if img.ndim == 2:
                img = img[:, :, None].repeat(3, axis=2)
            img = img[:, :, ::-1]  # BGR -> RGB (augmenters/means are RGB)
        # per-sample rng: reproducible regardless of thread scheduling
        # (and of which pool worker decodes the sample)
        epoch = self._epoch if epoch is None else epoch
        rng = _pyrandom.Random(hash((self._seed, epoch, int(offset))))
        for aug in self._auglist:
            img = aug(img, rng)
            if img.ndim == 2:
                img = img[:, :, None]  # cv2 ops drop the dim of (H,W,1)
        label = self._label_of(header)
        if out is not None:
            # single conversion+transpose pass into the caller's batch
            # buffer (dtype cast fused into the copy)
            np.copyto(out, img.transpose(2, 0, 1), casting="unsafe")
            return out, label
        chw = np.ascontiguousarray(
            np.asarray(img, np.float32).transpose(2, 0, 1))
        return chw, label

    def _decode_raw_one(self, offset, payload=None, out=None):
        """Device-augment decode: JPEG -> RGB -> ONE fixed resize to
        ``pre_shape`` -> uint8 HWC into ``out`` (a ring-slot row or a
        local batch buffer).  No host augmentation, no float conversion
        — that all happens on device in the fused prologue."""
        import cv2

        c = self.data_shape[0]
        if payload is None:
            payload = self._read_at(offset)
        header, img = rio.unpack_img(payload, iscolor=0 if c == 1 else 1)
        if c == 1:
            img = img[:, :, None]
        else:
            if img.ndim == 2:
                img = img[:, :, None].repeat(3, axis=2)
            img = img[:, :, ::-1]
        preH, preW = self._pre_shape
        if img.shape[:2] != (preH, preW):
            interp = (self._inter_method if self._inter_method is not None
                      else cv2.INTER_LINEAR)
            # aspect-preserving cover-resize + center crop into the
            # fixed ring window — matching the legacy ResizeAug
            # short-edge semantics, never a warping square resize
            h, w = img.shape[:2]
            s = max(preH / h, preW / w)
            nh = max(preH, int(round(h * s)))
            nw = max(preW, int(round(w * s)))
            img = cv2.resize(img, (nw, nh), interpolation=interp)
            if img.ndim == 2:
                img = img[:, :, None]
            y0, x0 = (nh - preH) // 2, (nw - preW) // 2
            img = img[y0:y0 + preH, x0:x0 + preW]
        np.copyto(out, img, casting="unsafe")
        return self._label_of(header)

    def _label_of(self, header):
        if self._label_map is not None:
            label = self._label_map.get(header.id)
            if label is None:
                # mixing remapped and packed labels would silently train
                # on wrong data (the reference's ImageLabelMap::Find
                # hard-fails the same way)
                raise MXNetError(
                    f"image id {header.id} not found in path_imglist")
        else:
            label = header.label
        if isinstance(label, np.ndarray):
            label = label[:self.label_width]
        else:
            label = np.array([label], np.float32)[:self.label_width]
        return np.asarray(label, np.float32)

    # -- decode-pool plumbing ------------------------------------------
    def _decode_batch_into(self, idxs, epoch, data_out, label_out):
        """Decode one whole batch into caller-provided buffers (the
        pool workers' entry point — ``data_out``/``label_out`` are ring
        slot views, so the decode IS the shared-memory write)."""
        offsets = self._offsets[np.asarray(idxs)]
        from . import _native
        if _native.lib() is not None:
            # same native batched payload fetch as the workers=0 path
            # (per-record Python seek/read measured as significant
            # overhead there); single-threaded — each pool worker IS
            # one decode lane
            payloads = rio.read_batch(self._path_imgrec, offsets,
                                      threads=1)
        else:
            payloads = [None] * len(offsets)
        for j, off in enumerate(offsets):
            if self._device_augment:
                label_out[j] = self._decode_raw_one(off, payloads[j],
                                                    out=data_out[j])
            else:
                _, lab = self._decode_one(off, payloads[j],
                                          out=data_out[j], epoch=epoch)
                label_out[j] = lab

    def _worker_reset_after_fork(self):
        """Make a forked decode worker self-contained: fresh record
        readers (the parent's fds share a file offset — seeking them
        from two processes races), no inherited thread pool (its
        threads did not survive the fork), and no pool handle (a worker
        must never recurse into ring management)."""
        self._tls = threading.local()
        self._readers = []
        self._readers_lock = threading.Lock()
        self._pool = None
        self._dpool = None

    def _slot_spec(self):
        if self._device_augment:
            return self._pre_shape + (self.data_shape[0],), np.uint8
        return self.data_shape, np.float32

    def _pool_next(self, expect_b):
        if self._dpool is None:
            slot_shape, slot_dtype = self._slot_spec()
            self._dpool = _iopool.DecodePool(
                self, self._workers, self._ring_slots, slot_shape,
                slot_dtype)
            self._dpool_epoch_sent = False
        try:
            if not self._dpool_epoch_sent:
                self._dpool.begin_epoch(self._epoch, self._order,
                                        start_batch=expect_b)
                self._dpool_epoch_sent = True
            out = self._dpool.next_batch()
            if out is None or out[2] != expect_b:
                got = None if out is None else out[2]
                raise MXNetError(f"decode pool out of sync: expected batch "
                                 f"{expect_b}, got {got}")
        except MXNetError:
            # fatal pool state (poisoned batch, dead fleet, desync):
            # release the workers and shm NOW rather than at iterator
            # GC; a caught error followed by reset() gets a fresh pool
            self._dpool.close()
            self._dpool = None
            self._dpool_epoch_sent = False
            raise
        return out[0], out[1]

    def _load_or_compute_mean(self, mean_path):
        key = os.path.abspath(mean_path)
        with _MEAN_CACHE_LOCK:
            cached = _MEAN_CACHE.get(key)
        if cached is not None:
            return cached
        if os.path.isfile(mean_path):
            loaded = nd.load(mean_path)
            arr = (loaded["mean_img"] if isinstance(loaded, dict)
                   else loaded[0])
            mean = arr.asnumpy().astype(np.float32)
        else:
            logging.info("ImageRecordIter: computing mean image -> %s",
                         mean_path)
            acc = np.zeros(self.data_shape, np.float64)
            n = 0
            if self._device_augment:
                # the host augmenter list is empty in this mode, so a
                # plain _decode_one would keep each record's native
                # size; accumulate over the fixed-resize + CENTER-crop
                # view instead — the same data_shape window the device
                # prologue normalizes at eval time
                preH, preW = self._pre_shape
                _, H, W = self.data_shape
                y0, x0 = (preH - H) // 2, (preW - W) // 2
                buf = np.empty((preH, preW, self.data_shape[0]), np.uint8)
                for off in self._offsets:
                    self._decode_raw_one(off, out=buf)
                    acc += buf[y0:y0 + H, x0:x0 + W].transpose(2, 0, 1)
                    n += 1
            else:
                for off in self._offsets:
                    chw, _ = self._decode_one(off)
                    acc += chw
                    n += 1
            mean = (acc / max(n, 1)).astype(np.float32)
            nd.save(mean_path, {"mean_img": nd.array(mean)})
        with _MEAN_CACHE_LOCK:
            # computed ONCE per process; pool workers inherit the array
            # through fork, so N workers never redo the full pass
            _MEAN_CACHE[key] = mean
        return mean

    # ------------------------------------------------------------------
    @property
    def provide_data(self):
        return [DataDesc(self.data_name, (self.batch_size,) + self.data_shape,
                         self.dtype)]

    @property
    def provide_label(self):
        shape = ((self.batch_size,) if self.label_width == 1
                 else (self.batch_size, self.label_width))
        return [DataDesc(self.label_name, shape, np.float32)]

    @property
    def raw_provide_data(self):
        """Shape/dtype of the batches actually yielded: the raw uint8
        NHWC wire format in device-augment mode (what crosses H2D),
        else the final descriptor."""
        if not self._device_augment:
            return self.provide_data
        preH, preW = self._pre_shape
        return [DataDesc(self.data_name,
                         (self.batch_size, preH, preW, self.data_shape[0]),
                         np.uint8, layout="NHWC")]

    @property
    def device_prologue(self):
        """The fused jitted device-side augment (crop/flip/normalize/
        mixup) paired with this iterator's raw batches; ``Module.fit``
        installs it automatically.  None unless ``device_augment=1``."""
        if not self._device_augment:
            return None
        if self._prologue is None:
            self._prologue = _iopool.make_device_prologue(
                self.data_name, self.data_shape, self._pre_shape,
                self.dtype, rand_crop=self._rand_crop,
                rand_mirror=self._rand_mirror, mean=self._mean,
                std=self._std, scale=self._scale,
                mixup_alpha=self._mixup_alpha)
        return self._prologue

    def reset(self):
        if self.shuffle:
            self._rng.shuffle(self._order)
        self._epoch += 1
        self._cursor = 0
        self._seen_epoch_end = False
        self._dpool_epoch_sent = False  # pool restarts lazily on next()

    def state_dict(self):
        return {"kind": "ImageRecordIter", "cursor": int(self._cursor),
                "order": self._order.copy(), "epoch": int(self._epoch),
                "seen_epoch_end": bool(self._seen_epoch_end),
                "rng": self._rng.get_state(), "seed": self._seed,
                "num_data": int(self.num_data),
                "workers": int(self._workers)}

    def set_state(self, state, rewind=False):
        if state.get("kind") != "ImageRecordIter":
            raise MXNetError("ImageRecordIter.set_state: wrong snapshot "
                             "kind")
        if int(state["num_data"]) != self.num_data:
            raise MXNetError(
                "ImageRecordIter.set_state: snapshot has num_data="
                f"{state['num_data']}, this iterator has {self.num_data} "
                "(different record file or sharding?)")
        # pool mode: tear the workers down FIRST (they may be mid-epoch
        # under the old order); the pool is rebuilt lazily on the next
        # next() and told to start straight at the restored batch
        # position — resume never re-decodes consumed batches
        if self._dpool is not None:
            self._dpool.close()
            self._dpool = None
        self._dpool_epoch_sent = False
        self._order = np.asarray(state["order"]).copy()
        self._cursor = 0 if rewind else int(state["cursor"])
        self._epoch = int(state["epoch"])
        self._seen_epoch_end = (False if rewind
                                else bool(state["seen_epoch_end"]))
        self._rng.set_state(state["rng"])
        # the per-sample augmentation stream is keyed on (seed, epoch,
        # offset) — restore the seed so augmentations replay too
        self._seed = state["seed"]

    def iter_next(self):
        return self._cursor < self.num_data and not self._seen_epoch_end

    def next(self):
        if not self.iter_next():
            raise StopIteration
        start = self._cursor
        stop = start + self.batch_size
        pad = 0
        b = start // self.batch_size
        if stop >= self.num_data:
            self._seen_epoch_end = True
            if stop > self.num_data:
                if not self.round_batch:
                    raise StopIteration
                pad = stop - self.num_data
        # ONE slicing formula (incl. the modular pad wrap) shared with
        # the pool workers — bit-identical batches for any worker count
        # by construction, not by keeping two copies in sync
        idxs = _iopool.batch_indices(self._order, b, self.batch_size,
                                     self.num_data)
        self._cursor = stop

        if self._workers > 0:
            data, label = self._pool_next(b)
        else:
            data, label = self._decode_batch_local(idxs)

        if self.label_width == 1:
            label = label[:, 0]
        if self._device_augment:
            # raw uint8 NHWC over the wire; crop/flip/normalize/mixup
            # happen on device in the fused prologue
            return DataBatch([_stage_batch(data)], [_stage_batch(label)],
                             pad=pad, index=np.asarray(idxs),
                             provide_data=self.raw_provide_data,
                             provide_label=self.provide_label)
        # vectorized normalize (iter_normalize.h: (img - mean) * scale / std)
        if self._mean is not None:
            data -= self._mean
        if self._std is not None:
            data /= self._std
        if self._scale != 1.0:
            data *= self._scale
        return DataBatch([_stage_batch(data.astype(self.dtype, copy=False))],
                         [_stage_batch(label)], pad=pad,
                         index=np.asarray(idxs),
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def _decode_batch_local(self, idxs):
        """Single-process batch assembly (the ``workers=0`` path)."""
        offsets = self._offsets[idxs]
        from . import _native
        if _native.lib() is not None:
            # one native threaded call fetches all payloads (no
            # per-record Python seek/read); decode+augment still fan
            # out over the pool
            payloads = rio.read_batch(self._path_imgrec, offsets,
                                      threads=self._preprocess_threads)
        else:
            payloads = [None] * len(offsets)  # per-thread cached readers

        # decode straight into a preallocated batch buffer: one
        # uint8->f32 conversion+transpose per image (np.copyto), no
        # np.stack second copy — and chunked pool submissions so the
        # futures machinery costs O(threads), not O(batch) (profiled:
        # stack+per-sample futures were ~35% of iterator time on the
        # reference JPEG set; the OMP loop in the reference's
        # iter_image_recordio.cc:29-120 writes into the batch the same
        # way)
        n = len(offsets)
        slot_shape, slot_dtype = self._slot_spec()
        data = np.empty((n,) + slot_shape, slot_dtype)
        label = np.empty((n, self.label_width), np.float32)

        def work(lo, hi):
            for j in range(lo, hi):
                if self._device_augment:
                    label[j] = self._decode_raw_one(offsets[j], payloads[j],
                                                    out=data[j])
                else:
                    _, lab = self._decode_one(offsets[j], payloads[j],
                                              out=data[j])
                    label[j] = lab

        nchunk = min(self._preprocess_threads, n) or 1
        bounds = np.linspace(0, n, nchunk + 1, dtype=int)
        if nchunk == 1:
            work(0, n)
        else:
            list(self._executor().map(
                lambda t: work(bounds[t], bounds[t + 1]), range(nchunk)))
        return data, label

    def close(self):
        if self._dpool is not None:
            self._dpool.close()
            self._dpool = None
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        with self._readers_lock:
            for rec in self._readers:
                rec.close()
            self._readers.clear()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
