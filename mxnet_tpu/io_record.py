"""ImageRecordIter: the packed-image training data pipeline.

Capability parity with the reference's C++ chain
``ImageRecordIOParser → ImageAugmenter → ImageNormalizeIter →
BatchLoader → PrefetcherIter`` (``src/io/iter_image_recordio.cc:29-120``,
``image_aug_default.cc``, ``iter_normalize.h``, ``iter_batchloader.h``;
SURVEY §2.5), including ``num_parts``/``part_index`` sharding for
distributed workers and mean-image caching.

TPU-first design: record framing is native C++ (``native/recordio.cc``),
JPEG decode + augmentation run in a thread pool (cv2 releases the GIL),
normalization is vectorized per batch, and device staging/overlap comes
from wrapping in ``PrefetchingIter(ctx=...)`` rather than a bespoke
prefetch thread — one prefetch mechanism for every iterator.
"""

from __future__ import annotations

import logging
import os
import random as _pyrandom
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from . import image as _image
from . import ndarray as nd
from . import recordio as rio
from .base import MXNetError
from .io import DataBatch, DataDesc, DataIter

__all__ = ["ImageRecordIter"]


class ImageRecordIter(DataIter):
    """Iterate packed-image records as augmented NCHW float batches.

    Parameters mirror the reference iterator's
    (``iter_image_recordio.cc:93-120`` + augmenter/normalize params):
    ``path_imgrec``, ``path_imgidx``, ``data_shape`` (CHW), ``batch_size``,
    ``label_width``, ``shuffle``, ``num_parts``/``part_index`` (worker
    sharding), ``round_batch`` (wrap the last partial batch and report
    ``pad``), ``preprocess_threads``, mean/std/scale normalization
    (``mean_img`` file caching like iter_normalize.h), and the
    augmentation knobs (resize, rand_crop, rand_mirror, rotate/shear/
    scale/aspect, HSL).
    """

    def __init__(self, path_imgrec, data_shape, batch_size,
                 path_imgidx=None, path_imglist=None, label_width=1,
                 shuffle=False, seed=0,
                 mean_img=None, mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 std_r=0.0, std_g=0.0, std_b=0.0, scale=1.0,
                 resize=0, rand_crop=False, rand_resize=False,
                 rand_mirror=False, max_rotate_angle=0, max_shear_ratio=0,
                 max_aspect_ratio=0, min_random_scale=1.0,
                 max_random_scale=1.0, random_h=0, random_s=0, random_l=0,
                 fill_value=255, inter_method=None,
                 num_parts=1, part_index=0, round_batch=True,
                 preprocess_threads=4, data_name="data",
                 label_name="softmax_label", dtype="float32", **kwargs):
        super().__init__(batch_size)
        if kwargs:
            # the reference C++ iterator rejects unknown parameters too
            raise TypeError("unsupported ImageRecordIter parameters: "
                            f"{sorted(kwargs)}")
        if not os.path.isfile(path_imgrec):
            raise MXNetError(f"ImageRecordIter: no such file {path_imgrec!r}")
        assert len(data_shape) == 3, "data_shape must be (C, H, W)"
        assert 0 <= part_index < num_parts
        if data_shape[0] == 1 and (random_h or random_s or random_l):
            raise MXNetError("HSL jitter (random_h/s/l) requires 3-channel "
                             "data_shape")
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.round_batch = round_batch
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = np.dtype(dtype)
        self._seed = seed
        self._epoch = 0
        self._rng = np.random.RandomState(seed)
        self._path_imgrec = path_imgrec
        # one reader per decode thread: seek+read is stateful.  All
        # created readers are also tracked here so close() can release
        # the file handles without waiting for thread-local GC.
        self._tls = threading.local()
        self._readers = []
        self._readers_lock = threading.Lock()

        # --- optional label map: image id -> fresh labels, overriding
        # the labels packed in the records (reference: "supply a list
        # file that maps image id to new labels",
        # src/io/image_recordio.h:24-30 + iter_image_recordio.cc:29-90)
        self._label_map = None
        if path_imglist:
            self._label_map = {}
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) < 2:
                        continue
                    self._label_map[int(parts[0])] = np.asarray(
                        [float(x) for x in parts[1:1 + label_width]],
                        np.float32)

        # --- record offsets, sharded across workers -------------------
        if path_imgidx and os.path.isfile(path_imgidx):
            keys, idx = rio.read_idx_file(path_imgidx)
            offsets = [idx[k] for k in keys]
        else:
            offsets = rio.list_records(path_imgrec)
        if not offsets:
            raise MXNetError(f"ImageRecordIter: {path_imgrec!r} is empty")
        # strided partition: same per-worker count (±1) without needing
        # the byte-balanced InputSplit machinery of dmlc-core
        self._offsets = np.asarray(offsets[part_index::num_parts], np.int64)
        self.num_data = len(self._offsets)
        if self.num_data < batch_size and not round_batch:
            raise MXNetError("fewer records than batch_size in this part")

        # --- augmentation pipeline ------------------------------------
        self._auglist = _image.CreateAugmenter(
            self.data_shape, resize=resize, rand_crop=rand_crop,
            rand_resize=rand_resize, rand_mirror=rand_mirror,
            random_h=random_h, random_s=random_s, random_l=random_l,
            max_rotate_angle=max_rotate_angle,
            max_shear_ratio=max_shear_ratio,
            max_aspect_ratio=max_aspect_ratio,
            min_random_scale=min_random_scale,
            max_random_scale=max_random_scale,
            fill_value=fill_value, inter_method=inter_method)

        # --- normalization (iter_normalize.h behavior) ----------------
        c = self.data_shape[0]
        self._scale = float(scale)
        self._mean = None   # (C,1,1) or full CHW image
        self._std = None
        if any((mean_r, mean_g, mean_b)):
            self._mean = np.array([mean_r, mean_g, mean_b][:c],
                                  np.float32).reshape(c, 1, 1)
        if any((std_r, std_g, std_b)):
            self._std = np.array([std_r or 1, std_g or 1, std_b or 1][:c],
                                 np.float32).reshape(c, 1, 1)
        if mean_img:
            self._mean = self._load_or_compute_mean(mean_img)

        self._preprocess_threads = max(1, preprocess_threads)
        self._pool = ThreadPoolExecutor(max_workers=self._preprocess_threads)
        self._order = np.arange(self.num_data)
        self._cursor = 0
        self._seen_epoch_end = False
        self.reset()

    # ------------------------------------------------------------------
    def _read_at(self, offset):
        rec = getattr(self._tls, "record", None)
        if rec is None:
            rec = rio.MXRecordIO(self._path_imgrec, "r")
            self._tls.record = rec
            with self._readers_lock:
                self._readers.append(rec)
        rec.seek(int(offset))
        s = rec.read()
        if s is None:
            raise MXNetError("truncated record file")
        return s

    def _decode_one(self, offset, payload=None, out=None):
        c = self.data_shape[0]
        if payload is None:
            payload = self._read_at(offset)
        header, img = rio.unpack_img(payload, iscolor=0 if c == 1 else 1)
        if c == 1:
            img = img[:, :, None]  # HW -> HW1
        else:
            if img.ndim == 2:
                img = img[:, :, None].repeat(3, axis=2)
            img = img[:, :, ::-1]  # BGR -> RGB (augmenters/means are RGB)
        # per-sample rng: reproducible regardless of thread scheduling
        rng = _pyrandom.Random(hash((self._seed, self._epoch, int(offset))))
        for aug in self._auglist:
            img = aug(img, rng)
            if img.ndim == 2:
                img = img[:, :, None]  # cv2 ops drop the dim of (H,W,1)
        if self._label_map is not None:
            label = self._label_map.get(header.id)
            if label is None:
                # mixing remapped and packed labels would silently train
                # on wrong data (the reference's ImageLabelMap::Find
                # hard-fails the same way)
                raise MXNetError(
                    f"image id {header.id} not found in path_imglist")
        else:
            label = header.label
        if isinstance(label, np.ndarray):
            label = label[:self.label_width]
        else:
            label = np.array([label], np.float32)[:self.label_width]
        if out is not None:
            # single conversion+transpose pass into the caller's batch
            # buffer (dtype cast fused into the copy)
            np.copyto(out, img.transpose(2, 0, 1), casting="unsafe")
            return out, np.asarray(label, np.float32)
        chw = np.ascontiguousarray(
            np.asarray(img, np.float32).transpose(2, 0, 1))
        return chw, np.asarray(label, np.float32)

    def _load_or_compute_mean(self, mean_path):
        if os.path.isfile(mean_path):
            loaded = nd.load(mean_path)
            arr = (loaded["mean_img"] if isinstance(loaded, dict)
                   else loaded[0])
            return arr.asnumpy().astype(np.float32)
        logging.info("ImageRecordIter: computing mean image -> %s", mean_path)
        acc = np.zeros(self.data_shape, np.float64)
        n = 0
        for off in self._offsets:
            chw, _ = self._decode_one(off)
            acc += chw
            n += 1
        mean = (acc / max(n, 1)).astype(np.float32)
        nd.save(mean_path, {"mean_img": nd.array(mean)})
        return mean

    # ------------------------------------------------------------------
    @property
    def provide_data(self):
        return [DataDesc(self.data_name, (self.batch_size,) + self.data_shape,
                         self.dtype)]

    @property
    def provide_label(self):
        shape = ((self.batch_size,) if self.label_width == 1
                 else (self.batch_size, self.label_width))
        return [DataDesc(self.label_name, shape, np.float32)]

    def reset(self):
        if self.shuffle:
            self._rng.shuffle(self._order)
        self._epoch += 1
        self._cursor = 0
        self._seen_epoch_end = False

    def state_dict(self):
        return {"kind": "ImageRecordIter", "cursor": int(self._cursor),
                "order": self._order.copy(), "epoch": int(self._epoch),
                "seen_epoch_end": bool(self._seen_epoch_end),
                "rng": self._rng.get_state(), "seed": self._seed,
                "num_data": int(self.num_data)}

    def set_state(self, state, rewind=False):
        if state.get("kind") != "ImageRecordIter":
            raise MXNetError("ImageRecordIter.set_state: wrong snapshot "
                             "kind")
        if int(state["num_data"]) != self.num_data:
            raise MXNetError(
                "ImageRecordIter.set_state: snapshot has num_data="
                f"{state['num_data']}, this iterator has {self.num_data} "
                "(different record file or sharding?)")
        self._order = np.asarray(state["order"]).copy()
        self._cursor = 0 if rewind else int(state["cursor"])
        self._epoch = int(state["epoch"])
        self._seen_epoch_end = (False if rewind
                                else bool(state["seen_epoch_end"]))
        self._rng.set_state(state["rng"])
        # the per-sample augmentation stream is keyed on (seed, epoch,
        # offset) — restore the seed so augmentations replay too
        self._seed = state["seed"]

    def iter_next(self):
        return self._cursor < self.num_data and not self._seen_epoch_end

    def next(self):
        if not self.iter_next():
            raise StopIteration
        start = self._cursor
        stop = start + self.batch_size
        pad = 0
        idxs = self._order[start:stop]
        if stop >= self.num_data:
            self._seen_epoch_end = True
            if stop > self.num_data:
                if not self.round_batch:
                    raise StopIteration
                pad = stop - self.num_data
                # modular wrap: correct even when pad > num_data
                idxs = np.concatenate(
                    [idxs, self._order[np.arange(pad) % self.num_data]])
        self._cursor = stop

        offsets = self._offsets[idxs]
        from . import _native
        if _native.lib() is not None:
            # one native threaded call fetches all payloads (no
            # per-record Python seek/read); decode+augment still fan
            # out over the pool
            payloads = rio.read_batch(self._path_imgrec, offsets,
                                      threads=self._preprocess_threads)
        else:
            payloads = [None] * len(offsets)  # per-thread cached readers

        # decode straight into a preallocated batch buffer: one
        # uint8->f32 conversion+transpose per image (np.copyto), no
        # np.stack second copy — and chunked pool submissions so the
        # futures machinery costs O(threads), not O(batch) (profiled:
        # stack+per-sample futures were ~35% of iterator time on the
        # reference JPEG set; the OMP loop in the reference's
        # iter_image_recordio.cc:29-120 writes into the batch the same
        # way)
        n = len(offsets)
        data = np.empty((n,) + tuple(self.data_shape), np.float32)
        label = np.empty((n, self.label_width), np.float32)

        def work(lo, hi):
            for j in range(lo, hi):
                chw, lab = self._decode_one(offsets[j], payloads[j],
                                            out=data[j])
                label[j] = lab

        nchunk = min(self._preprocess_threads, n) or 1
        bounds = np.linspace(0, n, nchunk + 1, dtype=int)
        if nchunk == 1:
            work(0, n)
        else:
            list(self._pool.map(lambda t: work(bounds[t], bounds[t + 1]),
                                range(nchunk)))
        if self.label_width == 1:
            label = label[:, 0]
        # vectorized normalize (iter_normalize.h: (img - mean) * scale / std)
        if self._mean is not None:
            data -= self._mean
        if self._std is not None:
            data /= self._std
        if self._scale != 1.0:
            data *= self._scale
        return DataBatch([nd.array(data.astype(self.dtype, copy=False))],
                         [nd.array(label)], pad=pad,
                         index=np.asarray(idxs),
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def close(self):
        self._pool.shutdown(wait=True)
        with self._readers_lock:
            for rec in self._readers:
                rec.close()
            self._readers.clear()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
