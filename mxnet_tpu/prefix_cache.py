"""Prefix-shared KV cache: a radix index over block-aligned prompt
prefixes, composed with the ref-counted page allocator.

At scale most serving traffic shares long common prefixes — system
prompts, few-shot templates, chat history — yet an exclusive-owner
cache makes every stream pay full pages for bytes that are already on
the device.  Two published designs compose to fix that, and both map
directly onto this repo's paged cache:

* **PagedAttention** (Kwon et al. SOSP '23): K/V lives in fixed-size
  pages addressed through per-stream block tables, so *sharing a
  prefix is a block-table splice* — N streams point rows of their
  tables at the same page ids;
* **RadixAttention** (SGLang, Zheng et al. '23): a radix tree over
  token-block keys maps every cached block-aligned prefix to its page
  chain, so admission finds the longest cached prefix in O(prompt
  blocks) and prefill runs only on the uncached suffix.

Sharing rules (the correctness core):

* only **full** pages enter the index — a full page of a causal
  model's K/V depends exclusively on the tokens at and before it, so
  identical token prefixes mean bit-identical page bytes, and a full
  page is never written again (immutable ⇒ shareable);
* the **partially-filled tail** page is private by construction — the
  index stores block-aligned prefixes only — EXCEPT on a fully-cached
  block-aligned prompt, where the stream's first decode step must
  re-write the last prompt token's slot: a write landing on a page
  with other holders (or one the index still maps) triggers
  **copy-on-write** — the engine allocates a private page, copies the
  bytes on device, and splices its block table;
* a page released by every holder while still indexed is **parked**:
  it keeps its bytes and revives on the next hit, and is reclaimed in
  strict LRU order (leaf-first, deterministic insertion/touch stamps)
  when the pool runs dry (``MXNET_SERVING_EVICT=lru``; ``off``
  disables retention — release frees immediately and drops the index
  entry).

This module is pure host-side bookkeeping (dict/tree arithmetic, no
jax): :class:`mxnet_tpu.serving.DecodeEngine` drives it at admission
(attach + suffix-only prefill), at each decode step (the COW probe),
at preemption/retire (release), and inside allocation (evict-on-
pressure).  Page ids, refcounts and the radix index are HOST-GLOBAL
and mesh-invariant: under a tp x pp serving mesh every device holds
the same page GRID (its shard of each page's head/layer slice), so
one block-table splice, one COW copy, one eviction decision applies
to all shards at once — nothing here learns about the mesh.  Counters: ``serving.prefix_hits`` /
``serving.prefix_hit_tokens`` / ``serving.cow_copies`` /
``serving.evictions``; the ``serving.shared_blocks`` gauge lives with
the allocator.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from . import profiler
from .base import MXNetError
from .kv_cache import BlockAllocator

__all__ = ["PrefixIndex", "PrefixCache"]

EVICT_POLICIES = ("lru", "off")


class _Node:
    """One cached block: the radix-tree edge label is the block's
    token bytes; the payload is the page id holding its K/V."""

    __slots__ = ("key", "page", "parent", "children", "stamp")

    def __init__(self, key: bytes, page: int, parent):
        self.key = key
        self.page = page
        self.parent = parent
        self.children: Dict[bytes, "_Node"] = {}
        self.stamp = 0

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"_Node(page={self.page}, children={len(self.children)})"


class PrefixIndex:
    """Radix tree over block-aligned token prefixes -> page chains.

    Keys are the raw bytes of each ``block_tokens``-sized token block
    (exact match — no hash collisions to reason about); depth d holds
    the d-th block of a prefix.  LRU stamps come from a monotonic
    logical clock, so eviction order is a deterministic function of
    the request sequence, never of wall time."""

    def __init__(self, block_tokens: int):
        if block_tokens < 1:
            raise MXNetError(f"bad block_tokens {block_tokens}")
        self.block_tokens = int(block_tokens)
        self._root: Dict[bytes, _Node] = {}
        self._clock = 0
        self._nodes = 0

    def __len__(self) -> int:
        return self._nodes

    @staticmethod
    def _salted(salt: bytes) -> bytes:
        # length-prefixed so no (salt, block-bytes) pair can collide
        # with another salt's — or with the unsalted tree, whose root
        # keys are exactly block_tokens * 4 bytes
        return len(salt).to_bytes(4, "big") + salt if salt else b""

    def _keys(self, tokens: np.ndarray, nblocks: int,
              salt: bytes = b"") -> List[bytes]:
        t = np.ascontiguousarray(np.asarray(tokens, np.int32))
        B = self.block_tokens
        keys = [t[j * B:(j + 1) * B].tobytes() for j in range(nblocks)]
        if salt and keys:
            # namespace the tree at its ROOT block: K/V bytes depend on
            # the (tokens, adapter) pair, not tokens alone — a prefix
            # prefilled under LoRA adapter X must never satisfy a
            # stream of adapter Y (or a plain stream)
            keys[0] = self._salted(salt) + keys[0]
        return keys

    def roots_for(self, salt: bytes) -> List[_Node]:
        """Depth-0 nodes living under ``salt``'s namespace — the
        handles an adapter republish uses to drop every chain whose
        bytes were computed under the name's OLD weights."""
        p = self._salted(salt)
        want = len(p) + self.block_tokens * 4
        return [n for k, n in list(self._root.items())
                if len(k) == want and k.startswith(p)]

    def _touch(self, node: _Node) -> None:
        self._clock += 1
        node.stamp = self._clock

    # ------------------------------------------------------------------
    def match(self, tokens, touch: bool = True,
              salt: bytes = b"") -> List[_Node]:
        """Longest cached block-aligned prefix of ``tokens``: the node
        chain, shallowest first (``len(chain) * block_tokens`` cached
        tokens).  ``touch`` refreshes the chain's LRU stamps."""
        nblocks = len(tokens) // self.block_tokens
        chain: List[_Node] = []
        children = self._root
        for key in self._keys(tokens, nblocks, salt):
            node = children.get(key)
            if node is None:
                break
            chain.append(node)
            children = node.children
        if touch:
            for node in chain:  # shallow->deep: deepest gets newest
                self._touch(node)
        return chain

    def insert(self, tokens, pages: List[int], nblocks: int,
               salt: bytes = b"") -> List[_Node]:
        """Map the first ``nblocks`` full blocks of ``tokens`` to
        ``pages[j]``.  Existing nodes keep THEIR page (the content is
        identical by construction; the caller's duplicate page simply
        stays private).  Returns the nodes newly created — whose pages
        the index now co-owns."""
        created: List[_Node] = []
        children = self._root
        parent: Optional[_Node] = None
        for j, key in enumerate(self._keys(tokens, nblocks, salt)):
            node = children.get(key)
            if node is None:
                node = _Node(key, int(pages[j]), parent)
                children[key] = node
                self._nodes += 1
                created.append(node)
            self._touch(node)
            parent = node
            children = node.children
        return created

    def remove(self, node: _Node) -> None:
        """Unlink a LEAF node (eviction).  Interior nodes cannot go
        first — their children's chains would dangle."""
        if node.children:
            raise MXNetError("PrefixIndex.remove of an interior node")
        siblings = node.parent.children if node.parent is not None \
            else self._root
        if siblings.get(node.key) is not node:  # pragma: no cover
            raise MXNetError("PrefixIndex.remove of an unlinked node")
        del siblings[node.key]
        self._nodes -= 1

    def leaves(self) -> List[_Node]:
        out = []
        stack = list(self._root.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            else:
                out.append(n)
        return out


class PrefixCache:
    """The sharing layer the engine talks to: allocator + radix index
    + eviction policy + the hit/COW/eviction counters.

    All page-state transitions used by the serving scheduler flow
    through here so the invariants hold in one place:

    * ``peek``/``attach`` — longest-prefix lookup at admission;
      attach bumps refcounts (reviving parked pages) so the matched
      chain cannot be evicted from under the stream;
    * ``register`` — after (suffix) prefill, the prompt's full pages
      enter the index and become shareable;
    * ``release`` — a retiring/preempted stream detaches; indexed
      pages park (bytes kept) instead of freeing;
    * ``alloc`` — pages for new work, evicting parked pages LRU when
      the free list runs dry;
    * ``needs_cow`` — the decode-step write probe: true when the
      target page has other holders or is still index-mapped.
    """

    def __init__(self, alloc: BlockAllocator, policy: str = "lru"):
        if policy not in EVICT_POLICIES:
            raise MXNetError(
                f"unknown eviction policy {policy!r} "
                f"(MXNET_SERVING_EVICT wants one of {EVICT_POLICIES})")
        self.allocator = alloc
        self.policy = policy
        self.index = PrefixIndex(alloc.block_tokens)
        self._page_node: Dict[int, _Node] = {}  # indexed pages
        self.hits = 0
        self.hit_tokens = 0
        self.full_hits = 0
        self.cow_copies = 0
        self.evictions = 0

    # -- admission ------------------------------------------------------
    def peek(self, tokens, salt: bytes = b"") -> Tuple[int, int]:
        """(cached_tokens, parked_matched) for the longest cached
        prefix — refcounts untouched, stamps untouched (a peek that
        doesn't admit must not distort LRU order).  ``parked_matched``
        pages revive on attach, so they are NOT spare capacity for the
        admission check.  ``salt`` namespaces the lookup (the stream's
        adapter identity — adapted K/V never crosses tenants)."""
        chain = self.index.match(tokens, touch=False, salt=salt)
        parked = sum(1 for n in chain if self.allocator.is_parked(n.page))
        return len(chain) * self.index.block_tokens, parked

    def attach(self, tokens, owner=None,
               salt: bytes = b"") -> Tuple[int, List[int]]:
        """Acquire the longest cached prefix for a new stream: bump
        each chain page's refcount (reviving parked ones) and return
        (cached_tokens, pages).  Counted as ONE prefix hit when
        anything matched."""
        chain = self.index.match(tokens, touch=True, salt=salt)
        pages = []
        for node in chain:
            if self.allocator.is_parked(node.page):
                self.allocator.revive(node.page, owner=owner)
            else:
                self.allocator.share(node.page)
            pages.append(node.page)
        cached = len(chain) * self.index.block_tokens
        if cached:
            self.hits += 1
            self.hit_tokens += cached
            profiler.inc_counter("serving.prefix_hits")
            profiler.inc_counter("serving.prefix_hit_tokens", cached)
        return cached, pages

    # -- registration ---------------------------------------------------
    def register(self, tokens, pages: List[int],
                 salt: bytes = b"") -> None:
        """Index every FULL block of ``tokens`` (held by the calling
        stream as ``pages``).  Blocks already indexed keep the
        incumbent page; the caller's duplicate stays private."""
        nblocks = len(tokens) // self.index.block_tokens
        if nblocks > len(pages):  # pragma: no cover - caller bug
            raise MXNetError(
                f"register: {nblocks} full blocks but only "
                f"{len(pages)} pages")
        for node in self.index.insert(tokens, pages, nblocks, salt):
            self._page_node[node.page] = node

    # -- release / eviction ---------------------------------------------
    def release(self, pages: List[int]) -> None:
        """A stream detaches from its pages (retire, preemption,
        failure).  Indexed pages whose refcount hits zero park (bytes
        kept for future hits) under the 'lru' policy; with 'off' they
        free immediately and leave the index."""
        for p in pages:
            keep = self.policy == "lru" and p in self._page_node
            left = self.allocator.release(p, park=keep)
            if left == 0 and not keep and p in self._page_node:
                self._drop_chain(self._page_node[p])

    def _drop_chain(self, node: _Node) -> None:
        """Remove a node's whole subtree from the index (policy 'off'
        release: the page just freed must not stay reachable).
        Descendant pages still held by live streams merely lose their
        index entry (they free normally at their own release); parked
        descendants are reclaimed."""
        stack = [node]
        order: List[_Node] = []
        while stack:
            n = stack.pop()
            order.append(n)
            stack.extend(n.children.values())
        for n in reversed(order):  # deepest-first: leaves before parents
            self.index.remove(n)
            self._page_node.pop(n.page, None)
            if self.allocator.is_parked(n.page):
                self.allocator.reclaim(n.page)

    def invalidate_salt(self, salt: bytes) -> int:
        """Drop every cached chain in ``salt``'s namespace (adapter
        publish/retire): after a retire-then-republish the name maps
        to NEW weights, so chains prefilled under the old ones must
        stop being matchable.  Pages still held by (retiring) live
        streams merely lose their index entry; parked ones are
        reclaimed.  Returns the number of root chains dropped."""
        roots = self.index.roots_for(salt) if salt else []
        for node in roots:
            self._drop_chain(node)
        return len(roots)

    def detach(self, pages: List[int]) -> int:
        """Un-index pages about to be EXPORTED (live KV migration): a
        migrating stream's pages leave this replica's pool, so any
        radix entry mapping them — and the whole subtree hanging off
        it, whose chains would dangle — must stop being matchable
        first.  Pages other streams still hold merely lose their index
        entry (their holders keep reading them and they free at their
        own release); parked descendants of a dropped chain are
        reclaimed by :meth:`_drop_chain` as usual.  Returns the number
        of pages whose index entry was dropped.  After detach, a page
        held only by the migrating stream is exclusively owned and
        eligible for ``BlockAllocator.export_pages``."""
        dropped = 0
        for p in pages:
            node = self._page_node.get(p)
            if node is None:
                continue
            self._drop_chain(node)
            dropped += 1
            profiler.inc_counter("serving.prefix_detached")
        return dropped

    def _evictable(self) -> List[_Node]:
        """Leaf nodes whose page is parked, LRU-first."""
        cands = [n for n in self.index.leaves()
                 if self.allocator.is_parked(n.page)]
        cands.sort(key=lambda n: n.stamp)
        return cands

    def evict(self, need: int) -> int:
        """Reclaim up to ``need`` parked pages in LRU leaf order
        (evicting a leaf may expose its parent as the next
        candidate).  Returns the number reclaimed."""
        if self.policy != "lru":
            return 0
        done = 0
        while done < need:
            cands = self._evictable()
            if not cands:
                break
            for n in cands:
                if done >= need:
                    break
                self.index.remove(n)
                del self._page_node[n.page]
                self.allocator.reclaim(n.page)
                self.evictions += 1
                profiler.inc_counter("serving.evictions")
                done += 1
        return done

    def alloc(self, n: int, owner=None) -> Optional[List[int]]:
        """Allocator facade: evict parked pages (LRU) when the free
        list alone cannot cover ``n``, then allocate all-or-nothing."""
        short = n - self.allocator.free_list_blocks
        if short > 0:
            self.evict(short)
        return self.allocator.alloc(n, owner=owner)

    # -- copy-on-write ---------------------------------------------------
    def needs_cow(self, page: int) -> bool:
        """Would a K/V write to ``page`` be visible beyond its writer?
        True when another stream holds it, or the index still maps its
        bytes (a future hit would read the overwrite)."""
        return self.allocator.refcount(page) > 1 or page in self._page_node

    def note_cow(self) -> None:
        self.cow_copies += 1
        profiler.inc_counter("serving.cow_copies")

    def reset_counters(self) -> None:
        """Zero the hit/COW/eviction counters (bench sweep points);
        the index and page states are untouched."""
        self.hits = self.hit_tokens = self.full_hits = 0
        self.cow_copies = self.evictions = 0

    # -- introspection ---------------------------------------------------
    def stats(self) -> dict:
        return {
            "prefix_hits": self.hits,
            "prefix_hit_tokens": self.hit_tokens,
            "prefix_full_hits": self.full_hits,
            "cow_copies": self.cow_copies,
            "evictions": self.evictions,
            "indexed_blocks": len(self.index),
            "cached_blocks": self.allocator.parked_blocks,
            "shared_blocks": self.allocator.shared_blocks,
        }
